"""Headline benchmark: EC encode throughput, k=8 m=4, 1 MiB objects.

Mirrors the reference harness semantics (`ceph_erasure_code_benchmark -p isa
-P k=8 -P m=4 -S 1048576 -w encode`, src/test/erasure-code/
ceph_erasure_code_benchmark.cc:150-189): GiB/s of object data erasure-coded.
The device path batches S objects' stripes into one (S, k, C) device call
(the whole point — the reference encodes object-by-object on the CPU).

Baseline = the native C++ 4-bit split-table region coder
(native/gf_rs.cpp, the isa-l ec_encode_data-class host path) measured on
this machine.

Survivability contract (the driver kills this process with an external
timeout; three rounds of TPU evidence were lost to that):
  - ONE overall wall-clock budget (CEPH_TPU_BENCH_BUDGET, default 480 s)
    covers probing AND measuring; sections are skipped when the budget is
    nearly exhausted instead of overrunning.
  - The JSON result line is (re-)printed after EVERY completed section —
    a kill at any moment leaves a parseable last line on stdout with
    whatever was measured so far.
  - A dedicated sigwait() watcher thread dumps the partial line on
    SIGTERM/SIGINT even while the main thread is blocked inside a
    tunnelled remote compile (Python-level signal handlers only run on
    the main thread between bytecodes, so a plain handler would never
    fire there); a deadline watchdog thread covers budget overrun.
  - The TPU tunnel (axon PJRT) can be dead or hang on backend init, so the
    device backend is probed in a subprocess with a timeout before this
    process ever imports jax; probe retries are bounded by the budget.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

K, M = 8, 4
OBJECT_SIZE = 1 << 20           # 1 MiB per object
CHUNK = OBJECT_SIZE // K        # 128 KiB
BATCH = 64                      # objects per device call
TARGET_SECONDS = 3.0
PROBE_TIMEOUT = float(os.environ.get("CEPH_TPU_BENCH_PROBE_TIMEOUT", "120"))
PROBE_RETRY_DELAY = 15.0

# One budget to rule the whole run.  The driver's external timeout killed
# round 3's bench mid-flight (rc=124, nothing parseable); everything below
# is paced against this deadline so we exit cleanly first.
BUDGET = float(os.environ.get("CEPH_TPU_BENCH_BUDGET", "480"))
_T0 = time.monotonic()


def _remaining() -> float:
    return BUDGET - (time.monotonic() - _T0)


RESULT: dict = {
    "metric": "ec_encode_k8m4_1MiB_throughput",
    "value": 0.0,
    "unit": "GiB/s",
    "vs_baseline": None,
}
_ERRORS: list[str] = []
_SKIPPED: list[str] = []


def _emit() -> None:
    """(Re-)print the result line with everything measured so far.

    Serializes a snapshot: this runs from the watcher/watchdog threads
    while the main thread may be inserting keys, and json.dumps over a
    mutating dict raises mid-dump."""
    if _ERRORS:
        RESULT["error"] = "; ".join(list(_ERRORS))
    if _SKIPPED:
        RESULT["skipped_sections"] = ",".join(list(_SKIPPED))
    RESULT["elapsed_s"] = round(time.monotonic() - _T0, 1)
    sys.stdout.write(json.dumps(dict(RESULT)) + "\n")
    sys.stdout.flush()


def _dump_and_exit(reason: str, code: int) -> None:
    # async-safe-ish: plain dict -> json -> one write.  Used from signal
    # handlers and the watchdog thread, where the main thread may be
    # blocked inside a remote compile.
    _ERRORS.append(reason)
    try:
        _emit()
    finally:
        os._exit(code)


def _sig_watcher() -> None:  # pragma: no cover - signal path
    """Block in sigwait() on a non-main thread: fires immediately on
    SIGTERM/SIGINT even while the main thread is stuck in a native PJRT
    call (where a Python-level signal handler would be deferred
    indefinitely).  Requires the signals to be masked process-wide
    before any thread starts."""
    sig = signal.sigwait({signal.SIGTERM, signal.SIGINT})
    _dump_and_exit(f"killed by signal {sig}; partial results", 128 + sig)


def _watchdog() -> None:  # pragma: no cover - timing path
    """If the main thread overruns the budget by >30 s (stuck compile),
    dump whatever we have.  Daemon thread: a clean exit just drops it."""
    while True:
        left = _remaining()
        if left <= -30.0:
            _dump_and_exit("watchdog: budget exceeded; partial results", 3)
        time.sleep(min(max(left + 30.0, 1.0), 30.0))


def _probe_once(timeout: float) -> tuple[str | None, bool]:
    """One probe attempt: jax.devices() in a child process so a hung
    tunnel cannot hang the bench itself.  Returns (platform | None,
    permanent): permanent means retrying cannot help (jax missing)."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM:' + d[0].platform)")
    try:
        # the parent blocks SIGTERM/SIGINT process-wide (sigwait
        # watcher); the child must NOT inherit that or a hung-tunnel
        # probe becomes unkillable by the driver and leaks a process
        # holding the TPU tunnel
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout,
                           preexec_fn=lambda: signal.pthread_sigmask(
                               signal.SIG_UNBLOCK,
                               {signal.SIGTERM, signal.SIGINT}))
    except Exception:
        return None, False          # hang/timeout: the flaky-tunnel case
    if p.returncode != 0:
        permanent = ("ModuleNotFoundError" in p.stderr
                     or "ImportError" in p.stderr)
        return None, permanent
    for line in p.stdout.splitlines():
        if line.startswith("PLATFORM:"):
            plat = line.split(":", 1)[1].strip()
            # "cpu" can mean a flapping tunnel plugin that failed to
            # register and fell back — worth retrying, not permanent
            return (plat if plat != "cpu" else None), False
    return None, False


def probe_accelerator() -> str | None:
    """Return the accelerator platform name, or None if unusable.

    Retries failed probes in a bounded loop, but never spends more than
    ~45% of the remaining budget probing — the measurements need the
    rest.  Progress goes to stderr so stdout stays pure JSON lines.
    """
    window = max(_remaining() * 0.45, 60.0)
    env_window = os.environ.get("CEPH_TPU_BENCH_PROBE_WINDOW")
    if env_window is not None:
        window = min(window, float(env_window))
    deadline = time.monotonic() + window
    attempt = 0
    while True:
        attempt += 1
        left = deadline - time.monotonic()
        plat, permanent = _probe_once(min(PROBE_TIMEOUT, max(left, 30.0)))
        if plat is not None:
            if attempt > 1:
                print(f"[bench] accelerator up on probe #{attempt}",
                      file=sys.stderr)
            return plat
        left = deadline - time.monotonic()
        if permanent or left <= PROBE_RETRY_DELAY:
            print(f"[bench] accelerator unreachable after {attempt} "
                  f"probes{' (permanent)' if permanent else ''}; "
                  "cpu fallback", file=sys.stderr)
            return None
        print(f"[bench] probe #{attempt} failed; retrying "
              f"({left:.0f}s left in probe window)", file=sys.stderr)
        time.sleep(PROBE_RETRY_DELAY)


def measure_host(matrix: np.ndarray, data2d: np.ndarray) -> float:
    """GiB/s of the native C++ path on one (k, C) object."""
    from ceph_tpu.native import native_rs_encode, native_available
    if not native_available():
        return 0.0
    rows = matrix[K:]
    native_rs_encode(rows, data2d)  # warm tables
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < TARGET_SECONDS / 2:
        native_rs_encode(rows, data2d)
        n += 1
    dt = time.perf_counter() - t0
    return n * OBJECT_SIZE / dt / (1 << 30)


def _salted_matmul_step():
    """One shared jitted (payload ^ salt) @ bits step.

    Salting with a never-repeating per-iteration scalar means no layer
    (XLA or a tunnelled PJRT shim) can serve a repeat dispatch from
    cache: every iteration is a genuinely new execution.  (Without this,
    repeat dispatches of identical inputs measured 3-10x above the
    chip's int8-MXU compute floor — a cache, not the hardware.)  The
    full 32-bit salt is xored across u32 lanes so the input never
    repeats within a run — a uint8 salt would cycle every 256 iters.
    """
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops.gf_matmul import gf_bit_matmul

    @jax.jit
    def step(d, b, salt):
        s_, k_, c_ = d.shape
        d32 = jax.lax.bitcast_convert_type(
            d.reshape(s_, k_, c_ // 4, 4), jnp.uint32)
        d8 = jax.lax.bitcast_convert_type(
            d32 ^ salt, jnp.uint8).reshape(s_, k_, c_)
        return gf_bit_matmul(d8, b)

    return step


_STEP = None


def _step_fn():
    global _STEP
    if _STEP is None:
        _STEP = _salted_matmul_step()
    return _STEP


def measure_device(matrix: np.ndarray, batch: np.ndarray) -> float:
    """GiB/s of the jitted device path on (S, k, C) batches."""
    import jax
    import jax.numpy as jnp
    from ceph_tpu.gf.tables import expand_to_bitmatrix

    bits = jnp.asarray(expand_to_bitmatrix(matrix[K:]).astype(np.int8))
    dev = jax.device_put(jnp.asarray(batch))
    step = _step_fn()
    step(dev, bits, jnp.uint32(0)).block_until_ready()  # compile + warm
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < TARGET_SECONDS:
        step(dev, bits, jnp.uint32(n + 1)).block_until_ready()
        n += 1
    dt = time.perf_counter() - t0
    return n * BATCH * OBJECT_SIZE / dt / (1 << 30)


def measure_decode(matrix: np.ndarray, batch: np.ndarray,
                   erasures: int = 2) -> float:
    """GiB/s of the device decode path with *erasures* data shards lost
    (the reference's ``-w decode -e 2``): reconstruct the missing data
    chunks from k survivors via the signature-cached inverted bitmatrix
    (ErasureCodeIsa decode + table cache role).

    The survivor payload here is random: the GF matmul's timing is
    data-independent, and a large device->host fetch mid-run flips this
    tunnelled transport into a sync-dispatch mode that poisons every
    later measurement in the process (measured: 137 us -> 81 ms per
    dispatch after one 16 MB fetch).  Correctness on REAL coded data is
    verified separately by parity_check(), which runs LAST for exactly
    that reason."""
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops.gf_matmul import DeviceRSBackend

    be = DeviceRSBackend(matrix)
    lost = tuple(range(erasures))                   # data shards 0..e-1
    srcs = tuple(range(erasures, K)) + tuple(K + i for i in range(erasures))
    bits = be._decode_bits_for(srcs, lost)
    dev = jax.device_put(jnp.asarray(batch))        # (S, k, C) survivors
    step = _step_fn()
    step(dev, bits, jnp.uint32(0)).block_until_ready()
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < TARGET_SECONDS:
        step(dev, bits, jnp.uint32(n + 1)).block_until_ready()
        n += 1
    dt = time.perf_counter() - t0
    return n * BATCH * OBJECT_SIZE / dt / (1 << 30)


def parity_check(matrix: np.ndarray) -> bool:
    """Encode REAL data on device, erase two data shards, decode on
    device, fetch, byte-compare against the original.  This is the
    on-hardware correctness receipt for the decode throughput number;
    it involves device->host fetches, so it must be the LAST section
    (sync-dispatch poisoning no longer matters)."""
    from ceph_tpu.ops.gf_matmul import DeviceRSBackend
    rng = np.random.default_rng(20260731)
    data = rng.integers(0, 256, size=(2, K, 4096), dtype=np.uint8)
    be = DeviceRSBackend(matrix)
    coding = be.encode(data)                         # (2, m, C) fetched
    lost = (0, 1)
    srcs = tuple(range(2, K)) + (K, K + 1)
    survivors = np.concatenate([data[:, 2:, :], coding[:, :2, :]], axis=1)
    got = be.decode_data(survivors, srcs, lost)      # (2, 2, C)
    return bool(np.array_equal(got, data[:, :2, :]))


def measure_crush_remap(n_osds=1000, n_pgs=100_000, epochs=10,
                        uniform=True, partial=None, infix=""):
    """The <50 ms north star: remap ALL PGs after an epoch change.

    The workload is OSDMapMapping's per-epoch job (OSDMapMapping.h:17): the
    crush topology is unchanged (candidate tables cached on device), one
    osd flips out per epoch (new weight vector), and the resolution kernel
    re-derives every PG's mapping.  Reported:
      - wall: full map_batch (device resolve + transfer + host compaction
        + exact residual replay) per epoch, median over ``epochs``;
      - device: sustained resolve-kernel time amortized over back-to-back
        dispatches (what a pipelined consumer pays per epoch).
    """
    import jax
    import jax.numpy as jnp
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    from ceph_tpu.ops.crush_fast import compile_fast_rule
    per_host = 20
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    rng_w = np.random.default_rng(7)
    for h in range(n_osds // per_host):
        osds = list(range(h * per_host, (h + 1) * per_host))
        if uniform:
            ws = [0x10000] * per_host
        else:
            # heterogeneous drives: the exact64 draw path (u64 table
            # divide, zero residuals; f32+replay when a backend can't
            # lower u64), not the quotient tables
            ws = [int(v) * 0x8000
                  for v in rng_w.integers(1, 5, size=per_host)]
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}",
                                   osds, ws, id=-(h + 2)))
    cw.set_max_devices(n_osds)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x10000 * per_host] * len(hosts), id=-1)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    xs = np.arange(n_pgs, dtype=np.uint32)
    w = np.full(n_osds, 0x10000, dtype=np.uint32)

    dbg = os.environ.get("CEPH_TPU_BENCH_DEBUG")
    tmark = time.monotonic()

    def mark(label: str) -> None:
        nonlocal tmark
        if dbg:
            now = time.monotonic()
            print(f"[crush-bench] {label}: {now - tmark:.1f}s",
                  file=sys.stderr)
            tmark = now

    def report(**kv) -> None:
        # milestone callback: the caller re-emits the JSON line, so a
        # watchdog kill later in the section cannot erase what this
        # section already measured (the remap north star must survive
        # a budget overrun in a LATER phase).  *infix* keeps the
        # uniform and nonuniform sections' keys distinct.
        if partial is not None:
            partial({k.replace("@", infix): v for k, v in kv.items()})

    # the native-host baseline first: pure C++, no tunnel exposure —
    # worst case the device phases die and the line still carries it
    host_ms = None
    try:
        from ceph_tpu.native import NativeCrushMapper, native_available
        if native_available():
            nm = NativeCrushMapper(cw.crush)
            w0 = [0x10000] * n_osds
            sample = 2000
            t0 = time.perf_counter()
            nm.do_rule_batch(rno, list(range(sample)), 3, w0)
            host_ms = (time.perf_counter() - t0) \
                * (n_pgs / sample) * 1000
            if uniform:
                report(crush_remap_native_host_ms=round(host_ms, 2))
    except Exception:
        pass
    mark("native host baseline")

    fr = compile_fast_rule(cw.crush, rno, 3)
    mark("compile_fast_rule (host tables)")
    fr.map_batch(xs, w)  # compile + candidate tables + warm (full fetch)
    mark("map_batch warm #1 (cand+resolve compiles)")
    wwarm = w.copy()
    wwarm[1] = 0
    fr.map_batch(xs, wwarm)  # warm the delta-path trace/compile too
    mark("map_batch warm #2 (delta compile)")
    # per-epoch wall time: one osd out per epoch.  map_batch's delta path
    # fetches only changed rows, so the wall is one resolve + one small
    # device->host transfer (OSDMapMapping's per-epoch job).
    walls = []
    for e in range(epochs):
        w2 = w.copy()
        w2[(7 * e + 3) % n_osds] = 0
        t0 = time.perf_counter()
        fr.map_batch(xs, w2)
        walls.append(time.perf_counter() - t0)
    wall_ms = sorted(walls)[len(walls) // 2] * 1000
    report(**{"crush_remap@_pgs": n_pgs,
              "crush_remap@_wall_ms": round(wall_ms, 2),
              "crush@_residual_fraction": fr.residual_fraction})
    mark("per-epoch wall loop")
    # device->host round-trip floor of this transport (tunnelled PJRT
    # pays ~100 ms here; local PCIe pays ~0) so wall_ms is interpretable
    tiny = jnp.zeros((8,), jnp.int32) + jnp.int32(1)
    jax.block_until_ready(tiny)
    t0 = time.perf_counter()
    np.asarray(tiny)
    rtt_ms = (time.perf_counter() - t0) * 1000
    # sustained device resolve time: back-to-back dispatches drained by
    # fetching one element of the LAST output.  PJRT executes in
    # submission order, so that fetch completing means every dispatch
    # completed — block_until_ready alone is not trustworthy over a
    # tunnelled transport (it can acknowledge before remote completion).
    # The fetch round trip itself is subtracted via the measured rtt.
    wds = []
    for e in range(epochs):
        w2 = w.copy()
        w2[(13 * e + 29) % n_osds] = 0
        wds.append(jnp.asarray(w2))
    np.asarray(fr.resolve_device(wds[0])[0][0, 0])   # warm + drain
    mark("resolve_device warm")
    t0 = time.perf_counter()
    outs = [fr.resolve_device(wd) for wd in wds]
    np.asarray(outs[-1][0][0, 0])
    total = (time.perf_counter() - t0) * 1000
    mark("sustained resolve loop")
    # subtracting the fetch rtt can hit zero when the resolves are
    # faster than one round trip; fall back to the un-subtracted upper
    # bound so the metric never reads as "didn't run"
    dev_ms = max(total - rtt_ms, 0.0) / len(wds)
    if dev_ms == 0.0:
        dev_ms = total / len(wds)
    kv = {"crush_remap@_us": round(dev_ms * 1000.0, 2)}
    if uniform:
        kv["transport_rtt_ms"] = round(rtt_ms, 2)
    report(**kv)
    return wall_ms, dev_ms, host_ms, fr.residual_fraction, rtt_ms


def main() -> None:
    signal.pthread_sigmask(signal.SIG_BLOCK,
                           {signal.SIGTERM, signal.SIGINT})
    threading.Thread(target=_sig_watcher, daemon=True).start()
    threading.Thread(target=_watchdog, daemon=True).start()

    global TARGET_SECONDS, BATCH
    platform = probe_accelerator()
    if platform is None:
        # Dead/absent tunnel: keep this process off the accelerator path
        # entirely so nothing below can hang on backend init.  The CPU
        # fallback exists to always emit a parseable line, not to be a
        # meaningful number — shrink the workload so the whole run stays
        # under ~1 minute instead of ~10.
        os.environ["JAX_PLATFORMS"] = "cpu"
        _ERRORS.append("accelerator backend unavailable; cpu fallback")
        RESULT["platform"] = "cpu"
        TARGET_SECONDS = 0.5
        BATCH = 4
    else:
        RESULT["platform"] = platform
    _emit()     # first parseable line exists before any jax work

    try:
        import jax
        if platform is None:
            jax.config.update("jax_platforms", "cpu")
        # Persistent compilation cache: the tunnelled XLA compiles are
        # the dominant cost (a cold crush section pays ~7 min compiling
        # its four kernels); with the on-disk cache warm, a full run
        # fits easily inside the driver's 480 s budget.
        cache_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # pragma: no cover - catastrophic env breakage
        _ERRORS.append(f"jax import failed: {e!r}")

    from ceph_tpu.gf.matrices import gf_gen_rs_matrix
    rng = np.random.default_rng(1234)
    matrix = gf_gen_rs_matrix(K + M, K)
    batch = rng.integers(0, 256, size=(BATCH, K, CHUNK), dtype=np.uint8)

    host_gibs = 0.0
    try:
        host_gibs = measure_host(matrix, batch[0])
        RESULT["host_native_gibs"] = round(host_gibs, 3)
    except Exception as e:
        _ERRORS.append(f"host bench failed: {e!r}")
    _emit()

    def run_section(label: str, fn, min_needed: float) -> None:
        """Run one section inside the budget; re-emit the line after.
        One retry after a settle delay (the tunnel can drop a remote
        compile mid-flight) — but only if the budget still allows."""
        if _remaining() < min_needed:
            _SKIPPED.append(label)
            _emit()
            return
        for attempt in range(2):
            try:
                fn()
                break
            except Exception as e:
                if attempt == 1 or _remaining() < min_needed:
                    _ERRORS.append(f"{label} failed: {e!r}")
                    break
                time.sleep(5.0)
        _emit()

    def encode_section() -> None:
        dev_gibs = measure_device(matrix, batch)
        RESULT["value"] = round(dev_gibs, 3)
        if host_gibs:
            RESULT["vs_baseline"] = round(dev_gibs / host_gibs, 2)

    def decode_section() -> None:
        RESULT["ec_decode_e2_gibs"] = round(
            measure_decode(matrix, batch), 3)

    def _partial(kv: dict) -> None:
        # milestone flush: remap numbers hit the JSON line the moment
        # they exist, so a watchdog kill later in the section cannot
        # erase the north star
        RESULT.update(kv)
        host = RESULT.get("crush_remap_native_host_ms")
        us = RESULT.get("crush_remap_us")
        if host and us:
            RESULT["crush_remap_vs_native_host"] = round(
                host / (us / 1000.0), 2)
        _emit()

    def crush_section() -> None:
        # STABLE metric keys across rounds/platforms: the workload
        # size lives in crush_remap_pgs, never in the key name, so
        # r(N) and r(N+1) JSON lines stay field-compatible even when
        # a CPU fallback shrinks the workload.  The partial path is
        # the ONE writer of the remap keys (milestone flushes; see
        # _partial) — microseconds so "fast" and "didn't run" can
        # never be confused.
        n_pgs = 100_000 if platform else 10_000
        measure_crush_remap(n_pgs=n_pgs,
                            epochs=10 if platform else 2,
                            partial=_partial)

    def crush_nonuniform_section() -> None:
        # the <50 ms target on a 2-level map with NON-uniform weights:
        # exercises the exact64 draw; same milestone flushing with
        # the _nonuniform key infix
        n_pgs = 100_000 if platform else 10_000
        measure_crush_remap(n_pgs=n_pgs,
                            epochs=10 if platform else 2,
                            uniform=False, partial=_partial,
                            infix="_nonuniform")

    def parity_section() -> None:
        RESULT["decode_parity"] = parity_check(matrix)

    # Ordered so a budget kill costs the least AND so the dispatch-
    # timing sections run before anything does a large device->host
    # fetch: the crush sections' 100k-row map_batch fetches flip the
    # tunnelled transport into sync-dispatch mode (~80 ms/dispatch),
    # which poisoned a decode bench run after them (measured 0.76 GiB/s
    # vs 313-627 clean).  So: encode, decode (both pure dispatch), then
    # the remap north star, then extras, then the fetch-heavy parity
    # receipt dead last.  min_needed gates reflect that a cold-cache
    # section pays a tunnelled XLA compile (minutes); with the
    # persistent cache warm they're seconds.
    run_section("device bench", encode_section, 45.0)
    run_section("decode bench", decode_section, 45.0)
    run_section("crush bench", crush_section, 110.0)
    run_section("crush nonuniform bench", crush_nonuniform_section, 80.0)
    run_section("decode parity", parity_section, 45.0)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # last-ditch: the JSON line must still appear,
        _ERRORS.append(f"bench crashed: {e!r}")  # but rc stays truthful
        try:
            _emit()
        except Exception:
            print(json.dumps({
                "metric": "ec_encode_k8m4_1MiB_throughput",
                "value": 0.0, "unit": "GiB/s", "vs_baseline": None,
                "error": f"bench crashed: {e!r}",
            }))
        raise SystemExit(1)
