"""Headline benchmark driver: EC encode/decode + CRUSH remap, k=8 m=4.

Thin survivability shell over the ``ceph_tpu.bench`` subsystem, which
owns ALL measurement mechanics: completion-fenced timers (the clock
stops only after a device→host drain of the last output — dispatch
acknowledgements are not completions over a tunnelled transport),
warmup/repeat statistics (median/IQR/min), a roofline validator that
stamps ``suspect: true`` on any reading implying more than the chip's
physical peak, and the versioned metric schema.  See
docs/BENCHMARKING.md for the methodology.

What stays HERE is the survivability contract (the driver kills this
process with an external timeout; three rounds of TPU evidence were
lost to that):
  - ONE overall wall-clock budget (CEPH_TPU_BENCH_BUDGET, default
    480 s) covers probing AND measuring; sections are skipped when the
    budget is nearly exhausted instead of overrunning.
  - The JSON result line is (re-)printed after EVERY completed section
    with ``"partial": true``; only the final complete emit flips it to
    false — a kill at any moment leaves a parseable last line on stdout
    that is distinguishable from a finished run.
  - A dedicated sigwait() watcher thread dumps the partial line on
    SIGTERM/SIGINT even while the main thread is blocked inside a
    tunnelled remote compile; a deadline watchdog covers budget
    overrun.
  - The TPU tunnel (axon PJRT) can be dead or hang on backend init, so
    the device backend is probed in a subprocess with a timeout before
    this process ever imports jax.

Legacy flat keys (value, ec_decode_e2_gibs, crush_remap_*) are kept so
the BENCH_r*.json trajectory stays field-compatible; the new
schema-versioned records ride alongside under ``"metrics"``.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np

K, M = 8, 4
OBJECT_SIZE = 1 << 20           # 1 MiB per object
CHUNK = OBJECT_SIZE // K        # 128 KiB
BATCH = 64                      # objects per device call
TARGET_SECONDS = 3.0
PROBE_TIMEOUT = float(os.environ.get("CEPH_TPU_BENCH_PROBE_TIMEOUT", "120"))
PROBE_RETRY_DELAY = 15.0

# One budget to rule the whole run.  The driver's external timeout killed
# round 3's bench mid-flight (rc=124, nothing parseable); everything below
# is paced against this deadline so we exit cleanly first.
BUDGET = float(os.environ.get("CEPH_TPU_BENCH_BUDGET", "480"))
_T0 = time.monotonic()


def _remaining() -> float:
    return BUDGET - (time.monotonic() - _T0)


RESULT: dict = {
    "metric": "ec_encode_k8m4_1MiB_throughput",
    "value": 0.0,
    "unit": "GiB/s",
    "vs_baseline": None,
    "partial": True,
    "metrics": [],
}
_ERRORS: list[str] = []
_SKIPPED: list[str] = []


def _emit(final: bool = False) -> None:
    """(Re-)print the result line with everything measured so far.

    ``partial`` stays true on every milestone re-print and on watcher/
    watchdog dumps; only the one complete end-of-run emit flips it to
    false, so a kill mid-run is distinguishable from a finished line
    even though both re-print identical measurement keys.

    Serializes a snapshot: this runs from the watcher/watchdog threads
    while the main thread may be inserting keys, and json.dumps over a
    mutating container raises mid-dump."""
    if final:
        RESULT["partial"] = False
    if _ERRORS:
        RESULT["error"] = "; ".join(list(_ERRORS))
    if _SKIPPED:
        RESULT["skipped_sections"] = ",".join(list(_SKIPPED))
    RESULT["elapsed_s"] = round(time.monotonic() - _T0, 1)
    snap = dict(RESULT)
    snap["metrics"] = list(RESULT["metrics"])
    sys.stdout.write(json.dumps(snap) + "\n")
    sys.stdout.flush()


def _dump_and_exit(reason: str, code: int) -> None:
    # async-safe-ish: plain dict -> json -> one write.  Used from signal
    # handlers and the watchdog thread, where the main thread may be
    # blocked inside a remote compile.
    _ERRORS.append(reason)
    try:
        _emit()
    finally:
        os._exit(code)


def _sig_watcher() -> None:  # pragma: no cover - signal path
    """Block in sigwait() on a non-main thread: fires immediately on
    SIGTERM/SIGINT even while the main thread is stuck in a native PJRT
    call (where a Python-level signal handler would be deferred
    indefinitely).  Requires the signals to be masked process-wide
    before any thread starts."""
    sig = signal.sigwait({signal.SIGTERM, signal.SIGINT})
    _dump_and_exit(f"killed by signal {sig}; partial results", 128 + sig)


def _watchdog() -> None:  # pragma: no cover - timing path
    """If the main thread overruns the budget by >30 s (stuck compile),
    dump whatever we have.  Daemon thread: a clean exit just drops it."""
    while True:
        left = _remaining()
        if left <= -30.0:
            _dump_and_exit("watchdog: budget exceeded; partial results", 3)
        time.sleep(min(max(left + 30.0, 1.0), 30.0))


def _probe_once(timeout: float) -> tuple[str | None, bool]:
    """One probe attempt: jax.devices() in a child process so a hung
    tunnel cannot hang the bench itself.  Returns (platform | None,
    permanent): permanent means retrying cannot help (jax missing)."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM:' + d[0].platform)")
    try:
        # the parent blocks SIGTERM/SIGINT process-wide (sigwait
        # watcher); the child must NOT inherit that or a hung-tunnel
        # probe becomes unkillable by the driver and leaks a process
        # holding the TPU tunnel
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout,
                           preexec_fn=lambda: signal.pthread_sigmask(
                               signal.SIG_UNBLOCK,
                               {signal.SIGTERM, signal.SIGINT}))
    except Exception:
        return None, False          # hang/timeout: the flaky-tunnel case
    if p.returncode != 0:
        permanent = ("ModuleNotFoundError" in p.stderr
                     or "ImportError" in p.stderr)
        return None, permanent
    for line in p.stdout.splitlines():
        if line.startswith("PLATFORM:"):
            plat = line.split(":", 1)[1].strip()
            # "cpu" can mean a flapping tunnel plugin that failed to
            # register and fell back — worth retrying, not permanent
            return (plat if plat != "cpu" else None), False
    return None, False


def probe_accelerator() -> str | None:
    """Return the accelerator platform name, or None if unusable.

    Retries failed probes in a bounded loop, but never spends more than
    ~45% of the remaining budget probing — the measurements need the
    rest.  Progress goes to stderr so stdout stays pure JSON lines.
    """
    window = max(_remaining() * 0.45, 60.0)
    env_window = os.environ.get("CEPH_TPU_BENCH_PROBE_WINDOW")
    if env_window is not None:
        window = min(window, float(env_window))
    deadline = time.monotonic() + window
    attempt = 0
    while True:
        attempt += 1
        left = deadline - time.monotonic()
        plat, permanent = _probe_once(min(PROBE_TIMEOUT, max(left, 30.0)))
        if plat is not None:
            if attempt > 1:
                print(f"[bench] accelerator up on probe #{attempt}",
                      file=sys.stderr)
            return plat
        left = deadline - time.monotonic()
        if permanent or left <= PROBE_RETRY_DELAY:
            print(f"[bench] accelerator unreachable after {attempt} "
                  f"probes{' (permanent)' if permanent else ''}; "
                  "cpu fallback", file=sys.stderr)
            return None
        print(f"[bench] probe #{attempt} failed; retrying "
              f"({left:.0f}s left in probe window)", file=sys.stderr)
        time.sleep(PROBE_RETRY_DELAY)


def main() -> None:
    signal.pthread_sigmask(signal.SIG_BLOCK,
                           {signal.SIGTERM, signal.SIGINT})
    threading.Thread(target=_sig_watcher, daemon=True).start()
    threading.Thread(target=_watchdog, daemon=True).start()

    global TARGET_SECONDS, BATCH
    platform = probe_accelerator()
    if platform is None:
        # Dead/absent tunnel: keep this process off the accelerator path
        # entirely so nothing below can hang on backend init.  The CPU
        # fallback exists to always emit a parseable line, not to be a
        # meaningful number — shrink the workload so the whole run stays
        # under ~1 minute instead of ~10.
        os.environ["JAX_PLATFORMS"] = "cpu"
        _ERRORS.append("accelerator backend unavailable; cpu fallback")
        RESULT["platform"] = "cpu"
        TARGET_SECONDS = 0.5
        BATCH = 4
    else:
        RESULT["platform"] = platform
    _emit()     # first parseable line exists before any jax work

    try:
        import jax
        if platform is None:
            jax.config.update("jax_platforms", "cpu")
        # Persistent compilation cache: the tunnelled XLA compiles are
        # the dominant cost (a cold crush section pays ~7 min compiling
        # its four kernels); with the on-disk cache warm, a full run
        # fits easily inside the driver's 480 s budget.
        cache_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), ".jax_cache")
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception as e:  # pragma: no cover - catastrophic env breakage
        _ERRORS.append(f"jax import failed: {e!r}")

    from ceph_tpu.bench import workloads
    from ceph_tpu.gf.matrices import gf_gen_rs_matrix
    rng = np.random.default_rng(1234)
    matrix = gf_gen_rs_matrix(K + M, K)
    batch = rng.integers(0, 256, size=(BATCH, K, CHUNK), dtype=np.uint8)

    host_gibs = 0.0
    try:
        hm = workloads.measure_host_native(
            matrix, batch[0], target_seconds=TARGET_SECONDS / 2)
        if hm is not None:
            host_gibs = hm["value"]
            RESULT["host_native_gibs"] = round(host_gibs, 3)
            RESULT["metrics"].append(hm)
    except Exception as e:
        _ERRORS.append(f"host bench failed: {e!r}")
    _emit()

    def run_section(label: str, fn, min_needed: float) -> None:
        """Run one section inside the budget; re-emit the line after.
        One retry after a settle delay (the tunnel can drop a remote
        compile mid-flight) — but only if the budget still allows."""
        if _remaining() < min_needed:
            _SKIPPED.append(label)
            _emit()
            return
        for attempt in range(2):
            try:
                fn()
                break
            except Exception as e:
                if attempt == 1 or _remaining() < min_needed:
                    _ERRORS.append(f"{label} failed: {e!r}")
                    break
                time.sleep(5.0)
        _emit()

    def encode_section() -> None:
        m = workloads.measure_encode(
            matrix, batch, target_seconds=TARGET_SECONDS,
            repeats=3 if platform else 2)
        RESULT["metrics"].append(m)
        # headline value = the FENCED median; the roofline verdict and
        # implied TOPS ride inside the metric record
        RESULT["value"] = m["value"]
        RESULT["encode_suspect"] = m["suspect"]
        if host_gibs:
            RESULT["vs_baseline"] = round(m["value"] / host_gibs, 2)

    def decode_section() -> None:
        m = workloads.measure_decode(
            matrix, batch, target_seconds=TARGET_SECONDS,
            repeats=3 if platform else 2)
        RESULT["metrics"].append(m)
        RESULT["ec_decode_e2_gibs"] = m["value"]

    def _partial(kv: dict) -> None:
        # milestone flush: remap numbers hit the JSON line the moment
        # they exist, so a watchdog kill later in the section cannot
        # erase the north star
        RESULT.update(kv)
        host = RESULT.get("crush_remap_native_host_ms")
        us = RESULT.get("crush_remap_us")
        if host and us:
            RESULT["crush_remap_vs_native_host"] = round(
                host / (us / 1000.0), 2)
        _emit()

    def crush_section(uniform: bool = True, infix: str = "") -> None:
        # STABLE metric keys across rounds/platforms: the workload size
        # lives in crush_remap_pgs, never in the key name, so r(N) and
        # r(N+1) JSON lines stay field-compatible even when a CPU
        # fallback shrinks the workload.
        *_ignored, ms = workloads.measure_crush_remap(
            n_pgs=100_000 if platform else 10_000,
            epochs=10 if platform else 2,
            uniform=uniform, partial=_partial, infix=infix,
            debug=bool(os.environ.get("CEPH_TPU_BENCH_DEBUG")))
        RESULT["metrics"].extend(ms)

    def pipeline_section() -> None:
        # depth-8 async write pipeline vs depth-1 synchronous submit
        # from ONE thread — the dispatch-amortization headline; host-
        # materialized completions, so safe before the fetch-heavy
        # parity receipt but after the pure one-element-drain sections
        mp, mp1 = workloads.measure_ec_pipeline(
            n_requests=32 if platform else 16,
            target_seconds=TARGET_SECONDS / 2,
            repeats=3 if platform else 2)
        RESULT["metrics"].extend([mp, mp1])
        RESULT["ec_pipeline_gibs"] = mp["value"]
        RESULT["ec_pipeline_speedup"] = mp["speedup"]
        RESULT["ec_pipeline_occupancy"] = mp["mean_batch_occupancy"]

    def parity_section() -> None:
        RESULT["decode_parity"] = workloads.parity_check(matrix)

    # Ordered so a budget kill costs the least AND so the dispatch-
    # timing sections run before anything does a large device->host
    # fetch: the crush sections' 100k-row map_batch fetches flip the
    # tunnelled transport into sync-dispatch mode (~80 ms/dispatch),
    # which poisoned a decode bench run after them (measured 0.76 GiB/s
    # vs 313-627 clean).  So: encode, decode (both drain via one-element
    # fetches only), then the remap north star, then extras, then the
    # fetch-heavy parity receipt dead last.  min_needed gates reflect
    # that a cold-cache section pays a tunnelled XLA compile (minutes);
    # with the persistent cache warm they're seconds.
    run_section("device bench", encode_section, 45.0)
    run_section("decode bench", decode_section, 45.0)
    run_section("crush bench", lambda: crush_section(True), 110.0)
    run_section("crush nonuniform bench",
                lambda: crush_section(False, "_nonuniform"), 80.0)
    run_section("ec pipeline bench", pipeline_section, 45.0)
    run_section("decode parity", parity_section, 45.0)
    _emit(final=True)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # last-ditch: the JSON line must still appear,
        _ERRORS.append(f"bench crashed: {e!r}")  # but rc stays truthful
        try:
            _emit()
        except Exception:
            print(json.dumps({
                "metric": "ec_encode_k8m4_1MiB_throughput",
                "value": 0.0, "unit": "GiB/s", "vs_baseline": None,
                "partial": True,
                "error": f"bench crashed: {e!r}",
            }))
        raise SystemExit(1)
