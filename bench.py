"""Headline benchmark: EC encode throughput, k=8 m=4, 1 MiB objects.

Mirrors the reference harness semantics (`ceph_erasure_code_benchmark -p isa
-P k=8 -P m=4 -S 1048576 -w encode`, src/test/erasure-code/
ceph_erasure_code_benchmark.cc:150-189): GiB/s of object data erasure-coded.
The device path batches S objects' stripes into one (S, k, C) device call
(the whole point — the reference encodes object-by-object on the CPU).

Baseline = the native C++ 4-bit split-table region coder
(native/gf_rs.cpp, the isa-l ec_encode_data-class host path) measured on
this machine.  Prints ONE json line.

Fail-soft contract: the TPU tunnel (axon PJRT) can be dead or hang on
backend init, so the device backend is probed in a *subprocess with a
timeout* before this process ever imports jax.  On probe failure we fall
back to the CPU backend and record an "error" field — the JSON line is
always printed, whatever happens.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

K, M = 8, 4
OBJECT_SIZE = 1 << 20           # 1 MiB per object
CHUNK = OBJECT_SIZE // K        # 128 KiB
BATCH = 64                      # objects per device call
TARGET_SECONDS = 3.0
PROBE_TIMEOUT = float(os.environ.get("CEPH_TPU_BENCH_PROBE_TIMEOUT", "150"))
# Total wall budget for accelerator probing.  The tunnel flaps: a dead
# probe at minute 0 says nothing about minute 5 (round 2 lost its driver
# bench to exactly that).  Keep retrying inside this window before
# accepting the CPU fallback.
PROBE_WINDOW = float(os.environ.get("CEPH_TPU_BENCH_PROBE_WINDOW", "600"))
PROBE_RETRY_DELAY = 20.0


def _probe_once(timeout: float) -> tuple[str | None, bool]:
    """One probe attempt: jax.devices() in a child process so a hung
    tunnel cannot hang the bench itself.  Returns (platform | None,
    permanent): permanent means retrying cannot help (jax missing)."""
    code = ("import jax; d = jax.devices(); "
            "print('PLATFORM:' + d[0].platform)")
    try:
        p = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout)
    except Exception:
        return None, False          # hang/timeout: the flaky-tunnel case
    if p.returncode != 0:
        permanent = ("ModuleNotFoundError" in p.stderr
                     or "ImportError" in p.stderr)
        return None, permanent
    for line in p.stdout.splitlines():
        if line.startswith("PLATFORM:"):
            plat = line.split(":", 1)[1].strip()
            # "cpu" can mean a flapping tunnel plugin that failed to
            # register and fell back — worth retrying, not permanent
            return (plat if plat != "cpu" else None), False
    return None, False


def probe_accelerator() -> str | None:
    """Return the accelerator platform name, or None if unusable.

    Retries failed probes in a bounded loop across PROBE_WINDOW seconds
    rather than falling back to CPU on the first dead-tunnel handshake;
    progress goes to stderr so the one stdout line stays pure JSON.
    """
    deadline = time.monotonic() + PROBE_WINDOW
    attempt = 0
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        plat, permanent = _probe_once(min(PROBE_TIMEOUT,
                                          max(remaining, 30.0)))
        if plat is not None:
            if attempt > 1:
                print(f"[bench] accelerator up on probe #{attempt}",
                      file=sys.stderr)
            return plat
        remaining = deadline - time.monotonic()
        if permanent or remaining <= PROBE_RETRY_DELAY:
            print(f"[bench] accelerator unreachable after {attempt} "
                  f"probes{' (permanent)' if permanent else ''}; "
                  "cpu fallback", file=sys.stderr)
            return None
        print(f"[bench] probe #{attempt} failed; retrying "
              f"({remaining:.0f}s left in window)", file=sys.stderr)
        time.sleep(PROBE_RETRY_DELAY)


def measure_host(matrix: np.ndarray, data2d: np.ndarray) -> float:
    """GiB/s of the native C++ path on one (k, C) object."""
    from ceph_tpu.native import native_rs_encode, native_available
    if not native_available():
        return 0.0
    rows = matrix[K:]
    native_rs_encode(rows, data2d)  # warm tables
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < TARGET_SECONDS / 2:
        native_rs_encode(rows, data2d)
        n += 1
    dt = time.perf_counter() - t0
    return n * OBJECT_SIZE / dt / (1 << 30)


def measure_device(matrix: np.ndarray, batch: np.ndarray) -> float:
    """GiB/s of the jitted device path on (S, k, C) batches."""
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops.gf_matmul import gf_bit_matmul
    from ceph_tpu.gf.tables import expand_to_bitmatrix

    bits = jnp.asarray(expand_to_bitmatrix(matrix[K:]).astype(np.int8))
    dev = jax.device_put(jnp.asarray(batch))

    # Salt the payload with a never-repeating per-iteration scalar so no
    # layer (XLA or a tunnelled PJRT shim) can serve a repeat dispatch
    # from cache: every iteration is a genuinely new execution.  (Without
    # this, repeat dispatches of identical inputs measured 3-10x above
    # the chip's int8-MXU compute floor — a cache, not the hardware.)
    @jax.jit
    def step(d, b, salt):
        # xor the full 32-bit salt across the payload (bitcast to u32
        # lanes) so the input genuinely never repeats within a run — a
        # uint8 salt would cycle every 256 iterations
        s_, k_, c_ = d.shape
        d32 = jax.lax.bitcast_convert_type(
            d.reshape(s_, k_, c_ // 4, 4), jnp.uint32)
        d8 = jax.lax.bitcast_convert_type(
            d32 ^ salt, jnp.uint8).reshape(s_, k_, c_)
        return gf_bit_matmul(d8, b)

    step(dev, bits, jnp.uint32(0)).block_until_ready()  # compile + warm
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < TARGET_SECONDS:
        step(dev, bits, jnp.uint32(n + 1)).block_until_ready()
        n += 1
    dt = time.perf_counter() - t0
    return n * BATCH * OBJECT_SIZE / dt / (1 << 30)


def measure_decode(matrix: np.ndarray, batch: np.ndarray,
                   erasures: int = 2) -> float:
    """GiB/s of the device decode path with *erasures* data shards lost
    (the reference's ``-w decode -e 2``): reconstruct the missing data
    chunks from k survivors via the signature-cached inverted bitmatrix
    (ErasureCodeIsa decode + table cache role).

    The survivor payload is random rather than real coding output: the
    GF matmul's timing is data-independent, and producing real chunks
    would need a large device->host fetch first — which flips this
    tunnelled transport into a sync-dispatch mode that poisons every
    later measurement in the process (measured: 137 us -> 81 ms per
    dispatch after one 16 MB fetch)."""
    import jax
    import jax.numpy as jnp
    from ceph_tpu.ops.gf_matmul import DeviceRSBackend, gf_bit_matmul

    be = DeviceRSBackend(matrix)
    lost = tuple(range(erasures))                   # data shards 0..e-1
    srcs = tuple(range(erasures, K)) + tuple(K + i for i in range(erasures))
    bits = be._decode_bits_for(srcs, lost)
    dev = jax.device_put(jnp.asarray(batch))        # (S, k, C) survivors

    @jax.jit
    def step(d, b, salt):
        s_, k_, c_ = d.shape
        d32 = jax.lax.bitcast_convert_type(
            d.reshape(s_, k_, c_ // 4, 4), jnp.uint32)
        d8 = jax.lax.bitcast_convert_type(
            d32 ^ salt, jnp.uint8).reshape(s_, k_, c_)
        return gf_bit_matmul(d8, b)

    step(dev, bits, jnp.uint32(0)).block_until_ready()
    n, t0 = 0, time.perf_counter()
    while time.perf_counter() - t0 < TARGET_SECONDS:
        step(dev, bits, jnp.uint32(n + 1)).block_until_ready()
        n += 1
    dt = time.perf_counter() - t0
    return n * BATCH * OBJECT_SIZE / dt / (1 << 30)


def measure_crush_remap(n_osds=1000, n_pgs=100_000, epochs=10,
                        uniform=True):
    """The <50 ms north star: remap ALL PGs after an epoch change.

    The workload is OSDMapMapping's per-epoch job (OSDMapMapping.h:17): the
    crush topology is unchanged (candidate tables cached on device), one
    osd flips out per epoch (new weight vector), and the resolution kernel
    re-derives every PG's mapping.  Reported:
      - wall: full map_batch (device resolve + transfer + host compaction
        + exact residual replay) per epoch, median over ``epochs``;
      - device: sustained resolve-kernel time amortized over back-to-back
        dispatches (what a pipelined consumer pays per epoch).
    """
    import jax
    import jax.numpy as jnp
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    from ceph_tpu.ops.crush_fast import compile_fast_rule
    per_host = 20
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    rng_w = np.random.default_rng(7)
    for h in range(n_osds // per_host):
        osds = list(range(h * per_host, (h + 1) * per_host))
        if uniform:
            ws = [0x10000] * per_host
        else:
            # heterogeneous drives: the f32+risk draw path with exact
            # residual replay (crush_fast.py), not the quotient tables
            ws = [int(v) * 0x8000
                  for v in rng_w.integers(1, 5, size=per_host)]
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}",
                                   osds, ws, id=-(h + 2)))
    cw.set_max_devices(n_osds)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x10000 * per_host] * len(hosts), id=-1)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    xs = np.arange(n_pgs, dtype=np.uint32)
    w = np.full(n_osds, 0x10000, dtype=np.uint32)
    fr = compile_fast_rule(cw.crush, rno, 3)
    fr.map_batch(xs, w)  # compile + candidate tables + warm (full fetch)
    wwarm = w.copy()
    wwarm[1] = 0
    fr.map_batch(xs, wwarm)  # warm the delta-path trace/compile too
    # per-epoch wall time: one osd out per epoch.  map_batch's delta path
    # fetches only changed rows, so the wall is one resolve + one small
    # device->host transfer (OSDMapMapping's per-epoch job).
    walls = []
    for e in range(epochs):
        w2 = w.copy()
        w2[(7 * e + 3) % n_osds] = 0
        t0 = time.perf_counter()
        fr.map_batch(xs, w2)
        walls.append(time.perf_counter() - t0)
    wall_ms = sorted(walls)[len(walls) // 2] * 1000
    # device->host round-trip floor of this transport (tunnelled PJRT
    # pays ~100 ms here; local PCIe pays ~0) so wall_ms is interpretable
    tiny = jnp.zeros((8,), jnp.int32) + jnp.int32(1)
    jax.block_until_ready(tiny)
    t0 = time.perf_counter()
    np.asarray(tiny)
    rtt_ms = (time.perf_counter() - t0) * 1000
    # sustained device resolve time: back-to-back dispatches drained by
    # fetching one element of the LAST output.  PJRT executes in
    # submission order, so that fetch completing means every dispatch
    # completed — block_until_ready alone is not trustworthy over a
    # tunnelled transport (it can acknowledge before remote completion).
    # The fetch round trip itself is subtracted via the measured rtt.
    wds = []
    for e in range(epochs):
        w2 = w.copy()
        w2[(13 * e + 29) % n_osds] = 0
        wds.append(jnp.asarray(w2))
    np.asarray(fr.resolve_device(wds[0])[0][0, 0])   # warm + drain
    t0 = time.perf_counter()
    outs = [fr.resolve_device(wd) for wd in wds]
    np.asarray(outs[-1][0][0, 0])
    total = (time.perf_counter() - t0) * 1000
    dev_ms = max(total - rtt_ms, 0.0) / len(wds)
    host_ms = None
    try:
        from ceph_tpu.native import NativeCrushMapper, native_available
        if native_available():
            nm = NativeCrushMapper(cw.crush)
            sample = 2000
            t0 = time.perf_counter()
            nm.do_rule_batch(rno, xs[:sample].tolist(), 3, w.tolist())
            host_ms = (time.perf_counter() - t0) * (n_pgs / sample) * 1000
    except Exception:
        pass
    return wall_ms, dev_ms, host_ms, fr.residual_fraction, rtt_ms


def main() -> None:
    errors = []
    result = {
        "metric": "ec_encode_k8m4_1MiB_throughput",
        "value": 0.0,
        "unit": "GiB/s",
        "vs_baseline": None,
    }

    global TARGET_SECONDS, BATCH
    platform = probe_accelerator()
    if platform is None:
        # Dead/absent tunnel: keep this process off the accelerator path
        # entirely so nothing below can hang on backend init.  The CPU
        # fallback exists to always emit a parseable line, not to be a
        # meaningful number — shrink the workload so the whole run stays
        # under ~1 minute instead of ~10.
        os.environ["JAX_PLATFORMS"] = "cpu"
        errors.append("accelerator backend unavailable; cpu fallback")
        result["platform"] = "cpu"
        TARGET_SECONDS = 0.5
        BATCH = 4
    else:
        result["platform"] = platform

    try:
        import jax
        if platform is None:
            jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # pragma: no cover - catastrophic env breakage
        errors.append(f"jax import failed: {e!r}")

    from ceph_tpu.gf.matrices import gf_gen_rs_matrix
    rng = np.random.default_rng(1234)
    matrix = gf_gen_rs_matrix(K + M, K)
    batch = rng.integers(0, 256, size=(BATCH, K, CHUNK), dtype=np.uint8)

    host_gibs = 0.0
    try:
        host_gibs = measure_host(matrix, batch[0])
        result["host_native_gibs"] = round(host_gibs, 3)
    except Exception as e:
        errors.append(f"host bench failed: {e!r}")

    def retry_section(label: str, fn) -> None:
        # the tunnel can drop a long-running remote compile mid-flight;
        # re-run the section once (after a settle delay) before
        # recording the failure
        for attempt in range(2):
            try:
                fn()
                return
            except Exception as e:
                if attempt == 1:
                    errors.append(f"{label} failed: {e!r}")
                else:
                    time.sleep(10.0)

    def encode_section() -> None:
        dev_gibs = measure_device(matrix, batch)
        result["value"] = round(dev_gibs, 3)
        if host_gibs:
            result["vs_baseline"] = round(dev_gibs / host_gibs, 2)

    def decode_section() -> None:
        result["ec_decode_e2_gibs"] = round(
            measure_decode(matrix, batch), 3)

    def crush_section() -> None:
        n_pgs = 100_000 if platform else 10_000
        wall_ms, dev_ms, host_ms, resid, rtt_ms = measure_crush_remap(
            n_pgs=n_pgs, epochs=10 if platform else 2)
        result[f"crush_remap_{n_pgs // 1000}k_pgs_ms"] = round(dev_ms, 1)
        result["crush_remap_wall_ms"] = round(wall_ms, 1)
        result["transport_rtt_ms"] = round(rtt_ms, 1)
        result["crush_residual_fraction"] = resid
        if host_ms:
            result["crush_remap_vs_native_host"] = round(
                host_ms / dev_ms, 2)

    def crush_nonuniform_section() -> None:
        # the <50 ms target on a 2-level map with NON-uniform weights:
        # exercises the f32 draw + exact-residual-replay path
        n_pgs = 100_000 if platform else 10_000
        wall_ms, dev_ms, _host, resid, _rtt = measure_crush_remap(
            n_pgs=n_pgs, epochs=10 if platform else 2, uniform=False)
        result["crush_remap_nonuniform_ms"] = round(dev_ms, 1)
        result["crush_remap_nonuniform_wall_ms"] = round(wall_ms, 1)
        result["crush_nonuniform_residual_fraction"] = resid

    retry_section("device bench", encode_section)
    retry_section("decode bench", decode_section)
    retry_section("crush bench", crush_section)
    retry_section("crush nonuniform bench", crush_nonuniform_section)

    if errors:
        result["error"] = "; ".join(errors)
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # last-ditch: the JSON line must still appear,
        print(json.dumps({   # but the exit status stays truthful (rc=1)
            "metric": "ec_encode_k8m4_1MiB_throughput",
            "value": 0.0, "unit": "GiB/s", "vs_baseline": None,
            "error": f"bench crashed: {e!r}",
        }))
        raise SystemExit(1)
