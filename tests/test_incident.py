"""Event journal + incident forensics (ceph_tpu/trace/journal,
ceph_tpu/mgr/incident): the always-on bounded event rings, the causal
merge, and the auto-captured diagnostic bundles on health transitions.

The end-to-end chaos smoke here is the PR's acceptance gate: an OSD
kill plus a 10x-slowed chip must yield ONE auto-captured bundle whose
merged timeline reads causally — fault fire, SUSPECT mark, health
raise, control actuation, health clear — in strictly increasing
global-sequence order, with zero operator action and zero device
syncs (the fence-count extension lives in test_observability.py).
"""
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.fault import g_breakers, g_faults
from ceph_tpu.mgr.incident import incident_perf_counters
from ceph_tpu.trace.journal import (EVENT_TYPES, g_journal,
                                    journal_perf_counters)

TOUCHED = (
    "mgr_journal_ring_size", "mgr_incident_retention",
    "mgr_incident_timeline_tail", "mgr_control_enable",
    "mgr_control_cooldown_ticks", "ec_mesh_chips", "ec_mesh_rateless",
    "ec_mesh_rateless_tasks", "ec_mesh_skew_sample_every",
    "ec_mesh_skew_threshold", "ec_dispatch_batch_max",
    "ec_dispatch_batch_window_us",
)


@pytest.fixture(autouse=True)
def _clean():
    from ceph_tpu.dispatch import g_dispatcher
    from ceph_tpu.mesh import g_chipstat, g_mesh
    g_journal.reset()
    saved = {n: g_conf.values.get(n) for n in TOUCHED}
    yield
    for n, v in saved.items():
        if v is None:
            g_conf.rm_val(n)
        else:
            g_conf.set_val(n, v)
    g_faults.clear()
    g_breakers.reset()
    g_dispatcher.flush()
    g_mesh.topology()
    g_chipstat.reset()
    g_journal.reset()


# ---- the journal itself ----------------------------------------------------
def test_journal_typed_events_and_causal_merge():
    """Typed emit, per-daemon monotone seq, and a merge whose global
    order is emission order (gseq) — never the per-daemon interleave."""
    with pytest.raises(ValueError):
        g_journal.emit("mgr", "not_a_real_event_type")
    g_journal.set_clock(12.0)
    g_journal.emit("mgr", "health_raise", check="A", message="m")
    g_journal.emit("mesh", "chip_suspect_mark", chip=3, probe=7,
                   skew_ratio=4.2)
    g_journal.emit("mgr", "health_clear", check="A")
    merged = g_journal.merged()
    assert [e["daemon"] for e in merged] == ["mgr", "mesh", "mgr"]
    assert [e["type"] for e in merged] == \
        ["health_raise", "chip_suspect_mark", "health_clear"]
    gseqs = [e["gseq"] for e in merged]
    assert gseqs == sorted(gseqs) and len(set(gseqs)) == len(gseqs)
    # per-daemon seq is monotone from 1 independent of the interleave
    mgr_seqs = [e["seq"] for e in merged if e["daemon"] == "mgr"]
    assert mgr_seqs == sorted(mgr_seqs)
    assert all(e["clock"] == 12.0 for e in merged)
    # merged_since is a strict gseq watermark
    later = g_journal.merged_since(merged[0]["gseq"])
    assert [e["type"] for e in later] == \
        ["chip_suspect_mark", "health_clear"]
    assert set(e["type"] for e in merged) <= set(EVENT_TYPES)


def test_journal_ring_bounded_under_10k_event_storm():
    """Bounded memory: a 10k-event storm never grows any daemon ring
    past mgr_journal_ring_size, evictions are counted, and an
    injectargs shrink takes effect on the very next emit."""
    g_conf.set_val("mgr_journal_ring_size", 64)
    pc = journal_perf_counters().dump()
    ev0, evict0 = pc["events"], pc["evictions"]
    for i in range(10_000):
        g_journal.emit("osd.0" if i % 3 else "mgr", "slow_op",
                       description=f"op{i}", duration=0.001)
    d = g_journal.dump()
    for name, ring in d["daemons"].items():
        assert len(ring["events"]) <= 64, \
            f"{name} ring grew past the configured cap"
    # the survivors are the NEWEST events, per-daemon seq still monotone
    tail = d["daemons"]["mgr"]["events"]
    assert tail[-1]["description"] == "op9999"
    seqs = [e["seq"] for e in tail]
    assert seqs == sorted(seqs)
    pc = journal_perf_counters().dump()
    assert pc["events"] == ev0 + 10_000
    assert pc["evictions"] >= evict0 + 10_000 - 2 * 64
    # injectargs-live: shrinking the ring trims on the next emit
    g_conf.set_val("mgr_journal_ring_size", 8)
    g_journal.emit("mgr", "slo_streak", check="X", phase="sustain")
    d = g_journal.dump(daemon="mgr")
    assert len(d["daemons"]["mgr"]["events"]) <= 8
    dropped = g_journal.reset()["dropped"]
    assert dropped > 0
    assert g_journal.dump()["daemons"] == {}


# ---- incident capture ------------------------------------------------------
def _boot(n_osds=4):
    from ceph_tpu.cluster import MiniCluster
    return MiniCluster(n_osds=n_osds)


def test_operator_capture_bundle_shape_and_retention():
    """`tpu incident capture` snapshots a full bundle (trigger, SLO
    streaks, timeline tail, rollup, slow ops, breakers, chips,
    control); the archive honours mgr_incident_retention live —
    shrinking it via set_val prunes immediately (observer)."""
    g_conf.set_val("mgr_incident_retention", 4)
    c = _boot()
    out = c.admin_socket.execute("tpu incident capture")
    assert out["captured"] is True and out["id"] == 1
    bundle = c.admin_socket.execute("tpu incident dump")["incident"]
    for key in ("id", "clock", "state", "reason", "trigger", "slo",
                "health_checks", "timeline", "rollup", "slow_ops",
                "breakers_open", "chip_scoreboard", "control"):
        assert key in bundle, f"bundle missing {key}"
    assert bundle["state"] == "manual"
    assert bundle["reason"] == "operator"
    # the capture itself is journaled, so the NEXT bundle's timeline
    # carries the previous incident_capture event
    out2 = c.admin_socket.execute("tpu incident capture")
    b2 = c.admin_socket.execute(
        "tpu incident dump", {"id": str(out2["id"])})["incident"]
    assert any(e["type"] == "incident_capture"
               for e in b2["timeline"])
    for _ in range(6):
        c.admin_socket.execute("tpu incident capture")
    listing = c.admin_socket.execute("tpu incident list")
    assert len(listing["incidents"]) == 4, "retention cap ignored"
    assert listing["captures_total"] == 8
    # ids survive pruning: the listing holds the NEWEST four
    assert [r["id"] for r in listing["incidents"]] == [5, 6, 7, 8]
    # injectargs-live shrink prunes the archive immediately
    g_conf.set_val("mgr_incident_retention", 2)
    listing = c.admin_socket.execute("tpu incident list")
    assert [r["id"] for r in listing["incidents"]] == [7, 8]
    with pytest.raises(ValueError):
        c.mgr.incident.dump(incident_id=999)


def test_capture_failure_drops_bundle_never_wedges():
    """Chaos-style: an injected `mgr.incident_capture` failure drops
    THAT bundle (dropped counter up, archive unchanged, drop event
    journaled) and the next raise captures normally — a failing
    capture can never wedge the mgr tick."""
    c = _boot()
    pc0 = incident_perf_counters().dump()
    g_faults.inject("mgr.incident_capture", mode="once")
    out = c.admin_socket.execute("tpu incident capture")
    assert out["captured"] is False
    pc = incident_perf_counters().dump()
    assert pc["dropped"] == pc0["dropped"] + 1
    assert c.admin_socket.execute("tpu incident list")["incidents"] \
        == []
    assert any(e["type"] == "incident_drop"
               for e in g_journal.merged())
    # the once-shot is spent: the next capture lands
    out = c.admin_socket.execute("tpu incident capture")
    assert out["captured"] is True
    assert len(c.admin_socket.execute(
        "tpu incident list")["incidents"]) == 1
    # a real raise right after an injected drop also still captures:
    # force a health raise through the tick-diff path
    g_faults.inject("mgr.incident_capture", mode="once")
    c.mgr.health_checks["TPU_TEST_RAISE"] = \
        "synthetic raise for the drop test"
    c.clock += 1.0
    c.mgr.tick(c.clock)          # raise journaled, capture DROPPED
    assert "TPU_TEST_RAISE" in [
        e.get("check") for e in g_journal.merged()
        if e["type"] == "health_raise"]
    n_before = len(c.admin_socket.execute(
        "tpu incident list")["incidents"])
    del c.mgr.health_checks["TPU_TEST_RAISE"]
    c.mgr.health_checks["TPU_TEST_RAISE_2"] = "second raise captures"
    c.clock += 1.0
    c.mgr.tick(c.clock)
    listing = c.admin_socket.execute("tpu incident list")
    assert len(listing["incidents"]) == n_before + 1
    assert listing["incidents"][-1]["trigger"] == "TPU_TEST_RAISE_2"
    del c.mgr.health_checks["TPU_TEST_RAISE_2"]


# ---- the acceptance chaos scenario -----------------------------------------
@pytest.mark.chaos
def test_chaos_storyline_yields_causally_ordered_bundle():
    """OSD kill + 10x chip slowdown: the mgr auto-captures a bundle on
    the TPU_MESH_SKEW raise with ZERO operator action, and once the
    check clears the finalized bundle's timeline contains the full
    causal chain — fault_fire -> chip_suspect_mark -> health_raise ->
    control_actuate -> health_clear — in strictly increasing gseq
    order, the osd_down/osd_out events riding the same merged tail."""
    import numpy as np
    from ceph_tpu.dispatch import g_dispatcher
    from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
    from ceph_tpu.mesh import g_chipstat
    from ceph_tpu.osd.ecutil import encode as eu_encode, stripe_info_t

    g_conf.set_val("ec_mesh_chips", 8)
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    g_conf.set_val("ec_mesh_skew_threshold", 3.0)
    g_conf.set_val("ec_mesh_rateless", True)
    g_conf.rm_val("ec_mesh_rateless_tasks")
    # a long tail keeps every fault_fire of the storm in the bundle
    g_conf.set_val("mgr_incident_timeline_tail", 512)
    c = _boot(n_osds=4)
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 1)
    impl = ErasureCodeTpu()
    impl.init({"k": "4", "m": "2", "technique": "reed_sol_van"})
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    rng = np.random.default_rng(20260807)

    def flush():
        payloads = [rng.integers(0, 256, size=2 * 4 * 1024,
                                 dtype=np.uint8) for _ in range(3)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        for f, oracle in zip(futs, oracles):
            res = f.result()
            assert sorted(res) == sorted(oracle)

    flush()                                    # compile warmup
    g_chipstat.reset()
    g_journal.reset()
    # ---- the composed storyline: an OSD dies AND a chip goes slow ---
    c.kill_osd(3)
    c.mark_osd_down(3)
    c.mark_osd_out(3)
    g_faults.inject("mesh.chip_slowdown", mode="always",
                    match="chip=5/", delay_us=30_000)
    raised_at = None
    try:
        for i in range(16):
            flush()
            c.tick(dt=1.0)
            if "TPU_MESH_SKEW" in c.mgr.health_checks:
                raised_at = i
                break
    finally:
        g_faults.clear("mesh.chip_slowdown")
    assert raised_at is not None, c.mgr.health_checks
    # the raise auto-captured — no operator involved
    listing = c.admin_socket.execute("tpu incident list")
    assert listing["captures_total"] >= 1
    assert listing["incidents"][0]["trigger"] == "TPU_MESH_SKEW"
    assert listing["incidents"][0]["state"] == "open"
    # ---- fault gone: keep flushing until the hysteretic clear -------
    cleared = False
    for _ in range(40):
        flush()
        c.tick(dt=1.0)
        if "TPU_MESH_SKEW" not in c.mgr.health_checks:
            cleared = True
            break
    assert cleared, c.mgr.health_checks
    bundle = next(b for b in c.admin_socket.execute(
        "tpu incident list")["incidents"]
        if b["trigger"] == "TPU_MESH_SKEW")
    bundle = c.admin_socket.execute(
        "tpu incident dump", {"id": str(bundle["id"])})["incident"]
    assert bundle["state"] == "resolved"
    tl = bundle["timeline"]
    gseqs = [e["gseq"] for e in tl]
    assert gseqs == sorted(gseqs) and len(set(gseqs)) == len(gseqs), \
        "bundle timeline is not strictly gseq-ordered"

    def first(etype, **match):
        for e in tl:
            if e["type"] == etype and all(
                    e.get(k) == v for k, v in match.items()):
                return e["gseq"]
        raise AssertionError(
            f"{etype} {match} missing from the bundle timeline: "
            f"{[(e['gseq'], e['daemon'], e['type']) for e in tl]}")

    fire = first("fault_fire", site="mesh.chip_slowdown")
    mark = first("chip_suspect_mark", chip=5)
    raise_ = first("health_raise", check="TPU_MESH_SKEW")
    act = first("control_actuate", knob="ec_mesh_rateless_tasks")
    clear = first("health_clear", check="TPU_MESH_SKEW")
    assert fire < mark < raise_ < act < clear, \
        (fire, mark, raise_, act, clear)
    # the OSD leg of the storyline rode the same merged journal
    assert any(e["type"] == "osd_down" and e["daemon"].startswith("mon")
               for e in g_journal.merged())
    assert any(e["type"] == "osd_out" for e in g_journal.merged())
