"""rgw-lite: buckets, objects, two-phase index, multipart, S3 HTTP.

Mirrors the reference's rgw test surface at lite scale (src/test/rgw):
bucket/object CRUD with EC data pools, ListObjects prefix/delimiter/
marker semantics, the cls_rgw two-phase index protocol under a
simulated gateway crash, multipart stitching, and the path-style S3
REST frontend with v2-HMAC auth over a real socket.
"""
import hashlib
import json

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rgw import RGWError, RGWLite, S3Frontend, serve
from ceph_tpu.rgw.http import _sign_v2


@pytest.fixture()
def rgw():
    c = MiniCluster(n_osds=5)
    c.create_replicated_pool("rgwmeta", size=3, pg_num=8)
    c.create_ec_pool("rgwdata", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.rgw")
    g = RGWLite(cl, "rgwmeta", "rgwdata")
    user = g.create_user("alice", "Alice")
    return c, cl, g, user


def test_user_bucket_lifecycle(rgw):
    c, cl, g, user = rgw
    assert g.get_user("alice")["access_key"] == user["access_key"]
    assert g.user_by_access_key(user["access_key"])["uid"] == "alice"
    assert g.user_by_access_key("nope") is None
    with pytest.raises(RGWError):
        g.create_user("alice")
    g.create_bucket("alice", "photos")
    g.create_bucket("alice", "logs")
    assert g.list_buckets("alice") == ["logs", "photos"]
    with pytest.raises(RGWError):
        g.create_bucket("alice", "photos")
    g.put_object("logs", "x", b"data")
    with pytest.raises(RGWError):
        g.delete_bucket("logs")              # BucketNotEmpty
    g.delete_object("logs", "x")
    g.delete_bucket("logs")
    assert g.list_buckets("alice") == ["photos"]


def test_object_roundtrip_and_chunking(rgw):
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    import ceph_tpu.rgw.gateway as gw
    old = gw.CHUNK
    gw.CHUNK = 4096                          # force multi-chunk
    try:
        payload = bytes(range(256)) * 64     # 16 KiB -> 4 chunks
        meta = g.put_object("b", "big.bin", payload)
        assert meta["size"] == len(payload)
        assert meta["etag"] == hashlib.md5(payload).hexdigest()
        assert meta["chunks"] == 4
        assert g.get_object("b", "big.bin") == payload
        # overwrite with smaller single-chunk payload
        g.put_object("b", "big.bin", b"small")
        assert g.get_object("b", "big.bin") == b"small"
        g.delete_object("b", "big.bin")
        with pytest.raises(RGWError):
            g.head_object("b", "big.bin")
    finally:
        gw.CHUNK = old


def test_list_prefix_delimiter_marker(rgw):
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    for k in ["a/1.txt", "a/2.txt", "a/sub/3.txt", "b/4.txt", "top.txt"]:
        g.put_object("b", k, b"x")
    res = g.list_objects("b")
    assert [e["name"] for e in res["contents"]] == [
        "a/1.txt", "a/2.txt", "a/sub/3.txt", "b/4.txt", "top.txt"]
    res = g.list_objects("b", prefix="a/")
    assert [e["name"] for e in res["contents"]] == [
        "a/1.txt", "a/2.txt", "a/sub/3.txt"]
    res = g.list_objects("b", delimiter="/")
    assert [e["name"] for e in res["contents"]] == ["top.txt"]
    assert res["common_prefixes"] == ["a/", "b/"]
    res = g.list_objects("b", prefix="a/", delimiter="/")
    assert [e["name"] for e in res["contents"]] == ["a/1.txt", "a/2.txt"]
    assert res["common_prefixes"] == ["a/sub/"]
    res = g.list_objects("b", marker="a/2.txt")
    assert [e["name"] for e in res["contents"]] == [
        "a/sub/3.txt", "b/4.txt", "top.txt"]
    res = g.list_objects("b", max_keys=2)
    assert len(res["contents"]) == 2 and res["truncated"]


def test_two_phase_index_crash_safety(rgw):
    """A gateway dying between data write and index complete must not
    surface a listing entry (cls_rgw prepare/complete contract)."""
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    b = g.get_bucket("b")
    idx = g._index_oid(b["id"])
    # simulate the crash: prepare + data, no complete
    g._exec("rgwmeta", idx, "bucket_prepare_op",
            {"tag": "t1", "name": "ghost", "op": "put"})
    g._write_chunked(g._data_oid(b["id"], "ghost"), b"orphan")
    res = g.list_objects("b")
    assert res["contents"] == []             # never listed
    with pytest.raises(RGWError):
        g.head_object("b", "ghost")
    stats = json.loads(g._exec("rgwmeta", idx, "bucket_stats"))
    assert stats["pending_ops"] == 1         # the debt is visible
    # a later complete with the same tag lands exactly once
    g._exec("rgwmeta", idx, "bucket_complete_op",
            {"tag": "t1", "name": "ghost", "op": "put",
             "meta": {"size": 6, "etag": "x", "mtime": 0,
                      "content_type": "b", "chunks": 1}})
    assert [e["name"] for e in g.list_objects("b")["contents"]] == \
        ["ghost"]
    # completing a cancelled/unknown tag is ECANCELED
    with pytest.raises(RGWError) as ei:
        g._exec("rgwmeta", idx, "bucket_complete_op",
                {"tag": "zz", "name": "n", "op": "put", "meta": {}})
    assert ei.value.result == -125


def test_key_chunk_namespace_no_collision(rgw):
    """A key named like another key's chunk object must not collide
    (distinct o_/c_/mp_ data-oid namespaces)."""
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    import ceph_tpu.rgw.gateway as gw
    old = gw.CHUNK
    gw.CHUNK = 4096
    try:
        big = bytes(range(256)) * 32             # 8 KiB -> 2 chunks
        g.put_object("b", "a", big)
        g.put_object("b", "a.chunk.1", b"innocent")  # old collision name
        g.put_object("b", "a.1", b"also-fine")
        assert g.get_object("b", "a") == big     # chunks intact
        assert g.get_object("b", "a.chunk.1") == b"innocent"
        g.delete_object("b", "a.chunk.1")
        assert g.get_object("b", "a") == big     # still intact
        # shrinking overwrite collects the stranded tail chunks
        b = g.get_bucket("b")
        tail = g._chunk_oids(b["id"], "a", 2)[1]
        cl.read("rgwdata", tail)                 # exists before
        g.put_object("b", "a", b"tiny")
        with pytest.raises(IOError):
            cl.read("rgwdata", tail)             # collected after
    finally:
        gw.CHUNK = old


def test_reads_require_ownership(rgw):
    """GET/HEAD/listing are owner-gated too, not just mutations."""
    c, cl, g, user = rgw
    g.create_bucket("alice", "secret")
    g.put_object("secret", "doc", b"private")
    mallory = g.create_user("mallory")
    fe = S3Frontend(g)

    def req(method, path, u):
        from ceph_tpu.rgw.http import _sign_v2 as sv
        sig = sv(u["secret_key"], method, "d", path.split("?")[0])
        return fe.handle(method, path, {
            "Date": "d", "Authorization": f"AWS {u['access_key']}:{sig}"})

    assert req("GET", "/secret/doc", mallory)[0] == 403
    assert req("HEAD", "/secret/doc", mallory)[0] == 403
    assert req("GET", "/secret", mallory)[0] == 403
    assert req("GET", "/secret/doc", user)[0] == 200
    # malformed query args return an S3 error, not a dropped socket
    st, _, out = fe.handle("GET", "/secret?max-keys=abc", {
        "Date": "d", "Authorization": "AWS %s:%s" % (
            user["access_key"],
            __import__("ceph_tpu.rgw.http", fromlist=["_sign_v2"]
                       )._sign_v2(user["secret_key"], "GET", "d",
                                  "/secret"))}, b"",
        {"max-keys": "abc"})
    assert st == 400 and b"InvalidArgument" in out


def test_delimiter_truncation_honest(rgw):
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    for k in ["a/1", "b/2", "top"]:
        g.put_object("b", k, b"x")
    res = g.list_objects("b", delimiter="/", max_keys=1)
    assert res["truncated"] is True              # more rollups remain
    assert res["common_prefixes"] == ["a/"]


def test_multipart(rgw):
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    uid = g.initiate_multipart("b", "assembled")
    g.upload_part("b", "assembled", uid, 2, b"-part-two")
    g.upload_part("b", "assembled", uid, 1, b"part-one")
    meta = g.complete_multipart("b", "assembled", uid)
    assert g.get_object("b", "assembled") == b"part-one-part-two"
    assert meta["size"] == len(b"part-one-part-two")
    # parts staging is cleaned up
    with pytest.raises(RGWError):
        g.upload_part("b", "assembled", uid, 3, b"late")
    # abort path
    uid2 = g.initiate_multipart("b", "dropped")
    g.upload_part("b", "dropped", uid2, 1, b"zzz")
    g.abort_multipart("b", "dropped", uid2)
    with pytest.raises(RGWError):
        g.head_object("b", "dropped")


def test_s3_http_frontend(rgw):
    """Full S3 path-style REST roundtrip over a real socket with
    v2-HMAC auth."""
    import http.client

    c, cl, g, user = rgw
    fe = S3Frontend(g)
    srv, port = serve(fe)
    try:
        def req(method, path, body=b"", sign_as=user, date="now"):
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=30)
            headers = {"Date": date}
            if sign_as is not None:
                sig = _sign_v2(sign_as["secret_key"], method, date,
                               path.split("?")[0])
                headers["Authorization"] = \
                    f"AWS {sign_as['access_key']}:{sig}"
            conn.request(method, path, body, headers)
            r = conn.getresponse()
            out = r.read()
            conn.close()
            return r.status, dict(r.getheaders()), out

        assert req("PUT", "/web")[0] == 200
        st, hdrs, _ = req("PUT", "/web/site/index.html",
                          b"<h1>hello</h1>")
        assert st == 200
        assert hdrs["ETag"] == \
            f'"{hashlib.md5(b"<h1>hello</h1>").hexdigest()}"'
        st, hdrs, out = req("GET", "/web/site/index.html")
        assert st == 200 and out == b"<h1>hello</h1>"
        st, hdrs, _ = req("HEAD", "/web/site/index.html")
        assert st == 200 and hdrs["Content-Length"] == "14"
        req("PUT", "/web/site/a.css", b"body{}")
        st, _, out = req("GET", "/web?prefix=site/&delimiter=/")
        assert st == 200
        assert b"<Key>site/a.css</Key>" in out
        assert b"<Key>site/index.html</Key>" in out
        st, _, out = req("GET", "/")
        assert b"<Name>web</Name>" in out
        # auth failures
        assert req("GET", "/web/site/index.html", sign_as=None)[0] == 403
        bad = dict(user, secret_key="wrong")
        assert req("GET", "/web/site/index.html", sign_as=bad)[0] == 403
        # another user cannot write into alice's bucket
        mallory = g.create_user("mallory")
        st, _, out = req("PUT", "/web/evil", b"x", sign_as=mallory)
        assert st == 403
        assert req("DELETE", "/web/site/index.html")[0] == 204
        st, _, out = req("GET", "/web/site/index.html")
        assert st == 404 and b"NoSuchKey" in out
    finally:
        srv.shutdown()


def test_list_objects_v2(rgw):
    """S3 ListObjectsV2: continuation tokens + KeyCount."""
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    for i in range(5):
        g.put_object("b", f"k{i}", b"x")
    fe = S3Frontend(g)

    def req(path, query):
        sig = _sign_v2(user["secret_key"], "GET", "d",
                       path.split("?")[0])
        return fe.handle("GET", path, {
            "Date": "d",
            "Authorization": f"AWS {user['access_key']}:{sig}"},
            b"", query)

    st, _, out = req("/b", {"list-type": "2", "max-keys": "2"})
    assert st == 200
    assert b"<KeyCount>2</KeyCount>" in out
    assert b"<NextContinuationToken>k1</NextContinuationToken>" in out
    st, _, out = req("/b", {"list-type": "2", "max-keys": "2",
                            "continuation-token": "k1"})
    assert b"<Key>k2</Key>" in out and b"<Key>k3</Key>" in out
    st, _, out = req("/b", {"list-type": "2",
                            "continuation-token": "k3"})
    assert b"<Key>k4</Key>" in out
    assert b"<IsTruncated>false</IsTruncated>" in out
    # start-after works like an initial cursor
    st, _, out = req("/b", {"list-type": "2", "start-after": "k2"})
    assert b"<Key>k3</Key>" in out and b"<Key>k0</Key>" not in out


def test_v2_delimiter_pagination_no_stall_no_dupes(rgw):
    """Prefix groups are never split across pages: pagination with a
    delimiter always yields a continuation token and never repeats a
    CommonPrefix (boto3-paginator compatibility)."""
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    for k in ["a", "p/1", "p/2", "p/3", "q"]:
        g.put_object("b", k, b"x")
    # page of 1 starting at the rollup: token must still appear
    res = g.list_objects("b", delimiter="/", max_keys=1, marker="a")
    assert res["common_prefixes"] == ["p/"]
    assert res["truncated"] and res["next_marker"] == "p/3"
    res2 = g.list_objects("b", delimiter="/", max_keys=10,
                          marker=res["next_marker"])
    assert [e["name"] for e in res2["contents"]] == ["q"]
    assert res2["common_prefixes"] == []
    # mixed page: group is consumed whole, not split
    res = g.list_objects("b", delimiter="/", max_keys=2)
    assert [e["name"] for e in res["contents"]] == ["a"]
    assert res["common_prefixes"] == ["p/"]
    assert res["next_marker"] == "p/3"


def test_gc_protects_bucket_with_lost_index(rgw):
    """Meta alive, index object LOST: the bucket's data is unknowable
    and gc must not touch it (the inverse of lost-meta protection)."""
    c, cl, g, user = rgw
    g.create_bucket("alice", "b")
    g.put_object("b", "obj", b"indexed")
    bid = g.get_bucket("b")["id"]
    cl.remove("rgwmeta", g._index_oid(bid))
    report = g.gc(repair=True)
    assert g._data_oid(bid, "obj") not in report["orphan_objects"]
    cl.read("rgwdata", g._data_oid(bid, "obj"))   # data intact
    # the listing itself is loud, not silently empty
    with pytest.raises(RGWError) as ei:
        g.list_objects("b")
    assert ei.value.result == -116


def test_swift_api(rgw):
    """The Swift dialect over the same gateway core: auth token,
    container + object CRUD, listings (plain + json + delimiter)."""
    import http.client
    from ceph_tpu.rgw import SwiftFrontend

    c, cl, g, user = rgw
    fe = SwiftFrontend(g)
    # auth handshake
    st, hdrs, _ = fe.handle("GET", "/auth/v1.0", {
        "X-Auth-User": "alice:swift",
        "X-Auth-Key": user["secret_key"]})
    assert st == 204
    token = hdrs["X-Auth-Token"]
    url = hdrs["X-Storage-Url"]
    assert url == "/v1/AUTH_alice"
    assert fe.handle("GET", "/auth/v1.0", {
        "X-Auth-User": "alice:swift", "X-Auth-Key": "wrong"})[0] == 401
    auth = {"X-Auth-Token": token}
    # containers + objects
    assert fe.handle("PUT", f"{url}/photos", auth)[0] == 201
    assert fe.handle("PUT", f"{url}/photos", auth)[0] == 202  # existed
    st, hdrs, _ = fe.handle("PUT", f"{url}/photos/a/cat.jpg", auth,
                            b"meow")
    assert st == 201
    fe.handle("PUT", f"{url}/photos/dog.jpg", auth, b"woof")
    st, _, out = fe.handle("GET", f"{url}/photos/a/cat.jpg", auth)
    assert st == 200 and out == b"meow"
    st, hdrs, _ = fe.handle("HEAD", f"{url}/photos/dog.jpg", auth)
    assert st == 200 and hdrs["Content-Length"] == "4"
    # listings
    st, _, out = fe.handle("GET", f"{url}/photos", auth)
    assert out == b"a/cat.jpg\ndog.jpg\n"
    st, _, out = fe.handle("GET", f"{url}/photos", auth, b"",
                           {"delimiter": "/"})
    assert out == b"dog.jpg\na/\n"
    st, _, out = fe.handle("GET", f"{url}/photos", auth, b"",
                           {"format": "json"})
    listing = json.loads(out)
    assert {e.get("name") for e in listing} == {"a/cat.jpg", "dog.jpg"}
    # account listing + auth boundaries
    st, _, out = fe.handle("GET", url, auth)
    assert st == 200 and b"photos" in out
    mallory = g.create_user("mallory")
    st, h2, _ = fe.handle("GET", "/auth/v1.0", {
        "X-Auth-User": "mallory:swift",
        "X-Auth-Key": mallory["secret_key"]})
    assert fe.handle("GET", f"{url}/photos/dog.jpg",
                     {"X-Auth-Token": h2["X-Auth-Token"]})[0] == 401
    mauth = {"X-Auth-Token": h2["X-Auth-Token"]}
    assert fe.handle("GET", f"/v1/AUTH_mallory/../photos",
                     mauth)[0] in (401, 404)
    # cleanup path
    fe.handle("DELETE", f"{url}/photos/a/cat.jpg", auth)
    fe.handle("DELETE", f"{url}/photos/dog.jpg", auth)
    assert fe.handle("DELETE", f"{url}/photos", auth)[0] == 204
