"""choose_acting + pg_temp: EC shard-position shuffles must not lose data.

CRUSH indep re-draws can move SURVIVING osds to different shard
positions when a member goes out (a collision cascade).  The pg_log is
per-OSD, so a shuffled replica's log looks current while its store
holds the WRONG shard — without choose_acting the primary computes an
empty missing set and serves EIO forever.  The primary now compares
each peer's held shards against its acting position and pins pg_temp
via the mon (OSD::send_pg_temp / MOSDPGTemp) so data-bearing OSDs keep
serving the shards they hold while freed positions backfill.
"""
import pytest

from ceph_tpu.cluster import MiniCluster


def _shards_of(c, oid):
    out = {}
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == oid:
                    out.setdefault(osd.osd_id, []).append(
                        (cid, ho.shard))
    return out


def _find_shuffling_object(c, cl, pool_id):
    """An oid whose EC pg experiences a position shuffle when its
    primary goes out (brute-forced; CRUSH makes some exist)."""
    for i in range(200):
        oid = f"probe-{i}"
        pgid, primary = cl._calc_target(pool_id, oid)
        import copy
        m = c.mon.osdmap
        from ceph_tpu.osdmap import pg_t
        pg = pg_t(*pgid)
        *_, acting, _p = m.pg_to_up_acting_osds(pg)
        # simulate the weight-out remap
        m2 = copy.deepcopy(m)
        m2.osd_weight[primary] = 0
        m2.pg_temp.clear()
        *_, acting2, _p2 = m2.pg_to_up_acting_osds(pg)
        survivors_moved = any(
            o in acting2 and acting2.index(o) != s
            for s, o in enumerate(acting) if o != primary)
        if survivors_moved:
            return oid, primary
    pytest.skip("no shuffling pg found in 200 probes")


def test_ec_position_shuffle_recovers_via_pg_temp():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("pt", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.t")
    oid, victim = _find_shuffling_object(c, cl, cl.lookup_pool("pt"))
    payload = bytes(range(256)) * 32
    cl.write_full("pt", oid, payload)
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    c.mark_osd_out(victim)
    for _ in range(6):
        c.run_recovery()
        c.network.pump()
    # data survives the shuffle
    assert cl.read("pt", oid) == payload
    # a pg_temp pin realigned the acting set to the data holders
    assert c.mon.osdmap.pg_temp, "expected a pg_temp pin"
    # and full redundancy is restored: k+m distinct live osds hold chunks
    holders = {o for o, lst in _shards_of(c, oid).items() if o != victim}
    assert len(holders) >= 3, _shards_of(c, oid)
    # overwrite still works under the pinned acting set
    cl.write_full("pt", oid, b"fresh")
    assert cl.read("pt", oid) == b"fresh"


def test_pg_temp_clears_after_realign_to_up():
    """Once the PG is clean under a pin, the primary pushes each shard
    to its CRUSH-up position and clears pg_temp — the pin is temporary,
    so a later failure of a pinned member cannot strand the PG."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("pt", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.t")
    oid, victim = _find_shuffling_object(c, cl, cl.lookup_pool("pt"))
    payload = bytes(range(256)) * 16
    cl.write_full("pt", oid, payload)
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    c.mark_osd_out(victim)
    for _ in range(6):
        c.run_recovery()
        c.network.pump()
    assert cl.read("pt", oid) == payload
    assert c.mon.osdmap.pg_temp
    # ticks drive realign-to-up; the pin must clear and data stay intact
    for _ in range(12):
        c.tick(dt=6.0)
        c.run_recovery()
        c.network.pump()
    assert not c.mon.osdmap.pg_temp, c.mon.osdmap.pg_temp
    assert cl.read("pt", oid) == payload
    cl.write_full("pt", oid, b"after-clear")
    assert cl.read("pt", oid) == b"after-clear"
