"""Stray PG removal: old copies are purged once the PG is clean.

The reference keeps a migrated-away PG's data as a "stray" until the
primary confirms the PG is clean, then authorizes deletion
(PG RecoveryState::Stray notifies, src/messages/MOSDPGRemove.h,
OSD::_remove_pg).  Here strays self-report from the store (so copies
with no live PG object — restarts — are found too), a clean unpinned
primary acks with MOSDPGRemove, and the stray re-checks its own map
before deleting.  Stale copies otherwise accumulate forever and
confuse choose_acting's holder bookkeeping.
"""
from __future__ import annotations

import numpy as np

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osdmap import pg_t

NONE = 0x7FFFFFFF


def _settle(c, rounds=6):
    for _ in range(rounds):
        c.network.pump()
        c.run_recovery()


def _stray_collections(c, pid):
    """[(osd, cid)] for data held by non-members, across the cluster."""
    out = []
    pool = c.mon.osdmap.pools[pid]
    for i, osd in c.osds.items():
        for pg_id, cids in osd._local_pg_collections().items():
            if pg_id[0] != pid or pg_id[1] >= pool.pg_num:
                continue
            up, _u, acting, _a = \
                c.mon.osdmap.pg_to_up_acting_osds(pg_t(*pg_id))
            members = {o for o in list(up) + list(acting) if o != NONE}
            if i not in members:
                out.extend((i, cid) for cid in cids)
    return out


def test_migration_strays_get_removed():
    """After a pgp_num migration, the old holders' copies disappear
    once every PG is clean — and the data stays fully readable."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("e", k=2, m=1, plugin="isa", pg_num=8,
                     failure_domain="osd")
    cl = c.client()
    rng = np.random.default_rng(1)
    blobs = {f"o{i}": rng.integers(0, 256, 4096,
                                   dtype=np.uint8).tobytes()
             for i in range(12)}
    for oid, d in blobs.items():
        assert cl.write_full("e", oid, d) == 0
    pid = c.mon.osdmap.lookup_pg_pool_name("e")
    c.mon.set_pool_pg_num("e", 16)
    c.publish()
    _settle(c)
    c.mon.set_pool_pgp_num("e", 16)
    c.publish()
    for _ in range(12):
        c.tick(dt=1.0)
        _settle(c, rounds=3)
    assert not c.mon.osdmap.pg_temp
    # several tick rounds: notify -> remove ack -> deletion
    for _ in range(8):
        c.tick(dt=6.0)
        _settle(c, rounds=3)
    strays = _stray_collections(c, pid)
    assert strays == [], f"stray copies survived: {strays}"
    for oid, d in blobs.items():
        assert cl.read("e", oid) == d


def test_degraded_pg_keeps_its_strays():
    """While a PG is degraded its strays must NOT be purged — they can
    become recovery sources (choose_acting can pin back to them)."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("e", k=2, m=1, plugin="isa", pg_num=8,
                     failure_domain="osd")
    cl = c.client()
    rng = np.random.default_rng(2)
    for i in range(12):
        assert cl.write_full(
            "e", f"o{i}",
            rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()) == 0
    pid = c.mon.osdmap.lookup_pg_pool_name("e")
    c.mon.set_pool_pg_num("e", 16)
    c.publish()
    _settle(c)
    c.mon.set_pool_pgp_num("e", 16)
    c.publish()
    # migrate, but then kill an OSD so some PGs go degraded BEFORE the
    # strays are acked away
    for _ in range(4):
        c.tick(dt=1.0)
        _settle(c, rounds=2)
    victim = 0
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
        _settle(c, rounds=2)
    rng = np.random.default_rng(2)
    for i in range(12):
        expect = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
        assert cl.read("e", f"o{i}") == expect
    # and assert the GATE itself: a primary that is recovering (or
    # pinned, or whose data lags the stray) must not ack a removal
    from ceph_tpu.msg.messages import MOSDPGNotify, MOSDPGRemove
    from ceph_tpu.osd.pg import STATE_ACTIVE_RECOVERING
    live = next(o for o in c.osds.values())
    pg = next(p for p in live.pgs.values() if p.is_primary())
    saved_state = pg.state
    pg.state = STATE_ACTIVE_RECOVERING
    before = len(c.network.queue)
    live._handle_pg_notify(MOSDPGNotify(
        pgid=pg.pgid, epoch=live.osdmap.epoch, from_osd=99,
        held_shards=[0], last_update=0))
    removes = [m for _s, _d, m in list(c.network.queue)[before:]
               if isinstance(m, MOSDPGRemove)]
    assert removes == [], "recovering primary acked a stray removal"
    pg.state = saved_state
    # a stray NEWER than the primary's data is refused even when clean
    if pg.state == "active":
        before = len(c.network.queue)
        live._handle_pg_notify(MOSDPGNotify(
            pgid=pg.pgid, epoch=live.osdmap.epoch, from_osd=99,
            held_shards=[0],
            last_update=pg.data_high_water() + 1000))
        removes = [m for _s, _d, m in list(c.network.queue)[before:]
                   if isinstance(m, MOSDPGRemove)]
        assert removes == [], "primary acked removal of a NEWER stray"


def test_restarted_stray_is_found_from_the_store():
    """A stray with no live PG object (OSD restarted after the remap)
    is discovered by scanning the store and still gets purged."""
    c = MiniCluster(n_osds=6)
    c.create_replicated_pool("p", size=3, pg_num=8)
    cl = c.client()
    rng = np.random.default_rng(3)
    blobs = {f"r{i}": rng.integers(0, 256, 3000,
                                   dtype=np.uint8).tobytes()
             for i in range(10)}
    for oid, d in blobs.items():
        assert cl.write_full("p", oid, d) == 0
    pid = c.mon.osdmap.lookup_pg_pool_name("p")
    c.mon.set_pool_pg_num("p", 16)
    c.publish()
    _settle(c)
    c.mon.set_pool_pgp_num("p", 16)
    c.publish()
    for _ in range(10):
        c.tick(dt=1.0)
        _settle(c, rounds=3)
    # restart every OSD: stray PG objects are gone, collections remain
    for i in list(c.osds):
        c.restart_osd(i)
    _settle(c, rounds=6)
    for _ in range(8):
        c.tick(dt=6.0)
        _settle(c, rounds=3)
    strays = _stray_collections(c, pid)
    assert strays == [], f"stray copies survived restart: {strays}"
    for oid, d in blobs.items():
        assert cl.read("p", oid) == d
