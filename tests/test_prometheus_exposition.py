"""Golden test: Manager.prometheus_metrics renders valid text exposition.

Satellite of the observability PR: every line must parse under the
Prometheus text-format grammar, histogram families must be declared
``# TYPE ... histogram`` with cumulative/monotone ``_bucket`` series
ending in a ``+Inf`` bucket equal to ``_count``, and the
``_bucket``/``_sum``/``_count`` names must be consistent per family.
"""
import math
import re

import pytest

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME})"                                   # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\]*\""        # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"\\]*\")*\})?"   # more labels
    r" (-?\d+(\.\d+)?([eE][+-]?\d+)?|[+-]Inf|NaN)$"  # value
)


def _parse(text):
    """(types, samples): metric family types and parsed sample lines."""
    types = {}
    samples = []          # (name, labels_str, value)
    seen_names = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            assert parts[0] == "#" and parts[1] in ("HELP", "TYPE"), \
                f"malformed comment line: {line!r}"
            if parts[1] == "TYPE":
                name = parts[2]
                assert name not in types, f"duplicate TYPE for {name}"
                assert name not in seen_names, \
                    f"TYPE for {name} after its samples"
                types[name] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name, labels = m.group(1), m.group(2) or ""
        value = float(m.group(4).replace("Inf", "inf"))
        seen_names.add(name)
        samples.append((name, labels, value))
    return types, samples


def _labels_minus_le(labels: str):
    inner = labels.strip("{}")
    return tuple(sorted(kv for kv in inner.split(",")
                        if kv and not kv.startswith("le=")))


def _le_of(labels: str):
    m = re.search(r'le="([^"]+)"', labels)
    assert m, f"bucket sample without le label: {labels!r}"
    return math.inf if m.group(1) == "+Inf" else float(m.group(1))


@pytest.fixture(scope="module")
def exposition():
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common.config import g_conf
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("prom", k=3, m=2, pg_num=8)
    cl = c.client("client.prom")
    assert cl.write_full("prom", "o1", b"p" * 20000) == 0
    assert cl.write_full("prom", "o2", b"q" * 4000) == 0
    assert cl.read("prom", "o1")[:1] == b"p"
    # one write through the async pipeline so its histogram/counters
    # carry samples on the exposition surface
    g_conf.set_val("ec_pipeline_depth", 4)
    g_conf.set_val("ec_dispatch_batch_window_us", 100_000)
    try:
        assert cl.write_full("prom", "o3", b"r" * 8000) == 0
        # and one through the MESH path (ceph_tpu/mesh) so the per-chip
        # occupancy family and ceph_daemon_mesh_* counters render —
        # with skew probes on EVERY flush so the per-chip latency
        # family and the mesh_chip counters render too
        g_conf.set_val("ec_mesh_chips", 8)
        g_conf.set_val("ec_mesh_skew_sample_every", 1)
        assert cl.write_full("prom", "o4", b"s" * 60000) == 0
        # and one through the RATELESS coded path (ceph_tpu/mesh/
        # rateless) so the mesh_rateless_* counter family renders with
        # real content
        g_conf.set_val("ec_mesh_rateless", True)
        assert cl.write_full("prom", "o5", b"t" * 60000) == 0
    finally:
        from ceph_tpu.mesh import g_mesh
        g_conf.rm_val("ec_pipeline_depth")
        g_conf.rm_val("ec_dispatch_batch_window_us")
        g_conf.rm_val("ec_mesh_chips")
        g_conf.rm_val("ec_mesh_skew_sample_every")
        g_conf.rm_val("ec_mesh_rateless")
        g_mesh.topology()
    # and one DEGRADED read through the MESH path (kill a data-shard
    # holder, reconstruct with the mesh up) so the mesh_decode_*
    # counter family and the decode occupancy histogram render with
    # real content
    pid = c.mon.osdmap.lookup_pg_pool_name("prom")
    victim = next(
        o.osd_id for o in c.osds.values()
        for cid in o.store.list_collections()
        if cid.startswith(f"{pid}.") and "s" in cid
        and cid.rsplit("s", 1)[1] in ("1", "2")   # non-primary DATA shard
        and any(ho.oid == "o4" for ho in o.store.list_objects(cid)))
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    g_conf.set_val("ec_mesh_chips", 8)
    try:
        assert cl.read("prom", "o4")[:1] == b"s"
    finally:
        from ceph_tpu.mesh import g_mesh
        g_conf.rm_val("ec_mesh_chips")
        g_mesh.topology()
    # and one write through the DEVICE-RESIDENT path (fused encode+crc
    # kernel, shard bodies held in HBM) with a materializing read-back
    # so the memstore_device_* counter family renders with real content
    g_conf.set_val("os_memstore_device_bytes_max", 1 << 30)
    try:
        assert cl.write_full("prom", "o6", b"u" * 20000) == 0
        assert cl.read("prom", "o6") == b"u" * 20000
    finally:
        g_conf.rm_val("os_memstore_device_bytes_max")
    return c.admin_socket.execute("prometheus metrics")


def test_exposition_parses(exposition):
    types, samples = _parse(exposition)
    assert samples, "no samples rendered"
    assert types, "no TYPE declarations"
    # the cluster gauges of the pre-existing renderer survive
    assert types.get("ceph_osdmap_epoch") == "gauge"
    assert any(n == "ceph_osd_up" for n, _l, _v in samples)


def test_histogram_families_cumulative_and_consistent(exposition):
    types, samples = _parse(exposition)
    hist_families = [n for n, t in types.items() if t == "histogram"]
    assert any("op_w_latency_in_bytes" in n for n in hist_families), \
        "OSD write histogram family missing"

    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))

    for fam in hist_families:
        buckets = by_name.get(f"{fam}_bucket", [])
        sums = dict(by_name.get(f"{fam}_sum", []))
        counts = dict(by_name.get(f"{fam}_count", []))
        assert buckets and sums and counts, \
            f"{fam}: _bucket/_sum/_count series incomplete"
        # no stray samples under the family's base name
        assert fam not in by_name, \
            f"{fam}: bare samples next to histogram series"
        series = {}
        for labels, value in buckets:
            series.setdefault(_labels_minus_le(labels), []).append(
                (_le_of(labels), value))
        for key, pts in series.items():
            pts.sort()
            les = [le for le, _v in pts]
            vals = [v for _le, v in pts]
            assert les[-1] == math.inf, f"{fam}{key}: no +Inf bucket"
            assert vals == sorted(vals), \
                f"{fam}{key}: bucket series not cumulative/monotone"
            # +Inf bucket equals _count for the same label set
            cnt = next(v for labels, v in counts.items()
                       if _labels_minus_le(labels) == key)
            assert vals[-1] == cnt, f"{fam}{key}: +Inf != _count"
            sm = next(v for labels, v in sums.items()
                      if _labels_minus_le(labels) == key)
            assert sm >= 0.0


def test_dispatch_occupancy_family_and_counters(exposition):
    """Dispatch-PR golden coverage: the batch-occupancy histogram
    renders as a real histogram family (monotone cumulative buckets,
    +Inf == _count — enforced for every family by the generic test
    above) with RAW occupancy bucket edges (not usec-scaled), and the
    dispatch perf counters render as daemon series."""
    types, samples = _parse(exposition)
    fam = "ceph_dispatch_batch_occupancy_histogram"
    assert types.get(fam) == "histogram", \
        "batch-occupancy histogram family missing"
    buckets = [(_le_of(labels), v) for n, labels, v in samples
               if n == f"{fam}_bucket"]
    assert buckets, "no occupancy buckets rendered"
    # occupancy axis is dimensionless: unit-quant linear edges survive
    # un-scaled (1.0, 2.0, ... not 1e-06); the fixture's writes all ran
    # at occupancy 1, so the le="1.0" bucket is still 0 and le="2.0"
    # carries them
    les = sorted(le for le, _v in buckets if le != math.inf)
    assert les[0] == 0.0 and 2.0 in les, f"unexpected edges {les[:4]}"
    counts = {n for n, _l, _v in samples}
    assert f"{fam}_count" in counts and f"{fam}_sum" in counts
    # dispatch counters on the daemon surface
    sub = [v for n, _l, v in samples
           if n == "ceph_daemon_dispatch_submitted"]
    assert sub and sub[0] > 0, "dispatch_submitted counter missing"
    assert any(n == "ceph_daemon_dispatch_passthrough"
               for n, _l, _v in samples)


def test_mesh_family_and_counters(exposition):
    """Mesh-PR golden coverage: the per-chip occupancy histogram
    renders as a real histogram family (the generic cumulative test
    above already enforces monotone buckets and +Inf == _count) with
    RAW dimensionless stripe-count edges, and the mesh runtime's
    counters render as ``ceph_daemon_mesh_*`` daemon series carrying
    the fixture's mesh write."""
    types, samples = _parse(exposition)
    fam = "ceph_dispatch_chip_occupancy_histogram"
    assert types.get(fam) == "histogram", \
        "per-chip occupancy histogram family missing"
    buckets = [(_le_of(labels), v) for n, labels, v in samples
               if n == f"{fam}_bucket"]
    assert buckets, "no chip-occupancy buckets rendered"
    # axis 0 is chip_stripes: dimensionless unit-quant linear edges
    # survive un-scaled
    les = sorted(le for le, _v in buckets if le != math.inf)
    assert les[0] == 0.0 and 1.0 in les and 2.0 in les, les[:4]
    # the fixture's mesh write landed samples (one per chip per flush)
    infs = [v for le, v in buckets if le == math.inf]
    assert infs and infs[0] >= 8, "fewer than 8 per-chip samples"
    for counter, expect_positive in (
            ("ceph_daemon_mesh_dispatches", True),
            ("ceph_daemon_mesh_stripes", True),
            ("ceph_daemon_mesh_plan_builds", True),
            ("ceph_daemon_mesh_chips", False),
            ("ceph_daemon_mesh_fallbacks", False)):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
        if expect_positive:
            assert vals[0] > 0, f"{counter} never moved"


def test_mesh_rateless_counters(exposition):
    """Rateless-PR golden coverage: the ``mesh_rateless_*`` counter
    family renders as ``ceph_daemon_mesh_rateless_*`` daemon series
    carrying the fixture's coded write — flushes and coded tasks
    moved, the failure/fallback counters render at zero."""
    _types, samples = _parse(exposition)
    for counter, expect_positive in (
            ("ceph_daemon_mesh_rateless_flushes", True),
            ("ceph_daemon_mesh_rateless_coded_tasks", True),
            ("ceph_daemon_mesh_rateless_parity_tasks", True),
            ("ceph_daemon_mesh_rateless_wasted_blocks", False),
            ("ceph_daemon_mesh_rateless_subset_completions", False),
            ("ceph_daemon_mesh_rateless_host_resolves", False),
            ("ceph_daemon_mesh_rateless_suspect_deweights", False),
            ("ceph_daemon_mesh_rateless_chip_failures", False),
            ("ceph_daemon_mesh_rateless_insufficient", False)):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
        if expect_positive:
            assert vals[0] > 0, f"{counter} never moved"


def test_memstore_device_counters(exposition):
    """Zero-copy-PR golden coverage: the ``memstore_device_*`` counter
    family renders as ``ceph_daemon_memstore_device_*`` daemon series
    carrying the fixture's device-resident write — device-side CRCs
    and materializations moved (o6 was written resident then read
    back), resident_shards/resident_bytes are gauges that render even
    when the budget reset drained them.  Values are process-global
    cumulative; the demotion/LRU semantics live in the delta-based
    assertions of tests/test_device_shard.py, not here."""
    _types, samples = _parse(exposition)
    for counter, expect_positive in (
            ("ceph_daemon_memstore_device_crc_device", True),
            ("ceph_daemon_memstore_device_materializations", True),
            ("ceph_daemon_memstore_device_resident_bytes", False),
            ("ceph_daemon_memstore_device_resident_shards", False),
            ("ceph_daemon_memstore_device_demotions", False),
            ("ceph_daemon_memstore_device_crc_host", False)):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
        if expect_positive:
            assert vals[0] > 0, f"{counter} never moved"


def test_mesh_decode_counters(exposition):
    """Meshed-READ-path golden coverage (the straggler-proof read PR):
    the ``mesh_decode_*`` counter family renders as
    ``ceph_daemon_mesh_decode_*`` daemon series carrying the fixture's
    degraded read — dispatches/stripes/plan builds moved, the
    inflight gauge settled back to zero — and the decode occupancy
    histogram renders as a real histogram family with per-chip
    samples.  The counters are process-global cumulative (other tests
    in the session may have exercised the fallback path on purpose),
    so zero-fallback semantics live in the delta-based assertions of
    tests/test_mesh_decode.py, not here."""
    types, samples = _parse(exposition)
    for counter, expect_positive in (
            ("ceph_daemon_mesh_decode_dispatches", True),
            ("ceph_daemon_mesh_decode_stripes", True),
            ("ceph_daemon_mesh_decode_bytes", True),
            ("ceph_daemon_mesh_decode_plan_builds", True),
            ("ceph_daemon_mesh_decode_fallbacks", False),
            ("ceph_daemon_mesh_decode_repair_solves", False),
            ("ceph_daemon_mesh_decode_inflight", False)):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
        if expect_positive:
            assert vals[0] > 0, f"{counter} never moved"
        elif counter.endswith("inflight"):
            assert vals[0] == 0, f"{counter} stuck: {vals[0]}"
    fam = "ceph_mesh_decode_chip_occupancy_histogram"
    assert types.get(fam) == "histogram", \
        "decode occupancy histogram family missing"
    buckets = [(_le_of(labels), v) for n, labels, v in samples
               if n == f"{fam}_bucket"]
    assert buckets, "no decode-occupancy buckets rendered"
    infs = [v for le, v in buckets if le == math.inf]
    assert infs and infs[0] >= 8, "fewer than 8 per-chip decode samples"


def test_mesh_chip_family_and_counters(exposition):
    """Per-chip-timing golden coverage (the skew PR): the 2-D
    ``mesh_chip_latency_histogram`` renders as a real histogram family
    whose axis-0 ``probe_usec`` edges export SCALED TO SECONDS (the
    ``_usec`` renderer rule; the chip_index axis keeps raw edges on
    the dump surface), and the scoreboard's counters render as
    ``ceph_daemon_mesh_chip_*`` series carrying the fixture's probed
    mesh write."""
    types, samples = _parse(exposition)
    fam = "ceph_mesh_chip_latency_histogram"
    assert types.get(fam) == "histogram", \
        "per-chip latency histogram family missing"
    buckets = [(_le_of(labels), v) for n, labels, v in samples
               if n == f"{fam}_bucket"]
    assert buckets, "no per-chip latency buckets rendered"
    # usec axis scaled to seconds: every finite edge must be small
    # (the raw log2 usec edges reach 2^30; scaled they stay < 2^30/1e6)
    les = sorted(le for le, _v in buckets if le != math.inf)
    assert les and les[-1] < 1100.0, les[-4:]
    assert any(0.0 < le < 1.0 for le in les), les[:6]
    # the probed mesh flush landed one sample per chip
    infs = [v for le, v in buckets if le == math.inf]
    assert infs and infs[0] >= 8, "fewer than 8 per-chip probe samples"
    for counter, expect_positive in (
            ("ceph_daemon_mesh_chip_probes", True),
            ("ceph_daemon_mesh_chip_samples", True),
            ("ceph_daemon_mesh_chip_suspects_marked", False),
            ("ceph_daemon_mesh_chip_suspects_cleared", False),
            ("ceph_daemon_mesh_chip_suspect_chips", False),
            ("ceph_daemon_mesh_chip_slowdowns_injected", False),
            ("ceph_daemon_mesh_chip_max_skew_permille", True)):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
        if expect_positive:
            assert vals[0] > 0, f"{counter} never moved"


def test_pipeline_family_and_counters(exposition):
    """Async-pipeline golden coverage: the per-PG pipeline-occupancy
    histogram renders as a real histogram family with RAW (unscaled)
    linear bucket edges — the dimensionless-axis renderer path the
    dispatch occupancy family established — and the pipeline perf
    counters (inflight gauge included) render as daemon series."""
    types, samples = _parse(exposition)
    fam = "ceph_pipeline_inflight_histogram"
    assert types.get(fam) == "histogram", \
        "pipeline-occupancy histogram family missing"
    buckets = [(_le_of(labels), v) for n, labels, v in samples
               if n == f"{fam}_bucket"]
    assert buckets, "no pipeline buckets rendered"
    les = sorted(le for le, _v in buckets if le != math.inf)
    assert les[0] == 0.0 and 2.0 in les, f"unexpected edges {les[:4]}"
    # the fixture's pipelined write landed a sample somewhere
    counts = [v for n, _l, v in samples if n == f"{fam}_count"]
    assert sum(counts) >= 1, "pipelined write left no histogram sample"
    # pipeline counters on the daemon surface, gauge included
    sub = [v for n, _l, v in samples
           if n == "ceph_daemon_pipeline_submitted"]
    assert sub and sub[0] >= 1, "pipeline_submitted counter missing"
    assert any(n == "ceph_daemon_pipeline_pipeline_inflight"
               for n, _l, _v in samples), "pipeline_inflight gauge missing"


def test_qos_families_and_counters(exposition):
    """QoS-PR golden coverage: the per-client queue-wait histogram
    renders as a real histogram family keyed by the CLIENT entity in
    the daemon label (cumulative/monotone buckets enforced by the
    generic test above), and the qos perf counters (per-class
    dequeues, admission/throttle accounting, queue-depth gauge) render
    as daemon series with the fixture's ops accounted."""
    types, samples = _parse(exposition)
    fam = "ceph_client_queue_wait_latency_histogram"
    assert types.get(fam) == "histogram", \
        "per-client queue-wait histogram family missing"
    counts = [v for n, labels, v in samples
              if n == f"{fam}_count" and 'daemon="client_prom"' in labels]
    # the fixture issued 4 ops as client.prom: each intake->dequeue
    # wait lands in THAT entity's histogram
    assert counts and counts[0] >= 4, counts
    deq = [v for n, _l, v in samples
           if n == "ceph_daemon_qos_dequeues_client"]
    assert deq and deq[0] >= 4, "qos dequeue accounting missing"
    for name in ("ceph_daemon_qos_admission_rejections",
                 "ceph_daemon_qos_throttle_events",
                 "ceph_daemon_qos_queue_depth"):
        assert any(n == name for n, _l, _v in samples), f"{name} missing"


def test_devprof_families_and_counters(exposition):
    """Devprof-PR golden coverage: the transfer-size histogram renders
    as a real histogram family with RAW log2 byte edges (dimensionless
    axis — the un-scaled renderer path), and the devprof counters
    (h2d/d2h bytes+transfers, compiles, device-mem high-water gauge)
    render as daemon series with the fixture's EC writes accounted."""
    types, samples = _parse(exposition)
    fam = "ceph_devprof_transfer_size_histogram"
    assert types.get(fam) == "histogram", \
        "devprof transfer-size histogram family missing"
    buckets = [(_le_of(labels), v) for n, labels, v in samples
               if n == f"{fam}_bucket"]
    assert buckets, "no transfer-size buckets rendered"
    # byte axis is dimensionless: log2 edges survive un-scaled
    # (512.0, 1024.0, ... not usec-to-seconds 0.000512)
    les = sorted(le for le, _v in buckets if le != math.inf)
    assert les[0] == 0.0 and 512.0 in les and 1024.0 in les, les[:6]
    # the generic histogram test above already enforced cumulative
    # monotonicity and +Inf == _count; here: the EC writes landed
    counts = [v for n, _l, v in samples if n == f"{fam}_count"]
    assert sum(counts) >= 2, "EC writes left no transfer samples"
    # counter families on the daemon surface, all non-trivial
    vals = {n: v for n, _l, v in samples}
    for name in ("ceph_daemon_devprof_h2d_bytes",
                 "ceph_daemon_devprof_h2d_transfers",
                 "ceph_daemon_devprof_d2h_bytes",
                 "ceph_daemon_devprof_d2h_transfers"):
        assert vals.get(name, 0) > 0, f"{name} missing or zero"
    for name in ("ceph_daemon_devprof_compiles",
                 "ceph_daemon_devprof_host_copies",
                 "ceph_daemon_devprof_device_mem_highwater_bytes"):
        assert name in vals, f"{name} missing"


def test_oplat_families_and_agreement(exposition):
    """Oplat-PR golden coverage: the per-stage latency families render
    as real histogram families keyed by the daemon label (cumulative
    monotone buckets and +Inf == _count are enforced for every family
    by the generic test above), the usec axis exports as seconds, the
    oplat counters render on the daemon surface, and the exposition
    agrees with `perf histogram dump` / `latency dump` counts."""
    from ceph_tpu.trace import g_perf_histograms
    from ceph_tpu.trace.oplat import stage_hist_name
    types, samples = _parse(exposition)
    # every op the fixture issued crossed these stages (writes and the
    # read alike; batch_window is pipelined-only so it may have fewer)
    for stage in ("admission", "class_queue", "client_lane",
                  "dequeue_handoff", "op_service", "device_call",
                  "d2h", "fan_out", "ack_gather", "reply"):
        fam = f"ceph_{stage_hist_name(stage)}"
        assert types.get(fam) == "histogram", f"{fam} missing"
        counts = [(labels, v) for n, labels, v in samples
                  if n == f"{fam}_count"]
        assert counts, f"{fam}: no _count series"
        assert sum(v for _l, v in counts) >= 4, (fam, counts)
        # latency axis is usec: bucket edges export scaled to seconds
        les = sorted(_le_of(labels) for n, labels, v in samples
                     if n == f"{fam}_bucket" and _le_of(labels)
                     != math.inf)
        assert les[0] == 0.0 and 0.0001 in les, (fam, les[:4])
        # dump/exposition agreement per daemon series
        for labels, v in counts:
            m = re.search(r'daemon="([^"]+)"', labels)
            hits = [h for (lg, n), h in g_perf_histograms.items()
                    if n == stage_hist_name(stage)
                    and re.sub(r"[^a-zA-Z0-9_:]", "_", lg)
                    == m.group(1)]
            assert hits and hits[0].total_count == v, \
                f"{fam}{labels}: exposition disagrees with dump"
    # counter families on the daemon surface
    vals = {n: v for n, _l, v in samples}
    assert vals.get("ceph_daemon_oplat_ops", 0) >= 4
    assert vals.get("ceph_daemon_oplat_stage_samples", 0) >= 40


def test_op_histograms_carry_the_writes(exposition):
    """The two writes + one read issued by the fixture are visible in
    some OSD's latency histograms (non-zero _count)."""
    _types, samples = _parse(exposition)
    w = [v for n, _l, v in samples
         if n == "ceph_op_w_latency_in_bytes_histogram_count"]
    assert sum(w) >= 2
    r = [v for n, _l, v in samples
         if n == "ceph_op_r_latency_in_bytes_histogram_count"]
    assert sum(r) >= 1


def test_kernel_and_slow_op_series_render():
    """kernel_timer + slow_ops sources render as typed series."""
    from ceph_tpu.common.kernel_trace import KernelTimer
    kt = KernelTimer()
    kt.enable()
    kt._record("unit_kernel", 0.5)
    # render through a real Manager hanging off a minimal cluster
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=2)
    out = c.mgr.prometheus_metrics(kernel_timer=kt,
                                   slow_ops={"osd.0": 3})
    types, samples = _parse(out)
    assert types["ceph_kernel_dispatch_seconds_total"] == "counter"
    assert ('ceph_kernel_dispatch_seconds_total',
            '{kernel="unit_kernel"}', 0.5) in samples
    assert types["ceph_daemon_slow_ops"] == "gauge"
    assert ('ceph_daemon_slow_ops', '{daemon="osd_0"}', 3.0) in samples


def test_control_counters(exposition):
    """Control-plane golden coverage (ceph_tpu/control): every
    ``control`` logger counter renders as a ``ceph_daemon_control_*``
    daemon series, and the cluster-scope actuation rollup renders as
    the ``ceph_cluster_control_moves`` gauge.  Presence is the
    contract (the counters are process-global, so other tests may
    have moved them); the fixture's OWN mgr is observe-only
    (``mgr_control_enable`` defaults off), so its cluster-scope move
    rollup must render zero."""
    types, samples = _parse(exposition)
    for counter in ("ceph_daemon_control_ticks",
                    "ceph_daemon_control_moves",
                    "ceph_daemon_control_tightens",
                    "ceph_daemon_control_restores",
                    "ceph_daemon_control_pinned",
                    "ceph_daemon_control_actuate_retries",
                    "ceph_daemon_control_actuate_failures",
                    "ceph_daemon_control_episodes",
                    "ceph_daemon_control_teardown_reverts",
                    "ceph_daemon_control_skipped_cooldown",
                    "ceph_daemon_control_engaged_knobs",
                    "ceph_daemon_control_enabled"):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
    assert types["ceph_cluster_control_moves"] == "gauge"
    moves = [v for n, _l, v in samples
             if n == "ceph_cluster_control_moves"]
    assert moves == [0.0], moves


def test_journal_and_incident_counters(exposition):
    """Forensics golden coverage (trace/journal + mgr/incident): the
    ``journal`` and ``incident`` logger counters render as daemon
    series, and the cluster-scope capture rollup renders as the
    ``ceph_cluster_incidents_total`` gauge.  Presence is the contract
    (both loggers are process-global, so other tests may have moved
    them); the fixture's own mgr raised no health check, so its
    cluster-scope rollup must render zero."""
    types, samples = _parse(exposition)
    for counter in ("ceph_daemon_journal_events",
                    "ceph_daemon_journal_evictions",
                    "ceph_daemon_journal_resets",
                    "ceph_daemon_incident_captures",
                    "ceph_daemon_incident_operator_captures",
                    "ceph_daemon_incident_dropped",
                    "ceph_daemon_incident_resolved",
                    "ceph_daemon_incident_pruned",
                    "ceph_daemon_incident_open"):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
    assert types["ceph_cluster_incidents_total"] == "gauge"
    caps = [v for n, _l, v in samples
            if n == "ceph_cluster_incidents_total"]
    assert caps == [0.0], caps


def test_chaos_and_membership_counters(exposition):
    """Chaos-PR golden coverage (ceph_tpu/chaos + elastic mesh
    membership): the ``chaos`` and ``mesh_membership`` logger counters
    render as daemon series, and the cluster-scope storyline rollups
    render as the ``ceph_cluster_chaos_*`` gauges.  Presence is the
    contract (both loggers are process-global); the fixture ran no
    storyline, so the scenario gauge must render zero."""
    types, samples = _parse(exposition)
    for counter in ("ceph_daemon_chaos_scenarios",
                    "ceph_daemon_chaos_legs",
                    "ceph_daemon_chaos_events",
                    "ceph_daemon_chaos_faults_armed",
                    "ceph_daemon_chaos_accept_pass",
                    "ceph_daemon_chaos_accept_fail",
                    "ceph_daemon_chaos_wedges",
                    "ceph_daemon_mesh_membership_transitions",
                    "ceph_daemon_mesh_membership_chip_adds",
                    "ceph_daemon_mesh_membership_chip_retires",
                    "ceph_daemon_mesh_membership_drained_reqs",
                    "ceph_daemon_mesh_membership_suspect_retires",
                    "ceph_daemon_mesh_membership_target_chips"):
        vals = [v for n, _l, v in samples if n == counter]
        assert vals, f"{counter} missing from the exposition"
    assert types["ceph_cluster_chaos_scenarios"] == "gauge"
    assert types["ceph_cluster_chaos_accepted"] == "gauge"
