"""WALStore: journal-then-apply durability (FileJournal replay semantics,
src/os/filestore/FileJournal.{h,cc}; BlueStore fsck role)."""
import os
import struct

import pytest

from ceph_tpu.os_store import Transaction, hobject_t
from ceph_tpu.os_store.walstore import (WALStore, mount_store, encode_txn,
                                        decode_txn, _HDR, _REC_MAGIC)


def _txn(i: int) -> Transaction:
    t = Transaction()
    cid = "0.0s0"
    oid = hobject_t(f"obj{i}", 0)
    t.create_collection(cid)
    t.write(cid, oid, 0, bytes([i % 256]) * 64)
    t.setattr(cid, oid, "v", struct.pack("<Q", i))
    t.omap_setkeys(cid, oid, {f"k{i}": b"val"})
    return t


def test_txn_codec_roundtrip():
    t = Transaction()
    t.create_collection("1.2s3")
    o = hobject_t("x", 3)
    t.touch("1.2s3", o)
    t.write("1.2s3", o, 7, b"hello")
    t.zero("1.2s3", o, 2, 3)
    t.truncate("1.2s3", o, 9)
    t.setattr("1.2s3", o, "a", b"\x00\xff")
    t.rmattr("1.2s3", o, "a")
    t.omap_setkeys("1.2s3", o, {"k1": b"v1", "k2": b""})
    t.omap_rmkeys("1.2s3", o, ["k1"])
    t.remove("1.2s3", o)
    t.remove_collection("1.2s3")
    assert decode_txn(encode_txn(t)).ops == t.ops


def test_mount_replay_roundtrip(tmp_path):
    d = str(tmp_path / "osd0")
    s = mount_store(d)
    for i in range(10):
        s.queue_transaction(_txn(i))
    # NO umount: simulates kill -9 (the OS keeps the flushed WAL)
    s._wal_f.close()
    s2 = mount_store(d)
    assert s2.committed_txns == 10
    for i in range(10):
        assert s2.read("0.0s0", hobject_t(f"obj{i}", 0))[:1] == \
            bytes([i % 256])
        assert struct.unpack(
            "<Q", s2.getattr("0.0s0", hobject_t(f"obj{i}", 0), "v"))[0] == i
    assert s2.omap_get("0.0s0", hobject_t("obj3", 0)) == {"k3": b"val"}


def test_clean_umount_checkpoints(tmp_path):
    d = str(tmp_path / "osd0")
    s = mount_store(d)
    for i in range(5):
        s.queue_transaction(_txn(i))
    s.umount()
    assert os.path.getsize(os.path.join(d, "wal.bin")) == 0
    s2 = mount_store(d)
    assert s2.committed_txns == 5
    assert s2.exists("0.0s0", hobject_t("obj4", 0))


def test_torn_tail_replays_prefix(tmp_path):
    """A partially-written last record (crash mid-append) must not poison
    the intact prefix — replay stops at the tear."""
    d = str(tmp_path / "osd0")
    s = mount_store(d)
    for i in range(6):
        s.queue_transaction(_txn(i))
    s._wal_f.close()
    wal = os.path.join(d, "wal.bin")
    with open(wal, "r+b") as f:
        f.truncate(os.path.getsize(wal) - 11)     # tear the last record
    s2 = mount_store(d)
    assert s2.committed_txns == 5                  # txns 1..5 survive
    assert s2.exists("0.0s0", hobject_t("obj4", 0))
    assert not s2.exists("0.0s0", hobject_t("obj5", 0))


def test_corrupt_record_stops_replay_and_fsck_reports(tmp_path):
    d = str(tmp_path / "osd0")
    s = mount_store(d)
    for i in range(4):
        s.queue_transaction(_txn(i))
    s._wal_f.close()
    wal = os.path.join(d, "wal.bin")
    buf = bytearray(open(wal, "rb").read())
    # flip one payload byte in the SECOND record
    magic, seq, ln, crc = _HDR.unpack_from(buf, 0)
    assert magic == _REC_MAGIC and seq == 1
    second = _HDR.size + ln
    buf[second + _HDR.size + 5] ^= 0xFF
    open(wal, "wb").write(bytes(buf))
    rep = WALStore(d).fsck()                       # offline, pre-recovery
    assert rep["wal_torn_tail"]                    # crc break = frontier
    assert rep["wal_records"] == 1
    s2 = mount_store(d)
    assert s2.committed_txns == 1                  # only record 1 applies
    # recovery cut the log at the frontier: a re-check is clean
    rep2 = s2.fsck()
    assert not rep2["wal_torn_tail"] and rep2["wal_records"] == 1


def test_checkpoint_roll_and_recovery(tmp_path):
    """Exceeding wal_max_bytes checkpoints + truncates; old WAL records
    whose seq is under the fence are skipped on the next mount."""
    d = str(tmp_path / "osd0")
    s = WALStore(d, wal_max_bytes=2048)
    s.mount()
    for i in range(40):
        s.queue_transaction(_txn(i))
    assert os.path.exists(os.path.join(d, "checkpoint.bin"))
    assert s._wal_size < 2048 + 1024               # rolled recently
    s._wal_f.close()
    s2 = mount_store(d)
    assert s2.committed_txns == 40
    assert s2.exists("0.0s0", hobject_t("obj39", 0))
    rep = s2.fsck()
    assert rep["ok"] and not rep["wal_torn_tail"]
    assert rep["checkpoint"]["seq"] >= 1


def test_fsck_clean_store(tmp_path):
    d = str(tmp_path / "osd0")
    s = mount_store(d)
    s.queue_transaction(_txn(0))
    s.umount()
    rep = WALStore(d).fsck()
    assert rep["ok"]
    assert rep["checkpoint"]["objects"] == 1
    assert rep["wal_records"] == 0


def test_unmounted_degrades_to_memstore(tmp_path):
    s = WALStore(str(tmp_path / "x"))
    s.queue_transaction(_txn(0))                   # no mount(): no files
    assert s.exists("0.0s0", hobject_t("obj0", 0))
    assert not os.path.exists(str(tmp_path / "x" / "wal.bin"))


def test_append_after_torn_tail_survives_second_crash(tmp_path):
    """Recovery must CUT the log at the torn frontier before appending:
    post-recovery commits written after torn garbage would be stranded
    behind bytes the next replay refuses to cross."""
    d = str(tmp_path / "osd0")
    s = mount_store(d)
    for i in range(6):
        s.queue_transaction(_txn(i))
    s._wal_f.close()
    wal = os.path.join(d, "wal.bin")
    with open(wal, "r+b") as f:
        f.truncate(os.path.getsize(wal) - 7)       # tear record 6
    s2 = mount_store(d)                            # recovers 1..5
    assert s2.committed_txns == 5
    s2.queue_transaction(_txn(100))                # post-recovery commits
    s2.queue_transaction(_txn(101))
    s2._wal_f.close()                              # second kill -9
    s3 = mount_store(d)
    assert s3.committed_txns == 7
    assert s3.exists("0.0s0", hobject_t("obj101", 0)), \
        "post-recovery write stranded behind torn garbage"


def test_failed_apply_rewinds_journal(tmp_path):
    """A transaction that fails validation must not leave a poison WAL
    record (its seq would collide with the next good commit and break
    the next mount)."""
    d = str(tmp_path / "osd0")
    s = mount_store(d)
    s.queue_transaction(_txn(0))
    bad = Transaction()
    bad.rmattr("no_such_coll", hobject_t("x"), "a")   # raises pre-apply
    with pytest.raises(KeyError):
        s.queue_transaction(bad)
    assert s.committed_txns == 1
    s.queue_transaction(_txn(1))                   # reuses the seq slot
    s._wal_f.close()
    s2 = mount_store(d)                            # must not raise
    assert s2.committed_txns == 2
    assert s2.exists("0.0s0", hobject_t("obj1", 0))
    rep = s2.fsck()
    assert rep["ok"] and rep["wal_records"] == 2
