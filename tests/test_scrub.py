"""Scrub-lite: background crc consistency checking + repair by decode.

Models the reference scrub path (src/osd/PG.cc scrub, ScrubStore.cc,
ECUtil.cc:161-207 HashInfo): a background pass compares every stored
shard against its crc (EC) or cross-replica digests (replicated), turns
inconsistencies into missing entries, and lets recovery repair them —
with no client read involved.
"""
import numpy as np

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.pg_log import PG_META_OID


def payload(n=20000, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def _corrupt_one_shard(c, oid):
    """Flip a byte of one stored EC shard; returns (osd_id, cid, before)."""
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == oid and ho.shard >= 0:
                    obj = osd.store.colls[cid][ho]
                    before = bytes(obj.data)
                    obj.data[7] ^= 0x5A
                    return osd.osd_id, cid, ho, before
    raise AssertionError("no shard found")


def test_scrub_detects_and_repairs_bitrot_without_client_read():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=4, m=2, pg_num=4, plugin="tpu")
    cl = c.client("client.s")
    data = payload(seed=3)
    assert cl.write_full("p", "obj", data) == 0
    osd_id, cid, ho, before = _corrupt_one_shard(c, "obj")
    reads_before = sum(o.perf["op_r"] for o in c.osds.values())
    c.scrub()
    c.network.pump()
    c.run_recovery()
    # no client read happened
    assert sum(o.perf["op_r"] for o in c.osds.values()) == reads_before
    # the corrupt shard was rewritten byte-exact
    after = bytes(c.osds[osd_id].store.colls[cid][ho].data)
    assert after == before, "scrub repair must restore the shard"
    assert cl.read("p", "obj") == data


def test_scrub_detects_missing_shard():
    """An object silently deleted from one shard at rest (operator error,
    disk eating files) comes back after a scrub."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=2, plugin="tpu")
    cl = c.client("client.m")
    assert cl.write_full("p", "obj", payload(seed=4)) == 0
    # delete one shard's copy at rest
    for osd in c.osds.values():
        done = False
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in list(osd.store.list_objects(cid)):
                if ho.oid == "obj" and ho.shard >= 0:
                    del osd.store.colls[cid][ho]
                    done = True
                    break
            if done:
                break
        if done:
            break
    c.scrub()
    c.network.pump()
    c.run_recovery()
    holders = [1 for o in c.osds.values()
               for cid in o.store.list_collections()
               if "_meta" not in cid
               for ho in o.store.list_objects(cid) if ho.oid == "obj"]
    assert len(holders) == 5  # k+m shards restored
    assert cl.read("p", "obj") == payload(seed=4)


def test_scrub_replicated_digest_mismatch():
    c = MiniCluster(n_osds=5)
    c.create_replicated_pool("r", size=3, pg_num=2)
    cl = c.client("client.r")
    data = payload(5000, seed=6)
    assert cl.write_full("r", "ro", data) == 0
    # corrupt a NON-primary replica (the primary's copy is scrub-auth)
    _, primary = cl._calc_target(cl.lookup_pool("r"), "ro")
    for osd in c.osds.values():
        if osd.osd_id == primary:
            continue
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "ro":
                    osd.store.colls[cid][ho].data[3] ^= 0xFF
                    victim = osd.osd_id
                    break
    c.scrub()
    c.network.pump()
    c.run_recovery()
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "ro":
                    assert bytes(osd.store.read(cid, ho)) == data, \
                        f"osd.{osd.osd_id} copy still corrupt"


def test_scrub_clean_cluster_is_noop():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=2, plugin="tpu")
    cl = c.client("client.n")
    for i in range(3):
        assert cl.write_full("p", f"o{i}", payload(seed=i)) == 0
    before = sum(o.perf["recovery_push"] for o in c.osds.values())
    c.scrub()
    after = sum(o.perf["recovery_push"] for o in c.osds.values())
    assert after == before
    states = [pg.state for o in c.osds.values()
              for pg in o.pgs.values() if pg.is_primary()]
    assert all(s == "active" for s in states)
