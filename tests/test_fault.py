"""Fault injection + graceful degradation (ceph_tpu/fault).

The robustness PR's acceptance gates:

- registry semantics: deterministic seeding, prob/nth/once triggers,
  match scoping, the zero-cost nothing-armed fast path;
- guard: bounded retry with backoff, watchdog deadline, DeviceUnavailable
  after the budget — and the CPU matrix fallback serving the call;
- circuit breaker: trips after N consecutive failures, surfaces
  TPU_CODEC_DEGRADED on health + Prometheus, half-open probes restore;
- byte-identity in EVERY state (the property test satellite): a
  circuit-broken signature's output equals both the CPU reference and
  the pre-trip device path across k/m/technique mixes;
- shard-read EIO recovers by EC reconstruction instead of failing the
  client op.
"""
import time

import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.ec.isa import ErasureCodeIsa
from ceph_tpu.ec.jerasure import ErasureCodeJerasure
from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
from ceph_tpu.fault import (DeviceUnavailable, InjectedDeviceError,
                            fault_perf_counters, g_breakers, g_faults,
                            run_device_call)
from ceph_tpu.fault.registry import (l_fault_device_retries,
                                     l_fault_eio_injected,
                                     l_fault_eio_reconstructs,
                                     l_fault_watchdog_timeouts)
from ceph_tpu.trace import g_tracer


@pytest.fixture
def clean_faults():
    """Every test leaves the process-global fault state as found."""
    yield
    g_faults.clear()
    g_breakers.reset()
    g_tracer.enable(False)
    g_tracer.collector.clear()
    for name in ("ec_device_retry_max", "ec_device_retry_backoff_us",
                 "ec_device_watchdog_ms", "ec_breaker_threshold",
                 "ec_breaker_cooldown_s"):
        g_conf.rm_val(name)


def _fast_retries():
    g_conf.set_val("ec_device_retry_backoff_us", 0)


# ---- registry --------------------------------------------------------------
def test_nothing_armed_is_free_and_quiet(clean_faults):
    before = fault_perf_counters().dump()["injected"]
    for _ in range(100):
        assert not g_faults.should_fire("device.encode_batch")
    g_faults.check("device.encode_batch")          # must not raise
    assert fault_perf_counters().dump()["injected"] == before


def test_prob_trigger_deterministic_by_seed(clean_faults):
    import random
    import zlib
    g_faults.inject("msg.drop", mode="prob", p=0.5, seed=7)
    got = [g_faults.should_fire("msg.drop") for _ in range(64)]
    rng = random.Random(7)
    want = [rng.random() < 0.5 for _ in range(64)]
    assert got == want
    # unseeded arms must be reproducible too (cross-process: derived
    # from a stable digest of the site name, never salted str hash)
    g_faults.inject("msg.drop", mode="prob", p=0.5)
    a = [g_faults.should_fire("msg.drop") for _ in range(32)]
    g_faults.inject("msg.drop", mode="prob", p=0.5)
    b = [g_faults.should_fire("msg.drop") for _ in range(32)]
    assert a == b
    rng = random.Random(zlib.crc32(b"msg.drop"))
    assert a == [rng.random() < 0.5 for _ in range(32)]
    # an explicit seed=0 is honored, not treated as "unset"
    g_faults.inject("msg.drop", mode="prob", p=0.5, seed=0)
    rng = random.Random(0)
    assert [g_faults.should_fire("msg.drop") for _ in range(32)] \
        == [rng.random() < 0.5 for _ in range(32)]


def test_nth_once_count_and_match(clean_faults):
    g_faults.inject("msg.drop", mode="nth", n=3)
    fires = [g_faults.should_fire("msg.drop") for _ in range(9)]
    assert fires == [False, False, True] * 3
    g_faults.inject("msg.drop", mode="once")
    assert g_faults.should_fire("msg.drop")
    assert not g_faults.should_fire("msg.drop")    # disarmed itself
    assert g_faults.armed("msg.drop") is None
    g_faults.inject("msg.drop", mode="always", count=2)
    assert [g_faults.should_fire("msg.drop") for _ in range(4)] \
        == [True, True, False, False]
    # match scoping: only matching contexts participate
    g_faults.inject("msg.drop", mode="always", match="MOSDOp ")
    assert not g_faults.should_fire("msg.drop",
                                    ctx="MOSDOpReply osd.0>client.0")
    assert g_faults.should_fire("msg.drop", ctx="MOSDOp client.0>osd.0")


def test_inject_validation_and_clear(clean_faults):
    with pytest.raises(ValueError):
        g_faults.inject("no.such.site")
    with pytest.raises(ValueError):
        g_faults.inject("msg.drop", mode="sometimes")
    with pytest.raises(ValueError):
        g_faults.inject("msg.drop", error="enospc")
    g_faults.inject("msg.drop")
    g_faults.inject("osd.shard_read_eio")
    d = g_faults.dump()
    assert set(d["armed"]) == {"msg.drop", "osd.shard_read_eio"}
    assert "device.encode_batch" in d["sites"]     # full catalog listed
    assert g_faults.clear("msg.drop") == 1
    assert g_faults.clear() == 1


# ---- guard -----------------------------------------------------------------
def test_guard_retries_then_succeeds(clean_faults):
    _fast_retries()
    pc = fault_perf_counters()
    before = pc.get(l_fault_device_retries)
    g_faults.inject("device.encode_batch", mode="nth", n=2, count=1)
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        return "ok"

    # check 1 (attempt 0) doesn't fire, injection precedes fn... nth=2:
    # attempt 0 passes, fn runs; arm count exhausts on a later test run
    assert run_device_call(("sig",), "device.encode_batch", flaky) \
        == "ok"
    g_faults.clear()
    # a fn that fails twice then succeeds: two retries, success
    g_conf.set_val("ec_device_retry_max", 2)
    calls["n"] = 0

    def fail_twice():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise RuntimeError("transient")
        return 42

    assert run_device_call(("sig",), "x", fail_twice) == 42
    assert calls["n"] == 3
    assert pc.get(l_fault_device_retries) >= before + 2


def test_guard_exhaustion_raises_device_unavailable(clean_faults):
    _fast_retries()
    g_conf.set_val("ec_device_retry_max", 1)
    g_conf.set_val("ec_breaker_threshold", 100)    # keep breaker shut

    def always_fail():
        raise RuntimeError("dead device")

    with pytest.raises(DeviceUnavailable):
        run_device_call(("sig2",), "x", always_fail)
    # semantic errors are NOT retried and NOT wrapped
    calls = {"n": 0}

    def semantic():
        calls["n"] += 1
        raise IOError("not enough chunks")

    with pytest.raises(IOError):
        run_device_call(("sig2",), "x", semantic)
    assert calls["n"] == 1


def test_guard_watchdog_deadline(clean_faults):
    _fast_retries()
    g_conf.set_val("ec_device_retry_max", 0)
    g_conf.set_val("ec_device_watchdog_ms", 5.0)
    pc = fault_perf_counters()
    before = pc.get(l_fault_watchdog_timeouts)

    def wedged():
        time.sleep(0.02)        # > 5 ms deadline
        return "too late"

    with pytest.raises(DeviceUnavailable):
        run_device_call(("sig3",), "x", wedged)
    assert pc.get(l_fault_watchdog_timeouts) == before + 1
    g_conf.set_val("ec_device_watchdog_ms", 1000.0)
    assert run_device_call(("sig3b",), "x", lambda: "fast") == "fast"


def test_guard_stops_retrying_once_breaker_trips(clean_faults):
    _fast_retries()
    g_conf.set_val("ec_device_retry_max", 10)
    g_conf.set_val("ec_breaker_threshold", 2)
    calls = {"n": 0}

    def always_fail():
        calls["n"] += 1
        raise RuntimeError("dead")

    with pytest.raises(DeviceUnavailable):
        run_device_call(("sig4",), "x", always_fail)
    # threshold 2 trips on the second failure: no point burning the
    # remaining 9 retries, the CPU path will serve
    assert calls["n"] == 2


# ---- breaker ---------------------------------------------------------------
def test_breaker_trip_halfopen_restore_cycle(clean_faults):
    g_conf.set_val("ec_breaker_threshold", 3)
    g_conf.set_val("ec_breaker_cooldown_s", 0.03)
    sig = ("t", 4, 2)
    for _ in range(2):
        assert not g_breakers.record_failure(sig)
        assert g_breakers.allow_device(sig)
    assert g_breakers.record_failure(sig)          # third trips
    assert not g_breakers.allow_device(sig)
    time.sleep(0.04)
    assert g_breakers.allow_device(sig)            # half-open window
    # failed probe re-arms the cooldown
    g_breakers.record_failure(sig)
    assert not g_breakers.allow_device(sig)
    time.sleep(0.04)
    assert g_breakers.allow_device(sig)
    g_breakers.record_success(sig)                 # probe succeeds
    assert g_breakers.allow_device(sig)
    d = [b for b in g_breakers.dump()["breakers"]
         if tuple(b["signature"]) == tuple(map(str, sig))][0]
    assert d["state"] == "closed"
    assert d["trips"] == 1 and d["restores"] == 1
    assert g_breakers.degraded() == []


def test_failed_halfopen_probe_costs_one_attempt(clean_faults):
    """A failed half-open probe must not burn the retry budget: the
    breaker is already open, so the guard gives up after the single
    probe call and the CPU path serves."""
    _fast_retries()
    g_conf.set_val("ec_device_retry_max", 5)
    g_conf.set_val("ec_breaker_threshold", 1)
    g_conf.set_val("ec_breaker_cooldown_s", 0.01)
    sig = ("probe-sig",)
    calls = {"n": 0}

    def dead():
        calls["n"] += 1
        raise RuntimeError("dead device")

    with pytest.raises(DeviceUnavailable):
        run_device_call(sig, "x", dead)        # threshold 1: trips at once
    assert calls["n"] == 1
    time.sleep(0.02)                           # half-open window
    with pytest.raises(DeviceUnavailable):
        run_device_call(sig, "x", dead)        # the probe, and only it
    assert calls["n"] == 2, "failed probe burned the retry budget"


def test_fault_inject_rejects_unknown_args(clean_faults):
    """A typo'd trigger key (mdoe=) must not silently arm a different
    fault — the admin hook rejects unknown argument names."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=2)
    with pytest.raises(ValueError, match="unknown argument"):
        c.admin_socket.execute("fault inject",
                               {"name": "msg.drop", "mdoe": "prob",
                                "p": "0.05"})
    assert c.admin_socket.execute("fault list")["armed"] == {}


def test_breaker_success_resets_consecutive_run(clean_faults):
    g_conf.set_val("ec_breaker_threshold", 3)
    sig = ("t2",)
    g_breakers.record_failure(sig)
    g_breakers.record_failure(sig)
    g_breakers.record_success(sig)                 # run broken
    assert not g_breakers.record_failure(sig)
    assert not g_breakers.record_failure(sig)
    assert g_breakers.allow_device(sig)


# ---- byte-identity property test (satellite) -------------------------------
@pytest.mark.parametrize("k,m,technique", [(3, 2, "reed_sol_van"),
                                           (4, 2, "cauchy"),
                                           (6, 3, "reed_sol_van")])
def test_circuit_broken_codec_byte_identical(clean_faults, k, m,
                                             technique):
    """A circuit-broken signature must produce output byte-identical to
    BOTH the CPU reference (isa host) and the pre-trip device path, for
    encode and decode, across k/m/technique mixes."""
    _fast_retries()
    tpu = ErasureCodeTpu()
    tpu.init({"k": str(k), "m": str(m), "technique": technique,
              "backend": "tpu"})
    ref = ErasureCodeIsa()
    ref.init({"k": str(k), "m": str(m), "technique": technique,
              "backend": "host"})
    rng = np.random.default_rng(k * 100 + m)
    data = rng.integers(0, 256, size=(3, k, 512), dtype=np.uint8)
    pre_trip = np.asarray(tpu.encode_batch(data))  # device path
    cpu_ref = np.asarray(ref.encode_batch(data))
    assert pre_trip.tobytes() == cpu_ref.tobytes()
    # pre-trip decode oracle: reconstruct the first data chunk + one
    # parity from a k-survivor subset
    full = {i: (data[:, i, :] if i < k else pre_trip[:, i - k, :])
            for i in range(k + m)}
    survivors = {i: full[i] for i in list(range(1, k)) + [k]}
    want = [0, k + m - 1]
    pre_dec = {i: np.asarray(b) for i, b in
               tpu.decode_batch(dict(survivors), want).items()}
    # trip the breaker through real (injected) device failures
    g_faults.inject("device.encode_batch", mode="always", count=3)
    tripped = np.asarray(tpu.encode_batch(data))   # retries, trips, CPU
    assert not tpu._use_device(), "breaker did not trip"
    assert tripped.tobytes() == pre_trip.tobytes()
    # every call in the OPEN state serves from the CPU path, identical
    open_enc = np.asarray(tpu.encode_batch(data))
    assert open_enc.tobytes() == cpu_ref.tobytes()
    open_dec = tpu.decode_batch(dict(survivors), want)
    for i in want:
        assert np.asarray(open_dec[i]).tobytes() \
            == pre_dec[i].tobytes(), f"chunk {i} differs when degraded"
    # restore via half-open probe and re-check the device path
    g_conf.set_val("ec_breaker_cooldown_s", 0.01)
    time.sleep(0.02)
    assert tpu._use_device()
    restored = np.asarray(tpu.encode_batch(data))
    assert restored.tobytes() == pre_trip.tobytes()
    assert g_breakers.degraded() == []


def test_jerasure_family_guard_parity(clean_faults):
    """The guard also covers the jerasure word-layout device path: a
    degraded jerasure signature stays byte-identical to its host twin."""
    _fast_retries()
    dev = ErasureCodeJerasure()
    dev.init({"k": "4", "m": "2", "technique": "reed_sol_van",
              "backend": "tpu"})
    host = ErasureCodeJerasure()
    host.init({"k": "4", "m": "2", "technique": "reed_sol_van",
               "backend": "host"})
    rng = np.random.default_rng(11)
    data = rng.integers(0, 256, size=(2, 4, 512), dtype=np.uint8)
    oracle = np.asarray(host.encode_batch(data))
    assert np.asarray(dev.encode_batch(data)).tobytes() \
        == oracle.tobytes()
    g_faults.inject("device.encode_batch", mode="always")
    assert np.asarray(dev.encode_batch(data)).tobytes() \
        == oracle.tobytes()


# ---- cluster integration ---------------------------------------------------
def _boot(k=3, m=2):
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("flt", k=k, m=m, pg_num=8)
    return c


def test_shard_read_eio_reconstructs(clean_faults):
    """Injected shard-read EIO must be served by EC reconstruction from
    the surviving shards — the client op succeeds with the same bytes."""
    c = _boot()
    cl = c.client("client.flt")
    body = bytes(np.random.default_rng(2).integers(
        0, 256, 20000, dtype=np.uint8))
    assert cl.write_full("flt", "obj", body) == 0
    pc = fault_perf_counters()
    eio0 = pc.get(l_fault_eio_injected)
    rec0 = pc.get(l_fault_eio_reconstructs)
    # n=4 fires on <= 2 of any 5 consecutive shard reads — never more
    # than m=2 failures within one read's fan-out, so reconstruction
    # always has k survivors (deterministic, not luck)
    g_faults.inject("osd.shard_read_eio", mode="nth", n=4)
    for _ in range(6):
        assert cl.read("flt", "obj") == body
    g_faults.clear()
    assert pc.get(l_fault_eio_injected) > eio0
    assert pc.get(l_fault_eio_reconstructs) > rec0


def test_degraded_health_warning_and_prometheus(clean_faults):
    """Device failures trip the pool codec's breaker: the op still
    commits, TPU_CODEC_DEGRADED raises on health + Prometheus (gauge +
    health_check series + fault counters), and clearing the breaker
    clears the warning."""
    _fast_retries()
    c = _boot()
    cl = c.client("client.deg")
    body = b"x" * 20000
    g_faults.inject("device.encode_batch", mode="always")
    assert cl.write_full("flt", "deg", body) == 0     # CPU served it
    g_faults.clear()
    assert cl.read("flt", "deg") == body
    h = c.health()
    assert "TPU_CODEC_DEGRADED" in h
    prom = c.admin_socket.execute("prometheus metrics")
    assert 'ceph_health_check{check="TPU_CODEC_DEGRADED"} 1' in prom
    assert "ceph_tpu_codec_degraded 1" in prom
    assert "ceph_tpu_codec_breaker_open{signature=" in prom
    assert "ceph_daemon_fault_cpu_fallbacks" in prom
    bd = c.admin_socket.execute("breaker dump")
    assert bd["breakers"] and bd["breakers"][0]["state"] == "open"
    # restore: breaker board forgotten -> warning clears on next check
    g_breakers.reset()
    assert "TPU_CODEC_DEGRADED" not in c.health()
    prom = c.admin_socket.execute("prometheus metrics")
    assert "ceph_tpu_codec_degraded 0" in prom


def test_admin_socket_fault_control(clean_faults):
    c = _boot()
    out = c.admin_socket.execute("fault list")
    assert "osd.shard_read_eio" in out["sites"]
    assert out["armed"] == {}
    out = c.admin_socket.execute(
        "fault inject", {"name": "osd.shard_read_eio", "mode": "nth",
                         "n": "3"})
    assert out["site"] == "osd.shard_read_eio"
    assert out["armed"]["mode"] == "nth" and out["armed"]["n"] == 3
    out = c.admin_socket.execute("fault list")
    assert list(out["armed"]) == ["osd.shard_read_eio"]
    # validation errors surface as JSON errors, not tracebacks
    import json
    err = json.loads(c.admin_socket.execute_json(
        "fault inject", {"name": "bogus.site"}))
    assert "unknown fault site" in err["error"]
    err = json.loads(c.admin_socket.execute_json(
        "fault inject", {"name": "msg.drop", "p": "not-a-float"}))
    assert "invalid value" in err["error"]
    assert c.admin_socket.execute("fault clear") == {"cleared": 1}
    assert c.admin_socket.execute(
        "fault clear", {"name": "msg.drop"}) == {"cleared": 0}


def test_retry_and_trip_span_events(clean_faults):
    """Span events on retry/trip/restore (the PR 2 machinery): the op's
    span tree carries device_retry/device_error events and the breaker
    transition events land on trip and restore."""
    _fast_retries()
    g_conf.set_val("ec_breaker_threshold", 2)
    g_conf.set_val("ec_breaker_cooldown_s", 0.01)
    g_tracer.enable()
    impl = ErasureCodeTpu()
    impl.init({"k": "3", "m": "2", "backend": "tpu"})
    data = np.random.default_rng(3).integers(
        0, 256, size=(2, 3, 512), dtype=np.uint8)
    g_faults.inject("device.encode_batch", mode="always", count=2)
    with g_tracer.span("op_root", daemon="test", trace_id=555) as root:
        impl.encode_batch(data)
    events = root.tags.get("events", [])
    names = [e["event"] for e in events]
    assert "device_retry" in names
    assert "breaker_trip" in names
    assert "cpu_fallback" in names
    # restore event on the successful half-open probe
    time.sleep(0.02)
    with g_tracer.span("op_root2", daemon="test", trace_id=556) as r2:
        impl.encode_batch(data)
    assert "breaker_restore" in [e["event"]
                                 for e in r2.tags.get("events", [])]
