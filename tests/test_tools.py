"""Tools: crushtool compile/decompile/test, osdmaptool, ec_benchmark,
balancer.

Mirrors the reference's tool-level checks: text map round-trips
(crushtool -c / -d), --test distribution sweeps, osdmaptool
--test-map-pgs / --upmap, and the benchmark CLI's output contract.
"""
import io
import pickle

import numpy as np
import pytest

from ceph_tpu.crush.compiler import CrushCompiler
from ceph_tpu.crush.tester import CrushTester
from ceph_tpu.tools import crushtool, ec_benchmark, osdmaptool

MAP_TEXT = """
# begin crush map
tunable choose_local_tries 0
tunable choose_local_fallback_tries 0
tunable choose_total_tries 50
tunable chooseleaf_descend_once 1
tunable chooseleaf_vary_r 1
tunable chooseleaf_stable 1

# devices
device 0 osd.0
device 1 osd.1
device 2 osd.2
device 3 osd.3
device 4 osd.4
device 5 osd.5

# types
type 0 osd
type 1 host
type 10 root

# buckets
host host0 {
\tid -2
\talg straw2
\thash 0\t# rjenkins1
\titem osd.0 weight 1.00000
\titem osd.1 weight 1.00000
}
host host1 {
\tid -3
\talg straw2
\thash 0
\titem osd.2 weight 1.00000
\titem osd.3 weight 1.00000
}
host host2 {
\tid -4
\talg straw2
\thash 0
\titem osd.4 weight 1.00000
\titem osd.5 weight 1.00000
}
root default {
\tid -1
\talg straw2
\thash 0
\titem host0 weight 2.00000
\titem host1 weight 2.00000
\titem host2 weight 2.00000
}

# rules
rule replicated_rule {
\truleset 0
\ttype replicated
\tmin_size 1
\tmax_size 10
\tstep take default
\tstep chooseleaf firstn 0 type host
\tstep emit
}
# end crush map
"""


def test_compile_text_map():
    cw = CrushCompiler().compile(MAP_TEXT)
    assert cw.get_max_devices() == 6
    assert cw.get_item_id("host1") == -3
    rno = cw.get_rule_id("replicated_rule")
    assert rno >= 0
    out = cw.do_rule(rno, 7, 3, [0x10000] * 6)
    assert len(out) == 3
    assert len({o // 2 for o in out}) == 3  # one per host


def test_decompile_recompile_same_mappings():
    cw = CrushCompiler().compile(MAP_TEXT)
    text = CrushCompiler(cw).decompile()
    cw2 = CrushCompiler().compile(text)
    rno = cw.get_rule_id("replicated_rule")
    rno2 = cw2.get_rule_id("replicated_rule")
    w = [0x10000] * 6
    for x in range(200):
        assert cw.do_rule(rno, x, 3, w) == cw2.do_rule(rno2, x, 3, w)


def test_crush_tester_statistics():
    cw = CrushCompiler().compile(MAP_TEXT)
    buf = io.StringIO()
    t = CrushTester(cw, out=buf)
    t.set_num_rep(3)
    t.set_min_x(0)
    t.set_max_x(199)
    t.set_output_statistics(True)
    t.use_device = False
    assert t.test() == 0
    s = buf.getvalue()
    assert "rule 0" in s
    assert "result size == 3:\t200/200" in s
    assert t.bad_mappings == 0


def test_crush_tester_weights_zero_device():
    cw = CrushCompiler().compile(MAP_TEXT)
    buf = io.StringIO()
    t = CrushTester(cw, out=buf)
    t.set_num_rep(3)
    t.set_max_x(99)
    t.set_device_weight(0, 0.0)
    t.use_device = False
    t.set_output_mappings(True)
    t.test()
    assert " 0," not in buf.getvalue().replace("[0,", "[X,")


def test_crushtool_cli_roundtrip(tmp_path):
    src = tmp_path / "map.txt"
    src.write_text(MAP_TEXT)
    binf = tmp_path / "map.bin"
    assert crushtool.main(["-c", str(src), "-o", str(binf)]) == 0
    outf = tmp_path / "out.txt"
    assert crushtool.main(["-d", str(binf), "-o", str(outf)]) == 0
    assert "rule replicated_rule" in outf.read_text()
    # --test runs clean on the host mapper
    assert crushtool.main(["-i", str(binf), "--test", "--num-rep", "3",
                           "--max-x", "63", "--show-statistics",
                           "--host-mapper"]) == 0


def test_osdmaptool_createsimple_and_test_map_pgs(tmp_path, capsys):
    mf = tmp_path / "om"
    assert osdmaptool.main(["--createsimple", "12", str(mf),
                            "--pg-num", "64"]) == 0
    assert osdmaptool.main([str(mf), "--test-map-pgs",
                            "--host-mapper"]) == 0
    out = capsys.readouterr().out
    assert "mapped 64 pgs" in out
    # the legacy builder's pool id is 0; the tool assumes pool 1 when
    # --pool is omitted (osdmaptool.cc), so name it explicitly
    assert osdmaptool.main([str(mf), "--test-map-object", "foo",
                            "--pool", "0"]) == 0
    out = capsys.readouterr().out
    assert "object 'foo'" in out


def test_osdmaptool_upmap_balances(tmp_path, capsys):
    mf = tmp_path / "om"
    osdmaptool.main(["--createsimple", "16", str(mf), "--pg-num", "128"])
    upf = tmp_path / "upmaps.sh"
    assert osdmaptool.main([str(mf), "--upmap", str(upf),
                            "--upmap-max", "32"]) == 0
    out = capsys.readouterr().out
    assert "upmap, max-count 32" in out
    text = upf.read_text()
    # each line is a pg-upmap-items command
    for line in text.splitlines():
        assert line.startswith("ceph osd pg-upmap-items ")


def test_balancer_reduces_spread():
    m = osdmaptool.createsimple_legacy(16, pg_num=256)

    def spread():
        from ceph_tpu.osdmap import pg_t
        count = np.zeros(m.max_osd)
        for ps in range(256):
            up, _ = m.pg_to_raw_up(pg_t(0, ps))
            for o in up:
                count[o] += 1
        return count.max() - count.min()

    before = spread()
    from ceph_tpu.osdmap.balancer import calc_pg_upmaps
    n = calc_pg_upmaps(m, max_iterations=64)
    assert n > 0
    after = spread()
    assert after < before


def test_ec_benchmark_encode_and_decode(capsys):
    assert ec_benchmark.main(["-p", "isa", "-P", "k=4", "-P", "m=2",
                              "-P", "backend=host", "-S", "65536",
                              "-i", "3", "-w", "encode"]) == 0
    out = capsys.readouterr().out.strip()
    secs, kib = out.split("\t")
    assert float(secs) > 0
    assert int(kib) == 3 * 64
    assert ec_benchmark.main(["-p", "isa", "-P", "k=4", "-P", "m=2",
                              "-P", "backend=host", "-S", "16384",
                              "-i", "5", "-w", "decode", "-e", "2"]) == 0
    out = capsys.readouterr().out.strip()
    secs, kib = out.split("\t")
    assert int(kib) == 5 * 16


def test_ec_benchmark_dispatch_mode(capsys):
    """--dispatch N coalesces N concurrent encodes per iteration
    through the dynamic-batching scheduler and leaves it drained."""
    from ceph_tpu.common.config import g_conf
    from ceph_tpu.dispatch import g_dispatcher
    assert ec_benchmark.main(["-p", "isa", "-P", "k=4", "-P", "m=2",
                              "-P", "backend=host", "-S", "16384",
                              "-i", "2", "-w", "encode",
                              "--dispatch", "4"]) == 0
    out = capsys.readouterr().out.strip()
    secs, kib = out.split("\t")
    assert float(secs) > 0
    assert int(kib) == 2 * 4 * 16
    assert g_dispatcher.dump()["pending"] == 0
    assert g_conf.values.get("ec_dispatch_batch_window_us") is None


def test_ceph_osd_pool_ls_detail(tmp_path, capsys):
    """ceph osd pool ls [detail]: names, then the pg_pool_t summary
    line with flags/quotas/tiering (MonCommands.h 'osd pool ls')."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.tools import ceph_cli
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("plain", size=2, pg_num=8)
    c.create_ec_pool("ecp", k=2, m=1, plugin="isa", pg_num=8)
    c.mon.set_pool_quota("plain", max_objects=10)
    cl = c.client("client.t")
    cl.selfmanaged_snap_create("ecp")
    c.publish()
    ck = str(tmp_path / "ck")
    c.checkpoint(ck)
    assert ceph_cli.main(["--cluster", ck, "osd", "pool", "ls"]) == 0
    out = capsys.readouterr().out.split()
    assert "plain" in out and "ecp" in out
    assert ceph_cli.main(["--cluster", ck, "osd", "pool", "ls",
                          "detail"]) == 0
    out = capsys.readouterr().out
    assert "'plain' replicated" in out and "max_objects 10" in out
    assert "'ecp' erasure" in out and "selfmanaged_snaps" in out
    assert "ec_overwrites" in out


def test_ceph_mon_dump_prints_monmap(tmp_path, capsys):
    """ceph mon dump: the mon's roster is a first-class epoched
    MonMap (mon/MonMap.h role) with address-ordered ranks."""
    import re as _re
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.tools import ceph_cli
    c = MiniCluster(n_osds=3, n_mons=3)
    ck = str(tmp_path / "ck")
    c.checkpoint(ck)
    assert ceph_cli.main(["--cluster", ck, "mon", "dump"]) == 0
    out = capsys.readouterr().out.splitlines()
    assert out[0] == "epoch 1"
    assert _re.fullmatch(r"fsid [0-9a-f-]{36}", out[1])
    ranked = [ln for ln in out if _re.match(r"\d+: ", ln)]
    assert len(ranked) == 3
    assert all("mon." in ln for ln in ranked)


def test_ceph_fs_status_and_mds_stat(tmp_path, capsys):
    """ceph fs status / ceph mds stat surface the MDSMonitor fsmap."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.msg.messages import MMDSBeacon
    from ceph_tpu.tools import ceph_cli
    c = MiniCluster(n_osds=3)
    # two daemons beacon in: first active, second standby
    c.network.send("mds.0", c.mon.name, MMDSBeacon(name="mds.0"))
    c.network.pump()
    c.network.send("mds.1", c.mon.name, MMDSBeacon(name="mds.1"))
    c.network.pump()
    ck = str(tmp_path / "ck")
    c.checkpoint(ck)
    assert ceph_cli.main(["--cluster", ck, "mds", "stat"]) == 0
    out = capsys.readouterr().out
    assert "mds.0 up:active" in out and "1 up:standby" in out
    assert ceph_cli.main(["--cluster", ck, "fs", "status"]) == 0
    st = capsys.readouterr().out
    import json as _json
    parsed = _json.loads(st)
    assert parsed["active"] == ["mds.0"]
    assert parsed["standby"] == ["mds.1"]


def test_objectstore_tool_surgery(tmp_path):
    """Write-side store surgery (ceph-objectstore-tool set-bytes /
    set-attr / rm-attr / set-omap / rm-omap / get-attr / list-pgs):
    mutations rewrite the store file and read back offline."""
    import jax
    jax.config.update("jax_platforms", "cpu")
    import io
    import json
    from contextlib import redirect_stdout

    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.tools.objectstore_tool import main

    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("p", pg_num=4)
    c.client("client.t").write_full("p", "obj", b"original")
    d = str(tmp_path / "ck")
    c.checkpoint(d)
    store = f"{d}/osd.0.store"

    def run(*args):
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main(["--data-path", store, *args])
        return rc, buf.getvalue()

    rc, out = run("--op", "list-pgs")
    pgs = out.split()
    assert rc == 0 and pgs and all("." in l for l in pgs)
    # pg ids render like pg_t: hex ps ("1.a", never "1.10")
    assert not any(l.split(".")[1] == "10" for l in pgs)


    # find a collection holding the object on osd.0 (may be absent if
    # osd.0 is not in the acting set of that pg; find any object)
    rc, out = run("--op", "list")
    assert rc == 0
    recs = [json.loads(l) for l in out.splitlines()]
    recs = [r for r in recs if not r["cid"].endswith("_meta")
            and r["cid"] != "meta"]
    assert recs
    r0 = recs[0]
    cid, oid, shard = r0["cid"], r0["oid"], r0["shard"]
    sel = ["--cid", cid, "--oid", oid, "--shard", str(shard)]

    blob = tmp_path / "blob"
    blob.write_bytes(b"surgically replaced")
    assert run("--op", "set-bytes", *sel, "--in", str(blob))[0] == 0
    rc, _ = run("--op", "get-bytes", *sel,
                "--out", str(tmp_path / "back"))
    assert rc == 0
    assert (tmp_path / "back").read_bytes() == b"surgically replaced"

    # invalid hex exits 1 cleanly (against a REAL object)
    assert run("--op", "set-attr", *sel, "--key", "_t",
               "--value", "zz")[0] == 1
    assert run("--op", "set-attr", *sel, "--key", "_t",
               "--value", b"hello".hex())[0] == 0
    rc, out = run("--op", "get-attr", *sel, "--key", "_t")
    assert rc == 0 and bytes.fromhex(out.strip()) == b"hello"
    assert run("--op", "rm-attr", *sel, "--key", "_t")[0] == 0
    assert run("--op", "get-attr", *sel, "--key", "_t")[0] == 1

    assert run("--op", "set-omap", *sel, "--key", "k",
               "--value", b"v".hex())[0] == 0
    rc, out = run("--op", "get-omap", *sel)
    assert rc == 0 and json.loads(out).get("k") == b"v".hex()
    assert run("--op", "rm-omap", *sel, "--key", "k")[0] == 0
    assert run("--op", "rm-omap", *sel, "--key", "k")[0] == 1
