"""Straggler-proof meshed READ path (the degraded-read PR's gates).

- ``ec_mesh_chips=0`` (the default), a 1-device mesh, and codecs whose
  decode is not mesh-shardable keep the existing single-device decode
  path by construction;
- mesh-dispatched decode/reconstruct is byte-identical to the encoded
  truth (== the single-device oracle) across randomized
  (k, m, technique, stripe count, chunk size) mixes, on BOTH the SPMD
  and the rateless branch, including batch occupancies that are not a
  multiple of the mesh size;
- the regenerating family rides the same entry: ≥d decode and the d×d
  repair solve are survivor matmuls over [[I],[Ψ]] rows — byte-exact
  for ``pm_mbr`` and ``pm_msr``, with the thin repair batch folded
  along the byte axis (``col_folds``);
- rateless block loss (``mesh.chip_fail``) and a hard straggler
  (``mesh.chip_slowdown``) complete every decode from the first
  spanning subset — byte-exact, ZERO single-device fallbacks;
- guard exhaustion at ``mesh.decode_batch`` degrades the group to the
  single-device path (byte-identical), counts a fallback and journals
  ``mesh_decode_degraded``;
- an elastic-membership transition drains in-flight decode groups and
  invalidates their sharding-plan cache entries (the mid-decode
  regression);
- a mesh-up cluster under DEGRADED reads stores shard bodies
  byte-identical to a single-device twin.
"""
import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.dispatch import g_dispatcher
from ceph_tpu.ec.isa import ErasureCodeIsa
from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
from ceph_tpu.fault import g_breakers, g_faults
from ceph_tpu.mesh import (g_chipstat, g_mesh, mesh_decode_perf_counters,
                           rateless_perf_counters)
from ceph_tpu.mesh.rateless import (l_rl_host_resolves,
                                    l_rl_subset_completions)
from ceph_tpu.mesh.runtime import (l_mdec_col_folds, l_mdec_dispatches,
                                   l_mdec_fallbacks, l_mdec_plan_builds,
                                   l_mdec_plan_hits,
                                   l_mdec_repair_solves)
from ceph_tpu.osd.ecutil import encode as eu_encode, stripe_info_t
from ceph_tpu.trace.journal import g_journal


@pytest.fixture
def decode_conf():
    """Every test leaves the dispatcher drained, the options at their
    defaults, faults/breakers cleared and the mesh torn down."""
    yield
    g_faults.clear()
    g_dispatcher.flush()
    for name in ("ec_mesh_chips", "ec_mesh_rateless",
                 "ec_mesh_rateless_tasks", "ec_mesh_skew_sample_every",
                 "ec_mesh_skew_threshold", "ec_dispatch_batch_max",
                 "ec_dispatch_batch_window_us"):
        g_conf.rm_val(name)
    g_mesh.topology()
    g_chipstat.reset()
    g_breakers.reset()


def _mesh_on(chips=8, rateless=False):
    g_conf.set_val("ec_mesh_chips", chips)
    if rateless:
        g_conf.set_val("ec_mesh_rateless", True)


def _mk_impl(plugin, k, m, technique):
    impl = plugin()
    # explicit backend: these tests drive the device path on the CPU
    # host platform, where backend=auto would route to host
    impl.init({"k": str(k), "m": str(m), "technique": technique,
               "backend": "tpu"})
    return impl


def _encode_stacked(impl, rng, stripes, chunk):
    """Encode a random payload through the HOST oracle and return
    every shard as its (S, C) stack — the ground truth any
    reconstruction must reproduce byte-exactly."""
    k, m = impl.k, impl.m
    sinfo = stripe_info_t(k, k * chunk)
    payload = rng.integers(0, 256, size=stripes * k * chunk,
                           dtype=np.uint8)
    shards = eu_encode(sinfo, impl, payload, set(range(k + m)))
    return {i: np.ascontiguousarray(
        np.asarray(b).reshape(stripes, chunk))
        for i, b in shards.items()}


# ---- by-construction passthroughs ------------------------------------------
def test_mesh_off_decode_is_passthrough(decode_conf):
    """Mesh off (the default) and a 1-chip mesh: ``decode_stacked``
    returns None and the decode counters never move."""
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    pc = mesh_decode_perf_counters()
    before = pc.get(l_mdec_dispatches)
    full = _encode_stacked(impl, np.random.default_rng(3), 4, 1024)
    survivors = np.stack([full[i] for i in (0, 2, 3, 4)], axis=1)
    assert g_mesh.decode_stacked(impl, survivors, (0, 2, 3, 4),
                                 (1,)) is None
    g_conf.set_val("ec_mesh_chips", 1)
    assert g_mesh.active() is False
    assert g_mesh.decode_stacked(impl, survivors, (0, 2, 3, 4),
                                 (1,)) is None
    got = impl.decode_batch({i: full[i] for i in (0, 2, 3, 4)}, [1])
    assert np.array_equal(got[1], full[1])
    assert pc.get(l_mdec_dispatches) == before


def test_mesh_declines_non_shardable_decode(decode_conf):
    """Jerasure bitmatrix techniques transform the data layout before
    the backend matmul — their decode must DECLINE the mesh
    (mesh_decode_shardable False) and stay byte-identical on the
    single-device path with the mesh up."""
    from ceph_tpu.ec.jerasure import ErasureCodeJerasure
    impl = ErasureCodeJerasure()
    impl.init({"k": "4", "m": "2", "technique": "cauchy_good",
               "packetsize": "8", "backend": "tpu"})
    assert impl.mesh_decode_shardable is False
    _mesh_on(chips=8)
    pc = mesh_decode_perf_counters()
    before = pc.get(l_mdec_dispatches)
    chunk = impl._stripe_block() * 2
    full = _encode_stacked(impl, np.random.default_rng(17), 2, chunk)
    got = impl.decode_batch({i: full[i] for i in (0, 2, 3, 4)}, [1])
    assert np.array_equal(got[1], full[1])
    assert pc.get(l_mdec_dispatches) == before, \
        "the mesh must decline layout-transforming decodes"


# ---- byte identity (the property-test satellite) ---------------------------
MIX = [
    (ErasureCodeTpu, 4, 2, "reed_sol_van"),
    (ErasureCodeTpu, 8, 4, "reed_sol_van"),
    (ErasureCodeIsa, 3, 2, "cauchy"),
    (ErasureCodeIsa, 6, 3, "reed_sol_van"),
]


@pytest.mark.parametrize("seed,rateless", [(11, False), (23, True),
                                           (47, True)])
def test_meshed_decode_byte_identity_property(decode_conf, seed,
                                              rateless):
    """Meshed reconstruction vs the encoded truth across randomized
    (k, m, technique, chunk size, stripe count, erasure set) mixes on
    both branches.  Stripe totals are deliberately NOT multiples of
    the mesh size (padding lanes never leak), erasures mix data and
    parity shards up to m, and every reconstruction must be
    byte-exact with zero single-device fallbacks."""
    _mesh_on(chips=8, rateless=rateless)
    pc = mesh_decode_perf_counters()
    before = pc.get(l_mdec_dispatches)
    fb0 = pc.get(l_mdec_fallbacks)
    rng = np.random.default_rng(seed)
    impls = [_mk_impl(p, k, m, t) for p, k, m, t in MIX]
    for _ in range(10):
        impl = impls[rng.integers(0, len(impls))]
        k, m = impl.k, impl.m
        chunk = int(rng.choice([512, 1024, 1536]))
        stripes = int(rng.integers(1, 7))
        full = _encode_stacked(impl, rng, stripes, chunk)
        # at least one DATA erasure (else decode is a passthrough)
        n_lost = int(rng.integers(1, m + 1))
        lost = [int(rng.integers(0, k))]
        lost += [int(i) for i in rng.choice(
            [i for i in range(k + m) if i != lost[0]],
            size=n_lost - 1, replace=False)]
        chunks = {i: full[i] for i in range(k + m) if i not in lost}
        got = impl.decode_batch(chunks, lost)
        for i in lost:
            assert np.array_equal(got[i], full[i]), \
                (type(impl).__name__, k, m, stripes, chunk, lost, i)
    assert pc.get(l_mdec_dispatches) > before, \
        "no reconstruction rode the mesh"
    assert pc.get(l_mdec_fallbacks) == fb0, \
        "a meshed reconstruction degraded to single-device"


def test_decode_plan_cache_reuses(decode_conf):
    """Two signature-equal reconstructions share ONE decode sharding
    plan (build, then hit), and the plan rows show on the dispatch
    dump with their srcs/want fingerprint."""
    _mesh_on(chips=8)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    pc = mesh_decode_perf_counters()
    b0, h0 = pc.get(l_mdec_plan_builds), pc.get(l_mdec_plan_hits)
    rng = np.random.default_rng(29)
    for _ in range(2):
        full = _encode_stacked(impl, rng, 4, 1024)
        chunks = {i: full[i] for i in (0, 2, 3, 4)}
        got = impl.decode_batch(chunks, [1])
        assert np.array_equal(got[1], full[1])
    assert pc.get(l_mdec_plan_builds) == b0 + 1
    assert pc.get(l_mdec_plan_hits) >= h0 + 1
    rows = [p for p in g_mesh.dump()["plans"]
            if p.get("kind") == "decode"]
    assert rows and rows[0]["srcs"] == [0, 2, 3, 4]
    assert rows[0]["want_rows"] == [1]


# ---- the regenerating family ----------------------------------------------
def test_meshed_regenerating_decode_and_repair(decode_conf):
    """pm_mbr / pm_msr: the ≥d decode and the d×d repair solve are
    plain survivor matmuls — both ride the mesh byte-exactly, and the
    thin single-stripe repair batch is folded along the byte axis so
    it actually spans the chips (col_folds)."""
    from ceph_tpu.ec.regenerating import ErasureCodeRegenerating
    _mesh_on(chips=8, rateless=True)
    pc = mesh_decode_perf_counters()
    d0 = pc.get(l_mdec_dispatches)
    r0 = pc.get(l_mdec_repair_solves)
    f0 = pc.get(l_mdec_col_folds)
    fb0 = pc.get(l_mdec_fallbacks)
    rng = np.random.default_rng(11)
    for tech, m in (("pm_mbr", "2"), ("pm_msr", "3")):
        r = ErasureCodeRegenerating()
        r.init({"k": "4", "m": m, "technique": tech, "backend": "tpu"})
        n = r.k + r.m
        sw = r.preferred_stripe_width()
        sinfo = r.make_stripe_info(sw)
        payload = rng.integers(0, 256, size=2 * sw, dtype=np.uint8)
        shards = eu_encode(sinfo, r, payload, set(range(n)))
        stacked = {i: np.ascontiguousarray(
            np.asarray(b).reshape(2, -1)) for i, b in shards.items()}
        missing = 1
        sub = {i: b for i, b in stacked.items() if i != missing}
        dec = r.decode_batch(sub, [missing])
        assert np.array_equal(dec[missing], stacked[missing]), \
            f"{tech} decode mismatch"
        helpers = [i for i in range(n) if i != missing][:r.d]
        contribs = {h: r.repair_contribution(h, missing, stacked[h])
                    for h in helpers}
        rep = r.repair(missing, contribs)
        assert np.array_equal(rep, stacked[missing]), \
            f"{tech} repair mismatch"
    assert pc.get(l_mdec_dispatches) >= d0 + 4
    assert pc.get(l_mdec_repair_solves) >= r0 + 2
    assert pc.get(l_mdec_col_folds) > f0, \
        "the thin repair batch never folded across the byte axis"
    assert pc.get(l_mdec_fallbacks) == fb0


# ---- rateless protection under chip loss / straggling ----------------------
def test_rateless_decode_block_loss_resolved_from_subset(decode_conf):
    """A chip that dies mid-decode (mesh.chip_fail) is just an
    erasure: the drain completes from the first spanning subset and
    the missing systematic blocks are byte-identically re-solved on
    host — zero single-device fallbacks."""
    _mesh_on(chips=8, rateless=True)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    rng = np.random.default_rng(41)
    pc = mesh_decode_perf_counters()
    rl = rateless_perf_counters()
    fb0 = pc.get(l_mdec_fallbacks)
    hr0 = rl.get(l_rl_host_resolves)
    sc0 = rl.get(l_rl_subset_completions)
    g_faults.inject("mesh.chip_fail", mode="always", match="chip=3/")
    try:
        for _ in range(2):
            full = _encode_stacked(impl, rng, 8, 1024)
            chunks = {i: full[i] for i in (0, 1, 3, 4)}
            got = impl.decode_batch(chunks, [2, 5])
            for i in (2, 5):
                assert np.array_equal(got[i], full[i])
    finally:
        g_faults.clear("mesh.chip_fail")
    assert rl.get(l_rl_host_resolves) > hr0, \
        "the lost chip's blocks were never re-solved on host"
    assert rl.get(l_rl_subset_completions) > sc0
    assert pc.get(l_mdec_fallbacks) == fb0, \
        "a spanning subset answered — the single-device fallback " \
        "must not fire"


def test_decode_straggler_completes_from_spanning_subset(decode_conf):
    """A 10x-slowed chip (mesh.chip_slowdown) never blocks a rateless
    decode: the drain routes around it via parity and completes from
    the first spanning subset, byte-exact, zero fallbacks."""
    _mesh_on(chips=8, rateless=True)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    rng = np.random.default_rng(43)
    pc = mesh_decode_perf_counters()
    rl = rateless_perf_counters()
    fb0 = pc.get(l_mdec_fallbacks)
    sc0 = rl.get(l_rl_subset_completions)
    g_faults.inject("mesh.chip_slowdown", mode="always",
                    match="chip=5/", delay_us=20_000)
    try:
        full = _encode_stacked(impl, rng, 8, 1024)
        chunks = {i: full[i] for i in (1, 2, 3, 5)}
        got = impl.decode_batch(chunks, [0, 4])
        for i in (0, 4):
            assert np.array_equal(got[i], full[i])
    finally:
        g_faults.clear("mesh.chip_slowdown")
    assert rl.get(l_rl_subset_completions) > sc0, \
        "the drain waited for the straggler instead of completing " \
        "from the spanning subset"
    assert pc.get(l_mdec_fallbacks) == fb0


# ---- fault-guarded degradation ---------------------------------------------
def test_decode_guard_exhaustion_degrades_byte_identical(decode_conf):
    """mesh.decode_batch exhaustion: the group degrades to the
    single-device path — the client read stays byte-exact, the
    fallback is counted and ``mesh_decode_degraded`` is journaled."""
    _mesh_on(chips=8)
    g_journal.reset()
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    full = _encode_stacked(impl, np.random.default_rng(53), 4, 1024)
    pc = mesh_decode_perf_counters()
    fb0 = pc.get(l_mdec_fallbacks)
    g_faults.inject("mesh.decode_batch", mode="always", error="device")
    try:
        got = impl.decode_batch({i: full[i] for i in (0, 2, 3, 4)},
                                [1])
    finally:
        g_faults.clear("mesh.decode_batch")
        g_breakers.reset()
    assert np.array_equal(got[1], full[1]), \
        "the degraded decode lost byte identity"
    assert pc.get(l_mdec_fallbacks) > fb0
    evs = [e for e in g_journal.merged()
           if e["type"] == "mesh_decode_degraded"]
    assert evs and evs[0]["repair"] is False
    assert evs[0]["stripes"] == 4


# ---- elastic membership mid-decode (the regression satellite) ---------------
def test_membership_mid_decode_drains_and_invalidates(decode_conf):
    """An ec_mesh_chips transition with decode groups queued AND
    in-flight: the old mesh drains them first (byte-exact, zero
    fallbacks), their sharding-plan cache entries are invalidated,
    and the next decode rebuilds its plan on the NEW mesh."""
    from ceph_tpu.mesh.runtime import (l_member_drained_reqs,
                                       membership_perf_counters)
    _mesh_on(chips=8)
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    k, m, chunk = 4, 2, 1024
    sinfo = stripe_info_t(k, k * chunk)
    rng = np.random.default_rng(61)
    pc = mesh_decode_perf_counters()
    fb0 = pc.get(l_mdec_fallbacks)

    # a first decode builds the plan on the 8-mesh
    full = _encode_stacked(impl, rng, 4, chunk)
    got = impl.decode_batch({i: full[i] for i in (0, 2, 3, 4)}, [1])
    assert np.array_equal(got[1], full[1])
    assert [p for p in g_mesh.dump()["plans"]
            if p.get("kind") == "decode"], "no decode plan cached"

    # queue degraded reads (decode_concat groups), NOT yet flushed
    mpc = membership_perf_counters()
    dr0 = mpc.get(l_member_drained_reqs)
    futs, oracles = [], []
    for _ in range(3):
        fl = _encode_stacked(impl, rng, 2, chunk)
        chunks = {i: np.asarray(fl[i]).reshape(-1)
                  for i in (0, 2, 3, 4, 5)}
        want = np.stack([fl[i] for i in range(k)], axis=1)
        oracles.append(np.ascontiguousarray(want).reshape(-1))
        futs.append(g_dispatcher.submit_decode_concat(
            sinfo, impl, chunks))

    b_before = pc.get(l_mdec_plan_builds)
    g_conf.set_checked("ec_mesh_chips", 6)      # injectargs-live
    assert g_mesh.topology().size == 6
    for f, oracle in zip(futs, oracles):
        assert np.asarray(f.result()).tobytes() == oracle.tobytes(), \
            "a decode group lost bytes across the transition"
    assert mpc.get(l_member_drained_reqs) - dr0 >= 3, \
        "the transition did not drain the queued decode groups"
    # the 8-mesh decode plans are gone; the next decode rebuilds
    assert not [p for p in g_mesh.dump()["plans"]
                if p.get("kind") == "decode"], \
        "stale decode plans survived the membership transition"
    full = _encode_stacked(impl, rng, 4, chunk)
    got = impl.decode_batch({i: full[i] for i in (0, 2, 3, 4)}, [1])
    assert np.array_equal(got[1], full[1])
    assert pc.get(l_mdec_plan_builds) > b_before, \
        "the post-transition decode reused a stale plan"
    assert pc.get(l_mdec_fallbacks) == fb0


# ---- the cluster twin (stored-bytes satellite) ------------------------------
def _ec_shard_bodies(c):
    out = {}
    for i, osd in c.osds.items():
        for cid in osd.store.list_collections():
            if "_meta" in cid or "s" not in cid.split(".")[-1]:
                continue
            for ho in osd.store.list_objects(cid):
                out[(i, cid, str(ho))] = osd.store.read(cid, ho)
    return out


def test_twin_cluster_degraded_reads_byte_identical(decode_conf):
    """A mesh-up cluster under DEGRADED reads (a data-shard holder
    killed mid-workload) returns every read byte-exact through the
    meshed decode path and stores shard bodies byte-identical to a
    single-device twin."""
    from ceph_tpu.cluster import MiniCluster
    pc = mesh_decode_perf_counters()

    def run(mesh: bool):
        if mesh:
            _mesh_on(chips=8, rateless=True)
            g_conf.set_val("ec_dispatch_batch_window_us", 200_000)
        else:
            for name in ("ec_mesh_chips", "ec_mesh_rateless",
                         "ec_dispatch_batch_window_us"):
                g_conf.rm_val(name)
        g_mesh.topology()
        c = MiniCluster(n_osds=6)
        c.create_ec_pool("dtwin", k=3, m=2, pg_num=4)
        cl = c.client("client.dtwin")
        rng = np.random.default_rng(77)
        expected = {}
        for i in range(3):
            body = bytes(rng.integers(0, 256, 9000 + 3001 * i,
                                      dtype=np.uint8))
            assert cl.write_full("dtwin", f"o{i}", body) == 0
            expected[f"o{i}"] = body
        # kill a non-primary DATA-shard holder of o0 — identical
        # placement in both twins picks the same victim
        pid = c.mon.osdmap.lookup_pg_pool_name("dtwin")
        victim = next(
            o.osd_id for o in c.osds.values()
            for cid in o.store.list_collections()
            if cid.startswith(f"{pid}.") and "s" in cid
            and cid.rsplit("s", 1)[1] in ("1", "2")
            and any(ho.oid == "o0"
                    for ho in o.store.list_objects(cid)))
        c.kill_osd(victim)
        c.mark_osd_down(victim)
        for oid, body in expected.items():
            assert cl.read("dtwin", oid) == body, (mesh, oid)
        return victim, _ec_shard_bodies(c)

    d0 = pc.get(l_mdec_dispatches)
    fb0 = pc.get(l_mdec_fallbacks)
    victim_m, meshed = run(mesh=True)
    assert pc.get(l_mdec_dispatches) > d0, \
        "no degraded read rode the meshed decode path"
    assert pc.get(l_mdec_fallbacks) == fb0
    victim_s, single = run(mesh=False)
    assert victim_m == victim_s
    assert set(meshed) == set(single)
    diffs = [key for key in single
             if bytes(meshed[key]) != bytes(single[key])]
    assert not diffs, f"{len(diffs)} shard bodies differ: {diffs[:5]}"
