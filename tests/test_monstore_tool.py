"""ceph-monstore-tool (src/tools/ceph_monstore_tool.cc role): mon
store surgery whose extracted artifacts feed the sibling tools, epoch
reconstruction by incremental replay, and a crush rewrite that a
restored cluster actually observes."""
import io
import json
import os
from contextlib import redirect_stdout

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osdmap.encoding import osdmap_to_dict
from ceph_tpu.tools.monstore_tool import MonStore, main


@pytest.fixture()
def store(tmp_path):
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p1", pg_num=8)
    c.mark_osd_out(2)
    c.create_replicated_pool("p2", pg_num=8)
    d = str(tmp_path / "ck")
    c.checkpoint(d)
    return c, d


def _run(*args):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(list(args))
    return rc, buf.getvalue()


def test_show_versions_and_keys(store):
    _, d = store
    rc, out = _run(d, "show-versions")
    assert rc == 0
    lines = dict(l.split(":\t") for l in out.strip().splitlines())
    assert int(lines["first committed"]) == 1
    assert int(lines["last  committed"]) >= 3
    rc, out = _run(d, "dump-keys")
    assert rc == 0 and "monmap\tlatest" in out


def test_replay_identity_and_old_epochs(store):
    _, d = store
    st = MonStore(d)
    last = st.versions()[1]
    # replaying the WHOLE history reproduces the stored full map
    from ceph_tpu.osdmap.osdmap import OSDMap
    m = OSDMap()
    for inc in st.incrementals():
        m.apply_incremental(inc)
    assert osdmap_to_dict(m) == st.state["osdmap"]
    # mid-history replay: at epoch last-1, osd 2 is already out but
    # pool p2 (created in the last epoch) does not exist yet
    mid = st.osdmap_at(last - 1)
    assert mid.epoch == last - 1
    assert not mid.is_in(2)
    assert "p2" not in mid.pool_name.values()
    assert "p2" in st.osdmap_at(last).pool_name.values()
    # an old epoch differs from the latest (osd 2 not yet out)
    old = st.osdmap_at(1)
    assert old.epoch == 1 and old.is_in(2)
    for bad in (0, 9999):
        with pytest.raises(ValueError):
            st.osdmap_at(bad)


def test_artifacts_feed_sibling_tools(store, tmp_path):
    _, d = store
    mm = str(tmp_path / "monmap.bin")
    om = str(tmp_path / "osd.map")
    cm = str(tmp_path / "crush.bin")
    assert _run(d, "get", "monmap", "-o", mm)[0] == 0
    assert _run(d, "get", "osdmap", "-o", om)[0] == 0
    assert _run(d, "get", "crushmap", "-o", cm)[0] == 0

    from ceph_tpu.mon.monmap import MonMap
    assert MonMap.from_bytes(open(mm, "rb").read()).mons

    import pickle
    m = pickle.loads(open(om, "rb").read())
    assert m.epoch >= 3 and 0 in m.pools

    from ceph_tpu.crush.binfmt import decode_crushmap
    cw = decode_crushmap(open(cm, "rb").read())
    assert cw.get_item_id("default") is not None


def test_rewrite_crush_round_trip(store, tmp_path):
    c, d = store
    cm = str(tmp_path / "crush.bin")
    assert _run(d, "get", "crushmap", "-o", cm)[0] == 0
    # mutate the crushmap offline: reweight osd.0 to half
    from ceph_tpu.crush.binfmt import decode_crushmap, encode_crushmap
    cw = decode_crushmap(open(cm, "rb").read())
    cw.adjust_item_weight(0, 0x8000)          # half weight, 16.16
    open(cm, "wb").write(encode_crushmap(cw))
    st0 = MonStore(d)
    before = st0.versions()[1]
    rc, out = _run(d, "rewrite-crush", "--crush", cm)
    assert rc == 0 and f"epoch {before + 1}" in out
    # a cluster restored from the rewritten store sees the new weight
    c2 = MiniCluster.restore(d)
    assert c2.mon.osdmap.epoch == before + 1
    w = next(b.item_weights[b.items.index(0)]
             for b in c2.mon.osdmap.crush.crush.buckets
             if b is not None and 0 in b.items)
    assert w == 0x8000


def test_error_contracts(store, tmp_path):
    _, d = store
    assert _run()[0] == 1                      # usage
    assert _run(str(tmp_path / "nope"), "show-versions")[0] == 1
    assert _run(d, "get", "wat")[0] == 1
    assert _run(d, "rewrite-crush")[0] == 1
