"""rbd journaling + rbd-mirror-lite: write-ahead events, local crash
replay, cross-cluster replication with resume and trim.

Mirrors the reference's rbd-mirror test surface at lite scale
(src/test/rbd_mirror): journal events precede data application, a
mirror client replays them onto a second cluster's image and commits
its position, a killed mirror resumes where it stopped, and source
trim is gated on the mirror's progress.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rbd import Image, ImageMirror, RBD, RBDError

ORDER = 12
OBJ = 1 << ORDER


@pytest.fixture()
def pair():
    a = MiniCluster(n_osds=4)
    a.create_replicated_pool("rbd", size=3, pg_num=8)
    b = MiniCluster(n_osds=4)
    b.create_replicated_pool("rbd", size=3, pg_num=8)
    ca, cb = a.client("client.a"), b.client("client.b")
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, journaling=True)
    return a, b, ca, cb


def test_mirror_replicates_everything(pair):
    a, b, ca, cb = pair
    src = Image(ca, "rbd", "img")
    src.write(0, b"first-write")
    src.write(2 * OBJ, b"span" * 100)
    m = ImageMirror(ca, "rbd", "img", cb, "rbd")
    assert m.run_once() == 2
    dst = Image(cb, "rbd", "img")
    assert dst.read(0, 11) == b"first-write"
    assert dst.read(2 * OBJ, 400) == b"span" * 100
    # subsequent ops flow incrementally
    src.discard(0, 4)
    src.resize(4 * OBJ)
    src.snap_create("s1")
    src.write(4, b"XYZ")
    assert m.run_once() == 4
    dst = Image(cb, "rbd", "img")
    assert dst.size() == 4 * OBJ
    assert dst.read(0, 7) == b"\x00\x00\x00\x00XYZ"
    assert "s1" in dst.snap_list()
    # the dst snapshot view matches the src point-in-time
    snapv = Image(cb, "rbd", "img", snapshot="s1")
    assert snapv.read(0, 11) == b"\x00" * 4 + b"t-write"
    assert m.run_once() == 0            # idempotent when caught up
    # snap removal replicates too (journaled like every mutation)
    src.snap_remove("s1")
    assert m.run_once() == 1
    assert "s1" not in Image(cb, "rbd", "img").snap_list()


def test_mirror_resumes_after_kill(pair):
    a, b, ca, cb = pair
    src = Image(ca, "rbd", "img")
    for i in range(6):
        src.write(i * 100, b"e%d" % i)
    m = ImageMirror(ca, "rbd", "img", cb, "rbd")
    # simulate a crash mid-replay: apply only part of the stream
    applied = 0
    pos = m._commit_position()
    import json as _json
    from ceph_tpu.rbd import apply_image_event
    for tid, payload in m.journal.replay(after_tid=pos):
        apply_image_event(m.dst, _json.loads(payload))
        m.journal.commit("mirror", tid)
        applied += 1
        if applied == 3:
            break                        # "killed" here
    # a NEW mirror picks up exactly where the dead one committed
    m2 = ImageMirror(ca, "rbd", "img", cb, "rbd")
    assert m2.run_once() == 3
    dst = Image(cb, "rbd", "img")
    for i in range(6):
        assert dst.read(i * 100, 2) == b"e%d" % i


def test_trim_gated_on_mirror(pair):
    a, b, ca, cb = pair
    src = Image(ca, "rbd", "img")
    m = ImageMirror(ca, "rbd", "img", cb, "rbd")
    jr = m.journal
    for i in range(jr.splay * jr.entries_per_object + 5):
        src.write(0, b"%d" % (i % 10))
    # the primary has applied everything, but the mirror lags: trim
    # must reclaim nothing past the mirror's commit position
    assert m.trim_source() == 0
    m.run_once()
    assert m.trim_source() >= 1


def test_local_crash_replay(pair):
    """A primary dying between journal append and data apply heals on
    the next open via replay_local (write-ahead contract)."""
    a, b, ca, cb = pair
    src = Image(ca, "rbd", "img")
    src.write(0, b"applied")
    # append an event WITHOUT applying it (the crash window)
    import base64, json as _json
    src._journal_event({"op": "write", "offset": 100,
                        "data": base64.b64encode(b"torn").decode()})
    reopened = Image(ca, "rbd", "img")
    assert reopened.read(100, 4) == b"\x00\x00\x00\x00"   # not applied
    assert reopened.replay_local() == 1
    assert reopened.read(100, 4) == b"torn"
    assert reopened.read(0, 7) == b"applied"
    assert reopened.replay_local() == 0                   # idempotent


def test_failed_apply_healed_before_next_event(pair):
    """An event journaled but never applied (apply failed mid-op) must
    be healed before a LATER op commits a higher tid — commit is
    monotonic, so skipping it would diverge from the mirror forever."""
    a, b, ca, cb = pair
    src = Image(ca, "rbd", "img")
    src.write(0, b"base")
    import base64
    # simulate the crash window: append without applying
    src._journal_event({"op": "write", "offset": 200,
                        "data": base64.b64encode(b"ORPHAN").decode()})
    # the next op on the same handle heals the orphan first
    src.write(300, b"later")
    assert src.read(200, 6) == b"ORPHAN"
    assert src.read(300, 5) == b"later"
    # and the mirror sees both, in order
    m = ImageMirror(ca, "rbd", "img", cb, "rbd")
    m.run_once()
    dst = Image(cb, "rbd", "img")
    assert dst.read(200, 6) == b"ORPHAN"
    assert dst.read(300, 5) == b"later"


def test_mirror_requires_journaling(pair):
    a, b, ca, cb = pair
    RBD(ca).create("rbd", "plain", OBJ, ORDER)
    with pytest.raises(RBDError):
        ImageMirror(ca, "rbd", "plain", cb, "rbd")


def test_pool_mirror(pair):
    """Pool-mode mirroring: every journaled image replicates; plain
    images are skipped; images created later are picked up."""
    from ceph_tpu.rbd import PoolMirror
    a, b, ca, cb = pair
    RBD(ca).create("rbd", "second", 4 * OBJ, ORDER, journaling=True)
    RBD(ca).create("rbd", "plain", OBJ, ORDER)        # not journaled
    Image(ca, "rbd", "img").write(0, b"img-bytes")
    Image(ca, "rbd", "second").write(0, b"second-bytes")
    pm = PoolMirror(ca, "rbd", cb, "rbd")
    applied = pm.run_once()
    assert applied == {"img": 1, "second": 1}
    assert Image(cb, "rbd", "img").read(0, 9) == b"img-bytes"
    assert Image(cb, "rbd", "second").read(0, 12) == b"second-bytes"
    assert "plain" not in RBD(cb).list("rbd")
    # a later image joins on the next scan
    RBD(ca).create("rbd", "late", OBJ, ORDER, journaling=True)
    Image(ca, "rbd", "late").write(0, b"late-bytes")
    applied = pm.run_once()
    assert applied["late"] == 1
    assert Image(cb, "rbd", "late").read(0, 10) == b"late-bytes"
    pm.trim_sources()


def test_pool_mirror_recreated_image(pair):
    """Delete + recreate under the same name between scans: the pool
    mirror rebinds to the NEW image id instead of replaying the dead
    journal forever."""
    from ceph_tpu.rbd import PoolMirror
    a, b, ca, cb = pair
    Image(ca, "rbd", "img").write(0, b"old-gen")
    pm = PoolMirror(ca, "rbd", cb, "rbd")
    pm.run_once()
    RBD(ca).remove("rbd", "img")
    # the stale DESTINATION is dropped automatically on rebind (old
    # bytes must not shine through offsets the new generation never
    # wrote)
    RBD(ca).create("rbd", "img", 4 * OBJ, ORDER, journaling=True)
    Image(ca, "rbd", "img").write(0, b"new-gen!")
    applied = pm.run_once()
    assert applied["img"] == 1
    assert Image(cb, "rbd", "img").read(0, 8) == b"new-gen!"


def test_mirror_replicates_snap_rollback(pair):
    """snap_rollback is journaled as ONE op event (SnapRollbackEvent
    role): the mirror replays the semantic rollback against its own
    replicated snapshot, so a rolled-back primary and its secondary
    converge instead of silently diverging (the inner restore I/O
    never crosses the journal)."""
    a, b, ca, cb = pair
    src = Image(ca, "rbd", "img")
    src.write(0, b"keep-me")
    src.snap_create("s1")
    src.write(0, b"OVERWRITTEN")
    src.write(3 * OBJ, b"late-object")
    m = ImageMirror(ca, "rbd", "img", cb, "rbd")
    m.run_once()
    src.snap_rollback("s1")
    assert src.read(0, 7) == b"keep-me"
    n = m.run_once()
    assert n >= 1                       # the rollback event replicated
    dst = Image(cb, "rbd", "img")
    assert dst.read(0, 7) == b"keep-me"
    assert dst.read(0, 11) == src.read(0, 11)
    assert dst.size() == src.size()
    # post-rollback mutations keep flowing
    src.write(1, b"after")
    m.run_once()
    assert Image(cb, "rbd", "img").read(0, 8) == src.read(0, 8)
