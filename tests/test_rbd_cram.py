"""rbd CLI cram parity: replay the reference's recorded rbd shell
transcripts (src/test/cli/rbd/*.t) byte-exact through the mini-cram
interpreter.

These pin the whole argv surface the reference's Shell
(src/tools/rbd/Shell.cc) exposes without a cluster: the full help
corpus (80 commands through OptionPrinter/IndentStream formatting),
boost::program_options-stage errors (too many arguments, invalid
option values), and the execute-stage validation messages from
src/tools/rbd/Utils.cc (image/snap/path/lock/meta presence checks).
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cram import assert_cram  # noqa: E402

REF = "/root/reference/src/test/cli/rbd"

ALL = ["help.t", "not-enough-args.t", "too-many-args.t",
       "invalid-snap-usage.t"]


@pytest.mark.parametrize("name", ALL)
def test_rbd_cram(name, tmp_path):
    path = os.path.join(REF, name)
    if not os.path.exists(path):
        pytest.skip("reference cram corpus not present")
    assert_cram(path, str(tmp_path))
