"""rbd CLI cram parity: replay the reference's recorded rbd shell
transcripts (src/test/cli/rbd/*.t) byte-exact through the mini-cram
interpreter.

These pin the whole argv surface the reference's Shell
(src/tools/rbd/Shell.cc) exposes without a cluster: the full help
corpus (80 commands through OptionPrinter/IndentStream formatting),
boost::program_options-stage errors (too many arguments, invalid
option values), and the execute-stage validation messages from
src/tools/rbd/Utils.cc (image/snap/path/lock/meta presence checks).
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cram import assert_cram  # noqa: E402

REF = "/root/reference/src/test/cli/rbd"

ALL = ["help.t", "not-enough-args.t", "too-many-args.t",
       "invalid-snap-usage.t"]


@pytest.mark.parametrize("name", ALL)
def test_rbd_cram(name, tmp_path):
    path = os.path.join(REF, name)
    if not os.path.exists(path):
        pytest.skip("reference cram corpus not present")
    assert_cram(path, str(tmp_path))


def test_rbd_bench_flows(tmp_path):
    """rbd bench (tools/rbd/action/Bench.cc role) through the shell:
    write / readwrite+rand patterns produce the reference-shaped
    report; a missing --io-type is the action-level EINVAL."""
    import io
    import jax
    jax.config.update("jax_platforms", "cpu")
    from contextlib import redirect_stdout, redirect_stderr

    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.tools.rbd_shell import execute

    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("rbd", pg_num=8)
    ckpt = str(tmp_path / "ck")
    c.checkpoint(ckpt)

    def rbd(*args):
        out, err = io.StringIO(), io.StringIO()
        with redirect_stdout(out), redirect_stderr(err):
            rc = execute(list(args), ckpt)
        return rc, out.getvalue(), err.getvalue()

    assert rbd("create", "img", "--size", "4M")[0] == 0
    rc, out, _ = rbd("bench", "img", "--io-type", "write",
                     "--io-size", "64K", "--io-total", "1M")
    assert rc == 0 and "elapsed:" in out and "ops/sec:" in out
    rc, out, _ = rbd("bench", "img", "--io-type", "readwrite",
                     "--io-size", "16K", "--io-total", "128K",
                     "--io-pattern", "rand")
    assert rc == 0 and "elapsed:" in out
    rc, _, err = rbd("bench", "img")
    assert rc == 22 and "io-type" in err
    # bench WRITES persist (the checkpoint-back contract)
    rc, out, _ = rbd("export", "img", str(tmp_path / "img.out"))
    assert rc == 0
    data = (tmp_path / "img.out").read_bytes()
    assert b"\xbe" in data
    # size/pattern validation: EINVAL, not tracebacks
    assert rbd("bench", "img", "--io-type", "write",
               "--io-size", "0")[0] == 22
    assert rbd("bench", "img", "--io-type", "write",
               "--io-size", "8M")[0] == 22
    assert rbd("bench", "img", "--io-type", "write",
               "--io-pattern", "bogus")[0] == 22
