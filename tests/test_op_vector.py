"""Multi-op vector interpreter (do_osd_ops, PrimaryLogPG.cc:7796).

Atomic op vectors over both backends: guards abort everything, xattrs
ride shard transactions and survive recovery, omap works on replicated
pools and is rejected on EC pools — mirroring the reference's
TestRados-style op coverage.
"""
import struct

import pytest

from ceph_tpu.client import ObjectOperation
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.msg.messages import (
    CEPH_OSD_CMPXATTR_OP_GT, CEPH_OSD_CMPXATTR_OP_NE,
)


@pytest.fixture(scope="module")
def ec_cluster():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("vec", k=2, m=1, plugin="isa", pg_num=8)
    return c, c.client("client.vec")


@pytest.fixture(scope="module")
def rep_cluster():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rvec", size=3, pg_num=8)
    return c, c.client("client.rvec")


# ---- atomic write vectors -------------------------------------------------

@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_write_and_xattr_vector_is_atomic(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    op = (ObjectOperation().create(exclusive=True)
          .write_full(b"payload-one").set_xattr("tag", b"v1"))
    r, res = cl.operate(pool, "obj-a", op)
    assert r == 0 and all(rr == 0 for rr, _ in res)
    assert cl.read(pool, "obj-a") == b"payload-one"
    assert cl.getxattr(pool, "obj-a", "tag") == b"v1"
    # exclusive create on an existing object: whole vector aborts,
    # nothing committed
    op = (ObjectOperation().create(exclusive=True)
          .write_full(b"CLOBBER").set_xattr("tag", b"v2"))
    r, res = cl.operate(pool, "obj-a", op)
    assert r == -17                       # EEXIST
    assert cl.read(pool, "obj-a") == b"payload-one"
    assert cl.getxattr(pool, "obj-a", "tag") == b"v1"


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_cmpxattr_guard(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    cl.write_full(pool, "guarded", b"before")
    cl.setxattr(pool, "guarded", "ver", b"7")
    # matching guard: the write goes through
    op = (ObjectOperation().cmp_xattr("ver", b"7")
          .write_full(b"after").set_xattr("ver", b"8"))
    r, _ = cl.operate(pool, "guarded", op)
    assert r == 0
    assert cl.read(pool, "guarded") == b"after"
    # failing guard: ECANCELED, nothing changed
    op = (ObjectOperation().cmp_xattr("ver", b"7")
          .write_full(b"NOPE"))
    r, _ = cl.operate(pool, "guarded", op)
    assert r == -125
    assert cl.read(pool, "guarded") == b"after"
    # other comparison operators
    r, _ = cl.operate(pool, "guarded", ObjectOperation().cmp_xattr(
        "ver", b"7", CEPH_OSD_CMPXATTR_OP_GT))
    assert r == 0                         # "8" > "7"
    r, _ = cl.operate(pool, "guarded", ObjectOperation().cmp_xattr(
        "ver", b"8", CEPH_OSD_CMPXATTR_OP_NE))
    assert r == -125


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_truncate_zero_read_vector(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    cl.write_full(pool, "tz", bytes(range(100)) * 10)   # 1000 bytes
    assert cl.truncate(pool, "tz", 500) == 0
    assert cl.stat(pool, "tz") == 500
    assert cl.zero(pool, "tz", 100, 50) == 0
    body = cl.read(pool, "tz")
    assert len(body) == 500
    assert body[100:150] == b"\0" * 50
    assert body[:100] == (bytes(range(100)) * 10)[:100]
    # zero never extends (reference ZERO semantics)
    assert cl.zero(pool, "tz", 490, 100) == 0
    assert cl.stat(pool, "tz") == 500
    # truncate up zero-extends
    assert cl.truncate(pool, "tz", 600) == 0
    assert cl.read(pool, "tz")[500:] == b"\0" * 100
    # read + stat vector in one round trip
    r, res = cl.operate(pool, "tz", ObjectOperation().stat().read(0, 10))
    assert r == 0
    assert struct.unpack("<Q", res[0][1])[0] == 600
    assert res[1][1] == bytes(range(10))


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_xattr_lifecycle(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    cl.write_full(pool, "xa", b"body")
    cl.setxattr(pool, "xa", "a", b"1")
    cl.setxattr(pool, "xa", "b", b"2")
    assert cl.getxattrs(pool, "xa") == {"a": b"1", "b": b"2"}
    assert cl.rmxattr(pool, "xa", "a") == 0
    assert cl.getxattrs(pool, "xa") == {"b": b"2"}
    assert cl.rmxattr(pool, "xa", "a") == -61      # ENODATA
    with pytest.raises(IOError):
        cl.getxattr(pool, "xa", "a")
    # metadata-only mutation must not disturb the body
    assert cl.read(pool, "xa") == b"body"


def test_omap_on_replicated(rep_cluster):
    c, cl = rep_cluster
    cl.write_full("rvec", "om", b"x")
    assert cl.omap_set("rvec", "om", {"k1": b"v1", "k2": b"v2"}) == 0
    assert cl.omap_get("rvec", "om") == {"k1": b"v1", "k2": b"v2"}
    assert cl.omap_rm_keys("rvec", "om", ["k1"]) == 0
    assert cl.omap_get("rvec", "om") == {"k2": b"v2"}


def test_omap_rejected_on_ec(ec_cluster):
    c, cl = ec_cluster
    cl.write_full("vec", "om-ec", b"x")
    r, _ = cl.operate("vec", "om-ec",
                      ObjectOperation().omap_set({"k": b"v"}))
    assert r == -95                       # EOPNOTSUPP


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_delete_in_vector(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    cl.write_full(pool, "gone", b"short-lived")
    r, _ = cl.operate(pool, "gone", ObjectOperation().remove())
    assert r == 0
    with pytest.raises(IOError):
        cl.read(pool, "gone")


# ---- xattrs survive failure + recovery ------------------------------------

def test_xattrs_survive_osd_kill_and_recovery_ec():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("surv", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.surv")
    cl.write_full("surv", "keep", b"important-bytes")
    cl.setxattr("surv", "keep", "owner", b"alice")
    _pg, victim = cl._calc_target(cl.lookup_pool("surv"), "keep")
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    c.run_recovery()
    c.network.pump()
    assert cl.read("surv", "keep") == b"important-bytes"
    assert cl.getxattr("surv", "keep", "owner") == b"alice"
    # revive and let it re-peer: attrs still intact afterwards
    c.revive_osd(victim)
    for _ in range(4):
        c.tick(dt=6.0)
    c.run_recovery()
    c.network.pump()
    assert cl.getxattr("surv", "keep", "owner") == b"alice"


def test_xattrs_omap_survive_recovery_replicated():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rsurv", size=3, pg_num=8)
    cl = c.client("client.rsurv")
    cl.write_full("rsurv", "keep", b"rep-bytes")
    cl.setxattr("rsurv", "keep", "owner", b"bob")
    cl.omap_set("rsurv", "keep", {"idx": b"42"})
    _pg, victim = cl._calc_target(cl.lookup_pool("rsurv"), "keep")
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    c.run_recovery()
    c.network.pump()
    assert cl.read("rsurv", "keep") == b"rep-bytes"
    assert cl.getxattr("rsurv", "keep", "owner") == b"bob"
    assert cl.omap_get("rsurv", "keep") == {"idx": b"42"}


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_remove_then_recreate_in_one_vector(fixture, request):
    """The vector's FINAL state decides delete-vs-write: a vector that
    deletes and then recreates must leave the recreated object."""
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    cl.write_full(pool, "phoenix", b"old-body")
    cl.setxattr(pool, "phoenix", "gen", b"1")
    r, _ = cl.operate(pool, "phoenix", ObjectOperation()
                      .remove().write_full(b"new-body"))
    assert r == 0
    assert cl.read(pool, "phoenix") == b"new-body"
    # delete dropped the old attrs; the recreate carried none
    assert cl.getxattrs(pool, "phoenix") == {}


def test_concurrent_ec_vectors_serialize(ec_cluster):
    """Two vectors on one EC object submitted before any pump must not
    interleave their read-modify-write phases (the per-oid queue)."""
    from ceph_tpu.msg.messages import (
        CEPH_OSD_OP_APPEND, MOSDOp, OSDOp,
    )
    c, cl = ec_cluster
    cl.write_full("vec", "race", b"")
    pid = cl.lookup_pool("vec")
    pgid, primary = cl._calc_target(pid, "race")
    for i, payload in enumerate([b"AA", b"BB"]):
        cl._tid += 1
        m = MOSDOp(tid=cl._tid, pool=pid, oid="race", pgid=pgid,
                   ops=[OSDOp(op=CEPH_OSD_OP_APPEND, data=payload)],
                   epoch=cl.osdmap.epoch)
        cl.messenger.send_message(m, f"osd.{primary}")
    c.network.pump()
    assert cl.read("vec", "race") == b"AABB"


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_setxattr_creates_consistent_empty_object(fixture, request):
    """A metadata-only vector on a nonexistent object creates an empty
    object whose size/read/stat remain consistent (SIZE_ATTR stamped)."""
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    assert cl.setxattr(pool, "ghost", "tag", b"boo") == 0
    assert cl.getxattr(pool, "ghost", "tag") == b"boo"
    assert cl.stat(pool, "ghost") == 0
    assert cl.read(pool, "ghost") == b""


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_metadata_reads_on_absent_object_return_enoent(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    with pytest.raises(IOError):
        cl.getxattrs(pool, "never-created")
    if fixture == "rep_cluster":
        with pytest.raises(IOError):
            cl.omap_get(pool, "never-created")


# ---- assert_ver guard (PrimaryLogPG.cc do_osd_ops CEPH_OSD_OP_ASSERT_VER)

@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_assert_version_guard(fixture, request):
    """assert_version passes at the observed version, aborts the whole
    vector with -ERANGE once an intervening write bumps it."""
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    cl.write_full(pool, "av", b"one")
    v = cl.get_version(pool, "av")
    assert v > 0
    r, _ = cl.operate(pool, "av", ObjectOperation()
                      .assert_version(v).write_full(b"two"))
    assert r == 0
    assert cl.read(pool, "av") == b"two"
    # the guarded write bumped the version: the old guard must now fail
    # and the payload must NOT land
    r, _ = cl.operate(pool, "av", ObjectOperation()
                      .assert_version(v).write_full(b"stale"))
    assert r == -34
    assert cl.read(pool, "av") == b"two"


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_stat_at_snap_resolves_clone(fixture, request):
    """Snap-targeted stat sizes the clone, not the head (_do_stat now
    resolves snapid like _do_read)."""
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    cl.write_full(pool, "ss", b"short")
    cl.snap_create(pool, "ssnap")
    cl.write_full(pool, "ss", b"a-much-longer-head-payload")
    assert cl.stat(pool, "ss") == 26
    assert cl.stat(pool, "ss", snap="ssnap") == 5
    # object born after the snap is absent at the snap
    cl.write_full(pool, "ss2", b"late")
    cl.snap_create(pool, "ssnap2")
    cl.write_full(pool, "ss3", b"later")
    with pytest.raises(IOError):
        cl.stat(pool, "ss3", snap="ssnap2")


# ---- object classes (src/cls; do_osd_ops CEPH_OSD_OP_CALL) ----------------

@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_cls_hello_and_numops(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    ret, out = cl.exec(pool, "greet", "hello", "say_hello", b"tpu")
    assert ret == 0 and out == b"Hello, tpu!"
    # WR method: mutation commits like any write
    ret, _ = cl.exec(pool, "greet", "hello", "record_hello", b"disk")
    assert ret == 0
    assert cl.read(pool, "greet") == b"Hello, disk!"
    assert cl.getxattr(pool, "greet", "hello") == b"1"
    # numops arithmetic on the stored value (cls_numops.cc)
    assert cl.exec(pool, "n", "numops", "add", b"10")[0] == 0
    assert cl.exec(pool, "n", "numops", "add", b"5")[0] == 0
    assert cl.exec(pool, "n", "numops", "mul", b"3")[0] == 0
    assert cl.read(pool, "n") == b"45"
    # unknown method -> EOPNOTSUPP, nothing committed
    ret, _ = cl.exec(pool, "n", "nope", "nada")
    assert ret == -95
    # a failing call aborts the whole vector atomically
    r, _ = cl.operate(pool, "n", ObjectOperation()
                      .call("numops", "add", b"not-a-number")
                      .set_xattr("t", b"x"))
    assert r == -22
    with pytest.raises(IOError):
        cl.getxattr(pool, "n", "t")


@pytest.mark.parametrize("fixture", ["ec_cluster", "rep_cluster"])
def test_copy_from_same_and_cross_pool(fixture, request):
    c, cl = request.getfixturevalue(fixture)
    pool = "vec" if fixture == "ec_cluster" else "rvec"
    payload = bytes(range(256)) * 30
    assert cl.write_full(pool, "src", payload) == 0
    assert cl.setxattr(pool, "src", "tag", b"copied") == 0
    assert cl.copy(pool, "dst", "src") == 0
    assert cl.read(pool, "dst") == payload
    assert cl.getxattr(pool, "dst", "tag") == b"copied"
    # REAL cross-pool copy, with the source in pool id 0 (the falsy-id
    # regression: 0 must not read as "same pool")
    assert cl.lookup_pool(pool) == 0
    c.create_replicated_pool(f"x{pool}", size=3, pg_num=4)
    cl.mon.send_full_map(cl.name)
    c.network.pump()
    assert cl.copy(f"x{pool}", "xdst", "src", src_pool=pool) == 0
    assert cl.read(f"x{pool}", "xdst") == payload
    assert cl.getxattr(f"x{pool}", "xdst", "tag") == b"copied"
    # and rep -> original direction (omap rides along to rep dsts)
    assert cl.write_full(f"x{pool}", "rsrc", b"with-omap") == 0
    cl.omap_set(f"x{pool}", "rsrc", {"k": b"v"})
    assert cl.copy(f"x{pool}", "rdst", "rsrc") == 0
    assert cl.omap_get(f"x{pool}", "rdst") == {"k": b"v"}
    # missing source -> ENOENT, destination untouched
    assert cl.copy(pool, "dst3", "no-such-src") == -2
    with pytest.raises(IOError):
        cl.read(pool, "dst3")
