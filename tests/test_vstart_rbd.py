"""rbd over the multi-process cluster: object classes must be loaded
in every OSD daemon process (osd_class_load_list='*' — the reference
OSD dlopens all cls plugins at start), so cls_rbd calls arriving over
TCP execute the same as in-process.
"""
import time

import pytest

from ceph_tpu.vstart import ProcessCluster


def test_rbd_image_over_process_cluster():
    c = ProcessCluster(
        n_osds=3,
        pool={"name": "rbd", "type": "replicated", "size": 2,
              "pg_num": 8},
        heartbeat_interval=1.0, heartbeat_grace=4.0)
    try:
        cl = c.client("client.x")
        c.wait_healthy(cl)       # map delivery + peering (loaded host)
        from ceph_tpu.rbd import Image, RBD
        rbd = RBD(cl)
        # short retry only for daemons still loading object classes
        last = None
        for attempt in range(30):
            try:
                rbd.create("rbd", "disk", 1 << 14, order=12)
                break
            except Exception as e:
                last = e
                cl.mon.send_full_map(cl.name)
                cl.network.pump(deadline=0.3)
                time.sleep(0.5)
        else:
            raise last
        img = Image(cl, "rbd", "disk")
        img.write(0, b"over-the-wire")
        assert img.read(0, 13) == b"over-the-wire"
        img.snap_create("s1")
        img.write(0, b"after-snap!!!")
        assert Image(cl, "rbd", "disk", snapshot="s1").read(0, 13) == \
            b"over-the-wire"
        assert rbd.list("rbd") == ["disk"]
        # advisory lock round-trips over TCP too
        assert img.lock_exclusive("c1") == 0
        assert img.list_lockers()[0]["cookie"] == "c1"
        assert img.unlock("c1") == 0
    finally:
        c.close()
