"""rbd exclusive lock + object map / fast-diff.

Mirrors the reference's librbd::ExclusiveLock (auto-acquire on first
write, cooperative transition over the header watch, dead-owner break)
and librbd::ObjectMap (per-object existence bitmap maintained under the
lock, consumed by du and export-diff) at lite scale.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rbd import Image, RBD, RBDError

ORDER = 12
OBJ = 1 << ORDER


@pytest.fixture()
def cl():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rbd", size=3, pg_num=8)
    return c, c.client("client.a"), c.client("client.b")


def test_auto_acquire_and_cooperative_transition(cl):
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, exclusive_lock=True)
    a = Image(ca, "rbd", "img")
    b = Image(cb, "rbd", "img")
    assert not a._lock_owned
    a.write(0, b"A-first")                  # auto-acquire on first write
    assert a._lock_owned
    assert len(a.list_lockers()) == 1
    # B's write requests the lock over the header watch; A surrenders
    # cooperatively (it is not mid-op) and B breaks + acquires
    b.write(OBJ, b"B-takes-over")
    assert b._lock_owned
    assert not a._lock_owned and a._lock_surrendered
    # A re-acquires on its next write — the lock keeps moving
    a.write(2 * OBJ, b"A-again")
    assert a._lock_owned and not b._lock_owned
    assert a.read(0, 7) == b"A-first"
    assert a.read(OBJ, 12) == b"B-takes-over"


def test_dead_owner_lock_breaks_on_watch_timeout(cl):
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, exclusive_lock=True)
    a = Image(ca, "rbd", "img")
    a.write(0, b"alive")
    assert a._lock_owned
    # kill A's client: its watch never acks the surrender request
    c.network.down.add("client.a")
    b = Image(cb, "rbd", "img")
    b.write(OBJ, b"B-recovers")             # NotifyTimeout -> break
    assert b._lock_owned
    assert b.read(OBJ, 10) == b"B-recovers"
    assert len(b.list_lockers()) == 1
    assert b.list_lockers()[0]["cookie"] == b._lock_cookie


def test_journal_never_corrupted_by_two_writers(cl):
    """The done-criterion: two handles alternating writes on a
    journaled image must leave ONE coherent journal (each acquisition
    re-scans the append position; the lock serializes appends)."""
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, journaling=True)
    a = Image(ca, "rbd", "img")
    b = Image(cb, "rbd", "img")
    payloads = []
    for i in range(6):
        img = a if i % 2 == 0 else b
        data = bytes([65 + i]) * 100
        img.write(i * 200, data)
        payloads.append((i * 200, data))
    # the journal replays into an identical image: tids never collided
    from ceph_tpu.journal import Journaler
    jr = Journaler(ca, "rbd", a.id)
    jr.open()
    tids = [t for t, _ in jr.replay()]
    assert tids == sorted(set(tids)), "duplicate/reordered journal tids"
    # and a full local replay reproduces exactly the written state
    fresh = Image(ca, "rbd", "img")
    for off, data in payloads:
        assert fresh.read(off, len(data)) == data


def test_object_map_tracks_existence_and_du(cl):
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, object_map=True)
    img = Image(ca, "rbd", "img")
    assert img.object_map_feature
    img.write(0, b"x" * 10)
    img.write(3 * OBJ, b"y" * OBJ)
    m = img.object_map()
    assert m[0] == Image.OM_EXISTS and m[3] == Image.OM_EXISTS
    assert m[1] == Image.OM_NONE
    # du comes from the map: 2 objects' spans
    assert img.du()["used"] == 2 * OBJ
    img.discard(3 * OBJ, OBJ)               # whole-object punch
    assert img.object_map()[3] == Image.OM_NONE
    assert img.du()["used"] == OBJ
    img.resize(2 * OBJ)
    assert len(img.object_map()) == 2


def test_fast_diff_snapshots_and_export(cl):
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, object_map=True)
    img = Image(ca, "rbd", "img")
    img.write(0, b"base0" * 10)
    img.write(2 * OBJ, b"base2" * 10)
    img.snap_create("s1")
    # after the snap every existing object is CLEAN; a write dirties it
    m = img.object_map()
    assert m[0] == Image.OM_CLEAN and m[2] == Image.OM_CLEAN
    img.write(2 * OBJ, b"NEW" * 10)
    assert img.object_map()[2] == Image.OM_EXISTS
    assert img.object_map("s1")[2] == Image.OM_EXISTS  # frozen snap map
    # export-diff from the latest snap reads ONLY dirty objects
    blob = img.export_diff(from_snap="s1")
    import json
    offs = [r[1] for r in json.loads(blob) if r[0] == "w"]
    assert offs and all(2 * OBJ <= o < 3 * OBJ for o in offs)
    # applying the diff onto a copy of s1 reproduces head
    RBD(ca).copy("rbd", "img", "rbd", "restore", src_snap="s1")
    restored = Image(ca, "rbd", "restore")
    restored.import_diff(blob)
    assert restored.read(2 * OBJ, 30) == img.read(2 * OBJ, 30)
    assert restored.read(0, 50) == img.read(0, 50)


def test_object_map_thrash_stays_consistent(cl):
    """Random writes/discards/resizes/snaps: after every op the map
    must match reality exactly (exists <-> non-NONE)."""
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 16 * OBJ, ORDER, object_map=True)
    img = Image(ca, "rbd", "img")
    rng = np.random.default_rng(42)

    def check():
        m = img.object_map()
        nobj = img._objects_in(img.size())
        assert len(m) == nobj
        for objno in range(nobj):
            try:
                ca.stat("rbd", img._obj(objno))
                real = True
            except IOError:
                real = False
            assert (m[objno] != Image.OM_NONE) == real, \
                (objno, m[objno], real)

    snaps = 0
    for i in range(40):
        op = rng.integers(0, 10)
        size = img.size()
        if op < 5:
            off = int(rng.integers(0, max(size - 100, 1)))
            img.write(off, bytes(rng.integers(0, 256, 100,
                                              dtype=np.uint8)))
        elif op < 7:
            off = int(rng.integers(0, max(size - 1, 1)))
            ln = int(rng.integers(1, 2 * OBJ))
            img.discard(off, min(ln, size - off))
        elif op < 8 and size > 2 * OBJ:
            img.resize(int(rng.integers(size // 2, size)))
        elif op < 9:
            img.resize(min(size + OBJ, 32 * OBJ))
        else:
            snaps += 1
            img.snap_create(f"t{snaps}")
        check()


def test_same_client_two_handles_transition(cl):
    """The OSD excludes the notifier's own watches from a notify, so
    sibling handles on ONE client coordinate locally — a live sibling
    mid-op answers busy; an idle one surrenders and the lock moves
    without ever inferring 'owner dead'."""
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, journaling=True)
    a1 = Image(ca, "rbd", "img")
    a2 = Image(ca, "rbd", "img")
    a1.write(0, b"one")
    assert a1._lock_owned
    a2.write(OBJ, b"two")                   # local cooperative handoff
    assert a2._lock_owned and not a1._lock_owned
    a1.write(2 * OBJ, b"three")             # and back
    assert a1._lock_owned and not a2._lock_owned
    # the journal stayed coherent across the handoffs
    from ceph_tpu.journal import Journaler
    jr = Journaler(ca, "rbd", a1.id)
    jr.open()
    tids = [t for t, _ in jr.replay()]
    assert tids == sorted(set(tids))


def test_fast_diff_survives_latest_snap_removal(cl):
    """Removing the LATEST snap invalidates CLEAN bits (they were
    relative to it): export-diff from the new latest snap must not
    skip objects that changed since IT."""
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, object_map=True)
    img = Image(ca, "rbd", "img")
    img.write(0, b"B" * 64)
    img.snap_create("s1")
    img.write(2 * OBJ, b"C" * 64)           # changed after s1
    img.snap_create("s2")                   # C now CLEAN (vs s2)
    img.snap_remove("s2")
    blob = img.export_diff(from_snap="s1")
    import json
    offs = [r[1] for r in json.loads(blob) if r[0] == "w"]
    assert any(2 * OBJ <= o < 3 * OBJ for o in offs), offs


def test_fast_diff_sees_partial_discard_and_shrink(cl):
    c, ca, cb = cl
    RBD(ca).create("rbd", "img", 8 * OBJ, ORDER, object_map=True)
    img = Image(ca, "rbd", "img")
    img.write(0, b"\xAA" * OBJ)
    img.write(OBJ, b"\xBB" * OBJ)
    img.snap_create("s1")
    img.discard(100, 50)                    # partial punch in obj 0
    blob = img.export_diff(from_snap="s1")
    import json
    recs = json.loads(blob)
    offs = [r[1] for r in recs if r[0] in ("w", "z")]
    assert any(o < OBJ for o in offs), recs  # obj 0 not skipped
    # shrink that truncates obj 1's tail: obj 1 must show in the diff
    img2 = Image(ca, "rbd", "img")
    img2.resize(OBJ + 100)
    img2.resize(2 * OBJ)                    # grow back (zeros)
    blob = img2.export_diff(from_snap="s1")
    recs = json.loads(blob)
    offs = [r[1] for r in recs if r[0] in ("w", "z")]
    assert any(OBJ <= o < 2 * OBJ for o in offs), recs
