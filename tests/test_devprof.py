"""Device-flow profiler: host↔device transfer, compile, and memory
accounting (ceph_tpu/trace/devprof.py).

Acceptance gates of the devprof PR:

- a traced EC write in the mini-cluster yields a COMPLETE copy ledger:
  the op's span tree carries ≥1 h2d and ≥1 d2h stage with non-zero
  bytes, plus the host staging stages (pad/stack → device → host →
  sub-op messages);
- ``prof dump`` and the Prometheus exposition agree on transfer
  totals;
- fresh XLA compiles are detected via jit cache-miss observation and
  attributed to the active call-site stage;
- ``devflow_delta`` produces the bench block (copies_per_op /
  bytes_per_op) and regress.py gates it (the copy-budget gate).
"""
import re

import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.trace import devflow_delta, g_devprof, g_tracer
from ceph_tpu.trace.devprof import (DevFlowProfiler,
                                    devprof_perf_counters,
                                    l_devprof_compiles,
                                    l_devprof_d2h_bytes,
                                    l_devprof_h2d_bytes)


@pytest.fixture
def clean_devprof():
    yield
    g_tracer.enable(False)
    g_tracer.collector.clear()
    g_devprof.reset()


# ---- unit: accounting primitives -------------------------------------------
def test_site_accounting_and_totals(clean_devprof):
    p = DevFlowProfiler()
    p.account_h2d("unit.a", 1000)
    p.account_h2d("unit.a", 24)
    p.account_d2h("unit.a", 512)
    p.account_host_copy("unit.b", 4096)
    t = p.totals()
    assert t["h2d_bytes"] == 1024 and t["h2d_count"] == 2
    assert t["d2h_bytes"] == 512 and t["d2h_count"] == 1
    assert t["transfers"] == 3
    assert t["host_copies"] == 1 and t["host_copy_bytes"] == 4096
    d = p.dump()
    assert d["sites"]["unit.a"]["h2d_bytes"] == 1024
    assert d["sites"]["unit.b"]["host_copies"] == 1


def test_ledger_attaches_to_active_span(clean_devprof):
    g_tracer.enable()
    with g_tracer.span("op", daemon="t", trace_id=77) as sp:
        g_devprof.account_h2d("unit.site", 100)
        g_devprof.account_d2h("unit.site", 64)
        g_devprof.account_host_copy("unit.pad", 32)
    led = sp.tags["copy_ledger"]
    assert ({e["dir"] for e in led} == {"h2d", "d2h", "host"}
            and all(e["bytes"] > 0 for e in led))


def test_ledger_free_when_tracing_disabled(clean_devprof):
    """Default-off tracing: accounting still counts (always-on, like
    perf counters) but allocates no ledger anywhere."""
    before = g_devprof.totals()["transfers"]
    g_devprof.account_h2d("unit.off", 10)
    assert g_devprof.totals()["transfers"] == before + 1
    assert g_tracer.collector.dump() == {}


def test_devflow_delta_block():
    before = {"h2d_bytes": 100, "d2h_bytes": 50, "h2d_count": 1,
              "d2h_count": 1, "host_copies": 0, "host_copy_bytes": 0,
              "compiles": 0}
    after = {"h2d_bytes": 1124, "d2h_bytes": 562, "h2d_count": 5,
             "d2h_count": 3, "host_copies": 2, "host_copy_bytes": 99,
             "compiles": 1}
    block = devflow_delta(before, after, n_ops=4)
    assert block["h2d_bytes"] == 1024 and block["d2h_bytes"] == 512
    assert block["transfers"] == 6 and block["compiles"] == 1
    # copies = transfers + host staging copies, per op
    assert block["copies_per_op"] == pytest.approx(8 / 4)
    assert block["bytes_per_op"] == pytest.approx(1536 / 4)


def test_compile_detection_attributes_to_stage(clean_devprof):
    """A fresh jit compile (cache miss) bumps the compile counter under
    the active stage; a cache HIT adds nothing."""
    import jax
    import jax.numpy as jnp
    g_devprof.install_compile_listener()
    pc = devprof_perf_counters()

    # a never-before-seen jaxpr: closure over a fresh python constant
    # makes the trace unique to this test run
    salt = np.random.default_rng().integers(1 << 30)

    def fresh(x):
        return x * jnp.int32(int(salt) % 7 + 2) + jnp.int32(int(salt) % 5)

    jitted = jax.jit(fresh)
    before = pc.get(l_devprof_compiles)
    with g_devprof.stage("unit.compile_probe"):
        jax.block_until_ready(jitted(jnp.arange(4, dtype=jnp.int32)))
    after_first = pc.get(l_devprof_compiles)
    assert after_first > before, "fresh jit compile not detected"
    assert g_devprof.dump()["sites"].get(
        "unit.compile_probe", {}).get("compiles", 0) >= 1
    # same shape again: cache hit, no compile event
    with g_devprof.stage("unit.compile_probe"):
        jax.block_until_ready(jitted(jnp.arange(4, dtype=jnp.int32)))
    assert pc.get(l_devprof_compiles) == after_first, \
        "jit cache hit was miscounted as a compile"


def test_device_mem_sample_never_raises(clean_devprof):
    out = g_devprof.sample_device_mem()
    assert out["source"] in ("memory_stats", "live_arrays", "none")
    assert out["peak_bytes_in_use"] >= 0


def test_reset_zeroes_everything(clean_devprof):
    g_devprof.account_h2d("unit.r", 10)
    g_devprof.account_host_copy("unit.r", 10)
    g_devprof.reset()
    d = g_devprof.dump()
    assert d["sites"] == {}
    t = d["totals"]
    assert all(v == 0 for v in t.values())
    assert d["counters"]["h2d_bytes"] == 0


# ---- cluster acceptance -----------------------------------------------------
@pytest.fixture(scope="module")
def prof_cluster():
    """One shared mini-cluster for the acceptance tests (the profiler
    is process-global; each test works off counter deltas / its own
    trace id, so sharing the boot costs tier-1 nothing in isolation)."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("prof", k=3, m=2, pg_num=8)
    return c


def test_traced_ec_write_yields_complete_copy_ledger(prof_cluster,
                                                     clean_devprof):
    """Acceptance: one traced EC write shows its full copy ledger on
    the op's span tree — ≥1 h2d and ≥1 d2h stage with non-zero bytes,
    plus the host staging stages (stripe pad, shard pack-out)."""
    c = prof_cluster
    cl = c.client()
    g_tracer.enable()
    assert cl.write_full("prof", "obj", b"L" * 20000) == 0

    # collect every ledger entry on the write's trace
    spans = [s for ring in g_tracer.collector._rings.values()
             for s in ring]
    trace_ids = {s.trace_id for s in spans
                 if s.name.startswith("osd_op:writefull")}
    assert trace_ids, "no traced write op span"
    tid = trace_ids.pop()
    ledger = [e for s in spans if s.trace_id == tid
              for e in s.tags.get("copy_ledger", [])]
    dirs = {e["dir"] for e in ledger}
    assert "h2d" in dirs and "d2h" in dirs, ledger
    assert all(e["bytes"] > 0 for e in ledger)
    stages = {e["stage"] for e in ledger}
    # the write path's staging stages are all visible (the pack is the
    # one materialized host copy; fan-out sends zero-copy memoryviews
    # of its rows, so the old shard_slice/subop_messages pair is gone)
    assert "gf_matmul.encode" in stages
    assert "ecutil.pack_shards" in stages
    assert "ecutil.shard_slice" not in stages
    assert "ec.subop_messages" not in stages


def test_prof_dump_and_prometheus_agree(prof_cluster, clean_devprof):
    """Acceptance: the admin socket's `prof dump` totals equal the
    Prometheus exposition's ceph_daemon_devprof_* samples (one source
    of truth, two surfaces)."""
    c = prof_cluster
    cl = c.client()
    assert cl.write_full("prof", "agree", b"A" * 16000) == 0
    dump = c.admin_socket.execute("prof dump")
    totals = dump["totals"]
    assert totals["h2d_bytes"] > 0 and totals["d2h_bytes"] > 0

    text = c.admin_socket.execute("prometheus metrics")

    def prom(name):
        m = re.search(rf"^ceph_daemon_devprof_{name} (\d+(?:\.\d+)?)$",
                      text, re.M)
        assert m, f"ceph_daemon_devprof_{name} missing from exposition"
        return float(m.group(1))

    # the exposition is rendered AFTER the dump: totals can only grow,
    # and nothing in between touches the device — they must agree
    assert prom("h2d_bytes") == totals["h2d_bytes"]
    assert prom("d2h_bytes") == totals["d2h_bytes"]
    assert prom("h2d_transfers") == totals["h2d_count"]
    assert prom("d2h_transfers") == totals["d2h_count"]
    assert prom("compiles") == totals["compiles"]
    # high-water gauge present (sampled at scrape)
    assert prom("device_mem_highwater_bytes") >= 0


def test_prof_dump_counts_batched_writes_too(prof_cluster,
                                             clean_devprof):
    """The dispatcher's coalesced path accounts through the same
    funnels: a batched write adds pad/stack host copies and one
    h2d/d2h pair for the whole batch."""
    c = prof_cluster
    cl = c.client()
    cl.write_full("prof", "warm", b"w" * 8000)
    g_conf.set_val("ec_dispatch_batch_window_us", 100_000)
    g_conf.set_val("ec_dispatch_batch_max", 8)
    try:
        t0 = g_devprof.totals()
        assert cl.write_full("prof", "batched", b"B" * 16000) == 0
        t1 = g_devprof.totals()
    finally:
        g_conf.rm_val("ec_dispatch_batch_window_us")
        g_conf.rm_val("ec_dispatch_batch_max")
    assert t1["h2d_count"] > t0["h2d_count"]
    assert t1["d2h_count"] > t0["d2h_count"]
    assert t1["h2d_bytes"] - t0["h2d_bytes"] >= 16000


def test_transfer_size_histogram_lands_samples(clean_devprof):
    """Every transfer lands in the devprof log2 size histogram (the
    `perf histogram dump` / Prometheus family)."""
    from ceph_tpu.trace import g_perf_histograms
    hist = g_perf_histograms.get("devprof",
                                 "devprof_transfer_size_histogram")
    n0 = hist.total_count
    g_devprof.account_h2d("unit.hist", 4096)
    g_devprof.account_d2h("unit.hist", 100)
    assert hist.total_count == n0 + 2
    # host staging copies are NOT transfers: histogram untouched
    g_devprof.account_host_copy("unit.hist", 8192)
    assert hist.total_count == n0 + 2


# ---- copy-budget gate -------------------------------------------------------
def _metric(name, value, devflow, unit="GiB/s"):
    return {"schema_version": 1, "name": name, "value": value,
            "unit": unit, "fenced": True, "devflow": devflow}


def _flow(copies, bpo):
    return {"h2d_bytes": 0, "d2h_bytes": 0, "transfers": 0,
            "compiles": 0, "host_copies": 0,
            "copies_per_op": copies, "bytes_per_op": bpo}


def test_copy_budget_gate_flags_copy_regression(tmp_path):
    """regress.py: copies_per_op / bytes_per_op are gated metrics —
    more copies than baseline beyond tolerance is a REGRESSION even
    when throughput is unchanged."""
    import json
    from ceph_tpu.bench import regress
    base = _metric("wl", 1.0, _flow(2.0, 1000.0))
    with open(tmp_path / "BENCH_r90.json", "w") as f:
        json.dump({"n": 90, "rc": 0,
                   "parsed": {"platform": "cpu", "metrics": [base]}}, f)
    traj = regress.load_trajectory(str(tmp_path))
    # same throughput, 2x the copies: the copy budget trips
    cur = [_metric("wl", 1.0, _flow(4.0, 1000.0))]
    gate = regress.compare_against_trajectory(cur, traj, "cpu")
    names = [r["name"] for r in gate["regressions"]]
    assert "wl.copies_per_op" in names
    assert "wl.bytes_per_op" not in names
    # fewer copies: an improvement, not a regression
    cur = [_metric("wl", 1.0, _flow(1.0, 400.0))]
    gate = regress.compare_against_trajectory(cur, traj, "cpu")
    assert not gate["regressions"]
    imp = [r["name"] for r in gate["improvements"]]
    assert "wl.copies_per_op" in imp and "wl.bytes_per_op" in imp


def test_copy_budget_gate_zero_copy_baseline_is_sacred(tmp_path):
    """A workload whose baseline moved (effectively) ZERO bytes must
    stay zero-copy: a real per-op copy chain appearing regresses
    regardless of relative tolerance — but sub-floor drift (the fence
    drain's 1/n_steps noise on device-resident workloads, whose step
    count is calibration-dependent) gates nothing."""
    import json
    from ceph_tpu.bench import regress
    base = _metric("zc", 1.0, _flow(0.0, 0.0))
    with open(tmp_path / "BENCH_r91.json", "w") as f:
        json.dump({"n": 91, "rc": 0,
                   "parsed": {"platform": "cpu", "metrics": [base]}}, f)
    traj = regress.load_trajectory(str(tmp_path))
    cur = [_metric("zc", 1.0, _flow(0.5, 2048.0))]
    gate = regress.compare_against_trajectory(cur, traj, "cpu")
    assert {"zc.copies_per_op", "zc.bytes_per_op"} <= \
        {r["name"] for r in gate["regressions"]}
    # sub-floor drift (drain-fence noise): clean — this is what keeps
    # measure_encode/measure_decode (device-resident, copies_per_op
    # ~ 1/n_steps with run-calibrated n_steps) from flapping the gate
    cur = [_metric("zc", 1.0, _flow(0.1, 100.0))]
    gate = regress.compare_against_trajectory(cur, traj, "cpu")
    assert not gate["regressions"]
    # still exactly zero-copy: clean
    cur = [_metric("zc", 1.0, _flow(0.0, 0.0))]
    gate = regress.compare_against_trajectory(cur, traj, "cpu")
    assert not gate["regressions"]


def test_legacy_rounds_without_devflow_gate_nothing(tmp_path):
    """Archived rounds predating the devprof PR carry no devflow —
    the copy gate must skip them silently, not crash or fabricate a
    zero baseline."""
    import json
    from ceph_tpu.bench import regress
    base = {"schema_version": 1, "name": "wl", "value": 1.0,
            "unit": "GiB/s", "fenced": True}     # no devflow key
    with open(tmp_path / "BENCH_r92.json", "w") as f:
        json.dump({"n": 92, "rc": 0,
                   "parsed": {"platform": "cpu", "metrics": [base]}}, f)
    traj = regress.load_trajectory(str(tmp_path))
    cur = [_metric("wl", 1.0, _flow(3.0, 999.0))]
    gate = regress.compare_against_trajectory(cur, traj, "cpu")
    assert not gate["regressions"]
