"""Controller state machine (ceph_tpu/control, docs/CONTROL.md):
damping, bounds, anti-windup, cooldowns, episode restore/tear-down,
fault-bounded actuation, and the controller-off twin property.

The closed-loop scenarios (abusive client / recovery storm / slowed
chip) converging on a REAL MiniCluster are in
tests/test_control_loop.py; these tests drive the state machine
through a minimal fake mgr so every transition is pinned exactly.
"""
from typing import Dict, List, Optional, Tuple

import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.control import Controller, control_perf_counters
from ceph_tpu.control.controller import _parse_bounds


class FakeTelemetry:
    def __init__(self):
        self.slo: Dict[str, Dict] = {}

    def slo_state(self):
        return self.slo


class FakeMgr:
    """The two surfaces Controller.step senses: telemetry SLO streak
    state and the health-check map (plus the cluster log sink)."""

    def __init__(self):
        self.telemetry = FakeTelemetry()
        self.health_checks: Dict[str, Dict] = {}
        self.log: List[Tuple[str, str]] = []

    def _cluster_log(self, lvl, msg):
        self.log.append((lvl, msg))

    def breach(self, check: str):
        self.telemetry.slo = {check: {"state": "breach"}}

    def clear(self):
        self.telemetry.slo = {}


CONTROL_OPTS = ("mgr_control_enable", "mgr_control_bounds",
                "mgr_control_cooldown_ticks", "mgr_control_damping",
                "mgr_control_actuate_retries", "mgr_control_ledger_size")
ACTUATED_OPTS = ("osd_recovery_max_active", "osd_mclock_class_overrides",
                 "osd_mclock_client_overrides",
                 "osd_op_queue_admission_max", "ec_mesh_rateless_tasks")


@pytest.fixture()
def env():
    """Fresh controller + fake mgr; every option either side touches
    is restored afterwards (the options are process-global)."""
    saved = {n: g_conf.get_val(n)
             for n in CONTROL_OPTS + ACTUATED_OPTS}
    from ceph_tpu.recovery import (l_recovery_active,
                                   recovery_perf_counters)
    try:
        yield Controller(), FakeMgr()
    finally:
        for n, v in saved.items():
            g_conf.set_val(n, v)
        recovery_perf_counters().set(l_recovery_active, 0)
        from ceph_tpu.fault import g_faults
        g_faults.clear("control.actuate")


def _storm_on():
    from ceph_tpu.recovery import (l_recovery_active,
                                   recovery_perf_counters)
    recovery_perf_counters().set(l_recovery_active, 1)


def _storm_off():
    from ceph_tpu.recovery import (l_recovery_active,
                                   recovery_perf_counters)
    recovery_perf_counters().set(l_recovery_active, 0)


def test_disabled_controller_is_inert(env):
    """mgr_control_enable off (the default): step() returns before
    sensing — no tick counts, no moves, no config deltas, no log."""
    ctl, mgr = env
    mgr.breach("TPU_SLO_OPLAT")
    _storm_on()
    before = dict(g_conf.values)
    for _ in range(10):
        ctl.step(mgr, 1.0)
    assert ctl._tick == 0
    assert ctl.dump()["ledger"] == []
    assert ctl.moves_total == 0
    assert dict(g_conf.values) == before
    assert mgr.log == []


def test_recovery_reflex_steps_down_damped_and_bounded(env):
    """A sustained TPU_SLO_OPLAT breach during a storm walks
    osd_recovery_max_active down in shrinking steps, one move per
    cooldown window, and pins at the floor without further ledger
    growth (anti-windup)."""
    ctl, mgr = env
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 2)
    g_conf.set_val("osd_recovery_max_active", 8)
    mgr.breach("TPU_SLO_OPLAT")
    _storm_on()
    values = [8]
    for _ in range(40):
        ctl.step(mgr, 1.0)
        values.append(int(g_conf.get_val("osd_recovery_max_active")))
    # one move per cooldown window: at most one change per
    # mgr_control_cooldown_ticks ticks
    changes = [i for i in range(1, len(values))
               if values[i] != values[i - 1]]
    assert all(b - a >= 2 for a, b in zip(changes, changes[1:])), \
        (changes, values)
    # damped: 8 -> 4 (step 4), then shrinking steps, never below floor
    steps = [values[i - 1] - values[i] for i in changes]
    assert steps[0] == 4
    assert all(a >= b for a, b in zip(steps, steps[1:])), steps
    assert min(values) >= 1
    assert values[-1] == 1            # floor reached, held
    # anti-windup: once pinned at the floor the ledger stops growing
    moves_at_floor = [e for e in ctl.dump()["ledger"]
                      if e["knob"] == "osd_recovery_max_active"
                      and e["to"] == 1]
    assert len(moves_at_floor) == 1
    assert control_perf_counters().get(94005) > 0   # pinned counter
    # every ledger entry stayed inside [floor, ceiling]
    for e in ctl.dump()["ledger"]:
        assert 1 <= e["to"] <= 64, e
    # second knob engaged after the first pinned: recovery weight down
    assert ctl.dump()["knobs"]["recovery_class_weight"]["value"] < 100.0


def test_restore_walks_back_to_baseline_and_closes_episode(env):
    """When the breach clears, engaged knobs converge back to their
    episode baselines and the episode state empties."""
    ctl, mgr = env
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 0)
    g_conf.set_val("osd_recovery_max_active", 8)
    mgr.breach("TPU_SLO_OPLAT")
    _storm_on()
    for _ in range(6):
        ctl.step(mgr, 1.0)
    assert int(g_conf.get_val("osd_recovery_max_active")) < 8
    mgr.clear()
    _storm_off()
    for _ in range(30):
        ctl.step(mgr, 1.0)
    assert int(g_conf.get_val("osd_recovery_max_active")) == 8
    d = ctl.dump()
    assert all(k["baseline"] is None for k in d["knobs"].values()), d
    assert any(e["reflex"] == "restore" for e in d["ledger"])
    # hysteretic: restored value holds over further clean ticks
    for _ in range(5):
        ctl.step(mgr, 1.0)
    assert int(g_conf.get_val("osd_recovery_max_active")) == 8


def test_operator_bounds_clamp_every_move(env):
    """mgr_control_bounds floors override the built-ins and the
    controller never steps past them."""
    ctl, mgr = env
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 0)
    g_conf.set_val("mgr_control_bounds",
                   "osd_recovery_max_active:4:32")
    g_conf.set_val("osd_recovery_max_active", 8)
    mgr.breach("TPU_SLO_OPLAT")
    _storm_on()
    for _ in range(20):
        ctl.step(mgr, 1.0)
    assert int(g_conf.get_val("osd_recovery_max_active")) == 4
    assert all(e["to"] >= 4 for e in ctl.dump()["ledger"]
               if e["knob"] == "osd_recovery_max_active")


def test_bounds_parser_tolerates_garbage():
    assert _parse_bounds("") == {}
    assert _parse_bounds("bogus_knob:1:2") == {}
    assert _parse_bounds("osd_recovery_max_active:nope:2") == {}
    assert _parse_bounds("osd_recovery_max_active:2:") == \
        {"osd_recovery_max_active": (2.0, None)}
    assert _parse_bounds(
        "osd_recovery_max_active:2:32,client_lane_weight::10") == \
        {"osd_recovery_max_active": (2.0, 32.0),
         "client_lane_weight": (None, 10.0)}


def test_disable_mid_episode_tears_down(env):
    """Flipping mgr_control_enable off mid-episode restores every
    engaged knob to its baseline on the NEXT step and leaves no
    half-applied state."""
    ctl, mgr = env
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 0)
    g_conf.set_val("osd_recovery_max_active", 8)
    mgr.breach("TPU_SLO_OPLAT")
    _storm_on()
    for _ in range(8):
        ctl.step(mgr, 1.0)
    assert int(g_conf.get_val("osd_recovery_max_active")) < 8
    engaged = sum(1 for k in ctl.dump()["knobs"].values()
                  if k["baseline"] is not None)
    assert engaged >= 1
    g_conf.set_val("mgr_control_enable", False)
    ctl.step(mgr, 1.0)                # the disable lands here
    assert int(g_conf.get_val("osd_recovery_max_active")) == 8
    d = ctl.dump()
    assert all(k["baseline"] is None for k in d["knobs"].values())
    assert any(e["reflex"] == "teardown" for e in d["ledger"])
    # and the controller is inert again: breach on, zero new moves
    moves = ctl.moves_total
    for _ in range(5):
        ctl.step(mgr, 1.0)
    assert ctl.moves_total == moves


def test_faulted_actuation_bounded_retry_never_wedges(env):
    """control.actuate armed always: every actuation fails, the
    retry budget bounds the attempts per tick, the knob never moves,
    and clearing the fault lets the very next move land."""
    from ceph_tpu.fault import g_faults
    ctl, mgr = env
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 0)
    g_conf.set_val("mgr_control_actuate_retries", 2)
    g_conf.set_val("osd_recovery_max_active", 8)
    mgr.breach("TPU_SLO_OPLAT")
    _storm_on()
    g_faults.inject("control.actuate", mode="always")
    pc = control_perf_counters()
    f0, r0 = pc.get(94007), pc.get(94006)
    for _ in range(4):
        ctl.step(mgr, 1.0)
    assert int(g_conf.get_val("osd_recovery_max_active")) == 8
    assert ctl.moves_total == 0
    assert ctl.dump()["ledger"] == []
    # bounded: exactly retries attempts per tick, then the drop
    assert pc.get(94007) - f0 == 4              # one drop per tick
    assert pc.get(94006) - r0 == 4 * 2          # retries per tick
    assert any("actuation dropped" in m for _l, m in mgr.log)
    g_faults.clear("control.actuate")
    ctl.step(mgr, 1.0)
    assert int(g_conf.get_val("osd_recovery_max_active")) == 4
    assert ctl.moves_total == 1


def test_admission_reflex_targets_the_abuser_lane(env):
    """TPU_SLO_ADMISSION burning: the lane whose queue-wait histogram
    grew most is the abuser; its dmClock weight steps down first, then
    its limit cap imposes, all through osd_mclock_client_overrides."""
    from ceph_tpu.trace import g_perf_histograms, latency_axes
    ctl, mgr = env
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 0)
    h = g_perf_histograms.get("client.ctlabuse",
                              "client_queue_wait_latency_histogram",
                              latency_axes)
    mgr.breach("TPU_SLO_ADMISSION")
    for i in range(14):
        for _ in range(50):
            h.inc(1000.0)
        ctl.step(mgr, 1.0)
    ov = str(g_conf.get_val("osd_mclock_client_overrides"))
    assert "client.ctlabuse:" in ov, ov
    d = ctl.dump()
    assert d["abuser"] == "client.ctlabuse"
    assert d["knobs"]["client_lane_weight"]["value"] < 1.0
    assert d["knobs"]["client_lane_limit"]["value"] > 0   # cap imposed
    # clear: the lane walks back to defaults and the abuser forgets
    mgr.clear()
    for _ in range(40):
        ctl.step(mgr, 1.0)
    d = ctl.dump()
    assert d["abuser"] == ""
    assert all(k["baseline"] is None for k in d["knobs"].values())


def test_twin_cluster_controller_off_is_behavior_identical():
    """Twin-cluster property: a cluster whose mgr steps a DISABLED
    controller ends bit-identical (config, health, controller state)
    to one whose mgr never calls step at all — the pre-PR mgr."""
    from ceph_tpu.cluster import MiniCluster

    def drive(strip_step: bool):
        c = MiniCluster(n_osds=3)
        if strip_step:
            c.mgr.control.step = lambda *_a, **_k: None
        c.create_replicated_pool("twin", size=2, pg_num=8)
        cl = c.client("client.twin")
        before = dict(g_conf.values)
        for i in range(8):
            assert cl.write_full("twin", f"o{i}",
                                 bytes([i]) * 2048) == 0
            c.tick(dt=1.0)
        return (dict(g_conf.values) == before,
                sorted(c.mgr.health_checks),
                c.mgr.control.moves_total,
                c.mgr.control._tick,
                list(c.mgr.control._ledger))

    with_step = drive(strip_step=False)
    without_step = drive(strip_step=True)
    assert with_step == without_step
    assert with_step[0] is True       # no config delta either way
    assert with_step[2] == 0 and with_step[3] == 0


def test_control_asok_panes():
    """`tpu control dump` + `control enable|disable|reset` round-trip
    through the admin socket; disable mid-episode restores."""
    from ceph_tpu.cluster import MiniCluster
    saved = {n: g_conf.get_val(n)
             for n in CONTROL_OPTS + ACTUATED_OPTS}
    try:
        c = MiniCluster(n_osds=3)
        asok = c.admin_socket
        assert asok.execute("tpu control dump")["enabled"] is False
        assert asok.execute("control enable") == {"enabled": True}
        assert bool(g_conf.get_val("mgr_control_enable")) is True
        assert asok.execute("tpu control dump")["enabled"] is True
        # open an episode by hand, then disable through the socket:
        # the tear-down must land immediately
        c.mgr.control._state("osd_recovery_max_active")["baseline"] \
            = 8.0
        g_conf.set_val("osd_recovery_max_active", 2)
        assert asok.execute("control disable") == {"enabled": False}
        assert int(g_conf.get_val("osd_recovery_max_active")) == 8
        assert bool(g_conf.get_val("mgr_control_enable")) is False
        out = asok.execute("control reset")
        assert out == {"reset": True, "restored": 0}
        assert asok.execute("tpu control dump")["ledger"] == []
    finally:
        for n, v in saved.items():
            g_conf.set_val(n, v)
