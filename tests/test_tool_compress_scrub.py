"""objectstore-tool, compressor registry + TCP frame compression, and
the periodic scrub scheduler."""
import io
import json
import os
import sys

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common.config import g_conf
from ceph_tpu.compressor import create_compressor, g_compressor_registry
from ceph_tpu.tools import objectstore_tool as ot


# ---- objectstore tool ------------------------------------------------------

@pytest.fixture()
def saved_store(tmp_path):
    c = MiniCluster(n_osds=4)
    c.create_ec_pool("os", k=2, m=1, plugin="isa", pg_num=4)
    cl = c.client("client.os")
    cl.write_full("os", "alpha", b"alpha-bytes" * 100)
    cl.setxattr("os", "alpha", "k", b"v")
    osd = next(iter(c.osds.values()))
    path = str(tmp_path / "osd.store")
    osd.store.save(path)
    return path


def _run(argv, capsys):
    rc = ot.main(argv)
    return rc, capsys.readouterr().out


def test_list_and_info(saved_store, capsys):
    rc, out = _run(["--data-path", saved_store, "--op", "list"], capsys)
    assert rc == 0
    rows = [json.loads(ln) for ln in out.splitlines()]
    assert any(r["oid"] == "alpha" for r in rows)
    rc, out = _run(["--data-path", saved_store, "--op", "info"], capsys)
    assert rc == 0
    info = json.loads(out)
    assert info["objects"] >= 1 and info["collections"] >= 1


def test_get_bytes_attrs_remove(saved_store, capsys, tmp_path):
    rows = []
    rc, out = _run(["--data-path", saved_store, "--op", "list"], capsys)
    rows = [json.loads(ln) for ln in out.splitlines()
            if json.loads(ln)["oid"] == "alpha"]
    r = rows[0]
    outf = str(tmp_path / "bytes.bin")
    rc, _ = _run(["--data-path", saved_store, "--op", "get-bytes",
                  "--cid", r["cid"], "--oid", "alpha",
                  "--shard", str(r["shard"]), "--out", outf], capsys)
    assert rc == 0 and os.path.getsize(outf) == r["size"]
    rc, out = _run(["--data-path", saved_store, "--op", "list-attrs",
                    "--cid", r["cid"], "--oid", "alpha",
                    "--shard", str(r["shard"])], capsys)
    assert rc == 0 and "_u_k" in json.loads(out)
    rc, _ = _run(["--data-path", saved_store, "--op", "remove",
                  "--cid", r["cid"], "--oid", "alpha",
                  "--shard", str(r["shard"])], capsys)
    assert rc == 0
    rc, _ = _run(["--data-path", saved_store, "--op", "get-bytes",
                  "--cid", r["cid"], "--oid", "alpha",
                  "--shard", str(r["shard"])], capsys)
    assert rc == 1


def test_export_import(saved_store, capsys, tmp_path):
    rc, out = _run(["--data-path", saved_store, "--op", "list"], capsys)
    cid = json.loads(out.splitlines()[0])["cid"]
    exp = str(tmp_path / "coll.export")
    rc, _ = _run(["--data-path", saved_store, "--op", "export",
                  "--cid", cid, "--out", exp], capsys)
    assert rc == 0
    # import into a fresh empty store
    from ceph_tpu.os_store import MemStore
    empty = str(tmp_path / "empty.store")
    MemStore().save(empty)
    rc, _ = _run(["--data-path", empty, "--op", "import",
                  "--in", exp], capsys)
    assert rc == 0
    rc, out = _run(["--data-path", empty, "--op", "list"], capsys)
    assert any(json.loads(ln)["cid"] == cid for ln in out.splitlines())


# ---- compressor registry ---------------------------------------------------

def test_compressor_roundtrip_all_supported():
    payload = b"the quick brown fox " * 500
    for name in g_compressor_registry.supported():
        c = create_compressor(name)
        blob = c.compress(payload)
        assert c.decompress(blob) == payload
        if name not in ("none",):
            assert len(blob) < len(payload)


def test_compressor_unknown_name():
    with pytest.raises(KeyError):
        create_compressor("nope")


def test_tcp_frame_compression_roundtrip():
    """zlib-compressed frames flow between two TcpNetworks, including a
    mixed pair where only one side compresses (receiver decodes by the
    frame's algo id, not its own config)."""
    import socket
    from ceph_tpu.msg import messages as M
    from ceph_tpu.msg.tcp import TcpNetwork

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    pa, pb = free_port(), free_port()
    directory = {"a": ("127.0.0.1", pa), "b": ("127.0.0.1", pb)}
    na = TcpNetwork(("127.0.0.1", pa), directory, compression="zlib",
                    compress_min=16)
    nb = TcpNetwork(("127.0.0.1", pb), directory)   # uncompressed sender
    try:
        ma = na.create_messenger("a")
        mb = nb.create_messenger("b")
        got = []

        class Sink:
            def ms_fast_dispatch(self, m):
                got.append(m)

        mb.add_dispatcher_head(Sink())
        ma.add_dispatcher_head(Sink())
        big = b"x" * 4096
        ma.send_message(M.MOSDOp(tid=1, oid="o", data=big), "b")
        for _ in range(20):
            na.pump(deadline=0.3)
            nb.pump(deadline=0.3)
            if got:
                break
        assert got and got[0].data == big
        got.clear()
        mb.send_message(M.MOSDOpReply(tid=1, data=big), "a")
        for _ in range(20):
            nb.pump(deadline=0.3)
            na.pump(deadline=0.3)
            if got:
                break
        assert got and got[0].data == big
    finally:
        na.close()
        nb.close()


# ---- scrub scheduler -------------------------------------------------------

def test_periodic_scrub_detects_bitrot():
    """With a short osd_scrub_min_interval, ticking the cluster alone
    (no client read, no manual scrub call) finds and repairs at-rest
    corruption."""
    c = MiniCluster(n_osds=4)
    c.create_ec_pool("ss", k=2, m=1, plugin="isa", pg_num=4)
    cl = c.client("client.ss")
    data = bytes(range(256)) * 64
    cl.write_full("ss", "victim", data)
    # corrupt one stored shard at rest
    corrupted = False
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == "victim" and not corrupted:
                    from ceph_tpu.os_store import Transaction
                    t = Transaction()
                    t.write(cid, ho, 0, b"\xff\xfe\xfd")
                    osd.store.queue_transaction(t)
                    corrupted = True
    assert corrupted
    old = g_conf.get_val("osd_scrub_min_interval")
    old_deep = g_conf.get_val("osd_deep_scrub_interval")
    g_conf.set_val("osd_scrub_min_interval", 10.0)
    # same-size bitrot is only visible to data-checksumming scrubs, so
    # the deep interval must lapse within the ticks below
    g_conf.set_val("osd_deep_scrub_interval", 10.0)
    try:
        for _ in range(8):
            c.tick(dt=6.0)
        c.run_recovery()
        c.network.pump()
        c.run_recovery()
        c.network.pump()
    finally:
        g_conf.set_val("osd_scrub_min_interval", old)
        g_conf.set_val("osd_deep_scrub_interval", old_deep)
    # every stored copy of the shard is consistent again
    assert cl.read("ss", "victim") == data
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == "victim":
                    body = osd.store.read(cid, ho)
                    assert body[:3] != b"\xff\xfe\xfd"
