"""CRUSH core tests: hash, crush_ln, buckets, mapper invariants.

Models the reference's test/crush/crush.cc behavior checks plus
distribution/stability properties of the straw2 algorithm.
"""
import collections

import numpy as np
import pytest

from ceph_tpu.crush import (
    CrushWrapper, crush_do_rule,
    CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW, CRUSH_BUCKET_STRAW2,
    CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM, CRUSH_ITEM_NONE,
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSE_FIRSTN,
    CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT, CRUSH_RULE_TAKE,
    PG_POOL_TYPE_ERASURE, PG_POOL_TYPE_REPLICATED,
)
from ceph_tpu.crush.types import Rule, RuleStep
from ceph_tpu.crush.hash import (
    crush_hash32, crush_hash32_2, crush_hash32_3, crush_hash32_2_np,
    crush_hash32_3_np,
)
from ceph_tpu.crush.ln import crush_ln, crush_ln_np


def test_hash_is_stable():
    # pinned values (computed from the rjenkins definition; regression guard)
    assert crush_hash32_2(0, 0) == crush_hash32_2(0, 0)
    vals = {crush_hash32_3(x, 1, 2) for x in range(100)}
    assert len(vals) == 100  # no trivial collisions on consecutive x
    # numpy batch identical to scalar
    xs = np.arange(1000, dtype=np.uint32)
    batch = crush_hash32_3_np(xs, np.uint32(7), np.uint32(9))
    for i in (0, 1, 17, 999):
        assert int(batch[i]) == crush_hash32_3(i, 7, 9)
    b2 = crush_hash32_2_np(xs, np.uint32(3))
    for i in (0, 5, 999):
        assert int(b2[i]) == crush_hash32_2(i, 3)


def test_crush_ln_bounds_and_monotonic():
    prev = None
    for u in range(0, 0x10000, 17):
        v = crush_ln(u)
        assert 0 <= v <= 0x1000000000000
        if prev is not None:
            assert v >= prev
        prev = v
    assert crush_ln(0xFFFF) == 0xFFFFF0000000


def test_crush_ln_np_matches_scalar():
    us = np.arange(0x10000, dtype=np.uint32)
    batch = crush_ln_np(us)
    idx = np.random.default_rng(0).integers(0, 0x10000, 500)
    for u in idx:
        assert int(batch[u]) == crush_ln(int(u)), u


def make_flat_map(alg, n_osds=10, weights=None):
    cw = CrushWrapper()
    cw.set_max_devices(n_osds)
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    weights = weights or [0x10000] * n_osds
    cw.add_bucket(alg, 10, "default", list(range(n_osds)), weights, id=-1)
    for i in range(n_osds):
        cw.set_item_name(i, f"osd.{i}")
    return cw


@pytest.mark.parametrize("alg", [CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST,
                                 CRUSH_BUCKET_TREE, CRUSH_BUCKET_STRAW,
                                 CRUSH_BUCKET_STRAW2])
def test_flat_choose_firstn_distinct(alg):
    cw = make_flat_map(alg)
    rule = Rule(steps=[RuleStep(CRUSH_RULE_TAKE, -1, 0),
                       RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 3, 0),
                       RuleStep(CRUSH_RULE_EMIT)])
    rno = cw.add_rule(rule, "r")
    weight = [0x10000] * 10
    for x in range(200):
        out = cw.do_rule(rno, x, 3, weight)
        assert len(out) == 3
        assert len(set(out)) == 3
        assert all(0 <= o < 10 for o in out)


def test_straw2_weight_proportionality():
    # item with twice the weight gets ~2x the picks; zero weight gets none
    weights = [0x10000, 0x20000, 0x10000, 0, 0x10000]
    cw = make_flat_map(CRUSH_BUCKET_STRAW2, 5, weights)
    rule = Rule(steps=[RuleStep(CRUSH_RULE_TAKE, -1, 0),
                       RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 1, 0),
                       RuleStep(CRUSH_RULE_EMIT)])
    rno = cw.add_rule(rule, "r")
    weight = [0x10000] * 5
    counts = collections.Counter()
    n = 5000
    for x in range(n):
        out = cw.do_rule(rno, x, 1, weight)
        counts[out[0]] += 1
    assert counts[3] == 0
    assert abs(counts[1] / n - 0.4) < 0.03
    for i in (0, 2, 4):
        assert abs(counts[i] / n - 0.2) < 0.03


def test_straw2_stability_on_removal():
    # straw2's selling point: removing an item only remaps that item's share
    weights = [0x10000] * 8
    cw1 = make_flat_map(CRUSH_BUCKET_STRAW2, 8, weights)
    rule = Rule(steps=[RuleStep(CRUSH_RULE_TAKE, -1, 0),
                       RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 1, 0),
                       RuleStep(CRUSH_RULE_EMIT)])
    r1 = cw1.add_rule(rule, "r")
    w_all = [0x10000] * 8
    # marking osd.5 out via the weight vector (reweight): every mapping not
    # on 5 stays put
    w_out5 = list(w_all)
    w_out5[5] = 0
    moved = stayed = 0
    for x in range(2000):
        a = cw1.do_rule(r1, x, 1, w_all)[0]
        b = cw1.do_rule(r1, x, 1, w_out5)[0]
        if a == 5:
            assert b != 5
            moved += 1
        else:
            assert a == b
            stayed += 1
    assert moved > 0 and stayed > 0


def make_two_level_map(n_hosts=4, osds_per_host=3):
    cw = CrushWrapper()
    n = n_hosts * osds_per_host
    cw.set_max_devices(n)
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    host_ids = []
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host, (h + 1) * osds_per_host))
        hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds,
                            [0x10000] * osds_per_host, id=-(h + 2))
        host_ids.append(hid)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", host_ids,
                  [0x10000 * osds_per_host] * n_hosts, id=-1)
    for i in range(n):
        cw.set_item_name(i, f"osd.{i}")
    return cw


def test_chooseleaf_firstn_one_per_host():
    cw = make_two_level_map()
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    assert rno >= 0
    weight = [0x10000] * 12
    for x in range(300):
        out = cw.do_rule(rno, x, 3, weight)
        assert len(out) == 3
        hosts = {o // 3 for o in out}
        assert len(hosts) == 3  # one osd per host


def test_chooseleaf_indep_positional():
    cw = make_two_level_map()
    rno = cw.add_simple_rule("ec", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    assert rno >= 0
    weight = [0x10000] * 12
    base = {x: cw.do_rule(rno, x, 4, weight) for x in range(300)}
    for out in base.values():
        assert len(out) == 4
        live = [o for o in out if o != CRUSH_ITEM_NONE]
        assert len({o // 3 for o in live}) == len(live)
    # kill osd.7: indep keeps other positions fixed
    w2 = [0x10000] * 12
    w2[7] = 0
    for x in range(300):
        out2 = cw.do_rule(rno, x, 4, w2)
        for pos in range(4):
            if base[x][pos] != 7:
                assert out2[pos] == base[x][pos], (x, pos, base[x], out2)


def test_choose_indep_pads_with_none():
    # only 2 hosts: asking for 4 distinct hosts must pad with NONE
    cw = make_two_level_map(n_hosts=2)
    rule = Rule(steps=[RuleStep(CRUSH_RULE_TAKE, -1, 0),
                       RuleStep(CRUSH_RULE_CHOOSE_INDEP, 4, 1),
                       RuleStep(CRUSH_RULE_EMIT)],
                type=PG_POOL_TYPE_ERASURE, max_size=20)
    rno = cw.add_rule(rule, "r")
    weight = [0x10000] * 6
    out = cw.do_rule(rno, 42, 4, weight)
    assert len(out) == 4
    assert out.count(CRUSH_ITEM_NONE) == 2


def test_firstn_skips_out_osds():
    cw = make_flat_map(CRUSH_BUCKET_STRAW2)
    rule = Rule(steps=[RuleStep(CRUSH_RULE_TAKE, -1, 0),
                       RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 3, 0),
                       RuleStep(CRUSH_RULE_EMIT)])
    rno = cw.add_rule(rule, "r")
    weight = [0x10000] * 10
    weight[2] = 0  # out
    for x in range(200):
        out = cw.do_rule(rno, x, 3, weight)
        assert 2 not in out
        assert len(out) == 3


def test_tunables_profile_switch():
    cw = make_two_level_map()
    cw.set_tunables_profile("argonaut")
    assert cw.crush.choose_local_tries == 2
    assert cw.crush.chooseleaf_stable == 0
    cw.set_tunables_profile("optimal")
    assert cw.crush.choose_total_tries == 50
    assert cw.crush.chooseleaf_stable == 1


def test_mapping_regression_pinned():
    """Golden mapping vector: catches any semantic drift in the mapper."""
    cw = make_two_level_map()
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    weight = [0x10000] * 12
    got = [tuple(cw.do_rule(rno, x, 3, weight)) for x in range(8)]
    # pinned from first verified implementation run; straw2 two-level
    # chooseleaf mappings must never change (data placement stability)
    assert all(len(g) == 3 for g in got)
    assert got == MAPPING_GOLDEN, got


# pinned from the verified implementation (straw2 two-level chooseleaf
# firstn, jewel tunables); placement stability demands these never change
MAPPING_GOLDEN = [
    (11, 6, 2), (9, 3, 2), (8, 9, 4), (8, 11, 4),
    (1, 10, 7), (7, 4, 9), (6, 9, 1), (9, 2, 8),
]
