"""Wall-clock dmClock: real IOPS floors and ceilings.

The reference's mclock scheduler enforces (reservation, weight, limit)
against wall time via src/dmclock — a limit is a hard ops-per-real-
second ceiling and a reservation is a floor the class achieves under
load.  The deterministic virtual-clock arbiter (MClockQueue) decides
only ORDER; WallMClockQueue is the rate enforcer.  Deterministic tests
drive it with a fake clock; one timing test proves enforcement under
the real thread pool.
"""
from __future__ import annotations

import time

import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.common.work_queue import (
    CLASS_CLIENT, CLASS_RECOVERY, CLASS_SCRUB, ShardedOpWQ,
    ShardedThreadPool, WallMClockQueue,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_limit_is_a_hard_ceiling_over_any_window():
    """limit=100/s: no window of 1 fake second may serve more than
    ~101 ops (the t=0 op plus 100 credits), however hungry the
    drainer."""
    clk = FakeClock()
    q = WallMClockQueue(tags={CLASS_SCRUB: (0.0, 1.0, 100.0)},
                        clock=clk)
    for i in range(500):
        q.enqueue(CLASS_SCRUB, i)
    served = []
    # greedy drain loop: take everything the scheduler allows, advance
    # time only when told to wait
    while q and clk.t <= 1.0:
        item, nxt = q.dequeue()
        if item is not None:
            served.append((clk.t, item))
        else:
            assert nxt > clk.t
            clk.t = nxt
    assert len(served) <= 101
    assert len(served) >= 95            # and the credits ARE usable


def test_reservation_floor_under_competing_load():
    """client has 1000x recovery's weight, but recovery's 50/s floor
    must still be met in real time."""
    clk = FakeClock()
    q = WallMClockQueue(tags={
        CLASS_CLIENT: (0.0, 1000.0, 0.0),
        CLASS_RECOVERY: (50.0, 1.0, 0.0),
    }, clock=clk)
    for i in range(2000):
        q.enqueue(CLASS_CLIENT, ("c", i))
        q.enqueue(CLASS_RECOVERY, ("r", i))
    # a drainer with 1000 ops/s of capacity (1 ms per dequeue)
    got = {"c": 0, "r": 0}
    while clk.t < 1.0:
        item, _nxt = q.dequeue()
        if item is not None:
            got[item[0]] += 1
        clk.t += 0.001
    # recovery achieves its floor (50/s) but little more (weight 1 vs
    # 1000 hands the rest to clients)
    assert got["r"] >= 45
    assert got["r"] <= 80
    assert got["c"] >= 850


def test_idle_class_cannot_hoard_reservation_credit():
    """A class idle for 10 fake seconds must NOT burst 10s x res ops
    when it wakes (dmclock tag re-clamping)."""
    clk = FakeClock()
    q = WallMClockQueue(tags={
        CLASS_CLIENT: (0.0, 100.0, 0.0),
        CLASS_RECOVERY: (100.0, 1.0, 0.0),
    }, clock=clk)
    q.enqueue(CLASS_CLIENT, "warm")
    q.dequeue()
    clk.t = 10.0                         # recovery idle this whole time
    for i in range(2000):
        q.enqueue(CLASS_CLIENT, ("c", i))
        q.enqueue(CLASS_RECOVERY, ("r", i))
    got = {"c": 0, "r": 0}
    t_end = clk.t + 0.5
    while clk.t < t_end:
        item, _ = q.dequeue()
        if item is not None:
            got[item[0]] += 1
        clk.t += 0.001
    # 0.5 s at res=100/s -> ~50 reserved ops, NOT 1000+ banked ones
    assert got["r"] <= 70
    assert got["r"] >= 40


def test_no_starvation_after_idle_period():
    """A class with heavy past work must compete fairly when it
    reactivates against a class that was idle through that work: the
    weight clamp pins newcomers to the last served finish tag."""
    clk = FakeClock()
    q = WallMClockQueue(tags={
        CLASS_CLIENT: (0.0, 1.0, 0.0),
        CLASS_SCRUB: (0.0, 1.0, 0.0),
    }, clock=clk)
    for i in range(10000):                   # client works alone
        q.enqueue(CLASS_CLIENT, ("c", i))
    while len(q):
        q.dequeue()
        clk.t += 0.0001
    # full idle, then both classes return with equal weight
    got = {"c": 0, "s": 0}
    for i in range(1000):
        q.enqueue(CLASS_SCRUB, ("s", i))
        q.enqueue(CLASS_CLIENT, ("c", i))
    for _ in range(1000):
        item, _ = q.dequeue()
        if item is not None:
            got[item[0]] += 1
        clk.t += 0.001
    assert abs(got["c"] - got["s"]) <= 2, got


def test_flush_does_not_wait_out_the_rate_limiter():
    """flush() blocks for dispatchable work only: a big rate-blocked
    backlog must not stall (or TimeoutError) the flush boundary the
    op-dispatch path runs on."""
    wq = ShardedOpWQ(n_shards=1, wall=True, tags={
        CLASS_CLIENT: (0.0, 100.0, 0.0),
        CLASS_SCRUB: (0.0, 1.0, 10.0),       # 10/s ceiling
    })
    pool = ShardedThreadPool(wq, lambda it: None, n_threads=2)
    try:
        for i in range(600):                 # a minute of backlog
            wq.enqueue((1, 0), CLASS_SCRUB, i)
        t0 = time.monotonic()
        pool.flush(timeout=30.0)             # must NOT take ~60s
        assert time.monotonic() - t0 < 5.0
        assert len(wq) > 500                 # backlog still queued
    finally:
        pool.stop()


def test_wall_limit_enforced_under_real_thread_pool():
    """The threaded drain obeys the ceiling in actual wall time: 60
    limited ops at 100/s must take >= ~0.5 s; unlimited client ops
    drain orders of magnitude faster."""
    wq = ShardedOpWQ(n_shards=1, wall=True, tags={
        CLASS_CLIENT: (0.0, 100.0, 0.0),
        CLASS_SCRUB: (0.0, 1.0, 100.0),
    })
    stamps = []
    pool = ShardedThreadPool(wq, lambda it: stamps.append(
        (time.monotonic(), it)), n_threads=2)
    try:
        t0 = time.monotonic()
        for i in range(60):
            wq.enqueue((1, 0), CLASS_SCRUB, ("s", i))
        pool.kick()
        # flush() deliberately does NOT wait out the rate limiter
        # (rate-blocked ops are not "ready"), so wait for delivery
        end = time.monotonic() + 30.0
        while len(stamps) < 60 and time.monotonic() < end:
            time.sleep(0.01)
        elapsed = time.monotonic() - t0
        assert len(stamps) == 60
        # 59 credit intervals at 10 ms each, minus scheduling slop
        assert elapsed >= 0.45, f"ceiling not enforced: {elapsed:.3f}s"
        # sanity: unlimited class is not throttled by the machinery,
        # and flush() blocks for ready work exactly as before
        stamps.clear()
        t0 = time.monotonic()
        for i in range(200):
            wq.enqueue((1, 0), CLASS_CLIENT, ("c", i))
        pool.kick()
        pool.flush(timeout=30.0)
        assert time.monotonic() - t0 < 2.0
        assert len(stamps) == 200
    finally:
        pool.stop()


@pytest.fixture
def wall_conf():
    g_conf.set_val("osd_op_queue_mclock_wall", True)
    g_conf.set_val("osd_op_num_threads", 2)
    yield
    g_conf.set_val("osd_op_num_threads", 0)
    g_conf.set_val("osd_op_queue_mclock_wall", False)


@pytest.fixture
def wall_sync_conf():
    g_conf.set_val("osd_op_queue_mclock_wall", True)
    yield
    g_conf.set_val("osd_op_queue_mclock_wall", False)


def test_wall_mode_without_threads_drains_from_tick(wall_sync_conf):
    """The shipped-default combination (wall clock on, no worker
    threads): rate-blocked ops left behind by the synchronous drain
    are re-driven from the OSD tick, not stranded until the next
    client op arrives."""
    import numpy as np
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common.work_queue import CLASS_SCRUB
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client()
    data = np.random.default_rng(2).integers(
        0, 256, 8000, dtype=np.uint8).tobytes()
    assert cl.write_full("p", "obj", data) == 0
    assert cl.read("p", "obj") == data
    # strand rate-blocked ops with NO further client traffic
    osd = next(iter(c.osds.values()))
    handled = []
    orig = osd._wq_handle
    osd._wq_handle = lambda item: (
        handled.append(item) if item[0] == "noop"
        else orig(item))
    for sh in osd.op_wq.shards:
        sh.tags[CLASS_SCRUB] = (0.0, 1.0, 50.0)
    for i in range(10):
        osd.op_wq.shards[0].enqueue(CLASS_SCRUB, ("noop", i))
    deadline = time.time() + 10.0
    while len(handled) < 10 and time.time() < deadline:
        c.tick(dt=0.05)
        time.sleep(0.02)
    assert len(handled) == 10, f"tick never drained: {len(handled)}"


def test_cluster_runs_with_wall_mclock(wall_conf):
    """End-to-end: a cluster whose OSDs enforce wall-clock QoS still
    serves EC writes/reads correctly."""
    import numpy as np
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=5)
    c.create_ec_pool("p", k=3, m=2, pg_num=8)
    assert all(o.op_wq.wall for o in c.osds.values())
    cl = c.client()
    rng = np.random.default_rng(7)
    data = rng.integers(0, 256, 20000, dtype=np.uint8).tobytes()
    assert cl.write_full("p", "obj", data) == 0
    assert cl.read("p", "obj") == data
