"""Candidate-table fast mapper: exact parity with the host interpreter.

The fast path materializes a bounded number of retries on the device and
hands unresolved lanes to the host, so its *combined* output must equal
crush_do_rule bit for bit on every x — including heavily reweighted maps
that force many retries.
"""
import numpy as np
import pytest

from ceph_tpu.crush import CRUSH_ITEM_NONE
from ceph_tpu.crush.types import Rule, RuleStep
from ceph_tpu.crush.constants import (
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_TAKE, PG_POOL_TYPE_ERASURE,
)
from ceph_tpu.ops.crush_fast import UnsupportedRule, compile_fast_rule

from test_crush_device import build_map

N_X = 600


def assert_fast_parity(cw, rno, result_max, weight, n_x=N_X):
    fr = compile_fast_rule(cw.crush, rno, result_max)
    res, cnt = fr.map_batch(np.arange(n_x, dtype=np.uint32), weight)
    for x in range(n_x):
        expect = cw.do_rule(rno, x, result_max, weight)
        got = list(res[x, :cnt[x]])
        assert got == expect, (x, got, expect, fr.residual_fraction)
    return fr


def test_fast_chooseleaf_firstn():
    cw, n = build_map(n_hosts=8, osds_per_host=4, uneven=True)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    fr = assert_fast_parity(cw, rno, 3, [0x10000] * n)
    assert fr.residual_fraction < 0.05


def test_fast_firstn_heavy_reweight_forces_residuals():
    cw, n = build_map(n_hosts=5, osds_per_host=3)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    rng = np.random.default_rng(0)
    weight = [int(w) for w in rng.choice([0, 0x2000, 0x8000, 0x10000],
                                         size=n)]
    assert_fast_parity(cw, rno, 3, weight)


def test_fast_choose_firstn_flat():
    cw, n = build_map(n_hosts=4, osds_per_host=6)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 0, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=1,
                           min_size=1, max_size=10), "flat")
    weight = [0x10000] * n
    weight[2] = 0
    weight[9] = 0x5000
    assert_fast_parity(cw, rno, 3, weight)


@pytest.mark.slow   # ~25-40 s of XLA compile+replay on 1 core: the
# indep/exact64 heavyweights run in the slow tier so tier-1 fits its
# wall budget (they were enable_x64-broken in the seed; fixed in PR 1)
def test_fast_chooseleaf_indep():
    cw, n = build_map(n_hosts=9, osds_per_host=3, uneven=True)
    rno = cw.add_simple_rule("ec", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    cw.set_rule_mask_max_size(rno, 8)
    assert_fast_parity(cw, rno, 6, [0x10000] * n)


@pytest.mark.slow   # exact64 indep compile heavyweight (~20 s on 1 core)
def test_fast_indep_with_down_outs():
    cw, n = build_map(n_hosts=6, osds_per_host=2)
    rno = cw.add_simple_rule("ec", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    weight = [0x10000] * n
    weight[0] = weight[3] = weight[8] = 0
    assert_fast_parity(cw, rno, 5, weight)


def test_fast_choose_indep_flat():
    cw, n = build_map(n_hosts=3, osds_per_host=5)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_INDEP, 0, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=3,
                           min_size=1, max_size=20), "flatec")
    weight = [0x10000] * n
    weight[4] = 0
    assert_fast_parity(cw, rno, 4, weight)


def test_fast_three_level_hierarchy():
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(2, "rack")
    cw.set_type_name(10, "root")
    osd = 0
    rack_ids = []
    bid = -2
    for rk in range(3):
        host_ids = []
        for h in range(3):
            osds = list(range(osd, osd + 3))
            osd += 3
            hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1,
                                f"host{rk}-{h}", osds, [0x10000] * 3, id=bid)
            bid -= 1
            host_ids.append(hid)
        rid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 2, f"rack{rk}", host_ids,
                            [0x30000] * 3, id=bid)
        bid -= 1
        rack_ids.append(rid)
    cw.set_max_devices(osd)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", rack_ids,
                  [0x90000] * 3, id=-1)
    rno = cw.add_simple_rule("data", "default", "rack", mode="firstn")
    assert_fast_parity(cw, rno, 3, [0x10000] * osd, n_x=300)


def test_fast_pathological_weight_dynamic_range():
    """Adversarial f32-guard stress (VERDICT weak #5): bucket item weights
    spanning the full 16.16 range (0x1 .. 0x7fffffff) make G*invw spacing
    collapse, so near-ties must be *flagged* (then replayed exactly), never
    silently mis-ordered.  Parity against the exact interpreter is the
    whole assertion."""
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    rng = np.random.default_rng(42)
    extremes = [0x1, 0x2, 0x7fffffff, 0x7ffffffe, 0x10000, 0x10001,
                0xffff, 0x40000000, 0x3, 0x20000000]
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts = []
    osd = 0
    for h in range(6):
        osds = list(range(osd, osd + 4))
        osd += 4
        ws = [int(extremes[(h * 4 + i) % len(extremes)]) for i in range(4)]
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"h{h}",
                                   osds, ws, id=-(h + 2)))
    cw.set_max_devices(osd)
    # host weights also pathological
    hws = [0x1, 0x7fffffff, 0x10000, 0x2, 0x40000000, 0x7ffffffe]
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts, hws, id=-1)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    assert_fast_parity(cw, rno, 3, [0x10000] * osd, n_x=400)


def test_fast_near_tie_storm_huge_weights():
    """Near-maximal, slightly distinct bucket item weights force the
    non-uniform path in the coarse-quotient regime: floor(G/w) has only
    ~2^17 distinct values, so draws tie constantly and the reference
    breaks them by item index.

    The default exact64 draw must get every tie right on device with
    ZERO residual replays (first-index argmin == strict-greater
    update); the f32 fallback must flag every such lane via TIE_PAD
    for exact replay.  Parity is the assertion for both."""
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    hosts, osd = [], 0
    for h in range(12):
        osds = list(range(osd, osd + 2))
        osd += 2
        ws = [0x7fffffff - h, 0x7ffffffe - h]   # huge, non-uniform
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"h{h}",
                                   osds, ws, id=-(h + 2)))
    cw.set_max_devices(osd)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x7fffffff - h for h in range(12)], id=-1)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    weight = [0x10000] * osd
    expect = [cw.do_rule(rno, x, 3, weight) for x in range(500)]
    # exact64 (default): device-exact, no replays even in a tie storm
    fr = compile_fast_rule(cw.crush, rno, 3)
    assert not any(fr.integer_exact_levels), \
        "non-uniform weights must not take the quotient-table path"
    res, cnt = fr.map_batch(np.arange(500, dtype=np.uint32), weight)
    assert fr.residual_fraction == 0.0
    for x in range(500):
        assert list(res[x, :cnt[x]]) == expect[x], x
    # f32 fallback: ties flagged for replay, combined result exact
    fr32 = compile_fast_rule(cw.crush, rno, 3, exact64=False)
    res, cnt = fr32.map_batch(np.arange(500, dtype=np.uint32), weight)
    assert fr32.residual_fraction > 0  # ties were actually flagged
    for x in range(500):
        assert list(res[x, :cnt[x]]) == expect[x], x


def test_fast_choose_args_disable_integer_path():
    """choose_args weight-set overrides must disable the quotient-table
    draw even with a single position (npos==1) — the tables are built
    from raw item weights and would silently diverge."""
    from ceph_tpu.crush.types import ChooseArg, WeightSet
    cw, n = build_map(n_hosts=6, osds_per_host=3)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    nb = len(cw.crush.buckets)
    args = [None] * nb
    # override one host bucket's weights with a single-position set
    for bi, b in enumerate(cw.crush.buckets):
        if b is not None and b.type == 1:
            args[bi] = ChooseArg(
                ids=None,
                weight_set=[WeightSet(weights=[0x8000] * b.size)])
            break
    fr = compile_fast_rule(cw.crush, rno, 3, choose_args=args)
    assert not any(fr.integer_exact_levels)
    weight = [0x10000] * n
    res, cnt = fr.map_batch(np.arange(400, dtype=np.uint32), weight)
    from ceph_tpu.crush.mapper import crush_do_rule
    for x in range(400):
        expect = crush_do_rule(cw.crush, rno, x, 3, weight, args)
        assert list(res[x, :cnt[x]]) == expect, x


def test_fast_residuals_route_through_native():
    """The exactness escape hatch should use the C++ batch evaluator when
    available (the serial-Python tail was the <50 ms risk, VERDICT #6)."""
    from ceph_tpu.native import native_available
    if not native_available():
        pytest.skip("native lib unavailable")
    cw, n = build_map(n_hosts=5, osds_per_host=3)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    rng = np.random.default_rng(1)
    weight = [int(w) for w in rng.choice([0, 0x2000, 0x10000], size=n)]
    fr = assert_fast_parity(cw, rno, 3, weight)
    # heavy reweighting forces unresolved lanes -> the native mapper
    # object must have been instantiated (and parity held above)
    if fr.residual_fraction > 0:
        assert getattr(fr, "_nm", None) is not None


def chained_rule(cw, mode, n1=2, n2=2, mid_type=1, leaf=False):
    from ceph_tpu.crush.constants import (
        CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    )
    first = mode == "firstn"
    op1 = CRUSH_RULE_CHOOSE_FIRSTN if first else CRUSH_RULE_CHOOSE_INDEP
    if leaf:
        op2 = CRUSH_RULE_CHOOSELEAF_FIRSTN if first \
            else CRUSH_RULE_CHOOSELEAF_INDEP
        t2 = 1
    else:
        op2 = op1
        t2 = 0
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(op1, n1, mid_type),
             RuleStep(op2, n2, t2),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    return cw.add_rule(Rule(steps=steps, ruleset=1, type=1,
                            min_size=1, max_size=10), f"chain-{mode}")


@pytest.mark.parametrize("mode", ["firstn", "indep"])
def test_fast_chained_choose(mode):
    """take root; choose <mode> 2 type host; choose <mode> 2 type 0;
    emit — the set-choose.t chained shape, exact vs the interpreter
    under healthy, non-uniform, and zeroed weight vectors."""
    cw, n = build_map(n_hosts=6, osds_per_host=4, uneven=True)
    rno = chained_rule(cw, mode)
    rng = np.random.default_rng(3)
    for weight in ([0x10000] * n,
                   [int(w) for w in rng.choice(
                       [0, 0x4000, 0x8000, 0x10000], size=n)]):
        assert_fast_parity(cw, rno, 4, weight)


def test_fast_chained_chooseleaf_three_levels():
    """3-level hierarchy: choose firstn 2 type rack; chooseleaf firstn 2
    type host; emit."""
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(2, "rack")
    cw.set_type_name(10, "root")
    rng = np.random.default_rng(11)
    osd = 0
    racks = []
    bid = -2
    for r in range(3):
        hosts = []
        for h in range(3):
            osds = list(range(osd, osd + 3))
            osd += 3
            ws = [int(rng.integers(1, 4)) * 0x10000 for _ in osds]
            hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1,
                                       f"h{r}{h}", osds, ws, id=bid))
            bid -= 1
        rws = [0x30000] * len(hosts)
        racks.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 2, f"rack{r}",
                                   hosts, rws, id=bid))
        bid -= 1
    cw.set_max_devices(osd)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", racks,
                  [0x90000] * len(racks), id=-1)
    rno = chained_rule(cw, "firstn", n1=2, n2=2, mid_type=2, leaf=True)
    weight = [0x10000] * osd
    weight[4] = 0
    weight[11] = 0x6000
    assert_fast_parity(cw, rno, 4, weight)


def test_fast_chained_numrep_zero_expands():
    """arg1=0 on the first step means result_max parents."""
    cw, n = build_map(n_hosts=5, osds_per_host=3)
    rno = chained_rule(cw, "firstn", n1=0, n2=1)
    weight = [0x10000] * n
    weight[1] = 0
    assert_fast_parity(cw, rno, 3, weight)


def test_fast_delta_epochs_stay_exact():
    """The per-epoch delta fetch must equal a from-scratch exact map for
    every epoch: weights flap up/down, residual lanes appear/disappear,
    and a tiny delta_cap forces the overflow -> full-fetch path too."""
    cw, n = build_map(n_hosts=6, osds_per_host=4, uneven=True)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    fr = compile_fast_rule(cw.crush, rno, 3)
    fr.delta_cap = 8  # force overflow on big epochs
    xs = np.arange(N_X, dtype=np.uint32)
    rng = np.random.default_rng(42)
    weight = np.full(n, 0x10000, dtype=np.uint32)
    for epoch in range(8):
        if epoch:
            if epoch % 3 == 0:
                # big epoch: heavy random reweight (overflows the cap)
                weight = rng.choice(
                    [0, 0x2000, 0x8000, 0x10000], size=n).astype(np.uint32)
            else:
                # small epoch: one osd flaps
                weight = weight.copy()
                weight[(5 * epoch) % n] ^= 0x10000
        res, cnt = fr.map_batch(xs, weight)
        wl = [int(w) for w in weight]
        for x in range(0, N_X, 7):
            expect = cw.do_rule(rno, int(x), 3, wl)
            got = list(res[x, :cnt[x]])
            assert got == expect, (epoch, x, got, expect)


def test_fast_delta_indep_epochs_stay_exact():
    cw, n = build_map(n_hosts=7, osds_per_host=3)
    rno = cw.add_simple_rule("data", "default", "host", mode="indep")
    fr = compile_fast_rule(cw.crush, rno, 3)
    xs = np.arange(300, dtype=np.uint32)
    weight = np.full(n, 0x10000, dtype=np.uint32)
    for epoch in range(4):
        if epoch:
            weight = weight.copy()
            weight[(3 * epoch + 1) % n] ^= 0x10000
        res, cnt = fr.map_batch(xs, weight)
        wl = [int(w) for w in weight]
        for x in range(0, 300, 11):
            expect = cw.do_rule(rno, int(x), 3, wl)
            got = [int(v) for v in res[x, :cnt[x]]]
            assert got == expect, (epoch, x, got, expect)


def test_fast_chained_indep_room_truncation():
    """result_max not a multiple of the last step's numrep: the
    reference truncates the straddling parent's block (out_size =
    result_max - osize), so retries must never collide with slots the
    reference never fills."""
    cw, n = build_map(n_hosts=4, osds_per_host=3)
    rno = chained_rule(cw, "indep", n1=2, n2=2)
    rng = np.random.default_rng(9)
    for trial in range(3):
        weight = [int(w) for w in rng.choice(
            [0, 0x6000, 0x10000], size=n, p=[0.25, 0.25, 0.5])]
        assert_fast_parity(cw, rno, 3, weight, n_x=1024)
