"""Candidate-table fast mapper: exact parity with the host interpreter.

The fast path materializes a bounded number of retries on the device and
hands unresolved lanes to the host, so its *combined* output must equal
crush_do_rule bit for bit on every x — including heavily reweighted maps
that force many retries.
"""
import numpy as np
import pytest

from ceph_tpu.crush import CRUSH_ITEM_NONE
from ceph_tpu.crush.types import Rule, RuleStep
from ceph_tpu.crush.constants import (
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_TAKE, PG_POOL_TYPE_ERASURE,
)
from ceph_tpu.ops.crush_fast import UnsupportedRule, compile_fast_rule

from test_crush_device import build_map

N_X = 600


def assert_fast_parity(cw, rno, result_max, weight, n_x=N_X):
    fr = compile_fast_rule(cw.crush, rno, result_max)
    res, cnt = fr.map_batch(np.arange(n_x, dtype=np.uint32), weight)
    for x in range(n_x):
        expect = cw.do_rule(rno, x, result_max, weight)
        got = list(res[x, :cnt[x]])
        assert got == expect, (x, got, expect, fr.residual_fraction)
    return fr


def test_fast_chooseleaf_firstn():
    cw, n = build_map(n_hosts=8, osds_per_host=4, uneven=True)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    fr = assert_fast_parity(cw, rno, 3, [0x10000] * n)
    assert fr.residual_fraction < 0.05


def test_fast_firstn_heavy_reweight_forces_residuals():
    cw, n = build_map(n_hosts=5, osds_per_host=3)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    rng = np.random.default_rng(0)
    weight = [int(w) for w in rng.choice([0, 0x2000, 0x8000, 0x10000],
                                         size=n)]
    assert_fast_parity(cw, rno, 3, weight)


def test_fast_choose_firstn_flat():
    cw, n = build_map(n_hosts=4, osds_per_host=6)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 0, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=1,
                           min_size=1, max_size=10), "flat")
    weight = [0x10000] * n
    weight[2] = 0
    weight[9] = 0x5000
    assert_fast_parity(cw, rno, 3, weight)


def test_fast_chooseleaf_indep():
    cw, n = build_map(n_hosts=9, osds_per_host=3, uneven=True)
    rno = cw.add_simple_rule("ec", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    cw.set_rule_mask_max_size(rno, 8)
    assert_fast_parity(cw, rno, 6, [0x10000] * n)


def test_fast_indep_with_down_outs():
    cw, n = build_map(n_hosts=6, osds_per_host=2)
    rno = cw.add_simple_rule("ec", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    weight = [0x10000] * n
    weight[0] = weight[3] = weight[8] = 0
    assert_fast_parity(cw, rno, 5, weight)


def test_fast_choose_indep_flat():
    cw, n = build_map(n_hosts=3, osds_per_host=5)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_INDEP, 0, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=3,
                           min_size=1, max_size=20), "flatec")
    weight = [0x10000] * n
    weight[4] = 0
    assert_fast_parity(cw, rno, 4, weight)


def test_fast_three_level_hierarchy():
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(2, "rack")
    cw.set_type_name(10, "root")
    osd = 0
    rack_ids = []
    bid = -2
    for rk in range(3):
        host_ids = []
        for h in range(3):
            osds = list(range(osd, osd + 3))
            osd += 3
            hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1,
                                f"host{rk}-{h}", osds, [0x10000] * 3, id=bid)
            bid -= 1
            host_ids.append(hid)
        rid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 2, f"rack{rk}", host_ids,
                            [0x30000] * 3, id=bid)
        bid -= 1
        rack_ids.append(rid)
    cw.set_max_devices(osd)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", rack_ids,
                  [0x90000] * 3, id=-1)
    rno = cw.add_simple_rule("data", "default", "rack", mode="firstn")
    assert_fast_parity(cw, rno, 3, [0x10000] * osd, n_x=300)


def test_fast_rejects_chained_rules():
    cw, n = build_map()
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 2, 1),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 2, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=1,
                           min_size=1, max_size=10), "chain")
    with pytest.raises(UnsupportedRule):
        compile_fast_rule(cw.crush, rno, 4)
