"""Actuator liveness (control-plane satellite): every knob the
controller drives must take effect on live config change — injectargs
semantics, NO daemon restart.  One test per actuator, each flipping
the option mid-flight and asserting the consuming path re-reads it.

The one gap this PR closed: the class-tier mClock tags
(CLASS_RECOVERY's weight among them) were frozen at queue
construction; ``osd_mclock_class_overrides`` now overlays them live
(work_queue._LiveClassTags).
"""
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.common.work_queue import (CLASS_CLIENT, CLASS_RECOVERY,
                                        DEFAULT_TAGS, MClockQueue,
                                        WallMClockQueue)


@pytest.fixture(autouse=True)
def _restore_options():
    opts = ("osd_mclock_class_overrides", "osd_mclock_client_overrides",
            "osd_op_queue_admission_max", "osd_op_queue_batch_intake",
            "ec_dispatch_batch_window_us", "osd_recovery_max_active",
            "ec_mesh_rateless_tasks", "ec_mesh_rateless",
            "osd_mclock_client_weight")
    saved = {n: g_conf.get_val(n) for n in opts}
    yield
    for n, v in saved.items():
        g_conf.set_val(n, v)


def test_mclock_class_tags_live_virtual_queue():
    """osd_mclock_class_overrides re-weights a CONSTRUCTED
    MClockQueue: the recovery class's tags change between two
    dequeues of the same queue instance."""
    q = MClockQueue()
    q.enqueue(CLASS_CLIENT, ("op", "c1"), client="client.a")
    q.enqueue(CLASS_RECOVERY, ("op", "r1"))
    q.enqueue(CLASS_RECOVERY, ("op", "r2"))
    assert q.tags[CLASS_RECOVERY] == DEFAULT_TAGS[CLASS_RECOVERY]
    g_conf.set_checked("osd_mclock_class_overrides",
                       "recovery:0:1:50")
    q.dequeue()
    assert q.tags[CLASS_RECOVERY] == (0.0, 1.0, 50.0)
    # removal restores the constructor base on the next arbitration
    g_conf.rm_val("osd_mclock_class_overrides")
    q.dequeue()
    assert q.tags[CLASS_RECOVERY] == DEFAULT_TAGS[CLASS_RECOVERY]
    # malformed entries and unknown classes fall through to base
    g_conf.set_val("osd_mclock_class_overrides",
                   "recovery:nope:1:1,ghostclass:1:1:1")
    q.dequeue()
    assert q.tags[CLASS_RECOVERY] == DEFAULT_TAGS[CLASS_RECOVERY]
    assert "ghostclass" not in q.tags


def test_mclock_class_tags_live_wall_queue():
    """The wall-clock dmClock enforcer honors the same overlay: a
    limit injected mid-run rate-blocks the class immediately."""
    q = WallMClockQueue(clock=lambda: 0.0)
    for i in range(4):
        q.enqueue(CLASS_CLIENT, ("op", i), client="client.w")
    # client class: no reservation/limit by default -> free dequeues
    item, _ = q.dequeue(now=1.0)
    assert item is not None
    g_conf.set_checked("osd_mclock_class_overrides",
                       "client:0:500:1")   # 1 op/s hard limit
    item, _ = q.dequeue(now=1.001)
    assert item is not None                # first limited slot
    item, nxt = q.dequeue(now=1.002)
    assert item is None and nxt > 1.002    # rate-blocked LIVE
    item, _ = q.dequeue(now=3.0)
    assert item is not None                # credit accrued


def test_admission_max_live(monkeypatch):
    """osd_op_queue_admission_max is read per intake (osd._admit_op):
    lowering it over a standing queue sheds the NEXT client op, and
    raising it re-admits — no OSD restart."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.msg.messages import MOSDOp
    c = MiniCluster(n_osds=1)
    c.create_replicated_pool("adm", size=1, pg_num=1)
    osd = c.osds[0]
    # park items in the op queue so depth is visible to admission
    for i in range(4):
        osd.op_wq.enqueue((0, 0), CLASS_CLIENT, ("noop", i),
                          client="client.adm")
    msg = MOSDOp(src="client.adm", tid=99, pool=0, oid="o",
                 pgid=(0, 0))
    sent = []
    monkeypatch.setattr(osd.messenger, "send_message",
                        lambda m, *a, **k: sent.append(m))
    assert osd._admit_op(msg) is True      # default 0 = disabled
    g_conf.set_checked("osd_op_queue_admission_max", 2)
    assert osd._admit_op(msg) is False     # depth 4 >= 2: shed, live
    assert sent and sent[-1].result != 0
    g_conf.set_checked("osd_op_queue_admission_max", 4096)
    # back under the cap AND under the depth-hysteresis low water, so
    # the throttle window clears too
    assert osd._admit_op(msg) is True


def test_dispatch_batch_window_live():
    """ec_dispatch_batch_window_us reaches DeviceDispatcher._opts on
    every call — the coalescing window follows injectargs."""
    from ceph_tpu.dispatch.scheduler import DeviceDispatcher
    g_conf.set_val("ec_dispatch_batch_window_us", 0)
    assert DeviceDispatcher._opts()[1] == 0
    g_conf.set_checked("ec_dispatch_batch_window_us", 250_000)
    assert DeviceDispatcher._opts()[1] == 250_000


def test_recovery_max_active_live():
    """osd_recovery_max_active reaches RecoveryScheduler._opts on
    every pacing decision — the controller's storm throttle is live."""
    from ceph_tpu.recovery.scheduler import RecoveryScheduler
    g_conf.set_checked("osd_recovery_max_active", 2)
    assert RecoveryScheduler._opts()[1] == 2
    g_conf.set_checked("osd_recovery_max_active", 16)
    assert RecoveryScheduler._opts()[1] == 16


def test_rateless_tasks_live():
    """ec_mesh_rateless_tasks is read per flush plan (rateless_opts)
    — widening the coded-task count needs no restart."""
    from ceph_tpu.mesh.rateless import rateless_opts
    g_conf.set_checked("ec_mesh_rateless", True)
    g_conf.set_checked("ec_mesh_rateless_tasks", 11)
    assert rateless_opts() == (True, 11)
    g_conf.set_checked("ec_mesh_rateless_tasks", 13)
    assert rateless_opts() == (True, 13)


def test_mclock_client_overrides_live():
    """osd_mclock_client_* overrides re-resolve on the next
    arbitration of a LIVE per-client lane (the cached-source idiom:
    a changed string drops the resolved cache)."""
    from ceph_tpu.common.work_queue import ClientDmClock
    lane = ClientDmClock()
    lane.push("client.a", ("op", 1))
    lane.push("client.b", ("op", 2))
    assert lane._tags_for("client.a")[1] == float(
        g_conf.get_val("osd_mclock_client_weight"))
    g_conf.set_checked("osd_mclock_client_overrides",
                       "client.a:0:0.125:0")
    lane.pop()                             # one arbitration refresh
    assert lane._tags_for("client.a")[1] == 0.125
    g_conf.set_checked("osd_mclock_client_weight", 7.0)
    lane.pop()
    assert lane._tags_for("client.b")[1] == 7.0
