"""mgr HTTP frontends: the prometheus /metrics endpoint and the
restful-module JSON read surface, both through handle() and over a
real socket."""
import http.client
import json

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.mgr.http import MgrHttp, serve


@pytest.fixture()
def fe():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("data", pg_num=8)
    c.create_ec_pool("ec", k=2, m=1, pg_num=8)
    return c, MgrHttp(c.mgr, cluster=c,
                      perf_collection=c.perf_collection)


def test_routes(fe):
    c, f = fe
    st, hdrs, body = f.handle("GET", "/metrics")
    assert st == 200 and b"ceph_osdmap_epoch" in body \
        and b"ceph_osd_up 4" in body
    # the telemetry cluster-rollup families ride the HTTP scrape too
    # (same rollup snapshot the admin-socket exposition renders)
    assert b"ceph_cluster_rate_ops" in body
    assert b"# TYPE ceph_cluster_oplat_p99_usec gauge" in body

    st, _, body = f.handle("GET", "/health")
    doc = json.loads(body)
    assert doc["health"].startswith("HEALTH")

    st, _, body = f.handle("GET", "/osd")
    osds = json.loads(body)
    assert len(osds) == 4 and all(o["up"] == 1 for o in osds)
    st, _, body = f.handle("GET", "/osd/2")
    assert json.loads(body)["osd"] == 2
    assert f.handle("GET", "/osd/99")[0] == 404
    assert f.handle("GET", "/osd/abc")[0] == 400

    st, _, body = f.handle("GET", "/pool")
    pools = json.loads(body)
    names = {p["pool_name"]: p for p in pools}
    assert names["data"]["type"] == "replicated"
    assert names["ec"]["type"] == "erasure"
    pid = names["ec"]["pool"]
    st, _, body = f.handle("GET", f"/pool/{pid}")
    assert json.loads(body)["pool_name"] == "ec"

    st, _, body = f.handle("GET", "/pg")
    doc = json.loads(body)
    assert doc["num_pgs"] == 16 and doc["pg_states"]

    st, _, body = f.handle("GET", "/crush/rule")
    rules = json.loads(body)
    assert any(r["rule_name"] for r in rules)

    st, _, body = f.handle("GET", "/mon")
    assert json.loads(body)[0]["name"]

    # perf counters flow through /metrics via the collection
    c.client("client.t").write_full("data", "o", b"x" * 64)
    _, _, body = f.handle("GET", "/metrics")
    assert b"ceph_daemon_" in body

    # the balancer history surfaces on /request
    c.mgr.balancer_optimize()
    st, _, body = f.handle("GET", "/request")
    log = json.loads(body)
    assert st == 200 and log and log[-1]["mode"] == "upmap"

    assert f.handle("GET", "/nope")[0] == 404
    assert f.handle("GET", "/osd/2/garbage")[0] == 404
    assert f.handle("GET", "/mon/extra")[0] == 404
    assert f.handle("POST", "/osd")[0] == 405


def test_osd_state_reflected(fe):
    c, f = fe
    c.mark_osd_out(1)
    doc = json.loads(f.handle("GET", "/osd/1")[2])
    assert doc["in"] == 0 and doc["up"] == 1


def test_over_socket(fe):
    c, f = fe
    srv, port = serve(f)
    try:
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=20)
        conn.request("GET", "/metrics")
        r = conn.getresponse()
        assert r.status == 200 and b"ceph_pools" in r.read()
        conn.request("GET", "/pool")
        r = conn.getresponse()
        assert r.status == 200 and len(json.loads(r.read())) == 2
        conn.close()
    finally:
        srv.shutdown()
        srv.server_close()
