"""A minimal cram(1) interpreter for replaying the reference's
recorded CLI transcripts (src/test/cli/*/*.t) byte-exact.

Cram format: 2-space-indented ``$ cmd`` lines (with ``> ``
continuations) followed by 2-space-indented expected output; a
trailing ``[N]`` line pins the exit status.  Expected lines may end
with `` (re)`` (regex fullmatch) or `` (esc)`` (escaped literals).
All commands of one file share a single bash session (env vars and
``$(...)`` captures persist), exactly like cram runs them; our CLIs
are exposed as PATH shims.
"""
from __future__ import annotations

import os
import re
import subprocess
import sys
from typing import Dict, List, Optional, Tuple

SALT = "===CRAM-73a1==="

TOOLS = {
    "monmaptool": "ceph_tpu.tools.monmaptool",
    "ceph-authtool": "ceph_tpu.tools.authtool",
    "crushtool": "ceph_tpu.tools.crushtool",
    "osdmaptool": "ceph_tpu.tools.osdmaptool",
    "rbd": "ceph_tpu.tools.rbd_shell",
    "radosgw-admin": "ceph_tpu.tools.rgw_admin",
    "ceph-conf": "ceph_tpu.tools.ceph_conf",
    "ceph-kvstore-tool": "ceph_tpu.tools.kvstore_tool",
    "ceph": "ceph_tpu.tools.ceph_cli",
}


class Command:
    def __init__(self, text: str):
        self.text = text
        self.expected: List[str] = []
        self.exit_code = 0


def parse(path: str) -> List[Command]:
    cmds: List[Command] = []
    cur: Optional[Command] = None
    text = open(path).read()
    # two dialects in the reference tree: standard cram (2-space
    # indent) and the column-0 form some crushtool files use
    indent = "  " if re.search(r"^  \$ ", text, re.M) else ""
    n = len(indent)
    for raw in text.splitlines():
        if raw.startswith(indent + "$ "):
            cur = Command(raw[n + 2:])
            cmds.append(cur)
        elif raw.startswith(indent + "> ") and cur is not None:
            cur.text += "\n" + raw[n + 2:]
        elif not indent and (not raw or raw.startswith("#")):
            cur = None          # column-0 dialect: comment/blank ends
        elif raw.startswith(indent) and cur is not None and \
                (indent or raw):
            line = raw[n:]
            m = re.fullmatch(r"\[(\d+)\]", line)
            if m:
                # an exit-status line always terminates the block
                cur.exit_code = int(m.group(1))
                cur = None
            else:
                cur.expected.append(line)
        else:
            cur = None          # comment / blank: block over
    return cmds


def _escape(s: str) -> str:
    return s.encode("unicode_escape").decode("ascii")


def _line_matches(expected: str, actual: str) -> bool:
    if expected == actual:
        return True
    if expected.endswith(" (esc)"):
        want = bytes(expected[:-len(" (esc)")],
                     "latin1").decode("unicode_escape")
        return want == actual
    if expected.endswith(" (re)"):
        pat = expected[:-len(" (re)")]
        try:
            if re.fullmatch(pat, actual):
                return True
            # cram matches escaped output forms too ("\tkey = ... (esc)")
            return re.fullmatch(pat, _escape(actual) + " (esc)") \
                is not None
        except re.error:
            return False
    return False


def run(path: str, tmpdir: str,
        env_extra: Optional[Dict[str, str]] = None
        ) -> List[Tuple[Command, int, List[str], str]]:
    """Replay a .t file; returns a list of mismatches
    (command, actual_exit, actual_lines, why)."""
    shimdir = os.path.join(tmpdir, "_shims")
    os.makedirs(shimdir, exist_ok=True)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for tool, mod in TOOLS.items():
        shim = os.path.join(shimdir, tool)
        with open(shim, "w") as f:
            f.write(f"""#!/bin/bash
exec {sys.executable} -m {mod} "$@"
""")
        os.chmod(shim, 0o755)
    import shutil
    if shutil.which("jq") is None:
        # choose-args.t validates --dump JSON through `jq .key`; the
        # image has no jq, so provide the one filter shape it uses
        jq = os.path.join(shimdir, "jq")
        with open(jq, "w") as f:
            f.write(f"""#!{sys.executable}
import json, sys
filt = sys.argv[1]
doc = json.load(sys.stdin)
for part in filt.lstrip(".").split("."):
    if not part:
        continue
    doc = doc.get(part) if isinstance(doc, dict) else None
print(json.dumps(doc, indent=2) if doc is not None else "null")
""")
        os.chmod(jq, 0o755)
    cmds = parse(path)
    script = ["set +e", "exec 2>&1", f"cd {tmpdir}",
              f'export PATH="{shimdir}:$PATH"',
              f'export PYTHONPATH="{repo}"',
              "export JAX_PLATFORMS=cpu",
              # cram exports the .t file's directory as TESTDIR
              f'export TESTDIR="{os.path.dirname(os.path.abspath(path))}"']
    for i, c in enumerate(cmds):
        script.append(c.text)
        script.append(f'echo "{SALT} {i} $?"')
    proc = subprocess.run(["bash", "-c", "\n".join(script)],
                          capture_output=True, text=True,
                          env={**os.environ, **(env_extra or {})},
                          timeout=2400)
    out = proc.stdout
    blocks: Dict[int, Tuple[List[str], int]] = {}
    curlines: List[str] = []
    for line in out.splitlines():
        m = re.fullmatch(rf"{re.escape(SALT)} (\d+) (\d+)", line)
        if m:
            blocks[int(m.group(1))] = (curlines, int(m.group(2)))
            curlines = []
        else:
            curlines.append(line)
    failures = []
    for i, c in enumerate(cmds):
        actual, rc = blocks.get(i, ([], -1))
        if rc != c.exit_code:
            failures.append((c, rc, actual,
                             f"exit {rc} != {c.exit_code}"))
            continue
        if len(actual) != len(c.expected):
            failures.append((c, rc, actual,
                             f"{len(actual)} lines != "
                             f"{len(c.expected)}"))
            continue
        for want, got in zip(c.expected, actual):
            if not _line_matches(want, got):
                failures.append((c, rc, actual,
                                 f"line {got!r} !~ {want!r}"))
                break
    return failures


def assert_cram(path: str, tmpdir: str) -> None:
    failures = run(path, str(tmpdir))
    if failures:
        msgs = []
        for c, rc, actual, why in failures[:5]:
            msgs.append(f"$ {c.text}\n  {why}\n  actual: "
                        + "\n          ".join(actual[:12]))
        raise AssertionError(
            f"{os.path.basename(path)}: {len(failures)} command(s) "
            f"diverged\n" + "\n".join(msgs))
