"""Three-monitor ProcessCluster: leader SIGKILL over real sockets.

The reference's vstart runs three mons and mon thrashing kills the
leader mid-flight (qa/tasks/mon_thrash.py); the survivors must elect,
recover possibly-committed values through the collect/LAST phase
(src/mon/Paxos.cc), and keep serving — with nothing unquorate ever
observable.  This is the in-process `tests/test_multimon.py` partition
scenario run across real process boundaries: every election, BEGIN,
ACCEPT, and command relay crosses a TCP socket.
"""
import time

import numpy as np
import pytest

from ceph_tpu.vstart import ProcessCluster


@pytest.fixture(scope="module")
def cluster():
    c = ProcessCluster(
        # mon_grace sized for LOADED hosts: a 3 s grace causes
        # spurious re-elections under an 8-worker suite, stalling
        # the relayed commands past any reasonable window
        n_osds=3, n_mons=3, mon_grace=8.0,
        pool={"name": "p", "type": "replicated", "size": 3, "pg_num": 4},
        client_names=("client.x", "client.y"),
        heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


def _snap_create_retrying(c, cl, timeout=120.0):
    """selfmanaged_snap_create through the wire-command path, retried
    across election windows; returns the acked snap id."""
    end = time.monotonic() + timeout
    last = None
    while time.monotonic() < end:
        try:
            return cl.selfmanaged_snap_create("p")
        except (IOError, ValueError) as e:
            last = e
            c.pump_for(0.5)
    raise AssertionError(f"snap create never succeeded: {last!r}")


def _refresh_map(c, cl, tries=3):
    for _ in range(tries):
        cl.mon.send_full_map(cl.name)
        c.pump_for(0.3)


def _read_retrying(c, cl, oid, timeout=90.0):
    """Read retried across the post-failover re-peering window: OSDs
    answer EAGAIN (-11) while they catch up on the new quorum's maps,
    and under suite load that window can outlast the Objecter's own
    8-attempt loop.  Only transient codes retry — anything else (wrong
    bytes, ENOENT) is a real failure and raises immediately."""
    end = time.monotonic() + timeout
    while True:
        try:
            return cl.read("p", oid)
        except IOError as e:
            if getattr(e, "errno", None) not in (11, 110) or \
                    time.monotonic() > end:
                raise
            c.pump_for(1.0)


def _wait_new_leader(c, cl, dead_rank, timeout=150.0):
    """Poll `quorum_status` (read-only, answerable on any mon even
    mid-election) until a DECIDED election has seated a leader other
    than *dead_rank* with a surviving-majority quorum.  Replaces
    guessing with pump counts: under a loaded host the re-election can
    take arbitrarily long, and asserting before it completes is the
    known flake."""
    end = time.monotonic() + timeout
    last = None
    while time.monotonic() < end:
        try:
            st = cl.mon_command("quorum_status")
        except (IOError, ValueError) as e:   # silent/hunting window
            last = e
            c.pump_for(0.5)
            continue
        last = st
        if (st["leader_rank"] >= 0 and st["leader_rank"] != dead_rank
                and st["election_epoch"] % 2 == 0
                and dead_rank not in st["quorum"]
                and len(st["quorum"]) >= 2):
            return st
        c.pump_for(0.5)
    raise AssertionError(f"no post-kill leader/quorum formed: {last!r}")


# loadflaky marker DROPPED (PR 12): the election-timing
# sensitivity was root-caused to starved-tick grace reads in
# Monitor.tick (docs/ANALYSIS.md) and fixed; two consecutive
# green full-suite rounds confirmed, zero auto-reruns
def test_three_mons_leader_sigkill_recovers(cluster):
    c = cluster
    # the client is BOUND TO A PEON (mon.1): its commands cross the
    # peon->leader relay, and its map feed survives the leader's death
    cl = c.client("client.x", mon_name="mon.1")
    c.wait_healthy(cl)

    data = np.random.default_rng(9).integers(
        0, 256, 20000, dtype=np.uint8).tobytes()
    end = time.monotonic() + 90.0
    while True:                    # daemons may still be applying maps
        # write_full RETURNS negative codes (e.g. -110 when the op
        # state machine exhausts its attempts mid-boot) rather than
        # raising — both shapes are retryable here
        try:
            r = cl.write_full("p", "obj", data)
        except IOError:
            r = -1
        if r == 0:
            break
        if time.monotonic() > end:
            raise AssertionError(f"first write never landed: {r}")
        c.pump_for(1.0)
    assert _read_retrying(c, cl, "obj") == data

    # committed allocations under the original leader (relayed mon.1 ->
    # mon.0): these are full-quorum commits the recovery must preserve
    pre_ids = [_snap_create_retrying(c, cl) for _ in range(3)]
    assert pre_ids == sorted(pre_ids) and len(set(pre_ids)) == 3

    # kill the leader MID-PROPOSAL: fire a relayed command and SIGKILL
    # mon.0 immediately, so a BEGIN can be in flight when it dies
    from ceph_tpu.msg.messages import MMonCommand
    c.network.send("client.x", "mon.1", MMonCommand(
        tid=990001, cmd="selfmanaged_snap_create",
        args={"pool_name": "p"}))
    c.kill_mon(0)

    # wait for the surviving majority to finish electing a NEW leader
    # (mon.1, the lowest surviving rank) before asserting anything —
    # on a loaded host the election itself can outlast any fixed pump
    # budget, which was this test's flake
    st = _wait_new_leader(c, cl, dead_rank=0)
    assert st["leader_rank"] == 1, st

    # service resumes; the first post-failover allocation must be
    # STRICTLY ABOVE every pre-kill ack — if collect/LAST recovery had
    # lost a committed value, the fresh leader would re-issue an old id
    post_id = _snap_create_retrying(c, cl, timeout=150.0)
    assert post_id > max(pre_ids), (pre_ids, post_id)

    # both survivors converge on one committed state: subscribe a
    # client to each and compare the replicated map
    cl2 = c.client("client.y", mon_name="mon.2")
    deadline = time.monotonic() + 90.0
    while True:
        _refresh_map(c, cl)
        _refresh_map(c, cl2)
        p1 = cl.osdmap.pools.get(cl.lookup_pool("p"))
        p2 = cl2.osdmap.pools.get(cl2.lookup_pool("p"))
        if (p1 is not None and p2 is not None
                and cl.osdmap.epoch == cl2.osdmap.epoch
                and p1.snap_seq == p2.snap_seq
                and p1.snap_seq >= post_id):
            break
        if time.monotonic() > deadline:
            raise AssertionError(
                f"survivors diverged: epochs {cl.osdmap.epoch}/"
                f"{cl2.osdmap.epoch}, snap_seq "
                f"{getattr(p1, 'snap_seq', None)}/"
                f"{getattr(p2, 'snap_seq', None)}, want >= {post_id}")
        c.pump_for(1.0)

    # data written under the old quorum still serves under the new one
    # (retried: OSDs may still be re-peering under the fresh maps)
    assert _read_retrying(c, cl, "obj") == data
    # and the cluster keeps accepting writes (generous window: under a
    # loaded host the re-peering after mon failover can take a while)
    end = time.monotonic() + 90.0
    while True:
        try:
            r = cl.write_full("p", "obj2", data[:5000])
        except IOError:
            r = -1
        if r == 0:
            break
        if time.monotonic() > end:
            raise AssertionError(f"post-failover write failed: {r}")
        c.pump_for(1.0)
    assert _read_retrying(c, cl, "obj2") == data[:5000]
