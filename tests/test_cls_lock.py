"""cls_lock: advisory object locks (src/cls/lock semantics).

Exclusive contention, shared coexistence under one tag, renewal,
expiration via the OSD clock, break_lock, assert_locked fencing inside
write vectors, and EC-pool locks (xattr state needs no omap).
"""
import json

import pytest

from ceph_tpu.client import ObjectOperation
from ceph_tpu.cluster import MiniCluster


@pytest.fixture()
def env():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=8)
    return c, c.client("client.a"), c.client("client.b")


def test_exclusive_contention_and_unlock(env):
    c, a, b = env
    assert a.lock_exclusive("p", "o", "lk", cookie="c1") == 0
    assert b.lock_exclusive("p", "o", "lk", cookie="c2") == -16  # EBUSY
    assert b.lock_shared("p", "o", "lk", cookie="c2") == -16
    # renewal by the same (entity, cookie) succeeds
    assert a.lock_exclusive("p", "o", "lk", cookie="c1") == 0
    info = a.list_lockers("p", "o", "lk")
    assert len(info["lockers"]) == 1
    assert info["lockers"][0]["entity"] == "client.a"
    # only the holder can unlock
    assert b.unlock("p", "o", "lk", cookie="c2") == -2
    assert a.unlock("p", "o", "lk", cookie="c1") == 0
    assert b.lock_exclusive("p", "o", "lk", cookie="c2") == 0


def test_shared_tag_semantics(env):
    c, a, b = env
    assert a.lock_shared("p", "o", "lk", cookie="c1", tag="T") == 0
    assert b.lock_shared("p", "o", "lk", cookie="c2", tag="T") == 0
    assert len(a.list_lockers("p", "o", "lk")["lockers"]) == 2
    # a different tag or an exclusive request conflicts
    c2 = c.client("client.x")
    assert c2.lock_shared("p", "o", "lk", cookie="c3", tag="OTHER") == -16
    assert c2.lock_exclusive("p", "o", "lk", cookie="c3") == -16
    a.unlock("p", "o", "lk", cookie="c1")
    b.unlock("p", "o", "lk", cookie="c2")
    assert c2.lock_exclusive("p", "o", "lk", cookie="c3") == 0


def test_sole_holder_redefines_type(env):
    """A sole holder downgrading exclusive->shared resets the stored
    type/tag so new shared lockers can join (cls_lock.cc re-set)."""
    c, a, b = env
    assert a.lock_exclusive("p", "o", "lk", cookie="c1") == 0
    assert a.lock_shared("p", "o", "lk", cookie="c1", tag="T") == 0
    assert b.lock_shared("p", "o", "lk", cookie="c2", tag="T") == 0
    assert len(a.list_lockers("p", "o", "lk")["lockers"]) == 2
    # upgrade back requires being sole holder again
    assert a.lock_exclusive("p", "o", "lk", cookie="c1") == -16
    b.unlock("p", "o", "lk", cookie="c2")
    assert a.lock_exclusive("p", "o", "lk", cookie="c1") == 0


def test_expiration_and_break(env):
    c, a, b = env
    assert a.lock_exclusive("p", "o", "lk", cookie="c1",
                            duration=5.0) == 0
    assert b.lock_exclusive("p", "o", "lk", cookie="c2") == -16
    c.tick(dt=3.0)
    assert b.lock_exclusive("p", "o", "lk", cookie="c2") == -16
    c.tick(dt=3.0)          # past the 5 s duration: lock expired
    assert b.lock_exclusive("p", "o", "lk", cookie="c2") == 0
    # operator break of a live lock
    assert a.break_lock("p", "o", "lk", entity="client.b",
                        cookie="c2") == 0
    assert a.lock_exclusive("p", "o", "lk", cookie="c1") == 0
    a.unlock("p", "o", "lk", cookie="c1")


def test_assert_locked_fences_writes(env):
    """The librbd exclusive-lock fencing pattern: writes guarded by
    assert_locked abort EBUSY unless the caller holds the lock."""
    c, a, b = env
    a.write_full("p", "img", b"initial")
    assert a.lock_exclusive("p", "img", "rbd_lock", cookie="c1") == 0

    def guarded_write(cl, cookie, payload):
        op = ObjectOperation()
        op.call("lock", "assert_locked", json.dumps(
            {"name": "rbd_lock", "cookie": cookie}).encode())
        op.write_full(payload)
        r, _ = cl.operate("p", "img", op)
        return r

    assert guarded_write(a, "c1", b"by-holder") == 0
    assert a.read("p", "img") == b"by-holder"
    assert guarded_write(b, "c2", b"by-intruder") == -16
    assert a.read("p", "img") == b"by-holder"     # write fenced off


def test_rbd_image_locks(env, capsys):
    """rbd lock add/ls/rm on the header object (librbd list_lockers)."""
    c, a, b = env
    from ceph_tpu.rbd import Image, RBD
    from ceph_tpu.tools import rbd_cli
    c.create_replicated_pool("rbd", size=3, pg_num=8)
    RBD(a).create("rbd", "vm", 1 << 14, order=12)
    img_a = Image(a, "rbd", "vm")
    img_b = Image(b, "rbd", "vm")
    assert img_a.lock_exclusive("qemu-1") == 0
    assert img_b.lock_exclusive("qemu-2") == -16
    lockers = img_b.list_lockers()
    assert lockers[0]["entity"] == "client.a"
    assert rbd_cli.run(c, b, ["-p", "rbd", "lock", "ls", "vm"]) == 0
    assert "client.a" in capsys.readouterr().out
    # operator break via the CLI, then the other client can lock
    assert rbd_cli.run(c, b, ["-p", "rbd", "lock", "rm", "vm",
                              "--locker", "client.a",
                              "--cookie", "qemu-1"]) == 0
    assert img_b.lock_exclusive("qemu-2") == 0


def test_locks_on_ec_pool(env):
    c, a, b = env
    c.create_ec_pool("e", k=2, m=1, plugin="isa", pg_num=8)
    a.write_full("e", "o", b"ec-data")
    assert a.lock_exclusive("e", "o", "lk", cookie="c1") == 0
    assert b.lock_exclusive("e", "o", "lk", cookie="c2") == -16
    info = b.list_lockers("e", "o", "lk")
    assert info["lockers"][0]["entity"] == "client.a"
    assert a.unlock("e", "o", "lk", cookie="c1") == 0
