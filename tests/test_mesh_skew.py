"""Per-chip timing telemetry: the chip-health scoreboard, the skew
SLO and the straggler ruler (ceph_tpu/mesh/chipstat.py).

- probe cadence: every Nth flush probes (``ec_mesh_skew_sample_every``,
  0 = off), the OSD tick arms a cadence floor, and probes land one
  sample per chip on the 2-D ``mesh_chip_latency_histogram``;
- the tier-1 acceptance: with one chip slowed 10x via the
  ``mesh.chip_slowdown`` fault site the scoreboard marks EXACTLY that
  chip suspect within K probes, ``TPU_MESH_SKEW`` raises at runtime
  (the mgr ticking during the run) naming the chip and its ratio,
  then clears after the fault is removed — and the healthy twin
  raises nothing;
- fence-count gate extended: with sampling OFF the mesh write path
  adds ZERO ``block_until_ready``; with sampling ON, exactly the
  probe's per-chip readbacks appear and ONLY under the dedicated
  ``mesh.skew_probe`` devprof site — which the copy-budget snapshots
  exclude (calibration policy);
- surfaces: ``mesh skew dump``/``reset`` over the admin socket, the
  skew block on ``dispatch dump``'s mesh pane, the ``tpu status``
  pane, and dump/exposition agreement for the
  ``ceph_daemon_mesh_chip_*`` counters.
"""
import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.dispatch import g_dispatcher
from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
from ceph_tpu.fault import g_faults
from ceph_tpu.mesh import g_chipstat, g_mesh, mesh_chip_perf_counters
from ceph_tpu.mesh.chipstat import (SKEW_CLEAR_PROBES,
                                    SKEW_SUSTAIN_PROBES, l_chip_probes,
                                    l_chip_samples,
                                    l_chip_suspects_cleared,
                                    l_chip_suspects_marked)
from ceph_tpu.osd.ecutil import encode as eu_encode, stripe_info_t


@pytest.fixture
def skew_conf():
    """Every test leaves the dispatcher drained, the options at their
    defaults, the scoreboard zeroed and the mesh torn down."""
    yield
    g_faults.clear()
    g_dispatcher.flush()
    for name in ("ec_mesh_chips", "ec_mesh_skew_sample_every",
                 "ec_mesh_skew_threshold", "ec_dispatch_batch_max",
                 "ec_dispatch_batch_window_us"):
        g_conf.rm_val(name)
    g_mesh.topology()
    g_chipstat.reset()


def _mesh_on(chips=8, sample_every=1, threshold=3.0):
    g_conf.set_val("ec_mesh_chips", chips)
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    g_conf.set_val("ec_mesh_skew_sample_every", sample_every)
    g_conf.set_val("ec_mesh_skew_threshold", threshold)


def _mk_impl(k=4, m=2):
    impl = ErasureCodeTpu()
    impl.init({"k": str(k), "m": str(m),
               "technique": "reed_sol_van"})
    return impl


_RNG = np.random.default_rng(20260804)


def _flush_batch(impl, sinfo, want, n_requests=3, n_stripes=2,
                 check=True):
    """One coalesced mesh flush, byte-checked against the oracle."""
    k = impl.k
    chunk = sinfo.get_chunk_size()
    payloads = [_RNG.integers(0, 256, size=n_stripes * k * chunk,
                              dtype=np.uint8)
                for _ in range(n_requests)]
    oracles = [eu_encode(sinfo, impl, p, want) for p in payloads] \
        if check else None
    futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
            for p in payloads]
    g_dispatcher.flush()
    results = [f.result() for f in futs]
    if check:
        for res, oracle in zip(results, oracles):
            assert sorted(res) == sorted(oracle)
            for i in oracle:
                assert np.asarray(res[i]).tobytes() \
                    == np.asarray(oracle[i]).tobytes()
    return results


def test_probe_cadence_every_nth_flush(skew_conf):
    """sample_every=N probes exactly every Nth flush, each probe
    recording one delta per chip (histogram + counters agree); 0
    disables probing entirely."""
    _mesh_on(chips=8, sample_every=0)
    impl = _mk_impl()
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    g_chipstat.reset()
    pc = mesh_chip_perf_counters()
    for _ in range(3):
        _flush_batch(impl, sinfo, want)
    assert pc.get(l_chip_probes) == 0
    assert g_chipstat.summary()["probes"] == 0
    g_conf.set_val("ec_mesh_skew_sample_every", 2)
    g_chipstat.reset()
    for _ in range(6):
        _flush_batch(impl, sinfo, want)
    assert pc.get(l_chip_probes) == 3          # flushes 2, 4, 6
    assert pc.get(l_chip_samples) == 3 * 8
    from ceph_tpu.trace import g_perf_histograms
    hist = g_perf_histograms.get("mesh", "mesh_chip_latency_histogram")
    assert hist.total_count == 3 * 8
    assert hist.axes[0].name == "probe_usec"
    assert hist.axes[1].name == "chip_index"
    per_chip = g_chipstat.summary()["per_chip"]
    assert len(per_chip) == 8
    assert all(row["probes"] == 3 for row in per_chip.values())


def test_osd_tick_arms_probe_cadence_floor(skew_conf):
    """The OSD tick's cadence floor: traffic that flushed since the
    last probe makes the NEXT flush probe even when the Nth-flush
    counter is nowhere near due."""
    from ceph_tpu.cluster import MiniCluster
    _mesh_on(chips=8, sample_every=1000)
    c = MiniCluster(n_osds=4)
    impl = _mk_impl()
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    g_chipstat.reset()
    _flush_batch(impl, sinfo, want)           # flush 1 of 1000: no probe
    assert g_chipstat.summary()["probes"] == 0
    c.tick(dt=1.0)                            # OSD tick arms the floor
    _flush_batch(impl, sinfo, want)
    assert g_chipstat.summary()["probes"] == 1
    # no flush since that probe: another tick must NOT arm again
    c.tick(dt=1.0)
    _flush_batch(impl, sinfo, want)
    _flush_batch(impl, sinfo, want)
    assert g_chipstat.summary()["probes"] == 1


def test_scoreboard_marks_exactly_the_slowed_chip(skew_conf):
    """THE tier-1 acceptance (ISSUE criteria): one chip slowed ~10x
    via mesh.chip_slowdown -> the scoreboard suspects EXACTLY that
    chip within the sustain window, TPU_MESH_SKEW raises while the
    mgr ticks (naming chip + ratio), clears after the fault is
    removed; the healthy run raises nothing; outputs stay
    byte-identical throughout (skew sampling never touches data)."""
    from ceph_tpu.cluster import MiniCluster
    _mesh_on(chips=8, sample_every=1, threshold=3.0)
    c = MiniCluster(n_osds=4)
    impl = _mk_impl()
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    pc = mesh_chip_perf_counters()
    _flush_batch(impl, sinfo, want)           # compile warmup
    g_chipstat.reset()
    # ---- healthy leg: quiet scoreboard, no health check -------------
    for _ in range(4):
        _flush_batch(impl, sinfo, want)
        c.tick(dt=1.0)
    assert g_chipstat.suspects() == []
    assert "TPU_MESH_SKEW" not in c.mgr.health_checks
    # ---- slowed leg -------------------------------------------------
    marked0 = pc.get(l_chip_suspects_marked)
    g_faults.inject("mesh.chip_slowdown", mode="always",
                    match="chip=5/", delay_us=30_000)
    detection = 0
    for i in range(1, 9):
        _flush_batch(impl, sinfo, want)
        c.tick(dt=1.0)
        if g_chipstat.suspects():
            detection = i
            break
    suspects = g_chipstat.suspects()
    assert [s["chip"] for s in suspects] == [5], suspects
    assert suspects[0]["skew_ratio"] >= 3.0
    assert detection == SKEW_SUSTAIN_PROBES   # hysteresis, not a spike
    assert pc.get(l_chip_suspects_marked) == marked0 + 1
    msg = c.mgr.health_checks.get("TPU_MESH_SKEW", "")
    assert "chip 5" in msg and "x the mesh median" in msg, msg
    assert "TPU_MESH_SKEW" in c.health()
    st = c.tpu_status()
    assert st["mesh_skew"]["suspects"][0]["chip"] == 5
    # the skew block rides dispatch dump's mesh pane too
    d = c.admin_socket.execute("dispatch dump")["mesh"]["skew"]
    assert d["suspects"][0]["chip"] == 5
    # ---- fault removed: hysteretic clear ----------------------------
    cleared0 = pc.get(l_chip_suspects_cleared)
    g_faults.clear("mesh.chip_slowdown")
    for _ in range(24):
        _flush_batch(impl, sinfo, want)
        c.tick(dt=1.0)
        if not g_chipstat.suspects() \
                and "TPU_MESH_SKEW" not in c.mgr.health_checks:
            break
    assert g_chipstat.suspects() == []
    assert "TPU_MESH_SKEW" not in c.mgr.health_checks
    assert pc.get(l_chip_suspects_cleared) == cleared0 + 1


def test_single_slow_probe_never_suspects(skew_conf):
    """Hysteresis: one slow probe (count=1 injection) breaches one
    scoreboard pass; the streak resets on the next clean probe and no
    suspect is ever marked — the breaker's spike discipline."""
    _mesh_on(chips=8, sample_every=1, threshold=3.0)
    impl = _mk_impl()
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    _flush_batch(impl, sinfo, want)
    g_chipstat.reset()
    for _ in range(3):
        _flush_batch(impl, sinfo, want)
    g_faults.inject("mesh.chip_slowdown", mode="always",
                    match="chip=2/", delay_us=30_000, count=1)
    for _ in range(SKEW_SUSTAIN_PROBES + 2):
        _flush_batch(impl, sinfo, want)
    assert g_chipstat.suspects() == []


def test_zero_syncs_and_probe_readbacks_only_under_skew_site(
        skew_conf, monkeypatch):
    """Fence-count gate extended (ISSUE satellite): sampling OFF adds
    ZERO block_until_ready to the mesh write path and never touches
    the mesh.skew_probe site; sampling ON still adds zero
    block_until_ready, and exactly mesh_size readbacks per probe
    appear — ONLY under the mesh.skew_probe devprof site, which the
    copy-budget snapshot (devflow) excludes as calibration."""
    import jax
    from ceph_tpu.trace import g_devprof
    _mesh_on(chips=8, sample_every=0)
    impl = _mk_impl()
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    _flush_batch(impl, sinfo, want)           # compile warmup
    calls = {"n": 0}
    real = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)

    def site(name):
        return dict(g_devprof.dump()["sites"].get(name, {}))

    before = site("mesh.skew_probe")
    _flush_batch(impl, sinfo, want, check=False)
    assert calls["n"] == 0, "sampling-off mesh write path synced"
    assert site("mesh.skew_probe") == before, \
        "probe site moved with sampling off"
    # sampling ON: 3 flushes -> 3 probes -> 8 readbacks each (oracle
    # checks off so NOTHING but the mesh flush itself accounts here)
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    d2h0 = before.get("d2h_count", 0)
    others0 = {name: s["d2h_count"]
               for name, s in g_devprof.dump()["sites"].items()
               if name != "mesh.skew_probe"}
    for _ in range(3):
        _flush_batch(impl, sinfo, want, check=False)
    assert calls["n"] == 0, "skew probe added a block_until_ready"
    probe_site = site("mesh.skew_probe")
    assert probe_site.get("d2h_count", 0) == d2h0 + 3 * 8
    # the probe's readbacks landed under NO other site: every other
    # site's d2h delta is exactly what 3 mesh flushes always cost
    # (one accounted mesh.encode materialization per flush)
    others1 = {name: s["d2h_count"]
               for name, s in g_devprof.dump()["sites"].items()
               if name != "mesh.skew_probe"}
    assert others1.get("mesh.encode", 0) \
        == others0.get("mesh.encode", 0) + 3
    for name, v in others1.items():
        if name != "mesh.encode":
            assert v == others0.get(name, v), \
                f"probe readbacks leaked into site {name}"
    # the copy-budget snapshot excludes the calibration site: its
    # totals must not move when ONLY the probe site does
    snap = g_devprof.snapshot()
    probe_only_d2h = probe_site["d2h_count"]
    full = g_devprof.totals()
    assert full["d2h_count"] - snap["d2h_count"] == probe_only_d2h


def test_mesh_skew_dump_reset_and_exposition_agreement(skew_conf):
    """`mesh skew dump` over the admin socket carries the scoreboard,
    per-chip percentiles and counters; the Prometheus exposition's
    ceph_daemon_mesh_chip_* samples agree with the dump; `mesh skew
    reset` zeroes all of it."""
    from ceph_tpu.cluster import MiniCluster
    _mesh_on(chips=8, sample_every=1)
    c = MiniCluster(n_osds=4)
    impl = _mk_impl()
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    for _ in range(3):
        _flush_batch(impl, sinfo, want)
    dump = c.admin_socket.execute("mesh skew dump")
    assert dump["probes"] == 3
    assert len(dump["per_chip"]) == 8
    assert len(dump["per_chip_percentiles"]) == 8
    for pct in dump["per_chip_percentiles"].values():
        assert pct["p99"] > 0
    assert dump["counters"]["probes"] == 3
    assert dump["counters"]["samples"] == 24
    # dump/exposition agreement: the scrape shows the same figures
    prom = c.admin_socket.execute("prometheus metrics")
    for cname, want_v in (("probes", 3), ("samples", 24)):
        line = next(ln for ln in prom.splitlines()
                    if ln.startswith(f"ceph_daemon_mesh_chip_{cname} "))
        assert float(line.split()[-1]) == want_v, line
    out = c.admin_socket.execute("mesh skew reset")
    assert out == {"reset": True}
    dump = c.admin_socket.execute("mesh skew dump")
    assert dump["probes"] == 0 and dump["per_chip"] == {}
    assert dump["counters"]["probes"] == 0


def test_skew_options_live_and_documented_defaults(skew_conf):
    """The two knobs read live (config set applies on the next flush)
    and carry the documented defaults: sampling default-on at a low
    rate, threshold 3.0."""
    assert int(g_conf.get_val("ec_mesh_skew_sample_every")) == 16
    assert float(g_conf.get_val("ec_mesh_skew_threshold")) == 3.0
    _mesh_on(chips=8, sample_every=0)
    impl = _mk_impl()
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    g_chipstat.reset()
    _flush_batch(impl, sinfo, want)
    assert g_chipstat.summary()["probes"] == 0
    g_conf.set_val("ec_mesh_skew_sample_every", 1)   # no rebuild needed
    _flush_batch(impl, sinfo, want)
    assert g_chipstat.summary()["probes"] == 1
