"""cephfs hard links: remote dentries, nlink, promotion on unlink.

Reference semantics (CDentry remote dentries + stray-directory inode
migration): every name is the same file; data survives until the LAST
name goes; renames keep the primary/remote pointers consistent.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.cephfs import CephFS, FsError, file_oid

ORDER = 12


@pytest.fixture()
def fs():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("fsmeta", size=3, pg_num=8)
    c.create_replicated_pool("fsdata", size=3, pg_num=8)
    cl = c.client("client.fs")
    f = CephFS(cl, "fsmeta", "fsdata")
    f.mkfs()
    return c, cl, f


def test_link_identity_and_nlink(fs):
    c, cl, f = fs
    f.create("/a", ORDER)
    f.write("/a", b"shared-bytes")
    f.mkdir("/d")
    f.hardlink("/a", "/d/b")
    assert f.read("/d/b") == b"shared-bytes"
    assert f.stat("/a")["nlink"] == 2
    assert f.stat("/d/b")["nlink"] == 2
    assert f.stat("/d/b")["ino"] == f.stat("/a")["ino"]
    # writes through either name are visible through both
    f.write("/d/b", b"NEW", offset=0)
    assert f.read("/a")[:3] == b"NEW"
    f.write("/a", b"!", offset=3)
    assert f.read("/d/b")[:4] == b"NEW!"
    # size growth through the remote name lands on the shared inode
    f.write("/d/b", b"Z" * 50, offset=100)
    assert f.stat("/a")["size"] == 150
    # hard links to directories are refused
    with pytest.raises(FsError) as ei:
        f.hardlink("/d", "/dlink")
    assert ei.value.result == -1


def test_unlink_order_data_survives_until_last(fs):
    c, cl, f = fs
    f.create("/orig", ORDER)
    f.write("/orig", b"payload")
    f.hardlink("/orig", "/l1")
    f.hardlink("/l1", "/l2")           # linking via a remote works
    assert f.stat("/orig")["nlink"] == 3
    ino = f.stat("/orig")["ino"]
    # drop a remote: others unaffected
    f.unlink("/l1")
    assert f.stat("/orig")["nlink"] == 2
    assert f.read("/l2") == b"payload"
    # drop the PRIMARY: a remote is promoted, data survives
    f.unlink("/orig")
    assert f.read("/l2") == b"payload"
    assert f.stat("/l2")["nlink"] == 1
    assert not f.exists("/orig")
    # last name purges the data objects
    f.unlink("/l2")
    with pytest.raises(IOError):
        cl.read("fsdata", file_oid(ino, 0))


def test_rename_keeps_pointers(fs):
    c, cl, f = fs
    f.mkdir("/x")
    f.create("/file", ORDER)
    f.write("/file", b"pointer-check")
    f.hardlink("/file", "/x/link")
    # move the REMOTE cross-dir: identity intact
    f.rename("/x/link", "/moved-link")
    assert f.read("/moved-link") == b"pointer-check"
    # then unlink the primary: the moved remote is still found/promoted
    f.unlink("/file")
    assert f.read("/moved-link") == b"pointer-check"
    # move the (now-)PRIMARY cross-dir after making another link
    f.hardlink("/moved-link", "/x/again")
    f.rename("/moved-link", "/x/primary-moved")
    assert f.read("/x/again") == b"pointer-check"
    f.unlink("/x/primary-moved")       # promotion chases moved pointers
    assert f.read("/x/again") == b"pointer-check"
    f.unlink("/x/again")


def test_repeated_hardlink_eexist_keeps_backpointer(fs):
    """A second hardlink to the same name fails EEXIST without
    stripping the original back-pointer (rollback only removes what
    the failing call itself added)."""
    c, cl, f = fs
    f.create("/a", ORDER)
    f.write("/a", b"keep-me")
    f.hardlink("/a", "/b")
    with pytest.raises(FsError) as ei:
        f.hardlink("/a", "/b")
    assert ei.value.result == -17
    assert f.stat("/a")["nlink"] == 2
    f.unlink("/a")                     # promotion must still find /b
    assert f.read("/b") == b"keep-me"
    # CLI ls renders the hard-link dentry without crashing
    from ceph_tpu.tools import cephfs_cli
    f.hardlink("/b", "/c")
    assert cephfs_cli.run(c, cl, ["ls", "/"]) == 0


def test_rename_between_same_file_names_is_noop(fs):
    """rename between two names of the same file is a POSIX no-op in
    BOTH directions — it must never displace the primary or purge."""
    c, cl, f = fs
    f.create("/a", ORDER)
    f.write("/a", b"precious")
    f.hardlink("/a", "/b")
    f.rename("/b", "/a")             # remote onto its primary
    assert f.read("/a") == b"precious"
    assert f.read("/b") == b"precious"
    assert f.stat("/a")["nlink"] == 2
    f.rename("/a", "/b")             # primary onto its remote
    assert f.read("/a") == b"precious"
    assert f.read("/b") == b"precious"
    assert f.stat("/b")["nlink"] == 2
    # cross-dir variant
    f.mkdir("/d")
    f.hardlink("/a", "/d/c")
    f.rename("/d/c", "/a")
    assert f.read("/d/c") == b"precious"
    assert f.stat("/a")["nlink"] == 3


def test_promotion_prunes_stale_backpointers(fs):
    """A recorded-but-absent link (the documented crash window) is
    pruned during promotion instead of wedging the unlink."""
    c, cl, f = fs
    f.create("/p", ORDER)
    f.write("/p", b"x")
    f.hardlink("/p", "/live")
    # manufacture a stale back-pointer (the crash between record+link)
    dino, name = f._resolve_parent("/p")
    inode = f._lookup(dino, name)
    f._update(dino, name, links=inode["links"] + [[999, "ghost"]])
    f.unlink("/p")                     # must promote /live, prune ghost
    assert f.read("/live") == b"x"
    assert f.stat("/live")["nlink"] == 1
