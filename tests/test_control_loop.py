"""Closed-loop tier-1 smokes (ceph_tpu/control): the three policy-map
scenarios converge on a REAL MiniCluster with ZERO operator action —
the mgr ticks, the controller senses the SLO streaks and moves the
responsible knob, the pressure clears, the knobs restore.

The state machine itself (damping, bounds, anti-windup, tear-down,
fault-bounded actuation) is pinned in tests/test_control.py; the
bench-gated version of these scenarios with convergence-tick receipts
is the `slo_autotune` workload (bench/workloads.py + the CONTROL GATE
in bench/regress.py).
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common.config import g_conf
from ceph_tpu.dispatch import g_dispatcher
from ceph_tpu.fault import g_faults
from ceph_tpu.mesh import g_chipstat, g_mesh

TOUCHED = (
    "mgr_control_enable", "mgr_control_cooldown_ticks",
    "mgr_control_bounds", "mgr_slo_admission_rate_max",
    "mgr_slo_oplat_p99_usec", "mgr_slo_fast_window_s",
    "mgr_slo_slow_window_s", "mgr_telemetry_retention",
    "osd_op_queue_admission_max", "osd_op_queue_batch_intake",
    "osd_mclock_client_overrides", "osd_mclock_class_overrides",
    "osd_recovery_max_active", "ec_mesh_chips", "ec_mesh_rateless",
    "ec_mesh_rateless_tasks", "ec_mesh_skew_sample_every",
    "ec_mesh_skew_threshold", "ec_dispatch_batch_max",
    "ec_dispatch_batch_window_us",
)


@pytest.fixture(autouse=True)
def _clean():
    saved = {n: g_conf.values.get(n) for n in TOUCHED}
    yield
    for n, v in saved.items():
        if v is None:
            g_conf.rm_val(n)
        else:
            g_conf.set_val(n, v)
    g_faults.clear()
    g_dispatcher.flush()
    g_mesh.topology()
    g_chipstat.reset()


def _enable_controller():
    g_conf.set_val("mgr_control_enable", True)
    g_conf.set_val("mgr_control_cooldown_ticks", 1)


def test_abusive_client_scenario_converges():
    """An abusive open-loop client burns TPU_SLO_ADMISSION; the
    controller de-weights exactly that client's dmClock lane (and caps
    it), the burn clears once the flood is contained, and the lane
    restores — zero operator action, ops byte-exact."""
    from ceph_tpu.load.traffic import TrafficSpec, run_traffic
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("abuse", size=2, pg_num=8)
    _enable_controller()
    g_conf.set_val("mgr_slo_admission_rate_max", 0.001)
    g_conf.set_val("mgr_slo_fast_window_s", 6.0)
    g_conf.set_val("mgr_slo_slow_window_s", 12.0)
    g_conf.set_val("osd_op_queue_admission_max", 4)
    spec = TrafficSpec(pool="abuse", n_clients=4, ops_per_client=160,
                       read_fraction=0.25, mode="open", rate=10.0,
                       rate_multipliers=(6.0, 1.0, 1.0, 1.0),
                       tick_every=1, seed=20260807,
                       keep_completions=False)
    res = run_traffic(c, spec)
    assert res.byte_exact, res.errors[:3]
    assert res.admission_rejections > 0
    ctl = c.mgr.control
    led = list(ctl._ledger)
    assert any(e["reflex"] == "admission" for e in led), led
    # the abuser the controller picked is the flooding client
    tightens = [e for e in led if e["reflex"] == "admission"]
    assert all("client.abuse.0" in e["reason"] for e in tightens), led
    ov = str(g_conf.get_val("osd_mclock_client_overrides"))
    assert "client.abuse.0:" in ov, ov
    # every move stayed inside its knob's bounds
    for e in led:
        k = ctl.dump()["knobs"][e["knob"]]
        assert k["floor"] <= e["to"] <= k["ceiling"], e
    # ---- traffic over: the check clears, the episode restores -------
    cleared_at = None
    for i in range(60):
        c.tick(dt=1.0)
        d = ctl.dump()
        if "TPU_SLO_ADMISSION" not in c.mgr.health_checks and \
                all(k["baseline"] is None for k in d["knobs"].values()):
            cleared_at = i
            break
    assert cleared_at is not None, ctl.dump()
    assert ctl.dump()["abuser"] == ""
    assert any(e["reflex"] == "restore" for e in ctl._ledger)


def test_recovery_storm_scenario_converges():
    """TPU_SLO_OPLAT burning while a recovery storm is in flight: the
    controller steps osd_recovery_max_active down (client latency
    wins), then restores it once the storm and the burn clear."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("storm", k=3, m=2, pg_num=4,
                     plugin="regenerating", extra_profile={"d": "4"})
    cl = c.client("client.storm")
    payloads = {f"o{i}": bytes([i % 256]) * 6000 for i in range(40)}
    for oid, body in payloads.items():
        assert cl.write_full("storm", oid, body) == 0
    _enable_controller()
    g_conf.set_val("mgr_slo_oplat_p99_usec", "reply:1")
    g_conf.set_val("mgr_slo_fast_window_s", 6.0)
    g_conf.set_val("mgr_slo_slow_window_s", 12.0)
    g_conf.set_val("mgr_telemetry_retention", 10_000)
    base_active = int(g_conf.get_val("osd_recovery_max_active"))
    ctl = c.mgr.control
    # ---- phase 1: the burn sustains (client IO, no storm yet) -------
    for i in range(6):
        assert cl.write_full("storm", f"pre{i}", b"x" * 2000) == 0
        c.tick(dt=1.0)
    assert "TPU_SLO_OPLAT" in c.mgr.health_checks
    assert ctl.moves_total == 0           # burn alone: no storm, no move
    # ---- phase 2: an OSD dies mid-burn -> recovery storm ------------
    pid = c.mon.osdmap.lookup_pg_pool_name("storm")
    victim = next(pg.acting[-1] for pgid, pg in c.primary_pgs()
                  if pgid[0] == pid and pg.backend is not None)
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    c.mark_osd_out(victim)
    moved_at = None
    for i in range(20):
        # client IO rides THROUGH the storm (the oplat samples the
        # SLO engine judges), the mgr ticking mid-run
        assert cl.write_full("storm", f"live{i}", b"x" * 2000) == 0
        c.tick(dt=1.0)
        if moved_at is None and c.mgr.control.moves_total > 0:
            moved_at = i
    assert moved_at is not None, \
        (c.mgr.health_checks, ctl.dump())
    led = list(ctl._ledger)
    assert any(e["reflex"] == "recovery"
               and e["knob"] == "osd_recovery_max_active"
               for e in led), led
    assert int(g_conf.get_val("osd_recovery_max_active")) < base_active
    # ---- quiesce: no samples -> burn clears -> restore --------------
    done = None
    for i in range(80):
        c.tick(dt=1.0)
        if "TPU_SLO_OPLAT" not in c.mgr.health_checks and \
                int(g_conf.get_val("osd_recovery_max_active")) \
                == base_active:
            done = i
            break
    assert done is not None, ctl.dump()
    # data survived the storm end to end
    for oid, body in payloads.items():
        assert cl.read("storm", oid) == body


def test_straggler_scenario_widens_then_narrows():
    """A slowed chip raises TPU_MESH_SKEW; the controller widens
    ec_mesh_rateless_tasks (straggler protection buys tail latency).
    With the fault gone and skew quiet, the wasted-block ratio of the
    widened plan dominates and the controller narrows back — the
    bandwidth-vs-tail dial self-tunes both ways."""
    from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
    from ceph_tpu.osd.ecutil import encode as eu_encode, stripe_info_t
    g_conf.set_val("ec_mesh_chips", 8)
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    g_conf.set_val("ec_mesh_skew_threshold", 3.0)
    g_conf.set_val("ec_mesh_rateless", True)
    c = MiniCluster(n_osds=4)
    _enable_controller()
    impl = ErasureCodeTpu()
    impl.init({"k": "4", "m": "2", "technique": "reed_sol_van"})
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    rng = np.random.default_rng(20260807)

    def flush():
        payloads = [rng.integers(0, 256, size=2 * 4 * 4096,
                                 dtype=np.uint8) for _ in range(3)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        for f, oracle in zip(futs, oracles):
            res = f.result()
            assert sorted(res) == sorted(oracle)
            for i in oracle:
                assert np.asarray(res[i]).tobytes() == \
                    np.asarray(oracle[i]).tobytes()

    flush()                                    # compile warmup
    g_chipstat.reset()
    mesh_size = g_mesh.topology().size
    auto_width = mesh_size + 2
    assert int(g_conf.get_val("ec_mesh_rateless_tasks") or 0) == 0
    g_faults.inject("mesh.chip_slowdown", mode="always",
                    match="chip=5/", delay_us=30_000)
    widened_at = None
    for i in range(16):
        flush()
        c.tick(dt=1.0)
        if int(g_conf.get_val("ec_mesh_rateless_tasks") or 0) \
                > auto_width:
            widened_at = i
            break
    ctl = c.mgr.control
    assert widened_at is not None, \
        (c.mgr.health_checks, ctl.dump())
    peak = int(g_conf.get_val("ec_mesh_rateless_tasks"))
    assert auto_width < peak <= 2 * mesh_size
    assert any(e["reflex"] == "straggler" and "widen" in e["reason"]
               for e in ctl._ledger), list(ctl._ledger)
    # ---- fault gone: skew clears, waste economics narrow back -------
    # (the controller may keep widening until the hysteretic clear
    # lands, so track the true peak through the loop)
    g_faults.clear("mesh.chip_slowdown")
    narrowed = False
    for _ in range(40):
        flush()
        c.tick(dt=1.0)
        width = int(g_conf.get_val("ec_mesh_rateless_tasks") or 0)
        peak = max(peak, width)
        if "TPU_MESH_SKEW" not in c.mgr.health_checks \
                and width < peak:
            narrowed = True
            break
    assert narrowed, ctl.dump()
    assert any(e["reflex"] == "straggler" and "narrow" in e["reason"]
               for e in ctl._ledger), list(ctl._ledger)
    # width never left [mesh+1, 2*mesh] at any move
    for e in ctl._ledger:
        if e["knob"] == "ec_mesh_rateless_tasks":
            assert mesh_size + 1 <= e["to"] <= 2 * mesh_size, e
