Stage-latency ledger admin CLI (`ceph daemon <who> latency
dump|reset`), in the style of the reference's recorded src/test/cli
transcripts: the zeroed ledger of a freshly restored cluster — the
stage catalog is the contract — and the reset.

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 latency dump
  {
    "daemons": {},
    "ops": 0,
    "stage_catalog": [
      "client_flight",
      "admission",
      "class_queue",
      "client_lane",
      "dequeue_handoff",
      "op_service",
      "batch_window",
      "device_call",
      "d2h",
      "fan_out",
      "ack_gather",
      "reply"
    ],
    "stage_samples": 0
  }

  $ ceph --cluster ck daemon osd.0 latency reset
  {
    "reset": true
  }

(The populated per-daemon per-stage table of a live op — admission
wait, mClock queue tiers, codec submit, device round trip, fan-out,
ack gathering, reply — is asserted in-process by tests/test_oplat.py;
booting an EC cluster inside a cram subprocess would re-compile the
encode kernel outside the shared XLA cache and burn tier-1 wall
budget for coverage that already exists.)
