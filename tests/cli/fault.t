Fault-injection admin CLI (`ceph daemon <who> fault inject|list|clear`),
in the style of the reference's recorded src/test/cli transcripts: the
site catalog, arming a trigger, the unknown-site refusal, and clearing.

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 fault list
  {
    "armed": {},
    "sites": {
      "control.actuate": "mgr control-plane config injection (ceph_tpu/control): a firing fails ONE knob actuation; the controller retries mgr_control_actuate_retries times within the tick, then drops the move and re-derives it next tick \u2014 context is '<knob>=<value> (<option>)' for match= scoping",
      "device.decode_batch": "batched EC decode/reconstruct device call (matrix_plugin.decode_batch)",
      "device.encode_batch": "batched EC encode device call (matrix_plugin.encode_batch)",
      "device.encode_chunks": "per-stripe encode device call (matrix_plugin.encode_chunks)",
      "dispatch.batch": "coalesced flush execution (scheduler._execute run_group) \u2014 exercises the per-request fallback isolation",
      "mesh.chip_fail": "hard per-chip failure mid-flush (ceph_tpu/mesh/rateless): the matching chip's coded blocks become erasures the subset completion re-solves around; context is 'chip=<i>/<mesh size>' for match= scoping, count= bounds the failed flushes",
      "mesh.chip_slowdown": "per-chip straggler injection (ceph_tpu/mesh/chipstat): delays the matching chip's probe readback by delay_us; context is 'chip=<i>/<mesh size>' so match='chip=3/' scopes one chip",
      "mesh.decode_batch": "mesh-sharded decode/reconstruct/repair execution (ceph_tpu/mesh runtime decode_stacked) \u2014 exhaustion degrades the group to the single-device path and journals mesh_decode_degraded",
      "mesh.encode_batch": "mesh-sharded flush execution (ceph_tpu/mesh runtime) \u2014 exhaustion degrades the flush to the single-device path",
      "mgr.incident_capture": "incident bundle snapshot on a health-check raise (ceph_tpu/mgr/incident): a firing drops that bundle \u2014 the raise is journaled, the tick proceeds, and the NEXT raise captures normally; context is the triggering check name",
      "msg.drop": "drop a fabric message (ms inject socket failures role); context is '<MsgType> <src>><dst>' for match= scoping",
      "osd.shard_read_eio": "shard-side EC read returns EIO (bluestore_debug_inject_read_err role) \u2014 the primary must reconstruct from surviving shards",
      "recovery.helper_fetch": "helper-side repair contribution read (handle_sub_read) \u2014 a dropped helper fails the round and the orchestrator falls back to full-stripe decode",
      "recovery.repair_read": "sub-chunk repair round start (recovery scheduler) \u2014 firing degrades the repair to the full-stripe decode path",
      "store.shard_corrupt": "flip one byte of a stored shard body at read time (memstore) \u2014 the shard-side crc32c verify must catch it and return EIO, whether the body is host bytes or a device-resident handle; context is '<coll>/<oid>' for match= scoping",
      "tpu.decode_batch_device": "device-resident decode entry point (tpu_plugin, mesh/bench)",
      "tpu.encode_batch_device": "device-resident encode entry point (tpu_plugin, mesh/bench)"
    }
  }

  $ ceph --cluster ck daemon osd.0 fault inject name=osd.shard_read_eio mode=nth n=3
  {
    "armed": {
      "checks": 0,
      "count": 0,
      "delay_us": 0,
      "error": "device",
      "fires": 0,
      "match": "",
      "mode": "nth",
      "n": 3,
      "p": 1.0,
      "seed": null
    },
    "site": "osd.shard_read_eio"
  }

The per-chip straggler site (ceph_tpu/mesh/chipstat): delay_us= stalls
the matching chip's probe completion, match='chip=<i>/' scopes the
injection to exactly one chip index.

  $ ceph --cluster ck daemon osd.0 fault inject name=mesh.chip_slowdown mode=always match=chip=5/ delay_us=30000
  {
    "armed": {
      "checks": 0,
      "count": 0,
      "delay_us": 30000,
      "error": "device",
      "fires": 0,
      "match": "chip=5/",
      "mode": "always",
      "n": 1,
      "p": 1.0,
      "seed": null
    },
    "site": "mesh.chip_slowdown"
  }

The hard per-chip failure site (ceph_tpu/mesh/rateless): the matching
chip's coded blocks become erasures mid-flush, match='chip=<i>/' scopes
one chip and count= bounds how many flushes lose it.

  $ ceph --cluster ck daemon osd.0 fault inject name=mesh.chip_fail mode=always match=chip=3/ count=2
  {
    "armed": {
      "checks": 0,
      "count": 2,
      "delay_us": 0,
      "error": "device",
      "fires": 0,
      "match": "chip=3/",
      "mode": "always",
      "n": 1,
      "p": 1.0,
      "seed": null
    },
    "site": "mesh.chip_fail"
  }

The control-plane actuation site (ceph_tpu/control): a firing fails one
mgr knob injection; the controller's retry budget is
mgr_control_actuate_retries per tick, then the move re-derives next
tick (tests/test_control.py proves it never wedges).

  $ ceph --cluster ck daemon osd.0 fault inject name=control.actuate mode=nth n=2
  {
    "armed": {
      "checks": 0,
      "count": 0,
      "delay_us": 0,
      "error": "device",
      "fires": 0,
      "match": "",
      "mode": "nth",
      "n": 2,
      "p": 1.0,
      "seed": null
    },
    "site": "control.actuate"
  }

  $ ceph --cluster ck daemon osd.0 fault inject name=bogus.site
  admin socket: unknown fault site 'bogus.site' (see 'fault list')
  [1]

  $ ceph --cluster ck daemon osd.0 fault clear
  {
    "cleared": 0
  }

The machine-readable site list (the composer's sites() API over
the admin socket): one row per site, sorted by name, `armed` is
the live trigger spec or null.

  $ ceph --cluster ck daemon osd.0 fault list format=json
  [
    {
      "armed": null,
      "description": "mgr control-plane config injection (ceph_tpu/control): a firing fails ONE knob actuation; the controller retries mgr_control_actuate_retries times within the tick, then drops the move and re-derives it next tick \u2014 context is '<knob>=<value> (<option>)' for match= scoping",
      "name": "control.actuate"
    },
    {
      "armed": null,
      "description": "batched EC decode/reconstruct device call (matrix_plugin.decode_batch)",
      "name": "device.decode_batch"
    },
    {
      "armed": null,
      "description": "batched EC encode device call (matrix_plugin.encode_batch)",
      "name": "device.encode_batch"
    },
    {
      "armed": null,
      "description": "per-stripe encode device call (matrix_plugin.encode_chunks)",
      "name": "device.encode_chunks"
    },
    {
      "armed": null,
      "description": "coalesced flush execution (scheduler._execute run_group) \u2014 exercises the per-request fallback isolation",
      "name": "dispatch.batch"
    },
    {
      "armed": null,
      "description": "hard per-chip failure mid-flush (ceph_tpu/mesh/rateless): the matching chip's coded blocks become erasures the subset completion re-solves around; context is 'chip=<i>/<mesh size>' for match= scoping, count= bounds the failed flushes",
      "name": "mesh.chip_fail"
    },
    {
      "armed": null,
      "description": "per-chip straggler injection (ceph_tpu/mesh/chipstat): delays the matching chip's probe readback by delay_us; context is 'chip=<i>/<mesh size>' so match='chip=3/' scopes one chip",
      "name": "mesh.chip_slowdown"
    },
    {
      "armed": null,
      "description": "mesh-sharded decode/reconstruct/repair execution (ceph_tpu/mesh runtime decode_stacked) \u2014 exhaustion degrades the group to the single-device path and journals mesh_decode_degraded",
      "name": "mesh.decode_batch"
    },
    {
      "armed": null,
      "description": "mesh-sharded flush execution (ceph_tpu/mesh runtime) \u2014 exhaustion degrades the flush to the single-device path",
      "name": "mesh.encode_batch"
    },
    {
      "armed": null,
      "description": "incident bundle snapshot on a health-check raise (ceph_tpu/mgr/incident): a firing drops that bundle \u2014 the raise is journaled, the tick proceeds, and the NEXT raise captures normally; context is the triggering check name",
      "name": "mgr.incident_capture"
    },
    {
      "armed": null,
      "description": "drop a fabric message (ms inject socket failures role); context is '<MsgType> <src>><dst>' for match= scoping",
      "name": "msg.drop"
    },
    {
      "armed": null,
      "description": "shard-side EC read returns EIO (bluestore_debug_inject_read_err role) \u2014 the primary must reconstruct from surviving shards",
      "name": "osd.shard_read_eio"
    },
    {
      "armed": null,
      "description": "helper-side repair contribution read (handle_sub_read) \u2014 a dropped helper fails the round and the orchestrator falls back to full-stripe decode",
      "name": "recovery.helper_fetch"
    },
    {
      "armed": null,
      "description": "sub-chunk repair round start (recovery scheduler) \u2014 firing degrades the repair to the full-stripe decode path",
      "name": "recovery.repair_read"
    },
    {
      "armed": null,
      "description": "flip one byte of a stored shard body at read time (memstore) \u2014 the shard-side crc32c verify must catch it and return EIO, whether the body is host bytes or a device-resident handle; context is '<coll>/<oid>' for match= scoping",
      "name": "store.shard_corrupt"
    },
    {
      "armed": null,
      "description": "device-resident decode entry point (tpu_plugin, mesh/bench)",
      "name": "tpu.decode_batch_device"
    },
    {
      "armed": null,
      "description": "device-resident encode entry point (tpu_plugin, mesh/bench)",
      "name": "tpu.encode_batch_device"
    }
  ]
