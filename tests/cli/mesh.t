Chip-health scoreboard admin CLI (`ceph daemon <who> mesh skew
dump|reset`), in the style of the reference's recorded src/test/cli
transcripts: the zeroed scoreboard of a freshly restored cluster — the
option defaults, hysteresis constants and counter catalog are the
contract — and the reset.

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 mesh skew dump
  {
    "clear_probes": 3,
    "counters": {
      "max_skew_permille": 0,
      "probes": 0,
      "samples": 0,
      "slowdowns_injected": 0,
      "suspect_chips": 0,
      "suspects_cleared": 0,
      "suspects_marked": 0
    },
    "flushes": 0,
    "options": {
      "ec_mesh_skew_sample_every": 16,
      "ec_mesh_skew_threshold": 3.0
    },
    "per_chip": {},
    "per_chip_percentiles": {},
    "probes": 0,
    "suspects": [],
    "sustain_probes": 3
  }

  $ ceph --cluster ck daemon osd.0 mesh skew reset
  {
    "reset": true
  }

The rateless coded-encode pane rides `dispatch dump`'s mesh block
(ceph_tpu/mesh/rateless): the option defaults (off; tasks 0 = auto)
and the zeroed mesh_rateless_* counter family of a freshly restored
cluster are the contract.

  $ ceph --cluster ck daemon osd.0 dispatch dump | python -c "import json,sys; print(json.dumps(json.load(sys.stdin)['mesh']['rateless'], indent=2, sort_keys=True))"
  {
    "counters": {
      "chip_failures": 0,
      "coded_tasks": 0,
      "flushes": 0,
      "host_resolves": 0,
      "insufficient": 0,
      "parity_tasks": 0,
      "subset_completions": 0,
      "suspect_deweights": 0,
      "wasted_blocks": 0
    },
    "options": {
      "ec_mesh_rateless": false,
      "ec_mesh_rateless_tasks": 0
    }
  }

(The populated scoreboard of a probed mesh — per-chip EWMAs, skew
ratios, a marked suspect and the TPU_MESH_SKEW raise/clear — is
asserted in-process by tests/test_mesh_skew.py; an 8-chip mesh
cluster inside a cram subprocess would re-compile the sharded encode
outside the shared XLA cache and burn tier-1 wall budget for coverage
that already exists.)
