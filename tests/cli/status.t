Telemetry rollup admin CLI (`ceph daemon <who> tpu status` and
`telemetry dump|reset`), in the style of the reference's recorded
src/test/cli transcripts: the single-pane status and the rollup dump
of a freshly restored cluster — the snapshot shape (rates catalog,
objectives table, SLO/breaker panes) is the contract — and the reset.

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 telemetry dump
  {
    "clock": 0.0,
    "copies_per_op": 0.0,
    "families": {},
    "objectives": {
      "admission_rate_max": 0.0,
      "copies_per_op_max": 0.0,
      "oplat_p99_usec": {}
    },
    "oplat": {},
    "oplat_p99_usec": {},
    "rates": {
      "admission_rejections": 0.0,
      "d2h_bytes": 0.0,
      "h2d_bytes": 0.0,
      "ops": 0.0
    },
    "retention": 360,
    "samples": 1,
    "slo": {},
    "span_s": 0.0,
    "window_s": 30.0
  }

  $ ceph --cluster ck daemon osd.0 tpu status
  {
    "breakers_open": [],
    "cluster_p99_usec": {},
    "copies_per_op": 0.0,
    "health": "HEALTH_OK",
    "mesh_skew": {
      "probes": 0,
      "suspects": []
    },
    "objectives": {
      "admission_rate_max": 0.0,
      "copies_per_op_max": 0.0,
      "oplat_p99_usec": {}
    },
    "rates": {
      "admission_rejections": 0.0,
      "d2h_bytes": 0.0,
      "h2d_bytes": 0.0,
      "ops": 0.0
    },
    "samples": 1,
    "slo": {},
    "window_s": 30.0
  }

  $ ceph --cluster ck daemon osd.0 telemetry reset
  {
    "reset": true
  }

(The populated pane — cluster-merged per-stage p99s, live rates, a
breaching TPU_SLO_* check raising and clearing through health — is
asserted in-process by tests/test_telemetry.py; driving harness load
inside a cram subprocess would recompile kernels outside the shared
XLA cache and burn tier-1 wall budget for coverage that already
exists.)
