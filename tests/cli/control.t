Control-plane admin CLI (ceph_tpu/control): the `tpu control dump`
pane is the operator's one-stop actuation ledger — enable state, per-
knob bounds/baseline/damping, and the move history — plus the
enable/disable/reset verbs.  A fresh mgr is observe-only by
construction (`mgr_control_enable` defaults off): enabled false, zero
moves, an empty ledger.

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 tpu control dump
  {
    "abuser": "",
    "enabled": false,
    "knobs": {
      "client_lane_limit": {
        "baseline": null,
        "ceiling": 500.0,
        "cooldown": 0,
        "floor": 20.0,
        "moves": 0,
        "step_scale": 1.0,
        "value": null
      },
      "client_lane_weight": {
        "baseline": null,
        "ceiling": 100.0,
        "cooldown": 0,
        "floor": 0.05,
        "moves": 0,
        "step_scale": 1.0,
        "value": null
      },
      "ec_mesh_rateless_tasks": {
        "baseline": null,
        "ceiling": null,
        "cooldown": 0,
        "floor": null,
        "moves": 0,
        "step_scale": 1.0,
        "value": null
      },
      "osd_op_queue_admission_max": {
        "baseline": null,
        "ceiling": 4096,
        "cooldown": 0,
        "floor": 8,
        "moves": 0,
        "step_scale": 1.0,
        "value": 0.0
      },
      "osd_recovery_max_active": {
        "baseline": null,
        "ceiling": 64,
        "cooldown": 0,
        "floor": 1,
        "moves": 0,
        "step_scale": 1.0,
        "value": 8.0
      },
      "recovery_class_weight": {
        "baseline": null,
        "ceiling": 400.0,
        "cooldown": 0,
        "floor": 10.0,
        "moves": 0,
        "step_scale": 1.0,
        "value": 100.0
      }
    },
    "ledger": [],
    "moves_total": 0,
    "options": {
      "actuate_retries": 2,
      "bounds": "",
      "cooldown_ticks": 2,
      "damping": 0.5,
      "ledger_size": 128
    },
    "tick": 0
  }

`control enable` flips the master switch live (injectargs semantics —
the next mgr tick starts sensing); `control disable` also tears down
any open episode, restoring every engaged knob to its recorded
baseline before the controller goes quiet.

  $ ceph --cluster ck daemon osd.0 control enable
  {
    "enabled": true
  }
  $ ceph --cluster ck daemon osd.0 control disable
  {
    "enabled": false
  }

`control reset` is disable plus amnesia: baselines restored, ledger
and streak state cleared ("restored" counts the knobs walked back).

  $ ceph --cluster ck daemon osd.0 control reset
  {
    "reset": true,
    "restored": 0
  }
