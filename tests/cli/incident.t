Incident forensics admin CLI (ceph_tpu/mgr/incident + the event
journal in ceph_tpu/trace/journal): `tpu incident list|dump|capture`
and `journal dump|reset`.  A restored cluster starts with a clean
black box — zero archived bundles, empty per-daemon event rings, the
deterministic clock at zero (the journal never reads the wall clock).

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 tpu incident list
  {
    "captures_total": 0,
    "incidents": [],
    "retention": 16
  }

  $ ceph --cluster ck daemon osd.0 tpu incident dump
  {
    "incident": null
  }

  $ ceph --cluster ck daemon osd.0 journal dump
  {
    "clock": 0.0,
    "daemons": {},
    "gseq": 0
  }

`tpu incident capture` snapshots a bundle on operator demand — the
same payload a health-check raise captures automatically, minus the
raise (state "manual", reason "operator").  The receipt carries the
bundle id and the size of the timeline tail it archived.

  $ ceph --cluster ck daemon osd.0 tpu incident capture
  {
    "captured": true,
    "events": 0,
    "id": 1
  }

`journal reset` drops every daemon ring (sequence numbers stay
monotone for the process lifetime) and reports what it dropped.

  $ ceph --cluster ck daemon osd.0 journal reset
  {
    "dropped": 0
  }
