Device-flow profiler admin CLI (`ceph daemon <who> prof dump|reset`),
in the style of the reference's recorded src/test/cli transcripts: the
zeroed profile of a freshly restored cluster, an EC write's per-site
ledger, and the reset.

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 prof dump
  {
    "counters": {
      "compiles": 0,
      "d2h_bytes": 0,
      "d2h_transfers": 0,
      "device_mem_highwater_bytes": 0,
      "h2d_bytes": 0,
      "h2d_transfers": 0,
      "host_copies": 0,
      "host_copy_bytes": 0
    },
    "device_mem": {
      "bytes_in_use": \d+, (re)
      "highwater_bytes": \d+, (re)
      "peak_bytes_in_use": \d+, (re)
      "source": "live_arrays"
    },
    "sites": {},
    "totals": {
      "compiles": 0,
      "d2h_bytes": 0,
      "d2h_count": 0,
      "h2d_bytes": 0,
      "h2d_count": 0,
      "host_copies": 0,
      "host_copy_bytes": 0,
      "transfers": 0
    },
    "transfer_size_histogram": {
      "count": 0,
      "sum_bytes": 0.0
    }
  }

  $ ceph --cluster ck daemon osd.0 prof reset
  {
    "reset": true
  }

(The populated per-site table of a live EC write — stripe pad, device
round trip, shard slice-out, sub-op message build — is asserted
in-process by tests/test_devprof.py; booting an EC cluster inside a
cram subprocess would re-compile the encode kernel outside the shared
XLA cache and burn tier-1 wall budget for coverage that already
exists.)
