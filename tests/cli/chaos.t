Composed-chaos admin CLI (`ceph daemon <who> chaos dump|compose`),
in the style of the reference's recorded src/test/cli transcripts: the
engine pane of a restored cluster (leg catalog, fault-site inventory,
zeroed counters, option defaults pinned), a deterministic storyline
composed from seed=24, and the missing-seed refusal.  Same-seed
equality and the full run_scenario acceptance are covered in-process
by tests/test_chaos_composer.py.

  $ python -c "from ceph_tpu.cluster import MiniCluster; MiniCluster(n_osds=2).checkpoint('ck')"

  $ ceph --cluster ck daemon osd.0 chaos dump
  {
    "counters": {
      "accept_fail": 0,
      "accept_pass": 0,
      "active": 0,
      "checks_cleared": 0,
      "checks_raised": 0,
      "events": 0,
      "faults_armed": 0,
      "faults_cleared": 0,
      "legs": 0,
      "scenarios": 0,
      "wedges": 0
    },
    "fault_sites": {
      "control.actuate": "mgr control-plane config injection (ceph_tpu/control): a firing fails ONE knob actuation; the controller retries mgr_control_actuate_retries times within the tick, then drops the move and re-derives it next tick \u2014 context is '<knob>=<value> (<option>)' for match= scoping",
      "device.decode_batch": "batched EC decode/reconstruct device call (matrix_plugin.decode_batch)",
      "device.encode_batch": "batched EC encode device call (matrix_plugin.encode_batch)",
      "device.encode_chunks": "per-stripe encode device call (matrix_plugin.encode_chunks)",
      "dispatch.batch": "coalesced flush execution (scheduler._execute run_group) \u2014 exercises the per-request fallback isolation",
      "mesh.chip_fail": "hard per-chip failure mid-flush (ceph_tpu/mesh/rateless): the matching chip's coded blocks become erasures the subset completion re-solves around; context is 'chip=<i>/<mesh size>' for match= scoping, count= bounds the failed flushes",
      "mesh.chip_slowdown": "per-chip straggler injection (ceph_tpu/mesh/chipstat): delays the matching chip's probe readback by delay_us; context is 'chip=<i>/<mesh size>' so match='chip=3/' scopes one chip",
      "mesh.decode_batch": "mesh-sharded decode/reconstruct/repair execution (ceph_tpu/mesh runtime decode_stacked) \u2014 exhaustion degrades the group to the single-device path and journals mesh_decode_degraded",
      "mesh.encode_batch": "mesh-sharded flush execution (ceph_tpu/mesh runtime) \u2014 exhaustion degrades the flush to the single-device path",
      "mgr.incident_capture": "incident bundle snapshot on a health-check raise (ceph_tpu/mgr/incident): a firing drops that bundle \u2014 the raise is journaled, the tick proceeds, and the NEXT raise captures normally; context is the triggering check name",
      "msg.drop": "drop a fabric message (ms inject socket failures role); context is '<MsgType> <src>><dst>' for match= scoping",
      "osd.shard_read_eio": "shard-side EC read returns EIO (bluestore_debug_inject_read_err role) \u2014 the primary must reconstruct from surviving shards",
      "recovery.helper_fetch": "helper-side repair contribution read (handle_sub_read) \u2014 a dropped helper fails the round and the orchestrator falls back to full-stripe decode",
      "recovery.repair_read": "sub-chunk repair round start (recovery scheduler) \u2014 firing degrades the repair to the full-stripe decode path",
      "store.shard_corrupt": "flip one byte of a stored shard body at read time (memstore) \u2014 the shard-side crc32c verify must catch it and return EIO, whether the body is host bytes or a device-resident handle; context is '<coll>/<oid>' for match= scoping",
      "tpu.decode_batch_device": "device-resident decode entry point (tpu_plugin, mesh/bench)",
      "tpu.encode_batch_device": "device-resident encode entry point (tpu_plugin, mesh/bench)"
    },
    "legs": [
      "abusive_client",
      "capture_drop",
      "chip_fail",
      "chip_straggler",
      "control_flap",
      "degraded_read_straggler",
      "device_error",
      "mesh_membership",
      "msg_drop",
      "recovery_storm",
      "shard_eio"
    ],
    "options": {
      "chaos_settle_ticks_max": 64,
      "chaos_storyline_legs_max": 3
    }
  }

The composer is a pure function of the seed: the same seed always
yields this exact storyline (arm/clear rounds on the deterministic
cluster clock, expected health checks, journal shape) — seed=24 is one
of the two pinned tier-1 smoke seeds.

  $ ceph --cluster ck daemon osd.0 chaos compose seed=24
  {
    "events": [
      {
        "action": "fault_arm",
        "count": 2,
        "match": "chip=3/",
        "mode": "always",
        "round": 1,
        "site": "mesh.chip_fail"
      },
      {
        "action": "fault_arm",
        "mode": "nth",
        "n": 5,
        "round": 3,
        "site": "device.encode_batch"
      },
      {
        "action": "osd_kill",
        "osd": 0,
        "round": 3
      },
      {
        "action": "osd_out",
        "osd": 0,
        "round": 4
      },
      {
        "action": "fault_clear",
        "round": 6,
        "site": "mesh.chip_fail"
      },
      {
        "action": "fault_clear",
        "round": 7,
        "site": "device.encode_batch"
      },
      {
        "action": "osd_revive",
        "osd": 0,
        "round": 11
      },
      {
        "action": "osd_in",
        "osd": 0,
        "round": 12
      }
    ],
    "expected_checks": [],
    "journal_expect": [
      "fault_arm",
      "fault_clear",
      "fault_fire",
      "osd_down",
      "osd_in",
      "osd_out"
    ],
    "legs": [
      "chip_fail",
      "device_error",
      "recovery_storm"
    ],
    "rate_multipliers": [],
    "seed": 24,
    "settle_clears": [],
    "tolerates_missing_bundle": false
  }

  $ ceph --cluster ck daemon osd.0 chaos compose
  admin socket: chaos compose requires seed=<int>
  [1]
