"""cephfs-lite: tree ops, file I/O, rename semantics, purge, EC data.

Mirrors the reference's libcephfs/client test surface at lite scale
(src/test/libcephfs): path resolution, mkdir/rmdir guards, striped
sparse file I/O, truncate, unlink purging data objects, rename within
and across directories, symlinks, and the reference-identical object
naming so the layout is inspectable with rados tools.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.cephfs import CephFS, FsError, ROOT_INO, dir_oid, file_oid

ORDER = 12
OBJ = 1 << ORDER


@pytest.fixture()
def fs():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("fsmeta", size=3, pg_num=8)
    c.create_replicated_pool("fsdata", size=3, pg_num=8)
    cl = c.client("client.fs")
    f = CephFS(cl, "fsmeta", "fsdata")
    f.mkfs()
    return c, cl, f


def test_tree_and_listing(fs):
    c, cl, f = fs
    f.mkdir("/a")
    f.mkdir("/a/b")
    f.create("/a/b/file", ORDER)
    f.mkdir("/c")
    assert sorted(f.listdir("/")) == ["a", "c"]
    assert sorted(f.listdir("/a")) == ["b"]
    assert f.stat("/a")["type"] == "dir"
    assert f.stat("/a/b/file")["type"] == "file"
    assert f.exists("/a/b/file") and not f.exists("/a/nope")
    with pytest.raises(FsError):
        f.mkdir("/a")                        # EEXIST via cls link
    with pytest.raises(FsError):
        f.listdir("/a/b/file")               # ENOTDIR
    walked = list(f.walk("/"))
    assert walked[0] == ("/", ["a", "c"], [])
    assert ("/a/b", [], ["file"]) in walked


def test_file_io_striping_sparse(fs):
    c, cl, f = fs
    f.create("/data", ORDER)
    payload = bytes(range(256)) * ((2 * OBJ + 700) // 256)
    f.write("/data", payload, offset=OBJ // 2)
    assert f.stat("/data")["size"] == OBJ // 2 + len(payload)
    assert f.read("/data", OBJ // 2, len(payload)) == payload
    # the hole before the write reads as zeros
    assert f.read("/data", 0, 100) == b"\x00" * 100
    # reference-identical data object naming in the data pool
    ino = f.stat("/data")["ino"]
    assert cl.read("fsdata", file_oid(ino, 1))    # object 1 exists
    # read past EOF clips
    size = f.stat("/data")["size"]
    assert f.read("/data", size - 5) == payload[-5:]
    assert f.read("/data", size + 10) == b""


def test_truncate_and_unlink_purge(fs):
    c, cl, f = fs
    f.create("/f", ORDER)
    f.write("/f", b"Z" * (3 * OBJ))
    f.truncate("/f", OBJ + 10)
    assert f.stat("/f")["size"] == OBJ + 10
    assert f.read("/f") == b"Z" * (OBJ + 10)
    f.write("/f", b"Z" * (3 * OBJ))           # regrow
    ino = f.stat("/f")["ino"]
    f.unlink("/f")
    assert not f.exists("/f")
    # purge removed the data objects (PurgeQueue role)
    for objno in range(3):
        with pytest.raises(IOError):
            cl.read("fsdata", file_oid(ino, objno))


def test_rmdir_guards(fs):
    c, cl, f = fs
    f.mkdir("/d")
    f.create("/d/x", ORDER)
    with pytest.raises(FsError):
        f.rmdir("/d")                        # ENOTEMPTY
    f.unlink("/d/x")
    f.rmdir("/d")
    assert not f.exists("/d")
    with pytest.raises(FsError):
        f.rmdir("/nope")


def test_rename_same_and_cross_dir(fs):
    c, cl, f = fs
    f.mkdir("/a")
    f.mkdir("/b")
    f.create("/a/src", ORDER)
    f.write("/a/src", b"payload")
    f.rename("/a/src", "/a/dst")             # same dir: one cls call
    assert not f.exists("/a/src")
    assert f.read("/a/dst") == b"payload"
    f.rename("/a/dst", "/b/moved")           # cross dir
    assert not f.exists("/a/dst")
    assert f.read("/b/moved") == b"payload"
    # rename over an existing file replaces it and purges the old data
    f.create("/b/victim", ORDER)
    f.write("/b/victim", b"to-be-replaced" * 400)
    victim_ino = f.stat("/b/victim")["ino"]
    f.rename("/b/moved", "/b/victim")
    assert f.read("/b/victim") == b"payload"
    with pytest.raises(IOError):
        cl.read("fsdata", file_oid(victim_ino, 0))


def test_unlink_and_rename_refuse_directories(fs):
    """unlink(2)/rename(2) must never silently destroy a subtree: the
    guards live server-side in the dentry's cls methods."""
    c, cl, f = fs
    f.mkdir("/d")
    f.create("/d/child", ORDER)
    with pytest.raises(FsError) as ei:
        f.unlink("/d")
    assert ei.value.result == -21                    # EISDIR
    assert f.exists("/d/child")
    f.create("/plain", ORDER)
    with pytest.raises(FsError) as ei:
        f.rename("/plain", "/d")                     # same-dir replace
    assert ei.value.result == -21
    f.mkdir("/other")
    f.mkdir("/other/dir2")
    with pytest.raises(FsError) as ei:
        f.rename("/plain", "/other/dir2")            # cross-dir replace
    assert ei.value.result == -21
    assert f.exists("/d/child") and f.stat("/other/dir2")["type"] == "dir"


def test_concurrent_size_growth_never_shrinks(fs):
    """Two clients with stale size views: the server-side size max
    keeps the larger committed size (no client RMW window)."""
    c, cl, f = fs
    cl2 = c.client("client.fs2")
    f2 = CephFS(cl2, "fsmeta", "fsdata")
    f.create("/grow", ORDER)
    f.write("/grow", b"A" * 4096)        # size 4096
    f2.write("/grow", b"B" * 100)        # stale writer, smaller extent
    assert f.stat("/grow")["size"] == 4096
    data = f.read("/grow")
    assert data[:100] == b"B" * 100 and data[100:] == b"A" * 3996


def test_relative_symlink(fs):
    c, cl, f = fs
    f.mkdir("/sd")
    f.create("/sd/t", ORDER)
    f.write("/sd/t", b"relative-ok")
    f.symlink("/sd/l", "t")              # relative target
    assert f.read("/sd/l") == b"relative-ok"
    # symlink loops fail ELOOP instead of recursing forever
    f.symlink("/loop1", "/loop2")
    f.symlink("/loop2", "/loop1")
    with pytest.raises(FsError) as ei:
        f.read("/loop1")
    assert ei.value.result == -40


def test_rename_identity_and_cycle_guards(fs):
    """rename(p, p) is a no-op; moving a dir into its own subtree is
    EINVAL — both would otherwise detach data forever."""
    c, cl, f = fs
    f.create("/x", ORDER)
    f.write("/x", b"survives")
    f.rename("/x", "/x")
    assert f.read("/x") == b"survives"
    f.mkdir("/d")
    f.mkdir("/d/sub")
    f.create("/d/sub/keep", ORDER)
    with pytest.raises(FsError) as ei:
        f.rename("/d", "/d/sub/trap")
    assert ei.value.result == -22
    assert f.exists("/d/sub/keep")
    with pytest.raises(FsError):
        f.rename("/missing", "/missing")     # still ENOENT
    # a symlink into the source subtree cannot smuggle the cycle past
    # the guard (inode-resolved ancestry, not path strings)
    f.symlink("/s", "/d")
    with pytest.raises(FsError) as ei:
        f.rename("/d", "/s/trap")
    assert ei.value.result == -22
    assert f.exists("/d/sub/keep")


def test_intermediate_symlink_resolution(fs):
    """Paths THROUGH a directory symlink resolve like the kernel
    client's walk; final-component stat stays lstat-shaped."""
    c, cl, f = fs
    f.mkdir("/real")
    f.create("/real/t", ORDER)
    f.write("/real/t", b"via-dir-link")
    f.symlink("/ld", "/real")
    assert f.read("/ld/t") == b"via-dir-link"
    assert sorted(f.listdir("/ld")) == ["t"]
    f.write("/ld/t", b"written-thru")
    assert f.read("/real/t") == b"written-thru"
    f.create("/ld/new", ORDER)               # create through the link
    assert f.exists("/real/new")
    assert f.stat("/ld")["type"] == "symlink"  # lstat semantics
    # relative dir symlink in the middle of a path
    f.mkdir("/real/deep")
    f.create("/real/deep/f", ORDER)
    f.symlink("/real/shortcut", "deep")
    assert f.exists("/real/shortcut/f")


def test_symlink(fs):
    c, cl, f = fs
    f.mkdir("/real")
    f.create("/real/target", ORDER)
    f.write("/real/target", b"through-the-link")
    f.symlink("/lnk", "/real/target")
    assert f.readlink("/lnk") == "/real/target"
    assert f.read("/lnk") == b"through-the-link"
    f.write("/lnk", b"WRITTEN", offset=0)
    assert f.read("/real/target")[:7] == b"WRITTEN"


def test_concurrent_create_one_winner(fs):
    """Two clients racing to create the same name: the dir object's PG
    orders the cls link calls — exactly one wins (the MDS-lock role)."""
    c, cl, f = fs
    cl2 = c.client("client.fs2")
    f2 = CephFS(cl2, "fsmeta", "fsdata")
    f.create("/winner", ORDER)
    with pytest.raises(FsError):
        f2.create("/winner", ORDER)
    # and the loser's error is EEXIST specifically
    try:
        f2.mkdir("/winner")
    except FsError as e:
        assert e.result == -17


def test_checkpoint_restore(fs, tmp_path):
    c, cl, f = fs
    f.mkdir("/keep")
    f.create("/keep/file", ORDER)
    f.write("/keep/file", b"persistent-bytes")
    c.checkpoint(str(tmp_path / "ckpt"))
    c2 = MiniCluster.restore(str(tmp_path / "ckpt"))
    f2 = CephFS(c2.client("client.r"), "fsmeta", "fsdata")
    assert f2.read("/keep/file") == b"persistent-bytes"
    assert sorted(f2.listdir("/")) == ["keep"]
    # ino allocation continues past the restored watermark
    f2.create("/keep/new", ORDER)
    inos = {f2.stat(p)["ino"] for p in ("/keep/file", "/keep/new")}
    assert len(inos) == 2


def test_ec_data_pool(fs):
    """File data on an EC pool, metadata replicated — the cephfs
    add_data_pool layout (EC pools hold file data, never dir omaps)."""
    c, cl, f = fs
    c.create_ec_pool("fsec", k=2, m=1, plugin="isa", pg_num=8)
    fec = CephFS(cl, "fsmeta", "fsec")
    fec.create("/ecfile", ORDER)
    fec.write("/ecfile", b"ec-file-data" * 50)
    assert fec.read("/ecfile") == b"ec-file-data" * 50
    ino = fec.stat("/ecfile")["ino"]
    assert cl.read("fsec", file_oid(ino, 0), length=12) == b"ec-file-data"


def test_setattr_chmod_chown(fs):
    """Mode/ownership attributes with server-side merge (the MDS
    setattr flow); hard links share them through the primary."""
    c, cl, f = fs
    f.create("/f", ORDER)
    st = f.stat("/f")
    assert st["mode"] == 0o644 and st["uid"] == 0
    f.chmod("/f", 0o600)
    f.chown("/f", 1000, 100)
    st = f.stat("/f")
    assert (st["mode"], st["uid"], st["gid"]) == (0o600, 1000, 100)
    f.mkdir("/d")
    assert f.stat("/d")["mode"] == 0o755
    # attrs travel with hard links (one inode)
    f.hardlink("/f", "/link")
    f.chmod("/link", 0o400)
    assert f.stat("/f")["mode"] == 0o400
    # setattr merges: concurrent-style partial updates keep other fields
    f.setattr("/f", mtime=12345.0)
    st = f.stat("/f")
    assert st["mode"] == 0o400 and st["mtime"] == 12345.0
    # CLI verbs
    from ceph_tpu.tools import cephfs_cli
    assert cephfs_cli.run(c, cl, ["chmod", "755", "/f"]) == 0
    assert f.stat("/f")["mode"] == 0o755
    assert cephfs_cli.run(c, cl, ["chown", "5:6", "/f"]) == 0
    assert f.stat("/f")["uid"] == 5
    # chmod THROUGH a symlink affects the target (chmod(2) follows)
    f.symlink("/sym", "/f")
    f.chmod("/sym", 0o640)
    assert f.stat("/f")["mode"] == 0o640
    assert f.stat("/sym")["type"] == "symlink"   # link untouched
    # root setattr refused with a clear error, no-op setattr is free
    with pytest.raises(FsError) as ei:
        f.chmod("/", 0o700)
    assert ei.value.result == -95
    assert f.setattr("/f")["mode"] == 0o640


def test_rmdir_seal_survives_object_deletion(fs):
    """After rmdir deletes the sealed dir object, a racing create that
    already resolved the child ino calls 'link' on the now-missing
    object.  WR cls methods implicitly recreate objects, so without the
    ctx.exists guard this resurrects the directory with an orphaned
    dentry fsck's root walk can never reach — the seal must keep
    holding after deletion."""
    c, cl, f = fs
    f.mkdir("/d")
    ino = f._resolve("/d")["ino"]
    f.rmdir("/d")
    # the racing create's link: must fail ENOENT, not recreate
    with pytest.raises(FsError) as ei:
        f._call(dir_oid(ino), "link", {"name": "orphan", "inode": {
            "ino": 999, "type": "file", "size": 0, "order": 22,
            "mode": 0o644, "uid": 0, "gid": 0, "mtime": 0.0}})
    assert ei.value.result == -2
    # the object stayed deleted (no resurrection), and the tree is clean
    with pytest.raises(IOError):
        cl.stat("fsmeta", dir_oid(ino))
    report = f.fsck()
    assert not any(report.values()), report
