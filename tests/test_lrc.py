"""LRC plugin: kml generation, layered encode/decode, local repair.

Mirrors the reference's TestErasureCodeLrc.cc behaviors: parse_kml layer
generation, minimum_to_decode preferring local layers, layered decode
walking upward, and full encode/decode roundtrips under erasure sweeps.
"""
import json

import numpy as np
import pytest

from ceph_tpu.ec import plugin_registry


def make_kml(k=4, m=2, l=3):
    return plugin_registry.factory("lrc", {
        "plugin": "lrc", "k": str(k), "m": str(m), "l": str(l)})


def payload(n=4096, seed=5):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()


def test_kml_generates_mapping_and_layers():
    codec = make_kml(4, 2, 3)
    # (k+m)/l = 2 groups; mapping DD__ per group (ErasureCodeLrc.cc:346-352)
    assert codec.get_chunk_count() == 8
    assert codec.get_data_chunk_count() == 4
    assert len(codec.layers) == 3  # one global + two local
    assert codec.layers[0].chunks_map == "DDc_DDc_"
    assert codec.layers[1].chunks_map == "DDDc____"
    assert codec.layers[2].chunks_map == "____DDDc"


def test_kml_validation():
    with pytest.raises(ValueError):
        make_kml(4, 2, 4)   # k+m not a multiple of l
    with pytest.raises(ValueError):
        plugin_registry.factory("lrc", {"k": "4", "m": "2"})  # l missing
    with pytest.raises(ValueError):
        plugin_registry.factory(
            "lrc", {"k": "4", "m": "2", "l": "3", "layers": "[]"})


def test_explicit_layers_profile():
    layers = json.dumps([["DDc", ""]])
    codec = plugin_registry.factory(
        "lrc", {"mapping": "DD_", "layers": layers})
    assert codec.get_chunk_count() == 3
    assert codec.get_data_chunk_count() == 2
    data = payload(1000)
    enc = codec.encode(set(range(3)), data)
    assert len(enc) == 3
    # xor-style single parity from the delegated RS layer: lose any one
    for lost in range(3):
        have = {i: enc[i] for i in range(3) if i != lost}
        assert codec.decode_concat(have)[:len(data)] == data


def test_roundtrip_no_erasure():
    codec = make_kml()
    data = payload()
    enc = codec.encode(set(range(8)), data)
    assert codec.decode_concat(enc)[:len(data)] == data


@pytest.mark.parametrize("lost", range(8))
def test_single_erasure_recovery(lost):
    codec = make_kml()
    data = payload()
    enc = codec.encode(set(range(8)), data)
    have = {i: enc[i] for i in range(8) if i != lost}
    assert codec.decode_concat(have)[:len(data)] == data


def test_double_erasure_same_group_uses_global():
    codec = make_kml()
    data = payload()
    enc = codec.encode(set(range(8)), data)
    # 0 and 1 are both in local group 0: local parity alone cannot fix
    have = {i: enc[i] for i in range(8) if i not in (0, 1)}
    assert codec.decode_concat(have)[:len(data)] == data


def test_minimum_to_decode_prefers_local_layer():
    codec = make_kml()
    # chunk 0 lost; local group 0 is chunks {0,1,2,3} with parity at 3
    minimum = codec.minimum_to_decode({0}, set(range(1, 8)))
    assert set(minimum) == {1, 2, 3}


def test_minimum_to_decode_no_erasure_is_want():
    codec = make_kml()
    assert set(codec.minimum_to_decode({0, 5}, set(range(8)))) == {0, 5}


def test_minimum_to_decode_impossible_raises():
    codec = make_kml()
    with pytest.raises(IOError):
        codec.minimum_to_decode({0}, {4, 5, 6, 7})


def test_chunk_size_stripes():
    codec = make_kml()
    cs = codec.get_chunk_size(4096)
    assert cs * codec.get_data_chunk_count() >= 4096


def test_create_rule_indep_steps():
    from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    ids = []
    for h in range(9):
        osds = [h * 2, h * 2 + 1]
        ids.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds,
                                 [0x10000] * 2, id=-(h + 2)))
    cw.set_max_devices(18)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", ids,
                  [0x20000] * 9, id=-1)
    codec = make_kml()
    rno = codec.create_rule("lrc_rule", cw)
    assert rno >= 0
    out = cw.do_rule(rno, 42, 8, [0x10000] * 18)
    assert len(out) == 8


def test_device_backend_byte_identical():
    """VERDICT #7: the layered code wired through the device backend —
    every layer's encode/decode runs the MXU bit-matmul path — must be
    byte-identical to the host path, including the batched ECUtil entry
    points (encode_batch_full / decode_batch)."""
    import numpy as np
    host = plugin_registry.factory("lrc", {
        "plugin": "lrc", "k": "4", "m": "2", "l": "3", "backend": "host"})
    dev = plugin_registry.factory("lrc", {
        "plugin": "lrc", "k": "4", "m": "2", "l": "3", "backend": "tpu"})
    # every layer delegate inherited the backend
    assert all(l.erasure_code.backend_name == "tpu" for l in dev.layers)
    data = payload(20000, seed=77)
    n = host.get_chunk_count()
    eh = host.encode(set(range(n)), data)
    ed = dev.encode(set(range(n)), data)
    for i in range(n):
        np.testing.assert_array_equal(eh[i], ed[i], err_msg=f"chunk {i}")
    # erasure decode parity (local + global repair)
    for gone in ([0], [1, 4], [2, 6]):
        have = {i: ed[i] for i in range(n) if i not in gone}
        dh = host.decode(set(gone), {i: eh[i] for i in have})
        dd = dev.decode(set(gone), have)
        for i in gone:
            np.testing.assert_array_equal(dh[i], dd[i])
    # batched paths through ECUtil striping
    from ceph_tpu.osd.ecutil import stripe_info_t, encode as ec_encode, \
        decode_concat as ec_decode_concat
    k = host.get_data_chunk_count()
    w = host.get_chunk_size(1) * k
    sinfo = stripe_info_t(k, w)
    buf = np.frombuffer(data, dtype=np.uint8)
    buf = np.concatenate([buf, np.zeros((-len(buf)) % w, np.uint8)])
    sh = ec_encode(sinfo, host, buf, set(range(n)))
    sd = ec_encode(sinfo, dev, buf, set(range(n)))
    for i in range(n):
        np.testing.assert_array_equal(sh[i], sd[i], err_msg=f"shard {i}")
    # degraded batched read (decode_batch path)
    avail = {i: sd[i] for i in range(n) if i not in (0, 5)}
    out = ec_decode_concat(sinfo, dev, avail)
    np.testing.assert_array_equal(out, buf)
