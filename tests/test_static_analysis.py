"""The repo-wide invariant analyzer (ceph_tpu/analysis).

Two layers, mirroring how the reference treats lockdep/lints as
first-class qa infrastructure:

1. **the catalog is live** — every rule is proven by a seeded-violation
   snippet it MUST flag next to a clean twin it MUST NOT (a lint that
   never fires is indistinguishable from no lint);
2. **the tree is clean** — the full ``ceph_tpu/`` pass runs here in
   tier-1 and fails the suite on any violation, which is the
   whole-tree static guarantee the per-PR conventions graduate into.
"""
import json
import os
import subprocess
import sys

import pytest

from ceph_tpu.analysis import run_analysis
from ceph_tpu.analysis.core import REPO_ROOT, AnalysisContext
from ceph_tpu.analysis.rules import (
    ALL_RULES, OPTIONS_DOC_ALLOW, JitCacheHygieneRule, NoBareLockRule,
    NoUntrackedSyncRule, NoWallClockRule, NoWireDriftRule,
    OptionsDocCoverageRule, collect_wire_fields, load_wire_manifest,
    rule_by_id,
)


def _check(rule, source, relpath="dispatch/snippet.py"):
    ctx = AnalysisContext(os.path.join(REPO_ROOT, "ceph_tpu", relpath),
                          source=source, relpath=relpath)
    return rule.run(ctx)


# ---------------------------------------------------------------------------
# per-rule seeded-violation fixtures + clean twins
# ---------------------------------------------------------------------------

def test_no_bare_lock_fires_and_clean_twin_passes():
    rule = NoBareLockRule()
    seeded = "import threading\nlock = threading.Lock()\n"
    assert [v.line for v in _check(rule, seeded)] == [2]
    seeded_r = "import threading\nlock = threading.RLock()\n"
    assert len(_check(rule, seeded_r)) == 1
    seeded_c = "import threading\ncv = threading.Condition()\n"
    assert len(_check(rule, seeded_c)) == 1
    clean = ("from ceph_tpu.common.lockdep import DebugLock\n"
             'lock = DebugLock("Snippet::lock")\n')
    assert _check(rule, clean) == []
    # a Condition wrapping a named lock is fine
    clean_c = ("import threading\n"
               "from ceph_tpu.common.lockdep import DebugLock\n"
               'cv = threading.Condition(DebugLock("S::l"))\n')
    assert _check(rule, clean_c) == []


def test_no_bare_lock_allows_lockdep_internals():
    rule = NoBareLockRule()
    src = "import threading\nlock = threading.Lock()\n"
    assert _check(rule, src, relpath="common/lockdep.py") == []


def test_no_untracked_sync_fires_and_clean_twin_passes():
    rule = NoUntrackedSyncRule()
    seeded = ("import jax\n"
              "def f(x):\n"
              "    return jax.block_until_ready(x)\n")
    assert [v.line for v in _check(rule, seeded)] == [3]
    # method-form sync and device_get too
    assert len(_check(rule, "def f(x):\n    x.block_until_ready()\n")) == 1
    assert len(_check(rule, "import jax\n"
                            "def f(x):\n"
                            "    return jax.device_get(x)\n")) == 1
    # np.asarray only suspect in a jax-importing (device-facing) module
    hidden = ("import jax\nimport numpy as np\n"
              "def fetch(dev):\n"
              "    return np.asarray(dev)\n")
    assert len(_check(rule, hidden)) == 1
    host_only = ("import numpy as np\n"
                 "def pack(xs):\n"
                 "    return np.asarray(xs)\n")
    assert _check(rule, host_only) == []
    # allowlisted call-site module: same source, zero violations
    assert _check(rule, hidden, relpath="ops/snippet.py") == []


def test_no_wall_clock_fires_and_clean_twin_passes():
    rule = NoWallClockRule()
    seeded = ("import time\n"
              "def tick_self():\n"
              "    return time.monotonic()\n")
    assert [v.line for v in _check(rule, seeded,
                                   relpath="mon/snippet.py")] == [3]
    assert len(_check(rule, "import time\nt = time.time()\n",
                      relpath="osd/snippet.py")) == 1
    assert len(_check(rule, "import datetime\n"
                            "t = datetime.datetime.now()\n",
                      relpath="msg/snippet.py")) == 1
    # tick-parameter twin is clean
    clean = "def tick(now):\n    return now + 1.0\n"
    assert _check(rule, clean, relpath="mon/snippet.py") == []
    # outside the fabric the rule does not apply at all
    assert _check(rule, seeded, relpath="tools/snippet.py") == []
    # the real-socket transport is module-allowlisted
    assert _check(rule, seeded, relpath="msg/tcp.py") == []


def test_jit_cache_hygiene_fires_and_clean_twin_passes():
    rule = JitCacheHygieneRule()
    seeded = ("import jax\n"
              "def hot_path(x):\n"
              "    return jax.jit(lambda a: a + 1)(x)\n")
    assert [v.line for v in _check(rule, seeded)] == [3]
    # nested decorator leaks a fresh trace per call
    seeded_dec = ("import jax\n"
                  "def hot(x):\n"
                  "    @jax.jit\n"
                  "    def k(a):\n"
                  "        return a\n"
                  "    return k(x)\n")
    assert len(_check(rule, seeded_dec)) == 1
    # clean twins: module level, __init__, recognized builder,
    # memoized self-attribute assign
    for clean in (
        "import jax\nf = jax.jit(lambda a: a)\n",
        ("import jax\n"
         "class C:\n"
         "    def __init__(self):\n"
         "        self._f = jax.jit(lambda a: a)\n"),
        ("import jax\n"
         "class C:\n"
         "    def _encode_jit(self):\n"
         "        return jax.jit(lambda a: a)\n"),
        ("import jax\n"
         "class C:\n"
         "    def encode(self, x):\n"
         "        fn = self._fn = jax.jit(lambda a: a)\n"
         "        return fn(x)\n"),
    ):
        assert _check(rule, clean) == [], clean


def test_pragma_suppresses_exactly_the_named_rule():
    rule = NoBareLockRule()
    src = ("import threading\n"
           "lock = threading.Lock()  # lint: allow[no-bare-lock]\n")
    assert _check(rule, src) == []
    # pragma on the line above works too
    src2 = ("import threading\n"
            "# lint: allow[no-bare-lock]\n"
            "lock = threading.Lock()\n")
    assert _check(rule, src2) == []
    # a pragma for a DIFFERENT rule does not suppress
    src3 = ("import threading\n"
            "lock = threading.Lock()  # lint: allow[no-wall-clock]\n")
    assert len(_check(rule, src3)) == 1


# ---------------------------------------------------------------------------
# no-wire-drift: manifest pinning
# ---------------------------------------------------------------------------

def _messages_source():
    with open(os.path.join(REPO_ROOT, "ceph_tpu", "msg",
                           "messages.py")) as f:
        return f.read()


def test_wire_manifest_matches_tree():
    rule = NoWireDriftRule()
    assert _check(rule, _messages_source(),
                  relpath="msg/messages.py") == []


def test_wire_drift_new_field_is_flagged():
    rule = NoWireDriftRule()
    src = _messages_source()
    # seed a drift: graft one extra dataclass field onto MOSDPing
    drifted = src.replace(
        "class MOSDPing(Message):",
        "class MOSDPing(Message):\n    sneaky_new_field: int = 0", 1)
    assert drifted != src
    viol = _check(rule, drifted, relpath="msg/messages.py")
    assert any("MOSDPing.sneaky_new_field" in v.message for v in viol)


def test_wire_drift_removed_class_is_flagged():
    rule = NoWireDriftRule()
    src = _messages_source().replace("class MOSDPing(Message):",
                                     "class MOSDPingRenamed(Message):", 1)
    viol = _check(rule, src, relpath="msg/messages.py")
    msgs = "\n".join(v.message for v in viol)
    assert "MOSDPing" in msgs and "disappeared" in msgs


def test_wire_manifest_covers_every_message_class():
    """The checked-in manifest and the AST collector agree on the
    class inventory — and the collector really walks subclass chains
    (MOSDOp etc. inherit Message transitively)."""
    import ast
    src = _messages_source()
    fields = collect_wire_fields(ast.parse(src))
    manifest = load_wire_manifest()
    assert set(fields) == set(manifest)
    assert "MOSDOp" in fields and "Message" in fields
    assert "trace_id" in manifest["Message"]


# ---------------------------------------------------------------------------
# options-doc-coverage
# ---------------------------------------------------------------------------

def test_options_doc_rule_fires_on_undocumented_option():
    rule = OptionsDocCoverageRule()
    src = ('Option = object\n'
           'opts = [Option("zz_surely_undocumented_option_xq")]\n')
    viol = _check(rule, src, relpath="common/config.py")
    assert len(viol) == 1
    assert "zz_surely_undocumented_option_xq" in viol[0].message
    # a documented one passes (mgr_slo_* live in OBSERVABILITY.md)
    src2 = 'Option = object\nopts = [Option("mgr_slo_fast_window_s")]\n'
    assert _check(rule, src2, relpath="common/config.py") == []


def test_options_allowlist_is_closed():
    """The one-time allowlist for pre-existing gaps is EMPTY: every
    currently-registered option is documented, so any future entry
    would be a new option dodging docs — exactly what the rule
    forbids."""
    assert OPTIONS_DOC_ALLOW == set()


def test_every_runtime_option_is_seen_statically():
    """Guard the AST enumeration: every literally-registered runtime
    option in g_conf.schema must be found by the same string scan the
    rule uses (the generated debug_<subsys> family excepted)."""
    import ast

    from ceph_tpu.common.config import g_conf
    with open(os.path.join(REPO_ROOT, "ceph_tpu", "common",
                           "config.py")) as f:
        tree = ast.parse(f.read())
    static = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                getattr(node.func, "id", "") == "Option" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                static.add(a.value)
    runtime = {n for n in g_conf.schema if not n.startswith("debug_")}
    missing = runtime - static
    assert not missing, f"options invisible to the lint: {missing}"


# ---------------------------------------------------------------------------
# the whole-tree pass (the tier-1 gate) + runner UX
# ---------------------------------------------------------------------------

def test_full_tree_is_clean():
    """THE gate: zero violations across ceph_tpu/ — every contract in
    the catalog holds tree-wide, not just where a runtime test
    samples it."""
    viol = run_analysis()
    assert viol == [], "\n" + "\n".join(str(v) for v in viol)


def test_rule_ids_unique_and_resolvable():
    ids = [cls.id for cls in ALL_RULES]
    assert len(ids) == len(set(ids))
    for i in ids:
        assert rule_by_id(i).id == i
    with pytest.raises(KeyError):
        rule_by_id("nonsense-rule")


def test_cli_json_and_exit_codes(tmp_path):
    """The module runner: --json on a seeded-violation file exits 1
    with machine-readable output; --rule filters."""
    bad = tmp_path / "bad.py"
    bad.write_text("import threading\nlock = threading.Lock()\n")
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis", "--json",
         "--rule", "no-bare-lock", str(bad)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 1, proc.stderr
    data = json.loads(proc.stdout)
    assert len(data) == 1 and data[0]["rule"] == "no-bare-lock"
    # clean file -> exit 0
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    proc = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.analysis", str(good)],
        capture_output=True, text=True, cwd=REPO_ROOT, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_from_import_aliases_cannot_evade_rules():
    """`from threading import Lock` / `import threading as th` /
    `from time import monotonic` resolve to the same canonical names
    the rules match — the obvious evasions are closed."""
    rule = NoBareLockRule()
    assert len(_check(rule, "from threading import Lock\n"
                            "x = Lock()\n")) == 1
    assert len(_check(rule, "import threading as th\n"
                            "x = th.RLock()\n")) == 1
    wall = NoWallClockRule()
    assert len(_check(wall, "from time import monotonic\n"
                            "t = monotonic()\n",
                      relpath="mon/snippet.py")) == 1
    # numpy from-import in a device-facing module
    sync = NoUntrackedSyncRule()
    assert len(_check(sync, "import jax\n"
                            "from numpy import asarray\n"
                            "def f(d):\n"
                            "    return asarray(d)\n")) == 1
