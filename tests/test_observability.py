"""Observability layer: spans, perf histograms, flight recorder.

Tier-1 smoke coverage for the trace/ package (the runtime-side
counterpart of the bench subsystem's rigor): the zero-sync contract of
the default-off path, the cross-daemon span tree a slow op preserves
(client -> OSD -> EC encode -> device drain), and the admin-socket
export surfaces (`perf histogram dump`, `dump_tracing`,
`dump_historic_slow_ops`).
"""
import pytest

from ceph_tpu.common import g_kernel_timer
from ceph_tpu.common.config import g_conf
from ceph_tpu.trace import (
    PerfHistogram, PerfHistogramAxis, SCALE_LINEAR, build_tree,
    g_flight_recorder, g_perf_histograms, g_tracer, latency_in_bytes_axes,
)


@pytest.fixture
def clean_tracing():
    """Every test leaves the process-global observability state as it
    found it (tracer off, kernel timer off, default complaint time)."""
    yield
    g_tracer.enable(False)
    g_tracer.collector.clear()
    g_kernel_timer.enable(False)
    g_kernel_timer.reset()
    g_flight_recorder.clear()
    g_conf.rm_val("op_complaint_time")
    g_conf.rm_val("tracing_spans")
    g_conf.rm_val("ec_dispatch_batch_window_us")
    g_conf.rm_val("ec_dispatch_batch_max")


# ---- span primitives -------------------------------------------------------
def test_spans_disabled_are_free(clean_tracing):
    assert g_tracer.begin("x") is None
    with g_tracer.span("y") as sp:
        assert sp is None
    assert g_tracer.collector.dump() == {}


def test_span_parent_inheritance_and_tree(clean_tracing):
    g_tracer.enable()
    with g_tracer.span("root", daemon="a", trace_id=7) as root:
        with g_tracer.span("child") as child:
            # parent + trace inherit from the activated span
            assert child.parent_span_id == root.span_id
            assert child.trace_id == 7
        # explicit parent id (the cross-daemon message header) wins
        remote = g_tracer.begin("remote", daemon="b", trace_id=7,
                                parent_id=root.span_id)
        g_tracer.finish(remote)
    tree = g_tracer.collector.tree(7)
    assert len(tree) == 1 and tree[0]["name"] == "root"
    names = sorted(c["name"] for c in tree[0]["children"])
    assert names == ["child", "remote"]
    assert tree[0]["end"] is not None


def test_span_ring_bounded_and_flight_recorder_pins(clean_tracing):
    g_tracer.enable()
    g_tracer.collector.ring_size = 2048
    keep = g_tracer.begin("pinned", daemon="ringtest", trace_id=99)
    g_tracer.finish(keep)
    entry = g_flight_recorder.record(
        99, "slow op", 1.0, g_tracer.collector.spans_for_trace(99))
    # overflow the daemon's ring: the collector forgets, the pin holds
    for i in range(3000):
        g_tracer.finish(g_tracer.begin(f"junk{i}", daemon="ringtest",
                                       trace_id=1))
    assert g_tracer.collector.spans_for_trace(99) == []
    tree = entry.tree()
    assert len(tree) == 1 and tree[0]["name"] == "pinned"
    assert g_flight_recorder.dump()["slow_ops"][-1]["trace_id"] == 99


def test_build_tree_orphan_parents_become_roots(clean_tracing):
    g_tracer.enable()
    sp = g_tracer.begin("orphan", daemon="d", trace_id=5,
                        parent_id=123456789)
    g_tracer.finish(sp)
    tree = build_tree(g_tracer.collector.spans_for_trace(5))
    assert [t["name"] for t in tree] == ["orphan"]


# ---- histogram primitives --------------------------------------------------
def test_histogram_log2_bucketing_matches_reference():
    ax = PerfHistogramAxis("lat", min=100, quant_size=10, buckets=8)
    # below min -> underflow bucket 0
    assert ax.bucket_for(99) == 0
    # d = 0 -> bucket 1; d = 1 -> bucket 2; d in [2,4) -> 3 ...
    assert ax.bucket_for(100) == 1
    assert ax.bucket_for(110) == 2
    assert ax.bucket_for(120) == 3
    assert ax.bucket_for(140) == 4
    # overflow clamps to the last bucket
    assert ax.bucket_for(10**9) == 7
    lin = PerfHistogramAxis("x", min=0, quant_size=2, buckets=4,
                            scale_type=SCALE_LINEAR)
    assert [lin.bucket_for(v) for v in (0, 2, 4, 100)] == [1, 2, 3, 3]


def test_histogram_2d_dump_shape_and_cumulative():
    hist = PerfHistogram(latency_in_bytes_axes())
    hist.inc(250, 4096)       # 250 usec, 4 KiB
    hist.inc(50, 100)
    hist.inc(10**9, 2**40)    # overflow both axes
    d = hist.dump()
    assert [a["name"] for a in d["axes"]] == ["latency_usec",
                                              "request_size_bytes"]
    assert d["axes"][0]["scale_type"] == "log2"
    assert len(d["values"]) == 32 and len(d["values"][0]) == 32
    assert sum(map(sum, d["values"])) == 3 == d["count"]
    cum = hist.cumulative_axis0()
    counts = [c for _e, c in cum]
    assert counts == sorted(counts)          # monotone by construction
    assert counts[-1] == 3
    assert cum[-1][0] == float("inf")


def test_histogram_collection_get_or_create():
    h1 = g_perf_histograms.get("unit.test", "h", latency_in_bytes_axes)
    h2 = g_perf_histograms.get("unit.test", "h")
    assert h1 is h2
    with pytest.raises(KeyError):
        g_perf_histograms.get("unit.test", "missing")


# ---- cluster wiring --------------------------------------------------------
def _boot_traced_cluster():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("trace", k=3, m=2, pg_num=8)
    return c


def test_write_path_zero_syncs_when_tracing_disabled(clean_tracing,
                                                     monkeypatch):
    """Acceptance gate: the default-off tracing path must add no
    block_until_ready/drain to the OSD write path — counted via a
    monkeypatched fence, with spans both off AND on (spans are
    host-side only; only tracing_kernels may ever add a sync)."""
    import jax
    c = _boot_traced_cluster()
    cl = c.client()
    cl.write_full("trace", "warm", b"w" * 20000)      # compile warmup
    calls = {"n": 0}
    real = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    assert cl.write_full("trace", "o_off", b"x" * 20000) == 0
    assert calls["n"] == 0, "write path synced with tracing disabled"
    g_tracer.enable()                                 # spans only
    assert cl.write_full("trace", "o_on", b"y" * 20000) == 0
    assert calls["n"] == 0, "span tracing added a device sync"
    # dispatch-PR extension: the batched path (non-zero collection
    # window) must stay sync-free too, tracing on or off
    g_tracer.enable(False)
    g_conf.set_val("ec_dispatch_batch_window_us", 100_000)
    g_conf.set_val("ec_dispatch_batch_max", 8)
    assert cl.write_full("trace", "o_batched", b"z" * 20000) == 0
    assert calls["n"] == 0, "batched dispatch added a device sync"
    # robustness-PR extension: the fault guard + breaker board wrap
    # every device call unconditionally — with NO site armed they must
    # add zero syncs and leave no degradation trace behind
    from ceph_tpu.fault import fault_perf_counters, g_breakers, g_faults
    assert g_faults.dump()["armed"] == {}
    errors_before = fault_perf_counters().dump()["device_errors"]
    g_conf.rm_val("ec_dispatch_batch_window_us")
    assert cl.write_full("trace", "o_guarded", b"g" * 20000) == 0
    assert calls["n"] == 0, "fault guard added a device sync"
    assert fault_perf_counters().dump()["device_errors"] \
        == errors_before, "unarmed guard recorded a device failure"
    assert g_breakers.degraded() == []
    # async-pipeline extension: the continuation-driven write path
    # (ec_pipeline_depth > 1, encode resolved via add_done_callback)
    # must add zero device syncs too, tracing off
    g_conf.set_val("ec_pipeline_depth", 8)
    g_conf.set_val("ec_dispatch_batch_window_us", 100_000)
    try:
        assert cl.write_full("trace", "o_piped", b"p" * 20000) == 0
        assert calls["n"] == 0, "async pipeline added a device sync"
    finally:
        g_conf.rm_val("ec_pipeline_depth")
    # devprof extension: the device-flow profiler is ALWAYS on (counter
    # bumps per boundary crossing) — it must have accounted the writes
    # above while this counting fence saw zero added syncs, and a
    # `prof dump` (device-mem sample included) must not sync either
    from ceph_tpu.trace import g_devprof
    g_conf.rm_val("ec_dispatch_batch_window_us")
    t0 = g_devprof.totals()
    assert cl.write_full("trace", "o_profiled", b"d" * 20000) == 0
    t1 = g_devprof.totals()
    assert t1["h2d_count"] > t0["h2d_count"], \
        "profiler missed the write's h2d transfer"
    assert t1["d2h_count"] > t0["d2h_count"], \
        "profiler missed the write's d2h transfer"
    assert calls["n"] == 0, "device-flow profiling added a device sync"
    g_devprof.sample_device_mem()
    assert calls["n"] == 0, "device-mem sampling added a device sync"
    # oplat extension: the stage-latency ledger is ALWAYS on too
    # (timestamp stamps at every handoff boundary) — it must have
    # accounted a full untraced AND a full traced write while this
    # counting fence saw zero added syncs, and a `latency dump` must
    # not sync either
    from ceph_tpu.trace import g_oplat
    s0 = g_oplat.snapshot()
    ops0 = g_oplat.dump()["ops"]
    assert cl.write_full("trace", "o_staged", b"s" * 20000) == 0
    g_tracer.enable()
    assert cl.write_full("trace", "o_staged_traced", b"t" * 20000) == 0
    g_tracer.enable(False)
    bd = g_oplat.breakdown_since(s0, wall_s=1.0, n_ops=2)
    for stage in ("admission", "class_queue", "device_call", "d2h",
                  "fan_out", "ack_gather", "reply"):
        assert bd["stages"].get(stage, {}).get("count", 0) >= 2, \
            f"stage clock missed the {stage} boundary"
    assert g_oplat.dump()["ops"] >= ops0 + 2
    assert calls["n"] == 0, "stage-latency ledger added a device sync"
    # telemetry extension: the mgr's cluster rollup collection + SLO
    # evaluation on tick is pure host-side histogram/counter reads —
    # a full mgr tick, the rollup snapshot, and the single-pane
    # status must add zero device syncs
    samples0 = c.mgr.telemetry.rollup()["samples"]
    c.clock += 1.0
    c.mgr.tick(c.clock)
    roll = c.mgr.telemetry.rollup()
    assert roll["samples"] == samples0 + 1
    assert roll["oplat_p99_usec"].get("device_call", 0) > 0, \
        "telemetry tick missed the device_call stage family"
    c.tpu_status()
    c.mgr.telemetry.dump()
    assert calls["n"] == 0, "telemetry collection added a device sync"
    # recovery extension: an ARMED recovery scheduler (repair reads
    # enabled, pacing configured — the default-on state every OSD
    # boots with) must add zero syncs to the client write path; a
    # `recovery dump` is pure counter reads and must not sync either
    assert bool(g_conf.get_val("osd_recovery_repair_reads"))
    for osd in c.osds.values():
        assert osd.recovery_sched is not None
    assert cl.write_full("trace", "o_recovery_armed",
                         b"r" * 20000) == 0
    c.admin_socket.execute("recovery dump")
    assert calls["n"] == 0, "armed recovery scheduler added a " \
        "device sync to the client write path"
    # journal/incident extension: event emission is a host-side dict
    # append, and a FULL incident capture (timeline merge + rollup +
    # slow-op ledgers + chip scoreboard + control dump) is pure
    # host-side snapshotting — neither may ever touch the device
    from ceph_tpu.trace import g_journal
    g_journal.emit("mgr", "slo_streak", check="FENCE_TEST",
                   phase="sustain")
    g_journal.emit("mesh", "chip_suspect_mark", chip=0, probe=1,
                   skew_ratio=9.9)
    bundle = c.mgr.incident.capture("FENCE_TEST", "fence-count probe",
                                    reason="operator")
    assert bundle is not None and bundle["timeline"]
    c.admin_socket.execute("journal dump")
    c.admin_socket.execute("tpu incident list")
    c.admin_socket.execute("tpu incident dump")
    assert calls["n"] == 0, "journal emit / incident capture added " \
        "a device sync"
    # meshed-READ extension (the straggler-proof read PR): a DEGRADED
    # read reconstructed through the mesh decode path — plan build,
    # pooled staging, survivor-sharded matmul, occupancy accounting —
    # must add zero untracked syncs, exactly like the meshed write
    from ceph_tpu.mesh import g_mesh, mesh_decode_perf_counters
    from ceph_tpu.mesh.runtime import l_mdec_dispatches
    pid = c.mon.osdmap.lookup_pg_pool_name("trace")
    victim = next(
        o.osd_id for o in c.osds.values()
        for cid in o.store.list_collections()
        if cid.startswith(f"{pid}.") and "s" in cid
        and cid.rsplit("s", 1)[1] in ("1", "2")
        and any(ho.oid == "o_off" for ho in o.store.list_objects(cid)))
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    g_conf.set_val("ec_mesh_chips", 8)
    mdec0 = mesh_decode_perf_counters().get(l_mdec_dispatches)
    try:
        assert cl.read("trace", "o_off") == b"x" * 20000
    finally:
        g_conf.rm_val("ec_mesh_chips")
        g_mesh.topology()
    assert mesh_decode_perf_counters().get(l_mdec_dispatches) > mdec0, \
        "degraded read never rode the meshed decode path"
    assert calls["n"] == 0, "meshed degraded read added a device sync"
    c.revive_osd(victim)
    for _ in range(3):
        c.tick(dt=6.0)
    assert calls["n"] == 0
    # chaos extension: the composer is pure host-side seeded sampling
    # (no jax import at all), and a FULL storyline run — engine knobs,
    # open-loop traffic, fault arms, settle ticks, acceptance judgment
    # — rides the same sync-free dispatch/mesh/trace surfaces end to
    # end: zero added fences for the whole chaos machinery
    from ceph_tpu.chaos import compose_scenario, run_seed
    assert compose_scenario(24) == compose_scenario(24)
    assert calls["n"] == 0, "composing a storyline added a device sync"
    r = run_seed(24)
    assert r["accepted"], r
    assert calls["n"] == 0, "a full storyline run added a device sync"
    # zero-copy residency extension: the device-resident write path
    # (fused encode+crc kernel, shard bodies kept in HBM as handles,
    # digests fetched as tiny scalars) must add zero block_until_ready
    # with tracing off — and so must the read that lazily materializes
    # those handles back to host bytes
    from ceph_tpu.os_store import g_device_budget
    saved_budget = g_conf.values.get("os_memstore_device_bytes_max")
    g_conf.set_val("os_memstore_device_bytes_max", 1 << 30)
    try:
        res0 = g_device_budget.resident_shards()
        assert cl.write_full("trace", "o_resident", b"z" * 20000) == 0
        assert g_device_budget.resident_shards() > res0, \
            "the write never took the device-resident path"
        assert calls["n"] == 0, "resident write path added a device sync"
        assert cl.read("trace", "o_resident") == b"z" * 20000
        assert calls["n"] == 0, \
            "resident read materialization added a device sync"
    finally:
        if saved_budget is None:
            g_conf.rm_val("os_memstore_device_bytes_max")
        else:
            g_conf.set_val("os_memstore_device_bytes_max", saved_budget)


def test_slow_op_span_tree_and_histogram_dump(clean_tracing):
    """Tier-1 smoke: boot the mini-cluster, one write through the traced
    path, assert a complete span tree (client -> OSD -> EC encode ->
    device drain, monotone timestamps) in dump_historic_slow_ops and a
    non-empty `perf histogram dump` via the admin socket."""
    g_conf.set_val("op_complaint_time", -1.0)   # every op is "slow"
    g_tracer.enable()
    g_kernel_timer.enable()                     # drain child spans exist
    c = _boot_traced_cluster()
    cl = c.client()
    assert cl.write_full("trace", "obj", b"z" * 20000) == 0

    hd = c.admin_socket.execute("perf histogram dump")
    w = [d["op_w_latency_in_bytes_histogram"] for d in hd.values()
         if d.get("op_w_latency_in_bytes_histogram", {}).get("count")]
    assert w, "no OSD recorded an op_w histogram sample"
    enc = [d["ec_encode_latency_in_bytes_histogram"] for d in hd.values()
           if d.get("ec_encode_latency_in_bytes_histogram",
                    {}).get("count")]
    assert enc, "no OSD recorded an ec_encode histogram sample"

    slow = c.admin_socket.execute("dump_historic_slow_ops")
    trees = [op["span_tree"] for d in slow.values() for op in d["ops"]
             if "span_tree" in op
             and op["description"].startswith("osd_op(writefull")]
    assert trees, "slow write op carried no span tree"
    roots = trees[0]
    assert len(roots) == 1 and roots[0]["name"].startswith("client_op:")

    def find(node, pred, path):
        if pred(node):
            return path + [node]
        for ch in node["children"]:
            hit = find(ch, pred, path + [node])
            if hit:
                return hit
        return None

    chain = find(roots[0],
                 lambda n: n["name"] == "device_drain", [])
    assert chain is not None, "no device_drain span under the op"
    names = [n["name"] for n in chain]
    assert any(n.startswith("osd_op:") for n in names)
    assert "ec_encode" in names
    assert any(n.startswith("kernel:") for n in names)
    # monotone: every child starts at/after its parent, all spans closed
    for parent, child in zip(chain, chain[1:]):
        assert child["start"] >= parent["start"]
        assert parent["end"] is not None and child["end"] is not None
        assert child["end"] <= parent["end"] + 1e-6

    # dump_tracing surfaces the same spans per daemon + flight entries
    dt = c.admin_socket.execute("dump_tracing")
    assert dt["enabled"] and "client.0" in dt["spans"]
    assert dt["flight_recorder"]["slow_ops"]

    # forensics satellite: the same historic entry carries the
    # aggregated copy_ledger next to its stage_ledger — which host<->
    # device boundary moved the bytes, without replaying the trace
    ledgers = [op["copy_ledger"] for d in slow.values()
               for op in d["ops"]
               if op["description"].startswith("osd_op(writefull")
               and "copy_ledger" in op]
    assert ledgers, "slow write op carried no copy_ledger"
    entries = ledgers[0]
    assert all(set(e) >= {"stage", "dir", "bytes"} for e in entries)
    assert any(e["dir"] == "h2d" and e["bytes"] > 0 for e in entries)


def test_queued_ec_write_keeps_trace_context(clean_tracing):
    """A write queued behind another on the same oid starts from the
    sub-write-reply dispatch context; its encode/fan-out must still
    trace under the SUBMITTING op's span (captured at enqueue), not
    whatever span is current at dequeue."""
    g_tracer.enable()
    c = _boot_traced_cluster()
    cl = c.client()
    cl.write_full("trace", "qq", b"a" * 8000)
    pid = cl.lookup_pool("trace")
    pgid, primary = cl._calc_target(pid, "qq")
    be = c.osds[primary].pgs[pgid].backend
    root = g_tracer.begin("test_root", daemon="test", trace_id=424242)
    with g_tracer.activate(root):
        # first starts inline; second queues until the first's shard
        # acks arrive (nothing pumps inside submit_transaction)
        be.submit_transaction("qq", b"b" * 8000, lambda _r: None)
        be.submit_transaction("qq", b"c" * 8000, lambda _r: None)
    g_tracer.finish(root)
    c.network.pump()
    spans = g_tracer.collector.spans_for_trace(424242)
    encodes = [s for s in spans if s.name == "ec_encode"]
    assert len(encodes) == 2, \
        "queued write's ec_encode span lost the submitting trace"
    assert all(s.parent_span_id == root.span_id for s in encodes)
    # the queued op's sub-writes carried the trace cross-daemon too
    assert sum(1 for s in spans if s.name.startswith("sub_write")) >= 10


def test_op_complaint_time_live_config(clean_tracing):
    """Runtime `config set op_complaint_time` must take effect on
    already-constructed OpTrackers (no restart)."""
    from ceph_tpu.common import OpTracker
    t = OpTracker()
    assert t.complaint_time == 30.0
    g_conf.set_val("op_complaint_time", 1.5)
    assert t.complaint_time == 1.5
    t.complaint_time = 99.0          # explicit override pins
    g_conf.set_val("op_complaint_time", 2.0)
    assert t.complaint_time == 99.0


def test_tracing_admin_toggle_and_config_observer(clean_tracing):
    c = _boot_traced_cluster()
    out = c.admin_socket.execute("span tracing", {"on": "1"})
    assert out["enabled"] and g_tracer.enabled
    out = c.admin_socket.execute("span tracing", {"on": "0"})
    assert not out["enabled"] and not g_tracer.enabled
    # config observer path ('ceph tell ... injectargs tracing_spans')
    c.admin_socket.execute("config set", {"name": "tracing_spans",
                                          "value": "true"})
    assert g_tracer.enabled
    c.admin_socket.execute("config set", {"name": "tracing_spans",
                                          "value": "false"})
    assert not g_tracer.enabled


def test_kernel_timer_record_thread_safe():
    """Satellite: concurrent _record calls must not lose samples."""
    import threading
    from ceph_tpu.common.kernel_trace import KernelTimer
    kt = KernelTimer()
    kt.enable()
    N, THREADS = 500, 8

    def worker():
        for _ in range(N):
            kt._record("hot", 0.001)

    ts = [threading.Thread(target=worker) for _ in range(THREADS)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert kt.dump()["hot"]["calls"] == N * THREADS
