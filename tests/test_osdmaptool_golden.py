"""osdmaptool golden parity: replay the reference's recorded cram
outputs byte-for-byte.

Like tests/test_reference_golden.py does for crushtool, these tests
parse the reference's cram files (src/test/cli/osdmaptool/*.t — the
EXPECTED outputs its own binary produced) and replay the same command
sequences through ceph_tpu's osdmaptool/crushtool, pinning
``calc_pg_upmaps`` to the reference algorithm's actual decisions (not
a stddev proxy) and the simple-map builders to its construction.
"""
import os
import re

import pytest

from ceph_tpu.tools import crushtool, osdmaptool

TDIR = "/root/reference/src/test/cli/osdmaptool"
CONF = os.path.join(TDIR, "ceph.conf.withracks")

pytestmark = pytest.mark.skipif(
    not os.path.isdir(TDIR), reason="reference cram files unavailable")


def expected_upmap_lines(tname: str):
    """The `cat c` block from a cram file: the recorded upmap commands."""
    text = open(os.path.join(TDIR, tname)).read()
    m = re.search(r"\$ cat c\n((?:  ceph osd [^\n]+\n)+)", text)
    assert m, f"no recorded upmap block in {tname}"
    return [ln[2:] for ln in m.group(1).splitlines()]


def run_upmap(tmp_path, mark_out=None):
    om = str(tmp_path / "om")
    c = str(tmp_path / "c")
    assert osdmaptool.main(["--create-from-conf", om, "-c", CONF,
                            "--with-default-pool"]) == 0
    argv = [om, "--mark-up-in"]
    if mark_out is not None:
        argv += ["--mark-out", str(mark_out)]
    argv += ["--upmap-max", "11", "--upmap", c]
    assert osdmaptool.main(argv) == 0
    return open(c).read().splitlines()


def test_upmap_t_byte_exact(tmp_path):
    """upmap.t: 239-osd two-rack map, `--upmap-max 11 --upmap c` —
    the 11 recorded pg-upmap-items commands, byte-for-byte."""
    assert run_upmap(tmp_path) == expected_upmap_lines("upmap.t")


def test_upmap_out_t_byte_exact(tmp_path):
    """upmap-out.t: same with osd.147 marked out."""
    assert run_upmap(tmp_path, mark_out=147) == \
        expected_upmap_lines("upmap-out.t")


def test_map_pgs_t_replay(tmp_path, capsys):
    """test-map-pgs.t: createsimple 500 osds @ pg_bits 4, import a
    crushtool --build straw map, and replay the cram's grep asserts:
    pool pg_num, the complete size histogram, and crush-vs-random
    stats differing."""
    om = str(tmp_path / "osdmap")
    cm = str(tmp_path / "crushmap")
    assert osdmaptool.main(["--pg_bits", "4", "--createsimple", "500",
                            om, "--with-default-pool"]) == 0
    assert crushtool.main(["--outfn", cm, "--build", "--num_osds",
                           "500", "node", "straw", "10",
                           "rack", "straw", "10",
                           "root", "straw", "0"]) == 0
    assert osdmaptool.main([om, "--import-crush", cm]) == 0
    capsys.readouterr()

    assert osdmaptool.main([om, "--mark-up-in", "--test-map-pgs"]) == 0
    out = capsys.readouterr().out
    assert "pool 1 pg_num 8000" in out            # 500 << 4
    assert re.search(r"size 3\t8000\b", out)      # every pg mapped full
    stats_crush = [ln for ln in out.splitlines()
                   if ln.startswith(" avg ")]
    assert stats_crush

    assert osdmaptool.main([om, "--mark-up-in", "--test-random",
                            "--test-map-pgs"]) == 0
    out = capsys.readouterr().out
    assert "pool 1 pg_num 8000" in out
    assert re.search(r"size 3\t8000\b", out)
    stats_random = [ln for ln in out.splitlines()
                    if ln.startswith(" avg ")]
    # "it is almost impossible to get the same stats with random and
    # crush; if they are, something went wrong somewhere" (the cram)
    assert stats_crush != stats_random


def test_crushtool_choose_args_roundtrip():
    """choose-args.t's compile/decompile/recompile identity: a text
    map carrying choose_args (per-position weight_set replacements +
    id overrides, crush.h:273) compiles, decompiles, and RECOMPILES to
    the identical binary (the cram's `cmp choose-args.compiled
    choose-args.recompiled`), with every recorded entry preserved."""
    from ceph_tpu.crush.binfmt import decode_crushmap, encode_crushmap
    from ceph_tpu.crush.compiler import CrushCompiler
    src = open("/root/reference/src/test/cli/crushtool/"
               "choose-args.crush").read()
    cw = CrushCompiler().compile(src)
    ca = cw.crush.choose_args
    assert set(ca) == {1, 2, 3, 4, 5, 6}
    # the recorded map-6 entries, verbatim from the reference file
    six = ca[6]
    assert six[0].ids == [-450]                      # bucket -1
    assert [w.weights for w in six[1].weight_set] == \
        [[0x10000], [0x30000]]                       # bucket -2
    assert [w.weights for w in six[2].weight_set] == \
        [[0x10000, 0x20000, 0x50000], [0x30000, 0x20000, 0x50000]]
    assert six[2].ids == [-20, -30, -25]
    bin_a = encode_crushmap(cw)
    text = CrushCompiler(cw).decompile()
    cw2 = CrushCompiler().compile(text)
    bin_b = encode_crushmap(cw2)
    assert bin_a == bin_b
    # and the binary codec round-trips the args structurally
    cw3 = decode_crushmap(bin_a)
    assert cw3.crush.choose_args[6][2].ids == [-20, -30, -25]


def test_crushtool_reweight_t_byte_exact(tmp_path):
    """reweight.t: compile multitype.before (uniform/list/tree/straw
    buckets), apply the four recorded --reweight-item ops, decompile —
    the output must equal multitype.after byte-for-byte (the cram's
    `diff final multitype.after`)."""
    d = "/root/reference/src/test/cli/crushtool"
    mt = str(tmp_path / "mt")
    assert crushtool.main(["-c", f"{d}/multitype.before",
                           "-o", mt]) == 0
    for name, w in [("osd0", "2.0"), ("osd3", "2.0"),
                    ("osd6", "2.0"), ("osd7", ".5")]:
        assert crushtool.main(["-i", mt, "--reweight-item", name, w,
                               "-o", mt]) == 0
    final = str(tmp_path / "final")
    assert crushtool.main(["-d", mt, "-o", final]) == 0
    assert open(final).read() == open(f"{d}/multitype.after").read()


def _cram_expected_decompile(tname: str, nth: int = 0) -> str:
    """The recorded `crushtool -d` output block from a cram file,
    unescaped (cram's '\\t...(esc)' notation)."""
    lines = open("/root/reference/src/test/cli/crushtool/"
                 + tname).read().splitlines()
    starts = [i for i, ln in enumerate(lines)
              if ln.strip().startswith("$ crushtool -d")]
    start = starts[nth]
    out = []
    for ln in lines[start + 1:]:
        if ln.startswith("  $ ") or not ln.startswith("  "):
            break
        body = ln[2:]
        if body.endswith(" (esc)"):
            body = body[:-6].replace("\\t", "\t")
        out.append(body)
    return "\n".join(out) + "\n"


def test_crushtool_add_item_t_byte_exact(tmp_path):
    """add-item.t: start from the reference's binary simple.template,
    --add-item two devices with --loc chains, --create-simple-rule,
    decompile — byte-for-byte against the cram's recorded output."""
    d = "/root/reference/src/test/cli/crushtool"
    one = str(tmp_path / "one")
    two = str(tmp_path / "two")
    assert crushtool.main(["-i", f"{d}/simple.template",
                           "--add-item", "0", "1.0", "device0",
                           "--loc", "host", "host0",
                           "--loc", "cluster", "cluster0",
                           "-o", one]) == 0
    assert crushtool.main(["-i", one,
                           "--add-item", "1", "1.0", "device1",
                           "--loc", "host", "host0",
                           "--loc", "cluster", "cluster0",
                           "-o", two]) == 0
    assert crushtool.main(["-i", two, "--create-simple-rule",
                           "simple-rule", "cluster0", "host", "firstn",
                           "-o", two]) == 0
    out = str(tmp_path / "out")
    assert crushtool.main(["-d", two, "-o", out]) == 0
    assert open(out).read() == _cram_expected_decompile("add-item.t")


def test_crushtool_compile_decompile_recompile_t(tmp_path):
    """compile-decompile-recompile.t: need_tree_order.crush is itself
    a recorded decompile — our decompile must reproduce it (comments
    and all) and the binary encoding must be deterministic; a rule
    taking an undefined bucket fails with the reference's diagnostic."""
    from ceph_tpu.crush.compiler import CrushCompiler
    d = "/root/reference/src/test/cli/crushtool"
    src = open(f"{d}/need_tree_order.crush").read()
    nto = str(tmp_path / "nto.compiled")
    conf = str(tmp_path / "nto.conf")
    reco = str(tmp_path / "nto.recompiled")
    srcf = str(tmp_path / "need_tree_order.crush")
    open(srcf, "w").write(src)
    assert crushtool.main(["-c", srcf, "-o", nto]) == 0
    assert crushtool.main(["-d", nto, "-o", conf]) == 0
    assert crushtool.main(["-c", conf, "-o", reco]) == 0
    assert open(conf).read() == src                     # cmp 1
    assert open(nto, "rb").read() == open(reco, "rb").read()  # cmp 2
    # missing-bucket.crushmap.txt: the recorded diagnostic
    with pytest.raises(ValueError) as ei:
        CrushCompiler().compile(
            open(f"{d}/missing-bucket.crushmap.txt").read())
    assert str(ei.value) == "in rule 'rule-bad' item 'root-404' " \
        "not defined"


def test_crushtool_rules_t_byte_exact(tmp_path):
    """rules.t: device classes build SHADOW trees with the recorded id
    allocation (-4..-9), --create-replicated-rule with and without
    --device-class, and both recorded decompiles match byte-for-byte
    (class id comments, 'step take default class ssd')."""
    d = "/root/reference/src/test/cli/crushtool"
    one = str(tmp_path / "one")
    assert crushtool.main(["-c", f"{d}/rules.txt",
                           "--create-replicated-rule", "foo",
                           "default", "host", "-o", one]) == 0
    out = str(tmp_path / "out")
    assert crushtool.main(["-d", one, "-o", out]) == 0
    assert open(out).read() == _cram_expected_decompile("rules.t", 0)
    two = str(tmp_path / "two")
    assert crushtool.main(["-c", f"{d}/rules.txt",
                           "--create-replicated-rule", "foo-ssd",
                           "default", "host",
                           "--device-class", "ssd", "-o", two]) == 0
    assert crushtool.main(["-d", two, "-o", out]) == 0
    assert open(out).read() == _cram_expected_decompile("rules.t", 1)


def test_class_map_roundtrip_pins_shadow_ids(tmp_path):
    """A decompiled class-bearing map recompiles to the IDENTICAL
    binary: the 'id N class C' lines pin the shadow-tree ids, so
    editing a decompiled map cannot scramble class_bucket references."""
    d = "/root/reference/src/test/cli/crushtool"
    one = str(tmp_path / "one")
    txt = str(tmp_path / "txt")
    two = str(tmp_path / "two")
    assert crushtool.main(["-c", f"{d}/rules.txt",
                           "--create-replicated-rule", "foo-ssd",
                           "default", "host", "--device-class", "ssd",
                           "-o", one]) == 0
    assert crushtool.main(["-d", one, "-o", txt]) == 0
    assert crushtool.main(["-c", txt, "-o", two]) == 0
    assert open(one, "rb").read() == open(two, "rb").read()


def test_crushtool_device_class_t_byte_exact(tmp_path):
    """device-class.t: a class-bearing map (shadow trees, class-scoped
    takes) compiles, decompiles back to the IDENTICAL text (the cram's
    `cmp device-class.crush device-class.conf`), and recompiles to the
    identical binary."""
    d = "/root/reference/src/test/cli/crushtool"
    c = str(tmp_path / "c")
    conf = str(tmp_path / "conf")
    r = str(tmp_path / "r")
    assert crushtool.main(["-c", f"{d}/device-class.crush",
                           "-o", c]) == 0
    assert crushtool.main(["-d", c, "-o", conf]) == 0
    assert crushtool.main(["-c", conf, "-o", r]) == 0
    assert open(conf).read() == \
        open(f"{d}/device-class.crush").read()
    assert open(c, "rb").read() == open(r, "rb").read()


def test_crushtool_dump_json_byte_exact(tmp_path, capsys):
    """choose-args.t's --dump block: the JSON map dump (devices/types/
    buckets/rules/tunables with profile+min-version detection/
    choose_args with %f weights) matches the recorded output
    byte-for-byte."""
    d = "/root/reference/src/test/cli/crushtool"
    c = str(tmp_path / "c")
    conf = str(tmp_path / "conf")
    assert crushtool.main(["-c", f"{d}/choose-args.crush",
                           "-o", c]) == 0
    assert crushtool.main(["-d", c, "-o", conf]) == 0
    capsys.readouterr()
    assert crushtool.main(["-c", conf, "-o", "/dev/null",
                           "--dump"]) == 0
    got = capsys.readouterr().out
    lines = open(f"{d}/choose-args.t").read().splitlines()
    start = next(i for i, ln in enumerate(lines) if "--dump" in ln)
    exp = []
    for ln in lines[start + 1:]:
        if ln.startswith("  $ ") or not ln.startswith("  "):
            break
        exp.append(ln[2:])
    assert got == "\n".join(exp) + "\n"


def test_crushtool_add_item_in_tree_t_byte_exact(tmp_path):
    """add-item-in-tree.t: eight sequential --add-item ops into a
    TREE-bucket template; the final decompile matches the recorded
    tree.template.final byte-for-byte (tree node arrays re-derive
    correctly through every membership change)."""
    d = "/root/reference/src/test/cli/crushtool"
    cur = f"{d}/tree.template"
    for i in range(8):
        nxt = str(tmp_path / f"m{i}")
        assert crushtool.main(
            ["-i", cur, "--add-item", str(i), "1.0", f"device{i}",
             "--loc", "host", "host0", "--loc", "cluster", "cluster0",
             "-o", nxt]) == 0
        cur = nxt
    final = str(tmp_path / "final")
    assert crushtool.main(["-d", cur, "-o", final]) == 0
    assert open(final).read() == \
        open(f"{d}/tree.template.final").read()


def test_crushtool_adjust_item_weight_t_byte_exact(tmp_path):
    """adjust-item-weight.t: a device living in TWO hosts keeps
    per-location weights — adding it to a second host sets the weight
    THERE, and --update-item adjusts only the named location; both
    recorded decompiles match byte-for-byte."""
    d = "/root/reference/src/test/cli/crushtool"
    one = str(tmp_path / "one")
    two = str(tmp_path / "two")
    three = str(tmp_path / "three")
    final = str(tmp_path / "final")
    assert crushtool.main(
        ["-i", f"{d}/simple.template", "--add-item", "0", "1.0",
         "device0", "--loc", "host", "host0",
         "--loc", "cluster", "cluster0", "-o", one]) == 0
    assert crushtool.main(
        ["-i", one, "--add-item", "0", "2.0", "device0",
         "--loc", "host", "fake", "--loc", "cluster", "cluster0",
         "-o", two]) == 0
    assert crushtool.main(["-d", two, "-o", final]) == 0
    assert open(final).read() == \
        open(f"{d}/simple.template.adj.two").read()
    assert crushtool.main(
        ["-i", two, "--update-item", "0", "3.0", "device0",
         "--loc", "host", "host0", "--loc", "cluster", "cluster0",
         "-o", three]) == 0
    assert crushtool.main(["-d", three, "-o", final]) == 0
    assert open(final).read() == \
        open(f"{d}/simple.template.adj.three").read()


def test_crushtool_check_t_behaviors(tmp_path, capsys):
    """The --check cram family: check-names.empty.t (the stray-osd
    type probe on an empty map), check-names.max-id.t (device ids vs
    the bound), and check-overlapped-rules.t (per-sub-interval
    overlap reporting + the duplicate-rule compile diagnostic) — all
    recorded outputs verbatim."""
    d = "/root/reference/src/test/cli/crushtool"
    e = str(tmp_path / "e")
    assert crushtool.main(["-c", f"{d}/check-names.empty.crushmap.txt",
                           "-o", e]) == 0
    capsys.readouterr()
    assert crushtool.main(["-i", e, "--check", "0"]) == 1
    assert capsys.readouterr().out == "unknown type name: item#0\n"

    cur = f"{d}/simple.template"
    for i in range(3):
        nxt = str(tmp_path / f"m{i}")
        assert crushtool.main(
            ["-i", cur, "--add-item", str(i), "1.0", f"device{i}",
             "--loc", "host", "host0", "--loc", "cluster", "cluster0",
             "-o", nxt]) == 0
        cur = nxt
    capsys.readouterr()
    assert crushtool.main(["-i", str(tmp_path / "m1"),
                           "--check", "2"]) == 0
    assert crushtool.main(["-i", str(tmp_path / "m2"),
                           "--check", "2"]) == 1
    assert capsys.readouterr().out == "item id too large: item#2\n"
    assert crushtool.main(["-i", str(tmp_path / "m2"),
                           "--check"]) == 0
    capsys.readouterr()

    assert crushtool.main(
        ["-i", f"{d}/check-overlapped-rules.crushmap", "--check"]) == 0
    assert capsys.readouterr().out == (
        "overlapped rules in ruleset 0: rule-r0, rule-r1, rule-r2\n"
        "overlapped rules in ruleset 0: rule-r0, rule-r2, rule-r3\n"
        "overlapped rules in ruleset 0: rule-r0, rule-r3\n")
    assert crushtool.main(
        ["-c", f"{d}/check-overlapped-rules.crushmap.txt",
         "-o", str(tmp_path / "x")]) == 1
    assert capsys.readouterr().out == "rule 0 already exists\n"


def test_crushtool_decode_failure_message(capsys):
    """crushtool -d on a non-crushmap prints the recorded diagnostic."""
    assert crushtool.main(["-d", "/etc/hosts"]) == 1
    assert capsys.readouterr().out == \
        "crushtool: unable to decode /etc/hosts\n"


def test_crushtool_show_location_t_byte_exact(capsys):
    """location.t: --show-location walks the ancestor chain of a
    device in the reference's big recorded binary map, printing
    type\\tname alphabetically (the std::map order); devices outside
    the map print nothing."""
    d = "/root/reference/src/test/cli/crushtool"
    m = f"{d}/test-map-big-1.crushmap"
    cases = {
        44: "",
        16: "",
        167: ("host\tp05151113587529\nrack\tRJ45\n"
              "room\t0513-R-0050\nroot\tdefault\n"),
        258: "host\tlxfssi44a06\nrack\tSI44\nroot\tcastor\n",
    }
    for dev, want in cases.items():
        assert crushtool.main(["-i", m, "--show-location",
                               str(dev)]) == 0
        assert capsys.readouterr().out == want, dev
