"""crush-compat balancer mode: per-position weight_set optimization.

The reference balancer's second mode (pybind/mgr/balancer/module.py
do_crush_compat) flattens PG distribution by optimizing the crush map's
choose_args weight_set (crush.h:273) instead of emitting pg_upmap
entries — for clients too old to decode upmaps.  These tests require:
stddev improves on a skewed map with ZERO upmap entries, and the device
mappers evaluate the optimized weight_set bit-exactly.
"""
import numpy as np
import pytest

from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2
from ceph_tpu.osdmap import OSDMap, pg_t
from ceph_tpu.osdmap.balancer import calc_weight_set
from ceph_tpu.osdmap.types import pg_pool_t, TYPE_REPLICATED


def skewed_map(n_hosts=6, per_host=4, pg_num=256):
    m = OSDMap()
    cw = m.crush
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    rng = np.random.default_rng(17)
    hosts, osd = [], 0
    for h in range(n_hosts):
        osds = list(range(osd, osd + per_host))
        osd += per_host
        # skew: identical CLAIMED weights but real clusters never land
        # perfectly — compat mode corrects the hash noise
        ws = [0x10000] * per_host
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"h{h}",
                                   osds, ws, id=-(h + 2)))
    m.set_max_osd(osd)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x10000 * per_host] * n_hosts, id=-1)
    for i in range(osd):
        m.set_osd(i, up=True)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    pool = pg_pool_t(type=TYPE_REPLICATED, size=3, min_size=2,
                     crush_rule=rno, pg_num=pg_num, pgp_num=pg_num)
    pid = m.add_pool("p", pool)
    m.epoch = 1
    return m, pid, rno


def per_osd_stddev(m, pid):
    pool = m.pools[pid]
    counts = {}
    for ps in range(pool.pg_num):
        up, _ = m.pg_to_raw_up(pg_t(pid, ps))
        for o in up:
            if o != 0x7FFFFFFF:
                counts[o] = counts.get(o, 0) + 1
    vals = [counts.get(o, 0) for o in range(m.max_osd)]
    return float(np.std(vals))


def test_weight_set_flattens_distribution_without_upmaps():
    m, pid, _ = skewed_map()
    before = per_osd_stddev(m, pid)
    b2, after = calc_weight_set(m, pid)
    assert b2 == pytest.approx(before)
    assert after < before, (before, after)
    assert per_osd_stddev(m, pid) == pytest.approx(after)
    # the whole point of compat mode: zero upmap entries
    assert not m.pg_upmap and not m.pg_upmap_items
    # the optimized args are per-position (one weight list per replica
    # slot, crush_choose_arg's weight_set shape)
    args = m.crush.crush.choose_args[pid]
    ws = next(a.weight_set for a in args if a.weight_set)
    assert len(ws) == m.pools[pid].size


@pytest.mark.slow   # ~18 s weight-set device sweep; fast-path weight-set
# coverage stays in tier-1 via test_batch_mapping_stays_on_device_*
def test_device_mappers_evaluate_weight_set_bit_exactly():
    """The optimized choose_args must map identically on the device
    (loop kernel) and the host interpreter."""
    from ceph_tpu.ops.crush_kernels import DeviceCrushMapper, compile_map
    m, pid, rno = skewed_map(n_hosts=5, per_host=3, pg_num=128)
    calc_weight_set(m, pid, max_iterations=10)
    args = m.crush.crush.choose_args[pid]
    cw = m.crush
    comp = compile_map(cw.crush, args)
    dev = DeviceCrushMapper(comp, rno, 3)
    xs = np.arange(400, dtype=np.uint32)
    weight = [0x10000] * m.max_osd
    res, cnt = dev.map_batch(xs, weight)
    for x in range(400):
        expect = cw.do_rule(rno, int(x), 3, weight,
                            choose_args_index=pid)
        assert list(res[x, :cnt[x]]) == expect, x


@pytest.mark.slow   # ~17 s weight-set device sweep heavyweight
def test_batch_mapping_uses_weight_set():
    """OSDMapMapping's whole-map batch path must agree with the scalar
    pipeline once choose_args are installed."""
    from ceph_tpu.osdmap.mapping import OSDMapMapping
    m, pid, _ = skewed_map(n_hosts=4, per_host=3, pg_num=64)
    calc_weight_set(m, pid, max_iterations=8)
    mapping = OSDMapMapping()
    mapping.update(m)
    for ps in range(64):
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg_t(pid, ps))
        bup, bprim = mapping.get(pg_t(pid, ps))[:2], None
        got_up, got_upp, got_acting, got_actp = mapping.get(pg_t(pid, ps))
        assert got_up == up and got_acting == acting
        assert got_upp == upp and got_actp == actp


def test_mgr_crush_compat_mode_publishes():
    """End-to-end through the mgr: the optimized weight_set rides a
    topology epoch to every subscriber; no upmaps appear."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=9, osds_per_host=3)
    c.create_replicated_pool("p", size=3, pg_num=128)
    pid = c.mon.osdmap.lookup_pg_pool_name("p")
    before, after = c.mgr.balancer_optimize_crush_compat(pid)
    assert after <= before
    assert not c.mon.osdmap.pg_upmap_items
    if after < before:
        # published: OSDs' maps carry the same choose_args
        osd = next(iter(c.osds.values()))
        assert pid in osd.osdmap.crush.crush.choose_args
    cl = c.client("client.b")
    assert cl.write_full("p", "o", b"balanced") == 0
    assert cl.read("p", "o") == b"balanced"


@pytest.mark.slow   # ~25-40 s of XLA compile+replay on 1 core: the
# indep/exact64 heavyweights run in the slow tier so tier-1 fits its
# wall budget (they were enable_x64-broken in the seed; fixed in PR 1)
def test_fast_path_firstn_weight_set_bit_exact():
    """The candidate-table fast path evaluates firstn rules under
    per-position weight sets bit-exactly: positions index by the
    DYNAMIC outpos (mapper.c:513), materialized as a candidate axis
    and gathered by each lane's success count during resolution."""
    from ceph_tpu.ops.crush_fast import compile_fast_rule
    m, pid, rno = skewed_map(n_hosts=5, per_host=3, pg_num=128)
    calc_weight_set(m, pid, max_iterations=10)
    args = m.crush.crush.choose_args[pid]
    assert max(len(a.weight_set) for a in args if a.weight_set) > 1
    cw = m.crush
    fr = compile_fast_rule(cw.crush, rno, 3, choose_args=args)
    assert fr.posP > 1 and fr.firstn
    xs = np.arange(400, dtype=np.uint32)
    rng = np.random.default_rng(3)
    for w in ([0x10000] * m.max_osd,
              [0x10000] * (m.max_osd - 2) + [0, 0x8000],
              list(rng.integers(0, 5, m.max_osd) * 0x4000)):
        res, cnt = fr.map_batch(xs, np.asarray(w, np.uint32))
        for x in range(len(xs)):
            expect = cw.do_rule(rno, int(x), 3, list(w),
                                choose_args_index=pid)
            assert list(res[x, :cnt[x]]) == expect, (x, w[:4])


@pytest.mark.slow   # ~25-40 s of XLA compile+replay on 1 core: the
# indep/exact64 heavyweights run in the slow tier so tier-1 fits its
# wall budget (they were enable_x64-broken in the seed; fixed in PR 1)
def test_reweighted_nonuniform_map_stays_device_zero_residual():
    """VERDICT r4 #9 done-criterion: a REWEIGHTED (non-uniform bucket
    weights) firstn map runs on the device mapper with ZERO host
    replays — the exact64 draw handles arbitrary weights bit-exactly,
    so crush_nonuniform_residual_fraction is 0.0, not ~0.08%."""
    from ceph_tpu.ops.crush_fast import compile_fast_rule
    m = OSDMap()
    cw = m.crush
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    rng = np.random.default_rng(5)
    hosts, osd = [], 0
    for h in range(16):
        osds = list(range(osd, osd + 4))
        osd += 4
        # ceph osd crush reweight aftermath: every device different
        ws = [int(w) for w in rng.integers(0x8000, 0x30000, 4)]
        hosts.append(cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"h{h}",
                                   osds, ws, id=-(h + 2)))
    m.set_max_osd(osd)
    # root stays uniform (the bench's shape): residuals here can only
    # come from draw inexactness, which exact64 eliminates — not from
    # the materialized-rounds collision tail a heavily skewed root
    # would add
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", hosts,
                  [0x40000] * 16, id=-1)
    for i in range(osd):
        m.set_osd(i, up=True)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    # tries_cap=7: enough materialized retry rounds that the
    # collision tail (orthogonal to draw exactness) can't flag a
    # lane; the residual then isolates draw inexactness alone
    fr = compile_fast_rule(cw.crush, rno, 3, tries_cap=7)
    # uniform root rides the quotient tables; the reweighted leaf
    # level is the exact64 path under test
    assert fr.integer_exact_levels == [True, False]
    xs = np.arange(2000, dtype=np.uint32)
    for w in ([0x10000] * osd,
              [0x10000] * (osd - 3) + [0, 0x8000, 0xc000]):
        res, cnt = fr.map_batch(xs, np.asarray(w, np.uint32))
        assert fr.residual_fraction == 0.0
        for x in range(0, 2000, 37):
            expect = cw.do_rule(rno, int(x), 3, list(w))
            assert list(res[x, :cnt[x]]) == expect, (x, w[-3:])
    # and the pool-level mapping keeps the device backend
    pool = pg_pool_t(type=TYPE_REPLICATED, size=3, min_size=2,
                     crush_rule=rno, pg_num=128, pgp_num=128)
    pid = m.add_pool("p", pool)
    m.epoch = 1
    from ceph_tpu.osdmap.mapping import OSDMapMapping
    mapping = OSDMapMapping()
    mapping.update(m)
    assert mapping.last_backend[pid] == "device"
    for ps in range(0, 128, 11):
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg_t(pid, ps))
        got = mapping.get(pg_t(pid, ps))
        assert got[0] == up and got[2] == acting


def test_native_mapper_choose_args_bit_exact():
    """The C++ batch evaluator consumes choose_args from the blob
    (ids overrides + per-position weight_set) and matches the host
    interpreter exactly — so the residual-replay and middle fallback
    tiers never degrade to the scalar Python loop."""
    from ceph_tpu.native import NativeCrushMapper, native_available
    if not native_available():
        pytest.skip("native lib unavailable")
    m, pid, rno = skewed_map(n_hosts=5, per_host=3, pg_num=64)
    calc_weight_set(m, pid, max_iterations=8)
    args = m.crush.crush.choose_args[pid]
    cw = m.crush
    nm = NativeCrushMapper(cw.crush, args)
    w = [0x10000] * (m.max_osd - 1) + [0]
    out, lens = nm.do_rule_batch(rno, list(range(300)), 3, w)
    for x in range(300):
        expect = cw.do_rule(rno, x, 3, list(w), choose_args_index=pid)
        assert list(out[x][:lens[x]]) == expect, x


def test_batch_mapping_stays_on_device_with_weight_set():
    """The VERDICT done-criterion: a compat-balanced firstn pool keeps
    the DEVICE batch mapper (no silent per-PG Python fallback)."""
    from ceph_tpu.osdmap.mapping import OSDMapMapping
    m, pid, _ = skewed_map(n_hosts=4, per_host=3, pg_num=64)
    calc_weight_set(m, pid, max_iterations=8)
    assert pid in m.crush.crush.choose_args
    mapping = OSDMapMapping()
    mapping.update(m)
    assert mapping.last_backend[pid] == "device"
    for ps in range(0, 64, 7):
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg_t(pid, ps))
        got_up, got_upp, got_acting, got_actp = mapping.get(pg_t(pid, ps))
        assert got_up == up and got_acting == acting
