"""Consistency tools: cephfs fsck and rgw gc.

Both dogfood the documented crash windows: fsck finds/repairs dangling
remotes, stale back-pointers, and orphan data objects
(cephfs-data-scan + scrub_path repair roles); gc collects data objects
and pending index markers stranded by crashed two-phase puts (rgw_gc).
"""
import json

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.cephfs import CephFS, dir_oid, file_oid
from ceph_tpu.rgw import RGWLite

ORDER = 12


@pytest.fixture()
def env():
    c = MiniCluster(n_osds=4)
    for p in ("fsmeta", "fsdata", "rgwmeta", "rgwdata"):
        c.create_replicated_pool(p, size=2, pg_num=8)
    return c, c.client("client.t")


def test_fsck_clean_tree(env):
    c, cl = env
    f = CephFS(cl, "fsmeta", "fsdata")
    f.mkfs()
    f.mkdir("/d")
    f.create("/d/file", ORDER)
    f.write("/d/file", b"healthy")
    f.hardlink("/d/file", "/alias")
    f.symlink("/lnk", "/d/file")
    report = f.fsck()
    assert report == {"dangling_remotes": [], "stale_backpointers": [],
                      "orphan_objects": [], "missing_dirs": []}


def test_fsck_finds_and_repairs(env):
    c, cl = env
    f = CephFS(cl, "fsmeta", "fsdata")
    f.mkfs()
    f.create("/keep", ORDER)
    f.write("/keep", b"k")
    f.hardlink("/keep", "/h")
    # crash artifact 1: stale back-pointer — recorded link whose dentry
    # is absent from an EXISTING directory (a pointer into a LOST dir
    # is 'unknowable' and deliberately not repaired; see
    # test_fsck_withholds_purge_on_missing_dir)
    ghost_dino = f.mkdir("/ghostdir")
    dino, name = f._resolve_parent("/keep")
    f._update_links(dino, name, add_links=[[ghost_dino, "ghost"]])
    # crash artifact 2: dangling remote (primary vanished)
    f.create("/gonner", ORDER)
    f.hardlink("/gonner", "/dangling")
    gd, gn = f._resolve_parent("/gonner")
    f._call(dir_oid(gd), "unlink", {"name": gn})   # raw unlink, no cleanup
    # crash artifact 3: orphan data objects (inode never linked)
    cl.write_full("fsdata", file_oid(0xdead, 0), b"orphan-bytes")
    report = f.fsck(repair=True)
    assert any(bp[0] == "/keep" and bp[1][1] == "ghost"
               for bp in report["stale_backpointers"])
    assert "/dangling" in report["dangling_remotes"]
    assert file_oid(0xdead, 0) in report["orphan_objects"]
    # repaired: second pass is clean and the healthy file survived
    assert f.fsck() == {"dangling_remotes": [], "stale_backpointers": [],
                        "orphan_objects": [], "missing_dirs": []}
    assert f.read("/h") == b"k"
    assert not f.exists("/dangling")
    with pytest.raises(IOError):
        cl.read("fsdata", file_oid(0xdead, 0))


def test_rgw_gc(env):
    c, cl = env
    g = RGWLite(cl, "rgwmeta", "rgwdata")
    g.create_user("u")
    g.create_bucket("u", "b")
    g.put_object("b", "live", b"live-data")
    mpid = g.initiate_multipart("b", "inflight")
    g.upload_part("b", "inflight", mpid, 1, b"part")
    bid = g.get_bucket("b")["id"]
    idx = g._index_oid(bid)
    # crashed put: prepare + chunks, never completed
    g._exec("rgwmeta", idx, "bucket_prepare_op",
            {"tag": "deadtag", "name": "ghost", "op": "put"})
    g._write_chunked(g._data_oid(bid, "ghost"), b"stranded")
    report = g.gc()
    assert g._data_oid(bid, "ghost") in report["orphan_objects"]
    assert ["b", "deadtag"] in report["stale_pending"]
    # live data and active multipart parts are NOT flagged
    assert g._data_oid(bid, "live") not in report["orphan_objects"]
    assert not any("_mp_inflight" in o for o in report["orphan_objects"])
    # repair collects the debt; everything live still works
    g.gc(repair=True)
    assert g.gc() == {"orphan_objects": [], "stale_pending": []}
    assert g.get_object("b", "live") == b"live-data"
    g.upload_part("b", "inflight", mpid, 2, b"-two")
    g.complete_multipart("b", "inflight", mpid)
    assert g.get_object("b", "inflight") == b"part-two"


def test_gc_collects_deleted_bucket_debris(env):
    """Crashed put, then bucket rm: the stranded chunks' bucket id no
    longer exists, but gc still reclaims them (bid-pattern match, not
    known-bucket membership)."""
    c, cl = env
    g = RGWLite(cl, "rgwmeta", "rgwdata")
    g.create_user("u")
    g.create_bucket("u", "doomed")
    bid = g.get_bucket("doomed")["id"]
    g._exec("rgwmeta", g._index_oid(bid), "bucket_prepare_op",
            {"tag": "t", "name": "ghost", "op": "put"})
    g._write_chunked(g._data_oid(bid, "ghost"), b"stranded")
    g.delete_bucket("doomed")          # num_objects==0: delete passes
    report = g.gc(repair=True)
    assert g._data_oid(bid, "ghost") in report["orphan_objects"]
    with pytest.raises(IOError):
        cl.read("rgwdata", g._data_oid(bid, "ghost"))


def test_fsck_withholds_purge_on_missing_dir(env):
    """A lost directory OBJECT makes its subtree's inos unknowable;
    fsck must report the orphan candidates but NOT delete them — that
    data is what a recovery would rebuild from."""
    c, cl = env
    f = CephFS(cl, "fsmeta", "fsdata")
    f.mkfs()
    f.mkdir("/broken")
    f.create("/broken/file", ORDER)
    f.write("/broken/file", b"survivor")
    ino = f.stat("/broken/file")["ino"]
    dino = f.stat("/broken")["ino"]
    cl.remove("fsmeta", dir_oid(dino))     # lose the dir object
    report = f.fsck(repair=True)
    assert "/broken" in report["missing_dirs"]
    assert file_oid(ino, 0) in report["orphan_objects"]
    # withheld: the data object survives despite repair=True
    assert cl.read("fsdata", file_oid(ino, 0)).startswith(b"survivor")


def test_cli_verbs(env, capsys):
    c, cl = env
    from ceph_tpu.tools import cephfs_cli, rgw_admin
    f = CephFS(cl, "fsmeta", "fsdata")
    f.mkfs()
    f.create("/x", ORDER)
    assert cephfs_cli.run(c, cl, ["fsck"]) == 0
    assert json.loads(capsys.readouterr().out)["orphan_objects"] == []
    g = RGWLite(cl, "rgwmeta", "rgwdata")
    g.create_user("u")
    assert rgw_admin.run(c, cl, ["gc", "list"]) == 0
    assert json.loads(capsys.readouterr().out)["stale_pending"] == []
