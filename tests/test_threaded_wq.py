"""ShardedThreadPool: real worker threads draining the sharded op queue.

The reference drains its sharded op queue with a ShardedThreadPool
(common/WorkQueue.h:618; OSD.cc:2008 osd_op_tp) and serializes per-PG
via pg->lock() in dequeue_op.  These tests require: genuine concurrency
(two workers demonstrably inside handlers at once), per-shard FIFO
survival, a deliberate lock-order inversion DETECTED by lockdep under
real threads, and a MiniCluster running green with threads on.
"""
import threading
import time

import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.common.lockdep import (
    DebugLock, LockOrderError, lockdep_enable, lockdep_reset,
)
from ceph_tpu.common.work_queue import (
    CLASS_CLIENT, ShardedOpWQ, ShardedThreadPool,
)


def test_pool_runs_handlers_concurrently_and_keeps_shard_fifo():
    wq = ShardedOpWQ(n_shards=4)
    seen = {}
    peak = [0]
    active = [0]
    gate = threading.Lock()

    def handler(item):
        pgid, seq = item
        with gate:
            active[0] += 1
            peak[0] = max(peak[0], active[0])
        time.sleep(0.002)       # give workers a window to overlap
        with gate:
            seen.setdefault(pgid, []).append(seq)
            active[0] -= 1

    pool = ShardedThreadPool(wq, handler, n_threads=3)
    try:
        pgids = [(0, i) for i in range(8)]
        for seq in range(30):
            for pgid in pgids:
                wq.enqueue(pgid, CLASS_CLIENT, (pgid, seq))
        pool.flush()
    finally:
        pool.stop()
    # every op handled, per-PG order preserved (same shard => FIFO)
    for pgid in pgids:
        assert seen[pgid] == list(range(30)), pgid
    assert peak[0] >= 2, "workers never actually overlapped"


def test_lockdep_catches_inversion_under_real_threads():
    """Two workers take (A then B) and (B then A): lockdep must flag
    the cycle from a real thread, not a simulated drain."""
    lockdep_reset()
    lockdep_enable(True)
    try:
        A, B = DebugLock("inv-A"), DebugLock("inv-B")
        wq = ShardedOpWQ(n_shards=2)
        sync = threading.Barrier(2, timeout=5.0)
        errors = []

        def handler(item):
            first, second = item
            try:
                with first:
                    sync.wait()     # both workers hold their first lock
                    time.sleep(0.01)
                    with second:
                        pass
            except LockOrderError as e:
                errors.append(e)
            except threading.BrokenBarrierError:
                pass

        pool = ShardedThreadPool(wq, handler, n_threads=2)
        try:
            wq.enqueue((0, 0), CLASS_CLIENT, (A, B))   # shard 0
            wq.enqueue((0, 1), CLASS_CLIENT, (B, A))   # shard 1
            deadline = time.monotonic() + 10
            while not errors and time.monotonic() < deadline:
                time.sleep(0.02)
        finally:
            pool.stop()
        assert errors, "lock-order inversion went undetected"
        assert "inv-" in str(errors[0])
    finally:
        lockdep_enable(False)
        lockdep_reset()


@pytest.fixture
def threaded_conf():
    g_conf.set_val("osd_op_num_threads", 3)
    lockdep_reset()
    lockdep_enable(True)
    yield
    lockdep_enable(False)
    lockdep_reset()
    g_conf.set_val("osd_op_num_threads", 0)


def test_cluster_green_with_threads_on(threaded_conf):
    """EC write/read/degraded-read/recovery with every OSD draining its
    op queue from a real thread pool, lockdep armed."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    assert all(o.op_tp is not None for o in c.osds.values())
    c.create_ec_pool("p", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.th")
    rng = np.random.default_rng(8)
    blobs = {}
    for i in range(12):
        data = rng.integers(0, 256, 4000 + i * 37,
                            dtype=np.uint8).tobytes()
        blobs[f"o{i}"] = data
        assert cl.write_full("p", f"o{i}", data) == 0
    for oid, data in blobs.items():
        assert cl.read("p", oid) == data
    # kill + detect + recover, all with threaded drains
    pgid, primary = cl._calc_target(cl.lookup_pool("p"), "o0")
    victim = next(o for o in range(6) if o != primary)
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    c.run_recovery()
    c.network.pump()
    for oid, data in blobs.items():
        assert cl.read("p", oid) == data
    assert cl.write_full("p", "after", b"threads-on") == 0
    assert cl.read("p", "after") == b"threads-on"
    c.scrub()
