"""radosgw-admin cram parity: the reference's recorded help
transcript (src/test/cli/radosgw-admin/help.t) replayed byte-exact —
the full usage surface of src/rgw/rgw_admin.cc including its exit-1
contract."""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cram import assert_cram  # noqa: E402

REF = "/root/reference/src/test/cli/radosgw-admin"


@pytest.mark.parametrize("name", ["help.t"])
def test_rgw_admin_cram(name, tmp_path):
    path = os.path.join(REF, name)
    if not os.path.exists(path):
        pytest.skip("reference cram corpus not present")
    assert_cram(path, str(tmp_path))
