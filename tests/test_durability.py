"""Durability + resume: store files, mon store, cluster checkpoint/restart.

Models the reference's persistence story (SURVEY §5 checkpoint/resume):
BlueStore transactions -> MemStore.save/load files; the mon store ->
Monitor.save/load (full epoch history); OSD::init resume -> mount store,
replay map incrementals, re-peer (OSD.cc:2469+).  Kill-and-restart must
bring every object back byte-exact, including pg logs for delta recovery.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.os_store import MemStore, Transaction, hobject_t


def payload(n=30000, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def test_memstore_save_load_roundtrip(tmp_path):
    s = MemStore()
    t = Transaction()
    t.create_collection("c1")
    t.write("c1", hobject_t("a", 2), 0, b"hello world")
    t.setattr("c1", hobject_t("a", 2), "k", b"\x00\xffbin")
    t.omap_setkeys("c1", hobject_t("a", 2), {"o1": b"v1", "o2": b"v2"})
    t.create_collection("c2")
    t.write("c2", hobject_t("b"), 5, b"offset")
    s.queue_transaction(t)
    p = str(tmp_path / "store.bin")
    s.save(p)
    s2 = MemStore.load(p)
    assert s2.list_collections() == ["c1", "c2"]
    assert s2.read("c1", hobject_t("a", 2)) == b"hello world"
    assert s2.getattr("c1", hobject_t("a", 2), "k") == b"\x00\xffbin"
    assert s2.omap_get("c1", hobject_t("a", 2)) == {"o1": b"v1",
                                                    "o2": b"v2"}
    assert s2.read("c2", hobject_t("b")) == b"\x00" * 5 + b"offset"
    assert s2.committed_txns == s.committed_txns


def test_osdmap_encoding_roundtrip():
    """Encoded->decoded maps must map PGs identically (the encode/decode
    parity the reference pins with ceph-object-corpus)."""
    from ceph_tpu.osdmap import pg_t
    from ceph_tpu.osdmap.encoding import osdmap_from_dict, osdmap_to_dict
    import json
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=16, plugin="tpu")
    c.create_replicated_pool("r", size=3, pg_num=8)
    c.mon.mark_osd_out(2)
    m = c.mon.osdmap
    # through actual JSON text to prove serializability
    m2 = osdmap_from_dict(json.loads(json.dumps(osdmap_to_dict(m))))
    assert m2.epoch == m.epoch
    for pool_id, pool in m.pools.items():
        for ps in range(pool.pg_num):
            assert m2.pg_to_up_acting_osds(pg_t(pool_id, ps)) == \
                m.pg_to_up_acting_osds(pg_t(pool_id, ps))


def test_cluster_checkpoint_restore(tmp_path):
    c = MiniCluster(n_osds=7)
    c.create_ec_pool("ec", k=4, m=2, pg_num=8, plugin="tpu")
    c.create_replicated_pool("rep", size=3, pg_num=8)
    cl = c.client("client.w")
    objs = {f"o{i}": payload(seed=i) for i in range(4)}
    for oid, d in objs.items():
        assert cl.write_full("ec", oid, d) == 0
    # partial write so the rmw path's state persists too
    patch = payload(1000, seed=99)
    assert cl.write("ec", "o0", patch, offset=5000) == 0
    body = bytearray(objs["o0"])
    body[5000:6000] = patch
    objs["o0"] = bytes(body)
    assert cl.write_full("rep", "r0", payload(seed=50)) == 0

    c.checkpoint(str(tmp_path / "ckpt"))
    del c

    c2 = MiniCluster.restore(str(tmp_path / "ckpt"))
    cl2 = c2.client("client.r")
    for oid, d in objs.items():
        assert cl2.read("ec", oid) == d, oid
    assert cl2.read("rep", "r0") == payload(seed=50)
    # the restored cluster is fully operational: degraded read + write
    holders = {o.osd_id for o in c2.osds.values()
               if any(ho.oid == "o1" for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    _, primary = cl2._calc_target(cl2.lookup_pool("ec"), "o1")
    victim = next(o for o in holders if o != primary)
    c2.kill_osd(victim)
    c2.mark_osd_down(victim)
    assert cl2.read("ec", "o1") == objs["o1"]
    assert cl2.write_full("ec", "new", payload(seed=77)) == 0
    assert cl2.read("ec", "new") == payload(seed=77)


def test_osd_restart_resumes_from_store():
    """Daemon restart: fresh OSD process mounts the same store; pg logs
    reload and delta recovery applies only what was missed."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=1, plugin="tpu")
    cl = c.client("client.rs")
    for i in range(4):
        assert cl.write_full("p", f"o{i}", payload(seed=i)) == 0
    holders = {o.osd_id for o in c.osds.values()
               if any(ho.oid == "o0" for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    _, primary = cl._calc_target(cl.lookup_pool("p"), "o0")
    victim = next(o for o in holders if o != primary)
    # log state before the restart
    pgid = next(iter(c.osds[victim].pgs))
    head_before = c.osds[victim].pgs[pgid].pg_log.head
    assert head_before > 0
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    assert cl.write_full("p", "while_down", payload(seed=10)) == 0
    before = sum(o.perf["recovery_push"] for o in c.osds.values())
    c.restart_osd(victim)
    c.run_recovery()
    after = sum(o.perf["recovery_push"] for o in c.osds.values())
    # the restarted osd's log came back from its store...
    assert c.osds[victim].pgs[pgid].pg_log.head >= head_before
    # ...so only the delta moved
    assert after - before == 1, (before, after)
    for i in range(4):
        assert cl.read("p", f"o{i}") == payload(seed=i)
    assert cl.read("p", "while_down") == payload(seed=10)
