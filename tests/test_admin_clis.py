"""Admin CLIs for the client layer: radosgw-admin and cephfs shells.

Mirrors the reference's admin-tool surface (src/rgw/rgw_admin.cc,
cephfs-shell): user/bucket administration and fs manipulation drive the
same library paths the gateways use.
"""
import json
import os
import sys

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.tools import cephfs_cli, rgw_admin

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cram import assert_cram  # noqa: E402


@pytest.fixture()
def env():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("rgwmeta", size=3, pg_num=8)
    c.create_replicated_pool("rgwdata", size=3, pg_num=8)
    c.create_replicated_pool("fsmeta", size=3, pg_num=8)
    c.create_replicated_pool("fsdata", size=3, pg_num=8)
    return c, c.client("client.cli")


def test_fault_cli_cram(tmp_path):
    """`ceph daemon <who> fault inject|list|clear` replayed from a
    recorded transcript (tests/cli/fault.t), byte-exact like the
    reference's src/test/cli corpora: the injection-site catalog, an
    armed trigger's dump, the unknown-site refusal and the clear."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "fault.t")
    assert_cram(path, str(tmp_path))


def test_prof_cli_cram(tmp_path):
    """`ceph daemon <who> prof dump|reset` replayed from a recorded
    transcript (tests/cli/prof.t): the zeroed device-flow profile of a
    restored cluster and the reset — through the same `ceph` shim as
    fault.t (the populated ledger is covered in-process by
    tests/test_devprof.py)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "prof.t")
    assert_cram(path, str(tmp_path))


def test_oplat_cli_cram(tmp_path):
    """`ceph daemon <who> latency dump|reset` replayed from a recorded
    transcript (tests/cli/oplat.t): the zeroed stage-latency ledger of
    a restored cluster (stage catalog pinned) and the reset — through
    the same `ceph` shim as fault.t/prof.t (the populated per-stage
    table is covered in-process by tests/test_oplat.py)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "oplat.t")
    assert_cram(path, str(tmp_path))


def test_mesh_skew_cli_cram(tmp_path):
    """`ceph daemon <who> mesh skew dump|reset` replayed from a
    recorded transcript (tests/cli/mesh.t): the zeroed chip-health
    scoreboard of a restored cluster (option defaults, hysteresis
    constants and counter catalog pinned) and the reset — through the
    same `ceph` shim as fault.t (the populated scoreboard and the
    TPU_MESH_SKEW lifecycle are covered in-process by
    tests/test_mesh_skew.py)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "mesh.t")
    assert_cram(path, str(tmp_path))


def test_control_cli_cram(tmp_path):
    """`ceph daemon <who> tpu control dump` and the
    enable/disable/reset verbs replayed from a recorded transcript
    (tests/cli/control.t): the observe-only default pane of a restored
    cluster (knob bounds, option defaults, empty ledger pinned) —
    through the same `ceph` shim as fault.t (the populated ledger and
    the closed-loop episodes are covered in-process by
    tests/test_control.py and tests/test_control_loop.py)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "control.t")
    assert_cram(path, str(tmp_path))


def test_incident_cli_cram(tmp_path):
    """`ceph daemon <who> tpu incident list|dump|capture` and
    `journal dump|reset` replayed from a recorded transcript
    (tests/cli/incident.t): the clean black box of a restored cluster
    (zero bundles, empty rings, clock at zero), an operator capture's
    receipt, and the journal reset — through the same `ceph` shim as
    fault.t (auto-capture on a health raise and the causal bundle
    timeline are covered in-process by tests/test_incident.py)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "incident.t")
    assert_cram(path, str(tmp_path))


def test_chaos_cli_cram(tmp_path):
    """`ceph daemon <who> chaos dump|compose` replayed from a recorded
    transcript (tests/cli/chaos.t): the engine pane of a restored
    cluster (leg catalog, fault-site inventory, zeroed counters,
    option defaults pinned), the deterministic storyline composed from
    pinned seed 24, and the missing-seed refusal — through the same
    `ceph` shim as fault.t (same-seed schedule equality and the full
    run_scenario universal acceptance are covered in-process by
    tests/test_chaos_composer.py)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "chaos.t")
    assert_cram(path, str(tmp_path))


def test_status_cli_cram(tmp_path):
    """`ceph daemon <who> tpu status` + `telemetry dump|reset`
    replayed from a recorded transcript (tests/cli/status.t): the
    single-pane status and rollup dump of a restored cluster (rates
    catalog, objectives table, SLO/breaker panes pinned) — through
    the same `ceph` shim as fault.t (the populated rollup and a live
    SLO breach are covered in-process by tests/test_telemetry.py)."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "cli", "status.t")
    assert_cram(path, str(tmp_path))


def test_rgw_admin_flow(env, capsys):
    c, cl = env
    run = lambda *a: rgw_admin.run(c, cl, list(a))
    assert run("user", "create", "--uid", "bob",
               "--display-name", "Bob") == 0
    out = json.loads(capsys.readouterr().out)
    assert out["uid"] == "bob" and out["access_key"]
    run("user", "info", "--uid", "bob")
    assert json.loads(capsys.readouterr().out)["display_name"] == "Bob"
    run("user", "list")
    assert "bob" in capsys.readouterr().out.split()

    from ceph_tpu.rgw import RGWLite
    g = RGWLite(cl, "rgwmeta", "rgwdata")
    g.create_bucket("bob", "pics")
    g.put_object("pics", "a.jpg", b"jpeg")
    run("bucket", "list", "--uid", "bob")
    assert "pics" in capsys.readouterr().out.split()
    run("bucket", "list", "--bucket", "pics")
    assert "a.jpg" in capsys.readouterr().out.split()
    run("bucket", "stats", "--bucket", "pics")
    stats = json.loads(capsys.readouterr().out)
    assert stats["num_objects"] == 1 and stats["size_bytes"] == 4
    # user rm refused while owning buckets
    assert run("user", "rm", "--uid", "bob") == 1
    g.delete_object("pics", "a.jpg")
    run("bucket", "rm", "--bucket", "pics")
    assert run("user", "rm", "--uid", "bob") == 0
    run("user", "list")
    assert "bob" not in capsys.readouterr().out.split()


def test_cephfs_cli_flow(env, tmp_path, capsys):
    c, cl = env
    run = lambda *a: cephfs_cli.run(c, cl, list(a))
    run("mkfs")
    run("mkdir", "/docs")
    src = tmp_path / "in.txt"
    src.write_bytes(b"file-body")
    run("put", str(src), "/docs/readme")
    run("cat", "/docs/readme")
    assert capsys.readouterr().out == "file-body"
    run("ln", "/docs/readme", "/latest")
    run("cat", "/latest")
    assert capsys.readouterr().out == "file-body"
    run("ls", "/")
    out = capsys.readouterr().out
    assert "docs" in out
    assert any(line.startswith("l") and "latest" in line
               for line in out.splitlines())
    run("mv", "/docs/readme", "/docs/manual")
    dst = tmp_path / "out.txt"
    run("get", "/docs/manual", str(dst))
    assert dst.read_bytes() == b"file-body"
    run("tree", "/")
    tree = capsys.readouterr().out
    assert "/docs" in tree and "manual" in tree
    run("stat", "/docs/manual")
    assert json.loads(capsys.readouterr().out)["type"] == "file"
    run("rm", "/docs/manual")
    run("rmdir", "/docs")
    run("ls", "/")
    assert "docs" not in capsys.readouterr().out
