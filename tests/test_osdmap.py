"""OSDMap layer: object→PG, the mapping pipeline, incrementals, batch cache.

Mirrors the reference's test/osd/TestOSDMap.cc checks: up/acting through
upmap, pg_temp, primary affinity, down/out OSDs; plus batch-vs-scalar
equality for OSDMapMapping (the device/native/host batch backends must agree
with pg_to_up_acting_osds everywhere).
"""
import numpy as np
import pytest

from ceph_tpu.crush import CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE
from ceph_tpu.osdmap import (
    Incremental, OSDMap, OSDMapMapping, TYPE_ERASURE, TYPE_REPLICATED,
    pg_pool_t, pg_t,
)
from ceph_tpu.utils import ceph_str_hash_rjenkins


def build_osdmap(n_hosts=5, per_host=4, pg_num=64, ec=False):
    m = OSDMap()
    m.epoch = 1
    n = n_hosts * per_host
    m.set_max_osd(n)
    cw = m.crush
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    host_ids = []
    for h in range(n_hosts):
        osds = list(range(h * per_host, (h + 1) * per_host))
        hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds,
                            [0x10000] * per_host, id=-(h + 2))
        host_ids.append(hid)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", host_ids,
                  [0x10000 * per_host] * n_hosts, id=-1)
    for i in range(n):
        m.set_osd(i, up=True)
    if ec:
        rno = cw.add_simple_rule("ecrule", "default", "host", mode="indep",
                                 rule_type=TYPE_ERASURE)
        cw.set_rule_mask_max_size(rno, 10)
        pool = pg_pool_t(type=TYPE_ERASURE, size=6, min_size=5,
                         crush_rule=rno, pg_num=pg_num, pgp_num=pg_num)
    else:
        rno = cw.add_simple_rule("replicated_rule", "default", "host",
                                 mode="firstn")
        pool = pg_pool_t(type=TYPE_REPLICATED, size=3, min_size=2,
                         crush_rule=rno, pg_num=pg_num, pgp_num=pg_num)
    pid = m.add_pool("rbd", pool)
    return m, pid, n


def test_object_to_pg_stable():
    m, pid, _ = build_osdmap()
    pg = m.map_to_pg(pid, "foo")
    assert pg.pool == pid
    assert pg.ps == ceph_str_hash_rjenkins("foo")
    # namespace changes the hash
    pg2 = m.map_to_pg(pid, "foo", nspace="ns")
    assert pg2.ps != pg.ps


def test_basic_mapping_properties():
    m, pid, n = build_osdmap()
    pool = m.get_pg_pool(pid)
    seen = set()
    for ps in range(pool.pg_num):
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg_t(pid, ps))
        assert len(up) == 3
        assert len(set(up)) == 3
        # one per host
        hosts = {o // 4 for o in up}
        assert len(hosts) == 3
        assert upp == up[0]
        assert acting == up
        seen.update(up)
    assert len(seen) > n // 2


def test_down_osd_drops_from_up():
    m, pid, _ = build_osdmap()
    target = None
    for ps in range(64):
        up, _, _, _ = m.pg_to_up_acting_osds(pg_t(pid, ps))
        if 0 in up:
            target = ps
            break
    assert target is not None
    m.set_osd(0, up=False)  # down but still in
    up, _, _, _ = m.pg_to_up_acting_osds(pg_t(pid, target))
    assert 0 not in up


def test_out_osd_remapped():
    m, pid, _ = build_osdmap()
    pgs_with_0 = [ps for ps in range(64)
                  if 0 in m.pg_to_up_acting_osds(pg_t(pid, ps))[0]]
    m.osd_weight[0] = 0  # marked out
    for ps in pgs_with_0:
        up, _, _, _ = m.pg_to_up_acting_osds(pg_t(pid, ps))
        assert 0 not in up
        assert len(up) == 3


def test_pg_temp_overrides_acting():
    m, pid, _ = build_osdmap()
    pg = pg_t(pid, 5)
    up, upp, _, _ = m.pg_to_up_acting_osds(pg)
    tmp = [o for o in range(12, 15)]
    m.pg_temp[pg] = tmp
    up2, upp2, acting, actp = m.pg_to_up_acting_osds(pg)
    assert up2 == up and upp2 == upp
    assert acting == tmp
    assert actp == tmp[0]
    m.primary_temp[pg] = tmp[2]
    *_, actp2 = m.pg_to_up_acting_osds(pg)
    assert actp2 == tmp[2]


def test_pg_upmap_and_items():
    m, pid, _ = build_osdmap()
    pg = pg_t(pid, 9)
    up, *_ = m.pg_to_up_acting_osds(pg)
    # full upmap
    explicit = [1, 6, 13]
    m.pg_upmap[pg] = explicit
    up2, *_ = m.pg_to_up_acting_osds(pg)
    assert up2 == explicit
    del m.pg_upmap[pg]
    # item remap: swap first to some unused osd
    src = up[0]
    dst = next(o for o in range(m.max_osd) if o not in up)
    m.pg_upmap_items[pg] = [(src, dst)]
    up3, *_ = m.pg_to_up_acting_osds(pg)
    assert dst in up3 and src not in up3
    # remap to an out osd is ignored
    m.osd_weight[dst] = 0
    up4, *_ = m.pg_to_up_acting_osds(pg)
    assert up4 == up
    m.osd_weight[dst] = 0x10000
    # a pg_upmap with an out target voids the whole override, including
    # pg_upmap_items (OSDMap.cc:1971 early return)
    m.osd_weight[1] = 0
    m.pg_upmap[pg] = [1, 6, 13]
    up5, *_ = m.pg_to_up_acting_osds(pg)
    assert up5 == up


def test_primary_affinity_shifts_lead():
    m, pid, _ = build_osdmap()
    m.set_primary_affinity(0, 0)  # never primary
    for ps in range(64):
        up, upp, _, _ = m.pg_to_up_acting_osds(pg_t(pid, ps))
        if 0 in up:
            assert upp != 0
            assert up[0] == upp  # replicated pools shift primary to front


def test_incremental_roundtrip():
    m, pid, _ = build_osdmap()
    inc = Incremental(epoch=2)
    inc.new_up[3] = False
    inc.new_weight[7] = 0
    m.apply_incremental(inc)
    assert m.epoch == 2
    assert m.is_down(3)
    assert m.is_out(7)
    inc2 = Incremental(epoch=3)
    inc2.new_pg_temp[pg_t(pid, 1)] = [2, 6, 10]
    m.apply_incremental(inc2)
    assert m.pg_temp[pg_t(pid, 1)] == [2, 6, 10]


@pytest.mark.parametrize("ec", [False, True])
def test_batch_mapping_matches_scalar(ec):
    m, pid, n = build_osdmap(pg_num=128, ec=ec)
    # sprinkle state: down, out, reweighted, affinity, overrides
    m.set_osd(2, up=False)
    m.osd_weight[5] = 0
    m.osd_weight[9] = 0x8000
    m.set_primary_affinity(1, 0x4000)
    m.pg_temp[pg_t(pid, 3)] = [15, 16, 17]
    m.primary_temp[pg_t(pid, 7)] = 11
    if not ec:
        m.pg_upmap_items[pg_t(pid, 11)] = [(0, 19)]
    mapping = OSDMapMapping()
    mapping.update(m)
    for ps in range(128):
        pg = pg_t(pid, ps)
        up, upp, acting, actp = m.pg_to_up_acting_osds(pg)
        bup, bupp, bacting, bactp = mapping.get(pg)
        assert bup == up, (ps, bup, up)
        assert bupp == upp, ps
        assert bacting == acting, (ps, bacting, acting)
        assert bactp == actp, ps


def test_batch_mapping_host_fallback_agrees():
    m, pid, n = build_osdmap(pg_num=64)
    dev = OSDMapMapping(use_device=True)
    host = OSDMapMapping(use_device=False, use_native=False)
    dev.update(m)
    host.update(m)
    for ps in range(64):
        assert dev.get(pg_t(pid, ps)) == host.get(pg_t(pid, ps))
    assert dev.last_backend[pid] == "device"
    assert host.last_backend[pid] == "host"
