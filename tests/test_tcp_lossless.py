"""Lossless-peer TCP policy: reconnect + resend, exactly-once.

The reference gives daemon<->daemon connections the lossless_peer
policy — messages survive a dropped TCP connection via seq numbers,
acks, and reconnect-resend — while client links are lossy and rely on
the Objecter's resend machinery (src/msg/Messenger.h Policy;
AsyncConnection replay on reconnect).  These tests kill live sockets
mid-stream and assert exactly-once, in-order delivery between daemons,
lossy-drop behavior for clients, and the same guarantees with cephx
signing enabled.
"""
from __future__ import annotations

import threading
import time

import pytest

from ceph_tpu.auth import Keyring
from ceph_tpu.msg.messages import MMonPing
from ceph_tpu.msg.messenger import Dispatcher
from ceph_tpu.msg.tcp import TcpAuth, TcpNetwork


class _Sink(Dispatcher):
    def __init__(self):
        self.got = []

    def ms_fast_dispatch(self, msg):
        self.got.append(msg)


def _free_port():
    import socket as sk
    s = sk.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class _Server:
    """Pump a net from a dedicated thread (it owns the net's fds)."""

    def __init__(self, net):
        self.net = net
        self.stop = threading.Event()
        self.t = threading.Thread(target=self._run, daemon=True)
        self.t.start()

    def _run(self):
        while not self.stop.is_set():
            self.net.pump(quiesce=0.01, deadline=0.1)

    def close(self):
        self.stop.set()
        self.t.join()


def _kill_outbound(net):
    """Hard-close every outbound socket (TCP reset analog)."""
    for addr in list(net._conns):
        net._drop_conn(addr)


def test_daemon_links_are_lossless_across_resets():
    pa, pb = _free_port(), _free_port()
    directory = {"osd.0": ("127.0.0.1", pa), "osd.1": ("127.0.0.1", pb)}
    a = TcpNetwork(("127.0.0.1", pa), directory, entity="osd.0")
    b = TcpNetwork(("127.0.0.1", pb), directory, entity="osd.1")
    sink = _Sink()
    b.create_messenger("osd.1").add_dispatcher_head(sink)
    srv = _Server(b)
    try:
        for i in range(20):
            a.send("osd.0", "osd.1", MMonPing(rank=i))
        a.pump(quiesce=0.02, deadline=2.0)
        _kill_outbound(a)                   # reset mid-stream
        for i in range(20, 40):
            a.send("osd.0", "osd.1", MMonPing(rank=i))
        end = time.monotonic() + 15
        while time.monotonic() < end and len(sink.got) < 40:
            a.pump(quiesce=0.02, deadline=0.3)
    finally:
        srv.close()
        a.close()
        b.close()
    assert [m.rank for m in sink.got] == list(range(40))


def test_unacked_resend_does_not_duplicate():
    """Kill the connection AFTER delivery but (possibly) before the
    ack lands: the reconnect resend must be dropped by seq, so the
    receiver sees each message exactly once."""
    pa, pb = _free_port(), _free_port()
    directory = {"mon": ("127.0.0.1", pa), "osd.1": ("127.0.0.1", pb)}
    a = TcpNetwork(("127.0.0.1", pa), directory, entity="mon")
    b = TcpNetwork(("127.0.0.1", pb), directory, entity="osd.1")
    sink = _Sink()
    b.create_messenger("osd.1").add_dispatcher_head(sink)
    srv = _Server(b)
    try:
        for round_no in range(5):
            base = round_no * 10
            for i in range(base, base + 10):
                a.send("mon", "osd.1", MMonPing(rank=i))
            end = time.monotonic() + 10
            while time.monotonic() < end and len(sink.got) < base + 10:
                a.pump(quiesce=0.02, deadline=0.3)
            # reset WITHOUT waiting for acks to drain
            _kill_outbound(a)
        end = time.monotonic() + 10
        while time.monotonic() < end and len(sink.got) < 50:
            a.pump(quiesce=0.02, deadline=0.3)
    finally:
        srv.close()
        a.close()
        b.close()
    assert [m.rank for m in sink.got] == list(range(50))


def test_client_links_stay_lossy():
    """A client net has no lossless queue: sends to a dead peer are
    dropped (the Objecter layer owns retries), and nothing accumulates."""
    pa = _free_port()
    dead = _free_port()                     # nobody listens here
    directory = {"client.x": ("127.0.0.1", pa),
                 "osd.0": ("127.0.0.1", dead)}
    a = TcpNetwork(("127.0.0.1", pa), directory, entity="client.x")
    try:
        before = a.dropped
        for i in range(5):
            a.send("client.x", "osd.0", MMonPing(rank=i))
        a.pump(quiesce=0.01, deadline=2.0)
        assert a.dropped == before + 5
        assert not a._sess_tx               # no lossless state grew
    finally:
        a.close()


def test_lossless_with_auth_signing(tmp_path):
    """Signed frames + lossless resend compose: daemons re-handshake
    (cephx + session hello) on reconnect and still deliver
    exactly-once."""
    kr = Keyring()
    for e in ("mon", "osd.0", "osd.1"):
        kr.create(e)
    path = str(tmp_path / "keyring")
    kr.save(path)
    pm, pa, pb = _free_port(), _free_port(), _free_port()
    directory = {"mon": ("127.0.0.1", pm),
                 "osd.0": ("127.0.0.1", pa),
                 "osd.1": ("127.0.0.1", pb)}
    mon = TcpNetwork(("127.0.0.1", pm), directory,
                     auth=TcpAuth("mon", path, kdc=True))
    a = TcpNetwork(("127.0.0.1", pa), directory,
                   auth=TcpAuth("osd.0", path))
    b = TcpNetwork(("127.0.0.1", pb), directory,
                   auth=TcpAuth("osd.1", path))
    sink = _Sink()
    b.create_messenger("osd.1").add_dispatcher_head(sink)
    srv_mon, srv_b = _Server(mon), _Server(b)
    try:
        assert a.authenticate()
        # osd.1 needs rotating keys to verify osd.0's authorizer
        assert b.authenticate()
        for i in range(15):
            a.send("osd.0", "osd.1", MMonPing(rank=i))
        end = time.monotonic() + 10
        while time.monotonic() < end and len(sink.got) < 15:
            a.pump(quiesce=0.02, deadline=0.3)
        _kill_outbound(a)
        for i in range(15, 30):
            a.send("osd.0", "osd.1", MMonPing(rank=i))
        end = time.monotonic() + 15
        while time.monotonic() < end and len(sink.got) < 30:
            a.pump(quiesce=0.02, deadline=0.3)
    finally:
        srv_mon.close()
        srv_b.close()
        for n in (mon, a, b):
            n.close()
    assert [m.rank for m in sink.got] == list(range(30))
    assert b.auth_rejects == 0


def test_rebooted_peer_seq_space_resets():
    """A daemon that dies and reboots restarts its send seqs at 1; the
    receiver must treat the new incarnation as a fresh session instead
    of swallowing every frame as a reconnect duplicate (the reference's
    peer-reset detection: msg/simple/Pipe.cc "existing connection
    reset" zeroes in_seq via the addr nonce + connect_seq exchange)."""
    pa, pb = _free_port(), _free_port()
    directory = {"osd.0": ("127.0.0.1", pa), "mon": ("127.0.0.1", pb)}
    mon_net = TcpNetwork(("127.0.0.1", pb), directory, entity="mon")
    sink = _Sink()
    mon_net.create_messenger("mon").add_dispatcher_head(sink)
    srv = _Server(mon_net)
    a = TcpNetwork(("127.0.0.1", pa), directory, entity="osd.0")
    try:
        for i in range(5):
            a.send("osd.0", "mon", MMonPing(rank=i))
        a.pump(quiesce=0.02, deadline=2.0)
        deadline = time.monotonic() + 5
        while len(sink.got) < 5 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert len(sink.got) == 5
        # daemon reboot: same entity + port, fresh process state
        a.close()
        a = TcpNetwork(("127.0.0.1", pa), directory, entity="osd.0")
        for i in range(5, 9):
            a.send("osd.0", "mon", MMonPing(rank=i))
        a.pump(quiesce=0.02, deadline=2.0)
        deadline = time.monotonic() + 5
        while len(sink.got) < 9 and time.monotonic() < deadline:
            a.pump(quiesce=0.02, deadline=0.2)
        assert [m.rank for m in sink.got] == list(range(9)), \
            "post-reboot frames were dropped as stale-session duplicates"
    finally:
        srv.close()
        a.close()
        mon_net.close()
