"""SnapMapper index + purged_snaps trim catch-up.

The reference pairs a snap->object omap index (src/osd/SnapMapper.cc,
get_next_objects_to_trim) with pg_info_t.purged_snaps so the trimmer
touches only the objects that matter and a primary dying mid-trim is
finished by its successor.  These tests cover the framework's analogs:
the derived SnapMapper index, its maintenance across clone/trim/split,
and the failover catch-up.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.snap_mapper import (SnapMapper, decode_purged,
                                      encode_purged)
from ceph_tpu.osd.pg_log import SNAP_CLONE, SNAP_TRIMMED, SNAP_WHITEOUT


# ---- unit: the index itself -------------------------------------------------

def test_covered_snaps_windows():
    # entry (seq, kind) covers (prev_seq, seq]
    entries = [(5, SNAP_CLONE), (9, SNAP_WHITEOUT)]
    assert SnapMapper.covered_snaps(entries, [3, 5, 7, 9, 11]) == {3, 5, 7, 9}
    # tombstones cover nothing
    assert SnapMapper.covered_snaps(
        [(5, SNAP_TRIMMED), (9, SNAP_CLONE)], [3, 7]) == {7}
    assert SnapMapper.covered_snaps([], [1, 2]) == set()


def test_update_oid_and_lookup():
    m = SnapMapper()
    m.update_oid("a", [(5, SNAP_CLONE)], [3, 5])
    m.update_oid("b", [(5, SNAP_CLONE)], [5])
    assert m.lookup(3) == {"a"}
    assert m.lookup(5) == {"a", "b"}
    # trim a: memberships drop out
    m.update_oid("a", [(5, SNAP_TRIMMED)], [3, 5])
    assert m.lookup(3) == set()
    assert m.lookup(5) == {"b"}
    # delete b entirely
    m.update_oid("b", [], [5])
    assert m.lookup(5) == set()
    assert m.by_snap == {} and m.by_oid == {}


def test_rebuild_matches_incremental():
    m1, m2 = SnapMapper(), SnapMapper()
    sets = {"x": [(4, SNAP_CLONE), (8, SNAP_CLONE)],
            "y": [(6, SNAP_WHITEOUT)],
            "z": [(8, SNAP_TRIMMED)]}
    interesting = [2, 4, 6, 8]
    for oid, ents in sets.items():
        m1.update_oid(oid, ents, interesting)
    m2.rebuild(sets, interesting)
    assert m1.by_snap == m2.by_snap
    assert m1.by_oid == m2.by_oid


def test_purged_codec_roundtrip():
    assert decode_purged(encode_purged({7, 3, 99})) == {3, 7, 99}
    assert decode_purged(b"") == set()


# ---- integration ------------------------------------------------------------

def _clone_count(c):
    n = 0
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if "\x00snap\x00" in ho.oid:
                    n += 1
    return n


def _pgs_of(c, pool, oid):
    cl = c.client("client.probe")
    pid = cl.lookup_pool(pool)
    pgid, _primary = cl._calc_target(pid, oid)
    return [osd.pgs[pgid] for osd in c.osds.values()
            if pgid in osd.pgs]


def test_mapper_indexes_only_touched_heads():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("sp", size=3, pg_num=8)
    cl = c.client("client.s")
    for i in range(6):
        cl.write_full("sp", f"o{i}", b"base")
    sid = c.pool_snap_create("sp", "s1")
    cl.write_full("sp", "o2", b"changed")      # only o2 clones
    hit = set()
    for osd in c.osds.values():
        for pg in osd.pgs.values():
            hit |= pg.snap_mapper.lookup(sid)
    assert hit == {"o2"}


def test_trim_updates_index_and_purged():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("sp", size=3, pg_num=8)
    cl = c.client("client.s")
    cl.write_full("sp", "o", b"v1")
    sid = c.pool_snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    assert _clone_count(c) > 0
    c.pool_snap_rm("sp", "s1")
    c.network.pump()
    assert _clone_count(c) == 0
    pgs = _pgs_of(c, "sp", "o")
    prim = next(p for p in pgs if p.is_primary())
    assert sid in prim.purged_snaps
    assert prim.snap_mapper.lookup(sid) == set()


def test_partial_trim_keeps_truthful_index():
    """A clone window covering a live AND a removed snap keeps its
    entry at trim; the index keeps truthfully reporting that the clone
    still covers the removed snap (purged_snaps is a hint, the index is
    ground truth) and stays exactly what rebuild() would produce."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("sp", size=3, pg_num=8)
    cl = c.client("client.s")
    cl.write_full("sp", "o", b"v1")
    s1 = c.pool_snap_create("sp", "s1")
    s2 = c.pool_snap_create("sp", "s2")
    cl.write_full("sp", "o", b"v2")      # one clone covers {s1, s2}
    c.pool_snap_rm("sp", "s1")
    c.network.pump()
    # clone survives (s2 still live in its window) ...
    assert _clone_count(c) > 0
    assert cl.read("sp", "o", snap="s2") == b"v1"
    for pg in _pgs_of(c, "sp", "o"):
        if not pg.is_primary():
            continue
        assert s1 in pg.purged_snaps
        fresh = SnapMapper()
        fresh.rebuild(pg.snapsets, pg._interesting_snaps())
        assert pg.snap_mapper.by_snap == fresh.by_snap
        assert pg.snap_mapper.by_oid == fresh.by_oid
        # truth: the surviving clone still covers s1
        assert pg.snap_mapper.lookup(s1) == {"o"}
    # removing s2 releases the clone (and the s1 membership with it)
    c.pool_snap_rm("sp", "s2")
    c.network.pump()
    assert _clone_count(c) == 0


def test_stale_purged_marker_is_redone():
    """A purged marker without the trim work behind it (primary killed
    between staging purged and the fan-out landing) must not suppress
    the trim: the index still shows references, so it reruns."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("sp", size=3, pg_num=8)
    cl = c.client("client.s")
    cl.write_full("sp", "o", b"v1")
    sid = c.pool_snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    assert _clone_count(c) > 0
    # forge the crash artifact: purged says done, nothing was done
    for pg in _pgs_of(c, "sp", "o"):
        if pg.is_primary():
            pg._adopt_purged([sid])
    c.pool_snap_rm("sp", "s1")
    c.network.pump()
    assert _clone_count(c) == 0
    assert cl.read("sp", "o") == b"v2"


def test_trim_survives_primary_failover():
    """Primary never sees the snap removal; its successor owes (and
    pays) the trim at activation — the purged_snaps catch-up."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("sp", size=3, pg_num=8)
    cl = c.client("client.s")
    cl.write_full("sp", "o", b"v1")
    sid = c.pool_snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    assert _clone_count(c) > 0
    pid = cl.lookup_pool("sp")
    _pgid, primary = cl._calc_target(pid, "o")
    # the primary dies BEFORE the removal epoch reaches it
    c.kill_osd(primary)
    c.pool_snap_rm("sp", "s1")
    c.mark_osd_down(primary)
    c.mark_osd_out(primary)
    c.tick(rounds=3)
    # survivors: clones trimmed by the successor primary
    live_clones = 0
    for oid_, osd in c.osds.items():
        if oid_ == primary:
            continue
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if "\x00snap\x00" in ho.oid:
                    live_clones += 1
    assert live_clones == 0
    pgs = [p for p in _pgs_of(c, "sp", "o")
           if p.osd.osd_id != primary]
    assert any(sid in p.purged_snaps for p in pgs)
    # the old primary comes back: log replay + snapset/purged adoption
    # deletes its stale clone instead of resurrecting it
    c.revive_osd(primary)
    c.tick(rounds=3)
    assert _clone_count(c) == 0
    assert cl.read("sp", "o") == b"v2"


def test_purged_snaps_survive_checkpoint_restore(tmp_path):
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("sp", size=3, pg_num=8)
    cl = c.client("client.s")
    cl.write_full("sp", "o", b"v1")
    sid = c.pool_snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    c.pool_snap_rm("sp", "s1")
    c.network.pump()
    c.checkpoint(str(tmp_path / "ck"))
    c2 = MiniCluster.restore(str(tmp_path / "ck"))
    pgs = _pgs_of(c2, "sp", "o")
    assert pgs and all(sid in p.purged_snaps for p in pgs
                       if p.is_primary())
    # and the trim does not rerun / the index stays empty for it
    assert all(p.snap_mapper.lookup(sid) == set() for p in pgs)


def test_mapper_follows_pg_split():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("sp", size=3, pg_num=4)
    cl = c.client("client.s")
    for i in range(8):
        cl.write_full("sp", f"o{i}", b"base")
    sid = c.pool_snap_create("sp", "s1")
    for i in range(8):
        cl.write_full("sp", f"o{i}", b"changed")   # all clone
    c.mon.set_pool_pg_num("sp", 8)
    c.publish()
    c.tick(rounds=3)
    # every head is indexed exactly where its snapset now lives
    for osd in c.osds.values():
        for pg in osd.pgs.values():
            for oid in pg.snap_mapper.lookup(sid):
                assert oid in pg.snapsets
    hit = set()
    for osd in c.osds.values():
        for pg in osd.pgs.values():
            hit |= pg.snap_mapper.lookup(sid)
    assert hit == {f"o{i}" for i in range(8)}
    # trimming after the split cleans everything
    c.pool_snap_rm("sp", "s1")
    c.network.pump()
    c.tick(rounds=2)
    assert _clone_count(c) == 0
