"""EC interrupted-write consistency (divergent-log rewind + rollback).

The reference makes EC writes atomic-per-stripe with append-only writes
plus roll-back info in the PG log (ECTransaction.h rollback extents;
doc/dev/osd_internals/erasure_coding/ecbackend.rst:1-27) and rewinds
divergent entries at peering (src/osd/PGLog.cc rewind_divergent_log /
merge_log).  These tests kill the primary between the MOSDECSubOpWrite
fan-out and all_commit and prove the stripe converges: every surviving
shard lands on ONE version and reads return either the old or the new
payload, never a torn mix.
"""
import struct

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.os_store import hobject_t
from ceph_tpu.osd.pg_log import VERSION_ATTR

OLD = b"A" * 4096
NEW = b"B" * 4096


def make_cluster():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("dp", k=2, m=1, plugin="isa", pg_num=1)
    return c, c.client("client.d")


def pg_of(c, cl, oid):
    pid = cl.lookup_pool("dp")
    pgid, primary = cl._calc_target(pid, oid)
    return pgid, primary, c.osds[primary].pgs[pgid]


def shard_versions(c, pgid, oid):
    """shard position -> stored VERSION_ATTR across every live osd."""
    out = {}
    for osd in c.osds.values():
        pg = osd.pgs.get(pgid)
        if pg is None or pg.backend is None:
            continue
        shard = pg.my_shard()
        if shard < 0:
            continue
        cid = pg.backend.shard_cid(shard)
        ho = hobject_t(oid, shard)
        store = osd.store
        if store.collection_exists(cid) and store.exists(cid, ho):
            try:
                v = struct.unpack(
                    "<Q", store.getattr(cid, ho, VERSION_ATTR))[0]
            except KeyError:
                v = 0
            out[shard] = v
    return out


def settle(c, ticks=6):
    for _ in range(ticks):
        c.tick(dt=6.0)
    c.run_recovery()
    c.network.pump()


def test_partial_fanout_rolls_back_to_old_data():
    """Write reaches fewer than k shards before the primary dies: the
    divergent entry must be rolled back and reads must return the OLD
    payload — the new one is undecodable and was never acked."""
    c, cl = make_cluster()
    assert cl.write_full("dp", "o", OLD) == 0
    pgid, primary, pg = pg_of(c, cl, "o")
    others = [o for o in pg.acting if o != primary]
    # the fan-out to every non-primary shard goes dark: only the
    # primary's own shard applies the new version
    for o in others:
        c.network.blackhole(f"osd.{primary}", f"osd.{o}")
    r = cl.write_full("dp", "o", NEW)
    assert r != 0            # all_commit never fired: no ack
    vs = shard_versions(c, pgid, "o")
    assert len(set(vs.values())) == 2, vs     # genuinely torn right now
    for o in others:
        c.network.blackhole(f"osd.{primary}", f"osd.{o}", on=False)
    c.kill_osd(primary)
    settle(c)
    assert cl.read("dp", "o") == OLD
    # the divergent shard rejoins: peering must rewind it via its
    # rollback stash, converging every shard on the old version
    c.revive_osd(primary)
    settle(c)
    settle(c)
    vs = shard_versions(c, pgid, "o")
    assert len(set(vs.values())) == 1, vs
    assert cl.read("dp", "o") == OLD
    # the pool keeps working at full health: a new write commits
    assert cl.write_full("dp", "o", b"C" * 1024) == 0
    assert cl.read("dp", "o") == b"C" * 1024


def test_full_fanout_unacked_rolls_forward_to_new_data():
    """Every shard applied the write but the primary died before acking:
    >= k shards hold the new version, so peering rolls FORWARD and reads
    return the NEW payload."""
    c, cl = make_cluster()
    assert cl.write_full("dp", "o", OLD) == 0
    pgid, primary, pg = pg_of(c, cl, "o")
    others = [o for o in pg.acting if o != primary]
    # fan-out delivers everywhere; the commit REPLIES go dark, so
    # all_commit never fires on the primary and the client sees no ack
    for o in others:
        c.network.blackhole(f"osd.{o}", f"osd.{primary}")
    r = cl.write_full("dp", "o", NEW)
    assert r != 0
    vs = shard_versions(c, pgid, "o")
    assert len(set(vs.values())) == 1, vs     # all applied the write
    for o in others:
        c.network.blackhole(f"osd.{o}", f"osd.{primary}", on=False)
    c.kill_osd(primary)
    settle(c)
    assert cl.read("dp", "o") == NEW
    c.revive_osd(primary)
    settle(c)
    settle(c)
    vs = shard_versions(c, pgid, "o")
    assert len(set(vs.values())) == 1, vs
    assert cl.read("dp", "o") == NEW


def test_divergent_delete_rolls_back():
    """A delete that reached only a minority of shards is rolled back:
    the object survives with its pre-delete payload and attrs."""
    c, cl = make_cluster()
    assert cl.write_full("dp", "o", OLD) == 0
    assert cl.setxattr("dp", "o", "tag", b"keep") == 0
    pgid, primary, pg = pg_of(c, cl, "o")
    others = [o for o in pg.acting if o != primary]
    for o in others:
        c.network.blackhole(f"osd.{primary}", f"osd.{o}")
    cl.remove("dp", "o")     # applies only on the primary's shard
    for o in others:
        c.network.blackhole(f"osd.{primary}", f"osd.{o}", on=False)
    c.kill_osd(primary)
    settle(c)
    assert cl.read("dp", "o") == OLD
    c.revive_osd(primary)
    settle(c)
    settle(c)
    vs = shard_versions(c, pgid, "o")
    assert len(set(vs.values())) == 1, vs
    assert cl.read("dp", "o") == OLD
    assert cl.getxattr("dp", "o", "tag") == b"keep"


def test_thrash_partial_fanouts_never_torn():
    """Thrasher-style loop: repeated partial fan-outs + primary kills.
    Invariant after every convergence: the read returns a payload some
    client write actually produced — never a torn mix."""
    c, cl = make_cluster()
    payloads = [bytes([0x41 + i]) * 2048 for i in range(4)]
    assert cl.write_full("dp", "t", payloads[0]) == 0
    legal = {payloads[0]}
    for i in range(1, 4):
        pgid, primary, pg = pg_of(c, cl, "t")
        others = [o for o in pg.acting if o != primary]
        dark = others[: i % 2 + 1]       # vary how far the fan-out got
        for o in dark:
            c.network.blackhole(f"osd.{primary}", f"osd.{o}")
        r = cl.write_full("dp", "t", payloads[i])
        legal.add(payloads[i])
        for o in dark:
            c.network.blackhole(f"osd.{primary}", f"osd.{o}", on=False)
        c.kill_osd(primary)
        settle(c)
        data = cl.read("dp", "t")
        assert data in legal, f"torn read on round {i}"
        c.revive_osd(primary)
        settle(c)
        settle(c)
        data2 = cl.read("dp", "t")
        assert data2 in legal, f"torn read after rejoin on round {i}"
        vs = shard_versions(c, pgid, "t")
        assert len(set(vs.values())) == 1, vs
        # re-establish a known committed baseline for the next round
        assert cl.write_full("dp", "t", payloads[i]) == 0
        legal = {payloads[i]}


def test_delete_replay_does_not_clobber_rollback_stash():
    """A resent delete whose log entry was dropped as stale (the shard's
    head had already advanced past it, so the log-based replay dedup
    can never see it) must not re-stash: the second apply would capture
    POST-delete state and peering's rollback would then restore
    'absent' instead of the pre-delete body."""
    from ceph_tpu.msg.messages import MOSDECSubOpWrite
    from ceph_tpu.osd.pg_log import load_rollback

    c, cl = make_cluster()
    assert cl.write_full("dp", "a", OLD) == 0
    assert cl.write_full("dp", "b", NEW) == 0
    pgid, primary, _pg = pg_of(c, cl, "a")
    # pick a non-primary shard holder and replay a delete there whose
    # version sits at the shard's head (so append_log drops the entry)
    osd = next(o for o in c.osds.values()
               if o.osd_id != primary and pgid in o.pgs
               and o.pgs[pgid].my_shard() >= 0)
    pg = osd.pgs[pgid]
    shard = pg.my_shard()
    head = pg.pg_log.head
    msg = MOSDECSubOpWrite(tid=991, pgid=pgid, shard=shard, oid="a",
                           chunk=b"", at_version=-1, version=head)
    msg.src = f"osd.{primary}"
    osd._apply_delete(msg)
    stash = load_rollback(osd.store, pg.meta_cid(), "a")
    assert stash is not None and stash[0] == head and stash[1], \
        "first apply must stash the pre-delete (existing) state"
    osd._apply_delete(msg)  # replay: ack was lost, fan resends
    stash = load_rollback(osd.store, pg.meta_cid(), "a")
    assert stash is not None and stash[0] == head and stash[1], \
        "replay clobbered the rollback stash with post-delete state"
