"""Test environment: force JAX onto a virtual 8-device CPU mesh.

Real-TPU runs happen via bench.py / the driver; tests must be hermetic and
exercise the multi-chip sharding path, so we ask XLA for 8 host devices.
Must run before jax is imported anywhere.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
