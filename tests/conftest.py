"""Test environment: force JAX onto a virtual 8-device CPU mesh.

Real-TPU runs happen via bench.py / the driver; tests must be hermetic and
exercise the multi-chip sharding path, so we ask XLA for 8 host devices.
Must run before jax is imported anywhere.
"""
import os

# Override, don't setdefault: the driver environment pre-sets JAX_PLATFORMS
# to the real-chip tunnel, but unit tests need the virtual 8-CPU mesh.
# Set CEPH_TPU_TEST_REAL=1 to run the suite against the real device instead.
# Always expose 8 virtual host devices: even in real-device mode the
# mesh-sized tests fall back to the host platform (make_mesh).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

if not os.environ.get("CEPH_TPU_TEST_REAL"):
    os.environ["JAX_PLATFORMS"] = "cpu"
    # The axon PJRT plugin (sitecustomize) already imported jax and forced
    # jax_platforms="axon,cpu"; the config value wins over the env var, so
    # force it back.  Backends are created lazily, so as long as no test
    # module touched a device yet this reliably lands on the virtual mesh.
    import jax
    jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache, shared with bench.py: XLA compiles
    # dominate the crush device/fast suites on a 1-core box (the exact64
    # kernels alone cost minutes cold); with the on-disk cache warm the
    # tier-1 suite fits its wall budget with room to spare.
    cache_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)


def pytest_runtest_protocol(item, nextitem):
    """Single auto-rerun for ``@pytest.mark.loadflaky`` tests.

    The two vstart thrash tests are known to flake ONLY under
    concurrent CPU load (verified pre-existing at their parent
    commits: both pass in isolation and in green full-suite runs) —
    their mon kill/revive event-waits time out when the box is
    oversubscribed.  One retry on a FRESH cluster (all fixtures torn
    down, module-scoped ProcessCluster included, so the rerun doesn't
    inherit a wedged quorum) keeps pre-existing load flakes from
    masking real regressions; a deterministic failure still fails
    twice and surfaces."""
    if item.get_closest_marker("loadflaky") is None:
        return None
    from _pytest.runner import runtestprotocol
    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(r.failed for r in reports):
        import warnings
        warnings.warn(f"loadflaky rerun: {item.nodeid} failed once, "
                      "retrying on a fresh cluster")
        try:
            # finalize EVERY live fixture so the retry boots clean
            item.session._setupstate.teardown_exact(None)
        except Exception:
            pass
        item._initrequest()
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for report in reports:
        item.ihook.pytest_runtest_logreport(report=report)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True
