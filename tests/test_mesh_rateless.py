"""Rateless coded mesh encode (ceph_tpu/mesh/rateless.py) — the
straggler-proof flush's acceptance gates.

- ``ec_mesh_rateless`` off (the default) is the block-sharded SPMD
  path by construction; on, every flushed encode group over-decomposes
  into coded row-blocks and completes from the first sufficient
  subset;
- byte identity: rateless-coded groups vs the single-device oracle
  across randomized (k, m, technique, chunk, stripes) mixes including
  non-multiple-of-mesh totals, with skew sampling on EVERY flush;
- the chaos-style ISSUE acceptance: a hard ``mesh.chip_fail``
  mid-flush completes every op from the surviving subset — host
  re-solves, zero single-device fallbacks — and only when the
  survivors cannot span does the flush degrade down the ladder
  (single-device, then host twin), still byte-identical;
- scoreboard feedback: a SUSPECT chip is deweighted to parity-only
  (zero real stripes on the occupancy table) and the flush stops
  waiting for it; once healed it clears through its parity probes;
- a rateless cluster twin stores shard BODIES byte-identical to the
  unprotected twin;
- observability: the ``mesh_rateless_*`` counter family on perf dump
  / ``dispatch dump`` / Prometheus, the rateless pane's geometry;
- fence-count gate extended: the rateless path adds ZERO
  ``block_until_ready`` beyond the existing drain policy (readiness
  polling + np.asarray fetches only), sampling on or off.
"""
import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.dispatch import g_dispatcher
from ceph_tpu.ec.isa import ErasureCodeIsa
from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
from ceph_tpu.fault import g_faults
from ceph_tpu.mesh import (g_chipstat, g_mesh, mesh_perf_counters,
                           rateless_perf_counters)
from ceph_tpu.mesh.rateless import (l_rl_chip_failures,
                                    l_rl_coded_tasks, l_rl_flushes,
                                    l_rl_host_resolves,
                                    l_rl_insufficient,
                                    l_rl_subset_completions,
                                    l_rl_suspect_deweights,
                                    l_rl_wasted_blocks)
from ceph_tpu.mesh.runtime import l_mesh_dispatches, l_mesh_fallbacks
from ceph_tpu.osd.ecutil import encode as eu_encode, stripe_info_t


@pytest.fixture
def rateless_conf():
    """Every test leaves the dispatcher drained, the options at their
    defaults, the scoreboard zeroed and the mesh torn down."""
    yield
    g_faults.clear()
    g_dispatcher.flush()
    for name in ("ec_mesh_chips", "ec_mesh_rateless",
                 "ec_mesh_rateless_tasks", "ec_mesh_skew_sample_every",
                 "ec_mesh_skew_threshold", "ec_dispatch_batch_max",
                 "ec_dispatch_batch_window_us"):
        g_conf.rm_val(name)
    g_mesh.topology()
    g_chipstat.reset()
    from ceph_tpu.fault import g_breakers
    g_breakers.reset()


def _rateless_on(chips=8, sample_every=0, tasks=0):
    g_conf.set_val("ec_mesh_chips", chips)
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    g_conf.set_val("ec_mesh_rateless", True)
    if tasks:
        g_conf.set_val("ec_mesh_rateless_tasks", tasks)
    g_conf.set_val("ec_mesh_skew_sample_every", sample_every)


def _mk_impl(plugin, k, m, technique):
    impl = plugin()
    impl.init({"k": str(k), "m": str(m), "technique": technique})
    return impl


def _same_shards(a, b):
    assert sorted(a) == sorted(b)
    for i in a:
        assert np.asarray(a[i]).tobytes() == np.asarray(b[i]).tobytes(), \
            f"shard {i} differs"


def test_rateless_off_by_default(rateless_conf):
    """The default is the SPMD path: a mesh flush with rateless off
    moves no rateless counters."""
    assert bool(g_conf.get_val("ec_mesh_rateless")) is False
    g_conf.set_val("ec_mesh_chips", 8)
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    pc = rateless_perf_counters()
    before = pc.get(l_rl_flushes)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    d = (np.arange(2 * 4 * 1024) % 251).astype(np.uint8)
    f = g_dispatcher.submit_encode(sinfo, impl, d, set(range(6)))
    g_dispatcher.flush()
    _same_shards(f.result(), eu_encode(sinfo, impl, d, set(range(6))))
    assert pc.get(l_rl_flushes) == before


MIX = [
    (ErasureCodeTpu, 4, 2, "reed_sol_van"),
    (ErasureCodeTpu, 8, 4, "reed_sol_van"),
    (ErasureCodeIsa, 3, 2, "cauchy"),
    (ErasureCodeIsa, 6, 3, "reed_sol_van"),
]


@pytest.mark.parametrize("seed", [7, 31, 61])
def test_rateless_byte_identity_property(rateless_conf, seed):
    """Rateless-coded groups vs the single-device oracle across
    randomized (k, m, technique, chunk size, stripe count) mixes —
    stripe totals deliberately NOT multiples of the mesh size, mixed
    chunk sizes sharing a bucket, and skew sampling probing EVERY
    flush (the drain-fed scoreboard must never touch the data
    path)."""
    _rateless_on(chips=8, sample_every=1)
    rng = np.random.default_rng(seed)
    impls = [_mk_impl(p, k, m, t) for p, k, m, t in MIX]
    specs = []
    for _ in range(18):
        impl = impls[rng.integers(0, len(impls))]
        k, m = impl.k, impl.m
        chunk = int(rng.choice([512, 768, 1024, 1536]))
        stripes = int(rng.integers(1, 7))     # totals rarely % 8 == 0
        sinfo = stripe_info_t(k, k * chunk)
        data = rng.integers(0, 256, size=stripes * k * chunk,
                            dtype=np.uint8)
        specs.append((sinfo, impl, data, set(range(k + m))))
    oracles = [eu_encode(s, i, d, w) for s, i, d, w in specs]
    pc = rateless_perf_counters()
    before = pc.get(l_rl_flushes)
    futs = [g_dispatcher.submit_encode(s, i, d, w)
            for s, i, d, w in specs]
    g_dispatcher.flush()
    for f, oracle in zip(futs, oracles):
        _same_shards(f.result(), oracle)
    # the rateless path actually ran (not a silent SPMD/single pass)
    assert pc.get(l_rl_flushes) > before
    assert g_chipstat.summary()["probes"] > 0


def test_chip_fail_completes_from_surviving_subset(rateless_conf):
    """THE chaos-style ISSUE acceptance: one chip hard-dead mid-flush
    (mesh.chip_fail) is just an erasure — every op completes from the
    surviving subset, byte-identical, with host re-solves and ZERO
    single-device fallbacks."""
    _rateless_on(chips=8)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    rng = np.random.default_rng(3)

    def flush_checked(n=3):
        payloads = [rng.integers(0, 256, size=3 * 4 * 1024,
                                 dtype=np.uint8) for _ in range(n)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        for f, o in zip(futs, oracles):
            _same_shards(f.result(), o)

    flush_checked()                  # warmup, healthy
    pc = rateless_perf_counters()
    mpc = mesh_perf_counters()
    fb0 = mpc.get(l_mesh_fallbacks)
    hr0 = pc.get(l_rl_host_resolves)
    cf0 = pc.get(l_rl_chip_failures)
    sc0 = pc.get(l_rl_subset_completions)
    g_faults.inject("mesh.chip_fail", mode="always", match="chip=3/")
    try:
        flush_checked()
        flush_checked()
    finally:
        g_faults.clear("mesh.chip_fail")
    assert pc.get(l_rl_host_resolves) > hr0, \
        "the dead chip's systematic block was never re-solved"
    assert pc.get(l_rl_chip_failures) >= cf0 + 2
    assert pc.get(l_rl_subset_completions) > sc0
    assert mpc.get(l_mesh_fallbacks) == fb0, \
        "a sufficient subset answered — the single-device fallback " \
        "must not be reached"


def test_insufficient_survivors_degrade_down_the_ladder(rateless_conf):
    """When fewer than a sufficient subset of chips answer (every chip
    failed), the flush degrades to the single-device path — the next
    ladder rung, not an op failure — and outputs stay byte-identical."""
    from ceph_tpu.fault import g_breakers
    _rateless_on(chips=8)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    rng = np.random.default_rng(5)
    payloads = [rng.integers(0, 256, size=2 * 4 * 1024, dtype=np.uint8)
                for _ in range(3)]
    oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
    pc = rateless_perf_counters()
    mpc = mesh_perf_counters()
    fb0 = mpc.get(l_mesh_fallbacks)
    ins0 = pc.get(l_rl_insufficient)
    g_faults.inject("mesh.chip_fail", mode="always")   # every chip
    try:
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        for f, o in zip(futs, oracles):
            _same_shards(f.result(), o)
    finally:
        g_faults.clear()
        g_breakers.reset()
    assert pc.get(l_rl_insufficient) > ins0
    assert mpc.get(l_mesh_fallbacks) > fb0


def test_suspect_chip_deweighted_to_parity_only(rateless_conf):
    """The scoreboard feedback loop (the telemetry finally actuates):
    once a chip is SUSPECT its placement carries zero real stripes —
    parity only — and the flush completes without waiting for it even
    though it is still slow."""
    import time
    _rateless_on(chips=8, sample_every=1)
    g_conf.set_val("ec_mesh_skew_threshold", 3.0)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    rng = np.random.default_rng(11)

    def flush_once():
        payloads = [rng.integers(0, 256, size=2 * 4 * 1024,
                                 dtype=np.uint8) for _ in range(3)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        t0 = time.perf_counter()
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        g_dispatcher.flush()
        for f, o in zip(futs, oracles):
            _same_shards(f.result(), o)
        return time.perf_counter() - t0

    flush_once()                     # warmup
    g_chipstat.reset()
    pc = rateless_perf_counters()
    g_faults.inject("mesh.chip_slowdown", mode="always",
                    match="chip=5/", delay_us=100_000)
    try:
        for _ in range(8):
            flush_once()
            if g_chipstat.suspects():
                break
        assert [s["chip"] for s in g_chipstat.suspects()] == [5]
        dw0 = pc.get(l_rl_suspect_deweights)
        before = {i: v["stripes"] for i, v in g_mesh.per_chip().items()}
        wall = flush_once()
        after = {i: v["stripes"] for i, v in g_mesh.per_chip().items()}
        assert after[5] == before.get(5, 0), \
            "a SUSPECT chip received real stripes"
        assert sum(after.values()) > sum(before.values())
        assert pc.get(l_rl_suspect_deweights) > dw0
        # the still-slow suspect (100 ms) never gated the flush
        assert wall < 0.09, f"flush waited for the suspect: {wall}"
    finally:
        g_faults.clear("mesh.chip_slowdown")


def test_cluster_twin_stored_shards_byte_identical(rateless_conf):
    """A rateless cluster stores shard BODIES byte-identical to the
    unprotected twin across a write/overwrite/append mix — the ISSUE's
    stored-bytes receipt, one level below the dispatch outputs."""
    from ceph_tpu.cluster import MiniCluster

    def shard_bodies(c):
        out = {}
        for i, osd in c.osds.items():
            for cid in osd.store.list_collections():
                if "_meta" in cid or "s" not in cid.split(".")[-1]:
                    continue
                for ho in osd.store.list_objects(cid):
                    out[(i, cid, str(ho))] = osd.store.read(cid, ho)
        return out

    def run(rateless: bool):
        if rateless:
            _rateless_on(chips=8)
            g_conf.set_val("ec_dispatch_batch_window_us", 200_000)
        else:
            for name in ("ec_mesh_chips", "ec_mesh_rateless",
                         "ec_dispatch_batch_max",
                         "ec_dispatch_batch_window_us"):
                g_conf.rm_val(name)
        g_mesh.topology()
        c = MiniCluster(n_osds=6)
        c.create_ec_pool("rltwin", k=3, m=2, pg_num=4)
        cl = c.client("client.rl")
        rng = np.random.default_rng(42)
        expected = {}
        for i in range(4):
            body = bytes(rng.integers(0, 256, 9000 + 4111 * i,
                                      dtype=np.uint8))
            assert cl.write_full("rltwin", f"o{i}", body) == 0
            expected[f"o{i}"] = body
        tail = bytes(rng.integers(0, 256, 5000, dtype=np.uint8))
        assert cl.append("rltwin", "o1", tail) == 0
        expected["o1"] = expected["o1"] + tail
        for oid, body in expected.items():
            assert cl.read("rltwin", oid) == body, (rateless, oid)
        return shard_bodies(c)

    pc = rateless_perf_counters()
    before = pc.get(l_rl_flushes)
    coded = run(rateless=True)
    assert pc.get(l_rl_flushes) > before
    plain = run(rateless=False)
    assert set(coded) == set(plain)
    diffs = [key for key in plain
             if bytes(coded[key]) != bytes(plain[key])]
    assert not diffs, f"{len(diffs)} shard bodies differ: {diffs[:5]}"


def test_rateless_task_knob_and_dump_pane(rateless_conf):
    """``ec_mesh_rateless_tasks`` reads live (geometry rebuilt on the
    next flush), clamps to mesh size + 1, and the rateless pane rides
    ``dispatch dump``'s mesh block with options, geometry and the
    counter family."""
    _rateless_on(chips=8, tasks=12)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    d = (np.arange(2 * 4 * 1024) % 251).astype(np.uint8)
    f = g_dispatcher.submit_encode(sinfo, impl, d, set(range(6)))
    g_dispatcher.flush()
    _same_shards(f.result(), eu_encode(sinfo, impl, d, set(range(6))))
    pane = g_dispatcher.dump()["mesh"]["rateless"]
    assert pane["options"]["ec_mesh_rateless"] is True
    assert pane["options"]["ec_mesh_rateless_tasks"] == 12
    assert pane["n_sys"] == 8 and pane["n_parity"] == 4
    assert pane["counters"]["flushes"] > 0
    assert pane["counters"]["coded_tasks"] > 0
    # under-asking clamps to one parity block (redundancy never zero)
    g_conf.set_val("ec_mesh_rateless_tasks", 3)
    f = g_dispatcher.submit_encode(sinfo, impl, d, set(range(6)))
    g_dispatcher.flush()
    f.result()
    pane = g_dispatcher.dump()["mesh"]["rateless"]
    assert pane["n_parity"] == 1


def test_wasted_blocks_account_the_bandwidth_price(rateless_conf):
    """Healthy flushes complete before consuming the parity blocks:
    wasted_blocks counts exactly the protection's bandwidth price and
    host_resolves stays zero (no erasures to solve around)."""
    _rateless_on(chips=8)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    d = (np.arange(2 * 4 * 1024) % 251).astype(np.uint8)
    f = g_dispatcher.submit_encode(sinfo, impl, d, want)
    g_dispatcher.flush()
    f.result()                       # warmup builds plans
    pc = rateless_perf_counters()
    w0, c0, h0 = (pc.get(l_rl_wasted_blocks), pc.get(l_rl_coded_tasks),
                  pc.get(l_rl_host_resolves))
    f = g_dispatcher.submit_encode(sinfo, impl, d, want)
    g_dispatcher.flush()
    f.result()
    coded = pc.get(l_rl_coded_tasks) - c0
    wasted = pc.get(l_rl_wasted_blocks) - w0
    assert coded == 10               # 8 systematic + 2 parity (auto)
    assert 0 < wasted <= 2, wasted   # at most the parity overhead
    assert pc.get(l_rl_host_resolves) == h0


def test_zero_syncs_on_rateless_path(rateless_conf, monkeypatch):
    """Fence-count gate extended (ISSUE satellite): the rateless path
    adds ZERO block_until_ready beyond the existing drain policy —
    readiness polling plus np.asarray fetches only — with sampling
    off AND with probes on every flush."""
    import jax
    _rateless_on(chips=8, sample_every=0)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    d = (np.arange(3 * 4 * 1024) % 251).astype(np.uint8)
    f = g_dispatcher.submit_encode(sinfo, impl, d, want)
    g_dispatcher.flush()
    f.result()                       # compile warmup
    calls = {"n": 0}
    real = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    for sample_every in (0, 1):
        g_conf.set_val("ec_mesh_skew_sample_every", sample_every)
        f = g_dispatcher.submit_encode(sinfo, impl, d, want)
        g_dispatcher.flush()
        f.result()
        assert calls["n"] == 0, \
            f"rateless path synced (sample_every={sample_every})"


def test_rateless_counters_on_prometheus(rateless_conf):
    """The mesh_rateless_* family renders on the mgr's Prometheus
    surface (golden-test satellite) and on perf dump."""
    from ceph_tpu.cluster import MiniCluster
    _rateless_on(chips=8)
    g_conf.set_val("ec_dispatch_batch_window_us", 200_000)
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("rlprom", k=3, m=2, pg_num=4)
    cl = c.client("client.rlprom")
    assert cl.write_full("rlprom", "o", b"r" * 60000) == 0
    prom = c.admin_socket.execute("prometheus metrics")
    for cname in ("flushes", "coded_tasks", "parity_tasks",
                  "wasted_blocks", "subset_completions",
                  "host_resolves", "suspect_deweights"):
        line = next((ln for ln in prom.splitlines()
                     if ln.startswith(f"ceph_daemon_mesh_rateless_"
                                      f"{cname} ")), None)
        assert line is not None, f"mesh_rateless_{cname} not exported"
    flushes = next(float(ln.split()[-1]) for ln in prom.splitlines()
                   if ln.startswith("ceph_daemon_mesh_rateless_"
                                    "flushes "))
    assert flushes > 0
