"""MDS daemon over a real multi-process cluster: SIGKILL + journal
replay recovery, with every request crossing TCP sockets.

The in-process tests (test_mds.py) cover the crash WINDOW (journaled
but unapplied events); this tier proves the process-level contract:
a kill -9'd MDS daemon restarts, re-opens the fs pools, replays its
MDS journal, and keeps serving the same namespace.
"""
import time

import pytest

from ceph_tpu.cephfs.mds_client import RemoteCephFS
from ceph_tpu.vstart import ProcessCluster


@pytest.fixture(scope="module")
def cluster():
    c = ProcessCluster(n_osds=3, n_mds=1,
                       client_names=("client.x", "client.y"),
                       heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


def _retrying(fn, timeout=150.0):
    end = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except IOError as e:
            if time.monotonic() > end:
                raise
            time.sleep(0.5)


@pytest.mark.slow   # ~50 s of real-process spin-up/kill/replay; the same
# contract is exercised in tier-1 by test_multi_active_subtrees_and_per_rank
# _failover (kill -9 + journal replay of rank 1) and in-process test_mds.py
def test_mds_sigkill_replay_recovers(cluster):
    c = cluster
    cl = c.client("client.x")
    c.wait_healthy(cl)
    fs = RemoteCephFS(cl, "mds.0")
    _retrying(lambda: fs.mkdir("/d"))
    fs.create("/d/f")
    fs.write("/d/f", b"survives kill -9", 0)
    fs.rename("/d/f", "/d/g")
    assert fs.read("/d/g") == b"survives kill -9"

    c.kill_mds(0)
    c.restart_mds(0)

    # a NEW session sees the recovered namespace (journal replayed)
    fs2 = RemoteCephFS(c.client("client.y"), "mds.0")
    assert _retrying(lambda: fs2.read("/d/g")) == b"survives kill -9"
    assert not fs2.exists("/d/f")
    # and the daemon keeps serving mutations
    fs2.mkdir("/post")
    fs2.create("/post/new")
    fs2.write("/post/new", b"after restart", 0)
    assert fs2.read("/post/new") == b"after restart"


@pytest.fixture(scope="module")
def ha_cluster():
    c = ProcessCluster(n_osds=3, n_mds=2, mds_grace=4.0,
                       client_names=("client.x", "client.y"),
                       heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


@pytest.mark.slow   # ~45 s; standby promotion + replay is also covered by
# the multi-active per-rank failover test that stays in tier-1
def test_mds_standby_takeover(ha_cluster):
    """MDS HA (MDSMonitor + standby daemons): two mds processes beacon
    to the mon; the first is active, the second stands by.  SIGKILL
    the active: the mon's beacon grace fails it over, the standby
    opens the fs, REPLAYS the MDS journal, and the client re-resolves
    the active from the replicated fsmap and keeps working."""
    c = ha_cluster
    cl = c.client("client.x")
    c.wait_healthy(cl)
    fs = RemoteCephFS(cl, mds_name=None)      # resolve via the fsmap
    _retrying(lambda: fs.mkdir("/ha"))
    fs.create("/ha/f")
    fs.write("/ha/f", b"pre-failover", 0)
    st = cl.mon_command("fs_status")
    first_active = st["active"][0]
    assert st["standby"], st                  # a standby is seated
    # kill the ACTIVE mds daemon
    active_idx = int(first_active.split(".")[1])
    c.kill_mds(active_idx)
    # the client's next ops ride the failover: re-resolve + retry
    # (generous: under an 8-worker xdist load the daemons starve)
    end = time.monotonic() + 150.0
    while True:
        try:
            assert fs.read("/ha/f") == b"pre-failover"
            break
        except IOError:
            if time.monotonic() > end:
                raise
            time.sleep(1.0)
    st = cl.mon_command("fs_status")
    assert st["active"] and st["active"][0] != first_active
    # and the promoted daemon serves mutations
    fs.write("/ha/f", b"post-failover", 0)
    fs.mkdir("/ha/sub")
    fs2 = RemoteCephFS(c.client("client.y"), mds_name=None)
    assert fs2.read("/ha/f") == b"post-failover"
    assert fs2.exists("/ha/sub")


@pytest.fixture(scope="module")
def multi_cluster():
    c = ProcessCluster(n_osds=3, n_mds=3, mds_grace=4.0,
                       client_names=("client.x", "client.y"),
                       heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


def _wait_status(cl, pred, timeout=150.0):
    """Event wait on the replicated fsmap (poll the map state, not
    wall time)."""
    end = time.monotonic() + timeout
    while True:
        try:
            st = cl.mon_command("fs_status")
            if st and pred(st):
                return st
        except (IOError, ValueError):
            pass
        if time.monotonic() > end:
            raise AssertionError(f"fsmap never satisfied: {st}")
        time.sleep(0.5)


def test_multi_active_subtrees_and_per_rank_failover(multi_cluster):
    """Two active ranks over real processes: disjoint pinned subtrees
    served concurrently; SIGKILL of rank 1 recovers ONLY rank 1 (the
    standby replays mdlog.1 and takes the rank; rank 0's incumbency
    is untouched); clients re-route via forwards + the fsmap."""
    c = multi_cluster
    cl = c.client("client.x")
    c.wait_healthy(cl)
    # grow to two ranks: a standby is promoted into rank 1
    _retrying(lambda: cl.mon_command("fs_set_max_mds", n=2))
    st = _wait_status(cl, lambda s: len(s.get("ranks", {})) == 2)
    rank0_before = st["ranks"]["0"]
    rank1_before = st["ranks"]["1"]
    assert st["standby"]                      # one standby remains
    fs = RemoteCephFS(cl, mds_name=None)
    _retrying(lambda: fs.mkdir("/zero"))
    fs.mkdir("/one")
    fs.set_dir_pin("/one", 1)
    fs.create("/zero/f")
    fs.write("/zero/f", b"rank-zero-data", 0)
    fs.create("/one/f")                       # forwarded to rank 1
    fs.write("/one/f", b"rank-one-data", 0)
    assert fs.read("/one/f") == b"rank-one-data"
    # SIGKILL the rank-1 daemon only
    c.kill_mds(int(rank1_before.split(".")[1]))
    st = _wait_status(cl, lambda s:
                      s.get("ranks", {}).get("1") not in
                      (None, rank1_before))
    assert st["ranks"]["0"] == rank0_before   # rank 0 untouched
    # the promoted daemon replayed mdlog.1: /one is intact and serves
    fs2 = RemoteCephFS(c.client("client.y"), mds_name=None)
    end = time.monotonic() + 150.0
    while True:
        try:
            assert fs2.read("/one/f") == b"rank-one-data"
            break
        except IOError:
            if time.monotonic() > end:
                raise
            time.sleep(1.0)
    fs2.write("/one/f", b"post-failover!", 0)
    assert fs2.read("/one/f") == b"post-failover!"
    # rank 0's subtree never blinked
    assert fs2.read("/zero/f") == b"rank-zero-data"
