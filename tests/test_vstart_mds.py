"""MDS daemon over a real multi-process cluster: SIGKILL + journal
replay recovery, with every request crossing TCP sockets.

The in-process tests (test_mds.py) cover the crash WINDOW (journaled
but unapplied events); this tier proves the process-level contract:
a kill -9'd MDS daemon restarts, re-opens the fs pools, replays its
MDS journal, and keeps serving the same namespace.
"""
import time

import pytest

from ceph_tpu.cephfs.mds_client import RemoteCephFS
from ceph_tpu.vstart import ProcessCluster


@pytest.fixture(scope="module")
def cluster():
    c = ProcessCluster(n_osds=3, n_mds=1,
                       client_names=("client.x", "client.y"),
                       heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


def _retrying(fn, timeout=45.0):
    end = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except IOError as e:
            if time.monotonic() > end:
                raise
            time.sleep(0.5)


def test_mds_sigkill_replay_recovers(cluster):
    c = cluster
    cl = c.client("client.x")
    c.wait_healthy(cl)
    fs = RemoteCephFS(cl, "mds.0")
    _retrying(lambda: fs.mkdir("/d"))
    fs.create("/d/f")
    fs.write("/d/f", b"survives kill -9", 0)
    fs.rename("/d/f", "/d/g")
    assert fs.read("/d/g") == b"survives kill -9"

    c.kill_mds(0)
    c.restart_mds(0)

    # a NEW session sees the recovered namespace (journal replayed)
    fs2 = RemoteCephFS(c.client("client.y"), "mds.0")
    assert _retrying(lambda: fs2.read("/d/g")) == b"survives kill -9"
    assert not fs2.exists("/d/f")
    # and the daemon keeps serving mutations
    fs2.mkdir("/post")
    fs2.create("/post/new")
    fs2.write("/post/new", b"after restart", 0)
    assert fs2.read("/post/new") == b"after restart"
