"""MDS daemon over a real multi-process cluster: SIGKILL + journal
replay recovery, with every request crossing TCP sockets.

The in-process tests (test_mds.py) cover the crash WINDOW (journaled
but unapplied events); this tier proves the process-level contract:
a kill -9'd MDS daemon restarts, re-opens the fs pools, replays its
MDS journal, and keeps serving the same namespace.
"""
import time

import pytest

from ceph_tpu.cephfs.mds_client import RemoteCephFS
from ceph_tpu.vstart import ProcessCluster


@pytest.fixture(scope="module")
def cluster():
    c = ProcessCluster(n_osds=3, n_mds=1,
                       client_names=("client.x", "client.y"),
                       heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


def _retrying(fn, timeout=150.0):
    end = time.monotonic() + timeout
    while True:
        try:
            return fn()
        except IOError as e:
            if time.monotonic() > end:
                raise
            time.sleep(0.5)


def test_mds_sigkill_replay_recovers(cluster):
    c = cluster
    cl = c.client("client.x")
    c.wait_healthy(cl)
    fs = RemoteCephFS(cl, "mds.0")
    _retrying(lambda: fs.mkdir("/d"))
    fs.create("/d/f")
    fs.write("/d/f", b"survives kill -9", 0)
    fs.rename("/d/f", "/d/g")
    assert fs.read("/d/g") == b"survives kill -9"

    c.kill_mds(0)
    c.restart_mds(0)

    # a NEW session sees the recovered namespace (journal replayed)
    fs2 = RemoteCephFS(c.client("client.y"), "mds.0")
    assert _retrying(lambda: fs2.read("/d/g")) == b"survives kill -9"
    assert not fs2.exists("/d/f")
    # and the daemon keeps serving mutations
    fs2.mkdir("/post")
    fs2.create("/post/new")
    fs2.write("/post/new", b"after restart", 0)
    assert fs2.read("/post/new") == b"after restart"


@pytest.fixture(scope="module")
def ha_cluster():
    c = ProcessCluster(n_osds=3, n_mds=2, mds_grace=4.0,
                       client_names=("client.x", "client.y"),
                       heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


def test_mds_standby_takeover(ha_cluster):
    """MDS HA (MDSMonitor + standby daemons): two mds processes beacon
    to the mon; the first is active, the second stands by.  SIGKILL
    the active: the mon's beacon grace fails it over, the standby
    opens the fs, REPLAYS the MDS journal, and the client re-resolves
    the active from the replicated fsmap and keeps working."""
    c = ha_cluster
    cl = c.client("client.x")
    c.wait_healthy(cl)
    fs = RemoteCephFS(cl, mds_name=None)      # resolve via the fsmap
    _retrying(lambda: fs.mkdir("/ha"))
    fs.create("/ha/f")
    fs.write("/ha/f", b"pre-failover", 0)
    st = cl.mon_command("fs_status")
    first_active = st["active"][0]
    assert st["standby"], st                  # a standby is seated
    # kill the ACTIVE mds daemon
    active_idx = int(first_active.split(".")[1])
    c.kill_mds(active_idx)
    # the client's next ops ride the failover: re-resolve + retry
    # (generous: under an 8-worker xdist load the daemons starve)
    end = time.monotonic() + 150.0
    while True:
        try:
            assert fs.read("/ha/f") == b"pre-failover"
            break
        except IOError:
            if time.monotonic() > end:
                raise
            time.sleep(1.0)
    st = cl.mon_command("fs_status")
    assert st["active"] and st["active"][0] != first_active
    # and the promoted daemon serves mutations
    fs.write("/ha/f", b"post-failover", 0)
    fs.mkdir("/ha/sub")
    fs2 = RemoteCephFS(c.client("client.y"), mds_name=None)
    assert fs2.read("/ha/f") == b"post-failover"
    assert fs2.exists("/ha/sub")
