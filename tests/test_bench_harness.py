"""The measurement harness measures the measurer.

Round 5's verdict: the published 807 GiB/s encode number was physically
impossible because the timing loop mistook dispatch acknowledgements
for completions.  These tests pin the properties that make that class
of bug structurally impossible again:

- the fenced timer cannot stop before outputs materialize on the host
  (proved with a delayed-materialization array double that acknowledges
  ``block_until_ready`` instantly — exactly the tunnelled-PJRT failure
  mode);
- any reading whose implied op rate exceeds the chip's physical peak is
  stamped ``suspect: true``;
- the schema refuses an exact-0.0 timing (round 5's
  ``nonuniform_us: 0.0``: "fast" must never read as "didn't run");
- the regression gate flags fenced metrics that move beyond tolerance
  against the archived trajectory, and never gates on unfenced or
  suspect baselines;
- ``python -m ceph_tpu.bench --smoke`` — the CI tier — exits 0 on CPU
  in seconds with schema-valid fenced metrics.
"""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from ceph_tpu.bench import fence, regress, roofline, schema, stats


# ---- fence -----------------------------------------------------------------

class DelayedArray:
    """Array double mimicking a tunnelled PJRT handle: the ready
    acknowledgement returns instantly, but the value only exists after
    ``delay`` more seconds of remote execution — observable solely via
    host readback."""

    def __init__(self, delay_s, t_dispatch):
        self._ready_at = t_dispatch + delay_s
        self._payload = np.arange(8, dtype=np.int32)

    def block_until_ready(self):
        return self            # lies, like the transport does

    def __array__(self, dtype=None, copy=None):
        now = time.perf_counter()
        if now < self._ready_at:
            time.sleep(self._ready_at - now)
        return self._payload


def test_fenced_timer_waits_for_materialization():
    """The clock must not stop until the last output's bytes exist on
    the host, even when block_until_ready acknowledges instantly."""
    DELAY = 0.15

    def step(i):
        return DelayedArray(DELAY, time.perf_counter())

    timing = fence.fenced_time(step, n_steps=3, rtt_s=0.0)
    # dispatches are instant; an unfenced timer would read ~0 here.
    assert timing.elapsed_s >= DELAY * 0.95
    assert timing.fenced is True
    assert timing.n_steps == 3


def test_drain_touches_host_bytes():
    done = {"materialized": False}

    class Probe:
        def block_until_ready(self):
            return self

        def __array__(self, dtype=None, copy=None):
            done["materialized"] = True
            return np.zeros(4, dtype=np.int32)

    fence.drain(Probe())
    assert done["materialized"]


def test_fenced_time_on_real_backend():
    """End-to-end on the CPU backend: jit dispatch, drain, sane fields."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x, s: x * s)
    x = jnp.arange(1024, dtype=jnp.int32)
    timing = fence.fenced_time(lambda i: f(x, jnp.int32(i + 1)), 4)
    assert timing.elapsed_s > 0.0
    assert timing.rtt_s >= 0.0
    d = timing.to_dict()
    assert d["fenced"] is True and d["n_steps"] == 4


def test_measure_rtt_custom_maker():
    rtt = fence.measure_rtt(lambda: np.ones(8, dtype=np.int32), repeats=3)
    assert 0.0 <= rtt < 1.0


# ---- roofline --------------------------------------------------------------

def test_roofline_flags_above_peak_reading():
    """807 GiB/s on a v5e implies ~444 int8 TOPS > 394 peak — the exact
    round-5 bogus headline must come back stamped suspect."""
    v = roofline.validate_reading(807.0, roofline.EC_ENCODE_K8M4,
                                  "tpu", "TPU v5 lite")
    assert v["suspect"] is True
    assert v["verdict"] == "suspect"
    assert v["implied_tops"] > v["peak_tops"]


def test_roofline_passes_physical_reading():
    v = roofline.validate_reading(300.0, roofline.EC_ENCODE_K8M4,
                                  "tpu", "TPU v5 lite")
    assert v["suspect"] is False
    assert v["verdict"] == "ok"
    assert 0.0 < v["mfu"] < 1.0


def test_roofline_memory_axis_trips_too():
    # 500 GiB/s of object data = 750 GiB/s of HBM traffic on the encode
    # model — fine for v5e compute but well past a 600 GiB/s host
    v = roofline.validate_reading(500.0, roofline.EC_ENCODE_K8M4, "cpu")
    assert v["suspect"] is True


def test_roofline_unknown_backend_never_ok():
    v = roofline.validate_reading(100.0, roofline.EC_ENCODE_K8M4,
                                  "rocm", "gfx90a")
    assert v["verdict"] == "unknown"
    assert v["suspect"] is False and v["peak_tops"] is None


def test_chip_spec_lookup():
    assert roofline.chip_spec("tpu", "TPU v5 lite")["int8_tops"] == 394.0
    assert roofline.chip_spec("tpu", "TPU v4")["int8_tops"] == 275.0
    assert roofline.chip_spec("cpu")["int8_tops"] == 2.0
    # unknown TPU generation: most permissive known peak, never None
    assert roofline.chip_spec("tpu", "")["int8_tops"] >= 394.0


# ---- stats -----------------------------------------------------------------

def test_summarize_median_iqr():
    st = stats.summarize([1.0, 2.0, 3.0, 4.0, 5.0])
    assert st["median"] == 3.0
    assert st["iqr"] == 2.0
    assert st["min"] == 1.0 and st["max"] == 5.0 and st["n"] == 5


def test_repeat_measure_discards_warmup():
    vals = iter([100.0, 1.0, 2.0, 3.0])   # first sample is compile cost
    st = stats.repeat_measure(lambda: next(vals), repeats=3, warmup=1)
    assert st["median"] == 2.0            # 100.0 excluded
    assert st["warmup_samples"] == [100.0]
    assert st["samples"] == [1.0, 2.0, 3.0]


# ---- schema ----------------------------------------------------------------

def test_make_metric_roundtrip():
    m = schema.make_metric(
        "x_gibs", 12.5, "GiB/s", fenced=True, rtt_s=0.07,
        stats=stats.summarize([12.0, 12.5, 13.0]),
        roofline=roofline.validate_reading(
            12.5, roofline.EC_ENCODE_K8M4, "cpu"))
    schema.validate_metric(m)
    assert m["fenced"] is True and m["rtt_ms"] == 70.0
    assert m["stats"]["n"] == 3
    assert m["suspect"] is m["roofline"]["suspect"]


def test_schema_rejects_exact_zero_timing():
    """A 0.0 reading in a time/throughput unit means 'didn't run' — the
    round-5 nonuniform_us:0.0 line must be unpublishable."""
    with pytest.raises(schema.SchemaError, match="0.0"):
        schema.make_metric("crush_remap_device", 0.0, "us", fenced=True)


def test_schema_rejects_missing_fence_field():
    with pytest.raises(schema.SchemaError):
        schema.validate_metric({"schema_version": 1, "name": "x",
                                "value": 1.0, "unit": "GiB/s"})


def test_schema_suspect_must_mirror_roofline():
    m = schema.make_metric(
        "x", 807.0, "GiB/s", fenced=True,
        roofline=roofline.validate_reading(
            807.0, roofline.EC_ENCODE_K8M4, "tpu", "TPU v5 lite"))
    assert m["suspect"] is True
    m["suspect"] = False       # tamper
    with pytest.raises(schema.SchemaError):
        schema.validate_metric(m)


# ---- regression gate -------------------------------------------------------

def _write_round(tmp_path, n, platform, metrics):
    rec = {"n": n, "rc": 0,
           "parsed": {"platform": platform, "metrics": metrics}}
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(json.dumps(rec))


def _metric(name, value, unit="GiB/s", fenced=True, suspect=False):
    m = schema.make_metric(name, value, unit, fenced=fenced)
    if suspect:   # hand-build: make_metric would need a roofline dict
        m["suspect"] = True
    return m


def test_gate_flags_throughput_regression(tmp_path):
    _write_round(tmp_path, 6, "cpu", [_metric("enc", 10.0)])
    traj = regress.load_trajectory(str(tmp_path))
    out = regress.compare_against_trajectory(
        [_metric("enc", 5.0)], traj, "cpu", tolerance=0.3)
    assert len(out["regressions"]) == 1
    assert out["regressions"][0]["baseline_round"] == 6
    assert out["regressions"][0]["change"] == -0.5


def test_gate_time_metrics_are_lower_better(tmp_path):
    _write_round(tmp_path, 6, "cpu", [_metric("remap", 10.0, unit="ms")])
    traj = regress.load_trajectory(str(tmp_path))
    out = regress.compare_against_trajectory(
        [_metric("remap", 20.0, unit="ms")], traj, "cpu")
    assert len(out["regressions"]) == 1
    out = regress.compare_against_trajectory(
        [_metric("remap", 5.0, unit="ms")], traj, "cpu")
    assert not out["regressions"] and len(out["improvements"]) == 1


def test_gate_recovery_block_lower_better(tmp_path):
    """The recovery gate: the storm's bytes-per-repaired-shard and the
    regen/RS ratio gate lower-better at the tight tolerance; a ratio
    creeping past tolerance is a regression even when the primary
    value held."""
    def storm(regen, rs, ratio):
        m = _metric("ec_recovery_storm", regen, unit="B/shard")
        m["recovery"] = {"bytes_per_repaired_shard_regen": regen,
                         "bytes_per_repaired_shard_rs": rs,
                         "regen_vs_rs_ratio": ratio}
        return m

    _write_round(tmp_path, 6, "cpu", [storm(5120.0, 32768.0, 0.156)])
    traj = regress.load_trajectory(str(tmp_path))
    # unchanged figures: compared, no regression
    out = regress.compare_against_trajectory(
        [storm(5120.0, 32768.0, 0.156)], traj, "cpu")
    assert out["recovery_compared"] == 3 and not out["regressions"]
    # repair bandwidth doubled: the regen figure AND the ratio regress
    out = regress.compare_against_trajectory(
        [storm(10240.0, 32768.0, 0.3125)], traj, "cpu")
    names = {r["name"] for r in out["regressions"]}
    assert "ec_recovery_storm.recovery.bytes_per_repaired_shard_regen" \
        in names
    assert "ec_recovery_storm.recovery.regen_vs_rs_ratio" in names
    # improvement direction classifies as improvement
    out = regress.compare_against_trajectory(
        [storm(2560.0, 32768.0, 0.078)], traj, "cpu")
    assert not out["regressions"] and out["improvements"]


def test_gate_skew_invariants(tmp_path):
    """The SKEW GATE is absolute (no baseline needed): late or missing
    detection, the wrong chip, a noisy healthy twin, or a health check
    that never raised/cleared each fail the gate on their own."""
    def skew_metric(**over):
        m = _metric("ec_mesh_skew", 12.0, unit="ratio")
        sk = {"mesh_chips": 8, "slow_chip": 5, "delay_us": 30000,
              "threshold": 3.0, "detected_chip": 5,
              "skew_ratio_detected": 12.0, "detection_probes": 3,
              "healthy_false_suspects": 0, "healthy_raised": False,
              "raised": True, "cleared": True}
        sk.update(over)
        m["skew"] = sk
        return m

    # a clean run gates clean — with or without any baseline round
    out = regress.compare_against_trajectory([skew_metric()], [], "cpu")
    assert out["skew_compared"] == 1 and not out["regressions"]
    cases = (
        ({"detection_probes": 0}, "detection_probes"),
        ({"detection_probes":
          regress.SKEW_MAX_DETECTION_PROBES + 1}, "detection_probes"),
        ({"detected_chip": 2}, "detected_chip"),
        ({"healthy_false_suspects": 1}, "healthy_false_suspects"),
        ({"healthy_raised": True}, "healthy_false_suspects"),
        ({"raised": False}, "raised"),
        ({"cleared": False}, "cleared"),
    )
    for over, key in cases:
        out = regress.compare_against_trajectory(
            [skew_metric(**over)], [], "cpu")
        names = {r["name"] for r in out["regressions"]}
        assert f"ec_mesh_skew.skew.{key}" in names, (over, names)


def test_gate_straggler_invariants(tmp_path):
    """The STRAGGLER GATE is absolute (no baseline needed): missing or
    late detection, the wrong chip, a protected p999 beyond the
    calibrated bounds, a byte divergence, a single-device fallback, a
    never-engaged subset completion, >= 2x coded bandwidth, or a noisy
    healthy twin each fail the gate on their own."""
    def straggler_metric(**over):
        m = _metric("ec_mesh_straggler", 1.0, unit="ratio")
        st = {"mesh_chips": 8, "slow_chip": 5, "delay_us": 30000,
              "threshold": 3.0, "detected_chip": 5,
              "skew_ratio_detected": 3.3, "detection_probes": 3,
              "healthy_false_suspects": 0,
              "protected_p999_ratio": 1.0,
              "protected_p999_wall_ratio": 0.95,
              "bandwidth_overhead": 1.25,
              "subset_completions": 40,
              "single_device_fallbacks": 0,
              "byte_identical": True}
        st.update(over)
        m["straggler"] = st
        return m

    # a clean run gates clean — with or without any baseline round
    out = regress.compare_against_trajectory([straggler_metric()], [],
                                             "cpu")
    assert out["straggler_compared"] == 1 and not out["regressions"]
    cases = (
        ({"detection_probes": 0}, "detection_probes"),
        ({"detection_probes":
          regress.STRAGGLER_MAX_DETECTION_PROBES + 1},
         "detection_probes"),
        ({"detected_chip": 2}, "detected_chip"),
        ({"skew_ratio_detected": 0.0}, "skew_ratio_detected"),
        ({"protected_p999_ratio":
          regress.STRAGGLER_MAX_P999_RATIO * 2},
         "protected_p999_ratio"),
        ({"protected_p999_ratio": 0.0}, "protected_p999_ratio"),
        ({"protected_p999_wall_ratio":
          regress.STRAGGLER_MAX_WALL_P999_RATIO + 0.1},
         "protected_p999_wall_ratio"),
        ({"bandwidth_overhead":
          regress.STRAGGLER_MAX_BANDWIDTH_OVERHEAD},
         "bandwidth_overhead"),
        ({"byte_identical": False}, "byte_identical"),
        ({"single_device_fallbacks": 1}, "single_device_fallbacks"),
        ({"subset_completions": 0}, "subset_completions"),
        ({"healthy_false_suspects": 1}, "healthy_false_suspects"),
    )
    for over, key in cases:
        out = regress.compare_against_trajectory(
            [straggler_metric(**over)], [], "cpu")
        names = {r["name"] for r in out["regressions"]}
        assert f"ec_mesh_straggler.straggler.{key}" in names, \
            (over, names)


def test_gate_zero_copy_invariants(tmp_path):
    """The ZERO-COPY GATE is absolute (no baseline needed): a resident
    leg that fetched shard-scale bytes back from device, that did not
    strictly beat the bytes twin's copies/op, that silently degraded
    (nothing resident when the write region closed), or that diverged
    on read-back each fail the gate on their own."""
    def zc_metric(**over):
        m = _metric("ec_write_zero_copy", 100.0, unit="ops_per_sec")
        zc = {"resident_d2h_bytes_per_op": 20.0,
              "resident_copies_per_op": 2.2,
              "twin_copies_per_op": 3.0,
              "resident_shards": 30,
              "byte_exact": True}
        zc.update(over)
        m["zero_copy"] = zc
        return m

    # a clean run gates clean — with or without any baseline round
    out = regress.compare_against_trajectory([zc_metric()], [], "cpu")
    assert out["zero_copy_compared"] == 1 and not out["regressions"]
    cases = (
        ({"resident_d2h_bytes_per_op":
          regress.ZERO_COPY_MAX_D2H_BYTES_PER_OP},
         "resident_d2h_bytes_per_op"),
        ({"resident_copies_per_op": 3.0}, "resident_copies_per_op"),
        ({"resident_copies_per_op": 3.5}, "resident_copies_per_op"),
        ({"resident_shards": 0}, "resident_shards"),
        ({"byte_exact": False}, "byte_exact"),
    )
    for over, key in cases:
        out = regress.compare_against_trajectory(
            [zc_metric(**over)], [], "cpu")
        names = {r["name"] for r in out["regressions"]}
        assert f"ec_write_zero_copy.zero_copy.{key}" in names, \
            (over, names)


def test_gate_control_invariants(tmp_path):
    """The CONTROL GATE is absolute (no baseline needed): a scenario
    that never raised, never moved, failed to converge inside the
    tick budget, moved outside its bounds corridor, a mis-identified
    abuser, a byte divergence, or ANY move from the disabled twin
    each fail the gate on their own."""
    def scenario(**over):
        s = {"raised": True, "moves": 4, "cleared": True,
             "converge_ticks": 6, "in_bounds": True}
        s.update(over)
        return s

    def control_metric(scen_over=None, **over):
        m = _metric("slo_autotune", 6.0, unit="ticks")
        ct = {"disabled_moves": 0, "byte_exact": True,
              "tick_budget": 80,
              "scenarios": {
                  "admission": scenario(abuser_correct=True),
                  "recovery": scenario(),
                  "straggler": scenario()}}
        ct.update(over)
        if scen_over:
            which, so = scen_over
            ct["scenarios"][which] = dict(ct["scenarios"][which],
                                          **so)
        m["control"] = ct
        return m

    # a clean run gates clean — with or without any baseline round
    out = regress.compare_against_trajectory([control_metric()], [],
                                             "cpu")
    assert out["control_compared"] == 1 and not out["regressions"]
    top_cases = (
        ({"disabled_moves": 1}, "disabled_moves"),
        ({"byte_exact": False}, "byte_exact"),
    )
    for over, key in top_cases:
        out = regress.compare_against_trajectory(
            [control_metric(**over)], [], "cpu")
        names = {r["name"] for r in out["regressions"]}
        assert f"slo_autotune.control.{key}" in names, (over, names)
    scen_cases = (
        ({"raised": False}, "raised"),
        ({"moves": 0}, "moves"),
        ({"cleared": False, "converge_ticks": -1}, "converge_ticks"),
        ({"converge_ticks": 81}, "converge_ticks"),
        ({"in_bounds": False}, "in_bounds"),
    )
    for over, key in scen_cases:
        for which in ("admission", "recovery", "straggler"):
            out = regress.compare_against_trajectory(
                [control_metric(scen_over=(which, over))], [], "cpu")
            names = {r["name"] for r in out["regressions"]}
            assert f"slo_autotune.control.{which}.{key}" in names, \
                (which, over, names)
    out = regress.compare_against_trajectory(
        [control_metric(scen_over=("admission",
                                   {"abuser_correct": False}))],
        [], "cpu")
    names = {r["name"] for r in out["regressions"]}
    assert "slo_autotune.control.admission.abuser_correct" in names


def test_gate_within_tolerance_passes(tmp_path):
    _write_round(tmp_path, 6, "cpu", [_metric("enc", 10.0)])
    traj = regress.load_trajectory(str(tmp_path))
    out = regress.compare_against_trajectory(
        [_metric("enc", 8.0)], traj, "cpu", tolerance=0.3)
    assert not out["regressions"] and out["compared"] == 1


def test_gate_ignores_unfenced_and_suspect_baselines(tmp_path):
    # legacy-style round: flat keys only, no schema metrics
    (tmp_path / "BENCH_r05.json").write_text(json.dumps(
        {"n": 5, "parsed": {"platform": "cpu", "value": 999.0}}))
    # a suspect reading must never become the gate baseline either
    _write_round(tmp_path, 6, "cpu",
                 [_metric("enc", 999.0, suspect=True)])
    traj = regress.load_trajectory(str(tmp_path))
    out = regress.compare_against_trajectory(
        [_metric("enc", 5.0)], traj, "cpu")
    assert out["compared"] == 0
    assert out["no_baseline"] == ["enc"]


def test_gate_platform_mismatch_is_no_baseline(tmp_path):
    _write_round(tmp_path, 6, "tpu", [_metric("enc", 500.0)])
    traj = regress.load_trajectory(str(tmp_path))
    out = regress.compare_against_trajectory(
        [_metric("enc", 0.01)], traj, "cpu")
    assert out["compared"] == 0 and not out["regressions"]


def _staged_metric(name, value, stages):
    """A fenced metric carrying a stage_breakdown whose stages are
    {stage: usec_per_op} — the shape the stage-budget gate reads."""
    total = sum(stages.values())
    return schema.make_metric(
        name, value, "GiB/s", fenced=True,
        extra={"stage_breakdown": {
            "wall_s": 1.0, "stage_sum_s": 1.0, "coverage": 1.0,
            "n_ops": 100,
            "stages": {s: {"count": 100, "total_usec": u * 100,
                           "usec_per_op": u,
                           "share": (u / total if total else 0.0),
                           "p50_usec": u, "p99_usec": u}
                       for s, u in stages.items()}}})


def test_stage_gate_flags_slower_stage(tmp_path):
    """The stage-budget gate: a stage's per-op time growing beyond
    STAGE_TOLERANCE is a regression even when the headline value is
    flat — the mesh/zero-copy refactors must move a watched stage
    number, and an accidental stall must fail the same gate."""
    _write_round(tmp_path, 6, "cpu", [_staged_metric(
        "enc", 10.0, {"device_call": 1000.0, "d2h": 200.0})])
    traj = regress.load_trajectory(str(tmp_path))
    out = regress.compare_against_trajectory(
        [_staged_metric("enc", 10.0,
                        {"device_call": 1000.0, "d2h": 800.0})],
        traj, "cpu")
    assert out["stage_compared"] == 2
    names = [r["name"] for r in out["regressions"]]
    assert names == ["enc.stage.d2h"]
    assert out["regressions"][0]["unit"] == "usec/op"
    assert out["regressions"][0]["change"] == 3.0
    # a stage getting faster beyond tolerance is an improvement
    out = regress.compare_against_trajectory(
        [_staged_metric("enc", 10.0,
                        {"device_call": 300.0, "d2h": 200.0})],
        traj, "cpu")
    assert not out["regressions"]
    assert any(i["name"] == "enc.stage.device_call"
               for i in out["improvements"])


def test_stage_gate_floor_semantics(tmp_path):
    """Sub-floor stages (scheduling jitter) gate nothing in either
    direction; a stage CROSSING the floor from a sub-floor baseline is
    flagged as a new time sink, mirroring the copy gate's zero-copy
    baseline rule.  Pre-oplat rounds (no stage_breakdown) gate no
    stages at all."""
    _write_round(tmp_path, 6, "cpu", [_staged_metric(
        "enc", 10.0, {"device_call": 1000.0, "batch_window": 5.0})])
    traj = regress.load_trajectory(str(tmp_path))
    # sub-floor wobble: 5 -> 40 usec/op is under the 50 usec floor
    out = regress.compare_against_trajectory(
        [_staged_metric("enc", 10.0, {"device_call": 1000.0,
                                      "batch_window": 40.0})],
        traj, "cpu")
    assert not out["regressions"]
    # crossing the floor: a new per-op time sink appeared
    out = regress.compare_against_trajectory(
        [_staged_metric("enc", 10.0, {"device_call": 1000.0,
                                      "batch_window": 900.0})],
        traj, "cpu")
    bad = [r for r in out["regressions"]
           if r["name"] == "enc.stage.batch_window"]
    assert bad and bad[0]["change"] is None
    # pre-oplat baseline: value gates, stages don't
    _write_round(tmp_path, 7, "cpu", [_metric("enc2", 10.0)])
    traj = regress.load_trajectory(str(tmp_path))
    out = regress.compare_against_trajectory(
        [_staged_metric("enc2", 10.0, {"device_call": 9999.0})],
        traj, "cpu")
    assert out["stage_compared"] == 0 and not out["regressions"]


def test_load_trajectory_orders_and_survives_junk(tmp_path):
    (tmp_path / "BENCH_r02.json").write_text("not json {")
    _write_round(tmp_path, 10, "cpu", [])
    _write_round(tmp_path, 3, "cpu", [])
    traj = regress.load_trajectory(str(tmp_path))
    assert [r["round"] for r in traj] == [2, 3, 10]
    assert traj[0]["parsed"] is None


# ---- the CI smoke tier -----------------------------------------------------

def test_smoke_mode_end_to_end():
    """`python -m ceph_tpu.bench --smoke` is the per-PR harness check:
    exit 0 on CPU, one schema-valid JSON line, fenced metrics with
    stats and a roofline verdict, in under a minute of measured time
    (the harness now spans 14 workloads — the budget is a
    minutes-scale canary, not a per-workload perf gate; those live in
    regress.py)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.run(
        [sys.executable, "-m", "ceph_tpu.bench", "--smoke"],
        capture_output=True, text=True, timeout=180, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    assert p.returncode == 0, p.stderr[-2000:]
    line = [ln for ln in p.stdout.splitlines() if ln.strip()][-1]
    out = json.loads(line)
    assert out["mode"] == "smoke" and out["platform"] == "cpu"
    assert out["elapsed_s"] < 60.0
    assert out["decode_parity"] is True
    names = set()
    for m in out["metrics"]:
        schema.validate_metric(m)
        names.add(m["name"])
        assert m["fenced"] is True
        assert {"median", "iqr", "min"} <= set(m["stats"])
        assert m["roofline"]["verdict"] in ("ok", "suspect", "unknown")
    assert {"ec_encode_k8m4_fenced", "ec_decode_k8m4_e2_fenced",
            "ec_dispatch_coalesce_fenced",
            "ec_dispatch_serial_fenced",
            "ec_pipeline_fenced", "ec_pipeline_depth1_fenced",
            "ec_mesh_fenced", "ec_mesh_single_fenced",
            "traffic_harness_smoke", "ec_recovery_storm",
            "ec_mesh_skew", "ec_mesh_straggler",
            "ec_degraded_read", "ec_write_zero_copy"} <= names
    # the coalesce metric carries its serial twin and speedup
    mc = next(m for m in out["metrics"]
              if m["name"] == "ec_dispatch_coalesce_fenced")
    assert mc["serial_gibs"] > 0 and mc["speedup"] > 0
    assert mc["batch_occupancy"] == mc["n_requests"] == 8
    # pipeline acceptance: a SINGLE submitter at depth 8 must fill real
    # batches (mean dispatch occupancy >= 4) and stay byte-identical to
    # the depth-1 passthrough
    mp = next(m for m in out["metrics"]
              if m["name"] == "ec_pipeline_fenced")
    assert mp["pipeline_depth"] == 8
    assert mp["mean_batch_occupancy"] >= 4, mp
    assert mp["identical"] is True
    assert mp["depth1_gibs"] > 0 and mp["speedup"] > 0
    # mesh acceptance (ceph_tpu/mesh): the 8-device CPU mesh smoke is
    # byte-identical to the single-device twin through the REAL
    # dispatch path, and the coalesced flush put work on EVERY chip
    mmesh = next(m for m in out["metrics"]
                 if m["name"] == "ec_mesh_fenced")
    assert mmesh["mesh_chips"] == 8 and mmesh["mesh_size"] == 8
    assert mmesh["identical"] is True
    assert mmesh["n_devices"] == 8
    assert len(mmesh["per_chip_stripes"]) == 8
    assert all(v > 0 for v in mmesh["per_chip_stripes"].values()), \
        mmesh["per_chip_stripes"]
    assert mmesh["single_gibs"] > 0 and mmesh["speedup"] > 0
    assert mmesh["plan_cache"] >= 1
    # the mesh leg's fence is drain_sharded + mesh_roofline: the
    # verdict must come back scaled by the mesh (never suspect on the
    # tiny smoke shapes) and the single twin keeps n_devices == 1
    assert mmesh["roofline"]["verdict"] in ("ok", "unknown")
    m1 = next(m for m in out["metrics"]
              if m["name"] == "ec_mesh_single_fenced")
    assert m1["n_devices"] == 1
    # traffic-harness acceptance (docs/QOS.md): >= 8 concurrent
    # synthetic clients, every op byte-exact, per-client p99 non-empty
    # in the bench JSON
    mt = next(m for m in out["metrics"]
              if m["name"] == "traffic_harness_smoke")
    assert mt["n_clients"] >= 8
    assert mt["byte_exact"] is True and not mt["errors"]
    assert mt["completed"] == mt["total_ops"] \
        == mt["n_clients"] * 32
    assert len(mt["per_client"]) == mt["n_clients"]
    for cname, st in mt["per_client"].items():
        assert st["p99"] > 0.0, (cname, st)
    assert mt["aggregate"]["p99"] > 0.0
    # telemetry acceptance: the end-of-run cluster rollup block rode
    # along, so harness A/B comparisons read ONE cluster tail number
    # per stage (mgr/telemetry.py) instead of per-daemon dumps
    roll = mt["cluster_rollup"]
    assert roll["oplat_p99_usec"].get("reply", 0) > 0, roll
    assert roll["oplat_p99_usec"].get("class_queue", 0) > 0, roll
    assert roll["rates"]["ops"] > 0, roll
    assert roll["samples"] >= 2 and "slo" in roll
    # recovery-storm acceptance (docs/RECOVERY.md): one OSD killed
    # under open-loop traffic at k8m4/d10 — the regenerating family's
    # bytes-moved-per-repaired-shard beats the RS full-stripe baseline
    # under the 0.6 gate, every object is byte-exact after backfill,
    # and the well-behaved clients' rollup raised no TPU_SLO_OPLAT
    mrs = next(m for m in out["metrics"]
               if m["name"] == "ec_recovery_storm")
    rec = mrs["recovery"]
    assert rec["bytes_per_repaired_shard_regen"] > 0
    assert rec["bytes_per_repaired_shard_rs"] > 0
    assert rec["regen_vs_rs_ratio"] <= 0.6, rec
    assert rec["families"]["pm-regen"]["repair_rounds"] > 0
    assert rec["families"]["isa-matrix"]["fullstripe_rounds"] > 0
    assert mrs["identical"] is True
    assert mrs["byte_exact_traffic"] is True
    assert mrs["slo"].get("TPU_SLO_OPLAT") == "ok", mrs["slo"]
    assert mrs["cluster_rollup"]["oplat_p99_usec"].get("reply", 0) > 0
    # straggler-ruler acceptance (ceph_tpu/mesh/chipstat): with one
    # chip slowed 10x via mesh.chip_slowdown the scoreboard marks
    # EXACTLY that chip suspect within the gate's probe window,
    # TPU_MESH_SKEW raises while the mgr ticks and clears after the
    # fault is removed, the healthy twin stays quiet, and skew
    # sampling never touched the data path (byte-identity receipt)
    msk = next(m for m in out["metrics"] if m["name"] == "ec_mesh_skew")
    sk = msk["skew"]
    assert 0 < sk["detection_probes"] <= regress.SKEW_MAX_DETECTION_PROBES
    assert sk["detected_chip"] == sk["slow_chip"]
    assert sk["skew_ratio_detected"] >= sk["threshold"]
    assert sk["healthy_false_suspects"] == 0
    assert sk["healthy_raised"] is False
    assert sk["raised"] is True and sk["cleared"] is True
    assert msk["identical"] is True
    assert out["gate"]["skew_compared"] >= 1
    # straggler-proof encode acceptance (ceph_tpu/mesh/rateless): with
    # one chip slowed 10x the rateless path keeps cluster_rollup
    # device_call p999 next to the healthy twin (the SPMD twin pays
    # the delay), detection receipts present, byte-identity holds,
    # the healthy twin pays < 2x coded bandwidth, and no protected
    # flush fell down the single-device ladder
    mstr = next(m for m in out["metrics"]
                if m["name"] == "ec_mesh_straggler")
    st = mstr["straggler"]
    assert 0 < st["detection_probes"] \
        <= regress.STRAGGLER_MAX_DETECTION_PROBES
    assert st["detected_chip"] == st["slow_chip"]
    assert st["skew_ratio_detected"] > 0
    assert 0 < st["protected_p999_ratio"] \
        <= regress.STRAGGLER_MAX_P999_RATIO
    assert 0 < st["protected_p999_wall_ratio"] \
        <= regress.STRAGGLER_MAX_WALL_P999_RATIO
    assert st["unprotected_p999_wall_ratio"] \
        > st["protected_p999_wall_ratio"]
    assert 1.0 < st["bandwidth_overhead"] \
        < regress.STRAGGLER_MAX_BANDWIDTH_OVERHEAD
    assert st["subset_completions"] > 0
    assert st["single_device_fallbacks"] == 0
    assert st["healthy_false_suspects"] == 0
    assert st["byte_identical"] is True and mstr["identical"] is True
    assert out["gate"]["straggler_compared"] >= 1
    # zero-copy acceptance (ISSUE 20): the resident leg of the A/B did
    # essentially no d2h on the write path (CRC scalars only, under
    # the 512 B/op gate), strictly beat the bytes twin on copies/op,
    # actually kept shards resident, and read back byte-exact
    mzc = next(m for m in out["metrics"]
               if m["name"] == "ec_write_zero_copy")
    zc = mzc["zero_copy"]
    assert zc["resident_d2h_bytes_per_op"] \
        < regress.ZERO_COPY_MAX_D2H_BYTES_PER_OP, zc
    assert zc["resident_copies_per_op"] < zc["twin_copies_per_op"], zc
    assert zc["resident_shards"] > 0
    assert zc["byte_exact"] is True
    assert mzc["twin_ops_per_sec"] > 0
    assert out["gate"]["zero_copy_compared"] >= 1
    # devprof acceptance: EVERY fenced workload emits a devflow block
    # with the gated per-op figures, and the dispatch/pipeline pairs
    # show coalescing as FEWER copies per op (the copy-budget story)
    for m in out["metrics"]:
        flow = m.get("devflow")
        assert isinstance(flow, dict), f"{m['name']}: no devflow block"
        assert {"h2d_bytes", "d2h_bytes", "transfers", "compiles",
                "copies_per_op", "bytes_per_op"} <= set(flow), m["name"]
        assert flow["copies_per_op"] >= 0
    flows = {m["name"]: m["devflow"] for m in out["metrics"]}
    assert flows["ec_dispatch_serial_fenced"]["copies_per_op"] > \
        flows["ec_dispatch_coalesce_fenced"]["copies_per_op"], \
        "coalescing did not reduce copies per op"
    assert flows["ec_pipeline_depth1_fenced"]["copies_per_op"] > \
        flows["ec_pipeline_fenced"]["copies_per_op"]
    assert flows["ec_dispatch_coalesce_fenced"]["h2d_bytes"] > 0
    # the run JSON also ships the per-site ledger (prof dump shape)
    assert flows and out["devprof"]["totals"]["transfers"] > 0
    assert "gf_matmul.encode" in out["devprof"]["sites"]
    # oplat acceptance: EVERY fenced workload emits a stage_breakdown
    # whose stage sum reconciles with its measured wall — coverage ~1
    # for serial regions; under coalescing each op accrues the SHARED
    # device call, so coverage approaches the occupancy (the story in
    # time units), never zero
    for m in out["metrics"]:
        sb = m.get("stage_breakdown")
        assert isinstance(sb, dict), f"{m['name']}: no stage_breakdown"
        assert sb["stages"], f"{m['name']}: empty stage_breakdown"
        assert sb["coverage"] > 0.2, (m["name"], sb)
        assert abs(sb["stage_sum_s"] - sum(
            s["total_usec"] for s in sb["stages"].values()) / 1e6) \
            < 1e-3, m["name"]
        shares = sum(s["share"] for s in sb["stages"].values())
        assert abs(shares - 1.0) < 0.02, (m["name"], shares)
        for st in sb["stages"].values():
            assert st["p50_usec"] <= st["p99_usec"]
    sbs = {m["name"]: m["stage_breakdown"] for m in out["metrics"]}
    # serial fenced regions reconcile tightly with wall
    for name in ("ec_encode_k8m4_fenced", "ec_decode_k8m4_e2_fenced",
                 "ec_dispatch_serial_fenced",
                 "ec_pipeline_depth1_fenced"):
        assert 0.5 <= sbs[name]["coverage"] <= 1.2, (name, sbs[name])
    # the occupancy story in time units (satellite): at depth 8 every
    # op waits in a real collection window (depth-1 flushes its own
    # batch immediately) and accrues the shared batched device call,
    # so per-op batch-window time grows and coverage tracks occupancy
    # while depth-1 stays device_call-dominated at coverage ~1
    p8, p1 = sbs["ec_pipeline_fenced"], sbs["ec_pipeline_depth1_fenced"]
    assert p8["stages"]["batch_window"]["usec_per_op"] > \
        p1["stages"].get("batch_window", {}).get("usec_per_op", 0.0), \
        (p8["stages"], p1["stages"])
    assert p8["coverage"] > 3.0 * p1["coverage"], (p8, p1)
    assert p1["stages"]["device_call"]["share"] > 0.5, p1
    assert sbs["ec_dispatch_coalesce_fenced"]["coverage"] > 2.0
    # the traffic workload decomposes the REAL op path: the mClock
    # class-queue wait under burst intake is a visible stage
    tsb = sbs["traffic_harness_smoke"]
    assert {"admission", "class_queue", "client_lane",
            "dequeue_handoff", "fan_out", "reply"} <= set(tsb["stages"])
    assert tsb["stages"]["class_queue"]["usec_per_op"] > 0
    # the run-level ledger rode along (latency dump shape)
    assert out["oplat"]["ops"] >= mt["completed"]
    assert out["oplat"]["stage_catalog"][0] == "client_flight"
    # the gate ran (warn mode) and the observability counters moved
    assert "gate" in out
    assert "stage_compared" in out["gate"]
    assert out["perf"]["dispatches"] > 0
    assert out["perf"]["fences"] > 0


def test_workload_metrics_in_process():
    """measure_encode/decode produce schema-valid fenced metrics on the
    test backend (tiny shapes — this is a harness test, not a perf
    run), and the shared kernel timer sees the fenced regions when
    tracing is enabled."""
    from ceph_tpu.bench import workloads
    from ceph_tpu.common.kernel_trace import g_kernel_timer
    from ceph_tpu.gf.matrices import gf_gen_rs_matrix

    rng = np.random.default_rng(7)
    matrix = gf_gen_rs_matrix(12, 8)
    batch = rng.integers(0, 256, size=(2, 8, 4096), dtype=np.uint8)
    g_kernel_timer.enable(True)
    try:
        m = workloads.measure_encode(matrix, batch, target_seconds=0.2,
                                     repeats=2, warmup=1)
        schema.validate_metric(m)
        assert m["fenced"] is True and m["value"] > 0
        m2 = workloads.measure_decode(matrix, batch, target_seconds=0.2,
                                      repeats=2, warmup=1)
        schema.validate_metric(m2)
        assert "bench_encode_fenced" in g_kernel_timer.dump()
    finally:
        g_kernel_timer.enable(False)
        g_kernel_timer.reset()
    assert workloads.parity_check(matrix) is True


def test_traffic_workload_in_process():
    """measure_traffic produces a schema-valid metric off a tiny run
    (harness shape test — throughput itself is measured by --smoke)
    and restores the admission config it set."""
    from ceph_tpu.bench import workloads
    from ceph_tpu.common.config import g_conf

    before = g_conf.values.get("osd_op_queue_admission_max")
    m = workloads.measure_traffic(n_clients=4, ops_per_client=8,
                                  n_osds=3, pg_num=4,
                                  admission_max=64, seed=3,
                                  name="traffic_tiny")
    schema.validate_metric(m)
    assert m["fenced"] is True and m["value"] > 0
    assert m["byte_exact"] is True
    assert m["completed"] == m["total_ops"] == 4 * 8
    assert len(m["per_client"]) == 4
    assert m["cluster_rollup"]["samples"] >= 1
    assert g_conf.values.get("mgr_telemetry_retention") is None, \
        "workload leaked the telemetry retention override"
    assert g_conf.values.get("osd_op_queue_admission_max") == before, \
        "workload leaked admission config"


def test_traffic_workload_rollup_survives_tiny_retention():
    """The whole-run cluster_rollup must keep the boot baseline even
    when the operator configured a ring too small for the run's tick
    count — the workload overrides retention for its own cluster and
    restores it after."""
    from ceph_tpu.bench import workloads
    from ceph_tpu.common.config import g_conf
    g_conf.set_val("mgr_telemetry_retention", 2)
    try:
        m = workloads.measure_traffic(n_clients=4, ops_per_client=8,
                                      n_osds=3, pg_num=4, seed=5,
                                      name="traffic_tiny_ret")
        # baseline + at least the final sample survived a ring the
        # operator sized at 2 (which would otherwise evict the boot
        # baseline and truncate the "whole-run" window to its tail)
        assert m["cluster_rollup"]["samples"] >= 2
        assert m["cluster_rollup"]["rates"]["ops"] > 0
        assert g_conf.get_val("mgr_telemetry_retention") == 2, \
            "workload clobbered the operator's retention value"
    finally:
        g_conf.rm_val("mgr_telemetry_retention")


def test_dispatch_coalesce_workload_in_process():
    """measure_dispatch_coalesce leaves the dispatcher drained and the
    config untouched, and both metric records validate."""
    from ceph_tpu.bench import workloads
    from ceph_tpu.common.config import g_conf
    from ceph_tpu.dispatch import g_dispatcher

    before = {n: g_conf.values.get(n) for n in
              ("ec_dispatch_batch_max", "ec_dispatch_batch_window_us")}
    mc, ms = workloads.measure_dispatch_coalesce(
        n_requests=4, object_bytes=16384, target_seconds=0.1,
        repeats=2, warmup=1)
    for m in (mc, ms):
        schema.validate_metric(m)
        assert m["fenced"] is True and m["value"] > 0
    assert mc["batch_occupancy"] == 4
    assert mc["speedup"] > 0 and mc["serial_gibs"] > 0
    assert g_dispatcher.dump()["pending"] == 0
    after = {n: g_conf.values.get(n) for n in before}
    assert after == before, "workload leaked dispatch config"
