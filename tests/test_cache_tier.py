"""Cache tiering: writeback overlay with HitSet-driven flush/evict.

Mirrors the reference flow (PrimaryLogPG.cc hit_set_setup /
promote_object / agent_work; HitSet.h bloom sets; Objecter
read_tier/write_tier retargeting): clients talk to the base pool name,
land on the cache pool, misses promote from the base, writes dirty the
cache, the agent flushes cold dirty objects down and evicts cold clean
ones — and reads are served by the tier.
"""
import numpy as np
import pytest

from ceph_tpu.client import ObjectOperation
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osdmap import pg_t


def make():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("base", k=2, m=1, plugin="isa", pg_num=4)
    # one cache PG makes the eviction-pressure math deterministic
    c.create_replicated_pool("hot", size=3, pg_num=1)
    c.mon.add_cache_tier("base", "hot", hit_set_period=30.0,
                         hit_set_count=2, target_max_objects=2)
    c.publish()
    return c, c.client("client.t")


def cache_pgs(c):
    pid = c.mon.osdmap.lookup_pg_pool_name("hot")
    for osd in c.osds.values():
        for pgid, pg in osd.pgs.items():
            if pgid[0] == pid and pg.is_primary() and pg.tier:
                yield pg


def base_holds(c, oid):
    pid = c.mon.osdmap.lookup_pg_pool_name("base")
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if cid.startswith(f"{pid}."):
                if any(ho.oid == oid
                       for ho in osd.store.list_objects(cid)):
                    return True
    return False


def cache_holds(c, oid):
    pid = c.mon.osdmap.lookup_pg_pool_name("hot")
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if cid.startswith(f"{pid}.") and not cid.endswith("_meta"):
                if any(ho.oid == oid
                       for ho in osd.store.list_objects(cid)):
                    return True
    return False


def agent(c, now):
    for pg in list(cache_pgs(c)):
        pg.tier.agent_work(now)
    c.network.pump()


def test_writes_land_in_tier_and_flush_cold(c=None):
    c, cl = make()
    data = b"tiered!" * 1000
    assert cl.write_full("base", "obj", data) == 0
    # the write landed in the CACHE pool, dirty; base has nothing yet
    assert cache_holds(c, "obj")
    assert not base_holds(c, "obj")
    assert cl.read("base", "obj") == data
    # stays hot across agent passes inside the hit-set window
    agent(c, now=10.0)
    assert not base_holds(c, "obj")
    # goes cold: two rotations push it out of every hit set -> flush
    agent(c, now=50.0)
    agent(c, now=100.0)
    agent(c, now=150.0)
    assert base_holds(c, "obj"), "cold dirty object never flushed"
    assert cl.read("base", "obj") == data


def test_promote_on_miss_serves_from_tier():
    c, cl = make()
    data = b"promote-me" * 500
    assert cl.write_full("base", "obj", data) == 0
    assert cl.setxattr("base", "obj", "tag", b"kept") == 0
    # flush + evict it out of the cache entirely
    for now in (50.0, 100.0, 150.0, 200.0):
        agent(c, now)
    # force eviction: it is clean + cold and the pool is over target
    for i in range(3):
        cl.write_full("base", f"filler{i}", b"x" * 100)
    for now in (250.0, 300.0, 350.0):
        agent(c, now)
    assert base_holds(c, "obj")
    assert not cache_holds(c, "obj"), "cold clean object never evicted"
    # a read MISSES the cache -> promote from base -> served by tier
    assert cl.read("base", "obj") == data
    assert cache_holds(c, "obj"), "miss did not promote"
    assert cl.getxattr("base", "obj", "tag") == b"kept"
    # prove subsequent reads hit the TIER: destroy every base copy;
    # the promoted cache copy still serves
    pid = c.mon.osdmap.lookup_pg_pool_name("base")
    from ceph_tpu.os_store import Transaction
    for osd in c.osds.values():
        for cid in list(osd.store.list_collections()):
            if cid.startswith(f"{pid}."):
                for ho in list(osd.store.list_objects(cid)):
                    if ho.oid == "obj":
                        t = Transaction()
                        t.remove(cid, ho)
                        osd.store.queue_transaction(t)
    assert not base_holds(c, "obj")
    assert cl.read("base", "obj") == data, "read did not hit the tier"


def test_delete_writes_through_and_does_not_resurrect():
    c, cl = make()
    assert cl.write_full("base", "obj", b"gone-soon") == 0
    for now in (50.0, 100.0, 150.0):
        agent(c, now)
    assert base_holds(c, "obj")
    assert cl.remove("base", "obj") == 0
    c.network.pump()
    assert not base_holds(c, "obj"), "delete did not write through"
    with pytest.raises(IOError):
        cl.read("base", "obj")


def test_dirty_markers_survive_restart():
    c, cl = make()
    assert cl.write_full("base", "obj", b"durable-dirt") == 0
    dirty_holders = [p for p in cache_pgs(c) if "obj" in p.tier.dirty]
    assert dirty_holders, "write did not dirty the cache copy"
    osd_id = dirty_holders[0].osd.osd_id
    c.restart_osd(osd_id)
    c.network.pump()
    held = [p for p in cache_pgs(c) if "obj" in p.tier.dirty]
    assert held, "dirty marker lost across restart"
    # and the flush still happens after the restart
    for now in (50.0, 100.0, 150.0):
        agent(c, now)
    assert base_holds(c, "obj")


def test_miss_on_absent_object_returns_enoent_not_hang():
    """A read through the tier for an object that exists NOWHERE must
    answer ENOENT, not promote-loop forever."""
    c, cl = make()
    with pytest.raises(IOError):
        cl.read("base", "never-written")
    # and a creating partial write works (promote finds nothing, the
    # op then creates the cache object)
    assert cl.write("base", "fresh", b"abc", 0) == 0
    assert cl.read("base", "fresh") == b"abc"


def test_write_during_flush_is_not_lost():
    """A write landing while its object's flush is in flight must stay
    dirty and reach the base on the next agent pass."""
    c, cl = make()
    assert cl.write_full("base", "obj", b"old-bytes") == 0
    pg = next(p for p in cache_pgs(c) if "obj" in p.tier.dirty)
    # start the flush but DON'T pump: the WRITEFULL to the base and its
    # reply are still in the network queue
    pg.tier.hit_sets.rotate(50.0)
    pg.tier.hit_sets.rotate(100.0)
    pg.tier._flush("obj")
    assert "obj" in pg.tier._flushing
    # overlapping client write (re-dirties the object mid-flush)
    assert cl.write_full("base", "obj", b"NEW-bytes") == 0
    c.network.pump()            # flush reply arrives, must NOT clear
    assert "obj" in pg.tier.dirty, "mid-flush write lost its marker"
    for now in (150.0, 200.0, 250.0):
        agent(c, now)
    assert cl.read("base", "obj") == b"NEW-bytes"
    # the BASE copy also converged on the new bytes
    c.mon.remove_cache_tier("base")
    c.publish()
    for _ in range(6):
        c.tick(dt=6.0)
    assert cl.read("base", "obj") == b"NEW-bytes"


def test_xattrs_promote_and_flush_through_tier():
    c, cl = make()
    assert cl.write_full("base", "obj", b"body") == 0
    assert cl.setxattr("base", "obj", "k", b"v1") == 0
    # flush, then evict so the next xattr read is a miss
    for now in (50.0, 100.0, 150.0):
        agent(c, now)
    for i in range(3):
        cl.write_full("base", f"fill{i}", b"x")
    for now in (200.0, 250.0, 300.0):
        agent(c, now)
    assert not cache_holds(c, "obj")
    # xattr read through the tier promotes (was ENOENT before)
    assert cl.getxattr("base", "obj", "k") == b"v1"
    assert cache_holds(c, "obj")
    # xattr write dirties the cache copy so it re-flushes
    assert cl.setxattr("base", "obj", "k", b"v2") == 0
    assert any("obj" in p.tier.dirty for p in cache_pgs(c))


def test_remove_cache_tier_drains_dirty_objects():
    """Tearing the overlay down must not strand acked writes in the
    cache pool: PGs drain their dirty objects to the base first."""
    c, cl = make()
    assert cl.write_full("base", "obj", b"must-survive") == 0
    assert not base_holds(c, "obj")
    c.mon.remove_cache_tier("base")
    c.publish()
    # agent ticks drain the dirty set regardless of temperature
    for _ in range(6):
        c.tick(dt=6.0)
    c.network.pump()
    assert base_holds(c, "obj"), "acked write stranded in the cache"
    assert cl.read("base", "obj") == b"must-survive"
    # the tier state dropped itself once drained
    pid = c.mon.osdmap.lookup_pg_pool_name("hot")
    for osd in c.osds.values():
        for pgid, pg in osd.pgs.items():
            if pgid[0] == pid:
                assert pg.tier is None or pg.tier.dirty
