"""Chaos: the mini-cluster under SIMULTANEOUS fault injection.

The robustness PR's end-to-end acceptance gate: with message drops
(`msg.drop`), transient device errors (`device.encode_batch` /
`device.decode_batch`) and shard-read EIO (`osd.shard_read_eio`) all
armed at once — plus an OSD kill/revive cycle — a mixed
write/overwrite/partial-write/read/recovery workload completes every
client op and the final object contents are byte-identical to an
uninjected run.

Determinism notes baked into the parameters:

- the SMOKE drops ALL traffic (``msg.drop`` unscoped): client requests
  replay via the Objecter's refresh-and-resend loop, EC sub-op writes
  via the ec_backend resend timer (shard-side replay is version-deduped
  so a lost ACK cannot double-apply), peering queries via the tick
  retry, and lost MOSDMap deliveries via the heartbeat-epoch
  resubscribe.  A deterministic scoped drop of one MOSDECSubOpWrite
  runs first, as the drop→resend receipt.
- the twin-cluster SOAK keeps ``match="MOSDOp "`` (client REQUESTS): a
  dropped request was never executed, so the replay count — and with
  it the twin comparison of stored shard bodies — stays exact.
- ``osd.shard_read_eio`` uses ``nth n=4``: any one read fans to at most
  5 shard reads (k=3 + m=2 retries), and 5 consecutive checks contain
  at most 2 multiples of 4 — never more than m failures per read, so
  reconstruction always has k survivors by construction, not luck.
- everything probabilistic is seeded, so a pass is reproducible.

The <10 s smoke runs in tier-1 (`-m chaos` selects it); the full soak
(twin-cluster byte comparison down to the stored shard bodies) is also
marked `slow`.
"""
import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.fault import fault_perf_counters, g_breakers, g_faults
from ceph_tpu.fault.registry import (l_fault_eio_reconstructs,
                                     l_fault_injected, l_fault_msg_drops)

pytestmark = pytest.mark.chaos


@pytest.fixture
def clean_faults():
    # chaos rounds run with the lock-order witness armed, like the
    # reference qa suites run under lockdep=1: a fault path that
    # acquires out of order fails HERE, not in a production deadlock
    from ceph_tpu.common.lockdep import lockdep_enable, lockdep_reset
    lockdep_reset()
    lockdep_enable(True)
    yield
    lockdep_enable(False)
    lockdep_reset()
    g_faults.clear()
    g_breakers.reset()
    for name in ("ec_device_retry_max", "ec_device_retry_backoff_us",
                 "ec_breaker_threshold", "ec_breaker_cooldown_s"):
        g_conf.rm_val(name)


def _boot(n_osds=6, k=3, m=2):
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=n_osds)
    c.create_ec_pool("chaos", k=k, m=m, pg_num=8)
    return c, c.client("client.chaos")


def _arm_chaos(seed: int, drop_match: str = "MOSDOp ",
               drop_p: float = 0.2) -> None:
    g_conf.set_val("ec_device_retry_backoff_us", 0)
    g_faults.inject("msg.drop", mode="prob", p=drop_p, seed=seed,
                    match=drop_match)
    g_faults.inject("device.encode_batch", mode="nth", n=3)
    g_faults.inject("device.decode_batch", mode="nth", n=3)
    g_faults.inject("osd.shard_read_eio", mode="nth", n=4)


def _read_healing(c, cl, oid, tries=8):
    """Degraded read across a re-peering window: peering-query resends
    are TICK-driven (PG.retry_peering), so a read that lands while a
    dropped query is still outstanding sees EAGAIN — tick and retry
    like a live client would, bounded so a real wedge still fails."""
    for _ in range(tries):
        try:
            return cl.read("chaos", oid)
        except IOError as e:
            if e.errno != 11:           # only EAGAIN is the heal case
                raise
            c.tick(dt=5.0)
    return cl.read("chaos", oid)


def _workload(c, cl, expected, rng, gens, kill_cycle=(1,),
              deleted=None):
    """Mixed write/overwrite/partial-write/delete/read/recovery
    generations; records every object's expected logical bytes in
    *expected* and removed oids in *deleted* (when given — deletes are
    exercised under whatever drop scope is armed; the EC delete fan is
    acked + resent like sub-op writes, docs/ROBUSTNESS.md)."""
    for gen in range(gens):
        # fresh full-object writes
        for i in range(3):
            oid = f"g{gen}o{i}"
            body = bytes(rng.integers(0, 256, 6000 + 700 * i,
                                      dtype=np.uint8))
            assert cl.write_full("chaos", oid, body) == 0, (gen, i)
            expected[oid] = body
        # whole-object overwrite of an older object
        oid = f"g{gen}o0"
        body = bytes(rng.integers(0, 256, 5000, dtype=np.uint8))
        assert cl.write_full("chaos", oid, body) == 0
        expected[oid] = body
        # partial write (the rmw pipeline: pre-read, splice, re-encode)
        oid = f"g{gen}o1"
        patch = bytes(rng.integers(0, 256, 1500, dtype=np.uint8))
        off = 800
        assert cl.write("chaos", oid, patch, off) == 0
        old = bytearray(expected[oid])
        old[off:off + len(patch)] = patch
        expected[oid] = bytes(old)
        # delete an older object with the chaos still armed: the
        # versioned delete fan must converge (ack + retry) and reads
        # must see a clean ENOENT, not a half-deleted object
        if deleted is not None and gen > 0:
            doid = f"g{gen - 1}o2"
            if doid in expected:
                assert cl.remove("chaos", doid) == 0, doid
                expected.pop(doid)
                deleted.add(doid)
                with pytest.raises(IOError):
                    cl.read("chaos", doid)
        # reads while injection is live (EIO recovery + decode path)
        for oid, body in list(expected.items())[-4:]:
            assert cl.read("chaos", oid) == body, oid
        # recovery leg: kill an OSD, read degraded, revive, recover
        if gen in kill_cycle:
            victim = 1 + (gen % 3)
            c.kill_osd(victim)
            for _ in range(6):
                c.tick(dt=5.0)
            for oid, body in list(expected.items())[:2]:
                assert _read_healing(c, cl, oid) == body, \
                    f"degraded {oid}"
            c.revive_osd(victim)
            for _ in range(3):
                c.tick(dt=2.0)
            c.run_recovery()


def test_chaos_smoke(clean_faults):
    """Tier-1: UNSCOPED message drops (sub-op writes included) + device
    errors + read EIO at once, one kill/revive cycle, every op
    completes, every object reads back exactly."""
    from ceph_tpu.osd.ec_backend import (l_pipeline_subwrite_resends,
                                         pipeline_perf_counters)
    c, cl = _boot()
    pc = fault_perf_counters()
    ppc = pipeline_perf_counters()
    before = {"inj": pc.get(l_fault_injected),
              "drop": pc.get(l_fault_msg_drops),
              "rec": pc.get(l_fault_eio_reconstructs),
              "resend": ppc.get(l_pipeline_subwrite_resends)}
    expected = {}
    # deterministic drop→resend receipt: lose exactly one EC sub-op
    # write; before the resend timer this wedged the per-oid pipeline
    # until peering — now the op must complete on the retry
    g_faults.inject("msg.drop", mode="once", match="MOSDECSubOpWrite ")
    assert cl.write_full("chaos", "receipt", b"r" * 4000) == 0
    assert cl.read("chaos", "receipt") == b"r" * 4000
    assert ppc.get(l_pipeline_subwrite_resends) > before["resend"], \
        "dropped sub-write was not resent"
    expected["receipt"] = b"r" * 4000
    g_faults.clear("msg.drop")
    # drop→resend receipt for the DELETE fan too (the last unacked
    # write-path class): lose exactly one sub-delete; the inflight
    # sweep must resend it and the object must be gone everywhere
    resend0 = ppc.get(l_pipeline_subwrite_resends)
    g_faults.inject("msg.drop", mode="once", match="MOSDECSubOpWrite ")
    assert cl.remove("chaos", "receipt") == 0
    assert ppc.get(l_pipeline_subwrite_resends) > resend0, \
        "dropped sub-delete was not resent"
    with pytest.raises(IOError):
        cl.read("chaos", "receipt")
    expected.pop("receipt")
    g_faults.clear("msg.drop")
    _arm_chaos(seed=1234, drop_match="", drop_p=0.04)  # ALL traffic
    rng = np.random.default_rng(99)
    deleted = set()
    _workload(c, cl, expected, rng, gens=2, kill_cycle=(1,),
              deleted=deleted)
    g_faults.clear()
    # final sweep with injection disarmed: contents are byte-identical
    # to what an uninjected run would hold (the payloads themselves),
    # and deleted objects stay deleted on every shard
    for oid, body in expected.items():
        assert cl.read("chaos", oid) == body, oid
    assert deleted, "workload exercised no deletes"
    for oid in deleted:
        with pytest.raises(IOError):
            cl.read("chaos", oid)
    # the chaos was real: every armed class actually fired
    assert pc.get(l_fault_injected) > before["inj"]
    assert pc.get(l_fault_msg_drops) > before["drop"]
    assert pc.get(l_fault_eio_reconstructs) > before["rec"]
    assert c.health().startswith("HEALTH")


def test_chaos_saturation_abusive_client(clean_faults):
    """QoS saturation scenario (docs/QOS.md): ONE abusive client at
    10x the arrival rate of 7 well-behaved clients against a small
    admission cap.  All well-behaved ops complete byte-exact with
    bounded completion latency (deterministic round metric — no wall
    time in any decision path), while the abusive client is throttled
    and the admission counter fires."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.load import TrafficSpec, run_traffic
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("load", size=3, pg_num=8)
    g_conf.set_val("osd_op_queue_admission_max", 16)
    try:
        res = run_traffic(c, TrafficSpec(
            n_clients=8, ops_per_client=40, read_fraction=0.4,
            mode="open", rate=3.0, rate_multipliers=(10.0,),
            seed=424242))
    finally:
        g_conf.rm_val("osd_op_queue_admission_max")
    assert res.byte_exact, res.errors[:5]
    assert res.admission_rejections > 0, "admission never fired"
    assert res.max_intake_depth <= 16
    abusive = res.per_client["client.load.0"]
    assert abusive["throttled"] > 0, abusive
    for name, st in sorted(res.per_client.items()):
        if name == "client.load.0":
            continue
        assert st["completed"] == 40, (name, st)
        # bounded p99: a well-behaved client's worst op finishes
        # within a handful of rounds of its issue, saturation or not
        assert st["round_latency_max"] <= 6, (name, st)


@pytest.mark.slow
def test_chaos_soak_byte_identical_to_uninjected_twin(clean_faults):
    """The full soak: the SAME workload sequence runs on an injected
    cluster and an uninjected twin; every client op completes on both,
    final object contents match object-for-object, and the EC pool's
    stored shard BODIES are byte-identical across the two clusters
    (CPU-degraded encodes, retried dispatches and reconstruct-served
    reads must leave no trace in the bytes)."""
    results = {}
    for label, inject in (("twin", False), ("injected", True)):
        c, cl = _boot()
        expected = {}
        if inject:
            _arm_chaos(seed=4321)
            # push the breaker through a trip + half-open restore
            # mid-run: device failures must only ever cost throughput
            g_conf.set_val("ec_breaker_threshold", 2)
            g_conf.set_val("ec_breaker_cooldown_s", 0.05)
        rng = np.random.default_rng(7)
        _workload(c, cl, expected, rng, gens=4, kill_cycle=(1, 3))
        g_faults.clear()
        for oid, body in expected.items():
            assert cl.read("chaos", oid) == body, (label, oid)
        # collect the EC pool's stored shard bodies
        pool_id = cl.lookup_pool("chaos")
        shards = {}
        for i, osd in c.osds.items():
            for cid in osd.store.list_collections():
                if not cid.startswith(f"{pool_id}.") or "_meta" in cid:
                    continue
                for ho in osd.store.list_objects(cid):
                    shards[(i, cid, str(ho))] = osd.store.read(cid, ho)
        results[label] = (expected, shards)
        g_breakers.reset()
    exp_twin, shards_twin = results["twin"]
    exp_inj, shards_inj = results["injected"]
    assert exp_twin == exp_inj
    assert set(shards_twin) == set(shards_inj)
    diff = [k for k in shards_twin if shards_twin[k] != shards_inj[k]]
    assert not diff, f"shard bodies diverged: {diff[:5]}"
