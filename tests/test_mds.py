"""MDS-lite: capabilities, MDS journal replay, per-directory snapshots.

The reference's cephfs is MDS-mediated (src/mds/MDSDaemon.cc, Locker.cc
caps, MDLog.cc journal, SnapRealm.h per-directory snapshots); these
tests drive that architecture at lite scale over the in-process fabric:
conflicting caps serialize buffered writes through a revoke/flush
round, a crashed MDS replays its journal, and `snap_create` on a
subdirectory snapshots only that subtree.
"""
import json

import pytest

from ceph_tpu.cephfs import FsError
from ceph_tpu.cephfs.cls_fs import file_oid
from ceph_tpu.cephfs.mds_client import RemoteCephFS
from ceph_tpu.cluster import MiniCluster
from ceph_tpu.mds import MDSDaemon
from ceph_tpu.msg.messages import CEPH_CAP_FILE_BUFFER


@pytest.fixture()
def world():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("fsmeta", size=3, pg_num=8)
    c.create_replicated_pool("fsdata", size=3, pg_num=8)
    mds = MDSDaemon(c.network, c.client("client.mds"), "mds.0",
                    mkfs=True)
    fa = RemoteCephFS(c.client("client.a"))
    fb = RemoteCephFS(c.client("client.b"))
    # cooperative scheduling: each blocked client drives the mds and
    # its peer (stand-ins for "everyone has their own thread")
    fa._drive = lambda: (mds.process(), fb.process())
    fb._drive = lambda: (mds.process(), fa.process())
    return c, mds, fa, fb


def test_metadata_ops_cross_the_mds(world):
    c, mds, fa, fb = world
    fa.mkdir("/d")
    fa.create("/d/f")
    fa.write("/d/f", b"hello mds", 0)
    # the OTHER client sees it through its own session
    assert fb.stat("/d/f")["size"] == 9
    assert fb.read("/d/f") == b"hello mds"
    assert sorted(fb.listdir("/d")) == ["f"]
    fb.rename("/d/f", "/d/g")
    assert fa.read("/d/g") == b"hello mds"
    assert not fa.exists("/d/f")
    fa.unlink("/d/g")
    fa.rmdir("/d")
    assert not fb.exists("/d")


def test_conflicting_caps_serialize_buffered_writes(world):
    """The done-criterion: A buffers writes under CEPH_CAP_FILE_BUFFER;
    B's conflicting open triggers the revoke round; A's buffer is
    flushed (data objects + wrstat) BEFORE B's read is granted."""
    c, mds, fa, fb = world
    fh = fa.open("/f", "w")
    assert fh.caps & CEPH_CAP_FILE_BUFFER
    fh.write(b"buffered-by-A", 0)
    # nothing on the OSDs yet: the bytes live in A's buffer only
    import ceph_tpu.cephfs.mds_client as mc
    raw = mds.fs.read("/f") if mds.fs.exists("/f") else b""
    assert raw == b""                       # size still 0 server-side
    assert fh.read(0, 13) == b"buffered-by-A"   # A sees its own buffer
    # B's read forces the revoke/flush/grant round
    assert fb.read("/f") == b"buffered-by-A"
    # A's caps were revoked; its handle degraded to write-through
    assert fh.caps == 0
    fh.write(b"THROUGH", 0)
    assert fb.read("/f", 0, 7) == b"THROUGH"


def test_two_buffered_writers_serialize(world):
    c, mds, fa, fb = world
    ha = fa.open("/w", "w")
    ha.write(b"AAAA", 0)
    # B opening for write revokes A first — A's flush lands before B's
    # buffer starts accumulating
    hb = fb.open("/w", "w")
    assert hb.caps & CEPH_CAP_FILE_BUFFER
    hb.write(b"BB", 0)
    hb.close()
    assert fa.read("/w") == b"BBAA"


def test_mds_journal_replays_after_crash(world):
    """SIGKILL-shaped recovery: an event journaled but never applied
    (the crash window) is replayed by the next MDS incarnation."""
    c, mds, fa, fb = world
    fa.mkdir("/dir")
    fa.create("/dir/a")
    fa.write("/dir/a", b"payload", 0)
    # crash window: the rename is journaled, the apply never runs
    mds.journal.append(json.dumps(
        {"op": "rename",
         "args": {"src": "/dir/a", "dst": "/dir/b"}}).encode())
    # the old incarnation is abandoned (never cleanly shut down)
    mds2 = MDSDaemon(c.network, c.client("client.mds2"), "mds.0")
    f2 = RemoteCephFS(c.client("client.a2"))
    f2._drive = lambda: mds2.process()
    assert f2.exists("/dir/b") and not f2.exists("/dir/a")
    assert f2.read("/dir/b") == b"payload"
    # replay is idempotent: a THIRD incarnation changes nothing
    mds3 = MDSDaemon(c.network, c.client("client.mds3"), "mds.0")
    f3 = RemoteCephFS(c.client("client.a3"))
    f3._drive = lambda: mds3.process()
    assert f3.exists("/dir/b") and not f3.exists("/dir/a")
    # and the tree is consistent
    assert not any(mds3.fs.fsck().values())


def test_per_directory_snapshot_covers_only_subtree(world):
    """The SnapRealm done-criterion: snap_create on /a preserves /a's
    files only — /b's files keep writing with a snapc that excludes
    the snap, so no clone of them exists at that snap id."""
    c, mds, fa, fb = world
    fa.mkdir("/a")
    fa.mkdir("/b")
    fa.create("/a/in")
    fa.create("/b/out")
    fa.write("/a/in", b"inside-v1", 0)
    fa.write("/b/out", b"outsideV1", 0)
    snap = fa.snap_create("/a", "s1")
    data_sid = snap["data"]
    # overwrite both AFTER the snapshot
    fa.write("/a/in", b"inside-v2", 0)
    fa.write("/b/out", b"outsideV2", 0)
    # the view resolves only the subtree, at the snapshot
    view = fa.snapshot("/a", "s1")
    assert view.read("in") == b"inside-v1"
    assert sorted(view.listdir("/")) == ["in"]
    assert not view.exists("out")
    # head keeps the new bytes
    assert fb.read("/a/in") == b"inside-v2"
    # the OUTSIDE file has NO clone at the snap id: reading it at the
    # snap yields the post-snap bytes (nothing was preserved)
    out_ino = fb.stat("/b/out")["ino"]
    got = fb.client.read("fsdata", file_oid(out_ino, 0), snap=data_sid)
    assert got == b"outsideV2"
    # nested realms: a root snapshot later covers /b too
    fa.snap_create("/", "root1")
    fa.write("/b/out", b"outsideV3", 0)
    rv = fa.snapshot("/", "root1")
    assert rv.read("b/out") == b"outsideV2"
    assert rv.read("a/in") == b"inside-v2"
    # snap listing is per-directory
    assert list(fa.snap_list("/a")) == ["s1"]
    assert list(fa.snap_list("/")) == ["root1"]


def test_snapshot_remove_and_readonly(world):
    c, mds, fa, fb = world
    fa.mkdir("/a")
    fa.create("/a/f")
    fa.write("/a/f", b"v1", 0)
    fa.snap_create("/a", "s")
    fa.write("/a/f", b"v2", 0)
    assert fa.snapshot("/a", "s").read("f") == b"v1"
    fa.snap_remove("/a", "s")
    with pytest.raises(FsError):
        fa.snapshot("/a", "s")


def test_failover_retry_dedup(world):
    """A mutating op retried with its original reqid — the failover
    retry shape — is answered from effect, not re-executed; a PROMOTED
    incarnation that replayed the journal dedups it too."""
    c, mds, fa, fb = world
    out1 = fa._request("mkdir", path="/dup", _reqid="client.a#7")
    out2 = fa._request("mkdir", path="/dup", _reqid="client.a#7")
    assert out2.get("replayed") and out2["ino"] == out1["ino"]
    # without the reqid it is a genuine duplicate -> EEXIST
    with pytest.raises(FsError):
        fa._request("mkdir", path="/dup")
    # a fresh incarnation rebuilt the completed set from the journal
    mds2 = MDSDaemon(c.network, c.client("client.mdsB"), "mds.0")
    f2 = RemoteCephFS(c.client("client.a4"))
    f2._drive = lambda: mds2.process()
    out3 = f2._request("mkdir", path="/dup", _reqid="client.a#7")
    assert out3.get("replayed") and out3["ino"] == out1["ino"]


def test_tell_mds_commands(world):
    """'ceph tell mds.<name>' through the PUBLIC mds_command client
    API: status, session ls, config get, and an atomic injectargs
    against a live metadata server (MCommand executes synchronously
    in dispatch, so a blocked teller needs no one driving
    process())."""
    from ceph_tpu.common.config import g_conf

    c, mds, fa, fb = world
    fa.create("/tellfile")
    fh = fa.open("/tellfile", "w")  # holds caps -> a live session
    cl = c.client("client.teller")

    st = cl.mds_command(mds.name, "status")
    assert st["name"] == mds.name and st["rank"] == 0
    sessions = cl.mds_command(mds.name, "session ls")["sessions"]
    assert "client.a" in sessions
    fh.close()
    before = g_conf.get_val("osd_heartbeat_grace")
    try:
        out = cl.mds_command(mds.name, "injectargs",
                             opts={"osd_heartbeat_grace": "27"})
        assert out["osd_heartbeat_grace"] == 27.0
        assert cl.mds_command(mds.name, "config get",
                              name="osd_heartbeat_grace")[
            "osd_heartbeat_grace"] == 27.0
        # atomic: one bad name means nothing applies
        import pytest as _pytest
        with _pytest.raises(ValueError):
            cl.mds_command(mds.name, "injectargs",
                           opts={"osd_heartbeat_grace": "99",
                                 "nope": "1"})
        assert g_conf.get_val("osd_heartbeat_grace") == 27.0
        with _pytest.raises(ValueError):
            cl.mds_command(mds.name, "no-such-command")
    finally:
        g_conf.set_val("osd_heartbeat_grace", before)


def test_dual_writer_duplicate_fence(world):
    """The deposed-incumbent race: daemon A lands a mutation in the
    shared journal AFTER daemon B's startup scan; B answering a
    client retry must detect the duplicate by re-scanning the journal
    and reply from effect — never EEXIST (the under-load
    multi-active flake's root cause)."""
    c, mds, fa, fb = world
    # B's incarnation scans the journal NOW (no /race entry yet)
    mdsB = MDSDaemon(c.network, c.client("client.mdsFence"), "mds.0")
    # A (the soon-deposed incumbent) steals the entity name back —
    # the real race's shape: the old holder still serving while B
    # already finished its startup scan
    mds.messenger = c.network.create_messenger("mds.0")
    mds.messenger.add_dispatcher_head(mds)
    out1 = fa._request("mkdir", path="/race", _reqid="client.a#99")
    # failover completes: B owns the name from here on
    mdsB.messenger = c.network.create_messenger("mds.0")
    mdsB.messenger.add_dispatcher_head(mdsB)
    # the client's failover retry lands on B, whose memo predates A's
    # append: the journal re-scan fence must answer from effect
    f2 = RemoteCephFS(c.client("client.a9"))
    f2._drive = lambda: mdsB.process()
    out2 = f2._request("mkdir", path="/race", _reqid="client.a#99")
    assert out2.get("replayed") and out2["ino"] == out1["ino"]
    # a DIFFERENT reqid is a genuine conflict: still EEXIST
    with pytest.raises(FsError) as ei:
        f2._request("mkdir", path="/race", _reqid="client.a#100")
    assert ei.value.result == -17


def test_failed_attempt_retry_stays_failed(world):
    """A genuinely-failing op retried with its original reqid must
    KEEP failing: the failed attempt's journal frame carries an
    __annul__ record, so neither the duplicate fence nor a restarted
    daemon's memo can mistake it for applied effect."""
    c, mds, fa, fb = world
    fa._request("mkdir", path="/owned")        # someone else's dir
    with pytest.raises(FsError) as e1:
        fa._request("mkdir", path="/owned", _reqid="client.a#501")
    assert e1.value.result == -17
    # the failover-retry shape: same reqid again -> STILL -17
    with pytest.raises(FsError) as e2:
        fa._request("mkdir", path="/owned", _reqid="client.a#501")
    assert e2.value.result == -17
    # a restarted incarnation must not remember the failed reqid as
    # applied either
    mds2 = MDSDaemon(c.network, c.client("client.mdsAnnul"), "mds.0")
    f2 = RemoteCephFS(c.client("client.a11"))
    f2._drive = lambda: mds2.process()
    with pytest.raises(FsError) as e3:
        f2._request("mkdir", path="/owned", _reqid="client.a#501")
    assert e3.value.result == -17
