"""jerasure bitmatrix technique family: constructions, layout, parity.

The reference executes cauchy/liberation-class techniques as scheduled-XOR
bitmatrix codes over packets (src/erasure-code/jerasure/
ErasureCodeJerasure.cc:259-269,340-348); these tests pin the construction
properties (density, ring structure, MDS), the packet layout semantics,
and host/device agreement of the packet execution.
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import create_erasure_code
from ceph_tpu.gf.bitmatrix import (
    BitmatrixPacketCodec, blaum_roth_bitmatrix, cauchy_good_matrix,
    cauchy_original_matrix, element_bitmatrix, gf2_invert, gfw_inv, gfw_mul,
    liber8tion_bitmatrix, liberation_bitmatrix, matrix_to_bitmatrix, n_ones,
)


def test_gfw_field_axioms():
    for w in (4, 8, 16, 32):
        a, b, c = 3, 7, 0x0B
        assert gfw_mul(a, b, w) == gfw_mul(b, a, w)
        assert gfw_mul(a, gfw_mul(b, c, w), w) == \
            gfw_mul(gfw_mul(a, b, w), c, w)
        assert gfw_mul(a, gfw_inv(a, w), w) == 1
    # w=8 must agree with the GF(2^8) tables (same 0x11D polynomial)
    from ceph_tpu.gf.tables import gf_mul
    for a in (1, 2, 77, 200, 255):
        for b in (1, 3, 128, 254):
            assert gfw_mul(a, b, 8) == gf_mul(a, b)


def test_element_bitmatrix_is_multiplication():
    """bits(e * v) == M(e) @ bits(v) over GF(2) for every v — the
    jerasure_matrix_to_bitmatrix companion property."""
    for w in (4, 8):
        for e in (1, 2, 3, 9, (1 << w) - 1):
            M = element_bitmatrix(e, w)
            for v in range(1 << w):
                bits_v = np.array([(v >> i) & 1 for i in range(w)],
                                  dtype=np.uint8)
                got = (M @ bits_v) % 2
                pv = gfw_mul(e, v, w)
                expect = np.array([(pv >> i) & 1 for i in range(w)],
                                  dtype=np.uint8)
                np.testing.assert_array_equal(got, expect, err_msg=(w, e, v))


def test_cauchy_good_is_denser_improvement():
    """cauchy_good's improvement must not increase total bitmatrix ones
    and must keep row 0 all ones."""
    for (k, m, w) in [(4, 3, 8), (5, 2, 8), (7, 3, 8), (5, 2, 4)]:
        orig = cauchy_original_matrix(k, m, w)
        good = cauchy_good_matrix(k, m, w)
        assert all(int(e) == 1 for e in good[0])
        ones_orig = sum(n_ones(int(e), w) for e in orig.ravel())
        ones_good = sum(n_ones(int(e), w) for e in good.ravel())
        assert ones_good <= ones_orig


def test_liberation_density_bound():
    """Liberation codes have exactly k*w + k - 1 ones in the Q block set
    (the minimal-density bound from the paper)."""
    for (k, w) in [(2, 7), (5, 7), (7, 7), (4, 5), (11, 11)]:
        bm = liberation_bitmatrix(k, w)
        assert int(bm[w:].sum()) == k * w + k - 1
        assert int(bm[:w].sum()) == k * w  # parity identities


def test_blaum_roth_ring_property():
    """Q blocks are powers of the x-multiplication matrix: block_j =
    T^j, so block_{j+1} = block_j @ T."""
    k, w = 4, 6
    bm = blaum_roth_bitmatrix(k, w)
    blocks = [bm[w:, j * w:(j + 1) * w] for j in range(k)]
    np.testing.assert_array_equal(blocks[0], np.eye(w, dtype=np.uint8))
    T = blocks[1]
    acc = np.eye(w, dtype=np.uint8)
    for j in range(k):
        np.testing.assert_array_equal(blocks[j], acc)
        acc = (acc @ T) % 2
    # and every pair of erasures is decodable (MDS over the ring)
    full = np.vstack([np.eye(k * w, dtype=np.uint8), bm])
    for e1, e2 in itertools.combinations(range(k + 2), 2):
        avail = [c for c in range(k + 2) if c not in (e1, e2)][:k]
        rows = np.concatenate([np.arange(c * w, (c + 1) * w) for c in avail])
        gf2_invert(full[rows])  # raises if singular


def test_liber8tion_mds_all_k():
    for k in range(2, 9):
        w = 8
        bm = liber8tion_bitmatrix(k)
        full = np.vstack([np.eye(k * w, dtype=np.uint8), bm])
        for e1, e2 in itertools.combinations(range(k + 2), 2):
            avail = [c for c in range(k + 2) if c not in (e1, e2)][:k]
            rows = np.concatenate(
                [np.arange(c * w, (c + 1) * w) for c in avail])
            gf2_invert(full[rows])


def test_packet_layout_semantics():
    """Coding packet (i, l) is the XOR of the data packets selected by
    bitmatrix row i*w+l — checked against a direct packet-loop oracle."""
    k, m, w, ps = 3, 2, 4, 4
    rng = np.random.default_rng(3)
    bm = matrix_to_bitmatrix(cauchy_original_matrix(k, m, w), w)
    codec = BitmatrixPacketCodec(bm, k, m, w, ps)
    C = w * ps * 3  # three super-blocks
    data = rng.integers(0, 256, (k, C), dtype=np.uint8)
    coding = codec.encode(data)
    for b in range(3):          # super-block
        for i in range(m):
            for l in range(w):
                acc = np.zeros(ps, dtype=np.uint8)
                for j in range(k):
                    for xbit in range(w):
                        if bm[i * w + l, j * w + xbit]:
                            pkt = data[j, b * w * ps + xbit * ps:
                                       b * w * ps + (xbit + 1) * ps]
                            acc ^= pkt
                got = coding[i, b * w * ps + l * ps:b * w * ps + (l + 1) * ps]
                np.testing.assert_array_equal(got, acc,
                                              err_msg=(b, i, l))


def test_packetsize_changes_chunk_bytes():
    """Packet layout is part of the on-disk format: different packetsize
    must shuffle bytes (unlike pointwise RS)."""
    prof = {"plugin": "jerasure", "technique": "cauchy_good", "k": "4",
            "m": "2", "backend": "host"}
    rng = np.random.default_rng(4)
    payload = rng.integers(0, 256, 4 * 8 * 64, dtype=np.uint8).tobytes()
    c1 = create_erasure_code(dict(prof, packetsize="4"))
    c2 = create_erasure_code(dict(prof, packetsize="8"))
    e1 = c1.encode(set(range(6)), payload)
    e2 = c2.encode(set(range(6)), payload)
    # chunk 4 is the all-ones parity row (layout-invariant pointwise
    # XOR); chunk 5 carries real bitmatrix structure and must shuffle
    assert bytes(e1[5]) != bytes(e2[5])
    # data chunks identical (systematic either way)
    assert bytes(e1[0])[:len(payload) // 4] == \
        bytes(e2[0])[:len(payload) // 4]


@pytest.mark.parametrize("tech,prof", [
    ("cauchy_good", {"k": "4", "m": "2", "packetsize": "8"}),
    ("liber8tion", {"k": "4", "packetsize": "4"}),
])
def test_device_host_parity_bitmatrix(tech, prof):
    """The MXU bit-matmul over virtual packet chunks must equal the host
    XOR path byte for byte."""
    rng = np.random.default_rng(5)
    payload = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    base = {"plugin": "jerasure", "technique": tech, **prof}
    host = create_erasure_code(dict(base, backend="host"))
    dev = create_erasure_code(dict(base, backend="tpu"))
    n = host.get_chunk_count()
    eh = host.encode(set(range(n)), payload)
    ed = dev.encode(set(range(n)), payload)
    for i in range(n):
        np.testing.assert_array_equal(eh[i], ed[i], err_msg=f"chunk {i}")


@pytest.mark.parametrize("w,tech,k,m", [
    (16, "reed_sol_van", 4, 2), (32, "reed_sol_van", 5, 3),
    (16, "reed_sol_r6_op", 4, 2), (32, "reed_sol_r6_op", 6, 2),
])
def test_reed_sol_word_widths(w, tech, k, m):
    """w=16/32 LE-word layout: exhaustive erasure roundtrip + the word
    semantics (coding word = XOR gfw_mul(coeff, data word))."""
    from ceph_tpu.gf.bitmatrix import gfw_mul
    prof = {"plugin": "jerasure", "technique": tech, "k": str(k),
            "m": str(m), "w": str(w), "backend": "host"}
    c = create_erasure_code(prof)
    n = c.get_chunk_count()
    k, m = c.get_data_chunk_count(), n - c.get_data_chunk_count()
    rng = np.random.default_rng(w + k)
    payload = rng.integers(0, 256, 3333, dtype=np.uint8).tobytes()
    enc = c.encode(set(range(n)), payload)
    assert c.decode_concat(enc)[:len(payload)] == payload
    for e in range(1, m + 1):
        for gone in itertools.combinations(range(n), e):
            avail = {i: enc[i] for i in range(n) if i not in gone}
            dec = c.decode(set(gone), avail)
            for i in gone:
                np.testing.assert_array_equal(dec[i], enc[i],
                                              err_msg=(w, tech, gone))
    # word-level oracle on the first words
    dt = np.dtype("<u2") if w == 16 else np.dtype("<u4")
    words = [np.frombuffer(bytes(enc[j]), dtype=dt) for j in range(n)]
    mat = c.codec.matrix
    for i in range(m):
        acc = 0
        for j in range(k):
            acc ^= gfw_mul(int(mat[k + i, j]), int(words[j][0]), w)
        assert acc == int(words[k + i][0]), (w, tech, i)


def test_reed_sol_word_device_parity():
    """The companion-bitmatrix MXU path equals the split-table host path."""
    prof = {"plugin": "jerasure", "technique": "reed_sol_van", "k": "4",
            "m": "2", "w": "16"}
    rng = np.random.default_rng(11)
    payload = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
    host = create_erasure_code(dict(prof, backend="host"))
    dev = create_erasure_code(dict(prof, backend="tpu"))
    eh = host.encode(set(range(6)), payload)
    ed = dev.encode(set(range(6)), payload)
    for i in range(6):
        np.testing.assert_array_equal(eh[i], ed[i], err_msg=f"chunk {i}")


def test_reed_sol_w9_rejected():
    with pytest.raises(ValueError):
        create_erasure_code({"plugin": "jerasure", "k": "4", "m": "2",
                             "w": "9"})


def test_mini_cluster_with_bitmatrix_pool():
    """End-to-end: a cauchy_good EC pool in the vstart-lite cluster."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("cg", k=3, m=2, pg_num=8, plugin="jerasure",
                     extra_profile={"technique": "cauchy_good",
                                    "packetsize": "4"})
    client = c.client("client.cg")
    rng = np.random.default_rng(6)
    data = rng.integers(0, 256, 20000, dtype=np.uint8).tobytes()
    assert client.write_full("cg", "ob", data) == 0
    assert client.read("cg", "ob") == data
    holders = {o.osd_id for o in c.osds.values()
               if any(ho.oid == "ob"
                      for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    victim = next(iter(holders))
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    assert client.read("cg", "ob") == data
