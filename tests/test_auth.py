"""Auth subsystem tests (src/auth/ cephx role).

Protocol level: challenge/response against the KDC, ticket issuance,
authorizer verification from rotating secrets, expiry/rotation, and
tamper-evidence of every blob.  Transport level: two TcpNetworks in one
process handshake and exchange signed frames; wrong keys, unknown
entities, spoofed src names, and bit-flipped frames are all rejected.
"""
from __future__ import annotations

import os
import struct
import threading
import time

import pytest

from ceph_tpu.auth import (
    AuthError, CephxClient, CephxServer, CephxServiceVerifier, Keyring,
    decrypt, encrypt, hmac_tag,
)
from ceph_tpu.msg.messages import MMonPing
from ceph_tpu.msg.messenger import Dispatcher
from ceph_tpu.msg.tcp import TcpAuth, TcpNetwork


# ---- crypto ----------------------------------------------------------------

def test_encrypt_decrypt_roundtrip():
    key = os.urandom(16)
    for n in (0, 1, 31, 32, 33, 4096):
        pt = os.urandom(n)
        assert decrypt(key, encrypt(key, pt)) == pt


def test_decrypt_rejects_tamper_and_wrong_key():
    key = os.urandom(16)
    blob = bytearray(encrypt(key, b"secret payload"))
    for pos in (0, len(blob) // 2, len(blob) - 1):
        t = bytearray(blob)
        t[pos] ^= 0x01
        with pytest.raises(AuthError):
            decrypt(key, bytes(t))
    with pytest.raises(AuthError):
        decrypt(os.urandom(16), bytes(blob))


def test_keyring_file_roundtrip(tmp_path):
    kr = Keyring()
    s1 = kr.create("mon")
    s2 = kr.create("osd.0")
    assert kr.create("mon") == s1          # get-or-create is stable
    path = str(tmp_path / "keyring")
    kr.save(path)
    back = Keyring.load(path)
    assert back.get("mon") == s1 and back.get("osd.0") == s2
    assert back.get("osd.99") is None


# ---- KDC protocol ----------------------------------------------------------

def _kdc_pair(entities=("mon", "osd.0", "client.x")):
    kr = Keyring()
    for e in entities:
        kr.create(e)
    return kr, CephxServer(kr)


def _login(server: CephxServer, entity: str, secret: bytes) -> CephxClient:
    client = CephxClient(entity, secret)
    ch = server.get_challenge(entity)
    cch, proof = client.make_proof(ch)
    client.handle_reply(server.authenticate(entity, ch, cch, proof))
    return client


def test_kdc_exchange_issues_tickets_and_rotating_keys():
    kr, server = _kdc_pair()
    osd = _login(server, "osd.0", kr.get("osd.0"))
    assert osd.authenticated()
    for svc in ("mon", "osd", "mgr", "client"):
        assert svc in osd.tickets
    # daemon got its own service's rotating secrets, nothing else's
    assert "osd" in osd.rotating and "mon" not in osd.rotating
    cl = _login(server, "client.x", kr.get("client.x"))
    assert "client" in cl.rotating and "osd" not in cl.rotating


def test_kdc_rejects_wrong_secret_unknown_entity_stale_challenge():
    kr, server = _kdc_pair()
    bad = CephxClient("osd.0", os.urandom(16))
    ch = server.get_challenge("osd.0")
    cch, proof = bad.make_proof(ch)
    with pytest.raises(AuthError):
        server.authenticate("osd.0", ch, cch, proof)
    # challenge is consumed by the failed attempt (no retry oracle)
    good = CephxClient("osd.0", kr.get("osd.0"))
    cch, proof = good.make_proof(ch)
    with pytest.raises(AuthError):
        server.authenticate("osd.0", ch, cch, proof)
    with pytest.raises(AuthError):
        server.authenticate("osd.99", server.get_challenge("osd.99"),
                            b"x" * 16, b"y" * 16)
    # a challenge issued to one entity cannot prove another
    kr.create("client.evil")
    ch2 = server.get_challenge("client.evil")
    victim = CephxClient("osd.0", kr.get("osd.0"))
    cch, proof = victim.make_proof(ch2)
    with pytest.raises(AuthError):
        server.authenticate("osd.0", ch2, cch, proof)


def test_authorizer_verify_and_mutual_proof():
    kr, server = _kdc_pair()
    cl = _login(server, "client.x", kr.get("client.x"))
    osd = _login(server, "osd.0", kr.get("osd.0"))
    verifier = CephxServiceVerifier("osd", osd.rotating["osd"])
    auth, sk, nonce = cl.build_authorizer("osd")
    entity, vsk, reply = verifier.verify_authorizer(auth)
    assert entity == "client.x" and vsk == sk
    assert cl.check_authorizer_reply(sk, nonce, reply)
    # a reply proof computed under the wrong key fails the mutual check
    assert not cl.check_authorizer_reply(sk, nonce,
                                         hmac_tag(os.urandom(16),
                                                  struct.pack("<Q",
                                                              nonce + 1)))


def test_authorizer_rejects_tampered_ticket_wrong_service_bad_proof():
    kr, server = _kdc_pair()
    cl = _login(server, "client.x", kr.get("client.x"))
    osd = _login(server, "osd.0", kr.get("osd.0"))
    verifier = CephxServiceVerifier("osd", osd.rotating["osd"])
    auth, _sk, _nonce = cl.build_authorizer("osd")
    t = dict(auth)
    tb = bytearray(t["ticket"])
    tb[len(tb) // 2] ^= 1
    t["ticket"] = bytes(tb)
    with pytest.raises(AuthError):
        verifier.verify_authorizer(t)
    mon_auth, _, _ = cl.build_authorizer("mon")
    with pytest.raises(AuthError):          # mon ticket shown to an osd
        verifier.verify_authorizer(mon_auth)
    t2 = dict(auth)
    t2["proof"] = os.urandom(16)
    with pytest.raises(AuthError):
        verifier.verify_authorizer(t2)


def test_authorizer_replay_needs_fresh_challenge():
    """A recorded authorizer cannot re-authenticate a new connection:
    the proof binds the connection's server challenge
    (CVE-2018-1128-class replay, closed the same way)."""
    kr, server = _kdc_pair()
    cl = _login(server, "client.x", kr.get("client.x"))
    osd = _login(server, "osd.0", kr.get("osd.0"))
    verifier = CephxServiceVerifier("osd", osd.rotating["osd"])
    ch1 = os.urandom(16)
    auth, _, _ = cl.build_authorizer("osd", ch1)
    verifier.verify_authorizer(auth, ch1)        # live connection: ok
    with pytest.raises(AuthError):               # replay, new challenge
        verifier.verify_authorizer(auth, os.urandom(16))
    with pytest.raises(AuthError):               # replay, no challenge
        verifier.verify_authorizer(auth)


def test_kdc_challenge_table_bounded():
    """HELLO floods can't grow the KDC's challenge table: unknown
    entities are rejected outright and expired entries are swept."""
    kr, server = _kdc_pair()
    with pytest.raises(AuthError):
        server.get_challenge("osd.999")
    t0 = time.time()
    for _ in range(50):
        server.get_challenge("osd.0", now=t0)
    assert len(server._challenges) == 50
    # all expired by the next issue -> swept down to the new one
    server.get_challenge("osd.0", now=t0 + 61.0)
    assert len(server._challenges) == 1


def test_client_knows_when_to_renew():
    """Tickets carry a client-readable expiry; needs_renewal() trips
    RENEW_MARGIN early so reconnects re-run the KDC exchange instead
    of retrying an expired ticket forever."""
    kr, _ = _kdc_pair()
    server = CephxServer(kr, ticket_ttl=120.0)
    cl = _login(server, "osd.0", kr.get("osd.0"))
    now = time.time()
    assert not cl.needs_renewal(now=now)
    assert cl.needs_renewal(now=now + 61.0)     # inside the margin
    assert CephxClient("osd.1", os.urandom(16)).needs_renewal()


def test_ticket_expiry_and_rotation():
    kr, _ = _kdc_pair()
    server = CephxServer(kr, ticket_ttl=10.0)
    cl = _login(server, "client.x", kr.get("client.x"))
    osd = _login(server, "osd.0", kr.get("osd.0"))
    verifier = CephxServiceVerifier("osd", osd.rotating["osd"])
    auth, _, _ = cl.build_authorizer("osd")
    verifier.verify_authorizer(auth, now=time.time())
    with pytest.raises(AuthError):          # past the ttl
        verifier.verify_authorizer(auth, now=time.time() + 11.0)
    # rotation: new tickets use the new secret id; a verifier that
    # never learned it rejects, one that refreshed accepts
    server.rotate()
    cl2 = _login(server, "client.x", kr.get("client.x"))
    auth2, _, _ = cl2.build_authorizer("osd")
    with pytest.raises(AuthError):
        verifier.verify_authorizer(auth2)
    verifier.update_rotating(
        {sid: (sec, exp) for sid, (sec, exp)
         in server.rotating["osd"].items()})
    verifier.verify_authorizer(auth2)


# ---- transport integration -------------------------------------------------

class _Sink(Dispatcher):
    def __init__(self):
        self.got = []

    def ms_fast_dispatch(self, msg):
        self.got.append(msg)


def _free_port():
    import socket as sk
    s = sk.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture
def auth_pair(tmp_path):
    """mon-net (KDC) + osd-net on localhost TCP with auth enabled."""
    kr = Keyring()
    for e in ("mon", "osd.0", "client.x"):
        kr.create(e)
    path = str(tmp_path / "keyring")
    kr.save(path)
    pm, po = _free_port(), _free_port()
    directory = {"mon": ("127.0.0.1", pm), "osd.0": ("127.0.0.1", po)}
    mon_net = TcpNetwork(("127.0.0.1", pm), directory,
                         auth=TcpAuth("mon", path, kdc=True))
    osd_net = TcpNetwork(("127.0.0.1", po), directory,
                         auth=TcpAuth("osd.0", path))
    nets = [mon_net, osd_net]
    try:
        yield kr, path, directory, mon_net, osd_net, nets
    finally:
        for n in nets:
            n.close()


def _pump_until(nets, pred, seconds=10.0):
    end = time.monotonic() + seconds
    while time.monotonic() < end:
        for n in nets:
            n.pump(quiesce=0.01, deadline=0.2)
        if pred():
            return True
    return pred()


def _serve(nets, stop):
    """Pump *nets* from a thread so blocking handshakes can complete."""
    while not stop.is_set():
        for n in nets:
            n.pump(quiesce=0.01, deadline=0.1)


def test_tcp_auth_handshake_and_signed_delivery(auth_pair):
    kr, path, directory, mon_net, osd_net, nets = auth_pair
    mon_sink, osd_sink = _Sink(), _Sink()
    mon_net.create_messenger("mon").add_dispatcher_head(mon_sink)
    osd_net.create_messenger("osd.0").add_dispatcher_head(osd_sink)
    stop = threading.Event()
    t = threading.Thread(target=_serve, args=([mon_net], stop))
    t.start()
    try:
        # osd -> mon: triggers KDC bootstrap + authorizer on connect
        osd_net.send("osd.0", "mon", MMonPing(rank=0))
        assert _pump_until([osd_net], lambda: len(mon_sink.got) == 1)
    finally:
        stop.set()
        t.join()
    assert osd_net.auth.client.authenticated()
    # mon -> osd: replies flow over mon's own authed connection
    stop = threading.Event()
    t = threading.Thread(target=_serve, args=([osd_net], stop))
    t.start()
    try:
        mon_net.send("mon", "osd.0", MMonPing(rank=1))
        assert _pump_until([mon_net], lambda: len(osd_sink.got) == 1)
    finally:
        stop.set()
        t.join()
    assert mon_net.auth_rejects == 0 and osd_net.auth_rejects == 0


def test_tcp_auth_rejects_wrong_key_and_unkeyed_entity(auth_pair,
                                                       tmp_path):
    kr, path, directory, mon_net, osd_net, nets = auth_pair
    mon_sink = _Sink()
    mon_net.create_messenger("mon").add_dispatcher_head(mon_sink)
    # an intruder with a self-invented key for a real entity name
    bad_kr = Keyring()
    bad_kr.create("osd.0")
    bad_path = str(tmp_path / "bad_keyring")
    bad_kr.save(bad_path)
    ip = _free_port()
    intruder = TcpNetwork(("127.0.0.1", ip),
                          {**directory, "osd.0": ("127.0.0.1", ip)},
                          auth=TcpAuth("osd.0", bad_path))
    stop = threading.Event()
    t = threading.Thread(target=_serve, args=([mon_net], stop))
    t.start()
    try:
        intruder.send("osd.0", "mon", MMonPing(rank=0))
        _pump_until([intruder], lambda: False, seconds=2.0)
    finally:
        stop.set()
        t.join()
        intruder.close()
    assert mon_sink.got == []
    assert not intruder.auth.client.authenticated()


def test_tcp_auth_drops_unsigned_and_spoofed_frames(auth_pair):
    """A raw socket shoving unauthenticated or forged frames at an
    auth-enabled listener gets every frame dropped."""
    import socket as sk
    kr, path, directory, mon_net, osd_net, nets = auth_pair
    mon_sink = _Sink()
    mon_net.create_messenger("mon").add_dispatcher_head(mon_sink)
    from ceph_tpu.msg.wire import encode_message
    payload = encode_message(MMonPing(rank=0))
    dname = b"mon"
    frame = struct.pack("<I H B", len(payload), len(dname), 0) \
        + dname + payload
    raw = sk.create_connection(tuple(directory["mon"]), timeout=5.0)
    # no handshake at all; with and without a junk signature trailer
    raw.sendall(frame + os.urandom(8))
    raw.sendall(frame)
    _pump_until([mon_net], lambda: mon_net.auth_rejects > 0,
                seconds=5.0)
    raw.close()
    assert mon_sink.got == []
    assert mon_net.auth_rejects > 0


def test_tcp_auth_src_service_enforcement(auth_pair):
    """client.x's key cannot put osd-sourced frames on the wire: the
    signature binds frames to the authenticated principal's service."""
    kr, path, directory, mon_net, osd_net, nets = auth_pair
    mon_sink = _Sink()
    mon_net.create_messenger("mon").add_dispatcher_head(mon_sink)
    cp = _free_port()
    cl_net = TcpNetwork(("127.0.0.1", cp),
                        {**directory, "client.x": ("127.0.0.1", cp)},
                        auth=TcpAuth("client.x", path))
    stop = threading.Event()
    t = threading.Thread(target=_serve, args=([mon_net], stop))
    t.start()
    try:
        cl_net.send("osd.0", "mon", MMonPing(rank=0))   # spoofed src
        cl_net.send("client.x", "mon", MMonPing(rank=7))
        _pump_until([cl_net],
                    lambda: len(mon_sink.got) >= 1, seconds=5.0)
    finally:
        stop.set()
        t.join()
        cl_net.close()
    assert [m.rank for m in mon_sink.got] == [7]
    assert mon_net.auth_rejects >= 1
