"""PGLS object listing: the rados_nobjects_list client surface.

Reference shape: the Objecter sends pg-targeted PGNLS ops with cursor
pagination (PrimaryLogPG::do_pg_op); listings cover head objects only
(no clones, no PG metadata) and work on replicated and EC pools, during
degradation, and after the primary moves.
"""
import pytest

from ceph_tpu.cluster import MiniCluster


@pytest.mark.parametrize("kind", ["rep", "ec"])
def test_listing_complete_and_clean(kind):
    c = MiniCluster(n_osds=6)
    if kind == "ec":
        c.create_ec_pool("p", k=2, m=1, plugin="isa", pg_num=8)
    else:
        c.create_replicated_pool("p", size=3, pg_num=8)
    cl = c.client("client.ls")
    names = {f"obj-{i:03d}" for i in range(40)}
    for n in names:
        cl.write_full("p", n, n.encode())
    assert set(cl.list_objects("p")) == names
    # deletions disappear from the listing
    cl.remove("p", "obj-000")
    assert set(cl.list_objects("p")) == names - {"obj-000"}


def test_listing_excludes_clones_and_pagination():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=8)
    cl = c.client("client.ls")
    for i in range(25):
        cl.write_full("p", f"o{i:02d}", b"v1")
    cl.snap_create("p", "s1")
    for i in range(25):
        cl.write_full("p", f"o{i:02d}", b"v2")     # makes clones
    got = list(cl.list_objects("p", page=4))       # force pagination
    assert sorted(got) == [f"o{i:02d}" for i in range(25)]
    assert len(got) == len(set(got))               # no duplicates


def test_listing_survives_failure():
    c = MiniCluster(n_osds=5)
    c.create_replicated_pool("p", size=3, pg_num=8)
    cl = c.client("client.ls")
    names = {f"x{i}" for i in range(20)}
    for n in names:
        cl.write_full("p", n, b"d")
    c.kill_osd(0)
    for _ in range(6):
        c.tick(dt=6.0)
    assert set(cl.list_objects("p")) == names
