"""Multi-process vstart cluster: mon + OSDs as real processes over TCP.

The reference's standalone tier (src/vstart.sh,
qa/standalone/erasure-code/test-erasure-code.sh:21-53) runs daemons on
localhost ports and thrashes them with kill -9
(qa/tasks/ceph_manager.py:195).  This test does the same with
ceph_tpu.vstart: spin mon + 6 OSD processes, write/read an EC pool,
SIGKILL an acting OSD, watch heartbeat detection + re-peer + backfill
happen entirely over sockets, then kill a SECOND original member —
readable data afterwards proves the replacement really received its
shard (k=2 of the surviving 2)."""
import time

import numpy as np
import pytest

from ceph_tpu.osdmap import pg_t
from ceph_tpu.vstart import ProcessCluster


@pytest.fixture(scope="module")
def cluster():
    c = ProcessCluster(
        n_osds=6,
        pool={"name": "p", "pg_num": 4,
              "profile": {"plugin": "isa", "k": "2", "m": "1"}},
        heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


NONE = 0x7FFFFFFF          # CRUSH_ITEM_NONE


def _acting(cl, oid):
    pgid, primary = cl._calc_target(cl.lookup_pool("p"), oid)
    *_, acting, ap = cl.osdmap.pg_to_up_acting_osds(pg_t(*pgid))
    return [o for o in acting if o != NONE], ap


def _wait_down(c, cl, osd_id, timeout=45.0):
    end = time.monotonic() + timeout
    while time.monotonic() < end:
        c.pump_for(1.0)
        cl.mon.send_full_map(cl.name)
        c.network.pump()
        if not cl.osdmap.is_up(osd_id):
            return True
    return False


def test_tell_osd_over_sockets(cluster):
    """'ceph tell osd.N' against a REAL daemon process: MCommand and
    its reply cross TCP; injectargs mutates the remote daemon's
    config registry and a follow-up config get reads it back."""
    c = cluster
    cl = c.client()
    c.wait_healthy(cl)
    out = None
    for _ in range(30):
        try:
            out = cl.osd_command(0, "config get",
                                 name="osd_heartbeat_grace")
            break
        except IOError:
            time.sleep(0.5)
    assert out is not None
    out = cl.osd_command(0, "injectargs",
                         opts={"osd_heartbeat_grace": "44"})
    assert out["osd_heartbeat_grace"] == 44.0
    got = cl.osd_command(0, "config get",
                         name="osd_heartbeat_grace")
    assert got["osd_heartbeat_grace"] == 44.0
    # other daemons are untouched: per-process registries
    other = cl.osd_command(1, "config get",
                           name="osd_heartbeat_grace")
    assert other["osd_heartbeat_grace"] != 44.0
    perf = cl.osd_command(0, "perf dump")
    assert isinstance(perf, dict) and perf


def test_process_cluster_write_kill_recover(cluster):
    c = cluster
    cl = c.client()
    # wait_healthy re-requests the map until every osd shows up, which
    # subsumes waiting for the FIRST map under heavy host load
    c.wait_healthy(cl)
    assert cl.osdmap.epoch > 0
    rng = np.random.default_rng(4)
    data = rng.integers(0, 256, 30000, dtype=np.uint8).tobytes()
    # daemons may still be chewing their map backlog: the reference
    # Objecter blocks/retries until ops land, so retry the first write
    r = -1
    for _ in range(30):
        r = cl.write_full("p", "obj", data)
        if r == 0:
            break
        time.sleep(0.5)
    assert r == 0
    assert cl.read("p", "obj") == data

    acting, primary = _acting(cl, "obj")
    assert len(acting) == 3
    victim = next(o for o in acting if o != primary)
    c.kill_osd(victim)
    # the surviving daemons' heartbeats must detect the silent peer and
    # convince the mon (2-reporter quorum), all over sockets
    assert _wait_down(c, cl, victim), "mon never marked the victim down"

    # degraded read + fresh writes keep working
    assert cl.read("p", "obj") == data
    data2 = rng.integers(0, 256, 16000, dtype=np.uint8).tobytes()
    assert cl.write_full("p", "obj2", data2) == 0
    assert cl.read("p", "obj2") == data2

    # the mon's down->out eviction re-places the dead slot; wait for a
    # full replacement acting set, then give backfill time to land
    deadline = time.monotonic() + 40
    new_acting = []
    while time.monotonic() < deadline:
        c.pump_for(1.0)
        cl.mon.send_full_map(cl.name)
        c.network.pump()
        new_acting, _ = _acting(cl, "obj")
        if len(new_acting) == 3 and victim not in new_acting:
            break
    assert len(new_acting) == 3 and victim not in new_acting, new_acting
    c.pump_for(12.0)     # backfill window (proved by the 2nd kill below)
    # kill a SECOND original member: the data is then only readable if
    # the replacement actually holds its recovered shard (k=2 needs 2)
    survivors = [o for o in acting if o != victim]
    victim2 = next(o for o in survivors if o in new_acting)
    c.kill_osd(victim2)
    assert _wait_down(c, cl, victim2), "second victim never marked down"
    deadline = time.monotonic() + 30
    got = None
    while time.monotonic() < deadline:
        c.pump_for(1.0)
        try:
            got = cl.read("p", "obj")
        except IOError:
            got = None
        if got == data:
            break
    assert got == data, "recovered shard missing: backfill never landed"
    assert cl.read("p", "obj2") == data2
