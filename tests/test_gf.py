"""GF(2^8) field + matrix generator tests."""
import numpy as np
import pytest

from ceph_tpu.gf import (
    GF_POLY, gf_exp, gf_log, gf_mul, gf_inv, gf_div, gf_pow, MUL_TABLE,
    gf_mult_bitmatrix, expand_to_bitmatrix,
    gf_gen_rs_matrix, gf_gen_cauchy1_matrix, jerasure_reed_sol_van_matrix,
    gf_invert_matrix, gf_matmul,
)


def slow_mul(a, b):
    """Carry-less multiply + reduction — independent of the tables."""
    p = 0
    for i in range(8):
        if b & (1 << i):
            p ^= a << i
    for i in range(15, 7, -1):
        if p & (1 << i):
            p ^= GF_POLY << (i - 8)
    return p


def test_tables_against_carryless_mult():
    rng = np.random.default_rng(0)
    for _ in range(2000):
        a, b = int(rng.integers(256)), int(rng.integers(256))
        assert gf_mul(a, b) == slow_mul(a, b)
        assert MUL_TABLE[a, b] == slow_mul(a, b)


def test_field_axioms():
    for a in range(1, 256):
        assert gf_mul(a, gf_inv(a)) == 1
        assert gf_div(a, a) == 1
        assert gf_mul(a, 1) == a
    # distributivity spot checks
    rng = np.random.default_rng(1)
    for _ in range(500):
        a, b, c = (int(x) for x in rng.integers(256, size=3))
        assert gf_mul(a, b ^ c) == gf_mul(a, b) ^ gf_mul(a, c)


def test_exp_log_roundtrip():
    for a in range(1, 256):
        assert gf_exp[gf_log[a]] == a


def test_bitmatrix_mult():
    rng = np.random.default_rng(2)
    for _ in range(200):
        c, x = int(rng.integers(256)), int(rng.integers(256))
        m = gf_mult_bitmatrix(c)
        xb = np.array([(x >> i) & 1 for i in range(8)], dtype=np.uint8)
        pb = (m @ xb) % 2
        p = sum(int(pb[i]) << i for i in range(8))
        assert p == gf_mul(c, x)


def _is_mds(matrix, k, m):
    """Every k x k submatrix from any k of the k+m rows must be invertible."""
    import itertools
    for rows in itertools.combinations(range(k + m), k):
        try:
            gf_invert_matrix(matrix[list(rows), :])
        except np.linalg.LinAlgError:
            return False
    return True


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (8, 4), (21, 4), (6, 3)])
def test_isa_vandermonde_mds_within_limits(k, m):
    # reference guarantees MDS only within k<=21..32, m<=4 (ErasureCodeIsa.cc:330)
    mat = gf_gen_rs_matrix(k + m, k)
    assert (mat[:k] == np.eye(k, dtype=np.uint8)).all()
    assert (mat[k] == 1).all()  # first coding row is XOR (region_xor fast path)
    assert _is_mds(mat, k, m)


@pytest.mark.parametrize("k,m", [(2, 2), (4, 3), (8, 4), (10, 4)])
def test_cauchy_mds(k, m):
    mat = gf_gen_cauchy1_matrix(k + m, k)
    assert (mat[:k] == np.eye(k, dtype=np.uint8)).all()
    assert _is_mds(mat, k, m)


@pytest.mark.parametrize("k,m", [(2, 1), (4, 2), (7, 3), (8, 4), (9, 6)])
def test_jerasure_reed_sol_van_mds(k, m):
    mat = jerasure_reed_sol_van_matrix(k, m)
    assert mat.shape == (m, k)
    full = np.vstack([np.eye(k, dtype=np.uint8), mat])
    assert _is_mds(full, k, m)


def test_jerasure_reed_sol_van_deterministic():
    # construction is deterministic and systematic; jerasure's own binary
    # output is unverifiable here (empty submodule in the reference tree),
    # so we pin our own construction to catch regressions
    a = jerasure_reed_sol_van_matrix(4, 2)
    b = jerasure_reed_sol_van_matrix(4, 2)
    np.testing.assert_array_equal(a, b)
    assert a.shape == (2, 4)


def test_matrix_inverse():
    rng = np.random.default_rng(3)
    for _ in range(50):
        k = int(rng.integers(2, 12))
        while True:
            a = rng.integers(0, 256, size=(k, k)).astype(np.uint8)
            try:
                inv = gf_invert_matrix(a)
                break
            except np.linalg.LinAlgError:
                continue
        prod = gf_matmul(a, inv)
        assert (prod == np.eye(k, dtype=np.uint8)).all()


def test_expand_to_bitmatrix_matches_scalar():
    rng = np.random.default_rng(4)
    k, m = 4, 2
    coding = rng.integers(0, 256, size=(m, k)).astype(np.uint8)
    big = expand_to_bitmatrix(coding)
    data = rng.integers(0, 256, size=k).astype(np.uint8)
    bits = np.concatenate(
        [[(int(d) >> i) & 1 for i in range(8)] for d in data]).astype(np.uint8)
    out_bits = (bits @ big) % 2
    for r in range(m):
        byte = sum(int(out_bits[r * 8 + i]) << i for i in range(8))
        ref = 0
        for c in range(k):
            ref ^= gf_mul(int(coding[r, c]), int(data[c]))
        assert byte == ref


def test_gf_pow():
    assert gf_pow(2, 0) == 1
    assert gf_pow(2, 1) == 2
    assert gf_pow(2, 8) == GF_POLY ^ 0x100  # 2^8 reduces by the polynomial
