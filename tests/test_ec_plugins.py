"""Erasure-code plugin tests: registry, padding, exhaustive erasure sweeps.

Models the reference test strategy (SURVEY.md §4): per-plugin encode/decode
checks across all failure combinations (mirroring TestErasureCodeIsa's
exhaustive (k,m) sweeps) plus registry behavior tests.
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec import plugin_registry, create_erasure_code


def roundtrip_sweep(codec, payload: bytes, max_erasures=None):
    k = codec.get_data_chunk_count()
    n = codec.get_chunk_count()
    m = n - k
    want_all = set(range(n))
    encoded = codec.encode(want_all, payload)
    assert set(encoded) == want_all
    blocksize = codec.get_chunk_size(len(payload))
    for c in encoded.values():
        assert len(c) == blocksize
    # reconstructed payload round-trips (with zero padding)
    out = codec.decode_concat(encoded)
    assert out[:len(payload)] == payload
    assert all(b == 0 for b in out[len(payload):])

    erasure_budget = m if max_erasures is None else max_erasures
    for e in range(1, erasure_budget + 1):
        for gone in itertools.combinations(range(n), e):
            avail = {i: encoded[i] for i in want_all - set(gone)}
            mind = codec.minimum_to_decode(set(gone), set(avail))
            assert len(mind) <= k
            decoded = codec.decode(set(gone), avail)
            for i in gone:
                np.testing.assert_array_equal(
                    decoded[i], encoded[i],
                    err_msg=f"chunk {i} mismatch after erasing {gone}")
    return encoded


@pytest.mark.parametrize("plugin,profile", [
    ("isa", {"k": "4", "m": "2", "backend": "host"}),
    ("isa", {"k": "8", "m": "4", "backend": "host"}),
    ("isa", {"k": "4", "m": "2", "technique": "cauchy", "backend": "host"}),
    ("jerasure", {"k": "4", "m": "2", "backend": "host"}),
    ("jerasure", {"k": "7", "m": "3", "backend": "host"}),
    ("jerasure", {"k": "4", "m": "2", "technique": "reed_sol_r6_op",
                  "backend": "host"}),
    ("jerasure", {"k": "4", "m": "3", "technique": "cauchy_orig",
                  "packetsize": "8", "backend": "host"}),
    ("jerasure", {"k": "4", "m": "3", "technique": "cauchy_good",
                  "packetsize": "8", "backend": "host"}),
    ("jerasure", {"k": "5", "m": "2", "technique": "cauchy_good", "w": "4",
                  "packetsize": "4", "backend": "host"}),
    ("jerasure", {"k": "5", "technique": "liberation", "w": "7",
                  "packetsize": "8", "backend": "host"}),
    ("jerasure", {"k": "4", "technique": "blaum_roth", "w": "6",
                  "packetsize": "8", "backend": "host"}),
    ("jerasure", {"k": "6", "technique": "liber8tion",
                  "packetsize": "8", "backend": "host"}),
    ("example_xor", {"k": "3", "backend": "host"}),
])
def test_roundtrip_exhaustive(plugin, profile):
    codec = plugin_registry.factory(plugin, profile)
    rng = np.random.default_rng(42)
    payload = rng.integers(0, 256, size=4096 + 17, dtype=np.uint8).tobytes()
    roundtrip_sweep(codec, payload)


def test_registry_names_and_create():
    names = plugin_registry.names()
    for expected in ("isa", "jerasure", "tpu", "example_xor"):
        assert expected in names
    codec = create_erasure_code({"plugin": "isa", "k": "4", "m": "2",
                                 "backend": "host"})
    assert codec.get_chunk_count() == 6


def test_registry_unknown_plugin():
    with pytest.raises(KeyError):
        plugin_registry.factory("nope", {})


def test_isa_defaults_and_clamps():
    codec = plugin_registry.factory("isa", {"backend": "host"})
    assert codec.get_data_chunk_count() == 7  # reference DEFAULT_K
    assert codec.get_coding_chunk_count() == 3
    # MDS clamps (ErasureCodeIsa.cc:330-361)
    codec = plugin_registry.factory(
        "isa", {"k": "40", "m": "6", "backend": "host"})
    assert codec.get_data_chunk_count() == 21  # 40->32, then m=4 forces 21
    assert codec.get_coding_chunk_count() == 4


def test_minimum_to_decode_semantics():
    codec = plugin_registry.factory("isa", {"k": "4", "m": "2",
                                            "backend": "host"})
    # want fully available -> want itself
    assert set(codec.minimum_to_decode({1, 2}, {0, 1, 2, 3, 4, 5})) == {1, 2}
    # missing chunk -> first k available in ascending order
    assert set(codec.minimum_to_decode({0}, {1, 2, 3, 4, 5})) == {1, 2, 3, 4}
    with pytest.raises(IOError):
        codec.minimum_to_decode({0}, {1, 2, 3})
    # sub-chunk lists are (0, 1) for MDS codes
    assert codec.minimum_to_decode({1}, {0, 1, 2, 3, 4, 5}) == {1: [(0, 1)]}


def test_chunk_size_semantics():
    isa = plugin_registry.factory("isa", {"k": "4", "m": "2",
                                          "backend": "host"})
    # ceil(len/k) rounded to 32
    assert isa.get_chunk_size(4096) == 1024
    assert isa.get_chunk_size(4097) == 1056  # 1025 -> pad to 32
    jer = plugin_registry.factory("jerasure", {"k": "4", "m": "2",
                                               "backend": "host"})
    # object padded to k*w*4 = 128, divided by k
    assert jer.get_chunk_size(4096) == 1024
    assert jer.get_chunk_size(4097) == (4096 + 128) // 4


def test_isa_m1_parity_is_xor():
    codec = plugin_registry.factory("isa", {"k": "4", "m": "1",
                                            "backend": "host"})
    rng = np.random.default_rng(7)
    payload = rng.integers(0, 256, size=1024, dtype=np.uint8).tobytes()
    enc = codec.encode(set(range(5)), payload)
    xor = np.zeros_like(enc[0])
    for i in range(4):
        xor ^= enc[i]
    np.testing.assert_array_equal(enc[4], xor)


def test_padding_small_objects():
    codec = plugin_registry.factory("isa", {"k": "4", "m": "2",
                                            "backend": "host"})
    payload = b"tiny"
    enc = codec.encode(set(range(6)), payload)
    assert codec.decode_concat(enc)[:4] == payload


def test_mapping_profile_roundtrip():
    # mapping= permutes logical->physical chunk placement: data chunks land
    # on 'D' positions, coding on the rest (ErasureCode.cc to_mapping)
    codec = plugin_registry.factory(
        "isa", {"k": "3", "m": "1", "mapping": "D_DD", "backend": "host"})
    assert list(codec.get_chunk_mapping()) == [0, 2, 3, 1]
    rng = np.random.default_rng(11)
    payload = rng.integers(0, 256, size=1024, dtype=np.uint8).tobytes()
    roundtrip_sweep(codec, payload)


def test_decode_no_chunks_raises_ioerror():
    codec = plugin_registry.factory("isa", {"k": "4", "m": "2",
                                            "backend": "host"})
    with pytest.raises(IOError):
        codec.decode({0}, {})
