"""PGLog + peering: log-bounded delta recovery, all over the messenger.

Models the reference behaviors: PGLog.{h,cc} delta recovery after a flap
(only objects changed while the peer was away move), backfill when a peer
falls beyond the log tail, GetLog when the primary is behind, and the
qa-thrasher blackhole scenarios (qa/tasks/ceph_manager.py:360) — recovery
must converge with a blackholed source because every byte moves through
the fault-injectable fabric (no peer-heap shortcuts).
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.osd.pg_log import LogEntry, OP_DELETE, OP_MODIFY, PGLog
from ceph_tpu.os_store import MemStore, Transaction


def payload(n=20000, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


# ---- unit: the log itself --------------------------------------------------

def test_log_append_trim_and_persistence():
    store = MemStore()
    t = Transaction()
    t.create_collection("meta")
    store.queue_transaction(t)
    log = PGLog(max_entries=5)
    for v in range(1, 12):
        t = Transaction()
        log.append(LogEntry(v, f"o{v % 3}", OP_MODIFY), t, "meta")
        store.queue_transaction(t)
    assert log.head == 11
    assert len(log.entries) == 5
    assert log.tail == 6
    # reload from the store: identical state
    log2 = PGLog(max_entries=5)
    log2.load(store, "meta")
    assert log2.head == 11 and log2.tail == 6
    assert [e.version for e in log2.entries] == [7, 8, 9, 10, 11]
    # bounded query semantics
    assert log2.entries_after(3) is None          # beyond tail: backfill
    assert [e.version for e in log2.entries_after(8)] == [9, 10, 11]
    miss = log2.missing_after(8)
    assert set(miss) <= {"o0", "o1", "o2"}


def test_log_missing_dedups_to_latest():
    log = PGLog()
    t = Transaction()
    t.create_collection("m")
    for v, oid, op in [(1, "a", OP_MODIFY), (2, "b", OP_MODIFY),
                       (3, "a", OP_MODIFY), (4, "b", OP_DELETE)]:
        log.append(LogEntry(v, oid, op), t, "m")
    miss = log.missing_after(0)
    assert miss["a"] == (3, OP_MODIFY)
    assert miss["b"] == (4, OP_DELETE)


# ---- integration: flap -> delta recovery -----------------------------------

def _holders(c, oid):
    return {o.osd_id for o in c.osds.values()
            if o.name not in c.network.down
            and any(ho.oid == oid for cid in o.store.list_collections()
                    for ho in o.store.list_objects(cid))}


def test_flap_recovers_only_the_delta():
    """An osd that flaps (down while writes continue, then back) must
    receive exactly the objects written in its absence — log-bounded
    recovery, not a full-PG rescan (PGLog.h role)."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=1, plugin="tpu")
    cl = c.client("client.f")
    for i in range(6):
        assert cl.write_full("p", f"pre{i}", payload(seed=i)) == 0
    holders = _holders(c, "pre0")
    _, primary = cl._calc_target(cl.lookup_pool("p"), "pre0")
    victim = next(o for o in holders if o != primary)
    before = sum(o.perf["recovery_push"] for o in c.osds.values())
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    # two new objects + one overwrite while the victim is away
    assert cl.write_full("p", "new1", payload(seed=10)) == 0
    assert cl.write_full("p", "new2", payload(seed=11)) == 0
    assert cl.write_full("p", "pre3", payload(seed=12)) == 0
    c.revive_osd(victim)
    c.run_recovery()
    after = sum(o.perf["recovery_push"] for o in c.osds.values())
    # exactly the 3 changed objects moved (one shard each), not all 8
    assert after - before == 3, (before, after)
    # and the data is consistent
    for i in range(6):
        expect = payload(seed=12) if i == 3 else payload(seed=i)
        assert cl.read("p", f"pre{i}") == expect
    assert cl.read("p", "new1") == payload(seed=10)


def test_flap_delete_propagates_via_log():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=1, plugin="tpu")
    cl = c.client("client.d")
    assert cl.write_full("p", "victim_obj", payload(seed=1)) == 0
    holders = _holders(c, "victim_obj")
    _, primary = cl._calc_target(cl.lookup_pool("p"), "victim_obj")
    victim = next(o for o in holders if o != primary)
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    assert cl.remove("p", "victim_obj") == 0
    c.network.pump()
    c.revive_osd(victim)
    c.run_recovery()
    c.network.pump()
    # the revived osd must have applied the delete from the log
    leftovers = [1 for cid in c.osds[victim].store.list_collections()
                 for ho in c.osds[victim].store.list_objects(cid)
                 if ho.oid == "victim_obj"]
    assert not leftovers


def test_backfill_when_log_trimmed():
    """A peer so far behind that the log was trimmed past it gets a
    backfill (scan diff) instead of silent data loss."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=1, plugin="tpu")
    # shrink the log so it trims quickly
    for o in c.osds.values():
        for pg in o.pgs.values():
            pg.pg_log.max_entries = 10
    cl = c.client("client.b")
    assert cl.write_full("p", "old", payload(seed=1)) == 0
    holders = _holders(c, "old")
    _, primary = cl._calc_target(cl.lookup_pool("p"), "old")
    victim = next(o for o in holders if o != primary)
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    for i in range(15):  # push the log tail past the victim's head
        assert cl.write_full("p", f"n{i}", payload(200, seed=i)) == 0
        for o in c.osds.values():
            for pg in o.pgs.values():
                pg.pg_log.max_entries = 10
    c.revive_osd(victim)
    c.run_recovery()
    c.network.pump()
    c.run_recovery()
    for i in range(15):
        assert cl.read("p", f"n{i}") == payload(200, seed=i)
    assert cl.read("p", "old") == payload(seed=1)
    # victim really caught up: kill another holder and read degraded
    holders2 = _holders(c, "n3")
    _, primary2 = cl._calc_target(cl.lookup_pool("p"), "n3")
    other = next(o for o in holders2 if o not in (victim, primary2))
    c.kill_osd(other)
    c.mark_osd_down(other)
    assert cl.read("p", "n3") == payload(200, seed=3)


def test_blackholed_recovery_source_converges():
    """Blackhole a shard holder: heartbeat quorum marks it down, peering
    recomputes, and recovery converges from the remaining shards — every
    recovery byte travels the fabric, so the fault injection actually
    bites (VERDICT #8)."""
    c = MiniCluster(n_osds=7)
    c.create_ec_pool("p", k=3, m=2, pg_num=4, plugin="tpu")
    cl = c.client("client.bh")
    data = {f"o{i}": payload(seed=20 + i) for i in range(5)}
    for oid, d in data.items():
        assert cl.write_full("p", oid, d) == 0
    holders = _holders(c, "o0")
    _, primary = cl._calc_target(cl.lookup_pool("p"), "o0")
    source = next(o for o in holders if o != primary)
    c.blackhole_osd(source)
    # heartbeats: multiple peers report; the single partitioned osd's
    # own reports must NOT take healthy peers down (min reporters)
    for _ in range(6):
        c.tick(dt=6.0)
    assert not c.mon.osdmap.is_up(source)
    up = [o for o in range(7) if c.mon.osdmap.is_up(o)]
    assert len(up) == 6, "healthy osds must stay up"
    c.mon.mark_osd_out(source)
    c.network.pump()
    c.run_recovery()
    c.network.pump()
    c.run_recovery()
    for oid, d in data.items():
        assert cl.read("p", oid) == d
    # redundancy restored on the remaining osds (the blackholed osd still
    # holds its stale copy — it was partitioned, not wiped)
    for oid in data:
        assert len(_holders(c, oid) - {source}) == 5


def test_new_primary_catches_up_via_getlog():
    """If the acting primary's shard is stale (it was down while writes
    landed), it must pull the authoritative log and recover itself before
    serving (the GetLog/GetMissing steps)."""
    c = MiniCluster(n_osds=5)
    c.create_ec_pool("p", k=2, m=2, pg_num=1, plugin="tpu")
    cl = c.client("client.g")
    assert cl.write_full("p", "x", payload(seed=5)) == 0
    pool_id = cl.lookup_pool("p")
    _, primary = cl._calc_target(pool_id, "x")
    c.kill_osd(primary)
    c.mark_osd_down(primary)
    assert cl.write_full("p", "x", payload(seed=6)) == 0
    assert cl.write_full("p", "y", payload(seed=7)) == 0
    c.revive_osd(primary)
    c.run_recovery()
    c.network.pump()
    c.run_recovery()
    # whoever is primary now, reads must see the newest data
    assert cl.read("p", "x") == payload(seed=6)
    assert cl.read("p", "y") == payload(seed=7)


def test_primary_beyond_log_tail_self_backfills():
    """A returning primary whose head predates the authority's log tail
    cannot replay entries — it must adopt the authoritative head and
    backfill itself from a listing diff instead of looping in GetLog."""
    c = MiniCluster(n_osds=5)
    c.create_ec_pool("p", k=2, m=2, pg_num=1, plugin="tpu")
    for o in c.osds.values():
        for pg in o.pgs.values():
            pg.pg_log.max_entries = 8
    cl = c.client("client.sb")
    assert cl.write_full("p", "keep", payload(seed=1)) == 0
    pool_id = cl.lookup_pool("p")
    _, primary = cl._calc_target(pool_id, "keep")
    c.kill_osd(primary)
    c.mark_osd_down(primary)
    for i in range(12):  # trim the log well past the dead primary's head
        assert cl.write_full("p", f"n{i}", payload(300, seed=i)) == 0
        for o in c.osds.values():
            for pg in o.pgs.values():
                pg.pg_log.max_entries = 8
    assert cl.remove("p", "keep") == 0  # delete must propagate via diff
    c.network.pump()
    c.revive_osd(primary)
    c.run_recovery()
    c.network.pump()
    c.run_recovery()
    for i in range(12):
        assert cl.read("p", f"n{i}") == payload(300, seed=i)
    with pytest.raises(IOError):
        cl.read("p", "keep")
    # the returned osd's stale copy of the deleted object is gone
    leftovers = [1 for cid in c.osds[primary].store.list_collections()
                 for ho in c.osds[primary].store.list_objects(cid)
                 if ho.oid == "keep"]
    assert not leftovers


def test_activation_missing_survives_promotion():
    """A replica whose log head advanced via activation but whose data
    never arrived must carry that debt (local_missing) into the next
    peering round — even if it becomes the primary."""
    c = MiniCluster(n_osds=5)
    c.create_ec_pool("p", k=2, m=2, pg_num=1, plugin="tpu")
    cl = c.client("client.pm")
    assert cl.write_full("p", "a", payload(seed=1)) == 0
    pool_id = cl.lookup_pool("p")
    pgid, primary = cl._calc_target(pool_id, "a")
    acting = c.osds[primary].pgs[pgid].acting
    behind = next(o for o in acting if o != primary)
    c.kill_osd(behind)
    c.mark_osd_down(behind)
    assert cl.write_full("p", "a", payload(seed=2)) == 0
    assert cl.write_full("p", "b", payload(seed=3)) == 0
    # bring it back WITHOUT driving recovery: activation merges the log
    c.network.set_down(f"osd.{behind}", False)
    c.mon.mark_osd_up(behind)
    c.mon.send_full_map(f"osd.{behind}")
    c.network.pump()
    pg_b = c.osds[behind].pgs[pgid]
    assert "a" in pg_b.local_missing or "b" in pg_b.local_missing
    # force a new interval immediately (old primary dies before pushes)
    c.kill_osd(primary)
    c.mark_osd_down(primary)
    c.run_recovery()
    c.network.pump()
    c.run_recovery()
    assert cl.read("p", "a") == payload(seed=2)
    assert cl.read("p", "b") == payload(seed=3)


def test_stale_failure_reports_expire_on_recovery():
    """One old report plus one new report from different eras must not
    reach the down-mark quorum (reports void on mark_osd_up)."""
    from ceph_tpu.msg import MOSDFailure
    c = MiniCluster(n_osds=5)
    mon = c.mon
    # one report arrives; target then proves healthy (marked up)
    mon.ms_fast_dispatch(MOSDFailure(src="osd.2", target_osd=1, epoch=1))
    assert mon.osdmap.is_up(1)
    mon.mark_osd_down(1)
    mon.mark_osd_up(1)   # recovery clears the partial report set
    mon.ms_fast_dispatch(MOSDFailure(src="osd.3", target_osd=1, epoch=2))
    assert mon.osdmap.is_up(1), "stale+fresh reports must not sum"
    mon.ms_fast_dispatch(MOSDFailure(src="osd.4", target_osd=1, epoch=2))
    assert not mon.osdmap.is_up(1)  # two contemporaneous reporters do


def test_replicated_stale_primary_pulls_not_pushes():
    """A returning replicated primary holding a STALE copy must pull the
    authoritative bytes, never push its old data over newer writes."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("r", size=3, pg_num=1)
    cl = c.client("client.sp")
    assert cl.write_full("r", "x", payload(seed=1)) == 0
    pool_id = cl.lookup_pool("r")
    pgid, primary = cl._calc_target(pool_id, "x")
    c.kill_osd(primary)
    c.mark_osd_down(primary)
    assert cl.write_full("r", "x", payload(seed=2)) == 0  # newer write
    c.revive_osd(primary)
    c.run_recovery()
    c.network.pump()
    c.run_recovery()
    # the newer bytes won everywhere, including on the returned primary
    assert cl.read("r", "x") == payload(seed=2)
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "x":
                    assert bytes(osd.store.read(cid, ho)) == \
                        payload(seed=2), f"osd.{osd.osd_id} stale"


def test_rewind_to_drops_suffix_and_persists():
    store = MemStore()
    t = Transaction()
    t.create_collection("meta")
    store.queue_transaction(t)
    log = PGLog(max_entries=50)
    for v in range(1, 9):
        t = Transaction()
        log.append(LogEntry(v, f"o{v}", OP_MODIFY), t, "meta")
        store.queue_transaction(t)
    t = Transaction()
    dropped = log.rewind_to(5, t, "meta")
    store.queue_transaction(t)
    assert [e.version for e in dropped] == [6, 7, 8]
    assert log.head == 5
    assert [e.version for e in log.entries] == [1, 2, 3, 4, 5]
    # persisted: a reload sees the rewound state, appends resume at 6
    log2 = PGLog(max_entries=50)
    log2.load(store, "meta")
    assert log2.head == 5
    assert [e.version for e in log2.entries] == [1, 2, 3, 4, 5]
    t = Transaction()
    log2.append(LogEntry(6, "new", OP_MODIFY), t, "meta")
    store.queue_transaction(t)
    assert log2.head == 6


def test_trim_clears_dead_rollback_stashes():
    """A stash is consumable only while its oid still has an in-log
    entry; trimming the oid's last entry must drop the stash, while an
    oid that keeps a live entry keeps its stash."""
    from ceph_tpu.osd.pg_log import (
        ROLLBACK_KEY_PREFIX, encode_rollback, load_rollback,
        stage_rollback,
    )
    store = MemStore()
    t = Transaction()
    t.create_collection("meta")
    store.queue_transaction(t)
    log = PGLog(max_entries=3)
    # o1 written at v1 only (will trim); o2 at v2 AND v5 (stays live)
    seq = [(1, "o1"), (2, "o2"), (3, "o3"), (4, "o4"), (5, "o2")]
    for v, oid in seq:
        t = Transaction()
        stage_rollback(t, "meta", oid,
                       encode_rollback(v, True, b"prev", {}))
        log.append(LogEntry(v, oid, OP_MODIFY), t, "meta")
        store.queue_transaction(t)
    # max_entries=3: entries 1-2 trimmed; o1 has no live entry -> stash
    # gone; o2's latest entry (v5) is live -> stash kept
    assert load_rollback(store, "meta", "o1") is None
    kept = load_rollback(store, "meta", "o2")
    assert kept is not None and kept[0] == 5
