"""Shallow vs deep scrub (the reference's scrub / deep-scrub split).

Shallow scrubs (src/osd/PG.cc chunky_scrub with deep=false) compare
metadata across copies — sizes, attr and omap digests — without reading
object data; deep scrubs additionally checksum every byte.  The OSD
scheduler runs cheap shallow scrubs often and upgrades to deep when
osd_deep_scrub_interval lapses (OSD.cc sched_scrub).
"""
import numpy as np

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common.config import g_conf


def payload(n=20000, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def _find_copy(c, oid, skip_primary_of=None):
    """(osd, cid, ho) of one stored copy, preferring a non-primary."""
    hits = []
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == oid:
                    hits.append((osd, cid, ho))
    if skip_primary_of is not None:
        nonprim = [h for h in hits if h[0].osd_id != skip_primary_of]
        if nonprim:
            return nonprim[0]
    return hits[0]


def _data_reads(c):
    return sum(o.perf["op_r"] for o in c.osds.values())


def test_shallow_scrub_reads_no_data_and_misses_bitrot():
    """Proof the shallow pass really is metadata-only: flipped bytes
    (same size, same attrs) sail through a shallow scrub and are caught
    by the next deep one."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    data = payload(seed=1)
    assert cl.write_full("p", "obj", data) == 0
    cl2 = c.client("client.probe")
    pid = cl2.lookup_pool("p")
    _pg, primary = cl2._calc_target(pid, "obj")
    osd, cid, ho, = _find_copy(c, "obj", skip_primary_of=primary)
    before = bytes(osd.store.colls[cid][ho].data)
    osd.store.colls[cid][ho].data[5] ^= 0xA5
    corrupted = bytes(osd.store.colls[cid][ho].data)

    reads = []
    orig_read = type(osd.store).read

    def counting_read(self, *a, **kw):
        reads.append(1)
        return orig_read(self, *a, **kw)

    type(osd.store).read = counting_read
    try:
        c.scrub(deep=False)
        shallow_reads = len(reads)
        # same size + attrs: the shallow pass cannot (and must not
        # claim to) see the rot
        assert bytes(osd.store.colls[cid][ho].data) == corrupted
        c.scrub(deep=True)
    finally:
        type(osd.store).read = orig_read
    assert shallow_reads == 0, "shallow scrub read object data"
    assert bytes(osd.store.colls[cid][ho].data) == before
    assert cl.read("p", "obj") == data


def test_shallow_scrub_catches_size_mismatch():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    data = payload(seed=2)
    assert cl.write_full("p", "obj", data) == 0
    cl2 = c.client("client.probe")
    _pg, primary = cl2._calc_target(cl2.lookup_pool("p"), "obj")
    osd, cid, ho = _find_copy(c, "obj", skip_primary_of=primary)
    del osd.store.colls[cid][ho].data[-100:]        # silent truncation
    c.scrub(deep=False)
    assert bytes(osd.store.colls[cid][ho].data) == data
    assert cl.read("p", "obj") == data


def test_shallow_scrub_catches_attr_divergence():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    assert cl.write_full("p", "obj", b"stable bytes") == 0
    assert cl.setxattr("p", "obj", "color", b"blue") == 0
    cl2 = c.client("client.probe")
    _pg, primary = cl2._calc_target(cl2.lookup_pool("p"), "obj")
    osd, cid, ho = _find_copy(c, "obj", skip_primary_of=primary)
    from ceph_tpu.osd.ec_backend import USER_ATTR_PREFIX
    osd.store.colls[cid][ho].attrs[USER_ATTR_PREFIX + "color"] = b"red"
    c.scrub(deep=False)
    assert osd.store.colls[cid][ho].attrs[
        USER_ATTR_PREFIX + "color"] == b"blue"
    assert cl.getxattr("p", "obj", "color") == b"blue"


def test_shallow_scrub_catches_ec_size_vs_hinfo():
    """EC shallow pass: a shard whose stored length disagrees with its
    HashInfo total is repaired without any data read on clean shards."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=2, plugin="isa")
    cl = c.client("client.s")
    data = payload(seed=5)
    assert cl.write_full("p", "obj", data) == 0
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj" and ho.shard >= 0:
                    del osd.store.colls[cid][ho].data[-16:]
                    c.scrub(deep=False)
                    assert cl.read("p", "obj") == data
                    return
    raise AssertionError("no EC shard found")


def test_corrupt_primary_loses_majority_vote():
    """A corrupt PRIMARY copy must not become the scrub authority and
    'repair' healthy replicas from bad data: the authoritative value is
    the majority among self-consistent copies (be_select_auth_object),
    so the primary repairs itself from the survivors."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    data = payload(seed=9)
    assert cl.write_full("p", "obj", data) == 0
    cl2 = c.client("client.probe")
    _pg, primary = cl2._calc_target(cl2.lookup_pool("p"), "obj")
    posd = c.osds[primary]
    hit = None
    for cid in posd.store.list_collections():
        if "_meta" in cid:
            continue
        for ho in posd.store.list_objects(cid):
            if ho.oid == "obj" and hit is None:
                posd.store.colls[cid][ho].data[7] ^= 0x3C
                hit = (cid, ho)
    assert hit is not None
    c.scrub(deep=True)
    c.tick()
    cid, ho = hit
    assert bytes(posd.store.colls[cid][ho].data) == data, \
        "primary must be repaired from the majority, not vice versa"
    assert cl.read("p", "obj") == data
    # and the healthy replicas were left alone / stayed correct
    for osd in c.osds.values():
        for c2 in osd.store.list_collections():
            if "_meta" in c2:
                continue
            for h2 in osd.store.list_objects(c2):
                if h2.oid == "obj":
                    assert bytes(osd.store.colls[c2][h2].data) == data


def test_identical_rot_on_majority_of_copies_still_repaired():
    """Even when the SAME corruption hits a majority of replicas,
    the write-time recorded digest (object_info data_digest role)
    identifies each rotted copy as self-inconsistent — voting alone
    would elect the corruption."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    data = payload(seed=11)
    assert cl.write_full("p", "obj", data) == 0
    cl2 = c.client("client.probe")
    _pg, primary = cl2._calc_target(cl2.lookup_pool("p"), "obj")
    # identical byte-flip on every NON-primary copy (2 of 3)
    n = 0
    for osd in c.osds.values():
        if osd.osd_id == primary:
            continue
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj":
                    osd.store.colls[cid][ho].data[3] ^= 0xFF
                    n += 1
    assert n == 2
    c.scrub(deep=True)
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj":
                    assert bytes(osd.store.colls[cid][ho].data) == data
    assert cl.read("p", "obj") == data


def test_identical_attr_rot_on_majority_cannot_outvote_primary():
    """Data digests validate bytes, not metadata — identical attr rot
    on two (data-validated) replicas must not outvote the healthy
    primary's metadata."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    assert cl.write_full("p", "obj", b"solid" * 200) == 0
    assert cl.setxattr("p", "obj", "owner", b"alice") == 0
    cl2 = c.client("client.probe")
    _pg, primary = cl2._calc_target(cl2.lookup_pool("p"), "obj")
    from ceph_tpu.osd.ec_backend import USER_ATTR_PREFIX
    n = 0
    for osd in c.osds.values():
        if osd.osd_id == primary:
            continue
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj":
                    osd.store.colls[cid][ho].attrs[
                        USER_ATTR_PREFIX + "owner"] = b"mallory"
                    n += 1
    assert n == 2
    c.scrub(deep=True)
    assert cl.getxattr("p", "obj", "owner") == b"alice"
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj":
                    assert osd.store.colls[cid][ho].attrs[
                        USER_ATTR_PREFIX + "owner"] == b"alice"


def test_digestless_object_keeps_primary_authority():
    """After a partial overwrite wipes the recorded digests, identical
    rot on a majority of replicas must NOT outvote the healthy primary
    (the pre-digest semantics are the fallback, not plain majority)."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    data = payload(seed=13)
    assert cl.write_full("p", "obj", data) == 0
    assert cl.write("p", "obj", b"QQ", offset=100) == 0   # digest wiped
    expect = bytearray(data)
    expect[100:102] = b"QQ"
    expect = bytes(expect)
    cl2 = c.client("client.probe")
    _pg, primary = cl2._calc_target(cl2.lookup_pool("p"), "obj")
    n = 0
    for osd in c.osds.values():
        if osd.osd_id == primary:
            continue
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj":
                    osd.store.colls[cid][ho].data[3] ^= 0xFF
                    n += 1
    assert n == 2
    c.scrub(deep=True)
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj":
                    assert bytes(osd.store.colls[cid][ho].data) == expect
    assert cl.read("p", "obj") == expect


def test_repaired_copy_does_not_rescrub_forever():
    """A recovery push mints a recorded digest the other copies lack;
    that must not read as an attr inconsistency, or every scrub would
    re-'repair' a correct copy forever."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    data = payload(seed=17)
    assert cl.write_full("p", "obj", data) == 0
    assert cl.write("p", "obj", b"ZZ", offset=50) == 0    # digests wiped
    expect = bytearray(data)
    expect[50:52] = b"ZZ"
    expect = bytes(expect)
    cl2 = c.client("client.probe")
    _pg, primary = cl2._calc_target(cl2.lookup_pool("p"), "obj")
    hit = 0
    for osd in c.osds.values():
        if osd.osd_id == primary:
            continue
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj" and hit == 0:
                    osd.store.colls[cid][ho].data[3] ^= 0x55
                    hit += 1
    assert hit == 1
    c.scrub(deep=True)          # finds + repairs (push mints a digest)
    c.tick()
    errs_after_repair = len(c.mon.log_last(100, level="ERR"))
    for _ in range(3):          # further scrubs must stay quiet
        c.scrub(deep=True)
        c.tick()
    assert len(c.mon.log_last(100, level="ERR")) == errs_after_repair, \
        c.mon.log_last(5, level="ERR")
    assert cl.read("p", "obj") == expect


def test_scheduler_upgrades_to_deep_on_interval():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    assert cl.write_full("p", "obj", b"x" * 1000) == 0
    shallow_iv = 100.0
    deep_iv = 1000.0
    g_conf.set_val("osd_scrub_min_interval", shallow_iv)
    g_conf.set_val("osd_deep_scrub_interval", deep_iv)
    try:
        prim = [pg for o in c.osds.values() for pg in o.pgs.values()
                if pg.is_primary() and pg.pg_log.head > 0]
        assert prim
        # past the shallow interval: scrub happens, deep does not
        c.tick(dt=shallow_iv * 1.2, rounds=1)
        assert all(p.last_scrub_stamp > 0 for p in prim)
        assert all(p.last_deep_scrub_stamp == 0 for p in prim)
        # past the deep interval: the due scrub upgrades to deep
        c.tick(dt=deep_iv, rounds=1)
        assert all(p.last_deep_scrub_stamp > 0 for p in prim)
    finally:
        g_conf.set_val("osd_scrub_min_interval", 86400.0)
        g_conf.set_val("osd_deep_scrub_interval", 604800.0)
