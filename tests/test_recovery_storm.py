"""Recovery orchestration (ceph_tpu/recovery) + the storm scenario.

- a killed OSD's shards on a regenerating pool rebuild via sub-chunk
  repair rounds (d helper contributions, not k whole chunks), tallied
  per codec family, byte-exact after backfill;
- the chaos sites degrade, never wedge: dropped helper fetches and the
  armed repair_read site both fall back to full-stripe decode;
- pacing parks excess rounds and drains them;
- repair rounds travel the recovery dmClock class (QoS accounting);
- the load harness schedules OSD kill/out/revive as first-class
  mid-run events;
- `recovery dump` serves the per-family bytes-per-repaired-shard the
  bench gate reads.
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common.config import g_conf
from ceph_tpu.fault import g_faults
from ceph_tpu.recovery import (
    l_recovery_deferrals, l_recovery_fallbacks, l_recovery_helper_bytes,
    l_recovery_fullstripe_rounds, l_recovery_repair_rounds,
    l_recovery_repaired_shards, recovery_perf_counters)


@pytest.fixture()
def clean_state():
    g_faults.clear()
    saved = {k: g_conf.values.get(k)
             for k in ("osd_recovery_repair_reads",
                       "osd_recovery_max_active")}
    yield
    g_faults.clear()
    for k, v in saved.items():
        if v is None:
            g_conf.rm_val(k)
        else:
            g_conf.set_val(k, v)


def _boot(n_osds=6, d=4, pg_num=2):
    c = MiniCluster(n_osds=n_osds)
    c.create_ec_pool("regen", k=3, m=2, pg_num=pg_num,
                     plugin="regenerating",
                     extra_profile={"d": str(d)})
    cl = c.client("client.rec")
    rng = np.random.default_rng(41)
    bodies = {}
    for i in range(4):
        oid = f"o{i}"
        body = rng.integers(0, 256, 2500 + i * 333,
                            dtype=np.uint8).tobytes()
        assert cl.write_full("regen", oid, body) == 0
        bodies[oid] = body
    return c, cl, bodies


def _storm(c, victim=None):
    """Kill + out one acting member of the EC pool, tick to recovery."""
    if victim is None:
        for _pgid, pg in c.primary_pgs():
            if pg.backend is not None:
                victim = pg.acting[-1]
                break
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    c.mark_osd_out(victim)
    for _ in range(10):
        c.tick(dt=1.0)
        if set(c.pg_states().values()) <= {"active"}:
            break
    return victim


def test_repair_rounds_rebuild_killed_osd(clean_state):
    c, cl, bodies = _boot()
    pc = recovery_perf_counters()
    r0 = pc.get(l_recovery_repair_rounds)
    s0 = pc.get(l_recovery_repaired_shards)
    b0 = pc.get(l_recovery_helper_bytes)
    _storm(c)
    rounds = pc.get(l_recovery_repair_rounds) - r0
    shards = pc.get(l_recovery_repaired_shards) - s0
    moved = pc.get(l_recovery_helper_bytes) - b0
    assert rounds > 0 and shards >= rounds
    # the repair-bandwidth claim, in moved bytes: each repaired shard
    # cost d sub-chunks, strictly under the k-chunk full-stripe read
    dump = c.admin_socket.execute("recovery dump")
    fam = dump["families"]["pm-regen"]
    assert fam["repair_rounds"] > 0
    chunk = fam["bytes_moved"] / fam["repaired_shards"]
    # k=3, d=4: helper bytes per shard = d·L = chunk; full-stripe
    # would read k·chunk
    assert chunk < 3 * 2048 and moved == fam["helper_bytes"]
    for oid, body in bodies.items():
        assert cl.read("regen", oid) == body, oid


def test_helper_fetch_drop_falls_back_not_wedges(clean_state):
    """Armed recovery.helper_fetch drops helper reads mid-repair: the
    orchestrator falls back to full-stripe decode; every object still
    recovers byte-exact."""
    c, cl, bodies = _boot()
    pc = recovery_perf_counters()
    f0 = pc.get(l_recovery_fallbacks)
    fs0 = pc.get(l_recovery_fullstripe_rounds)
    g_faults.inject("recovery.helper_fetch", mode="always")
    _storm(c)
    g_faults.clear("recovery.helper_fetch")
    for _ in range(4):
        c.tick(dt=1.0)
    assert pc.get(l_recovery_fallbacks) - f0 > 0
    assert pc.get(l_recovery_fullstripe_rounds) - fs0 > 0
    for oid, body in bodies.items():
        assert cl.read("regen", oid) == body, oid
    fam = c.admin_socket.execute(
        "recovery dump")["families"]["pm-regen"]
    assert fam["repair_fallbacks"] > 0


def test_repair_read_site_degrades_to_fullstripe(clean_state):
    """Armed recovery.repair_read skips the sub-chunk round at
    admission: full-stripe path used directly, objects byte-exact."""
    c, cl, bodies = _boot()
    pc = recovery_perf_counters()
    r0 = pc.get(l_recovery_repair_rounds)
    fs0 = pc.get(l_recovery_fullstripe_rounds)
    g_faults.inject("recovery.repair_read", mode="always")
    _storm(c)
    g_faults.clear("recovery.repair_read")
    assert pc.get(l_recovery_repair_rounds) == r0
    assert pc.get(l_recovery_fullstripe_rounds) - fs0 > 0
    for oid, body in bodies.items():
        assert cl.read("regen", oid) == body, oid


def test_repair_disabled_option_routes_fullstripe(clean_state):
    g_conf.set_val("osd_recovery_repair_reads", False)
    c, cl, bodies = _boot()
    pc = recovery_perf_counters()
    r0 = pc.get(l_recovery_repair_rounds)
    _storm(c)
    assert pc.get(l_recovery_repair_rounds) == r0
    for oid, body in bodies.items():
        assert cl.read("regen", oid) == body, oid


def test_pacing_parks_and_drains(clean_state):
    """osd_recovery_max_active=1 with several lost objects: deferrals
    fire, yet every round eventually drains and repairs."""
    g_conf.set_val("osd_recovery_max_active", 1)
    c, cl, bodies = _boot(pg_num=1)   # one PG -> one primary queues all
    pc = recovery_perf_counters()
    d0 = pc.get(l_recovery_deferrals)
    _storm(c)
    for _ in range(6):
        c.tick(dt=1.0)
    assert pc.get(l_recovery_deferrals) - d0 > 0
    for oid, body in bodies.items():
        assert cl.read("regen", oid) == body, oid
    dump = c.admin_socket.execute("recovery dump")
    per = dump["per_osd"]
    assert all(v["active_rounds"] == 0 and v["parked_rounds"] == 0
               for v in per.values())


def test_wedged_round_reaped_frees_slot(clean_state):
    """A round whose helper died mid-flight (reply never arrives) is
    reaped by the tick after ROUND_REAP_S and frees its pacing slot;
    a late reply then cannot double-release it (claim-once)."""
    from ceph_tpu.recovery.scheduler import RecoveryScheduler
    c = MiniCluster(n_osds=4)
    osd = c.osds[0]
    sched = osd.recovery_sched
    pc = recovery_perf_counters()
    from ceph_tpu.recovery import l_recovery_active
    token = sched._open_token()
    with sched._lock:
        sched._active += 1
    pc.inc(l_recovery_active)
    before = pc.get(l_recovery_active)
    osd.now += RecoveryScheduler.ROUND_REAP_S + 1.0
    sched.kick()
    assert pc.get(l_recovery_active) == before - 1
    assert sched._claim(token) is False          # already reaped
    assert sched.dump()["active_rounds"] == 0


def test_repair_rides_recovery_qos_class(clean_state):
    """Repair rounds enqueue under CLASS_RECOVERY: the qos logger's
    recovery-class dequeue counter moves during a storm."""
    from ceph_tpu.common.work_queue import (l_qos_dequeue_recovery,
                                            qos_perf_counters)
    c, cl, bodies = _boot()
    qos = qos_perf_counters()
    q0 = qos.get(l_qos_dequeue_recovery)
    pc = recovery_perf_counters()
    r0 = pc.get(l_recovery_repair_rounds)
    _storm(c)
    assert pc.get(l_recovery_repair_rounds) - r0 > 0
    assert qos.get(l_qos_dequeue_recovery) - q0 > 0


def test_rs_pool_fullstripe_accounting(clean_state):
    """The classic RS path tallies k-chunk source bytes per repaired
    shard — the storm baseline figure."""
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("rs", k=3, m=2, pg_num=2, plugin="tpu")
    cl = c.client("client.rs")
    rng = np.random.default_rng(43)
    bodies = {}
    for i in range(3):
        body = rng.integers(0, 256, 3000, dtype=np.uint8).tobytes()
        assert cl.write_full("rs", f"o{i}", body) == 0
        bodies[f"o{i}"] = body
    _storm(c)
    fam = c.admin_socket.execute(
        "recovery dump")["families"].get("isa-matrix")
    assert fam and fam["fullstripe_rounds"] > 0
    assert fam["repair_rounds"] == 0
    # full-stripe reads move >= k-1 surviving chunks per shard (the
    # exact k depends on which shard positions survived)
    assert fam["bytes_moved_per_repaired_shard"] > 0
    for oid, body in bodies.items():
        assert cl.read("rs", oid) == body, oid


def test_traffic_events_schedule_kill_and_revive(clean_state):
    """OSD add/remove as first-class load-harness events: traffic
    stays byte-exact across a scheduled mid-run kill + revive."""
    from ceph_tpu.load import TrafficSpec, run_traffic
    c = MiniCluster(n_osds=6)
    c.create_replicated_pool("load", size=3, pg_num=8)
    victim = 5
    spec = TrafficSpec(pool="load", n_clients=4, ops_per_client=16,
                       read_fraction=0.4, seed=77,
                       events=((2, "osd_kill", victim),
                               (6, "osd_revive", victim)))
    res = run_traffic(c, spec)
    assert res.byte_exact, res.errors[:4]
    assert res.completed == 4 * 16


def test_storm_workload_smoke(clean_state):
    """The bench workload end to end at tiny shape: regen repair
    bandwidth beats the RS full-stripe baseline under the 0.6 gate,
    objects byte-exact, SLO quiet."""
    from ceph_tpu.bench.workloads import measure_recovery_storm
    m = measure_recovery_storm(k=3, m=2, d=4, n_osds=7, pg_num=2,
                               n_objects=4, object_bytes=3000,
                               n_clients=3, ops_per_client=6)
    rec = m["recovery"]
    assert rec["families"]["pm-regen"]["repair_rounds"] > 0
    assert rec["families"]["isa-matrix"]["fullstripe_rounds"] > 0
    assert 0 < rec["regen_vs_rs_ratio"] <= 0.6
    assert m["identical"] is True
    assert m["byte_exact_traffic"] is True
    assert all(state != "raised" for state in m["slo"].values())
    assert m["fenced"] and m["unit"] == "B/shard"
