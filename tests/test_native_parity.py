"""Cross-validation: Python host implementations vs the independent C++ ones.

Two independently written implementations of the same published semantics
agreeing on random maps is the strongest mapping-exactness signal available
in this environment (the reference's native libs are empty submodules).
"""
import numpy as np
import pytest

from ceph_tpu import native
from ceph_tpu.crush import (
    CrushWrapper, CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW,
    CRUSH_BUCKET_STRAW2, CRUSH_BUCKET_TREE, CRUSH_BUCKET_UNIFORM,
    PG_POOL_TYPE_ERASURE,
)
from ceph_tpu.ec.rs_codec import MatrixRSCodec
from ceph_tpu.gf.matrices import gf_gen_rs_matrix
from ceph_tpu.gf.tables import gf_mul

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native toolchain unavailable")


def test_gf_mul_parity():
    rng = np.random.default_rng(0)
    for _ in range(500):
        a, b = (int(v) for v in rng.integers(0, 256, 2))
        assert native.get_lib().gf_mul_c(a, b) == gf_mul(a, b)


def test_rs_encode_parity():
    k, m = 8, 4
    matrix = gf_gen_rs_matrix(k + m, k)
    codec = MatrixRSCodec(matrix)
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(k, 4096), dtype=np.uint8)
    got = native.native_rs_encode(matrix[k:], data)
    np.testing.assert_array_equal(got, codec.encode(data))


def test_crc32c_reference_vectors():
    # golden vectors from the reference's test/common/test_crc32c.cc
    # (ceph convention: raw castagnoli update, no pre/post inversion)
    assert native.crc32c(b"foo bar baz", 0) == 4119623852
    assert native.crc32c(b"foo bar baz", 1234) == 881700046
    assert native.crc32c(b"whiz bang boom", 0) == 2360230088
    assert native.crc32c(b"whiz bang boom", 5678) == 3743019208
    assert native.crc32c(b"\x01" * 5, 0) == 2715569182
    assert native.crc32c(b"\x01" * 35, 0) == 440531800
    assert native.crc32c(b"\x01" * 4096000, 0) == 31583199
    assert native.crc32c(b"\x01" * 4096000, 1234) == 1400919119


def _random_map(rng, n_hosts, osds_per_host, algs):
    cw = CrushWrapper()
    n = n_hosts * osds_per_host
    cw.set_max_devices(n)
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    host_ids = []
    host_weights = []
    for h in range(n_hosts):
        osds = list(range(h * osds_per_host, (h + 1) * osds_per_host))
        weights = [int(rng.integers(1, 4)) * 0x10000 for _ in osds]
        alg = algs[int(rng.integers(len(algs)))]
        hid = cw.add_bucket(alg, 1, f"host{h}", osds, weights, id=-(h + 2))
        host_ids.append(hid)
        host_weights.append(sum(weights))
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", host_ids,
                  host_weights, id=-1)
    for i in range(n):
        cw.set_item_name(i, f"osd.{i}")
    return cw


@pytest.mark.parametrize("mode,rule_type", [("firstn", 1), ("indep", 3)])
@pytest.mark.parametrize("algs", [
    (CRUSH_BUCKET_STRAW2,),
    (CRUSH_BUCKET_UNIFORM, CRUSH_BUCKET_LIST, CRUSH_BUCKET_STRAW,
     CRUSH_BUCKET_STRAW2, CRUSH_BUCKET_TREE),
])
def test_mapper_parity_random_maps(mode, rule_type, algs):
    rng = np.random.default_rng(len(algs) * 10 + (1 if mode == "firstn" else 2))
    for trial in range(5):
        n_hosts = int(rng.integers(3, 8))
        oph = int(rng.integers(2, 5))
        cw = _random_map(rng, n_hosts, oph, algs)
        rno = cw.add_simple_rule("r", "default", "host", mode=mode,
                                 rule_type=rule_type)
        assert rno >= 0
        nm = native.NativeCrushMapper(cw.crush)
        n = n_hosts * oph
        weight = [0x10000] * n
        # randomly degrade some osds
        for i in rng.integers(0, n, size=max(1, n // 4)):
            weight[int(i)] = int(rng.integers(0, 2)) * 0x8000
        nrep = 3
        for x in range(500):
            py = cw.do_rule(rno, x, nrep, weight)
            cc = nm.do_rule(rno, x, nrep, weight)
            assert py == cc, (trial, x, py, cc)


def test_mapper_parity_batch():
    rng = np.random.default_rng(7)
    cw = _random_map(rng, 6, 4, (CRUSH_BUCKET_STRAW2,))
    rno = cw.add_simple_rule("r", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    nm = native.NativeCrushMapper(cw.crush)
    weight = [0x10000] * 24
    out, lens = nm.do_rule_batch(rno, list(range(1000)), 4, weight)
    for x in (0, 17, 500, 999):
        assert cw.do_rule(rno, x, 4, weight) == out[x, :lens[x]].tolist()
