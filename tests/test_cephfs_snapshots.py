"""cephfs filesystem snapshots: point-in-time read-only views.

The .snap surface at whole-fs scope: snap_create captures metadata AND
data (one selfmanaged snap id per pool, clone-on-write after), views
serve the tree exactly as it was — dentries, file bytes, symlinks,
hard links — while the head keeps evolving; removal retires both snap
ids for trimming.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.cephfs import CephFS, FsError

ORDER = 12


@pytest.fixture()
def fs():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("fsmeta", size=3, pg_num=8)
    c.create_replicated_pool("fsdata", size=3, pg_num=8)
    f = CephFS(c.client("client.fs"), "fsmeta", "fsdata")
    f.mkfs()
    return c, f


def test_snapshot_view_is_point_in_time(fs):
    c, f = fs
    f.mkdir("/proj")
    f.create("/proj/code", ORDER)
    f.write("/proj/code", b"v1-source")
    f.symlink("/latest", "/proj/code")
    f.snap_create("rel1")
    # mutate everything after the snapshot
    f.write("/proj/code", b"v2-rewritten")
    f.create("/proj/new", ORDER)
    f.mkdir("/docs")
    f.unlink("/latest")
    v = f.snapshot("rel1")
    assert sorted(v.listdir("/")) == ["latest", "proj"]
    assert sorted(v.listdir("/proj")) == ["code"]
    assert v.read("/proj/code") == b"v1-source"
    assert v.read("/latest") == b"v1-source"      # symlink at snap
    assert v.stat("/proj/code")["size"] == 9
    # head unaffected
    assert f.read("/proj/code") == b"v2-rewritten"
    assert sorted(f.listdir("/")) == ["docs", "proj"]
    # views are read-only
    with pytest.raises(FsError) as ei:
        v.write("/proj/code", b"nope")
    assert ei.value.result == -30
    with pytest.raises(FsError):
        v.mkdir("/x")
    with pytest.raises(FsError):
        v.unlink("/proj/code")


def test_layered_snapshots_and_removal(fs):
    c, f = fs
    f.create("/f", ORDER)
    f.write("/f", b"gen1")
    f.snap_create("s1")
    f.write("/f", b"gen2!")
    f.snap_create("s2")
    f.write("/f", b"gen3!!")
    assert f.snapshot("s1").read("/f") == b"gen1"
    assert f.snapshot("s2").read("/f") == b"gen2!"
    assert f.read("/f") == b"gen3!!"
    assert sorted(f.snap_list()) == ["s1", "s2"]
    with pytest.raises(FsError):
        f.snap_create("s1")                        # EEXIST
    f.snap_remove("s1")
    assert sorted(f.snap_list()) == ["s2"]
    with pytest.raises(FsError):
        f.snapshot("s1")
    c.tick(40)                                     # trim s1's clones
    assert f.snapshot("s2").read("/f") == b"gen2!"
    assert f.read("/f") == b"gen3!!"


def test_snapshot_sees_deleted_files(fs):
    """Files deleted after the snapshot remain readable in the view —
    the defining recovery use-case."""
    c, f = fs
    f.mkdir("/data")
    f.create("/data/precious", ORDER)
    f.write("/data/precious", b"do-not-lose" * 100)
    f.snap_create("backup")
    f.unlink("/data/precious")
    f.rmdir("/data")
    assert not f.exists("/data")
    v = f.snapshot("backup")
    assert v.read("/data/precious") == b"do-not-lose" * 100
    # restore from the snapshot view onto the head
    f.mkdir("/data")
    f.create("/data/precious", ORDER)
    f.write("/data/precious", v.read("/data/precious"))
    assert f.read("/data/precious") == b"do-not-lose" * 100


def test_hardlinks_in_snapshot(fs):
    c, f = fs
    f.create("/a", ORDER)
    f.write("/a", b"linked-at-snap")
    f.hardlink("/a", "/b")
    f.snap_create("s")
    f.unlink("/a")                                 # promotes /b on head
    v = f.snapshot("s")
    assert v.read("/a") == b"linked-at-snap"
    assert v.read("/b") == b"linked-at-snap"
    assert v.stat("/a")["nlink"] == 2
    assert f.stat("/b")["nlink"] == 1              # head promoted


def test_snapshot_survives_failure_and_checkpoint(fs, tmp_path):
    c, f = fs
    f.create("/x", ORDER)
    f.write("/x", b"pre-snap")
    f.snap_create("s")
    f.write("/x", b"post-snap")
    c.kill_osd(0)
    for _ in range(6):
        c.tick(dt=6.0)
    assert f.snapshot("s").read("/x") == b"pre-snap"
    c.checkpoint(str(tmp_path / "ck"))
    c2 = MiniCluster.restore(str(tmp_path / "ck"))
    f2 = CephFS(c2.client("client.r"), "fsmeta", "fsdata")
    assert f2.snapshot("s").read("/x") == b"pre-snap"
    assert f2.read("/x") == b"post-snap"
    # the restored client's write ctx still protects the snapshot
    f2.write("/x", b"post-restore")
    assert f2.snapshot("s").read("/x") == b"pre-snap"
