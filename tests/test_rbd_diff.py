"""rbd export-diff / import-diff / cp: the incremental-backup flow.

Reference workflow (rbd export-diff --from-snap A @B | rbd import-diff
on the backup cluster): a full export at the first snapshot, then
incremental diffs replayed in order, reproduce the source bit-for-bit
— including shrinks and punched holes.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rbd import Image, RBD

ORDER = 12
OBJ = 1 << ORDER


@pytest.fixture()
def env():
    a = MiniCluster(n_osds=4)
    a.create_replicated_pool("rbd", size=3, pg_num=8)
    b = MiniCluster(n_osds=3)
    b.create_replicated_pool("rbd", size=2, pg_num=8)
    return a.client("client.a"), b.client("client.b")


def test_incremental_backup_roundtrip(env):
    ca, cb = env
    RBD(ca).create("rbd", "img", 6 * OBJ, ORDER)
    src = Image(ca, "rbd", "img")
    src.write(0, b"base" * 500)
    src.write(3 * OBJ, b"far")
    src.snap_create("s1")
    # full export at s1 -> seed the backup image
    full = src.export_diff(to_snap="s1")
    RBD(cb).create("rbd", "img", 6 * OBJ, ORDER)
    dst = Image(cb, "rbd", "img")
    dst.import_diff(full)
    assert dst.read(0, 2000) == src.read(0, 2000)
    assert dst.read(3 * OBJ, 3) == b"far"
    # mutate: overwrite, punch a hole, shrink, then snap again
    src.write(OBJ, b"second-gen" * 100)
    src.discard(3 * OBJ, OBJ)
    src.resize(5 * OBJ)
    src.snap_create("s2")
    inc = src.export_diff(from_snap="s1", to_snap="s2")
    dst.import_diff(inc)
    assert dst.size() == 5 * OBJ
    s2 = Image(ca, "rbd", "img", snapshot="s2")
    for off, ln in [(0, 2000), (OBJ, 1000), (3 * OBJ, OBJ),
                    (4 * OBJ, OBJ)]:
        assert dst.read(off, ln) == s2.read(off, ln)
    # the incremental is much smaller than a full export
    assert len(inc) < len(src.export_diff(to_snap="s2"))


def test_diff_head_and_identity(env):
    ca, _ = env
    RBD(ca).create("rbd", "i", 4 * OBJ, ORDER)
    img = Image(ca, "rbd", "i")
    img.write(100, b"payload")
    img.snap_create("s")
    # no changes since the snap: diff carries only the size record
    import json
    assert json.loads(img.export_diff(from_snap="s")) == [["s", 4 * OBJ]]
    img.write(200, b"x")
    recs = json.loads(img.export_diff(from_snap="s"))
    assert any(r[0] == "w" for r in recs)


def test_cp(env):
    ca, _ = env
    RBD(ca).create("rbd", "src", 4 * OBJ, ORDER)
    img = Image(ca, "rbd", "src")
    img.write(0, b"copy-me" * 100)
    img.snap_create("point")
    img.write(0, b"after!!" * 100)
    rbd = RBD(ca)
    rbd.copy("rbd", "src", "rbd", "dup")
    rbd.copy("rbd", "src", "rbd", "dup-at-snap", src_snap="point")
    assert Image(ca, "rbd", "dup").read(0, 7) == b"after!!"
    assert Image(ca, "rbd", "dup-at-snap").read(0, 7) == b"copy-me"
    # copies are independent of the source
    img.write(0, b"mutated")
    assert Image(ca, "rbd", "dup").read(0, 7) == b"after!!"


def test_cli_roundtrip(env, tmp_path):
    ca, cb = env
    from ceph_tpu.tools import rbd_cli
    run_a = lambda *x: rbd_cli.run(None, ca, ["-p", "rbd", *x])
    run_b = lambda *x: rbd_cli.run(None, cb, ["-p", "rbd", *x])
    run_a("create", "d", "--size", str(2 * OBJ), "--order", str(ORDER))
    Image(ca, "rbd", "d").write(0, b"cli-diff")
    run_a("snap", "create", "d@s1")
    p = str(tmp_path / "d.diff")
    run_a("export-diff", "d", p, "--snap", "s1")
    run_b("create", "d", "--size", str(2 * OBJ), "--order", str(ORDER))
    run_b("import-diff", p, "d")
    assert Image(cb, "rbd", "d").read(0, 8) == b"cli-diff"
    run_a("cp", "d", "d2")
    assert Image(ca, "rbd", "d2").read(0, 8) == b"cli-diff"
