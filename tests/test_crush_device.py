"""Device CRUSH mapper parity: vmapped kernel vs the host interpreter.

Every mapping the jitted straw2 kernel produces must equal crush_do_rule's
output exactly — same winners, same retry outcomes, same NONE holes — across
rule styles (firstn/indep, chooseleaf and direct), tunable profiles,
weight-based rejection, choose_args, and uneven hierarchies.
"""
import numpy as np
import pytest

from ceph_tpu.crush import CrushWrapper, CRUSH_BUCKET_STRAW2, CRUSH_ITEM_NONE
from ceph_tpu.crush.types import Rule, RuleStep
from ceph_tpu.crush.constants import (
    CRUSH_RULE_CHOOSELEAF_FIRSTN, CRUSH_RULE_CHOOSELEAF_INDEP,
    CRUSH_RULE_CHOOSE_FIRSTN, CRUSH_RULE_CHOOSE_INDEP, CRUSH_RULE_EMIT,
    CRUSH_RULE_TAKE, PG_POOL_TYPE_ERASURE,
)

from ceph_tpu.ops.crush_kernels import DeviceCrushMapper, compile_map

N_X = 400


def build_map(n_hosts=5, osds_per_host=4, uneven=False, seed=7):
    rng = np.random.default_rng(seed)
    cw = CrushWrapper()
    cw.set_type_name(1, "host")
    cw.set_type_name(10, "root")
    host_ids, host_ws = [], []
    osd = 0
    for h in range(n_hosts):
        k = osds_per_host + (int(rng.integers(-2, 3)) if uneven else 0)
        k = max(1, k)
        osds = list(range(osd, osd + k))
        osd += k
        if uneven:
            ws = [int(rng.integers(1, 4)) * 0x10000 for _ in osds]
        else:
            ws = [0x10000] * k
        hid = cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, f"host{h}", osds, ws,
                            id=-(h + 2))
        host_ids.append(hid)
        host_ws.append(sum(ws))
    cw.set_max_devices(osd)
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 10, "default", host_ids, host_ws,
                  id=-1)
    return cw, osd


def assert_parity(cw, ruleno, result_max, weight, n_x=N_X,
                  choose_args=None):
    comp = compile_map(cw.crush, choose_args)
    dev = DeviceCrushMapper(comp, ruleno, result_max)
    res, cnt = dev.map_batch(np.arange(n_x, dtype=np.uint32), weight)
    res, cnt = np.asarray(res), np.asarray(cnt)
    for x in range(n_x):
        expect = cw.do_rule(
            ruleno, x, result_max, weight,
            choose_args_index=0 if choose_args is not None else None)
        got = list(res[x, :cnt[x]])
        assert got == expect, (x, got, expect)


def test_chooseleaf_firstn_parity():
    cw, n = build_map()
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    assert_parity(cw, rno, 3, [0x10000] * n)


def test_chooseleaf_firstn_uneven_weights():
    cw, n = build_map(n_hosts=7, osds_per_host=3, uneven=True)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    assert_parity(cw, rno, 3, [0x10000] * n)


@pytest.mark.slow   # ~19 s XLA compile+replay heavyweight on 1 core
def test_firstn_with_out_devices():
    cw, n = build_map(n_hosts=6, osds_per_host=4)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    rng = np.random.default_rng(3)
    weight = [0x10000] * n
    # a mix of fully-out, reweighted, and in devices
    for i in rng.choice(n, size=n // 3, replace=False):
        weight[i] = int(rng.choice([0, 0x4000, 0x8000, 0xC000]))
    assert_parity(cw, rno, 3, weight)


def test_choose_firstn_direct_osds():
    cw, n = build_map(n_hosts=4, osds_per_host=5)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 0, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=1,
                           min_size=1, max_size=10), "flat")
    weight = [0x10000] * n
    weight[3] = 0
    weight[11] = 0x7000
    assert_parity(cw, rno, 3, weight)


@pytest.mark.slow   # ~25-40 s of XLA compile+replay on 1 core: the
# indep/exact64 heavyweights run in the slow tier so tier-1 fits its
# wall budget (they were enable_x64-broken in the seed; fixed in PR 1)
def test_chooseleaf_indep_parity():
    cw, n = build_map(n_hosts=8, osds_per_host=3, uneven=True)
    rno = cw.add_simple_rule("ecrule", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    cw.set_rule_mask_max_size(rno, 8)
    assert_parity(cw, rno, 6, [0x10000] * n)


@pytest.mark.slow   # ~25-40 s of XLA compile+replay on 1 core: the
# indep/exact64 heavyweights run in the slow tier so tier-1 fits its
# wall budget (they were enable_x64-broken in the seed; fixed in PR 1)
def test_chooseleaf_indep_with_out_devices_emits_holes():
    cw, n = build_map(n_hosts=5, osds_per_host=2)
    rno = cw.add_simple_rule("ecrule", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    weight = [0x10000] * n
    weight[0] = 0
    weight[5] = 0
    assert_parity(cw, rno, 4, weight)
    # indep pads failures with CRUSH_ITEM_NONE: force an impossible layout
    cw2, n2 = build_map(n_hosts=3, osds_per_host=1)
    r2 = cw2.add_simple_rule("ec2", "default", "host", mode="indep",
                             rule_type=PG_POOL_TYPE_ERASURE)
    assert_parity(cw2, r2, 5, [0x10000] * n2)


def test_choose_indep_direct_osds():
    cw, n = build_map(n_hosts=4, osds_per_host=4)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_INDEP, 0, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=3,
                           min_size=1, max_size=20), "flatec")
    weight = [0x10000] * n
    weight[7] = 0
    assert_parity(cw, rno, 4, weight)


def test_chained_choose_steps():
    # take root -> choose firstn 2 type host -> chooseleaf/choose 2 osds
    cw, n = build_map(n_hosts=6, osds_per_host=4, uneven=True)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 2, 1),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 2, 0),
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=1,
                           min_size=1, max_size=10), "two-level")
    assert_parity(cw, rno, 4, [0x10000] * n)


@pytest.mark.parametrize("profile", ["bobtail", "firefly", "hammer", "jewel"])
def test_tunable_profiles(profile):
    cw, n = build_map(n_hosts=5, osds_per_host=3, uneven=True)
    cw.set_tunables_profile(profile)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    weight = [0x10000] * n
    weight[2] = 0
    assert_parity(cw, rno, 3, weight, n_x=200)


@pytest.mark.slow   # ~13 s XLA compile+replay heavyweight on 1 core
def test_choose_args_weight_override():
    cw, n = build_map(n_hosts=4, osds_per_host=3)
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    args = cw.choose_args_create(0)
    # give host1's middle osd a different per-position weight
    from ceph_tpu.crush.types import WeightSet
    b = cw.get_bucket(-3)
    args[2].weight_set = [
        WeightSet(weights=[0x8000 if i == 1 else w
                           for i, w in enumerate(b.item_weights)]),
        WeightSet(weights=list(b.item_weights)),
    ]
    assert_parity(cw, rno, 3, [0x10000] * n,
                  choose_args=cw.choose_args_get(0))


def test_rejects_non_straw2_map():
    from ceph_tpu.crush import CRUSH_BUCKET_STRAW
    cw = CrushWrapper()
    cw.set_max_devices(4)
    cw.set_type_name(10, "root")
    cw.add_bucket(CRUSH_BUCKET_STRAW, 10, "default", [0, 1, 2, 3],
                  [0x10000] * 4, id=-1)
    with pytest.raises(ValueError):
        compile_map(cw.crush)


def test_rejects_legacy_tunables():
    cw, _ = build_map()
    cw.set_tunables_profile("argonaut")
    with pytest.raises(ValueError):
        compile_map(cw.crush)


def test_choose_take_buckets_own_type():
    """A choose step targeting the take bucket's own type must still draw
    from the bucket (do-while semantics, mapper.c:487-498), not return the
    take bucket itself."""
    cw, n = build_map(n_hosts=4, osds_per_host=3)
    steps = [RuleStep(CRUSH_RULE_TAKE, -1, 0),
             RuleStep(CRUSH_RULE_CHOOSE_FIRSTN, 2, 10),  # type 10 == root
             RuleStep(CRUSH_RULE_EMIT, 0, 0)]
    rno = cw.add_rule(Rule(steps=steps, ruleset=1, type=1,
                           min_size=1, max_size=10), "degenerate")
    assert_parity(cw, rno, 2, [0x10000] * n, n_x=64)
