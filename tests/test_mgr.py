"""mgr-lite: balancer module, status, prometheus exposition.

Models the reference manager (src/mgr/ + pybind/mgr/): a map-subscribed
daemon hosting the balancer (calc_pg_upmaps -> mon upmap proposal, like
pybind/mgr/balancer/module.py) and a prometheus exporter.
"""
import numpy as np

from ceph_tpu.cluster import MiniCluster


def test_mgr_tracks_maps_and_reports_status():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("p", k=3, m=2, pg_num=16, plugin="tpu")
    s = c.mgr.status()
    assert s["epoch"] == c.mon.osdmap.epoch
    assert s["num_pools"] == 1
    assert s["num_pgs"] == 16
    assert s["num_up_osds"] == 6
    # kill the daemon first: a LIVE osd administratively marked down
    # boots itself right back in (MOSDBoot), as the reference does
    c.kill_osd(3)
    c.mark_osd_down(3)
    s = c.mgr.status()
    assert s["num_up_osds"] == 5
    assert s["epoch"] == c.mon.osdmap.epoch


def test_balancer_module_flattens_distribution():
    """The mgr's optimize pass proposes pg_upmap_items to the mon and
    the published map's placement actually changes (balancer role)."""
    c = MiniCluster(n_osds=8)
    c.create_replicated_pool("r", size=3, pg_num=64)
    before = dict(c.mon.osdmap.pg_upmap_items)
    changes = c.mgr.balancer_optimize(max_deviation=0.01,
                                      max_iterations=10)
    if changes == 0:
        return  # already perfectly flat (tiny chance)
    after = c.mon.osdmap.pg_upmap_items
    assert len(after) > len(before)
    # the committed upmaps reach the osds and stay mapping-consistent
    from ceph_tpu.osdmap import pg_t
    osd = next(iter(c.osds.values()))
    assert osd.osdmap.epoch == c.mon.osdmap.epoch
    for pg in after:
        up_mon = c.mon.osdmap.pg_to_up_acting_osds(pg)
        up_osd = osd.osdmap.pg_to_up_acting_osds(pg)
        assert up_mon == up_osd
    # IO still works on the rebalanced map
    cl = c.client("client.b")
    data = np.random.default_rng(1).integers(
        0, 256, 8000, dtype=np.uint8).tobytes()
    assert cl.write_full("r", "o", data) == 0
    assert cl.read("r", "o") == data


def test_prometheus_exposition():
    c = MiniCluster(n_osds=4)
    c.create_ec_pool("p", k=2, m=1, pg_num=8, plugin="tpu")
    cl = c.client("client.p")
    cl.write_full("p", "o", b"x" * 1000)
    text = c.admin_socket.execute("prometheus metrics")
    assert "ceph_osdmap_epoch" in text
    assert "ceph_osd_up 4" in text
    assert "ceph_pgs 8" in text
    # per-daemon perf counters exported
    assert "ceph_daemon_osd" in text and "_op_w" in text
    # admin-socket module commands
    st = c.admin_socket.execute("mgr status")
    assert st["num_pools"] == 1
