"""Wall-clock-mode QoS under the traffic harness (ROADMAP traffic
follow-up 1, satellite of the devprof PR).

PR 6 landed two tiers: the per-client dmClock lane runs a deterministic
virtual clock, while ``WallMClockQueue`` (``osd_op_queue_mclock_wall``)
enforces REAL ops-per-second class tags.  What was never proven is the
combination under load: N open-loop clients hammering OSDs whose
client class carries a wall-clock limit.  The contract under test:

- the limit is a hard ceiling over the whole run: no shard serves more
  client ops than ``limit x elapsed`` (+1 initial credit) — dmclock's
  ``_l_next`` advance makes this structural, the test proves the
  wiring end to end (harness -> sharded queue -> wall arbiter -> tick
  -driven drain);
- rate-blocked ops are never stranded: every op still completes
  byte-exact (the drain is re-driven from the OSD tick, not from new
  client traffic).

The tier-1 leg is a scaled-down smoke (<10 s); the ``slow`` leg soaks
the same contract at 8x the op count.
"""
import time

import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.common.work_queue import CLASS_CLIENT
from ceph_tpu.load import TrafficSpec, run_traffic


@pytest.fixture
def wall_mode():
    g_conf.set_val("osd_op_queue_mclock_wall", True)
    yield
    g_conf.set_val("osd_op_queue_mclock_wall", False)


def _client_served_per_shard(cluster):
    """{(osd, shard): total client-class dequeues} from the op-queue
    dump (the same per-client accounting the admin socket serves)."""
    out = {}
    for i, osd in cluster.osds.items():
        for name, sh in osd.op_wq.dump().items():
            deq = sh.get("clients", {}).get(CLASS_CLIENT, {}) \
                .get("dequeues", {})
            out[(i, name)] = sum(deq.values())
    return out


def _run_wall_limited(limit, n_clients, ops_per_client, rate=6.0,
                      seed=20260803):
    """Open-loop traffic against a cluster whose client class is
    wall-limited to *limit* ops/s per shard; returns (result,
    elapsed_s, {shard: ops served during the run})."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("load", size=3, pg_num=8)
    assert all(o.op_wq.wall for o in c.osds.values())
    # wall tags: no reservation floor (a floor legitimately overrides
    # the ceiling in dmclock), generous weight, hard wall limit
    for osd in c.osds.values():
        for sh in osd.op_wq.shards:
            sh.tags[CLASS_CLIENT] = (0.0, 500.0, float(limit))
    before = _client_served_per_shard(c)
    t0 = time.monotonic()
    res = run_traffic(c, TrafficSpec(
        n_clients=n_clients, ops_per_client=ops_per_client,
        read_fraction=0.5, mode="open", rate=rate, seed=seed,
        tick_every=1, keep_completions=False))
    elapsed = time.monotonic() - t0
    after = _client_served_per_shard(c)
    served = {k: after.get(k, 0) - before.get(k, 0) for k in after}
    return res, elapsed, served


def _assert_wall_limits_hold(res, elapsed, served, limit):
    # sheds-never-wedges under rate limiting: every op completed
    # byte-exact — rate-blocked ops were re-driven from the tick
    assert res.byte_exact, res.errors[:5]
    busiest = max(served.values())
    assert busiest > 0, "no client op went through the wall arbiter"
    for shard, n in served.items():
        # hard ceiling over the run window: one initial credit (idle
        # clamp serves the first op at t0) + limit/s thereafter, with
        # a small tolerance for clock-read skew around the run edges
        budget = limit * elapsed * 1.05 + 2
        assert n <= budget, \
            f"{shard} served {n} ops in {elapsed:.2f}s " \
            f"(wall limit {limit}/s => budget {budget:.1f})"
    # the limit actually bound the run (the test is not vacuous):
    # serving the busiest shard's ops takes at least (n-1)/limit
    # seconds of wall time
    assert elapsed >= (busiest - 1) / limit - 0.05, \
        f"busiest shard {busiest} ops in {elapsed:.2f}s — the wall " \
        f"limiter cannot have been active"


def test_wall_rate_limit_holds_under_open_loop_smoke(wall_mode):
    """Tier-1 smoke: 6 open-loop clients against a 30 op/s/shard wall
    limit — ceiling holds on every shard, every op completes."""
    res, elapsed, served = _run_wall_limited(
        limit=30.0, n_clients=6, ops_per_client=8)
    _assert_wall_limits_hold(res, elapsed, served, limit=30.0)


@pytest.mark.slow
def test_wall_rate_limit_holds_under_open_loop_soak(wall_mode):
    """Slow-tier soak: 8 clients x 64 ops of open-loop traffic against
    a 100 op/s/shard wall limit, Zipf-skewed arrivals included."""
    res, elapsed, served = _run_wall_limited(
        limit=100.0, n_clients=8, ops_per_client=64, rate=10.0)
    _assert_wall_limits_hold(res, elapsed, served, limit=100.0)
    # per-client percentiles stay well-formed under rate limiting
    assert len(res.per_client) == 8
    assert all(st["p99"] > 0.0 for st in res.per_client.values())
