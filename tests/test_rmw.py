"""rmw partial-write pipeline: offset writes, appends, extent cache.

Models the reference ECBackend rmw path (src/osd/ECBackend.cc:1793
start_rmw -> try_state_to_reads -> try_reads_to_commit with
src/osd/ExtentCache.h:23 caching): partial overwrites and appends must
read-modify-write whole stripes, leave every shard byte-identical to a
fresh full-object encode of the final content, and pipeline overlapping
in-flight writes per object.
"""
import struct

import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.ec import create_erasure_code
from ceph_tpu.osd.ec_backend import SIZE_ATTR
from ceph_tpu.osd.ecutil import encode as ec_encode, stripe_info_t


def payload(n, seed):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.fixture(scope="module")
def cluster():
    c = MiniCluster(n_osds=7)
    c.create_ec_pool("rmw", k=4, m=2, pg_num=16, plugin="tpu")
    return c


def stored_shards(c, oid):
    """shard -> (bytes, size_attr) pulled straight from OSD stores."""
    out = {}
    for osd in c.osds.values():
        if osd.name in c.network.down:
            continue
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == oid:
                    size = struct.unpack(
                        "<Q", osd.store.getattr(cid, ho, SIZE_ATTR))[0]
                    out[ho.shard] = (osd.store.read(cid, ho), size)
    return out


def assert_shards_match_full_encode(c, oid, logical, k=4, m=2):
    """Every stored shard == the matching shard of a clean full encode."""
    profile = {"plugin": "tpu", "k": str(k), "m": str(m)}
    impl = create_erasure_code(profile)
    pool = next(p for p in c.mon.osdmap.pools.values()
                if p.is_erasure())
    sinfo = stripe_info_t(k, pool.stripe_width)
    w = sinfo.get_stripe_width()
    padded = logical + b"\0" * (-len(logical) % w)
    expect = ec_encode(sinfo, impl, padded, set(range(k + m)))
    got = stored_shards(c, oid)
    assert len(got) == k + m
    for shard, (data, size) in got.items():
        assert size == len(logical)
        np.testing.assert_array_equal(
            np.frombuffer(data, dtype=np.uint8), expect[shard],
            err_msg=f"shard {shard} diverges from full-encode")


def test_partial_overwrite_unaligned(cluster):
    client = cluster.client("client.pw")
    base = payload(40000, seed=1)
    assert client.write_full("rmw", "o1", base) == 0
    patch = payload(5000, seed=2)
    off = 12345  # straddles stripe boundaries, unaligned both ends
    assert client.write("rmw", "o1", patch, offset=off) == 0
    final = bytearray(base)
    final[off:off + len(patch)] = patch
    assert client.read("rmw", "o1") == bytes(final)
    assert_shards_match_full_encode(cluster, "o1", bytes(final))


def test_append_sequence(cluster):
    client = cluster.client("client.ap")
    parts = [payload(n, seed=10 + i)
             for i, n in enumerate([1000, 37, 8192, 4093])]
    for p in parts:
        assert client.append("rmw", "o2", p) == 0
    final = b"".join(parts)
    assert client.read("rmw", "o2") == final
    assert client.stat("rmw", "o2") == len(final)
    assert_shards_match_full_encode(cluster, "o2", final)


def test_write_past_eof_zero_fills_gap(cluster):
    client = cluster.client("client.gap")
    head = payload(100, seed=20)
    tail = payload(200, seed=21)
    assert client.write_full("rmw", "o3", head) == 0
    assert client.write("rmw", "o3", tail, offset=5000) == 0
    final = head + b"\0" * (5000 - 100) + tail
    assert client.read("rmw", "o3") == final
    assert_shards_match_full_encode(cluster, "o3", final)


def test_offset_write_creates_object(cluster):
    client = cluster.client("client.new")
    body = payload(777, seed=30)
    assert client.write("rmw", "o4", body, offset=300) == 0
    final = b"\0" * 300 + body
    assert client.read("rmw", "o4") == final
    assert_shards_match_full_encode(cluster, "o4", final)


def test_ranged_reads(cluster):
    client = cluster.client("client.rr")
    data = payload(30000, seed=40)
    assert client.write_full("rmw", "o5", data) == 0
    for off, ln in [(0, 100), (9999, 4097), (29990, 100), (5, 0)]:
        want = data[off:off + ln] if ln else data[off:]
        got = client.read("rmw", "o5", offset=off, length=ln) if ln \
            else client.read("rmw", "o5", offset=off)
        assert got == want, (off, ln)
    # read entirely past EOF
    assert client.read("rmw", "o5", offset=50000, length=10) == b""


def test_concurrent_overlapping_writes_pipeline(cluster):
    """Two overlapping rmw ops submitted before any delivery must apply
    in order through the per-object queue + extent cache."""
    c = cluster
    client = c.client("client.cc")
    base = payload(20000, seed=50)
    assert client.write_full("rmw", "o6", base) == 0
    # reach the primary's ECBackend directly so both ops queue up
    pool_id = client.lookup_pool("rmw")
    pgid, primary = client._calc_target(pool_id, "o6")
    pg = c.osds[primary].pgs[pgid]
    results = []
    p1, p2 = payload(6000, seed=51), payload(3000, seed=52)
    pg.backend.submit_write("o6", p1, 4000, results.append)
    pg.backend.submit_write("o6", p2, 7000, results.append)
    assert len(pg.backend._oid_queues["o6"]) >= 1
    c.network.pump()
    assert results == [0, 0]
    assert "o6" not in pg.backend._oid_queues
    final = bytearray(base)
    final[4000:4000 + len(p1)] = p1
    final[7000:7000 + len(p2)] = p2
    assert client.read("rmw", "o6") == bytes(final)
    assert_shards_match_full_encode(c, "o6", bytes(final))


def test_degraded_partial_write():
    """rmw with a down shard holder: pre-read reconstructs, commit covers
    the surviving shards, and the data reads back correct."""
    c = MiniCluster(n_osds=7)
    c.create_ec_pool("rmwd", k=4, m=2, pg_num=8, plugin="tpu")
    client = c.client("client.dg")
    base = payload(25000, seed=60)
    assert client.write_full("rmwd", "od", base) == 0
    holders = {o.osd_id for o in c.osds.values()
               if any(ho.oid == "od"
                      for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    pool_id = client.lookup_pool("rmwd")
    _, primary = client._calc_target(pool_id, "od")
    victim = next(o for o in holders if o != primary)
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    patch = payload(4000, seed=61)
    assert client.write("rmwd", "od", patch, offset=10001) == 0
    final = bytearray(base)
    final[10001:10001 + len(patch)] = patch
    assert client.read("rmwd", "od") == bytes(final)


def test_replicated_partial_write_and_append():
    c = MiniCluster(n_osds=5)
    c.create_replicated_pool("rp", size=3, pg_num=8)
    client = c.client("client.rp")
    base = payload(5000, seed=70)
    assert client.write_full("rp", "ro", base) == 0
    patch = payload(700, seed=71)
    assert client.write("rp", "ro", patch, offset=1234) == 0
    extra = payload(400, seed=72)
    assert client.append("rp", "ro", extra) == 0
    final = bytearray(base)
    final[1234:1234 + len(patch)] = patch
    final += extra
    assert client.read("rp", "ro") == bytes(final)


def test_partial_writes_require_ec_overwrites_flag():
    """Without FLAG_EC_OVERWRITES, offset writes/appends on an EC pool
    are rejected with EOPNOTSUPP; full-object writes still work
    (the reference gates rmw behind the pool flag)."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=4)
    c.create_ec_pool("noow", k=2, m=1, plugin="isa", pg_num=4,
                     ec_overwrites=False)
    cl = c.client("client.no")
    assert cl.write_full("noow", "o", b"full-ok") == 0
    assert cl.write("noow", "o", b"xx", offset=2) == -95
    assert cl.append("noow", "o", b"yy") == -95
    assert cl.read("noow", "o") == b"full-ok"


def test_overwrites_gate_covers_vectors_and_skips_clones():
    from ceph_tpu.client import ObjectOperation
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=4)
    c.create_ec_pool("gv", k=2, m=1, plugin="isa", pg_num=4,
                     ec_overwrites=False)
    cl = c.client("client.gv")
    cl.write_full("gv", "o", b"base")
    # vector-shaped partial updates are rejected identically
    for op in (ObjectOperation().write(b"x", 1),
               ObjectOperation().append(b"x"),
               ObjectOperation().truncate(2),
               ObjectOperation().zero(0, 2)):
        r, _ = cl.operate("gv", "o", op)
        assert r == -95, r
    # a rejected partial write must not leave a snapshot clone behind
    cl.snap_create("gv", "s1")
    assert cl.write("gv", "o", b"x", offset=1) == -95
    clones = sum(1 for o in c.osds.values()
                 for cid in o.store.list_collections()
                 for ho in o.store.list_objects(cid)
                 if "\x00snap\x00" in ho.oid)
    assert clones == 0
    # write_full still allowed (it replaces, not overwrites)
    assert cl.write_full("gv", "o", b"replaced") == 0
