"""ceph-conf + ceph-kvstore-tool cram parity: the reference's last
two recorded CLI families (src/test/cli/ceph-conf/*.t — 9 files — and
src/test/cli/ceph-kvstore-tool/help.t) replayed byte-exact.  With
these, EVERY .t under the reference's src/test/cli/ is replayed.

ceph-conf pins the config machinery itself: section search order
([type.id] [type] [global]), $metavariable expansion with the
reference's loop-detection report, CEPH_CONF/CEPH_ARGS environment
semantics, and the daemon-default paths ($cluster-$name expansion).
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cram import assert_cram  # noqa: E402

CONF_REF = "/root/reference/src/test/cli/ceph-conf"
KV_REF = "/root/reference/src/test/cli/ceph-kvstore-tool"

CONF_ALL = ["simple.t", "help.t", "option.t", "sections.t",
            "show-config-value.t", "show-config.t", "invalid-args.t",
            "env-vs-args.t", "manpage.t"]


@pytest.mark.parametrize("name", CONF_ALL)
def test_ceph_conf_cram(name, tmp_path):
    path = os.path.join(CONF_REF, name)
    if not os.path.exists(path):
        pytest.skip("reference cram corpus not present")
    assert_cram(path, str(tmp_path))


def test_kvstore_tool_cram(tmp_path):
    path = os.path.join(KV_REF, "help.t")
    if not os.path.exists(path):
        pytest.skip("reference cram corpus not present")
    assert_cram(path, str(tmp_path))


def test_kvstore_tool_round_trip(tmp_path):
    """Functional check beyond the help transcript: set/get/list/crc/
    rm/store-copy against the directory-backed store."""
    from ceph_tpu.tools.kvstore_tool import main
    import io
    from contextlib import redirect_stdout

    store = str(tmp_path / "db")
    blob = tmp_path / "blob"
    blob.write_bytes(b"hello kv")

    def run(*args):
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main(["bluestore-kv", store, *args])
        return rc, buf.getvalue()

    assert run("set", "p", "k", "in", str(blob))[0] == 0
    assert run("set", "p", "ver", "ver", "7")[0] == 0
    rc, out = run("list")
    assert rc == 0 and out.splitlines() == ["p\tk", "p\tver"]
    rc, out = run("exists", "p", "k")
    assert rc == 0 and out.strip() == "(p, k) exists"
    rc, out = run("get", "p", "k", "out", str(tmp_path / "back"))
    assert rc == 0 and (tmp_path / "back").read_bytes() == b"hello kv"
    rc, out = run("crc", "p", "k")
    assert rc == 0 and out.startswith("(p, k) crc ")
    rc, out = run("list-crc")
    assert rc == 0 and all(len(l.split("\t")) == 3
                           for l in out.splitlines())
    # copy, then mutate the source: the copy must be independent
    dst = str(tmp_path / "copy")
    assert run("store-copy", dst)[0] == 0
    assert run("rm", "p", "k")[0] == 0
    assert run("exists", "p", "k")[0] == 1
    with redirect_stdout(io.StringIO()):
        assert main(["bluestore-kv", dst, "exists", "p", "k"]) == 0
    # escaped names survive the filename round trip
    assert run("set", "pre/fix", "k y%", "in", str(blob))[0] == 0
    rc, out = run("get", "pre/fix", "k y%")
    assert rc == 0 and "pre%2ffix" in out


def test_dump_formats(tmp_path):
    """--format plain|json|json-pretty on -D (the help's FLAGS
    contract), beyond what the cram corpus pins."""
    import io
    import json as _json
    from contextlib import redirect_stdout

    from ceph_tpu.tools.ceph_conf import main

    def run(*args):
        buf = io.StringIO()
        with redirect_stdout(buf):
            rc = main(list(args))
        return rc, buf.getvalue()

    rc, out = run("-n", "osd.0", "-D", "-c", "/dev/null")
    assert rc == 0 and "log_file = /var/log/ceph/ceph-osd.0.log" in out
    rc, out = run("-n", "osd.0", "-D", "--format", "json",
                  "-c", "/dev/null")
    assert rc == 0
    doc = _json.loads(out)
    assert doc["log_file"] == "/var/log/ceph/ceph-osd.0.log"
    rc, out = run("-n", "osd.0", "-D", "--format", "json-pretty",
                  "-c", "/dev/null")
    assert rc == 0 and _json.loads(out)["admin_socket"].endswith(
        "ceph-osd.0.asok")
    # identity keys lead the structured dumps (_show_config order)
    assert list(_json.loads(out))[:2] == ["name", "cluster"]
    rc, out = run("-n", "osd.0", "-D", "--format", "xml",
                  "-c", "/dev/null")
    assert rc == 0 and out.startswith("<config>") \
        and "<name>osd.0</name>" in out
    rc, out = run("-D", "--format", "table-kv", "-c", "/dev/null")
    assert rc == 0 and "fsid: " in out
    # unknown formats: Formatter::create's refusal, only at dump time
    assert run("-D", "--format", "yaml")[0] == 1
    assert run("-L", "--format", "yaml", "-c", "/dev/null")[0] == 0
