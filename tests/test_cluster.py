"""vstart-lite integration: EC pool IO, degraded reads, recovery, thrashing.

Models the reference's standalone cluster tests
(qa/standalone/erasure-code/test-erasure-code.sh: build a cluster, create
an EC pool with crush-failure-domain=osd, write/read objects, kill OSDs)
plus the Thrasher loop behaviors (qa/tasks/ceph_manager.py).
"""
import numpy as np
import pytest

from ceph_tpu.cluster import MiniCluster


def payload(n=40000, seed=7):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


@pytest.fixture(scope="module")
def ec_cluster():
    c = MiniCluster(n_osds=7)
    c.create_ec_pool("ecpool", k=4, m=2, pg_num=16, plugin="tpu",
                     failure_domain="host")
    return c


def test_ec_write_read_roundtrip(ec_cluster):
    c = ec_cluster
    client = c.client("client.rt")
    data = payload()
    assert client.write_full("ecpool", "obj1", data) == 0
    assert client.read("ecpool", "obj1") == data
    assert client.stat("ecpool", "obj1") == len(data)


def test_object_chunks_land_on_distinct_osds(ec_cluster):
    c = ec_cluster
    client = c.client("client.place")
    client.write_full("ecpool", "obj2", payload(seed=2))
    holders = []
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj2":
                    holders.append((osd.osd_id, ho.shard))
    assert len(holders) == 6           # k+m shards
    assert len({h[0] for h in holders}) == 6  # all on distinct osds


def test_degraded_read_after_failure_detection(ec_cluster):
    c = ec_cluster
    client = c.client("client.deg")
    data = payload(seed=3)
    client.write_full("ecpool", "obj3", data)
    # find a shard holder that is not any pg primary we need, kill it
    victim = None
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj3":
                    victim = osd.osd_id
        if victim is not None:
            break
    c.kill_osd(victim)
    # heartbeats detect the silent osd and the mon publishes a new epoch
    for _ in range(6):
        c.tick(dt=6.0)
    assert not c.mon.osdmap.is_up(victim)
    # degraded read must reconstruct the lost shard
    assert client.read("ecpool", "obj3") == data
    c.revive_osd(victim)
    for _ in range(3):
        c.tick(dt=6.0)
    assert c.mon.osdmap.is_up(victim)


def test_recovery_restores_redundancy():
    c = MiniCluster(n_osds=8)
    c.create_ec_pool("ec2", k=3, m=2, pg_num=8)
    client = c.client("client.rec")
    data = payload(seed=4)
    client.write_full("ec2", "objr", data)
    holders = {o.osd_id for o in c.osds.values()
               if any(ho.oid == "objr"
                      for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    victim = next(iter(holders))
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    c.mark_osd_out(victim)   # out -> crush remaps to a replacement shard
    # recovery should have pushed the lost chunk to the replacement
    new_holders = {o.osd_id for o in c.osds.values()
                   if o.osd_id != victim
                   and o.name not in c.network.down
                   and any(ho.oid == "objr"
                           for cid in o.store.list_collections()
                           for ho in o.store.list_objects(cid))}
    assert len(new_holders) == 5  # k+m distinct live holders again
    assert client.read("ec2", "objr") == data
    # second failure after recovery is still survivable
    victim2 = next(iter(new_holders))
    c.kill_osd(victim2)
    c.mark_osd_down(victim2)
    assert client.read("ec2", "objr") == data


def test_corrupt_shard_detected_and_reconstructed():
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("ec3", k=4, m=2, pg_num=8)
    client = c.client("client.scrub")
    data = payload(seed=5)
    client.write_full("ec3", "objc", data)
    # flip bits in one stored shard; HashInfo crc must catch it on read
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == "objc":
                    obj = osd.store.colls[cid][ho]
                    obj.data[10] ^= 0xFF
                    break
            else:
                continue
            break
        else:
            continue
        break
    assert client.read("ec3", "objc") == data


def test_replicated_pool_roundtrip_and_recovery():
    c = MiniCluster(n_osds=5)
    c.create_replicated_pool("rbd", size=3, pg_num=8)
    client = c.client("client.rep")
    data = payload(seed=6, n=10000)
    assert client.write_full("rbd", "ro", data) == 0
    assert client.read("rbd", "ro") == data
    holders = {o.osd_id for o in c.osds.values()
               if any(ho.oid == "ro"
                      for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    assert len(holders) == 3
    victim = next(iter(holders))
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    c.mark_osd_out(victim)
    assert client.read("rbd", "ro") == data
    new_holders = {o.osd_id for o in c.osds.values()
                   if o.name not in c.network.down
                   and any(ho.oid == "ro"
                           for cid in o.store.list_collections()
                           for ho in o.store.list_objects(cid))}
    assert len(new_holders) == 3


def test_delete_removes_all_shards(ec_cluster):
    c = ec_cluster
    client = c.client("client.del")
    client.write_full("ecpool", "objd", payload(seed=8, n=5000))
    assert client.remove("ecpool", "objd") == 0
    c.network.pump()
    leftovers = [1 for o in c.osds.values()
                 for cid in o.store.list_collections()
                 for ho in o.store.list_objects(cid) if ho.oid == "objd"]
    assert not leftovers
    with pytest.raises(IOError):
        client.read("ecpool", "objd")


def test_lrc_pool_end_to_end():
    c = MiniCluster(n_osds=9)
    c.create_ec_pool("lrcpool", pg_num=8, plugin="lrc",
                     extra_profile={"k": "4", "m": "2", "l": "3"})
    client = c.client("client.lrc")
    data = payload(seed=9)
    assert client.write_full("lrcpool", "objl", data) == 0
    assert client.read("lrcpool", "objl") == data


def test_writes_blocked_below_min_size():
    """Writes to a PG with fewer than min_size live acting members are
    refused (EAGAIN -> client retry -> -110), while reads still serve
    degraded; recovery of the acting set unblocks writes."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=3)          # exactly k+m: no spare to remap to
    c.create_ec_pool("ms", k=2, m=1, plugin="isa", pg_num=4)
    cl = c.client("client.ms")
    data = payload(seed=11)
    cl.write_full("ms", "obj", data)
    victim = None
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            for ho in osd.store.list_objects(cid):
                if ho.oid == "obj" and victim is None:
                    victim = osd.osd_id
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    c.mark_osd_out(victim)
    c.network.pump()
    # only 2 live osds remain for a min_size=3 pool: writes refuse
    assert cl.write_full("ms", "obj", b"nope") in (-11, -110)
    # degraded reads still reconstruct
    assert cl.read("ms", "obj") == data
    # revive AND mark back in: acting refills, writes flow again
    c.revive_osd(victim)
    for _ in range(4):
        c.tick(dt=6.0)
    c.mon.mark_osd_in(victim)
    c.network.pump()
    c.run_recovery()
    c.network.pump()
    assert cl.write_full("ms", "obj", b"back") == 0
    assert cl.read("ms", "obj") == b"back"
