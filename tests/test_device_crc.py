"""Device crc32c kernel — bit-identity with the host path, end to end.

The fused encode+crc path (ops/crc32c_device.py, ops/resident.py) only
holds together if the jitted CRC is byte-identical to ``utils/crc32c``
for every length the store can produce — including the
non-word-aligned tails the slicing-by-8 word loop hands to the
byte-at-a-time epilogue.  The cluster-twin tests then pin the derived
property actually relied on: HashInfo digests stored by a
device-resident write equal the host-hashed twin's, and a corrupted
resident shard still fails its crc verify with EIO (the
``store.shard_corrupt`` fault site), reconstructing from survivors.
"""
import struct

import numpy as np
import pytest

from ceph_tpu.utils.crc32c import crc32c

pytest.importorskip("jax")

from ceph_tpu.ops.crc32c_device import (crc32c_device_batch,  # noqa: E402
                                        crc32c_device_padded,
                                        crc32c_of_device_array,
                                        device_crc_available)


def test_device_crc_matches_host_for_every_length_0_to_4097():
    """The property sweep: one padded shape (ONE compile — length is a
    traced operand), every length 0..4097 including all word-tail
    residues, bit-compared against the host table implementation."""
    assert device_crc_available()
    rng = np.random.default_rng(20260807)
    lengths = np.arange(0, 4098, dtype=np.uint32)
    pad_w = 4104                      # 4097 rounded up to a word multiple
    padded = np.zeros((len(lengths), pad_w), dtype=np.uint8)
    for i, n in enumerate(lengths):
        padded[i, :n] = rng.integers(0, 256, size=int(n), dtype=np.uint8)
    got = crc32c_device_padded(padded, lengths)
    for i, n in enumerate(lengths):
        assert int(got[i]) == crc32c(padded[i, :n]), f"length {n}"


def test_device_crc_batch_and_single_entries_agree():
    rng = np.random.default_rng(7)
    rows = rng.integers(0, 256, size=(5, 12289), dtype=np.uint8)
    batch = crc32c_device_batch(rows)
    for i in range(rows.shape[0]):
        expect = crc32c(rows[i])
        assert int(batch[i]) == expect
        import jax.numpy as jnp
        assert crc32c_of_device_array(jnp.asarray(rows[i])) == expect


def test_device_crc_seed_convention_matches_ceph():
    # Ceph's convention: seed -1, no final inversion — the empty buffer
    # hashes to the seed itself
    got = crc32c_device_padded(np.zeros((1, 8), dtype=np.uint8),
                               np.zeros(1, dtype=np.uint32))
    assert int(got[0]) == 0xFFFFFFFF
    assert crc32c(b"") == 0xFFFFFFFF


# ---- cluster twins ----------------------------------------------------------
@pytest.fixture
def residency():
    from ceph_tpu.common.config import g_conf
    saved = g_conf.values.get("os_memstore_device_bytes_max")
    g_conf.set_val("os_memstore_device_bytes_max", 1 << 30)
    yield
    if saved is None:
        g_conf.rm_val("os_memstore_device_bytes_max")
    else:
        g_conf.set_val("os_memstore_device_bytes_max", saved)


def _shard_digests(c, oid):
    """{(cid, shard): (stored hinfo digest, host crc of stored body)}
    across every OSD holding a shard of *oid*."""
    from ceph_tpu.osd.ec_backend import HINFO_ATTR
    out = {}
    for osd in c.osds.values():
        for cid in osd.store.list_collections():
            if "_meta" in cid:
                continue
            for ho in osd.store.list_objects(cid):
                if ho.oid != oid:
                    continue
                total, digest = struct.unpack(
                    "<QI", osd.store.getattr(cid, ho, HINFO_ATTR))
                body = osd.store.read(cid, ho)
                assert total == len(body)
                out[(cid, ho.shard)] = (digest, crc32c(body))
    return out


def test_resident_write_stores_host_identical_hinfo_digests(residency):
    """Cluster twin: a device-resident write's stored HashInfo digests
    (computed by the fused kernel, fetched as 4-byte scalars) equal the
    host crc32c of the materialized shard bodies — and equal the
    digests a residency-off twin stores for the same payload."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common.config import g_conf
    data = np.random.default_rng(13).integers(
        0, 256, size=36864, dtype=np.uint8).tobytes()

    c = MiniCluster(n_osds=6)
    c.create_ec_pool("dc", k=3, m=2, pg_num=8)
    assert c.client("client.dc").write_full("dc", "obj", data) == 0
    resident = _shard_digests(c, "obj")
    assert len(resident) == 5
    for key, (stored, host) in resident.items():
        assert stored == host, f"digest mismatch at {key}"

    g_conf.set_val("os_memstore_device_bytes_max", 0)
    tw = MiniCluster(n_osds=6)
    tw.create_ec_pool("dc", k=3, m=2, pg_num=8)
    assert tw.client("client.tw").write_full("dc", "obj", data) == 0
    twin = _shard_digests(tw, "obj")
    assert {k[1]: v[0] for k, v in resident.items()} \
        == {k[1]: v[0] for k, v in twin.items()}


def test_chaos_pinned_seed_green_with_residency_on(residency):
    """Acceptance: a pinned composed-chaos storyline (seed 24 — the
    tier-1 pin in tests/test_chaos_composer.py) passes the universal
    acceptance with the device-resident shard store ENABLED, so
    residency survives OSD kills, EIOs and stragglers like host
    bytes do."""
    from ceph_tpu.chaos import run_seed
    r = run_seed(24)
    assert r["accepted"], r


def test_corrupted_resident_shard_fails_crc_and_reconstructs(residency):
    """The ``store.shard_corrupt`` fault site flips one byte of a
    still-resident shard body at read time: the shard-side device-crc
    verify must return EIO and the primary must serve the read
    byte-exact from the surviving shards."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.fault import g_faults
    from ceph_tpu.os_store.device_shard import DeviceShard
    data = np.random.default_rng(17).integers(
        0, 256, size=24576, dtype=np.uint8).tobytes()
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("dc", k=3, m=2, pg_num=8)
    cl = c.client("client.dc")
    assert cl.write_full("dc", "obj", data) == 0
    # residency engaged: at least one stored body is still a handle
    assert any(isinstance(osd.store.colls[cid][ho].data, DeviceShard)
               for osd in c.osds.values()
               for cid in osd.store.list_collections()
               if "_meta" not in cid
               for ho in osd.store.list_objects(cid)
               if ho.oid == "obj")
    spec = g_faults.inject("store.shard_corrupt", mode="once",
                           match="obj")
    try:
        assert cl.read("dc", "obj") == data
        assert spec.fires == 1, "the corruption never fired"
    finally:
        g_faults.clear("store.shard_corrupt")
