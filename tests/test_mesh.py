"""Mesh runtime: one scheduler feeding N chips (the mesh PR's
acceptance gates).

- ``ec_mesh_chips=0`` (the default) and a 1-device mesh are the
  existing single-device dispatch path by construction;
- with an 8-device mesh up, mesh-dispatched encode groups are
  byte-identical to the single-device oracle across randomized
  (k, m, technique, size) mixes INCLUDING batch occupancies that are
  not a multiple of the mesh size (padding lanes never leak);
- a mesh-dispatched cluster stores shard BODIES byte-identical to a
  single-device twin;
- the tier-1 mesh smoke: a batched write on the forced 8-device
  host-platform mesh puts work on EVERY chip (per-chip occupancy > 0);
- the sharding-plan cache and the staging pool actually reuse;
- a DeviceUnavailable mesh call degrades to the single-device path
  (fault site ``mesh.encode_batch``), clients never see it;
- observability: per-chip occupancy histogram, ``ceph_daemon_mesh_*``
  counters on Prometheus, the mesh block on ``dispatch dump``;
- the mesh write path adds ZERO device syncs with tracing off
  (fence-count gate extended).
"""
import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.dispatch import g_dispatcher
from ceph_tpu.ec.isa import ErasureCodeIsa
from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
from ceph_tpu.mesh import g_mesh, mesh_perf_counters
from ceph_tpu.mesh.runtime import (l_mesh_dispatches, l_mesh_fallbacks,
                                   l_mesh_plan_builds, l_mesh_pool_hits)
from ceph_tpu.osd.ecutil import (decode_concat as eu_decode_concat,
                                 encode as eu_encode, stripe_info_t)


@pytest.fixture
def mesh_conf():
    """Every test leaves the dispatcher drained, the options at their
    defaults, and the mesh torn back down."""
    yield
    g_dispatcher.flush()
    for name in ("ec_mesh_chips", "ec_mesh_pool_buffers",
                 "ec_mesh_donate", "ec_dispatch_batch_max",
                 "ec_dispatch_batch_window_us", "ec_dispatch_queue_max",
                 "ec_pipeline_depth", "ec_mesh_skew_sample_every"):
        g_conf.rm_val(name)
    g_mesh.topology()      # rebuild to the default (mesh off)
    # the scoreboard is process-global: drop any probe state (or a
    # suspect marked on an oversubscribed CI host) so later tests'
    # health() panes start clean
    from ceph_tpu.mesh import g_chipstat
    g_chipstat.reset()


def _mesh_on(chips=8, batch_max=64, window_us=10_000_000):
    g_conf.set_val("ec_mesh_chips", chips)
    g_conf.set_val("ec_dispatch_batch_window_us", window_us)
    g_conf.set_val("ec_dispatch_batch_max", batch_max)


def _mk_impl(plugin, k, m, technique):
    impl = plugin()
    impl.init({"k": str(k), "m": str(m), "technique": technique})
    return impl


def _same_shards(a, b):
    assert sorted(a) == sorted(b)
    for i in a:
        assert np.asarray(a[i]).tobytes() == np.asarray(b[i]).tobytes(), \
            f"shard {i} differs"


def test_mesh_off_by_default(mesh_conf):
    assert int(g_conf.get_val("ec_mesh_chips")) == 0
    assert g_mesh.active() is False
    d = g_dispatcher.dump()["mesh"]
    assert d["active"] is False and d["size"] == 0


def test_single_chip_mesh_is_passthrough(mesh_conf):
    """ec_mesh_chips=1: a 1-device topology never shards — the mesh
    dispatch counter must not move and outputs are the oracle's."""
    _mesh_on(chips=1)
    assert g_mesh.active() is False
    pc = mesh_perf_counters()
    before = pc.get(l_mesh_dispatches)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    d = (np.arange(3 * 4 * 1024) % 251).astype(np.uint8)
    f = g_dispatcher.submit_encode(sinfo, impl, d, set(range(6)))
    _same_shards(f.result(), eu_encode(sinfo, impl, d, set(range(6))))
    assert pc.get(l_mesh_dispatches) == before


# ---- byte identity (the property-test satellite) ---------------------------
MIX = [
    (ErasureCodeTpu, 4, 2, "reed_sol_van"),
    (ErasureCodeTpu, 8, 4, "reed_sol_van"),
    (ErasureCodeIsa, 3, 2, "cauchy"),
    (ErasureCodeIsa, 6, 3, "reed_sol_van"),
]


@pytest.mark.parametrize("seed", [11, 23, 47])
def test_mesh_byte_identity_property(mesh_conf, seed):
    """Mesh-dispatched groups vs the single-device oracle across
    randomized (k, m, technique, chunk size, stripe count) mixes.
    Stripe totals are deliberately NOT multiples of the mesh size —
    the zero-pad lanes must never leak into any request's output —
    and mixed chunk sizes share a bucket like any dispatch group.
    Skew sampling runs on EVERY flush here (the per-chip timing PR's
    byte-identity extension): the probe drains the same coalesced
    output the flush materializes anyway, so it must never touch the
    data path."""
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    rng = np.random.default_rng(seed)
    impls = [_mk_impl(p, k, m, t) for p, k, m, t in MIX]
    specs = []
    for _ in range(18):
        impl = impls[rng.integers(0, len(impls))]
        k, m = impl.k, impl.m
        chunk = int(rng.choice([512, 768, 1024, 1536]))
        stripes = int(rng.integers(1, 7))     # totals rarely % 8 == 0
        sinfo = stripe_info_t(k, k * chunk)
        data = rng.integers(0, 256, size=stripes * k * chunk,
                            dtype=np.uint8)
        specs.append((sinfo, impl, data, set(range(k + m))))
    oracles = [eu_encode(s, i, d, w) for s, i, d, w in specs]
    _mesh_on(chips=8)
    futs = [g_dispatcher.submit_encode(s, i, d, w)
            for s, i, d, w in specs]
    g_dispatcher.flush()
    for f, oracle in zip(futs, oracles):
        _same_shards(f.result(), oracle)
    # the mesh actually ran (not a silent single-device pass)
    assert mesh_perf_counters().get(l_mesh_dispatches) > 0


def test_mesh_declines_layout_transforming_codecs(mesh_conf):
    """Jerasure bitmatrix techniques reshape data into a virtual
    layout before the backend matmul — the mesh plan models the PLAIN
    row-independent matmul only, so the runtime must DECLINE them
    (mesh_row_shardable False) and the single-device path keeps them
    byte-identical with the mesh up."""
    from ceph_tpu.ec.jerasure import ErasureCodeJerasure
    impl = ErasureCodeJerasure()
    impl.init({"k": "4", "m": "2", "technique": "cauchy_good",
               "packetsize": "8"})
    assert impl.mesh_row_shardable is False
    chunk = impl._stripe_block() * 4
    sinfo = stripe_info_t(4, 4 * chunk)
    rng = np.random.default_rng(31)
    data = rng.integers(0, 256, size=3 * 4 * chunk, dtype=np.uint8)
    oracle = eu_encode(sinfo, impl, data, set(range(6)))
    _mesh_on(chips=8)
    pc = mesh_perf_counters()
    before = pc.get(l_mesh_dispatches)
    f = g_dispatcher.submit_encode(sinfo, impl, data, set(range(6)))
    g_dispatcher.flush()
    _same_shards(f.result(), oracle)
    assert pc.get(l_mesh_dispatches) == before, \
        "the mesh must decline layout-transforming codecs"


def test_mesh_on_decode_groups_ride_the_mesh(mesh_conf):
    """Decode groups ride the mesh alongside encode groups (the
    straggler-proof read PR; tests/test_mesh_decode.py holds the full
    gate set) — both byte-identical to their single-device oracles in
    one mixed flush."""
    from ceph_tpu.mesh import mesh_decode_perf_counters
    from ceph_tpu.mesh.runtime import l_mdec_dispatches
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    rng = np.random.default_rng(5)
    data = rng.integers(0, 256, size=3 * 4 * 1024, dtype=np.uint8)
    shards = eu_encode(sinfo, impl, data, set(range(6)))
    chunks = {i: shards[i] for i in (0, 2, 4, 5)}
    oracle = eu_decode_concat(sinfo, impl, dict(chunks))
    _mesh_on(chips=8)
    mdec0 = mesh_decode_perf_counters().get(l_mdec_dispatches)
    f_enc = g_dispatcher.submit_encode(sinfo, impl, data, set(range(6)))
    f_dec = g_dispatcher.submit_decode_concat(sinfo, impl, dict(chunks))
    g_dispatcher.flush()
    _same_shards(f_enc.result(), shards)
    assert np.asarray(f_dec.result()).tobytes() \
        == np.asarray(oracle).tobytes()
    assert mesh_decode_perf_counters().get(l_mdec_dispatches) > mdec0, \
        "the reconstruct group never rode the mesh"


def _ec_shard_bodies(c):
    """(osd, cid, oid) -> stored shard bytes for every EC collection
    (the test_pipeline.py receipt, applied to the mesh twin)."""
    out = {}
    for i, osd in c.osds.items():
        for cid in osd.store.list_collections():
            if "_meta" in cid or "s" not in cid.split(".")[-1]:
                continue
            for ho in osd.store.list_objects(cid):
                out[(i, cid, str(ho))] = osd.store.read(cid, ho)
    return out


def test_cluster_twin_stored_shards_byte_identical(mesh_conf):
    """A mesh-dispatched cluster stores shard BODIES byte-identical to
    a single-device twin across a write/overwrite/append mix."""
    from ceph_tpu.cluster import MiniCluster

    def run(mesh: bool):
        if mesh:
            _mesh_on(chips=8, window_us=200_000)
        else:
            for name in ("ec_mesh_chips", "ec_dispatch_batch_max",
                         "ec_dispatch_batch_window_us"):
                g_conf.rm_val(name)
        g_mesh.topology()
        c = MiniCluster(n_osds=6)
        c.create_ec_pool("mtwin", k=3, m=2, pg_num=4)
        cl = c.client("client.mesh")
        rng = np.random.default_rng(99)
        expected = {}
        for i in range(4):
            body = bytes(rng.integers(0, 256, 9000 + 4111 * i,
                                      dtype=np.uint8))
            assert cl.write_full("mtwin", f"o{i}", body) == 0
            expected[f"o{i}"] = body
        tail = bytes(rng.integers(0, 256, 5000, dtype=np.uint8))
        assert cl.append("mtwin", "o1", tail) == 0
        expected["o1"] = expected["o1"] + tail
        for oid, body in expected.items():
            assert cl.read("mtwin", oid) == body, (mesh, oid)
        return _ec_shard_bodies(c)

    meshed = run(mesh=True)
    assert mesh_perf_counters().get(l_mesh_dispatches) > 0
    single = run(mesh=False)
    assert set(meshed) == set(single)
    diffs = [key for key in single
             if bytes(meshed[key]) != bytes(single[key])]
    assert not diffs, f"{len(diffs)} shard bodies differ: {diffs[:5]}"


# ---- the tier-1 mesh smoke fixture (CI satellite) --------------------------
def test_tier1_mesh_smoke_all_chips_occupied(mesh_conf):
    """The conftest forces an 8-device host-platform mesh
    (XLA_FLAGS=--xla_force_host_platform_device_count=8); a batched
    write big enough to span >= 8 stripes must put real work on EVERY
    chip, and read back byte-exact."""
    from ceph_tpu.cluster import MiniCluster
    _mesh_on(chips=8, window_us=200_000)
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("msmoke", k=3, m=2, pg_num=4)
    cl = c.client("client.msmoke")
    before = {i: v["stripes"] for i, v in g_mesh.per_chip().items()}
    # exactly 16 stripes (stripe_width = k * 4096): the batch axis is
    # BLOCK-sharded, so full occupancy needs S >= a mesh multiple —
    # shorter writes park their zero-pad lanes on the tail chips, and
    # the occupancy histogram is what makes that imbalance visible
    body = bytes(np.random.default_rng(7).integers(
        0, 256, size=16 * 3 * 4096, dtype=np.uint8))
    assert cl.write_full("msmoke", "big", body) == 0
    assert cl.read("msmoke", "big") == body
    per_chip = {i: v["stripes"] - before.get(i, 0)
                for i, v in g_mesh.per_chip().items()}
    assert len(per_chip) == 8, per_chip
    assert all(v > 0 for v in per_chip.values()), per_chip
    # the occupancy surfaced on `dispatch dump` too
    d = c.admin_socket.execute("dispatch dump")["mesh"]
    assert d["size"] == 8 and d["active"] is True
    assert all(d["per_chip"][i]["dispatches"] > 0 for i in d["per_chip"])


# ---- plan cache + staging pool ---------------------------------------------
def test_plan_cache_and_pool_reuse(mesh_conf):
    _mesh_on(chips=8, batch_max=4)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    rng = np.random.default_rng(3)
    pc = mesh_perf_counters()
    builds0 = pc.get(l_mesh_plan_builds)
    hits0 = pc.get(l_mesh_pool_hits)

    def flush_batch():
        futs = [g_dispatcher.submit_encode(
            sinfo, impl,
            rng.integers(0, 256, size=2 * 4 * 1024, dtype=np.uint8),
            set(range(6))) for _ in range(4)]
        for f in futs:
            f.result()

    flush_batch()
    flush_batch()
    assert pc.get(l_mesh_plan_builds) == builds0 + 1, \
        "same signature+bucket must share ONE sharding plan"
    assert pc.get(l_mesh_pool_hits) > hits0, \
        "the second flush must reuse the pooled staging buffer"
    # a different chunk bucket builds a second plan
    sinfo2 = stripe_info_t(4, 4 * 4096)
    f = g_dispatcher.submit_encode(
        sinfo2, impl,
        rng.integers(0, 256, size=4 * 4096, dtype=np.uint8),
        set(range(6)))
    f.result()
    assert pc.get(l_mesh_plan_builds) == builds0 + 2
    dump = g_mesh.dump()
    assert len(dump["plans"]) == 2
    # on the cpu smoke platform donation is structurally off (no
    # buffer aliasing support); the plan records what it got
    assert all(p["donated"] is False for p in dump["plans"])
    assert dump["pool"]["hits"] >= 1
    # ec_mesh_pool_buffers is LIVE: a config change applies on the
    # next flush without a topology rebuild
    g_conf.set_val("ec_mesh_pool_buffers", 1)
    g_mesh.topology()
    assert g_mesh.dump()["pool"]["per_shape"] == 1


def test_ec_mesh_donate_receipt(mesh_conf):
    """ec_mesh_donate=True on the CPU backend: donation must be
    structurally OFF (the CPU runtime cannot alias XLA buffers) while
    the plumbing stays intact — the raw option is live in dump(), the
    per-backend resolution leaves every plan's donated flag False, and
    because donate is part of the plan key (resolved False on cpu) the
    toggle must NOT fork a second plan for the same signature.  The
    staging pool keeps recycling the padded batch buffer underneath —
    that reuse is the receipt that the zero-copy chain did not regress
    when donation was requested but structurally unavailable."""
    _mesh_on(chips=8, batch_max=4)
    g_conf.set_val("ec_mesh_donate", True)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    rng = np.random.default_rng(29)
    pc = mesh_perf_counters()
    builds0 = pc.get(l_mesh_plan_builds)
    hits0 = pc.get(l_mesh_pool_hits)

    def flush_batch():
        blobs = [rng.integers(0, 256, size=2 * 4 * 1024, dtype=np.uint8)
                 for _ in range(4)]
        futs = [g_dispatcher.submit_encode(sinfo, impl, d, set(range(6)))
                for d in blobs]
        for d, f in zip(blobs, futs):
            _same_shards(f.result(),
                         eu_encode(sinfo, impl, d, set(range(6))))

    flush_batch()
    flush_batch()
    dump = g_mesh.dump()
    assert dump["options"]["ec_mesh_donate"] is True
    assert dump["plans"], "mesh never built a plan"
    assert all(p["donated"] is False for p in dump["plans"]), \
        "donation must resolve to off on the cpu backend"
    assert pc.get(l_mesh_plan_builds) == builds0 + 1, \
        "donate resolves into the plan key: on cpu it is False either " \
        "way, so the toggle must not fork a second plan"
    assert pc.get(l_mesh_pool_hits) > hits0, \
        "staging-pool reuse must survive a donate request"


def test_mesh_fallback_on_device_unavailable(mesh_conf):
    """An exhausted mesh call degrades to the single-device path —
    the op completes byte-identically, the fallback is counted."""
    from ceph_tpu.fault import g_breakers, g_faults
    _mesh_on(chips=8)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 1024)
    rng = np.random.default_rng(13)
    payloads = [rng.integers(0, 256, size=2 * 4 * 1024, dtype=np.uint8)
                for _ in range(3)]
    pc = mesh_perf_counters()
    before = pc.get(l_mesh_fallbacks)
    g_faults.inject("mesh.encode_batch", mode="always")
    try:
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, set(range(6)))
                for p in payloads]
        g_dispatcher.flush()
        for f, p in zip(futs, payloads):
            _same_shards(f.result(),
                         eu_encode(sinfo, impl, p, set(range(6))))
    finally:
        g_faults.clear()
        # the injected failures TRIPPED the signature's breaker (3
        # consecutive) — reset it so this test cannot leak an open
        # breaker (host-routed codecs + breaker dumps) into the suite
        g_breakers.reset()
    assert pc.get(l_mesh_fallbacks) > before


# ---- observability ---------------------------------------------------------
def test_chip_histogram_and_prometheus_export(mesh_conf):
    """The per-chip occupancy histogram and the mesh counters render on
    the mgr's Prometheus surface (golden-test satellite)."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.trace import g_perf_histograms
    _mesh_on(chips=8, window_us=200_000)
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("mprom", k=3, m=2, pg_num=4)
    cl = c.client("client.mprom")
    assert cl.write_full("mprom", "o", b"p" * 60000) == 0
    hist = g_perf_histograms.get("dispatch",
                                 "dispatch_chip_occupancy_histogram")
    assert hist.total_count > 0
    assert hist.axes[0].name == "chip_stripes"
    assert hist.axes[1].name == "chip_index"
    prom = c.admin_socket.execute("prometheus metrics")
    assert "ceph_daemon_mesh_dispatches" in prom
    assert "ceph_daemon_mesh_stripes" in prom
    assert "ceph_dispatch_chip_occupancy_histogram_bucket" in prom


def test_zero_syncs_on_mesh_write_path(mesh_conf, monkeypatch):
    """Fence-count gate extended to the mesh path: with tracing off a
    mesh-dispatched write adds zero block_until_ready syncs."""
    import jax
    from ceph_tpu.cluster import MiniCluster
    _mesh_on(chips=8, window_us=200_000)
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("msync", k=3, m=2, pg_num=4)
    cl = c.client("client.msync")
    cl.write_full("msync", "warm", b"w" * 60000)     # compile warmup
    calls = {"n": 0}
    real = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    assert cl.write_full("msync", "obj", b"x" * 60000) == 0
    assert cl.read("msync", "obj")[:1] == b"x"
    assert calls["n"] == 0, "mesh path added a device sync"
    assert mesh_perf_counters().get(l_mesh_dispatches) > 0
