"""Pool quotas, cluster full/nearfull gating, stale-upmap cleanup.

Reference semantics: writes to a pool flagged FULL return EDQUOT when
quota-driven and ENOSPC otherwise (PrimaryLogPG.cc:7832-7842); deletes
pass so space can be freed; the mon drops upmap entries referencing
dead pools/OSDs (OSDMonitor::maybe_remove_pg_upmaps).
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.common.config import g_conf
from ceph_tpu.osdmap.osdmap import CEPH_OSDMAP_FULL, CEPH_OSDMAP_NEARFULL
from ceph_tpu.osdmap.types import FLAG_FULL, FLAG_FULL_QUOTA, pg_t


def settle(c, n=3):
    for _ in range(n):
        c.tick(dt=1.0)


def test_pool_quota_objects_edquot():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("q", size=2, pg_num=8)
    cl = c.client("client.q")
    c.mon.set_pool_quota("q", max_objects=3)
    c.publish()
    for i in range(3):
        assert cl.write_full("q", f"o{i}", b"x" * 10) == 0
    settle(c, 6)        # stats report (every 5th tick) + mgr reaction
    pid = c.mon.osdmap.lookup_pg_pool_name("q")
    assert c.mon.osdmap.pools[pid].has_flag(FLAG_FULL_QUOTA)
    assert cl.write_full("q", "o3", b"x") == -122        # EDQUOT
    # deletes pass (free space) and the quota clears after usage drops
    assert cl.remove("q", "o0") == 0
    assert cl.remove("q", "o1") == 0
    settle(c, 6)
    assert not c.mon.osdmap.pools[pid].has_flag(FLAG_FULL_QUOTA)
    assert cl.write_full("q", "o4", b"x") == 0


def test_pool_quota_bytes():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("q", size=2, pg_num=8)
    cl = c.client("client.q")
    c.mon.set_pool_quota("q", max_bytes=1000)
    c.publish()
    assert cl.write_full("q", "big", b"z" * 1200) == 0
    settle(c, 6)
    assert cl.write_full("q", "more", b"y") == -122
    # reads still work on a quota-full pool
    assert cl.read("q", "big")[:1] == b"z"


def test_cluster_full_ratio_blocks_writes():
    old = g_conf.get_val("osd_capacity_bytes")
    g_conf.set_val("osd_capacity_bytes", 10_000)
    try:
        c = MiniCluster(n_osds=3)
        c.create_replicated_pool("d", size=2, pg_num=8)
        cl = c.client("client.f")
        assert cl.write_full("d", "small", b"a" * 100) == 0
        settle(c, 6)
        assert not (c.mon.osdmap.flags & CEPH_OSDMAP_FULL)
        # push one OSD past 95% of its 10k capacity
        cl.write_full("d", "huge", b"b" * 20_000)
        settle(c, 6)
        assert c.mon.osdmap.flags & CEPH_OSDMAP_FULL
        assert "OSD_FULL" in c.mgr.status()["health_checks"]
        assert cl.write_full("d", "nope", b"c") == -28   # ENOSPC
        assert cl.read("d", "small") == b"a" * 100       # reads fine
        # deleting the hog clears the flag and unblocks writes
        assert cl.remove("d", "huge") == 0
        settle(c, 8)
        assert not (c.mon.osdmap.flags & CEPH_OSDMAP_FULL)
        assert cl.write_full("d", "ok-again", b"d") == 0
    finally:
        g_conf.set_val("osd_capacity_bytes", old)


def test_nearfull_health_warning():
    old = g_conf.get_val("osd_capacity_bytes")
    g_conf.set_val("osd_capacity_bytes", 10_000)
    try:
        c = MiniCluster(n_osds=3)
        c.create_replicated_pool("d", size=2, pg_num=8)
        cl = c.client("client.n")
        cl.write_full("d", "mid", b"m" * 9_000)          # ~90%: nearfull
        settle(c, 6)
        assert c.mon.osdmap.flags & CEPH_OSDMAP_NEARFULL
        assert not (c.mon.osdmap.flags & CEPH_OSDMAP_FULL)
        assert "OSD_NEARFULL" in c.mgr.status()["health_checks"]
        assert cl.write_full("d", "still-ok", b"x") == 0  # warn, not block
    finally:
        g_conf.set_val("osd_capacity_bytes", old)


def test_stale_upmaps_removed():
    c = MiniCluster(n_osds=5)
    c.create_replicated_pool("u", size=2, pg_num=8)
    pid = c.mon.osdmap.lookup_pg_pool_name("u")
    # a valid upmap entry survives publishes
    c.mon.osdmap.pg_upmap_items[pg_t(pid, 1)] = [(0, 3)]
    c.mon._topology_dirty = True
    c.publish()
    assert pg_t(pid, 1) in c.mon.osdmap.pg_upmap_items
    # an entry citing a nonexistent OSD is dropped at the next publish
    c.mon.osdmap.pg_upmap_items[pg_t(pid, 2)] = [(0, 97)]
    c.mon.osdmap.pg_upmap[pg_t(pid, 3)] = [98, 99]
    c.mon._topology_dirty = True
    c.publish()
    assert pg_t(pid, 2) not in c.mon.osdmap.pg_upmap_items
    assert pg_t(pid, 3) not in c.mon.osdmap.pg_upmap
    assert pg_t(pid, 1) in c.mon.osdmap.pg_upmap_items
    # entries for a deleted pool's pgs go too
    c.mon.osdmap.pg_upmap_items[pg_t(pid + 77, 0)] = [(0, 1)]
    c.mon._topology_dirty = True
    c.publish()
    assert pg_t(pid + 77, 0) not in c.mon.osdmap.pg_upmap_items
