"""AWS signature v4 at the rgw HTTP boundary (rgw_auth_s3.cc's
AWS4-HMAC-SHA256 header flavor): canonical request over signed
headers + credential-scope key chain, payload-hash verification, and
the same ACL enforcement as v2-signed requests.
"""
import hashlib

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.rgw import S3Frontend
from ceph_tpu.rgw.gateway import RGWLite
from ceph_tpu.rgw.http import sign_v4


@pytest.fixture()
def fe():
    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("rgw.meta", size=3, pg_num=8)
    c.create_replicated_pool("rgw.data", size=3, pg_num=8)
    g = RGWLite(c.client("client.rgw"), "rgw.meta", "rgw.data")
    alice = g.create_user("alice", "Alice")
    bob = g.create_user("bob", "Bob")
    return S3Frontend(g), alice, bob


def v4req(fe, user, method, path, body=b"", query=None, headers=None,
          unsigned=False, tamper_body=None):
    hdrs = dict(headers or {})
    hdrs.setdefault("Host", "s3.local")
    hdrs["Authorization"] = sign_v4(
        user["access_key"], user["secret_key"], method, path,
        hdrs, query or {}, body, unsigned_payload=unsigned)
    sent = tamper_body if tamper_body is not None else body
    return fe.handle(method, path, hdrs, sent, query or {})


def test_v4_round_trip(fe):
    front, alice, _ = fe
    assert v4req(front, alice, "PUT", "/b")[0] == 200
    assert v4req(front, alice, "PUT", "/b/k", b"payload")[0] == 200
    st, _, body = v4req(front, alice, "GET", "/b/k")
    assert (st, body) == (200, b"payload")
    # subresource + query participate in the canonical request
    st, _, body = v4req(front, alice, "GET", "/b",
                        query={"versioning": ""})
    assert st == 200 and b"VersioningConfiguration" in body


def test_v4_unsigned_payload(fe):
    front, alice, _ = fe
    assert v4req(front, alice, "PUT", "/b")[0] == 200
    assert v4req(front, alice, "PUT", "/b/u", b"data",
                 unsigned=True)[0] == 200


def test_v4_rejects_tampering(fe):
    front, alice, bob = fe
    assert v4req(front, alice, "PUT", "/b")[0] == 200
    # body swapped after signing: payload hash mismatch
    st, _, _ = v4req(front, alice, "PUT", "/b/k", b"good",
                     tamper_body=b"evil")
    assert st == 403
    # signature from the wrong secret
    fake = dict(alice)
    fake["secret_key"] = "not-the-secret"
    assert v4req(front, fake, "GET", "/b")[0] == 403
    # malformed credential scope
    st, _, _ = front.handle("GET", "/b", {
        "Host": "s3.local",
        "x-amz-date": "20260101T000000Z",
        "Authorization": "AWS4-HMAC-SHA256 Credential=zzz, "
                         "SignedHeaders=host, Signature=00"}, b"", {})
    assert st == 403


def test_v4_acl_enforced_same_as_v2(fe):
    front, alice, bob = fe
    assert v4req(front, alice, "PUT", "/priv")[0] == 200
    assert v4req(front, alice, "PUT", "/priv/doc", b"x")[0] == 200
    assert v4req(front, bob, "GET", "/priv/doc")[0] == 403
    # public-read opens GET for bob's correctly-signed v4 request
    acl = (b'<AccessControlPolicy><Owner><ID>alice</ID></Owner>'
           b'<AccessControlList><Grant><Grantee '
           b'xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance" '
           b'xsi:type="Group"><URI>http://acs.amazonaws.com/groups/'
           b'global/AllUsers</URI></Grantee>'
           b'<Permission>READ</Permission></Grant>'
           b'<Grant><Grantee xsi:type="CanonicalUser" '
           b'xmlns:xsi="http://www.w3.org/2001/XMLSchema-instance">'
           b'<ID>alice</ID></Grantee>'
           b'<Permission>FULL_CONTROL</Permission></Grant>'
           b'</AccessControlList></AccessControlPolicy>')
    st, _, out = v4req(front, alice, "PUT", "/priv",
                       body=acl, query={"acl": ""})
    assert st == 200, out
    assert v4req(front, bob, "GET", "/priv/doc")[0] == 200
    assert v4req(front, bob, "PUT", "/priv/doc", b"y")[0] == 403


def test_v4_streaming_payload_refused(fe):
    """Chunked uploads would need per-chunk verification (the
    reference's AWSv4ComplMulti); accepting them unverified would be
    an integrity hole, so the frontend refuses the marker."""
    front, alice, _ = fe
    assert v4req(front, alice, "PUT", "/b")[0] == 200
    hdrs = {"Host": "s3.local",
            "x-amz-content-sha256": "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"}
    hdrs["Authorization"] = sign_v4(
        alice["access_key"], alice["secret_key"], "PUT", "/b/s",
        hdrs, {}, b"")
    st, _, _ = front.handle("PUT", "/b/s", hdrs, b"tampered", {})
    assert st == 403


def test_v4_content_sha256_mismatch_header(fe):
    front, alice, _ = fe
    assert v4req(front, alice, "PUT", "/b")[0] == 200
    # a signed-but-wrong x-amz-content-sha256 fails even though the
    # signature over it is internally consistent
    hdrs = {"Host": "s3.local",
            "x-amz-content-sha256": hashlib.sha256(b"other").hexdigest()}
    hdrs["Authorization"] = sign_v4(
        alice["access_key"], alice["secret_key"], "PUT", "/b/k",
        hdrs, {}, b"other")
    st, _, _ = front.handle("PUT", "/b/k", hdrs, b"real", {})
    assert st == 403
