"""ShardedOpWQ + mClock QoS arbitration."""
from ceph_tpu.common.work_queue import (
    CLASS_CLIENT, CLASS_RECOVERY, CLASS_SCRUB, MClockQueue, ShardedOpWQ,
)


def test_per_pg_fifo_order_preserved():
    wq = ShardedOpWQ(n_shards=4)
    for i in range(20):
        wq.enqueue((1, i % 3), CLASS_CLIENT, (i % 3, i))
    seen = []
    wq.drain(seen.append)
    assert len(seen) == 20
    for pg in range(3):
        ours = [i for p, i in seen if p == pg]
        assert ours == sorted(ours)          # FIFO within one PG


def test_mclock_weight_sharing_under_burst():
    q = MClockQueue({CLASS_CLIENT: (0.0, 400.0, 0.0),
                     CLASS_RECOVERY: (0.0, 100.0, 0.0)})
    for i in range(100):
        q.enqueue(CLASS_CLIENT, ("c", i))
        q.enqueue(CLASS_RECOVERY, ("r", i))
    first_50 = [q.dequeue()[0] for _ in range(50)]
    # 4:1 weights -> clients dominate the early drain
    assert first_50.count("c") >= 35
    # nothing is starved forever: everything eventually drains
    rest = [q.dequeue() for _ in range(150)]
    assert all(x is not None for x in rest)
    assert q.dequeue() is None


def test_mclock_reservation_precedence():
    # scrub has a reservation; clients have all the weight.  Under a
    # long burst the reservation still gets its guaranteed trickle.
    q = MClockQueue({CLASS_CLIENT: (0.0, 1000.0, 0.0),
                     CLASS_SCRUB: (100.0, 1.0, 0.0)})
    for i in range(200):
        q.enqueue(CLASS_CLIENT, ("c", i))
    for i in range(20):
        q.enqueue(CLASS_SCRUB, ("s", i))
    first_100 = [q.dequeue()[0] for _ in range(100)]
    assert first_100.count("s") >= 5


def test_mclock_limit_caps_class():
    q = MClockQueue({CLASS_CLIENT: (0.0, 10.0, 0.0),
                     CLASS_RECOVERY: (0.0, 1000.0, 20.0)})
    for i in range(100):
        q.enqueue(CLASS_CLIENT, ("c", i))
        q.enqueue(CLASS_RECOVERY, ("r", i))
    first_100 = [q.dequeue()[0] for _ in range(100)]
    # despite recovery's huge weight, its limit (20/1000 per vtick)
    # keeps it a small fraction of the drain
    assert first_100.count("r") <= 30


def test_osd_ops_flow_through_the_queue():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("wq", size=3, pg_num=8)
    cl = c.client("client.wq")
    for i in range(10):
        cl.write_full("wq", f"o{i}", bytes([i]) * 100)
    for i in range(10):
        assert cl.read("wq", f"o{i}") == bytes([i]) * 100
    # the queue is empty after the pump settles
    assert all(len(o.op_wq) == 0 for o in c.osds.values())


def test_idle_class_cannot_cash_unbounded_deficit():
    """A class idle for thousands of vticks must not monopolize the
    queue when it wakes (dmclock tag clamping on idle->active)."""
    q = MClockQueue({CLASS_CLIENT: (0.0, 400.0, 0.0),
                     CLASS_SCRUB: (100.0, 1.0, 0.0)})
    # run the clock forward with client-only traffic
    for i in range(5000):
        q.enqueue(CLASS_CLIENT, ("c", i))
    for _ in range(5000):
        q.dequeue()
    # scrub wakes after a long idle next to a fresh client burst
    for i in range(200):
        q.enqueue(CLASS_CLIENT, ("c2", i))
    for i in range(200):
        q.enqueue(CLASS_SCRUB, ("s", i))
    first_50 = [q.dequeue()[0] for _ in range(50)]
    # without clamping, scrub's phantom deficit serves ~all of these
    assert first_50.count("s") <= 25, first_50.count("s")


def test_op_pq_state_admin_command():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=3)
    c.create_replicated_pool("pq", size=3, pg_num=4)
    cl = c.client("client.pq")
    cl.write_full("pq", "o", b"x")
    out = c.admin_socket.execute("dump_op_pq_state")
    assert "osd.0" in out
    shard0 = out["osd.0"]["shard_0"]
    assert "vclock" in shard0 and "queued" in shard0
    # the dump must reflect REAL activity: the write above flowed
    # through some shard's arbiter, advancing its virtual clock
    assert any(sh["vclock"] > 0
               for osd in out.values() for sh in osd.values())
