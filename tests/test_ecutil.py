"""ECUtil: batched striping equals the per-stripe reference loop; HashInfo.

The batched (S, k, C) device path must produce the same shard bytes as
looping ec_impl.encode stripe by stripe (ECUtil.cc:120-159 semantics).
"""
import numpy as np
import pytest

from ceph_tpu.ec import plugin_registry
from ceph_tpu.osd import (
    HashInfo, ecutil_decode, ecutil_decode_concat, ecutil_encode,
    stripe_info_t,
)
from ceph_tpu.utils.crc32c import crc32c, crc32c_sw

K, M, C = 4, 2, 512
SINFO = stripe_info_t(K, K * C)


def codecs():
    host = plugin_registry.factory("isa", {"k": str(K), "m": str(M),
                                           "backend": "host"})
    tpu = plugin_registry.factory("tpu", {"k": str(K), "m": str(M)})
    return host, tpu


def payload(stripes=8, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=stripes * K * C, dtype=np.uint8)


def test_stripe_info_math():
    si = stripe_info_t(4, 4096)
    assert si.get_chunk_size() == 1024
    assert si.logical_to_prev_stripe_offset(5000) == 4096
    assert si.logical_to_next_stripe_offset(5000) == 8192
    assert si.logical_to_next_stripe_offset(8192) == 8192
    assert si.aligned_logical_offset_to_chunk_offset(8192) == 2048
    assert si.aligned_chunk_offset_to_logical_offset(2048) == 8192
    assert si.offset_len_to_stripe_bounds(5000, 2000) == (4096, 4096)


def test_batched_encode_equals_stripe_loop():
    host, tpu = codecs()
    data = payload()
    want = set(range(K + M))
    out_host = ecutil_encode(SINFO, host, data, want)
    out_tpu = ecutil_encode(SINFO, tpu, data, want)
    assert set(out_host) == set(out_tpu) == want
    for i in want:
        np.testing.assert_array_equal(out_host[i], out_tpu[i])
        assert len(out_host[i]) == 8 * C


def test_decode_concat_roundtrip():
    _, tpu = codecs()
    data = payload(stripes=5)
    shards = ecutil_encode(SINFO, tpu, data, set(range(K + M)))
    # drop two shards, rebuild the logical payload
    have = {i: shards[i] for i in (0, 2, 4, 5)}
    got = ecutil_decode_concat(SINFO, tpu, have)
    np.testing.assert_array_equal(got, data)


def test_decode_specific_shards_for_recovery():
    host, tpu = codecs()
    data = payload(stripes=6, seed=2)
    shards = ecutil_encode(SINFO, host, data, set(range(K + M)))
    have = {i: shards[i] for i in range(K + M) if i not in (1, 5)}
    rec = ecutil_decode(SINFO, tpu, have, [1, 5])
    np.testing.assert_array_equal(rec[1], shards[1])
    np.testing.assert_array_equal(rec[5], shards[5])


def test_empty_payload():
    _, tpu = codecs()
    assert ecutil_encode(SINFO, tpu, b"", set(range(K + M))) == {}


def test_hashinfo_cumulative():
    hi = HashInfo(K + M)
    shards1 = {i: np.full(64, i, dtype=np.uint8) for i in range(K + M)}
    shards2 = {i: np.full(64, i + 1, dtype=np.uint8) for i in range(K + M)}
    hi.append(0, shards1)
    assert hi.get_total_chunk_size() == 64
    h_after_1 = hi.get_chunk_hash(0)
    hi.append(64, shards2)
    assert hi.get_total_chunk_size() == 128
    # cumulative: equals hashing the concatenation in one go
    both = np.concatenate([shards1[0], shards2[0]])
    assert hi.get_chunk_hash(0) == crc32c(both)
    assert hi.get_chunk_hash(0) != h_after_1
    # wrong old_size trips the append guard
    with pytest.raises(AssertionError):
        hi.append(5, shards1)


def test_crc32c_native_matches_software():
    data = np.arange(1000, dtype=np.uint8)
    assert crc32c(data) == crc32c_sw(data)
    assert crc32c(b"") == 0xFFFFFFFF
