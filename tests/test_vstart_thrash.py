"""Mon + MDS thrashing over real sockets (qa/tasks/mon_thrash.py):
kill -> recover -> kill a DIFFERENT mon across iterations with client
writes continuing throughout, and the compound mon-leader +
active-MDS kill.  All waits are EVENT waits — polls on map/fsmap
state, never bare sleeps sized to wall clocks.
"""
import time

import numpy as np
import pytest

from ceph_tpu.cephfs.mds_client import RemoteCephFS
from ceph_tpu.vstart import ProcessCluster


def _write_retrying(c, cl, pool, oid, data, timeout=150.0):
    """write_full with BOTH failure shapes retried (it RETURNS
    negative codes like -110 rather than raising; see round-4's
    retry-shape lesson) — the 'writes continue throughout' probe."""
    end = time.monotonic() + timeout
    while True:
        try:
            r = cl.write_full(pool, oid, data)
        except IOError:
            r = -1
        if r == 0:
            return
        if time.monotonic() > end:
            raise AssertionError(f"write {oid} never landed: {r}")
        c.pump_for(0.7)


def _wait_mon_answers(c, mon_name, timeout=150.0):
    """Event wait: the named mon answers a read-only wire command
    from its replicated state (proof it rejoined and synced)."""
    end = time.monotonic() + timeout
    last = None
    while time.monotonic() < end:
        cl = c.client(f"client.probe{int(time.monotonic()*1000)%97}",
                      mon_name=mon_name)
        try:
            st = cl.mon_command("fs_status")
            if st is not None:
                return
        except (IOError, ValueError) as e:
            last = e
        c.pump_for(0.7)
    raise AssertionError(f"{mon_name} never answered: {last!r}")


@pytest.fixture(scope="module")
def mon_cluster():
    c = ProcessCluster(
        n_osds=3, n_mons=3, mon_grace=8.0,
        pool={"name": "p", "type": "replicated", "size": 3,
              "pg_num": 4},
        client_names=tuple(["client.x"]
                           + [f"client.probe{i}" for i in range(97)]),
        heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


# loadflaky marker DROPPED (PR 12): the election-timing
# sensitivity was root-caused to starved-tick grace reads in
# Monitor.tick (docs/ANALYSIS.md) and fixed; two consecutive
# green full-suite rounds confirmed, zero auto-reruns
def test_mon_thrash_kill_revive_rotation(mon_cluster):
    """Three rounds: SIGKILL a different mon each time (leader
    included), writes continuing, then REVIVE it and event-wait for
    it to answer commands again before the next kill — the reference
    mon_thrash loop's kill/revive cadence."""
    c = mon_cluster
    cl = c.client("client.x", mon_name="mon.1")
    c.wait_healthy(cl)
    rng = np.random.default_rng(4)
    payloads = {}
    _write_retrying(c, cl, "p", "seed",
                    rng.integers(0, 256, 4096,
                                 dtype=np.uint8).tobytes())
    for i, victim in enumerate([0, 1, 2]):
        c.kill_mon(victim)
        # writes keep landing with the victim dead (quorum 2/3);
        # survivors relay/elect as needed
        data = rng.integers(0, 256, 4096, dtype=np.uint8).tobytes()
        payloads[f"obj{i}"] = data
        live = (victim + 1) % 3
        wcl = c.client(f"client.probe{i}",
                       mon_name=f"mon.{live}")
        _write_retrying(c, wcl, "p", f"obj{i}", data)
        assert wcl.read("p", f"obj{i}") == data
        # REVIVE: fresh empty process on the same port; it must sync
        # the committed history and answer commands itself
        c.restart_mon(victim)
        _wait_mon_answers(c, f"mon.{victim}")
    # everything written during the thrash is still there, readable
    # through a client bound to the mon that died FIRST
    final = c.client("client.probe90", mon_name="mon.0")
    for oid, data in payloads.items():
        assert final.read("p", oid) == data


@pytest.fixture(scope="module")
def fs_cluster():
    c = ProcessCluster(
        n_osds=3, n_mons=3, n_mds=2, mon_grace=6.0, mds_grace=4.0,
        client_names=("client.x", "client.y"),
        heartbeat_interval=1.0, heartbeat_grace=4.0)
    yield c
    c.close()


def test_mon_leader_and_active_mds_die_together(fs_cluster):
    """The compounding corner VERDICT r4 named: the mon leader and
    the active MDS SIGKILLed in the same instant.  Beacon liveness is
    leader-local RAM, so the new leader restarts the grace window —
    failover takes mon-election + full MDS grace — but the standby
    MUST eventually take rank 0 and serve the journaled namespace."""
    c = fs_cluster
    cl = c.client("client.x", mon_name="mon.1")
    c.wait_healthy(cl)
    fs = RemoteCephFS(cl, mds_name=None)
    end = time.monotonic() + 240.0
    done_mkdir = False
    while True:                      # first ops ride the mds boot
        try:
            if not done_mkdir:
                fs.mkdir("/d")
                done_mkdir = True
            fs.write("/d/f", b"before-the-storm", 0)
            break
        except IOError:
            if time.monotonic() > end:
                raise
            c.pump_for(1.0)
    st = cl.mon_command("fs_status")
    active = st["ranks"]["0"]
    # the compound kill: mon leader + active MDS in the same breath
    c.kill_mon(0)
    c.kill_mds(int(active.split(".")[1]))
    # event wait on the REPLICATED fsmap: a new mon leader must form
    # quorum, re-learn beacons, expire the dead active, and promote
    # the standby into rank 0
    end = time.monotonic() + 240.0
    while True:
        try:
            st = cl.mon_command("fs_status")
            holder = (st or {}).get("ranks", {}).get("0")
            if holder and holder != active:
                break
        except (IOError, ValueError):
            pass
        if time.monotonic() > end:
            raise AssertionError(f"rank 0 never failed over: {st}")
        c.pump_for(1.0)
    # the promoted standby replayed the journal; the namespace and
    # data survive, and new work proceeds
    fs2 = RemoteCephFS(c.client("client.y", mon_name="mon.2"),
                       mds_name=None)
    end = time.monotonic() + 150.0
    while True:
        try:
            assert fs2.read("/d/f") == b"before-the-storm"
            break
        except IOError:
            if time.monotonic() > end:
                raise
            c.pump_for(1.0)
    fs2.write("/d/g", b"after", 0)
    assert fs2.read("/d/g") == b"after"
