"""ceph-dencoder: the encoding non-regression surface
(src/test/encoding/readable.sh + ceph_dencoder.cc roles) — every
registered type round-trips encode→decode→re-encode byte-identical,
encodes deterministically, and dumps valid json; the command-stream
CLI itself is exercised end-to-end with import/export files.
"""
import json
import io
import os
from contextlib import redirect_stdout

import pytest

from ceph_tpu.tools.dencoder import _registry, main

REG = _registry()


@pytest.mark.parametrize("name", sorted(REG))
def test_round_trip_identity(name):
    h = REG[name]
    tests = h.tests()
    assert tests, f"{name} has no generated test instances"
    for t in tests:
        a = h.encode(t)
        assert isinstance(a, bytes) and a
        b = h.encode(h.decode(a))
        assert a == b, f"{name} re-encode differs"


@pytest.mark.parametrize("name", sorted(REG))
def test_dump_json_valid(name):
    h = REG[name]
    for t in h.tests():
        json.dumps(h.to_jsonable(t), default=repr)


def _run(*args):
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(list(args))
    return rc, buf.getvalue()


def test_cli_flow(tmp_path):
    rc, out = _run("list_types")
    assert rc == 0 and "MOSDOp" in out and "OSDMap" in out

    # select_test -> encode -> export -> import -> decode -> dump_json
    enc = str(tmp_path / "enc")
    rc, _ = _run("type", "MOSDOp", "select_test", "2", "encode",
                 "export", enc)
    assert rc == 0 and os.path.getsize(enc) > 0
    rc, out = _run("type", "MOSDOp", "import", enc, "decode",
                   "dump_json")
    assert rc == 0
    doc = json.loads(out)
    assert doc["src"] == "t"            # the synth-filled instance

    rc, out = _run("type", "CrushWrapper", "select_test", "1",
                   "encode", "decode", "dump_json")
    assert rc == 0 and "buckets" in json.loads(out)

    rc, out = _run("type", "MMonPaxos", "is_deterministic")
    assert rc == 0 and "deterministic" in out

    # error contracts
    assert _run("type", "NoSuchType")[0] == 1
    assert _run("decode")[0] == 1
    assert _run("type", "MOSDOp", "decode")[0] == 1
    assert _run("bogus-command")[0] == 1


def test_copy_preserves_encoding(tmp_path):
    before = str(tmp_path / "before")
    after = str(tmp_path / "after")
    rc, _ = _run("type", "MonMap", "select_test", "1", "encode",
                 "export", before, "copy", "encode", "export", after)
    assert rc == 0
    a, b = open(before, "rb").read(), open(after, "rb").read()
    assert a and a == b                 # the copy re-encodes identically


def test_decode_rejects_wrong_type(tmp_path):
    enc = str(tmp_path / "paxos")
    assert _run("type", "MMonPaxos", "select_test", "1", "encode",
                "export", enc)[0] == 0
    rc, _ = _run("type", "MOSDOp", "import", enc, "decode")
    assert rc == 1

    # malformed argument contracts exit 1, not a traceback
    assert _run("type", "MOSDOp", "import")[0] == 1
    assert _run("type", "MOSDOp", "select_test", "foo")[0] == 1
    assert _run("skip", "abc")[0] == 1
