"""Dispatch scheduler: cross-PG dynamic batching of EC codec work.

The acceptance gates of the dispatch PR:

- window=0 (the default) is an EXACT passthrough — same entry points,
  byte-identical output, zero device syncs added.
- with ANY window/batch_max setting, coalesced outputs are
  byte-identical to the passthrough path across randomized codec
  signature mixes submitted from >= 8 threads, including mid-batch
  decode failures (fail-fast isolation).
- the bounded queue backpressures by force-flushing, never by dropping.
- the observability surfaces exist: batch_dispatch span with the
  coalesced requests as children, batch-occupancy histogram, `dispatch
  dump` on the admin socket, dispatch counters.
"""
import threading

import numpy as np
import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.dispatch import (bucket_chunk_size, dispatch_perf_counters,
                               g_dispatcher)
from ceph_tpu.dispatch.scheduler import (l_dispatch_backpressure,
                                         l_dispatch_coalesced)
from ceph_tpu.ec.isa import ErasureCodeIsa
from ceph_tpu.ec.jerasure import ErasureCodeJerasure
from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
from ceph_tpu.osd.ecutil import (decode as eu_decode,
                                 decode_concat as eu_decode_concat,
                                 encode as eu_encode, stripe_info_t)
from ceph_tpu.trace import g_perf_histograms, g_tracer


@pytest.fixture
def dispatch_conf():
    """Every test leaves the dispatcher drained and the options at
    their defaults."""
    yield
    g_dispatcher.flush()
    for name in ("ec_dispatch_batch_max", "ec_dispatch_batch_window_us",
                 "ec_dispatch_queue_max"):
        g_conf.rm_val(name)
    g_tracer.enable(False)
    g_tracer.collector.clear()


def _mk_impl(plugin, k, m, technique, backend="host"):
    impl = plugin()
    prof = {"k": str(k), "m": str(m), "technique": technique,
            "backend": backend}
    impl.init(prof)
    return impl


# a randomized signature mix: (plugin, k, m, technique, chunk sizes)
MIX = [
    (ErasureCodeTpu, 4, 2, "reed_sol_van"),
    (ErasureCodeTpu, 8, 4, "reed_sol_van"),
    (ErasureCodeIsa, 4, 2, "reed_sol_van"),      # groups WITH tpu 4+2
    (ErasureCodeIsa, 3, 2, "cauchy"),
    (ErasureCodeJerasure, 4, 2, "reed_sol_van"),  # own family
]


def _random_requests(rng, n, backend="host"):
    """n randomized encode/decode/reconstruct requests with oracles."""
    impls = [_mk_impl(p, k, m, t, backend) for p, k, m, t in MIX]
    reqs = []
    for _ in range(n):
        idx = rng.integers(0, len(impls))
        impl = impls[idx]
        k, m = impl.k, impl.m
        chunk = int(rng.choice([512, 1024, 1536, 2048, 4096]))
        sinfo = stripe_info_t(k, k * chunk)
        stripes = int(rng.integers(1, 5))
        data = rng.integers(0, 256, size=stripes * k * chunk,
                            dtype=np.uint8)
        kind = rng.choice(["encode", "decode_concat", "decode",
                           "decode_fail"])
        want = set(range(k + m))
        if kind == "encode":
            reqs.append(("encode", sinfo, impl, data, want, None))
            continue
        shards = eu_encode(sinfo, impl, data, want)
        if kind == "decode_fail":
            # under-provisioned survivor set: must raise IOError for
            # THIS request only
            avail = sorted(rng.choice(k + m, size=k - 1, replace=False))
            chunks = {int(i): shards[int(i)] for i in avail}
            reqs.append(("decode_fail", sinfo, impl, chunks, None, None))
            continue
        avail = sorted(rng.choice(k + m, size=k, replace=False))
        chunks = {int(i): shards[int(i)] for i in avail}
        if kind == "decode_concat":
            reqs.append(("decode_concat", sinfo, impl, chunks, None,
                         None))
        else:
            lost = sorted(set(range(k + m)) - set(chunks))
            need = list(lost[:max(1, len(lost) // 2)]) or [0]
            reqs.append(("decode", sinfo, impl, chunks, None, need))
    return reqs


def _run_via_dispatcher(spec):
    kind, sinfo, impl, payload, want, need = spec
    if kind == "encode":
        return g_dispatcher.encode(sinfo, impl, payload, want)
    if kind in ("decode_concat", "decode_fail"):
        return g_dispatcher.decode_concat(sinfo, impl, payload)
    return g_dispatcher.decode(sinfo, impl, payload, need)


def _oracle(spec):
    kind, sinfo, impl, payload, want, need = spec
    if kind == "encode":
        return eu_encode(sinfo, impl, payload, want)
    if kind in ("decode_concat", "decode_fail"):
        return eu_decode_concat(sinfo, impl, payload)
    return eu_decode(sinfo, impl, payload, need)


def _same(kind, a, b):
    if kind == "encode" or kind == "decode":
        assert sorted(a) == sorted(b)
        for i in a:
            assert a[i].tobytes() == b[i].tobytes(), f"shard {i} differs"
    else:
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()


# ---- parity ----------------------------------------------------------------
def test_window_zero_is_exact_passthrough(dispatch_conf):
    rng = np.random.default_rng(7)
    for spec in _random_requests(rng, 24):
        kind = spec[0]
        if kind == "decode_fail":
            with pytest.raises(IOError):
                _run_via_dispatcher(spec)
            continue
        _same(kind, _run_via_dispatcher(spec), _oracle(spec))


@pytest.mark.parametrize("window_us,batch_max", [(50_000, 4),
                                                 (10_000_000, 64)])
def test_threaded_stress_byte_identical(dispatch_conf, window_us,
                                        batch_max):
    """>= 8 threads submit randomized (k, m, technique, size) mixes —
    every output must match the window-0 passthrough oracle
    byte-for-byte, and under-provisioned decodes must fail alone
    without poisoning their batchmates."""
    g_conf.set_val("ec_dispatch_batch_window_us", window_us)
    g_conf.set_val("ec_dispatch_batch_max", batch_max)
    rng = np.random.default_rng(1234)
    per_thread = 12
    n_threads = 8
    specs = [_random_requests(np.random.default_rng(100 + t), per_thread)
             for t in range(n_threads)]
    results = [[None] * per_thread for _ in range(n_threads)]
    errors = [[None] * per_thread for _ in range(n_threads)]

    def worker(t):
        for i, spec in enumerate(specs[t]):
            try:
                results[t][i] = _run_via_dispatcher(spec)
            except Exception as e:        # noqa: BLE001 — recorded
                errors[t][i] = e

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    for t in range(n_threads):
        for i, spec in enumerate(specs[t]):
            kind = spec[0]
            if kind == "decode_fail":
                assert isinstance(errors[t][i], IOError), \
                    f"thread {t} req {i}: expected isolated IOError, " \
                    f"got {errors[t][i]!r}"
                continue
            assert errors[t][i] is None, \
                f"thread {t} req {i} raised {errors[t][i]!r}"
            _same(kind, results[t][i], _oracle(spec))
    assert g_dispatcher.dump()["pending"] == 0


def test_cross_plugin_coalescing(dispatch_conf):
    """tpu and isa instances of the same (technique, k, m) share the
    isa-matrix signature family and ride one batch."""
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    tpu = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    isa = _mk_impl(ErasureCodeIsa, 4, 2, "reed_sol_van")
    assert tpu.codec_signature() == isa.codec_signature()
    rng = np.random.default_rng(3)
    s1 = stripe_info_t(4, 4 * 1024)
    s2 = stripe_info_t(4, 4 * 768)   # same pow2 bucket (1024)
    assert bucket_chunk_size(768) == 1024
    d1 = rng.integers(0, 256, size=2 * 4 * 1024, dtype=np.uint8)
    d2 = rng.integers(0, 256, size=3 * 4 * 768, dtype=np.uint8)
    want = set(range(6))
    before = dispatch_perf_counters().get(l_dispatch_coalesced)
    f1 = g_dispatcher.submit_encode(s1, tpu, d1, want)
    f2 = g_dispatcher.submit_encode(s2, isa, d2, want)
    r1, r2 = f1.result(), f2.result()
    _same("encode", r1, eu_encode(s1, tpu, d1, want))
    _same("encode", r2, eu_encode(s2, isa, d2, want))
    assert dispatch_perf_counters().get(l_dispatch_coalesced) \
        == before + 2, "the two requests did not share a flush"


# ---- queue mechanics -------------------------------------------------------
def test_backpressure_force_flushes(dispatch_conf):
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 1000)
    g_conf.set_val("ec_dispatch_queue_max", 4)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 512)
    rng = np.random.default_rng(4)
    before = dispatch_perf_counters().get(l_dispatch_backpressure)
    futs = []
    for _ in range(6):
        d = rng.integers(0, 256, size=4 * 512, dtype=np.uint8)
        futs.append((d, g_dispatcher.submit_encode(
            sinfo, impl, d, set(range(6)))))
    assert dispatch_perf_counters().get(l_dispatch_backpressure) > before
    assert g_dispatcher.dump()["pending"] <= 4
    for d, f in futs:
        _same("encode", f.result(),
              eu_encode(sinfo, impl, d, set(range(6))))


def test_window_expiry_poll_flushes(dispatch_conf):
    g_conf.set_val("ec_dispatch_batch_window_us", 1)   # expires at once
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 512)
    d = (np.arange(4 * 512) % 256).astype(np.uint8)
    f = g_dispatcher.submit_encode(sinfo, impl, d, set(range(6)))
    import time
    time.sleep(0.002)
    g_dispatcher.poll()
    assert f.done()
    _same("encode", f.result(), eu_encode(sinfo, impl, d, set(range(6))))


def test_unbatchable_codec_passes_through(dispatch_conf):
    """A codec that does not opt in (dispatch_batchable False) executes
    inline even with a window set — correct by construction, never
    grouped or queued."""
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)

    class OpaqueCodec(ErasureCodeIsa):
        dispatch_batchable = False

    impl = OpaqueCodec()
    impl.init({"k": "2", "m": "1", "backend": "host"})
    sinfo = stripe_info_t(2, 2 * 512)
    d = (np.arange(2 * 512) % 256).astype(np.uint8)
    out = g_dispatcher.encode(sinfo, impl, d, set(range(3)))
    _same("encode", out, eu_encode(sinfo, impl, d, set(range(3))))
    # never queued: executed inline, nothing pending even WITHOUT a
    # result() forcing the flush
    f = g_dispatcher.submit_encode(sinfo, impl, d, set(range(3)))
    assert f.done()
    assert g_dispatcher.dump()["pending"] == 0


# ---- observability ---------------------------------------------------------
def test_batch_dispatch_span_children(dispatch_conf):
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_tracer.enable()
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 512)
    rng = np.random.default_rng(5)
    with g_tracer.span("op_root", daemon="test", trace_id=777):
        futs = [g_dispatcher.submit_encode(
            sinfo, impl,
            rng.integers(0, 256, size=4 * 512, dtype=np.uint8),
            set(range(6))) for _ in range(3)]
        for f in futs:
            f.result()
    spans = g_tracer.collector.dump("dispatch")["dispatch"]
    batches = [s for s in spans if s["name"] == "batch_dispatch"]
    assert batches and batches[-1]["tags"]["occupancy"] == 3
    kids = [s for s in spans
            if s["parent_span_id"] == batches[-1]["span_id"]]
    assert len(kids) == 3
    assert all(s["name"] == "batched_req:encode" for s in kids)
    # the children carry the SUBMITTER's trace id, so per-trace dumps
    # surface the coalesced work next to the op that queued it
    assert all(s["trace_id"] == 777 for s in kids)


def test_mid_batch_fallback_counter_and_span_event(dispatch_conf):
    """Robustness-PR satellite: the mid-batch per-request fallback is
    no longer silent — each re-run request bumps the
    `dispatch_fallback` counter AND lands a `dispatch_fallback` event
    on the submitting op's span."""
    from ceph_tpu.dispatch.scheduler import (l_dispatch_fallback_reqs,
                                             l_dispatch_fallbacks)
    from ceph_tpu.fault import g_faults
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_tracer.enable()
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 512)
    rng = np.random.default_rng(21)
    payloads = [rng.integers(0, 256, size=4 * 512, dtype=np.uint8)
                for _ in range(3)]
    pc = dispatch_perf_counters()
    before_req = pc.get(l_dispatch_fallback_reqs)
    before_batch = pc.get(l_dispatch_fallbacks)
    # one-shot batched-call failure: the flush falls back per-request,
    # every request still resolves byte-identically
    g_faults.inject("dispatch.batch", mode="once")
    try:
        with g_tracer.span("op_root", daemon="test",
                           trace_id=888) as root:
            futs = [g_dispatcher.submit_encode(sinfo, impl, p,
                                               set(range(6)))
                    for p in payloads]
            for f, p in zip(futs, payloads):
                _same("encode", f.result(),
                      eu_encode(sinfo, impl, p, set(range(6))))
    finally:
        g_faults.clear()
    assert pc.get(l_dispatch_fallbacks) == before_batch + 1
    assert pc.get(l_dispatch_fallback_reqs) == before_req + 3
    events = [e for e in root.tags.get("events", [])
              if e["event"] == "dispatch_fallback"]
    assert len(events) == 3, \
        "each re-run request must stamp the submitter's span"
    assert all(e["kind"] == "encode" for e in events)
    # the batch span itself carries the fallback marker too
    spans = g_tracer.collector.dump("dispatch")["dispatch"]
    batch = [s for s in spans if s["name"] == "batch_dispatch"][-1]
    assert any(e["event"] == "batch_fallback"
               for e in batch["tags"].get("events", []))


def test_raising_done_callback_does_not_poison_batch(dispatch_conf):
    """concurrent.futures semantics: a consumer callback that raises is
    the consumer's bug — it must not be mistaken for a device failure
    (which would re-execute the whole batch and bump batch_fallbacks)
    and must not block batchmates' resolution."""
    from ceph_tpu.dispatch.scheduler import l_dispatch_fallbacks
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 512)
    rng = np.random.default_rng(9)
    payloads = [rng.integers(0, 256, size=4 * 512, dtype=np.uint8)
                for _ in range(3)]
    before = dispatch_perf_counters().get(l_dispatch_fallbacks)
    futs = [g_dispatcher.submit_encode(sinfo, impl, p, set(range(6)))
            for p in payloads]
    futs[0].add_done_callback(lambda f: 1 / 0)   # consumer bug
    for f, p in zip(futs, payloads):
        _same("encode", f.result(),
              eu_encode(sinfo, impl, p, set(range(6))))
    assert dispatch_perf_counters().get(l_dispatch_fallbacks) == before


def test_occupancy_histogram_and_dump(dispatch_conf):
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    hist = g_perf_histograms.get("dispatch",
                                 "dispatch_batch_occupancy_histogram")
    before = hist.total_count
    impl = _mk_impl(ErasureCodeTpu, 4, 2, "reed_sol_van")
    sinfo = stripe_info_t(4, 4 * 512)
    rng = np.random.default_rng(6)
    futs = [g_dispatcher.submit_encode(
        sinfo, impl, rng.integers(0, 256, size=4 * 512, dtype=np.uint8),
        set(range(6))) for _ in range(4)]
    for f in futs:
        f.result()
    assert hist.total_count == before + 1     # one flush of occupancy 4
    d = g_dispatcher.dump()
    assert d["options"]["ec_dispatch_batch_window_us"] == 10_000_000
    assert d["pending"] == 0
    assert d["counters"]["submitted"] > 0
    assert d["occupancy_histogram"]["axes"][0]["name"] \
        == "batch_occupancy"


def test_admin_socket_dispatch_dump(dispatch_conf):
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("dsp", k=3, m=2, pg_num=8)
    cl = c.client("client.dsp")
    assert cl.write_full("dsp", "o1", b"d" * 30000) == 0
    out = c.admin_socket.execute("dispatch dump")
    assert out["counters"]["submitted"] > 0
    assert out["occupancy_histogram"]["count"] > 0
    assert "ec_dispatch_batch_max" in out["options"]
    assert c.admin_socket.execute("dispatch flush") == {"flushed": 0}
    # the dispatch counters render on the mgr's Prometheus surface
    prom = c.admin_socket.execute("prometheus metrics")
    assert "ceph_daemon_dispatch_submitted" in prom
    assert "ceph_dispatch_batch_occupancy_histogram_bucket" in prom


def test_cluster_write_path_batched_parity(dispatch_conf):
    """A mini-cluster write/read cycle with a non-zero window must land
    the same bytes as the default path (single-threaded callers force
    their own flush, so semantics do not change)."""
    from ceph_tpu.cluster import MiniCluster
    g_conf.set_val("ec_dispatch_batch_window_us", 100_000)
    g_conf.set_val("ec_dispatch_batch_max", 8)
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("dspw", k=3, m=2, pg_num=8)
    cl = c.client("client.dspw")
    body = bytes(np.random.default_rng(8).integers(
        0, 256, size=50000, dtype=np.uint8))
    assert cl.write_full("dspw", "obj", body) == 0
    assert cl.read("dspw", "obj") == body


def test_zero_syncs_on_batched_path(dispatch_conf, monkeypatch):
    """PR 2's acceptance gate extended to the batched path: with
    tracing disabled the dispatcher must add zero block_until_ready
    syncs per op, whatever the window/batch settings."""
    import jax
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("dspz", k=3, m=2, pg_num=8)
    cl = c.client("client.dspz")
    cl.write_full("dspz", "warm", b"w" * 20000)       # compile warmup
    g_conf.set_val("ec_dispatch_batch_window_us", 100_000)
    g_conf.set_val("ec_dispatch_batch_max", 8)
    cl.write_full("dspz", "warm2", b"v" * 20000)      # batched-shape warm
    calls = {"n": 0}
    real = jax.block_until_ready

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", counting)
    assert cl.write_full("dspz", "obj", b"x" * 20000) == 0
    assert cl.read("dspz", "obj")[:1] == b"x"
    assert calls["n"] == 0, "dispatcher added a device sync"
