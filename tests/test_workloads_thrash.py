"""Combined client workloads under OSD thrashing (qa/workunits +
qa/tasks Thrasher role): rbd, cephfs and rgw all running against one
cluster while OSDs are killed, revived, and marked out — every layer
must stay consistent through re-peer and recovery.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.cephfs import CephFS
from ceph_tpu.rbd import Image, RBD
from ceph_tpu.rgw import RGWLite

ORDER = 12
OBJ = 1 << ORDER


def settle(c, rounds=8, dt=6.0):
    for _ in range(rounds):
        c.tick(dt=dt)


def test_three_workloads_survive_thrashing():
    c = MiniCluster(n_osds=6)
    for p in ("rbd", "fsmeta", "fsdata", "rgwmeta"):
        c.create_replicated_pool(p, size=3, pg_num=8)
    c.create_ec_pool("rgwdata", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.w")

    rbd = RBD(cl)
    rbd.create("rbd", "vm", 4 * OBJ, ORDER, journaling=False)
    img = Image(cl, "rbd", "vm")
    fs = CephFS(cl, "fsmeta", "fsdata")
    fs.mkfs()
    fs.mkdir("/logs")
    g = RGWLite(cl, "rgwmeta", "rgwdata")
    g.create_user("app")
    g.create_bucket("app", "events")

    expectations = {}
    victim_cycle = [0, 3, 1]
    for gen, victim in enumerate(victim_cycle):
        payload = bytes([65 + gen]) * 512
        img.write(gen * OBJ, payload)
        fs.create(f"/logs/gen{gen}", ORDER)
        fs.write(f"/logs/gen{gen}", payload)
        g.put_object("events", f"e{gen}", payload)
        expectations[gen] = payload

        c.kill_osd(victim)
        settle(c)
        c.mark_osd_out(victim)
        settle(c, rounds=5, dt=2.0)

        # everything written so far reads back while degraded
        for g2, data in expectations.items():
            assert img.read(g2 * OBJ, 512) == data
            assert fs.read(f"/logs/gen{g2}") == data
            assert g.get_object("events", f"e{g2}") == data

        c.revive_osd(victim)
        c.mon.mark_osd_in(victim)
        c.publish()
        settle(c, rounds=5, dt=2.0)

    # final sweep after all thrashing: listings + consistency tools
    assert sorted(fs.listdir("/logs")) == ["gen0", "gen1", "gen2"]
    assert [e["name"] for e in
            g.list_objects("events")["contents"]] == ["e0", "e1", "e2"]
    assert fs.fsck() == {"dangling_remotes": [], "stale_backpointers": [],
                         "orphan_objects": [], "missing_dirs": []}
    assert g.gc() == {"orphan_objects": [], "stale_pending": []}
    assert c.health().startswith("HEALTH")
    # scrub finds nothing to repair
    c.scrub()
    settle(c, rounds=3, dt=2.0)
    for g2, data in expectations.items():
        assert img.read(g2 * OBJ, 512) == data
