"""Combined client workloads under OSD thrashing (qa/workunits +
qa/tasks Thrasher role): rbd, cephfs and rgw all running against one
cluster while OSDs are killed, revived, and marked out — every layer
must stay consistent through re-peer and recovery.
"""
import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.cephfs import CephFS
from ceph_tpu.rbd import Image, RBD
from ceph_tpu.rgw import RGWLite

ORDER = 12
OBJ = 1 << ORDER


def settle(c, rounds=8, dt=6.0):
    for _ in range(rounds):
        c.tick(dt=dt)


def test_three_workloads_survive_thrashing():
    c = MiniCluster(n_osds=6)
    for p in ("rbd", "fsmeta", "fsdata", "rgwmeta"):
        c.create_replicated_pool(p, size=3, pg_num=8)
    c.create_ec_pool("rgwdata", k=2, m=1, plugin="isa", pg_num=8)
    cl = c.client("client.w")

    rbd = RBD(cl)
    rbd.create("rbd", "vm", 4 * OBJ, ORDER, journaling=False)
    img = Image(cl, "rbd", "vm")
    fs = CephFS(cl, "fsmeta", "fsdata")
    fs.mkfs()
    fs.mkdir("/logs")
    g = RGWLite(cl, "rgwmeta", "rgwdata")
    g.create_user("app")
    g.create_bucket("app", "events")

    expectations = {}
    victim_cycle = [0, 3, 1]
    for gen, victim in enumerate(victim_cycle):
        payload = bytes([65 + gen]) * 512
        img.write(gen * OBJ, payload)
        fs.create(f"/logs/gen{gen}", ORDER)
        fs.write(f"/logs/gen{gen}", payload)
        g.put_object("events", f"e{gen}", payload)
        expectations[gen] = payload

        c.kill_osd(victim)
        settle(c)
        c.mark_osd_out(victim)
        settle(c, rounds=5, dt=2.0)

        # everything written so far reads back while degraded
        for g2, data in expectations.items():
            assert img.read(g2 * OBJ, 512) == data
            assert fs.read(f"/logs/gen{g2}") == data
            assert g.get_object("events", f"e{g2}") == data

        c.revive_osd(victim)
        c.mon.mark_osd_in(victim)
        c.publish()
        settle(c, rounds=5, dt=2.0)

    # final sweep after all thrashing: listings + consistency tools
    assert sorted(fs.listdir("/logs")) == ["gen0", "gen1", "gen2"]
    assert [e["name"] for e in
            g.list_objects("events")["contents"]] == ["e0", "e1", "e2"]
    assert fs.fsck() == {"dangling_remotes": [], "stale_backpointers": [],
                         "orphan_objects": [], "missing_dirs": []}
    assert g.gc() == {"orphan_objects": [], "stale_pending": []}
    assert c.health().startswith("HEALTH")
    # scrub finds nothing to repair
    c.scrub()
    settle(c, rounds=3, dt=2.0)
    for g2, data in expectations.items():
        assert img.read(g2 * OBJ, 512) == data


def test_mds_and_versioned_rgw_survive_thrashing():
    """The round-4 tiers under the same thrasher: MDS-mediated cephfs
    (caps + journal) and a VERSIONED rgw bucket keep full histories
    through OSD kill/out/revive cycles, with an MDS crash-replay in
    the middle."""
    from ceph_tpu.cephfs.mds_client import RemoteCephFS
    from ceph_tpu.mds import MDSDaemon
    c = MiniCluster(n_osds=6)
    for p in ("fsmeta", "fsdata", "rgwmeta"):
        c.create_replicated_pool(p, size=3, pg_num=8)
    c.create_ec_pool("rgwdata", k=2, m=1, plugin="isa", pg_num=8)

    mds = MDSDaemon(c.network, c.client("client.mds"), "mds.0",
                    mkfs=True)
    fs = RemoteCephFS(c.client("client.f"))
    fs._drive = lambda: mds.process()
    g = RGWLite(c.client("client.g"), "rgwmeta", "rgwdata")
    g.create_user("app")
    g.create_bucket("app", "b")
    g.put_bucket_versioning("b", "enabled")
    fs.mkdir("/d")

    history = []
    for gen, victim in enumerate([2, 5]):
        payload = bytes([97 + gen]) * 256
        fs.create(f"/d/f{gen}")
        fs.write(f"/d/f{gen}", payload, 0)
        v = g.put_object("b", "doc", payload)     # new VERSION each gen
        history.append((v["vid"], payload))

        c.kill_osd(victim)
        settle(c)
        c.mark_osd_out(victim)
        settle(c, rounds=5, dt=2.0)

        # degraded reads: every fs file and every rgw VERSION
        for g2 in range(gen + 1):
            assert fs.read(f"/d/f{g2}") == bytes([97 + g2]) * 256
        for vid, data in history:
            assert g.get_object("b", "doc", version_id=vid) == data

        if gen == 0:
            # crash the MDS mid-run: a fresh incarnation replays and
            # the same namespace serves on
            mds = MDSDaemon(c.network, c.client("client.mds2"),
                            "mds.0")
            fs._drive = lambda: mds.process()
            assert fs.read("/d/f0") == bytes([97]) * 256

        c.revive_osd(victim)
        c.mon.mark_osd_in(victim)
        c.publish()
        settle(c, rounds=5, dt=2.0)

    assert sorted(fs.listdir("/d")) == ["f0", "f1"]
    vers = [v for v in g.list_object_versions("b") if v["key"] == "doc"]
    assert len(vers) == 2 and vers[0]["is_latest"]
    assert not any(mds.fs.fsck().values())
    assert g.gc() == {"orphan_objects": [], "stale_pending": []}
    assert c.health().startswith("HEALTH")
