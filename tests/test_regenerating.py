"""Product-matrix regenerating codec (ec/regenerating.py): property
tests.

The codec's contract, tested at every layer:

- encode/decode/repair byte-identical between the device path
  (backend=tpu — the [[I],[Ψ]] bit-matmul) and the CPU reference twin
  (backend=host — MUL_TABLE math), across (k, m, d, technique, chunk)
  mixes;
- any-k reconstruction (the structured product-matrix decode) and
  ≥d-survivor row reconstruction both recover exact bytes;
- d-helper sub-chunk repair rebuilds a lost shard from d·β·L moved
  bytes — the minimum_to_decode repair surface answers a single-shard
  query with d helpers at β sub-chunks each;
- breaker-open (CPU fallback) and mesh-on states stay byte-identical;
- a cluster twin (dispatch window on vs off) stores byte-exact shard
  BODIES, and the non-systematic whole-object rw guards keep ranged
  reads and rmw byte-exact.
"""
import itertools

import numpy as np
import pytest

from ceph_tpu.ec.registry import instance as plugin_registry


def _mk(profile, backend="host"):
    p = dict(profile)
    p["backend"] = backend
    return plugin_registry.factory("regenerating", p)


PROFILES = [
    {"k": "3", "m": "2", "d": "4"},
    {"k": "4", "m": "3", "d": "5"},
    {"k": "4", "m": "3", "d": "6"},
    {"k": "3", "m": "2", "d": "3"},                      # d = k edge
    {"k": "8", "m": "4", "d": "10"},                     # the storm shape
    {"k": "3", "m": "3", "technique": "pm_msr"},         # d = 4
    {"k": "4", "m": "3", "technique": "pm_msr"},         # d = 6
]


@pytest.mark.parametrize("profile", PROFILES,
                         ids=[str(p) for p in PROFILES])
def test_roundtrip_any_k_and_row_reconstruction(profile):
    codec = _mk(profile)
    n = codec.get_chunk_count()
    rng = np.random.default_rng(11)
    for size in (100, 3000, 7777):
        payload = rng.integers(0, 256, size, dtype=np.uint8).tobytes()
        full = codec.encode(set(range(n)), payload)
        combos = list(itertools.combinations(range(n), codec.k))
        # all shard chunks equal length, decode from any k recovers
        for K in combos[::max(1, len(combos) // 12)]:
            out = codec.decode_concat({i: full[i] for i in K})
            assert out[:size] == payload, (profile, size, K)
        # row reconstruction: every single lost shard, both the
        # structured (<d survivors) and matrix (>=d survivors) branches
        for lost in range(n):
            ids = [i for i in range(n) if i != lost]
            got = codec.decode_batch(
                {i: full[i][None, :] for i in ids}, [lost])
            assert np.array_equal(got[lost].reshape(-1), full[lost])
            got2 = codec.decode_batch(
                {i: full[i][None, :] for i in ids[:codec.k]}, [lost])
            assert np.array_equal(got2[lost].reshape(-1), full[lost])


@pytest.mark.parametrize("profile", PROFILES,
                         ids=[str(p) for p in PROFILES])
def test_repair_surface_and_bytes(profile):
    """minimum_to_decode({lost}, avail) answers d helpers x β
    sub-chunks; the contributions rebuild the exact shard at the
    advertised byte cost."""
    codec = _mk(profile)
    n = codec.get_chunk_count()
    rng = np.random.default_rng(13)
    payload = rng.integers(0, 256, 5000, dtype=np.uint8).tobytes()
    full = codec.encode(set(range(n)), payload)
    C = len(full[0])
    for lost in range(n):
        plan = codec.minimum_to_decode({lost}, set(range(n)) - {lost})
        assert len(plan) == codec.d and lost not in plan
        assert all(subs == [(0, codec.beta)] for subs in plan.values())
        contribs = {h: codec.repair_contribution(
            h, lost, full[h].reshape(1, C)) for h in plan}
        moved = sum(c.nbytes for c in contribs.values())
        assert moved == codec.repair_bytes_per_shard(C)
        # the repair-bandwidth claim: strictly under k whole chunks
        assert moved < codec.k * C
        rep = codec.repair(lost, contribs)
        assert np.array_equal(rep.reshape(-1), full[lost]), \
            (profile, lost)
    # a multi-shard or k-wide query keeps the base any-k semantics
    want = {codec.chunk_index(i) for i in range(codec.k)}
    fetch = codec.minimum_to_decode(want, set(range(n)))
    assert set(fetch) == want


@pytest.mark.parametrize("profile", [
    {"k": "4", "m": "3", "d": "5"},
    {"k": "4", "m": "3", "technique": "pm_msr"},
], ids=["mbr", "msr"])
def test_device_path_byte_identical_to_host_twin(profile):
    host = _mk(profile, "host")
    dev = _mk(profile, "tpu")
    n = host.get_chunk_count()
    rng = np.random.default_rng(17)
    S = 3
    W = host.preferred_stripe_width()
    payload = rng.integers(0, 256, S * W, dtype=np.uint8)
    eh = host.encode_batch(host.regen_prepare_batch(payload, S))
    ed = dev.encode_batch(dev.regen_prepare_batch(payload, S))
    assert np.array_equal(eh, ed)
    chunks = {i: np.ascontiguousarray(eh[:, i, :]) for i in range(n)}
    lost = 1
    avail = {i: b for i, b in chunks.items() if i != lost}
    gh = host.decode_batch(dict(avail), [lost])
    gd = dev.decode_batch(dict(avail), [lost])
    assert np.array_equal(np.asarray(gh[lost]), np.asarray(gd[lost]))
    assert np.array_equal(np.asarray(gh[lost]), chunks[lost])
    plan = host.minimum_to_decode({lost}, set(range(n)) - {lost})
    ch = {h: host.repair_contribution(h, lost, chunks[h]) for h in plan}
    cd = {h: dev.repair_contribution(h, lost, chunks[h]) for h in plan}
    rh = host.repair(lost, ch)
    rd = dev.repair(lost, cd)
    assert np.array_equal(rh, rd) and np.array_equal(rh, chunks[lost])


def test_breaker_open_falls_back_byte_identical():
    """A tripped signature breaker routes the regen codec to the host
    twin — outputs unchanged (the matrix_plugin discipline)."""
    from ceph_tpu.fault import g_breakers
    profile = {"k": "3", "m": "2", "d": "4"}
    dev = _mk(profile, "tpu")
    host = _mk(profile, "host")
    rng = np.random.default_rng(19)
    payload = rng.integers(0, 256, 4000, dtype=np.uint8).tobytes()
    from ceph_tpu.common.config import g_conf
    n = dev.get_chunk_count()
    before = dev.encode(set(range(n)), payload)
    sig = dev.codec_signature()
    saved_thr = g_conf.values.get("ec_breaker_threshold")
    saved_cd = g_conf.values.get("ec_breaker_cooldown_s")
    g_conf.set_val("ec_breaker_threshold", 1)
    g_conf.set_val("ec_breaker_cooldown_s", 3600.0)  # no probe mid-test
    try:
        assert g_breakers.record_failure(sig)        # trips open
        assert not dev._use_device()
        after = dev.encode(set(range(n)), payload)
        ref = host.encode(set(range(n)), payload)
        for i in range(n):
            assert np.array_equal(before[i], after[i])
            assert np.array_equal(after[i], ref[i])
        # repair under an open breaker: host solve, same bytes
        lost = 2
        plan = dev.minimum_to_decode({lost}, set(range(n)) - {lost})
        C = len(before[lost])
        contribs = {h: dev.repair_contribution(
            h, lost, before[h].reshape(1, C)) for h in plan}
        rep = dev.repair(lost, contribs)
        assert np.array_equal(rep.reshape(-1), before[lost])
    finally:
        for key, saved in (("ec_breaker_threshold", saved_thr),
                           ("ec_breaker_cooldown_s", saved_cd)):
            if saved is None:
                g_conf.rm_val(key)
            else:
                g_conf.set_val(key, saved)
        g_breakers.reset()


def _write_objects(cluster, cl, pool, rng, count=4, base=2000):
    bodies = {}
    for i in range(count):
        oid = f"o{i}"
        body = rng.integers(0, 256, base + i * 257,
                            dtype=np.uint8).tobytes()
        assert cl.write_full(pool, oid, body) == 0
        bodies[oid] = body
    return bodies


def _shard_bodies(cluster, pool_id):
    out = {}
    for osd in cluster.osds.values():
        for pgid, pg in osd.pgs.items():
            if pgid[0] != pool_id or pg.backend is None:
                continue
            s = pg.my_shard()
            if s < 0:
                continue
            cid = pg.backend.shard_cid(s)
            store = osd.store
            if not store.collection_exists(cid):
                continue
            for ho in store.list_objects(cid):
                out[(pgid, s, ho.oid)] = store.read(cid, ho)
    return out


def test_cluster_twin_shard_bodies_byte_exact():
    """A regen pool written through the coalescing dispatch window
    stores shard BODIES byte-identical to a window-off twin."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common.config import g_conf

    def build(window_us):
        saved = g_conf.values.get("ec_dispatch_batch_window_us")
        g_conf.set_val("ec_dispatch_batch_window_us", window_us)
        try:
            c = MiniCluster(n_osds=6)
            pid = c.create_ec_pool("twin", k=3, m=2, pg_num=4,
                                   plugin="regenerating",
                                   extra_profile={"d": "4"})
            cl = c.client("client.twin")
            rng = np.random.default_rng(23)
            bodies = _write_objects(c, cl, "twin", rng)
            for oid, body in bodies.items():
                assert cl.read("twin", oid) == body
            return _shard_bodies(c, pid)
        finally:
            if saved is None:
                g_conf.rm_val("ec_dispatch_batch_window_us")
            else:
                g_conf.set_val("ec_dispatch_batch_window_us", saved)

    plain = build(0)
    coalesced = build(50_000)
    assert plain and set(plain) == set(coalesced)
    for key in plain:
        assert plain[key] == coalesced[key], key


def test_mesh_on_stays_byte_identical():
    """With the mesh armed the regen codec declines row-sharding
    (mesh_row_shardable=False) and the flush degrades to the
    single-device path — stored bytes unchanged vs mesh-off."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.common.config import g_conf
    from ceph_tpu.mesh import g_mesh

    def build(mesh_on):
        for k, v in (("ec_dispatch_batch_window_us", 50_000),
                     ("ec_mesh_chips", 8 if mesh_on else 0)):
            g_conf.set_val(k, v)
        g_mesh.topology()
        try:
            c = MiniCluster(n_osds=6)
            pid = c.create_ec_pool("meshed", k=3, m=2, pg_num=4,
                                   plugin="regenerating",
                                   extra_profile={"d": "4"})
            cl = c.client("client.mesh")
            rng = np.random.default_rng(29)
            bodies = _write_objects(c, cl, "meshed", rng)
            for oid, body in bodies.items():
                assert cl.read("meshed", oid) == body
            return _shard_bodies(c, pid)
        finally:
            for k in ("ec_dispatch_batch_window_us", "ec_mesh_chips"):
                g_conf.rm_val(k)
            g_mesh.topology()

    off = build(False)
    on = build(True)
    assert off and set(off) == set(on)
    for key in off:
        assert off[key] == on[key], key


def test_whole_object_rw_guards_ranged_and_rmw():
    """Ranged reads, appends and offset writes on the non-systematic
    pool stay byte-exact (whole-object read/modify/write under the
    requires_whole_object_rw guard)."""
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("rw", k=3, m=2, pg_num=4, plugin="regenerating",
                     extra_profile={"d": "4"})
    cl = c.client("client.rw")
    rng = np.random.default_rng(31)
    body = bytearray(rng.integers(0, 256, 5000, dtype=np.uint8)
                     .tobytes())
    assert cl.write_full("rw", "o", bytes(body)) == 0
    # ranged reads across stripe boundaries
    for off, ln in ((0, 100), (1000, 2500), (4990, 10), (4000, 1000)):
        assert cl.read("rw", "o", offset=off, length=ln) == \
            bytes(body[off:off + ln])
    # offset write (rmw) then append
    patch = rng.integers(0, 256, 700, dtype=np.uint8).tobytes()
    assert cl.write("rw", "o", patch, offset=1234) == 0
    body[1234:1234 + len(patch)] = patch
    tail = rng.integers(0, 256, 300, dtype=np.uint8).tobytes()
    assert cl.append("rw", "o", tail) == 0
    body += tail
    assert cl.read("rw", "o") == bytes(body)


def test_profile_validation():
    with pytest.raises(ValueError):
        _mk({"k": "4", "m": "2", "d": "99"})            # d > n-1
    with pytest.raises(ValueError):
        _mk({"k": "4", "m": "2", "d": "3"})             # d < k (mbr)
    with pytest.raises(ValueError):
        _mk({"k": "4", "m": "3", "technique": "pm_msr", "d": "5"})
    with pytest.raises(ValueError):
        _mk({"k": "3", "m": "2", "d": "4", "technique": "bogus"})
    with pytest.raises(ValueError):
        _mk({"k": "3", "m": "2", "d": "4", "mapping": "DD_D_"})
