"""CrushLocation: create-or-move placement by location string.

crush/CrushLocation.cc + CrushWrapper::create_or_move_item/move_bucket:
OSDs place themselves by 'root=... host=...' strings at boot; moving an
item re-homes it and reweights every ancestor.
"""
import pytest

from ceph_tpu.crush import CrushWrapper


@pytest.fixture()
def cw():
    w = CrushWrapper()
    w.set_type_name(1, "host")
    w.set_type_name(2, "rack")
    w.set_type_name(10, "root")
    return w


def test_create_or_move_builds_chain_and_maps(cw):
    for osd in range(6):
        cw.create_or_move_item(
            osd, 0x10000, f"osd.{osd}",
            f"root=default rack=r{osd % 2} host=h{osd % 3}")
    cw.set_max_devices(6)
    root = cw.get_item_id("default")
    assert cw.crush.bucket(root).weight == 6 * 0x10000
    # hierarchy: root -> 2 racks -> hosts -> osds
    racks = cw.crush.bucket(root).items
    assert len(racks) == 2
    rno = cw.add_simple_rule("data", "default", "host", mode="firstn")
    res = cw.do_rule(rno, 1234, 3, [0x10000] * 6)
    assert len(res) == 3 and len(set(res)) == 3


def test_move_rehomes_and_reweights(cw):
    for osd in range(4):
        cw.create_or_move_item(osd, 0x10000, f"osd.{osd}",
                               "root=default host=h0")
    # move osd.3 to a new host: weights follow
    cw.create_or_move_item(3, 0x10000, "osd.3", "root=default host=h1")
    h0 = cw.crush.bucket(cw.get_item_id("h0"))
    h1 = cw.crush.bucket(cw.get_item_id("h1"))
    assert h0.weight == 3 * 0x10000 and 3 not in h0.items
    assert h1.weight == 1 * 0x10000 and 3 in h1.items
    root = cw.crush.bucket(cw.get_item_id("default"))
    assert root.weight == 4 * 0x10000
    # get_loc reports the position bottom-up
    loc = cw.get_loc(3)
    assert loc[0] == ("host", "h1") and loc[-1] == ("root", "default")


def test_move_bucket(cw):
    for osd in range(2):
        cw.create_or_move_item(osd, 0x10000, f"osd.{osd}",
                               "root=default rack=r0 host=h0")
    cw.create_or_move_item(2, 0x10000, "osd.2",
                           "root=default rack=r1 host=h9")
    # re-home host h0 (2 osds) under rack r1
    cw.move_bucket("h0", "root=default rack=r1")
    r0 = cw.crush.bucket(cw.get_item_id("r0"))
    r1 = cw.crush.bucket(cw.get_item_id("r1"))
    assert r0.weight == 0 and r1.weight == 3 * 0x10000
    assert cw.get_item_id("h0") in r1.items


def test_bad_locations_rejected(cw):
    with pytest.raises(ValueError):
        cw.create_or_move_item(0, 0x10000, "osd.0", "root=default nope")
    with pytest.raises(ValueError):
        cw.create_or_move_item(0, 0x10000, "osd.0", "widget=default")
    with pytest.raises(ValueError):
        cw.move_bucket("missing-bucket", "root=default")


def test_move_into_own_subtree_rejected(cw):
    cw.create_or_move_item(0, 0x10000, "osd.0",
                           "root=default rack=r0 host=h0")
    with pytest.raises(ValueError):
        cw.move_bucket("r0", "rack=r0")
    with pytest.raises(ValueError):
        cw.move_bucket("r0", "root=default rack=r0 host=h0")


def test_parentless_bucket_attaches_to_chain(cw):
    from ceph_tpu.crush import CRUSH_BUCKET_STRAW2
    # a bucket created standalone (no parent) joins the chain on use
    cw.add_bucket(CRUSH_BUCKET_STRAW2, 1, "h-solo", [], [])
    cw.create_or_move_item(0, 0x10000, "osd.0",
                           "root=default host=h-solo")
    root = cw.crush.bucket(cw.get_item_id("default"))
    assert cw.get_item_id("h-solo") in root.items
    assert root.weight == 0x10000
