"""Wire codec + TCP messenger: messages leave the process.

Models the reference's framed wire protocol between daemons
(src/msg/async/AsyncMessenger.h:74, src/msg/Message.h:254 framing):
every message type round-trips through the tagged binary codec, and a
real two-process cluster (mon + 3 OSDs here, 3 OSDs in a child process)
serves EC writes/reads with shards crossing the process boundary.
"""
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from ceph_tpu.msg import messages as M
from ceph_tpu.msg.wire import decode_message, encode_message

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _roundtrip(msg):
    out = decode_message(encode_message(msg))
    assert type(out) is type(msg)
    return out


def test_wire_roundtrip_all_message_types():
    samples = [
        M.MOSDOp(tid=7, pool=1, oid="o", pgid=(1, 3), op="write",
                 offset=5, length=9, data=b"\x00\xffbin", epoch=4),
        M.MOSDOpReply(tid=7, result=-2, data=b"zz", epoch=9),
        M.MOSDECSubOpWrite(tid=1, pgid=(2, 5), shard=3, oid="x",
                           chunk=b"abc", offset=64, partial=True,
                           at_version=100, version=12, is_push=True),
        M.MOSDECSubOpWriteReply(tid=1, pgid=(2, 5), shard=3,
                                committed=True),
        M.MOSDECSubOpRead(tid=2, pgid=(0, 0), shard=1, oid="y",
                          offset=128, length=256, attrs_only=True,
                          subchunks=[(0, 1)]),
        M.MOSDECSubOpReadReply(tid=2, pgid=(0, 0), shard=1, oid="y",
                               data=b"d" * 32, result=0,
                               attrs={"_size": b"\x01\x02"}),
        M.MOSDPGQuery(pgid=(1, 1), shard=2, epoch=7, log_since=3),
        M.MOSDPGInfo(pgid=(1, 1), shard=2, epoch=7, last_update=9,
                     log_tail=1, log_entries=[b"\x01\x02"],
                     missing_oids=[("a", 3)]),
        M.MOSDPGScan(pgid=(1, 1), shard=0, epoch=2),
        M.MOSDPGScanReply(pgid=(1, 1), shard=0, epoch=2,
                          objects=[("o1", 4), ("o2", 0)]),
        M.MOSDRepScrub(pgid=(0, 1), shard=1, epoch=3),
        M.MOSDRepScrubMap(pgid=(0, 1), shard=1, epoch=3,
                          objects=[("o", 10, True, 12345)]),
        M.MOSDPing(op=M.MOSDPing.PING_REPLY, stamp=1.5, epoch=2),
        M.MOSDFailure(target_osd=4, failed_since=3.25, epoch=8),
    ]
    for msg in samples:
        msg.src = "osd.1"
        out = _roundtrip(msg)
        assert vars(out) == vars(msg), type(msg).__name__


def test_wire_roundtrip_mosdmap_with_incrementals():
    """MOSDMap carries structured Incrementals (crush + pools) through
    the dict codecs; the decoded map must drive placement identically."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.osdmap import OSDMap, pg_t
    c = MiniCluster(n_osds=5)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    msg = M.MOSDMap(first=1, last=c.mon.osdmap.epoch,
                    incrementals=list(c.mon.incrementals))
    out = _roundtrip(msg)
    m = OSDMap()
    for inc in out.incrementals:
        if inc.epoch == m.epoch + 1:
            m.apply_incremental(inc)
    assert m.epoch == c.mon.osdmap.epoch
    for ps in range(8):
        pid = next(iter(m.pools))
        assert m.pg_to_up_acting_osds(pg_t(pid, ps)) == \
            c.mon.osdmap.pg_to_up_acting_osds(pg_t(pid, ps))


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


_CHILD = r"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
sys.path.insert(0, sys.argv[3])
import jax; jax.config.update("jax_platforms", "cpu")
from ceph_tpu.msg.tcp import TcpNetwork
from ceph_tpu.osd.osd import OSD

port_b, port_a = int(sys.argv[1]), int(sys.argv[2])
directory = {"mon": ("127.0.0.1", port_a),
             "client.x": ("127.0.0.1", port_a)}
for i in range(3):
    directory[f"osd.{i}"] = ("127.0.0.1", port_a)
net = TcpNetwork(("127.0.0.1", port_b), directory)
osds = [OSD(net, i) for i in range(3, 6)]
print("READY", flush=True)
end = time.time() + 120
while time.time() < end:
    net.pump(quiesce=0.02, deadline=0.5)
"""


def test_two_process_ec_cluster():
    """One mon + osds 0-2 + client here; osds 3-5 in a child process.
    An EC pool with failure-domain host spreads shards over both
    processes; write/read and a degraded read cross the TCP boundary."""
    from ceph_tpu.client import RadosClient
    from ceph_tpu.mon import Monitor
    from ceph_tpu.msg.tcp import TcpNetwork
    from ceph_tpu.osd.osd import OSD

    port_a, port_b = _free_port(), _free_port()
    directory = {f"osd.{i}": ("127.0.0.1", port_b) for i in range(3, 6)}
    net = TcpNetwork(("127.0.0.1", port_a), directory)
    child = subprocess.Popen(
        [sys.executable, "-c", _CHILD, str(port_b), str(port_a), REPO],
        stdout=subprocess.PIPE, text=True)
    try:
        assert child.stdout.readline().strip() == "READY"
        mon = Monitor(net)
        mon.bootstrap(6, osds_per_host=1)
        local_osds = [OSD(net, i) for i in range(3)]
        for i in range(6):
            mon.subscribe(f"osd.{i}")
        mon.create_ec_profile("prof", {"plugin": "tpu", "k": "3",
                                       "m": "2"})
        mon.create_ec_pool("p", "prof", pg_num=4)
        mon.publish()
        net.pump()

        cl = RadosClient(net, mon, "client.x")
        rng = np.random.default_rng(2)
        data = rng.integers(0, 256, 20000, dtype=np.uint8).tobytes()
        assert cl.write_full("p", "obj", data) == 0
        assert cl.read("p", "obj") == data

        # shards really live in both processes: the acting set spans
        # remote osds (3..5), some shards are local, and killing one
        # LOCAL holder still reads (reconstruction needs remote shards)
        pgid, _p = cl._calc_target(cl.lookup_pool("p"), "obj")
        from ceph_tpu.osdmap import pg_t
        *_, acting, _ap = cl.osdmap.pg_to_up_acting_osds(pg_t(*pgid))
        assert any(o >= 3 for o in acting), "no shard crossed the boundary"
        local_holders = [o for o in local_osds
                         if any(ho.oid == "obj"
                                for cid in o.store.list_collections()
                                for ho in o.store.list_objects(cid))]
        assert local_holders, "no shard landed in this process"
        victim = local_holders[0]
        _, primary = cl._calc_target(cl.lookup_pool("p"), "obj")
        if victim.osd_id != primary:
            net.set_down(victim.name, True)
            mon.mark_osd_down(victim.osd_id)
            net.pump()
            assert cl.read("p", "obj") == data
    finally:
        child.kill()
        net.close()
