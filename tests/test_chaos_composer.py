"""Composed-chaos scenario engine (ceph_tpu/chaos) + elastic mesh
membership (injectargs-live ``ec_mesh_chips``).

The tentpole's acceptance gates live here:

- same seed => IDENTICAL storyline (the composer consumes exactly one
  seeded stream and nothing else — no wall clock, no ambient state);
- the two nastiest found seeds are pinned as tier-1 smokes and must
  pass the engine's UNIVERSAL acceptance end to end: every op
  byte-exact, every expected health check raises AND clears, every
  raise leaves a finalized incident bundle whose gseq timeline tells
  the injected storyline back, zero wedges, zero operator action;
- the ISSUE-mandated storm+straggler+abusive combination completes the
  same way with the legs forced;
- ``ec_mesh_chips`` is injectargs-live: a mid-traffic retire drains
  in-flight dispatch on the OLD mesh (zero lost flushes, zero
  single-device fallbacks), a re-add takes real stripes within ONE
  flush, both byte-exact, both journaled as first-class
  mesh_chip_retire / mesh_chip_add events;
- the fault-site catalog is machine-readable (``sites()`` /
  ``fault list format=json``) and every site is documented in
  docs/ROBUSTNESS.md (the docs lint).

The N-seed soak scales with ``CEPH_TPU_SOAK_SEEDS`` (slow tier).
"""
import os

import numpy as np
import pytest

from ceph_tpu.chaos import (LEG_BUILDERS, ScenarioSpec, compose_scenario,
                            leg_names, run_scenario, run_seed)
from ceph_tpu.common.config import g_conf
from ceph_tpu.fault import g_breakers, g_faults
from ceph_tpu.trace.journal import g_journal

# the two nastiest storylines the seed scan surfaced, pinned forever
# (recomposed when the leg catalog grew to 11): 24 composes a hard
# chip-failure burst, probabilistic device errors AND a recovery storm;
# 103 loses incident captures under a 30ms straggler while the mesh
# retires and re-adds chips mid-flight
PINNED_SEEDS = (24, 103)

TOUCHED = (
    "ec_mesh_chips", "ec_mesh_rateless", "ec_mesh_rateless_tasks",
    "ec_mesh_skew_sample_every", "ec_mesh_skew_threshold",
    "ec_dispatch_batch_max", "ec_dispatch_batch_window_us",
    "mgr_control_enable", "mgr_control_cooldown_ticks",
    "chaos_storyline_legs_max", "chaos_settle_ticks_max",
)


@pytest.fixture(autouse=True)
def _clean():
    from ceph_tpu.dispatch import g_dispatcher
    from ceph_tpu.mesh import g_chipstat, g_mesh
    g_journal.reset()
    saved = {n: g_conf.values.get(n) for n in TOUCHED}
    yield
    for n, v in saved.items():
        if v is None:
            g_conf.rm_val(n)
        else:
            g_conf.set_val(n, v)
    g_faults.clear()
    g_breakers.reset()
    g_dispatcher.flush()
    g_mesh.topology()
    g_chipstat.reset()
    g_journal.reset()


# ---- the composer ----------------------------------------------------------
def test_same_seed_identical_schedule():
    """Determinism is the contract: one seed, one storyline — value
    equality across independent compositions, stable dump, and the
    legs-forced variant is just as reproducible."""
    for seed in (0, 7, 24, 103, 20260807):
        a, b = compose_scenario(seed), compose_scenario(seed)
        assert a == b, f"seed {seed} composed two different storylines"
        assert a.dump() == b.dump()
        assert isinstance(a, ScenarioSpec) and a.seed == seed
        assert a.events == tuple(sorted(
            a.events, key=lambda e: (e.round, e.action, e.detail)))
    f1 = compose_scenario(5, legs=("chip_straggler", "recovery_storm"))
    f2 = compose_scenario(5, legs=("chip_straggler", "recovery_storm"))
    assert f1 == f2
    assert f1.legs == ("chip_straggler", "recovery_storm")
    # different seeds must be able to differ (not a constant composer)
    assert any(compose_scenario(s) != compose_scenario(s + 1)
               for s in range(5))


def test_composer_samples_only_known_primitives():
    """Every sampled storyline stays inside the primitive inventory:
    leg names from the catalog, fault sites from the registry — and an
    unknown leg is a loud error, not a silent skip."""
    sites = set(g_faults.sites())
    for seed in range(40):
        spec = compose_scenario(seed)
        assert set(spec.legs) <= set(leg_names())
        assert 1 <= len(spec.legs) <= \
            int(g_conf.get_val("chaos_storyline_legs_max"))
        for ev in spec.events:
            d = dict(ev.detail)
            if ev.action in ("fault_arm", "fault_clear"):
                assert d["site"] in sites, \
                    f"seed {seed} schedules unknown site {d['site']}"
    with pytest.raises(ValueError):
        compose_scenario(1, legs=("not_a_leg",))


def test_legs_max_option_is_live():
    """chaos_storyline_legs_max caps the sampled leg count (the
    composer reads it at compose time, injectargs-live)."""
    g_conf.set_val("chaos_storyline_legs_max", 1)
    assert all(len(compose_scenario(s).legs) == 1 for s in range(20))


# ---- fault-site enumeration (the composer's primitive inventory) -----------
def test_fault_sites_api_and_json_listing():
    """sites() is a machine-readable name->description catalog,
    list_sites() the sorted `fault list format=json` shape, and both
    agree with the human pane."""
    sites = g_faults.sites()
    assert len(sites) >= 10
    assert all(isinstance(k, str) and isinstance(v, str) and v
               for k, v in sites.items())
    sites["bogus"] = "x"                     # a copy, not the catalog
    assert "bogus" not in g_faults.sites()
    rows = g_faults.list_sites()
    assert [r["name"] for r in rows] == sorted(g_faults.sites())
    g_faults.inject("msg.drop", mode="once", match="MOSDOp ")
    armed = {r["name"]: r["armed"] for r in g_faults.list_sites()}
    assert armed["msg.drop"] is not None
    assert armed["msg.drop"]["mode"] == "once"
    assert all(v is None for s, v in armed.items() if s != "msg.drop")
    g_faults.clear()
    assert set(g_faults.dump()["sites"]) == set(g_faults.sites())


def test_every_fault_site_documented_in_robustness():
    """The docs lint: a fault site that isn't in docs/ROBUSTNESS.md is
    an undocumented operator surface — adding a site requires adding
    its row to the catalog table."""
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "docs", "ROBUSTNESS.md")
    with open(path) as f:
        docs = f.read()
    missing = sorted(s for s in g_faults.sites() if s not in docs)
    assert not missing, \
        f"fault sites missing from docs/ROBUSTNESS.md: {missing}"


# ---- the pinned tier-1 storyline smokes ------------------------------------
@pytest.mark.parametrize("seed", PINNED_SEEDS)
def test_pinned_seed_passes_universal_acceptance(seed):
    """The nastiest found seeds, end to end on a real cluster: the
    engine's whole acceptance conjunction must hold with zero operator
    action."""
    r = run_seed(seed)
    assert r["byte_exact"], r
    assert not r["wedged"], r
    assert r["storyline_told"], r
    assert r["all_raises_resolved"], r
    for chk, row in r["checks"].items():
        assert all(row.values()), (chk, row)
    assert r["mesh_fallbacks"] == 0, r
    assert r["accepted"], r


def test_issue_storyline_storm_straggler_abusive():
    """The mandated composition: recovery storm + straggling chip +
    abusive client, forced legs, one seed — completes byte-exact with
    zero operator action, the finalized bundle timeline contains the
    injected events in causal order, and the same seed reproduces the
    exact schedule."""
    legs = ("abusive_client", "chip_straggler", "recovery_storm")
    spec = compose_scenario(20260807, legs=legs)
    assert spec == compose_scenario(20260807, legs=legs)
    assert spec.legs == legs
    assert "TPU_MESH_SKEW" in spec.expected_checks
    assert spec.rate_multipliers            # the abusive dial engaged
    r = run_scenario(spec)
    assert r["accepted"], r
    row = r["checks"]["TPU_MESH_SKEW"]
    # raise, clear, and a finalized bundle whose gseq-ordered timeline
    # tells the storyline back (fault fire -> suspect mark -> raise ->
    # clear, strictly increasing gseq) — _bundle_ok's chain contract
    assert row == {"raised": True, "cleared": True, "bundle_ok": True}
    assert any(b["state"] == "resolved" and b["trigger"] == "TPU_MESH_SKEW"
               for b in r["incidents"]["bundles"]), r["incidents"]


def test_issue_storyline_degraded_read_under_straggler():
    """The degraded-read storyline: a dead OSD forces every read of its
    objects through EC decode while one chip straggles 30ms, a second
    chip fails hard and shard reads return EIO — the nastiest seed the
    forced-leg scan surfaced (28: kill at round 1, four chip failures,
    seven EIOs, straggler and failing chip distinct and overlapping).
    Decode groups must ride the mesh throughout (no single-device
    fallbacks), stay byte-exact, and the skew check must raise, clear
    and finalize its bundle with zero operator action."""
    from ceph_tpu.mesh import mesh_decode_perf_counters
    from ceph_tpu.mesh.runtime import l_mdec_dispatches, l_mdec_fallbacks
    legs = ("chip_fail", "degraded_read_straggler", "shard_eio")
    spec = compose_scenario(28, legs=legs)
    assert spec == compose_scenario(28, legs=legs)
    assert spec.legs == legs
    assert "TPU_MESH_SKEW" in spec.expected_checks
    before = mesh_decode_perf_counters().get(l_mdec_dispatches)
    fb_before = mesh_decode_perf_counters().get(l_mdec_fallbacks)
    r = run_scenario(spec)
    assert r["accepted"], r
    assert r["byte_exact"], r
    assert r["mesh_fallbacks"] == 0, r
    row = r["checks"]["TPU_MESH_SKEW"]
    assert row == {"raised": True, "cleared": True, "bundle_ok": True}
    mdec = mesh_decode_perf_counters()
    assert mdec.get(l_mdec_dispatches) > before, \
        "degraded reads never reached the meshed decode path"
    assert mdec.get(l_mdec_fallbacks) == fb_before, \
        "meshed decode fell back to single-device under the storyline"


@pytest.mark.slow
def test_seed_soak():
    """The N-seed soak (CEPH_TPU_SOAK_SEEDS, default 12): every
    sampled storyline in the range must pass universal acceptance —
    the composer has no unlucky seeds, only engine bugs."""
    n = int(os.environ.get("CEPH_TPU_SOAK_SEEDS", "12"))
    failed = []
    for seed in range(n):
        r = run_seed(seed)
        if not r["accepted"]:
            failed.append((seed, r["legs"], {
                k: r[k] for k in ("byte_exact", "wedged",
                                  "storyline_told",
                                  "all_raises_resolved", "checks")}))
    assert not failed, failed


# ---- elastic mesh membership ----------------------------------------------
def test_elastic_membership_retire_and_add_under_traffic():
    """ec_mesh_chips is injectargs-live: a retire mid-flight drains
    the dispatcher on the OLD mesh first (zero lost flushes, zero
    single-device fallbacks, every op byte-exact), a re-add takes real
    stripes within ONE flush (visible in the per-chip occupancy
    table), and both transitions are journaled first-class."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.dispatch import g_dispatcher
    from ceph_tpu.ec.tpu_plugin import ErasureCodeTpu
    from ceph_tpu.mesh import g_chipstat, g_mesh
    from ceph_tpu.mesh.runtime import (l_member_chip_adds,
                                       l_member_chip_retires,
                                       l_member_drained_reqs,
                                       l_mesh_fallbacks,
                                       membership_perf_counters,
                                       mesh_perf_counters)
    from ceph_tpu.osd.ecutil import encode as eu_encode, stripe_info_t

    g_conf.set_val("ec_mesh_chips", 8)
    g_conf.set_val("ec_mesh_rateless", True)
    g_conf.rm_val("ec_mesh_rateless_tasks")
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    g_conf.set_val("ec_dispatch_batch_window_us", 10_000_000)
    g_conf.set_val("ec_dispatch_batch_max", 64)
    g_dispatcher.flush()
    MiniCluster(n_osds=3)
    mesh = g_mesh.topology()
    if mesh is None or mesh.size < 8:
        pytest.skip("needs an 8-device mesh "
                    "(xla_force_host_platform_device_count)")
    impl = ErasureCodeTpu()
    impl.init({"k": "4", "m": "2", "technique": "reed_sol_van"})
    sinfo = stripe_info_t(4, 4 * 1024)
    want = set(range(6))
    rng = np.random.default_rng(24)

    def submit(n=3):
        payloads = [rng.integers(0, 256, size=2 * 4 * 1024,
                                 dtype=np.uint8) for _ in range(n)]
        oracles = [eu_encode(sinfo, impl, p, want) for p in payloads]
        futs = [g_dispatcher.submit_encode(sinfo, impl, p, want)
                for p in payloads]
        return futs, oracles

    def settle(futs, oracles):
        for f, oracle in zip(futs, oracles):
            res = f.result()
            assert sorted(res) == sorted(oracle)
            for i in oracle:
                assert np.asarray(res[i]).tobytes() == \
                    np.asarray(oracle[i]).tobytes()

    settle(*submit())                           # compile warmup
    g_dispatcher.flush()
    g_chipstat.reset()
    g_journal.reset()
    mpc = membership_perf_counters()
    fb0 = mesh_perf_counters().get(l_mesh_fallbacks)
    ret0 = mpc.get(l_member_chip_retires)
    add0 = mpc.get(l_member_chip_adds)
    dr0 = mpc.get(l_member_drained_reqs)

    # ---- RETIRE, with requests in flight --------------------------------
    futs, oracles = submit()                    # queued, NOT flushed
    g_conf.set_checked("ec_mesh_chips", 6)      # injectargs-live
    assert g_mesh.topology().size == 6
    settle(futs, oracles)                       # zero lost flushes
    assert mpc.get(l_member_drained_reqs) - dr0 >= 3, \
        "the retire did not drain the in-flight requests"
    assert mpc.get(l_member_chip_retires) - ret0 == 2
    retire_evs = [e for e in g_journal.merged()
                  if e["type"] == "mesh_chip_retire"]
    assert len(retire_evs) == 1
    assert retire_evs[0]["chips_from"] == 8
    assert retire_evs[0]["chips_to"] == 6
    assert retire_evs[0]["retired"] == [6, 7]
    settle(*submit())                           # traffic on the 6-mesh
    g_dispatcher.flush()

    # ---- ADD back to 8 ---------------------------------------------------
    occ_before = {i: v["stripes"]
                  for i, v in g_mesh.per_chip().items()}
    g_conf.set_checked("ec_mesh_chips", 8)
    assert g_mesh.topology().size == 8
    settle(*submit())                           # ONE flush after the add
    g_dispatcher.flush()
    occ_after = {i: v["stripes"] for i, v in g_mesh.per_chip().items()}
    gained = [i for i in (6, 7)
              if occ_after.get(i, 0) > occ_before.get(i, 0)]
    assert gained, \
        "re-added chips took no real stripes within one flush: " \
        f"{occ_before} -> {occ_after}"
    assert mpc.get(l_member_chip_adds) - add0 == 2
    add_evs = [e for e in g_journal.merged()
               if e["type"] == "mesh_chip_add"]
    assert len(add_evs) == 1
    assert add_evs[0]["chips_from"] == 6
    assert add_evs[0]["chips_to"] == 8
    # the whole cycle stayed on the coded path
    assert mesh_perf_counters().get(l_mesh_fallbacks) == fb0, \
        "a membership transition degraded a flush to single-device"
    assert g_mesh.dump()["membership"]["transitions"] >= 2


def test_membership_noop_and_lifecycle_edges_not_journaled():
    """Setting ec_mesh_chips to its current value is a no-op (no
    drain, no transition), and mesh up/down (0<->N at fixture
    boundaries) is lifecycle, never a membership event."""
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.mesh import g_mesh
    from ceph_tpu.mesh.runtime import membership_perf_counters
    g_conf.set_val("ec_mesh_chips", 8)
    MiniCluster(n_osds=3)
    mesh = g_mesh.topology()
    if mesh is None or mesh.size < 8:
        pytest.skip("needs an 8-device mesh")
    g_journal.reset()
    t0 = g_mesh.dump()["membership"]["transitions"]
    g_conf.set_checked("ec_mesh_chips", 8)      # same value: no-op
    assert g_mesh.dump()["membership"]["transitions"] == t0
    assert not [e for e in g_journal.merged()
                if e["type"] in ("mesh_chip_add", "mesh_chip_retire")]
    # target_chips gauge tracks the knob even when it is a no-op
    from ceph_tpu.mesh.runtime import l_member_target_chips
    assert membership_perf_counters().get(l_member_target_chips) == 8
