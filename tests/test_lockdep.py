"""lockdep: runtime lock-order inversion detection (common/lockdep.cc).

The reference's lockdep registers named mutexes and aborts on A->B then
B->A acquisition orders; the threaded cache paths (EC decode caches,
plugin registry) are instrumented with DebugLock so debug runs catch
ordering bugs the way vstart's lockdep=1 does.
"""
import threading

import pytest

from ceph_tpu.common import (
    DebugLock, LockOrderError, lockdep_enable, lockdep_reset,
)


@pytest.fixture(autouse=True)
def _lockdep():
    lockdep_reset()
    lockdep_enable(True)
    yield
    lockdep_enable(False)
    lockdep_reset()


def test_consistent_order_is_clean():
    a, b = DebugLock("A"), DebugLock("B")
    for _ in range(3):
        with a:
            with b:
                pass


def test_inversion_detected():
    a, b = DebugLock("A"), DebugLock("B")
    with a:
        with b:
            pass
    with pytest.raises(LockOrderError, match="inversion"):
        with b:
            with a:
                pass


def test_recursive_acquire_detected():
    a = DebugLock("A")
    with pytest.raises(LockOrderError, match="recursive"):
        with a:
            a.acquire()


def test_cross_thread_orders_shared():
    """Ordering knowledge is global, like the reference: thread 1
    establishes A->B, thread 2's B->A trips."""
    a, b = DebugLock("A2"), DebugLock("B2")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with pytest.raises(LockOrderError):
        with b:
            with a:
                pass


def test_instrumented_cache_paths_are_clean():
    """Drive the instrumented EC cache locks under lockdep: no ordering
    violations in the real code paths."""
    import numpy as np
    from ceph_tpu.ec import create_erasure_code
    c = create_erasure_code({"plugin": "tpu", "k": "3", "m": "2",
                             "backend": "tpu"})
    payload = np.random.default_rng(0).integers(
        0, 256, 3000, dtype=np.uint8).tobytes()
    enc = c.encode(set(range(5)), payload)
    avail = {i: enc[i] for i in range(5) if i != 1}
    dec = c.decode({1}, avail)
    np.testing.assert_array_equal(dec[1], enc[1])


def test_transitive_cycle_detected():
    """A->B, B->C, then C->A closes a three-lock cycle that a direct
    pair check would miss (the reference's recursive follows check)."""
    a, b, c = DebugLock("TA"), DebugLock("TB"), DebugLock("TC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(LockOrderError):
        with c:
            with a:
                pass


# ---------------------------------------------------------------------------
# DebugRLock + Condition protocol (the universal-witness sweep)
# ---------------------------------------------------------------------------

def test_rlock_reentry_is_not_an_inversion():
    """Same-instance re-acquisition is legal RLock semantics: no
    recursive-acquire report, and the outer pair still orders."""
    from ceph_tpu.common import DebugRLock
    r = DebugRLock("R1")
    with r:
        with r:                      # reentry: no LockOrderError
            assert r._is_owned()
    b = DebugLock("R1B")
    with r:
        with b:
            pass
    with pytest.raises(LockOrderError, match="inversion"):
        with b:
            with r:
                pass


def test_rlock_inversion_detected_at_outermost_only():
    """Only the outermost acquire participates in the order graph —
    an inner reentry while holding another lock must not fabricate a
    second edge."""
    from ceph_tpu.common import DebugRLock
    r, x = DebugRLock("R2"), DebugLock("X2")
    with r:
        with x:
            with r:                  # reentry under x: NOT x->r
                pass
    # the only recorded order is r->x, so x->r still trips
    with pytest.raises(LockOrderError):
        with x:
            with r:
                pass


def test_condition_on_debuglock_keeps_held_stack_honest():
    """threading.Condition(DebugLock): wait releases the lock (held
    stack drops it), wakeup re-acquires (held stack regains it), and
    Condition's ownership probe never reports a phantom recursive
    acquire."""
    lk = DebugLock("CV::lock")
    cv = threading.Condition(lk)
    hits = []

    def waiter():
        with cv:
            cv.wait(timeout=5.0)
            # re-acquired after wait: ordering against a second lock
            # still records from a correct held stack
            with DebugLock("CV::inner"):
                hits.append("woke")

    th = threading.Thread(target=waiter)
    th.start()
    # let the waiter reach wait(); then notify under the lock
    import time
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        with cv:
            if cv._is_owned():
                cv.notify_all()
        if hits:
            break
        time.sleep(0.01)
    th.join(timeout=5.0)
    assert hits == ["woke"]
    assert not lk.locked()


def test_swept_singletons_are_named_locks():
    """The sweep's acceptance: the process-global registries all carry
    witnessed locks now (spot-check the singletons a test can reach
    without booting a cluster)."""
    from ceph_tpu.common import DebugRLock
    from ceph_tpu.dispatch.scheduler import g_dispatcher
    from ceph_tpu.fault import g_breakers, g_faults
    from ceph_tpu.trace.devprof import g_devprof
    for obj, attr in ((g_devprof, "_lock"),
                      (g_faults, "_lock"), (g_breakers, "_lock")):
        assert isinstance(getattr(obj, attr), (DebugLock, DebugRLock)), \
            (obj, attr)
    assert isinstance(g_dispatcher._lock, DebugRLock)


def test_disabling_witness_mid_hold_does_not_strand_held_stack():
    """Toggling lockdep off while a thread is inside a critical
    section must not strand the lock's name on the thread-local held
    stack — a later re-enable would see a phantom hold and report a
    false recursive acquire (the chaos fixtures toggle per-test)."""
    a = DebugLock("TOG")
    a.acquire()
    lockdep_enable(False)
    a.release()                  # witness off: must still pop
    lockdep_enable(True)
    with a:                      # no phantom "recursive acquire"
        pass
