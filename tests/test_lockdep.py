"""lockdep: runtime lock-order inversion detection (common/lockdep.cc).

The reference's lockdep registers named mutexes and aborts on A->B then
B->A acquisition orders; the threaded cache paths (EC decode caches,
plugin registry) are instrumented with DebugLock so debug runs catch
ordering bugs the way vstart's lockdep=1 does.
"""
import threading

import pytest

from ceph_tpu.common import (
    DebugLock, LockOrderError, lockdep_enable, lockdep_reset,
)


@pytest.fixture(autouse=True)
def _lockdep():
    lockdep_reset()
    lockdep_enable(True)
    yield
    lockdep_enable(False)
    lockdep_reset()


def test_consistent_order_is_clean():
    a, b = DebugLock("A"), DebugLock("B")
    for _ in range(3):
        with a:
            with b:
                pass


def test_inversion_detected():
    a, b = DebugLock("A"), DebugLock("B")
    with a:
        with b:
            pass
    with pytest.raises(LockOrderError, match="inversion"):
        with b:
            with a:
                pass


def test_recursive_acquire_detected():
    a = DebugLock("A")
    with pytest.raises(LockOrderError, match="recursive"):
        with a:
            a.acquire()


def test_cross_thread_orders_shared():
    """Ordering knowledge is global, like the reference: thread 1
    establishes A->B, thread 2's B->A trips."""
    a, b = DebugLock("A2"), DebugLock("B2")

    def t1():
        with a:
            with b:
                pass

    th = threading.Thread(target=t1)
    th.start()
    th.join()
    with pytest.raises(LockOrderError):
        with b:
            with a:
                pass


def test_instrumented_cache_paths_are_clean():
    """Drive the instrumented EC cache locks under lockdep: no ordering
    violations in the real code paths."""
    import numpy as np
    from ceph_tpu.ec import create_erasure_code
    c = create_erasure_code({"plugin": "tpu", "k": "3", "m": "2",
                             "backend": "tpu"})
    payload = np.random.default_rng(0).integers(
        0, 256, 3000, dtype=np.uint8).tobytes()
    enc = c.encode(set(range(5)), payload)
    avail = {i: enc[i] for i in range(5) if i != 1}
    dec = c.decode({1}, avail)
    np.testing.assert_array_equal(dec[1], enc[1])


def test_transitive_cycle_detected():
    """A->B, B->C, then C->A closes a three-lock cycle that a direct
    pair check would miss (the reference's recursive follows check)."""
    a, b, c = DebugLock("TA"), DebugLock("TB"), DebugLock("TC")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(LockOrderError):
        with c:
            with a:
                pass
