"""crushtool whole-file cram parity: replay the reference's ENTIRE
recorded CLI transcripts (src/test/cli/crushtool/*.t) — every
command, output byte, and exit code — through the mini-cram
interpreter (tests/cram.py).

Exclusions, each with its reason:
- output-csv.t: a no-op in the reference's own test runs — its
  commands use a column-0 dialect stock cram never executes, and its
  assertions contradict the tool itself (the batch CSVs it checks
  for require --batches > 1, which it never passes).  Our
  --output-csv implementation covers the documented file set anyway.
- The test-map-* / straw2 / bad-mappings / set-choose mapping
  families are replayed HERE as whole files, superseding nothing:
  tests/test_reference_golden.py additionally replays their recorded
  mappings through the device mappers (a stronger assertion than the
  host-only cram replay).

These are slow (each crushtool invocation is a fresh interpreter);
the heavy mapping files are marked for the tail of the run.
"""
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from cram import assert_cram  # noqa: E402

CDIR = "/root/reference/src/test/cli/crushtool"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(CDIR), reason="reference cram files unavailable")

EXCLUDED = {"output-csv.t"}

# the test-map-* sweeps map 1024 inputs across every rule x numrep —
# ~20 min of wall even under xdist.  Their SUBSTANCE (the recorded
# mappings) is already replayed bit-exactly through the device
# mappers by tests/test_reference_golden.py; the whole-file replays
# were verified green this round and stay runnable via
# CEPH_TPU_CRAM_FULL=1.
# listdir must not run at import when the reference tree is absent —
# the skipif mark only guards test execution, not module collection
_TS = os.listdir(CDIR) if os.path.isdir(CDIR) else []
HEAVY = {t for t in _TS
         if t.startswith("test-map-")} | {"straw2.t", "set-choose.t"}
FULL = os.environ.get("CEPH_TPU_CRAM_FULL") == "1"

ALL_TS = sorted(t for t in _TS
                if t.endswith(".t") and t not in EXCLUDED
                and (FULL or t not in HEAVY))


@pytest.mark.parametrize("tname", ALL_TS)
def test_crushtool_cram(tname, tmp_path):
    assert_cram(os.path.join(CDIR, tname), str(tmp_path))
