"""Counter-coverage lint: no registered metric may silently skip the
Prometheus exporter.

Satellite of the devprof PR.  Twice now a counter family was added to
`perf dump` and only later discovered missing from the mgr exposition
(the PR 3 dimensionless-axis fix, the PR 6 qos wiring).  This lint
closes the loop structurally: it walks every ``PerfCounters`` logger
registered in the cluster's collection AND every ``PerfHistogram`` in
the process registry, and asserts each family appears in the rendered
exposition — so a new counter that skips the exporter fails tier-1,
not a dashboard review.
"""
import re

import pytest


@pytest.fixture(scope="module")
def cluster_and_text():
    from ceph_tpu.common.config import g_conf
    from ceph_tpu.cluster import MiniCluster
    from ceph_tpu.mesh import g_mesh
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("lint", k=3, m=2, pg_num=8)
    cl = c.client("client.lint")
    assert cl.write_full("lint", "o", b"c" * 16000) == 0
    assert cl.read("lint", "o")[:1] == b"c"
    # one write through the MESH path so the per-chip occupancy
    # histogram registers and the mesh counters move — the lint below
    # then covers the mesh families like any other; skew probes run on
    # every flush so the mesh_chip scoreboard families register too
    g_conf.set_val("ec_mesh_chips", 8)
    g_conf.set_val("ec_dispatch_batch_window_us", 200_000)
    g_conf.set_val("ec_mesh_skew_sample_every", 1)
    try:
        assert cl.write_full("lint", "om", b"m" * 60000) == 0
        # and one through the RATELESS coded path so the
        # mesh_rateless_* family registers, moves, and is lint-covered
        g_conf.set_val("ec_mesh_rateless", True)
        assert cl.write_full("lint", "or", b"n" * 60000) == 0
    finally:
        g_conf.rm_val("ec_mesh_chips")
        g_conf.rm_val("ec_dispatch_batch_window_us")
        g_conf.rm_val("ec_mesh_skew_sample_every")
        g_conf.rm_val("ec_mesh_rateless")
        g_mesh.topology()
    from ceph_tpu.mesh import g_chipstat, rateless_perf_counters
    from ceph_tpu.mesh.rateless import l_rl_flushes
    assert g_chipstat.summary()["probes"] > 0, \
        "mesh write produced no skew probe — scoreboard families " \
        "would be lint-invisible"
    assert rateless_perf_counters().get(l_rl_flushes) > 0, \
        "mesh write never rode the rateless path — its counter " \
        "family would be lint-invisible"
    # one DEGRADED read through the MESH path (kill a data-shard
    # holder, reconstruct with the mesh up) so the mesh_decode_*
    # counter family and the decode occupancy histogram register and
    # move — the lint below then covers the meshed READ path too
    lint_pid = c.mon.osdmap.lookup_pg_pool_name("lint")
    victim = next(
        o.osd_id for o in c.osds.values()
        for cid in o.store.list_collections()
        if cid.startswith(f"{lint_pid}.") and "s" in cid
        and cid.rsplit("s", 1)[1] in ("1", "2")   # non-primary DATA shard
        and any(ho.oid == "om" for ho in o.store.list_objects(cid)))
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    g_conf.set_val("ec_mesh_chips", 8)
    try:
        assert cl.read("lint", "om")[:1] == b"m"
    finally:
        g_conf.rm_val("ec_mesh_chips")
        g_mesh.topology()
    from ceph_tpu.mesh import mesh_decode_perf_counters
    from ceph_tpu.mesh.runtime import l_mdec_dispatches
    assert mesh_decode_perf_counters().get(l_mdec_dispatches) > 0, \
        "degraded read never rode the meshed decode path — its " \
        "counter family would be lint-invisible"
    c.revive_osd(victim)
    for _ in range(3):
        c.tick(dt=6.0)
    # one repair round through a regenerating pool so the `recovery`
    # counter families and the bytes-per-shard histogram register and
    # move — the lint below then covers them like any other family
    c.create_ec_pool("lintregen", k=3, m=2, pg_num=2,
                     plugin="regenerating", extra_profile={"d": "4"})
    assert cl.write_full("lintregen", "r", b"r" * 3000) == 0
    regen_pid = c.mon.osdmap.lookup_pg_pool_name("lintregen")
    victim = next(pg.acting[-1] for _pgid, pg in c.primary_pgs()
                  if pg.backend is not None and _pgid[0] == regen_pid)
    c.kill_osd(victim)
    c.mark_osd_down(victim)
    c.mark_osd_out(victim)
    for _ in range(6):
        c.tick(dt=1.0)
    from ceph_tpu.recovery import (l_recovery_repair_rounds,
                                   recovery_perf_counters)
    assert recovery_perf_counters().get(l_recovery_repair_rounds) > 0
    assert cl.read("lintregen", "r") == b"r" * 3000
    # one write through the DEVICE-RESIDENT path (fused encode+crc,
    # shard bodies kept in HBM) and one materializing read-back so the
    # memstore_device_* family registers AND moves — the lint below
    # then covers the zero-copy write path like any other family
    g_conf.set_val("os_memstore_device_bytes_max", 1 << 30)
    try:
        assert cl.write_full("lint", "od", b"z" * 16000) == 0
        assert cl.read("lint", "od") == b"z" * 16000
    finally:
        g_conf.rm_val("os_memstore_device_bytes_max")
    from ceph_tpu.os_store import memstore_device_perf_counters
    msd = memstore_device_perf_counters().dump()
    assert msd["crc_device"] > 0 and msd["materializations"] > 0, \
        "write never rode the device-resident path — its counter " \
        "family would be lint-invisible"
    # one mgr tick so the telemetry ring holds a post-IO sample and
    # the ceph_cluster_* rollup families render with real content
    c.tick(dt=1.0)
    return c, c.admin_socket.execute("prometheus metrics")


def _prom_name(raw: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", raw)


def test_every_perf_counter_is_exported(cluster_and_text):
    """Every numeric counter of every registered logger renders as a
    ``ceph_daemon_<logger>_<counter>`` sample."""
    c, text = cluster_and_text
    sample_names = {line.split("{")[0].split(" ")[0]
                    for line in text.splitlines()
                    if line and not line.startswith("#")}
    missing = []
    dump = c.perf_collection.dump()
    assert dump, "empty perf collection"
    for logger, counters in sorted(dump.items()):
        if not isinstance(counters, dict):
            continue
        for cname, val in sorted(counters.items()):
            if not isinstance(val, (int, float)):
                # time-avg counters dump as {sum, avgcount}: the
                # renderer skips them by design (no scalar sample)
                continue
            want = f"ceph_daemon_{_prom_name(f'{logger}_{cname}')}"
            if want not in sample_names:
                missing.append(want)
    assert not missing, \
        f"{len(missing)} registered counters missing from the " \
        f"exposition: {missing[:10]}"


def test_every_histogram_family_is_exported(cluster_and_text):
    """Every registered PerfHistogram NAME renders as a ``# TYPE ...
    histogram`` family with _bucket/_sum/_count series."""
    from ceph_tpu.trace import g_perf_histograms
    _c, text = cluster_and_text
    types = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _h, _t, name, typ = line.split(None, 3)
            types[name] = typ
    sample_names = {line.split("{")[0].split(" ")[0]
                    for line in text.splitlines()
                    if line and not line.startswith("#")}
    names = {hname for (_logger, hname), _h in g_perf_histograms.items()}
    assert names, "no histograms registered"
    missing = []
    for hname in sorted(names):
        fam = f"ceph_{_prom_name(hname)}"
        if types.get(fam) != "histogram":
            missing.append(f"{fam} (no TYPE histogram)")
            continue
        for sfx in ("_bucket", "_sum", "_count"):
            if f"{fam}{sfx}" not in sample_names:
                missing.append(f"{fam}{sfx}")
    assert not missing, \
        f"histogram families missing from the exposition: {missing[:10]}"


def test_known_new_families_covered_by_the_lint(cluster_and_text):
    """Canary: the lint actually sees the newest counter families
    (devprof, oplat) — if someone unregisters a logger the lint must
    not silently pass on an empty set."""
    c, _text = cluster_and_text
    assert "devprof" in c.perf_collection.dump()
    assert "oplat" in c.perf_collection.dump()
    # mesh-PR canary: the mesh logger is registered AND the fixture's
    # mesh write registered the per-chip occupancy family, so the
    # generic lints above really cover the mesh surfaces
    assert "mesh" in c.perf_collection.dump()
    assert c.perf_collection.dump()["mesh"]["dispatches"] > 0
    # control-plane canary (ceph_tpu/control): the controller's logger
    # is registered on every cluster, so ceph_daemon_control_* rides
    # the generic exposition/coverage lints above
    assert "control" in c.perf_collection.dump()
    assert "skipped_cooldown" in c.perf_collection.dump()["control"]
    # chaos-PR canaries: the scenario engine's logger and the elastic
    # mesh-membership family are registered on every cluster, so
    # ceph_daemon_chaos_* / ceph_daemon_mesh_membership_* ride the
    # generic exposition/coverage lints above
    assert "chaos" in c.perf_collection.dump()
    assert "accept_pass" in c.perf_collection.dump()["chaos"]
    assert "mesh_membership" in c.perf_collection.dump()
    assert "drained_reqs" in c.perf_collection.dump()["mesh_membership"]
    # zero-copy-PR canary: the memstore_device logger is registered on
    # every cluster and the fixture's residency write + read moved it,
    # so ceph_daemon_memstore_device_* rides the generic
    # exposition/coverage lints above
    assert "memstore_device" in c.perf_collection.dump()
    assert c.perf_collection.dump()["memstore_device"]["crc_device"] > 0
    assert c.perf_collection.dump()[
        "memstore_device"]["materializations"] > 0
    # meshed-READ-path canary: the mesh_decode logger is registered
    # and the fixture's degraded read moved it AND registered the
    # decode occupancy family, so the generic lints above really
    # cover the straggler-proof read path's surfaces
    assert "mesh_decode" in c.perf_collection.dump()
    assert c.perf_collection.dump()["mesh_decode"]["dispatches"] > 0
    assert c.perf_collection.dump()["mesh_decode"]["fallbacks"] == 0
    from ceph_tpu.trace import g_perf_histograms
    from ceph_tpu.trace.oplat import stage_of_hist_name
    assert any(lg == "devprof" for (lg, _n), _h
               in g_perf_histograms.items())
    # the fixture's write/read registered per-stage oplat families on
    # the OSD daemons — so the generic histogram lint above is really
    # covering the stage-latency ledger's exposition
    oplat_stages = {stage_of_hist_name(n)
                    for (_lg, n), _h in g_perf_histograms.items()
                    if stage_of_hist_name(n)}
    assert {"admission", "class_queue", "device_call", "reply"} <= \
        oplat_stages, oplat_stages
    assert any(n == "dispatch_chip_occupancy_histogram"
               for (_lg, n), _h in g_perf_histograms.items())
    assert any(n == "mesh_decode_chip_occupancy_histogram"
               for (_lg, n), _h in g_perf_histograms.items())


def test_cluster_rollup_families_exported(cluster_and_text):
    """Telemetry-PR lint: every stage and rate in the mgr rollup
    snapshot renders as a ``ceph_cluster_*`` gauge — a new rollup
    series that skips the exporter fails tier-1, like a counter."""
    c, text = cluster_and_text
    roll = c.mgr.telemetry.rollup()
    assert roll["oplat_p99_usec"], "rollup carries no oplat stages"
    assert {"device_call", "class_queue", "reply"} <= \
        set(roll["oplat_p99_usec"]), roll["oplat_p99_usec"]
    missing = []
    for q in ("p50", "p99", "p999"):
        for stage in roll["oplat_p99_usec"]:
            want = f'ceph_cluster_oplat_{q}_usec{{stage="{stage}"}}'
            if want not in text:
                missing.append(want)
    assert set(roll["rates"]) == {"ops", "h2d_bytes", "d2h_bytes",
                                  "admission_rejections"}
    for key in roll["rates"]:
        if f"ceph_cluster_rate_{key} " not in text:
            missing.append(f"ceph_cluster_rate_{key}")
    assert not missing, \
        f"cluster rollup series missing from the exposition: {missing}"


def test_slo_and_telemetry_options_documented():
    """Options-coverage lint: every ``mgr_slo_*`` / ``mgr_telemetry_*``
    option must be documented in docs/OBSERVABILITY.md's SLO option
    table — an objective an operator cannot discover is an objective
    nobody sets."""
    import os
    from ceph_tpu.common.config import g_conf
    doc_path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "OBSERVABILITY.md")
    with open(doc_path) as f:
        doc = f.read()
    opts = sorted(n for n in g_conf.schema
                  if n.startswith(("mgr_slo_", "mgr_telemetry_")))
    assert opts, "no SLO/telemetry options registered"
    missing = [n for n in opts if n not in doc]
    assert not missing, \
        f"undocumented mgr_slo_/mgr_telemetry_ options: {missing}"
