"""Multi-monitor control plane: election, replication, leader failover.

Models the reference's mon quorum (src/mon/Elector.cc lowest-rank-wins
elections, src/mon/Paxos.cc leader-driven replication): three monitors
replicate every committed epoch; killing the leader elects a successor
that continues publishing from the last committed state, and a revived
monitor catches up through the collect/last recovery phase.
"""
import numpy as np

from ceph_tpu.cluster import MiniCluster


def payload(n=15000, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def test_three_mons_elect_and_replicate():
    c = MiniCluster(n_osds=5, n_mons=3)
    assert c.mon.name == "mon.0"          # lowest rank leads
    assert c.mon.quorum == {0, 1, 2}
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    # every committed epoch is replicated to the peons
    for m in c.mons:
        assert m.osdmap.epoch == c.mons[0].osdmap.epoch
        assert len(m.incrementals) == len(c.mons[0].incrementals)
    cl = c.client("client.m")
    data = payload(seed=1)
    assert cl.write_full("p", "o", data) == 0
    assert cl.read("p", "o") == data


def test_leader_failover_continues_service():
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    cl = c.client("client.f")
    assert cl.write_full("p", "pre", payload(seed=2)) == 0
    epoch_before = c.mon.osdmap.epoch
    c.kill_mon(0)
    # keepalive grace expires -> survivors elect mon.1
    for _ in range(6):
        c.tick(dt=6.0)
    leader = c.mon
    assert leader.name == "mon.1"
    assert leader.is_leader()
    assert 0 not in leader.quorum
    # the new leader continues from the committed history
    assert leader.osdmap.epoch >= epoch_before
    # and the control plane still works: osd failure -> mark down -> remap
    holders = {o.osd_id for o in c.osds.values()
               if any(ho.oid == "pre"
                      for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    pool_id = cl.lookup_pool("p")
    _, primary = cl._calc_target(pool_id, "pre")
    victim = next(o for o in holders if o != primary)
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    assert not leader.osdmap.is_up(victim)
    assert cl.read("p", "pre") == payload(seed=2)


def test_revived_mon_catches_up():
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    c.kill_mon(2)
    # epochs committed while mon.2 is away
    c.mon.mark_osd_out(4)
    c.network.pump()
    c.mon.mark_osd_in(4)
    c.network.pump()
    target = c.mon.osdmap.epoch
    c.revive_mon(2)
    for _ in range(3):
        c.tick(dt=6.0)
    mon2 = next(m for m in c.mons if m.name == "mon.2")
    assert mon2.osdmap.epoch == target
    assert len(mon2.incrementals) == len(c.mon.incrementals)


def test_minority_cannot_elect():
    """A single partitioned mon must not declare itself leader (no
    split-brain: victory needs a majority of the mon map)."""
    c = MiniCluster(n_osds=3, n_mons=3)
    c.kill_mon(1)
    c.kill_mon(2)
    mon0 = c.mons[0]
    mon0.start_election()
    c.network.pump()
    for _ in range(4):
        c.tick(dt=6.0)
    # mon.0 alone is 1 of 3: not a majority
    assert not mon0.is_leader() or len(mon0.quorum) >= 2, \
        (mon0.leader_rank, mon0.quorum)
    assert mon0.leader_rank == -1


def test_osd_failure_detected_across_leader_outage():
    """An OSD that dies just before the mon leader dies must still get
    marked down by the successor: OSDs re-send failure reports every
    tick, and mid-election mons drop rather than act on them."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    cl = c.client("client.o")
    assert cl.write_full("p", "o", payload(seed=9)) == 0
    holders = {o.osd_id for o in c.osds.values()
               if any(ho.oid == "o" for cid in o.store.list_collections()
                      for ho in o.store.list_objects(cid))}
    pool_id = cl.lookup_pool("p")
    _, primary = cl._calc_target(pool_id, "o")
    victim = next(o for o in holders if o != primary)
    c.kill_osd(victim)
    c.kill_mon(0)   # leader dies in the same window
    for _ in range(10):
        c.tick(dt=6.0)
    leader = c.mon
    assert leader.is_leader() and leader.name != "mon.0"
    assert not leader.osdmap.is_up(victim), "successor must mark it down"
    assert cl.read("p", "o") == payload(seed=9)
    # quorum histories stayed convergent
    live = [m for m in c.mons if m.name != "mon.0"]
    assert live[0].osdmap.epoch == live[1].osdmap.epoch
    assert len(live[0].incrementals) == len(live[1].incrementals)


def test_mutation_without_quorum_raises():
    c = MiniCluster(n_osds=3, n_mons=3)
    c.kill_mon(1)
    c.kill_mon(2)
    for _ in range(5):
        c.tick(dt=6.0)
    import pytest
    with pytest.raises(RuntimeError, match="quorum"):
        c.mons[0].mark_osd_out(1)


def test_pool_creation_after_failover():
    """The bootstrap topology is committed as an epoch, so a successor
    leader can create pools (the topology survives mon.0's death even if
    nothing else was ever published)."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.kill_mon(0)
    for _ in range(6):
        c.tick(dt=6.0)
    leader = c.mon
    assert leader.name == "mon.1"
    assert leader.osdmap.max_osd == 5, "bootstrap topology must replicate"
    c.create_ec_pool("late", k=3, m=2, pg_num=8, plugin="tpu")
    cl = c.client("client.l")
    assert cl.write_full("late", "o", payload(seed=4)) == 0
    assert cl.read("late", "o") == payload(seed=4)


def test_mgr_follows_leader_failover():
    """The mgr resolves the CURRENT leader: balancer commits after a
    failover reach the live quorum, not the dead mon."""
    c = MiniCluster(n_osds=8, n_mons=3)
    c.create_replicated_pool("r", size=3, pg_num=64)
    c.kill_mon(0)
    for _ in range(6):
        c.tick(dt=6.0)
    assert c.mon.name == "mon.1"
    changes = c.mgr.balancer_optimize()
    if changes:
        # the commit landed on the live quorum (not the dead mon.0) and
        # both survivors agree
        live = [m for m in c.mons if m.name != "mon.0"]
        assert live[0].osdmap.pg_upmap_items
        assert live[0].osdmap.epoch == live[1].osdmap.epoch
        assert len(live[0].osdmap.pg_upmap_items) == \
            len(live[1].osdmap.pg_upmap_items)
    assert c.mgr.osdmap.epoch == c.mon.osdmap.epoch


def test_multimon_checkpoint_restore(tmp_path):
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    cl = c.client("client.c")
    assert cl.write_full("p", "o", payload(seed=5)) == 0
    c.checkpoint(str(tmp_path / "ck"))
    r = MiniCluster.restore(str(tmp_path / "ck"))
    assert len(r.mons) == 3
    for m in r.mons:
        assert m.osdmap.epoch == r.mons[0].osdmap.epoch
    cl2 = r.client("client.c2")
    assert cl2.read("p", "o") == payload(seed=5)
    r.kill_mon(0)
    for _ in range(6):
        r.tick(dt=6.0)
    assert r.mon.name == "mon.1"
    assert cl2.read("p", "o") == payload(seed=5)


# ---- real paxos commit semantics (Paxos.cc begin/accept/commit) -----------

def test_partitioned_leader_value_never_observable():
    """The leader is partitioned so its BEGIN reaches no peon (a
    minority: itself).  The value must never be committed or observable
    on ANY mon — commit requires an accept quorum, not just BEGIN."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    base_epoch = c.mons[0].osdmap.epoch
    base_weight = c.mons[0].osdmap.osd_weight[4]
    # cut the leader's OUTBOUND links: its BEGIN reaches nobody, while
    # it still hears the peons' pings (believes the quorum is fine)
    c.network.blackhole("mon.0", "mon.1")
    c.network.blackhole("mon.0", "mon.2")
    c.mons[0].mark_osd_out(4)          # stages + begins, cannot commit
    c.network.pump()
    # never committed anywhere — including the proposing leader itself
    for m in c.mons:
        assert m.osdmap.epoch == base_epoch, m.name
        assert m.osdmap.osd_weight[4] == base_weight, m.name
        assert len(m.incrementals) == base_epoch
    # survivors elect mon.1 (the old leader's pings are also dark)
    for _ in range(8):
        c.tick(dt=6.0)
    leader = c.mon
    assert leader.name == "mon.1" and leader.is_leader()
    # the uncommitted value did not leak into the new quorum's history
    assert leader.osdmap.osd_weight[4] == base_weight
    for m in (c.mons[1], c.mons[2]):
        for inc in m.incrementals:
            assert inc.new_weight.get(4) != 0
    # the new quorum keeps committing
    leader.mark_osd_out(3)
    c.network.pump()
    assert c.mons[1].osdmap.osd_weight[3] == 0
    assert c.mons[2].osdmap.osd_weight[3] == 0
    # partition heals: the old leader discards its uncommitted value
    # and converges on the quorum's history
    c.network.blackhole("mon.0", "mon.1", on=False)
    c.network.blackhole("mon.0", "mon.2", on=False)
    c.mons[0].start_election()
    c.network.pump()
    for _ in range(4):
        c.tick(dt=6.0)
    assert c.mons[0].osdmap.epoch == c.mons[1].osdmap.epoch
    assert c.mons[0].osdmap.osd_weight[4] == base_weight
    assert c.mons[0].osdmap.osd_weight[3] == 0
    assert c.mons[0]._uncommitted is None


def test_majority_accepted_value_survives_leader_death():
    """A value the peons staged (BEGIN delivered, majority accept) but
    whose commit the dying leader never sent must be finished by the
    next leader through collect/LAST re-proposal — paxos' completion
    guarantee."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    # the peons' ACCEPTs never reach the leader: BEGIN lands (staged on
    # a majority) but the leader cannot learn it and cannot commit
    c.network.blackhole("mon.1", "mon.0")
    c.network.blackhole("mon.2", "mon.0")
    c.mons[0].mark_osd_out(4)
    c.network.pump()
    assert c.mons[0].osdmap.osd_weight[4] != 0   # leader: uncommitted
    assert c.mons[1]._uncommitted is not None    # peons: staged
    assert c.mons[2]._uncommitted is not None
    c.kill_mon(0)
    for _ in range(8):
        c.tick(dt=6.0)
    leader = c.mon
    assert leader.name == "mon.1" and leader.is_leader()
    c.network.pump()
    # the staged value was re-proposed and committed by the new leader
    assert c.mons[1].osdmap.osd_weight[4] == 0
    assert c.mons[2].osdmap.osd_weight[4] == 0
    assert c.mons[1]._uncommitted is None
    assert c.mons[2]._uncommitted is None


def test_healed_leader_discards_ghost_topology():
    """An ex-leader whose TOPOLOGY proposal (in-place map mutation) died
    uncommitted must purge the ghost state when it re-wins the election
    after healing — the next snapshot commit must not resurrect it."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    pid = c.mons[0].osdmap.lookup_pg_pool_name("p")
    c.network.blackhole("mon.0", "mon.1")
    c.network.blackhole("mon.0", "mon.2")
    # topology proposal: mutates mon.0's working map in place
    c.mons[0].pool_snap_create("p", "ghost")
    c.mons[0].publish()
    c.network.pump()
    # survivors elect mon.1 and commit an epoch of their own
    for _ in range(8):
        c.tick(dt=6.0)
    assert c.mon.name == "mon.1"
    c.mon.mark_osd_out(4)
    c.network.pump()
    # heal: mon.0 (lowest rank) re-wins; its ghost snap must vanish
    c.network.blackhole("mon.0", "mon.1", on=False)
    c.network.blackhole("mon.0", "mon.2", on=False)
    c.mons[0].start_election()
    c.network.pump()
    for _ in range(4):
        c.tick(dt=6.0)
    assert c.mons[0].is_leader()
    assert c.mons[0].osdmap.pools[pid].snaps == {}, "ghost snap survived"
    assert c.mons[0].osdmap.epoch == c.mons[1].osdmap.epoch
    # the next topology commit must not resurrect it anywhere
    c.mons[0].pool_snap_create("p", "real")
    c.mons[0].publish()
    c.network.pump()
    for m in c.mons:
        assert list(m.osdmap.pools[pid].snaps.values()) == ["real"], m.name


def test_topology_snapshot_folds_deferred_deltas():
    """A topology publish issued while a delta proposal is still in
    flight must not snapshot the pre-delta working map and silently
    revert the delta at commit."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    leader = c.mons[0]
    # no pump between the two: the mark is still in flight (deferred)
    leader.mark_osd_out(4)
    leader.pool_snap_create("p", "s1")
    leader.publish()
    c.network.pump()
    pid = leader.osdmap.lookup_pg_pool_name("p")
    for m in c.mons:
        assert m.osdmap.osd_weight[4] == 0, m.name
        assert list(m.osdmap.pools[pid].snaps.values()) == ["s1"], m.name


def test_demoted_queued_topology_proposal_leaves_no_ghost():
    """A QUEUED (behind an in-flight delta) topology proposal dropped at
    demotion must purge its in-place working-map state."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    pid = c.mons[0].osdmap.lookup_pg_pool_name("p")
    c.network.blackhole("mon.0", "mon.1")
    c.network.blackhole("mon.0", "mon.2")
    # delta goes inflight (never accepted); topology queues behind it
    c.mons[0].mark_osd_out(4)
    c.mons[0].pool_snap_create("p", "ghost")
    c.mons[0].publish()
    c.network.pump()
    for _ in range(8):
        c.tick(dt=6.0)
    assert c.mon.name == "mon.1"
    c.mon.mark_osd_out(3)
    c.network.pump()
    c.network.blackhole("mon.0", "mon.1", on=False)
    c.network.blackhole("mon.0", "mon.2", on=False)
    c.mons[0].start_election()
    c.network.pump()
    for _ in range(4):
        c.tick(dt=6.0)
    assert c.mons[0].is_leader()
    # neither the ghost snap nor the never-accepted mark survives
    assert c.mons[0].osdmap.pools[pid].snaps == {}
    c.mons[0].pool_snap_create("p", "real")
    c.mons[0].publish()
    c.network.pump()
    for m in c.mons:
        assert list(m.osdmap.pools[pid].snaps.values()) == ["real"], m.name
        assert m.osdmap.osd_weight[3] == 0, m.name


def test_auto_out_weight_restored_across_leader_failover():
    """The pre-out weight memo rides the replicated map
    (osd_xinfo_t::old_weight, src/osd/OSDMap.h), so an osd that was
    AUTOMATICALLY marked out recovers its weight when it boots even if
    a different mon leads by then (OSDMonitor::prepare_boot +
    mon_osd_auto_mark_auto_out_in)."""
    c = MiniCluster(n_osds=5, n_mons=3)
    c.create_ec_pool("p", k=3, m=2, pg_num=8, plugin="tpu")
    for m in c.mons:
        m.down_out_interval = 10.0
    victim = 4
    w_before = c.mon.osdmap.osd_weight[victim]
    assert w_before > 0
    c.kill_osd(victim)
    for _ in range(8):                    # detect + down->out eviction
        c.tick(dt=6.0)
    assert not c.mon.osdmap.is_up(victim)
    assert c.mon.osdmap.osd_weight[victim] == 0
    # the memo is in every mon's replicated map, not leader RAM
    for m in c.mons:
        assert m.osdmap.osd_old_weight.get(victim) == w_before
    c.kill_mon(0)
    for _ in range(6):
        c.tick(dt=6.0)
    assert c.mon.name == "mon.1" and c.mon.is_leader()
    c.revive_osd(victim)
    for _ in range(4):
        c.tick(dt=6.0)
    m = c.mon.osdmap
    assert m.is_up(victim)
    assert m.osd_weight[victim] == w_before, \
        "auto-out weight memo lost across leader failover"
    assert victim not in m.osd_old_weight  # memo consumed


def test_wire_command_peon_relay_and_dedup():
    """A MMonCommand landing on a PEON must not mutate that mon: it is
    relayed to the leader (Monitor::forward_request_leader role), the
    ack routes back through the peon, and replays — by either route —
    are answered from the (origin, tid) ack cache instead of
    re-executing a non-idempotent command (snap id allocation)."""
    from ceph_tpu.msg.messages import MMonCommand
    c = MiniCluster(n_osds=3, n_mons=3)
    c.create_ec_pool("p", k=2, m=1, pg_num=8, plugin="jerasure")
    cl = c.client("client.w")

    def send(mon, tid):
        c.network.send("client.w", mon.name, MMonCommand(
            tid=tid, cmd="selfmanaged_snap_create",
            args={"pool_name": "p"}))
        c.network.pump()
        return cl._mon_acks.pop(tid)

    peon = c.mons[1]
    assert not peon.is_leader()
    ack1 = send(peon, 901)
    assert ack1.result == 0
    snapid = ack1.data["value"]
    assert snapid > 0
    # replay via the peon: dedup -> the SAME snap id, no re-allocation
    ack2 = send(peon, 901)
    assert ack2.result == 0 and ack2.data["value"] == snapid
    # replay direct to the leader: the cache keys by ORIGIN, so a
    # different route still dedups
    ack3 = send(c.mons[0], 901)
    assert ack3.result == 0 and ack3.data["value"] == snapid
    # a fresh tid is a fresh command: allocates the next id
    ack4 = send(peon, 902)
    assert ack4.result == 0 and ack4.data["value"] != snapid
    # the committed allocation replicated to every mon; no peon
    # diverged by executing locally
    for m in c.mons:
        pool = m.osdmap.pools[m.osdmap.lookup_pg_pool_name("p")]
        assert pool.snap_seq >= ack4.data["value"]


# ---------------------------------------------------------------------------
# starvation-aware liveness grace (the loadflaky root cause)
# ---------------------------------------------------------------------------

def _three_mons():
    from ceph_tpu.msg.messenger import Network
    from ceph_tpu.mon.monitor import Monitor
    net = Network()
    names = ["mon.0", "mon.1", "mon.2"]
    mons = [Monitor(net, name=n, rank=r,
                    peers=[p for p in names if p != n])
            for r, n in enumerate(names)]
    mons[0].start_election()
    net.pump()
    assert mons[0].is_leader() and mons[0].quorum == {0, 1, 2}
    return net, mons


def test_starved_tick_does_not_start_spurious_election():
    """The loadflaky election-timing root cause (ROADMAP residual
    debt 2): a peon whose OWN tick cadence stalled past the ping
    grace — an oversubscribed host, not a dead leader — must NOT
    start an election off stamps it had no chance to refresh.  The
    stall is credited to every liveness stamp before grace runs."""
    from ceph_tpu.mon.monitor import MON_PING_GRACE
    net, mons = _three_mons()
    t = 1000.0
    for m in mons:
        m.tick(t)
    net.pump()
    peon = mons[1]
    epoch_before = peon.election_epoch
    # the process was descheduled for 3 grace periods; it wakes and
    # ticks BEFORE its pump drains the leader's queued keepalives —
    # exactly the oversubscribed-box interleaving
    peon.tick(t + 3 * MON_PING_GRACE)
    assert peon.election_epoch == epoch_before
    assert peon.leader_rank == 0
    # and the cluster still converges normally afterwards
    for m in mons:
        m.tick(t + 3 * MON_PING_GRACE + 1.0)
    net.pump()
    assert mons[0].is_leader() and mons[0].quorum == {0, 1, 2}


def test_genuinely_silent_leader_still_times_out():
    """The compensation must not mask real failure: with a REGULAR
    tick cadence and a leader that stopped answering, the peon
    re-elects one grace period later, exactly as before."""
    from ceph_tpu.mon.monitor import MON_PING_GRACE
    net, mons = _three_mons()
    t = 1000.0
    for m in mons:
        m.tick(t)
    net.pump()
    peon = mons[1]
    epoch_before = peon.election_epoch
    # mon.0 is dead: the fabric drops its traffic, only the peons
    # tick, in small steps, and pings to the corpse go unanswered
    net.set_down("mon.0", True)
    step = 1.0
    now = t
    while now < t + MON_PING_GRACE + 2 * step:
        now += step
        peon.tick(now)
        mons[2].tick(now)
        # drain peon<->peon pings only; the dead leader neither sends
        # nor answers
        net.pump()
    assert peon.election_epoch > epoch_before


def test_sustained_slow_cadence_still_detects_dead_leader():
    """The compensation is CAPPED: a host that stays slow (every tick
    gap over grace/2) delays failover by at most one extra grace
    period — it can never postpone detecting a dead leader forever."""
    from ceph_tpu.mon.monitor import MON_PING_GRACE
    net, mons = _three_mons()
    t = 1000.0
    for m in mons:
        m.tick(t)
    net.pump()
    peon = mons[1]
    epoch_before = peon.election_epoch
    net.set_down("mon.0", True)
    # every gap is 0.6*grace: each tick would be compensated if the
    # credit were unbounded
    step = MON_PING_GRACE * 0.6
    now = t
    for _ in range(8):           # 4.8 grace periods of slow ticks
        now += step
        peon.tick(now)
        mons[2].tick(now)
        net.pump()
    assert peon.election_epoch > epoch_before


def test_first_tick_at_time_zero_still_compensates():
    """A deterministic clock starting at 0.0 must not disable the
    compensation (falsy-zero guard): tick(0.0) then a starved jump
    must NOT start a spurious election."""
    from ceph_tpu.mon.monitor import MON_PING_GRACE
    net, mons = _three_mons()
    for m in mons:
        m.tick(0.0)
    net.pump()
    peon = mons[1]
    epoch_before = peon.election_epoch
    peon.tick(3 * MON_PING_GRACE)      # starved jump from t=0
    assert peon.election_epoch == epoch_before
    assert peon.leader_rank == 0
