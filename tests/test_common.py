"""Common subsystems: config registry, perf counters, admin socket,
op tracker, and their cluster wiring.

Mirrors the reference surfaces: md_config_t observers (common/config.h),
PerfCounters dump (common/perf_counters.cc), the admin-socket command
contract (`perf dump`, `dump_historic_ops`), and TrackedOp event
timelines (common/TrackedOp.cc).
"""
import json

import pytest

from ceph_tpu.common import (
    AdminSocket, OpTracker, PerfCountersBuilder, PerfCountersCollection,
)
from ceph_tpu.common.config import ConfigProxy


def test_config_defaults_and_overrides():
    conf = ConfigProxy()
    assert conf.get_val("osd_pool_default_size") == 3
    conf.set_val("osd_pool_default_size", "5")
    assert conf.get_val("osd_pool_default_size") == 5
    conf.rm_val("osd_pool_default_size")
    assert conf.get_val("osd_pool_default_size") == 3


def test_config_observer_notified():
    conf = ConfigProxy()
    seen = []
    conf.add_observer("osd_heartbeat_grace",
                      lambda k, v: seen.append((k, v)))
    conf.set_val("osd_heartbeat_grace", 11)
    assert seen == [("osd_heartbeat_grace", 11.0)]


def test_config_ini_parsing():
    conf = ConfigProxy()
    conf.parse_ini("[global]\nosd pool default pg num = 64\n")
    assert conf.get_val("osd_pool_default_pg_num") == 64


def test_perf_counters_dump():
    b = PerfCountersBuilder("test", 0, 10)
    b.add_u64_counter(1, "ops")
    b.add_time_avg(2, "latency")
    pc = b.create_perf_counters()
    pc.inc(1)
    pc.inc(1, 5)
    pc.tinc(2, 0.25)
    pc.tinc(2, 0.75)
    d = pc.dump()
    assert d["ops"] == 6
    assert d["latency"] == {"sum": 1.0, "avgcount": 2}
    coll = PerfCountersCollection()
    coll.add(pc)
    assert coll.dump()["test"]["ops"] == 6
    assert coll.dump(counter="ops")["test"] == {"ops": 6}


def test_perf_counters_u64_avgcount_semantics():
    """inc()/dec() on a plain u64 must not move the avgcount
    denominator (the reference only bumps avgcount on LONGRUNAVG
    counters) — an inc-only count would skew any average built over
    the counter later."""
    b = PerfCountersBuilder("avg", 0, 10)
    b.add_u64_counter(1, "plain")
    b.add_u64(2, "gauge_like")
    b.add_time_avg(3, "lat")
    pc = b.create_perf_counters()
    pc.inc(1, 3)
    pc.dec(1, 1)
    pc.inc(2, 7)
    pc.dec(2, 2)
    assert pc.get(1) == 2
    assert pc.get(2) == 5
    assert pc._by_idx[1].count == 0
    assert pc._by_idx[2].count == 0
    # LONGRUNAVG counters DO advance avgcount via inc, and refuse dec
    pc.inc(3)
    assert pc._by_idx[3].count == 1
    with pytest.raises(AssertionError):
        pc.dec(3)


def test_admin_socket_dispatch():
    asok = AdminSocket()
    asok.register("perf dump", lambda c, a: {"x": 1})
    assert asok.execute("perf dump") == {"x": 1}
    # longest-prefix match, like the reference hook matching
    assert asok.execute("perf dump osd") == {"x": 1}
    out = json.loads(asok.execute_json("nope"))
    assert "error" in out
    helps = asok.execute("help")
    assert "perf dump" in helps


def test_op_tracker_history():
    clock = [0.0]
    t = OpTracker(history_size=2, clock=lambda: clock[0])
    op = t.create_request(1, "osd_op(write p/o)")
    clock[0] = 0.5
    op.mark_event("sub_op_sent")
    assert t.dump_ops_in_flight()["num_ops"] == 1
    clock[0] = 1.0
    op.finish()
    assert t.dump_ops_in_flight()["num_ops"] == 0
    hist = t.dump_historic_ops()
    assert len(hist["ops"]) == 1
    assert hist["ops"][0]["age"] == 1.0
    events = [e["event"] for e in hist["ops"][0]["type_data"]["events"]]
    assert events == ["initiated", "sub_op_sent"]
    # bounded ring
    for i in range(5):
        t.create_request(10 + i, "x").finish()
    assert len(t.dump_historic_ops()["ops"]) == 2


def test_cluster_admin_socket_end_to_end():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("adm", k=3, m=2, pg_num=8)
    cl = c.client("client.adm")
    cl.write_full("adm", "o1", b"y" * 20000)
    cl.read("adm", "o1")
    perf = c.admin_socket.execute("perf dump")
    total_w = sum(d.get("op_w", 0) for d in perf.values())
    total_sub = sum(d.get("subop_w", 0) for d in perf.values())
    assert total_w == 1
    assert total_sub == 5  # k+m shard writes
    # only OSD loggers carry op_latency (the collection also holds
    # non-OSD loggers, e.g. the dispatch scheduler's)
    lat = [d["op_latency"] for d in perf.values()
           if d.get("op_latency", {}).get("avgcount")]
    assert lat and all(e["sum"] >= 0 for e in lat)
    st = c.admin_socket.execute("status")
    assert st["health"] == "HEALTH_OK"
    hist = c.admin_socket.execute("dump_historic_ops")
    ops = [op for d in hist.values() for op in d["ops"]]
    assert any("osd_op(write" in op["description"] for op in ops)
    assert all(op["trace_id"] > 0 for op in ops)
    cfg = c.admin_socket.execute("config show")
    assert "osd_heartbeat_grace" in cfg
