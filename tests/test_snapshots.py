"""Pool snapshots: clone-on-first-write, read-at-snap, rollback, trim.

PrimaryLogPG's snapset/clone model (make_writeable's clone step) scoped
to pool snaps: a write after mksnap clones the pre-write state into an
ordinary PG object; reads at a snap resolve through the snapset.
"""
import pytest

from ceph_tpu.client import ObjectOperation
from ceph_tpu.cluster import MiniCluster


def make(fixture):
    if fixture == "ec":
        c = MiniCluster(n_osds=6)
        c.create_ec_pool("sp", k=2, m=1, plugin="isa", pg_num=8)
    else:
        c = MiniCluster(n_osds=4)
        c.create_replicated_pool("sp", size=3, pg_num=8)
    return c, c.client("client.s")


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_snap_read_and_head(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "o", b"version-one")
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"version-two-longer")
    assert cl.read("sp", "o") == b"version-two-longer"
    assert cl.read("sp", "o", snap="s1") == b"version-one"
    # a second write after the same snap must NOT re-clone
    cl.write_full("sp", "o", b"version-three")
    assert cl.read("sp", "o", snap="s1") == b"version-one"
    assert cl.read("sp", "o") == b"version-three"


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_multiple_snaps_layered(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "o", b"v1")
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    cl.snap_create("sp", "s2")
    cl.write_full("sp", "o", b"v3")
    assert cl.read("sp", "o", snap="s1") == b"v1"
    assert cl.read("sp", "o", snap="s2") == b"v2"
    assert cl.read("sp", "o") == b"v3"
    # unmodified-since-snap object serves its head at the snap
    cl.write_full("sp", "calm", b"steady")
    cl.snap_create("sp", "s3")
    assert cl.read("sp", "calm", snap="s3") == b"steady"


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_object_created_after_snap_is_absent_at_snap(fixture):
    c, cl = make(fixture)
    cl.snap_create("sp", "early")
    cl.write_full("sp", "late", b"newcomer")
    with pytest.raises(IOError):
        cl.read("sp", "late", snap="early")
    assert cl.read("sp", "late") == b"newcomer"


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_delete_after_snap_preserves_snap_view(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "doomed", b"precious")
    cl.snap_create("sp", "keep")
    assert cl.remove("sp", "doomed") == 0
    with pytest.raises(IOError):
        cl.read("sp", "doomed")
    assert cl.read("sp", "doomed", snap="keep") == b"precious"


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_partial_write_and_vector_trigger_clone(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "o", b"A" * 100)
    cl.snap_create("sp", "s1")
    # rmw offset write must clone first
    cl.write("sp", "o", b"BBB", offset=10)
    assert cl.read("sp", "o", snap="s1") == b"A" * 100
    assert cl.read("sp", "o")[10:13] == b"BBB"
    cl.snap_create("sp", "s2")
    # vector write must clone too
    r, _ = cl.operate("sp", "o", ObjectOperation()
                      .write_full(b"C" * 50).set_xattr("t", b"1"))
    assert r == 0
    at_s2 = cl.read("sp", "o", snap="s2")
    assert at_s2[10:13] == b"BBB" and len(at_s2) == 100
    assert cl.read("sp", "o") == b"C" * 50


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_rollback(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "o", b"golden")
    cl.snap_create("sp", "g")
    cl.write_full("sp", "o", b"corrupted")
    assert cl.rollback("sp", "o", "g") == 0
    assert cl.read("sp", "o") == b"golden"


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_snap_rm_trims_clones(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "o", b"v1")
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    assert cl.read("sp", "o", snap="s1") == b"v1"

    def clone_count():
        n = 0
        for osd in c.osds.values():
            for cid in osd.store.list_collections():
                for ho in osd.store.list_objects(cid):
                    if "\x00snap\x00" in ho.oid:
                        n += 1
        return n

    assert clone_count() > 0
    cl.snap_remove("sp", "s1")
    c.network.pump()
    assert clone_count() == 0
    assert cl.read("sp", "o") == b"v2"


def test_snapshots_survive_checkpoint_restore(tmp_path):
    c, cl = make("ec")
    cl.write_full("sp", "o", b"old-state")
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"new-state")
    c.checkpoint(str(tmp_path / "ckpt"))
    c2 = MiniCluster.restore(str(tmp_path / "ckpt"))
    cl2 = c2.client("client.r")
    assert cl2.read("sp", "o") == b"new-state"
    assert cl2.read("sp", "o", snap="s1") == b"old-state"


def test_snapshots_survive_failure_and_recovery():
    c, cl = make("ec")
    cl.write_full("sp", "o", b"pre-snap")
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"post-snap")
    _pg, victim = cl._calc_target(cl.lookup_pool("sp"), "o")
    c.kill_osd(victim)
    for _ in range(6):
        c.tick(dt=6.0)
    c.mark_osd_out(victim)
    c.run_recovery()
    c.network.pump()
    c.run_recovery()
    c.network.pump()
    assert cl.read("sp", "o") == b"post-snap"
    assert cl.read("sp", "o", snap="s1") == b"pre-snap"


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_rollback_restores_xattrs_and_guards_errors(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "o", b"golden")
    cl.setxattr("sp", "o", "tag", b"v1")
    cl.snap_create("sp", "g")
    cl.write_full("sp", "o", b"corrupted")
    cl.setxattr("sp", "o", "tag", b"v2")
    cl.setxattr("sp", "o", "extra", b"junk")
    assert cl.rollback("sp", "o", "g") == 0
    assert cl.read("sp", "o") == b"golden"
    assert cl.getxattrs("sp", "o") == {"tag": b"v1"}
    # snap-targeted vectors are read-only
    from ceph_tpu.client import ObjectOperation
    r, _ = cl.operate("sp", "o", ObjectOperation().write_full(b"x"),
                      snap="g")
    assert r == -30                       # EROFS


@pytest.mark.parametrize("fixture", ["ec", "rep"])
def test_no_clone_after_all_snaps_removed(fixture):
    c, cl = make(fixture)
    cl.write_full("sp", "o", b"v1")
    cl.snap_create("sp", "s1")
    cl.snap_remove("sp", "s1")
    c.network.pump()
    cl.write_full("sp", "o", b"v2")       # must NOT clone
    clones = sum(1 for o in c.osds.values()
                 for cid in o.store.list_collections()
                 for ho in o.store.list_objects(cid)
                 if "\x00snap\x00" in ho.oid)
    assert clones == 0


def test_stale_peer_cannot_resurrect_trimmed_snapset():
    c, cl = make("ec")
    cl.write_full("sp", "o", b"v1")
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    # take one replica down, trim while it is away
    pid = cl.lookup_pool("sp")
    pgid, primary = cl._calc_target(pid, "o")
    away = next(o for o in c.osds if o != primary
                and c.osds[o].pgs.get(pgid) is not None)
    c.kill_osd(away)
    for _ in range(6):
        c.tick(dt=6.0)
    cl.snap_remove("sp", "s1")
    c.network.pump()
    # rejoin: peering must NOT re-adopt the dead snapset
    c.revive_osd(away)
    for _ in range(4):
        c.tick(dt=6.0)
    c.run_recovery()
    c.network.pump()
    for o in c.osds.values():
        pg = o.pgs.get(pgid)
        if pg is not None and pg.is_primary():
            ents = pg.snapsets.get("o", [])
            from ceph_tpu.osd.pg_log import SNAP_CLONE
            assert not any(k == SNAP_CLONE for _s, k in ents), ents
    assert cl.read("sp", "o") == b"v2"


def test_clone_preserves_omap_on_replicated():
    c, cl = make("rep")
    cl.write_full("sp", "o", b"body")
    cl.omap_set("sp", "o", {"k": b"v-snap"})
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"body2")
    cl.omap_set("sp", "o", {"k": b"v-head"})
    r, res = cl.operate("sp", "o", ObjectOperation().omap_get(),
                        snap="s1")
    assert r == 0
    from ceph_tpu.msg.kv import unpack_kv
    assert unpack_kv(res[0][1]) == {"k": b"v-snap"}
    assert cl.omap_get("sp", "o") == {"k": b"v-head"}


def test_stale_peer_tombstone_below_live_clone():
    """A trim tombstone sitting BELOW a surviving live clone must still
    dominate a stale peer's pre-trim history of the same max seq
    (merge_snapsets rank tiebreak): the rejoined peer may never
    re-reference the trimmed clone."""
    from ceph_tpu.osd.pg_log import SNAP_CLONE, SNAP_TRIMMED
    c, cl = make("ec")
    cl.write_full("sp", "o", b"v1")
    cl.snap_create("sp", "s1")
    cl.write_full("sp", "o", b"v2")
    cl.snap_create("sp", "s2")
    cl.write_full("sp", "o", b"v3")       # snapset: clone@s1, clone@s2
    pid = cl.lookup_pool("sp")
    pgid, primary = cl._calc_target(pid, "o")
    away = next(o for o in c.osds if o != primary
                and c.osds[o].pgs.get(pgid) is not None)
    c.kill_osd(away)
    for _ in range(6):
        c.tick(dt=6.0)
    # trim only the LOWER snap: tombstone@s1 below the live clone@s2
    cl.snap_remove("sp", "s1")
    c.network.pump()
    c.revive_osd(away)
    for _ in range(4):
        c.tick(dt=6.0)
    c.run_recovery()
    c.network.pump()
    for o in c.osds.values():
        pg = o.pgs.get(pgid)
        if pg is not None:
            ents = pg.snapsets.get("o", [])
            kinds = [k for _s, k in ents]
            assert kinds.count(SNAP_CLONE) <= 1, ents
            if pg.is_primary():
                assert SNAP_TRIMMED in kinds, ents
    assert cl.read("sp", "o", snap="s2") == b"v2"
    assert cl.read("sp", "o") == b"v3"
