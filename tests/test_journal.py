"""journal-lite: append/replay ordering, commit/trim, torn tails.

Mirrors the reference's src/test/journal surface at lite scale:
splayed append layout, tid-ordered replay from a commit position,
slowest-client trim gating, torn-tail crc detection, and crash-replay
(reopen scans the next tid from the objects, not from memory).
"""
import json
import struct

import pytest

from ceph_tpu.cluster import MiniCluster
from ceph_tpu.journal import Journaler, JournalError


@pytest.fixture()
def jr():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("jp", size=3, pg_num=8)
    cl = c.client("client.j")
    j = Journaler(cl, "jp", "img1", entries_per_object=4)
    j.create(order=12, splay_width=3)
    return c, cl, j


def test_append_replay_order_and_splay(jr):
    c, cl, j = jr
    tids = [j.append(f"entry-{i}".encode()) for i in range(20)]
    assert tids == list(range(20))
    got = list(j.replay())
    assert [t for t, _ in got] == list(range(20))
    assert [p for _, p in got] == [f"entry-{i}".encode()
                                   for i in range(20)]
    # entries really splay round-robin over splay_width objects
    assert j._objno(0) == 0 and j._objno(1) == 1 and j._objno(2) == 2
    assert j._objno(3) == 0                      # wraps within the set
    assert j._objno(12) == 3                     # next object set
    # replay from a commit position skips applied entries
    assert [t for t, _ in j.replay(after_tid=14)] == [15, 16, 17, 18, 19]


def test_commit_trim_slowest_client(jr):
    c, cl, j = jr
    j.register_client("local")
    j.register_client("mirror")
    for i in range(30):
        j.append(b"x%d" % i)
    j.commit("local", 29)
    j.commit("mirror", 5)
    assert j.committed_tid() == 5
    assert j.trim() == 0                         # mirror pins set 0
    j.commit("mirror", 23)
    assert j.trim() == 2                         # sets 0,1 trimmed
    # trimmed entries no longer replay; order resumes at the boundary
    assert [t for t, _ in j.replay()] == []      # gap at tid 0 -> stop
    assert [t for t, _ in j.replay(after_tid=23)] == list(range(24, 30))
    # commit never regresses
    j.commit("mirror", 2)
    assert j.committed_tid() == 23


def test_reopen_resumes_tids(jr):
    c, cl, j = jr
    for i in range(7):
        j.append(b"a%d" % i)
    j2 = Journaler(cl, "jp", "img1", entries_per_object=4)
    j2.open()
    assert j2.append(b"after-reopen") == 7
    assert [t for t, _ in j2.replay()] == list(range(8))


def test_torn_tail_stops_replay(jr):
    c, cl, j = jr
    for i in range(3):
        j.append(b"good-%d" % i)
    # corrupt the tail of tid 2's frame (objno = 2)
    oid = j._data_oid(j._objno(2))
    blob = cl.read("jp", oid)
    cl.write_full("jp", oid, blob[:-2] + b"XX")  # crc now wrong
    got = list(j.replay())
    assert [t for t, _ in got] == [0, 1]         # stops before the tear
    # a truncated partial frame is also detected
    cl.write_full("jp", oid, blob[: len(blob) // 2])
    assert [t for t, _ in j.replay()] == [0, 1]


def test_journal_lifecycle_errors(jr):
    c, cl, j = jr
    with pytest.raises(JournalError):
        j.create()                               # EEXIST
    j.register_client("a")
    with pytest.raises(JournalError):
        j.register_client("a")
    j.unregister_client("a")
    with pytest.raises(JournalError):
        j.unregister_client("a")
    j.remove()
    with pytest.raises(JournalError):
        j.open()


def test_active_set_write_ahead_of_first_frame(jr):
    """The watermark bumps BEFORE the first frame of a new object set
    lands: a crash between the two leaves only an empty set to scan —
    never an applied-but-invisible entry whose tid gets silently
    reused (which a mirror would then never see)."""
    c, cl, j = jr
    per_set = j._entries_per_set()
    for i in range(per_set):                    # fill set 0 exactly
        j.append(b"x%d" % i)
    # simulate the crash: the metadata bump succeeds, the data append
    # never happens
    real_append = cl.append
    def boom(pool, oid, data):
        if oid.startswith("journal_data."):
            raise IOError("crash before data append")
        return real_append(pool, oid, data)
    cl.append = boom
    with pytest.raises(IOError):
        j.append(b"first-of-set-1")
    cl.append = real_append
    assert j.get_metadata()["active_set"] == 1  # write-ahead held
    # crash recovery: a fresh journaler re-derives the same next tid
    j2 = Journaler(cl, "jp", "img1", entries_per_object=4)
    md = j2.open()
    assert j2._next_tid == per_set
    t = j2.append(b"retry")
    assert t == per_set
    assert [p for tid, p in j2.replay() if tid == t] == [b"retry"]


def test_crash_into_empty_set_with_lagging_trim(jr):
    """The reviewer's corner: several live sets (trim lagging), crash
    in the write-ahead window so active_set points at an EMPTY set two
    past minimum_set.  Recovery must walk down to the first non-empty
    set (not just peek at active_set and minimum_set), and the
    recovered journaler must keep appending without trying to regress
    the stored watermark."""
    c, cl, j = jr
    per_set = j._entries_per_set()
    for i in range(2 * per_set):                # sets 0 and 1 full
        j.append(b"e%d" % i)
    real_append = cl.append
    def boom(pool, oid, data):
        if oid.startswith("journal_data."):
            raise IOError("crash before data append")
        return real_append(pool, oid, data)
    cl.append = boom
    with pytest.raises(IOError):
        j.append(b"first-of-set-2")             # bumped watermark only
    cl.append = real_append
    assert j.get_metadata()["active_set"] == 2
    j2 = Journaler(cl, "jp", "img1", entries_per_object=4)
    j2.open()
    assert j2._next_tid == 2 * per_set          # no tid reuse
    t = j2.append(b"recovered")                 # must not raise -22
    assert t == 2 * per_set
    assert [t2 for t2, _ in j2.replay()][-1] == t
