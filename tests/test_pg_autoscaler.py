"""pg_autoscaler + MPGStats: usage-driven PG budgeting.

The reference's OSDs report per-PG stats to the mgr (MPGStats /
MgrClient), whose pg_autoscaler module (pybind/mgr/pg_autoscaler/)
computes each pool's share of the PG budget from its share of used
bytes and grows pg_num toward a power-of-two target.  Shrinking is
report-only here (splitting exists, merging does not), matching the
module's warn mode.
"""
import numpy as np

from ceph_tpu.cluster import MiniCluster


def payload(n, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=n, dtype=np.uint8).tobytes()


def test_mpgstats_aggregate_to_pool_usage():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("busy", size=3, pg_num=4)
    c.create_replicated_pool("idle", size=3, pg_num=4)
    cl = c.client("client.s")
    for i in range(8):
        cl.write_full("busy", f"o{i}", payload(10000, seed=i))
    cl.write_full("idle", "only", payload(100, seed=99))
    c.tick()                      # primaries report MPGStats
    stats = c.mgr.pool_stats()
    busy = cl.lookup_pool("busy")
    idle = cl.lookup_pool("idle")
    assert stats[busy]["objects"] == 8
    assert stats[busy]["bytes"] == 8 * 10000
    assert stats[idle]["objects"] == 1
    assert stats[idle]["bytes"] == 100


def test_autoscaler_grows_hot_pool_and_data_survives():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("hot", size=2, pg_num=4)
    c.create_replicated_pool("cold", size=2, pg_num=4)
    cl = c.client("client.s")
    blobs = {}
    for i in range(24):
        blobs[f"h{i}"] = payload(20000, seed=i)
        cl.write_full("hot", f"h{i}", blobs[f"h{i}"])
    cl.write_full("cold", "c0", payload(50, seed=77))
    c.tick()
    recs = c.mgr.pg_autoscale(target_pgs_per_osd=64, apply=False)
    hot = next(r for r in recs if r["pool"] == "hot")
    cold = next(r for r in recs if r["pool"] == "cold")
    assert hot["action"] == "grow" and hot["target"] > hot["pg_num"]
    # a power-of-two target
    assert hot["target"] & (hot["target"] - 1) == 0
    assert "grow" not in cold["action"]
    # apply: splitting machinery runs, all data stays readable
    recs = c.mgr.pg_autoscale(target_pgs_per_osd=64, apply=True)
    c.tick(rounds=3)
    hot_pool = c.mon.osdmap.pools[cl.lookup_pool("hot")]
    assert hot_pool.pg_num == hot["target"]
    assert hot_pool.pgp_num == hot["target"]
    for oid, data in blobs.items():
        assert cl.read("hot", oid) == data
    assert cl.read("cold", "c0") == payload(50, seed=77)


def test_autoscaler_shrink_is_report_only():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("over", size=3, pg_num=64)
    c.create_replicated_pool("rest", size=3, pg_num=4)
    cl = c.client("client.s")
    cl.write_full("over", "tiny", payload(10))
    for i in range(10):
        cl.write_full("rest", f"r{i}", payload(20000, seed=i))
    c.tick()
    recs = c.mgr.pg_autoscale(target_pgs_per_osd=16, apply=True)
    over = next(r for r in recs if r["pool"] == "over")
    assert "shrink" in over["action"]
    assert "applied" not in over
    assert c.mon.osdmap.pools[cl.lookup_pool("over")].pg_num == 64


def test_autoscaler_admin_socket_dry_run():
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=3, pg_num=4)
    cl = c.client("client.s")
    cl.write_full("p", "o", payload(1000))
    c.tick()
    out = c.admin_socket.execute("pg_autoscale status")
    assert isinstance(out, list) and out[0]["pool"] == "p"
    # dry run: nothing changed
    assert c.mon.osdmap.pools[cl.lookup_pool("p")].pg_num == 4


def test_stale_parent_stats_dropped_after_split():
    """A pre-split parent's report for ps >= pg_num children doesn't
    linger; the pool aggregate converges to the real contents."""
    c = MiniCluster(n_osds=4)
    c.create_replicated_pool("p", size=2, pg_num=2)
    cl = c.client("client.s")
    blobs = {f"o{i}": payload(5000, seed=i) for i in range(8)}
    for oid, b in blobs.items():
        cl.write_full("p", oid, b)
    c.tick()
    before = c.mgr.pool_stats()[cl.lookup_pool("p")]
    c.mon.set_pool_pg_num("p", 8)
    c.publish()
    c.tick(rounds=2)
    after = c.mgr.pool_stats()[cl.lookup_pool("p")]
    assert after["objects"] == before["objects"] == 8
    assert after["bytes"] == before["bytes"]
    for oid, b in blobs.items():
        assert cl.read("p", oid) == b
