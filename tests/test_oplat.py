"""Stage-latency ledger (ceph_tpu/trace/oplat.py): per-stage time
attribution for every op.

Tier-1 coverage for the oplat PR's acceptance criteria: one traced EC
write shows a complete monotone stage ledger; the always-on aggregate
reconciles per op (stage sum == ledger wall); the mClock tiers stamp
the queue stages; slow ops carry their breakdown in
``dump_historic_slow_ops``; and the ``latency dump`` / ``latency
reset`` admin surface serves shares and percentiles.
"""
import time

import pytest

from ceph_tpu.common.config import g_conf
from ceph_tpu.trace import STAGES, g_oplat, g_tracer
from ceph_tpu.trace.oplat import (OpLedger, item_ledger, mark_item,
                                  stage_of_hist_name, stamp_client)

# the boundaries a default-config (window=0, depth=1) full EC write
# crosses, in order — batch_window only exists with a collection window
WRITE_STAGES_SYNC = [
    "client_flight", "admission", "class_queue", "client_lane",
    "dequeue_handoff", "op_service", "device_call", "d2h", "fan_out",
    "ack_gather", "reply",
]


@pytest.fixture
def clean_tracing():
    yield
    g_tracer.enable(False)
    g_tracer.collector.clear()
    g_conf.rm_val("op_complaint_time")
    g_conf.rm_val("ec_pipeline_depth")
    g_conf.rm_val("ec_dispatch_batch_window_us")
    g_conf.rm_val("ec_dispatch_batch_max")


def _boot():
    from ceph_tpu.cluster import MiniCluster
    c = MiniCluster(n_osds=6)
    c.create_ec_pool("lat", k=3, m=2, pg_num=8)
    return c


# ---- ledger primitives -----------------------------------------------------
def test_ledger_marks_record_and_reconcile():
    led = OpLedger("unit.oplat")
    t = led.t0
    for stage in ("admission", "class_queue", "reply"):
        t += 0.001
        led.mark(stage, t)
    d = led.dump()
    assert [s["stage"] for s in d["stages"]] == [
        "admission", "class_queue", "reply"]
    # stage sum reconciles with the ledger's wall exactly
    assert sum(s["usec"] for s in d["stages"]) == \
        pytest.approx(d["total_usec"], rel=1e-6)
    ats = [s["at_usec"] for s in d["stages"]]
    assert ats == sorted(ats)
    # out-of-order stamps clamp to zero, never negative
    led.mark("late", t - 0.5)
    assert led.dump()["stages"][-1]["usec"] == 0.0


def test_hist_name_roundtrip():
    assert stage_of_hist_name("oplat_d2h_latency_histogram") == "d2h"
    assert stage_of_hist_name("op_w_latency_in_bytes_histogram") is None


def test_item_ledger_finds_op_messages():
    class FakeMsg:
        pass

    msg = FakeMsg()
    led = stamp_client(msg, "client.unit")
    assert item_ledger(("op", msg)) is led
    assert item_ledger(("scrub", object(), True)) is None
    mark_item(("op", msg), "class_queue")
    assert [s for s, _t, _dt in led.marks] == ["class_queue"]


def test_mclock_tiers_stamp_queue_stages():
    """Both class-queue tiers (virtual + wall clock) stamp the
    class_queue/client_lane boundaries on dequeue."""
    from ceph_tpu.common.work_queue import (CLASS_CLIENT, MClockQueue,
                                            WallMClockQueue)

    class FakeMsg:
        pass

    for q, deq in ((MClockQueue(), lambda q: q.dequeue()),
                   (WallMClockQueue(), lambda q: q.dequeue()[0])):
        msg = FakeMsg()
        led = stamp_client(msg, "client.unit")
        q.enqueue(CLASS_CLIENT, ("op", msg), client="client.unit")
        item = deq(q)
        assert item[1] is msg
        assert [s for s, _t, _dt in led.marks] == ["class_queue",
                                                   "client_lane"]


# ---- acceptance: the traced EC write's complete monotone ledger ------------
def test_traced_ec_write_full_stage_ledger(clean_tracing):
    g_tracer.enable()
    c = _boot()
    cl = c.client()
    assert cl.write_full("lat", "obj", b"z" * 20000) == 0
    roots = [s for ring in g_tracer.collector._rings.values()
             for s in ring if s.name.startswith("client_op:writefull")]
    assert roots, "no client root span"
    ledger = roots[-1].tags.get("stage_ledger")
    assert ledger, "traced write carried no stage_ledger tag"
    stages = [e["stage"] for e in ledger]
    assert stages == WRITE_STAGES_SYNC
    # every entry is a known stage, timestamps monotone, durations sane
    assert set(stages) <= set(STAGES)
    ts = [e["t"] for e in ledger]
    assert ts == sorted(ts), "stage ledger not monotone"
    assert all(e["usec"] >= 0 for e in ledger)
    # the same ledger rides next to the copy ledger: one traced write
    # shows where the bytes AND the microseconds went
    tree_spans = g_tracer.collector.spans_for_trace(roots[-1].trace_id)
    assert any("copy_ledger" in s.tags for s in tree_spans)


def test_pipelined_write_adds_batch_window_stage(clean_tracing):
    """At ec_pipeline_depth > 1 with a collection window open, the
    ledger grows the batch_window stage between the codec submit and
    the coalesced flush."""
    g_tracer.enable()
    c = _boot()
    cl = c.client()
    cl.write_full("lat", "warm", b"w" * 20000)
    g_conf.set_val("ec_pipeline_depth", 8)
    g_conf.set_val("ec_dispatch_batch_window_us", 100_000)
    assert cl.write_full("lat", "piped", b"p" * 20000) == 0
    roots = [s for ring in g_tracer.collector._rings.values()
             for s in ring if s.name == "client_op:writefull:piped"]
    stages = [e["stage"] for e in roots[-1].tags["stage_ledger"]]
    i = stages.index
    assert i("op_service") < i("batch_window") < i("device_call") \
        < i("d2h") < i("fan_out") < i("ack_gather") < i("reply")


def test_rmw_write_and_read_mark_their_rounds(clean_tracing):
    """A partial EC write's ledger shows BOTH fan-out rounds (pre-read,
    then the write fan) and a read's ledger shows the decode's device
    stages after its gather — the ledger records boundaries in the
    order the op crossed them."""
    g_tracer.enable()
    c = _boot()
    cl = c.client()
    assert cl.write_full("lat", "rmw", b"a" * 20000) == 0
    assert cl.write("lat", "rmw", b"B" * 100, offset=7) == 0
    roots = [s for ring in g_tracer.collector._rings.values()
             for s in ring if s.name == "client_op:write:rmw"]
    stages = [e["stage"] for e in roots[-1].tags["stage_ledger"]]
    assert stages.count("fan_out") == 2, stages
    assert stages.count("ack_gather") == 2, stages
    assert stages[-1] == "reply"
    # read: sub-read fan + gather precede the decode's device stages
    assert cl.read("lat", "rmw")[:8] == b"a" * 7 + b"B"
    roots = [s for ring in g_tracer.collector._rings.values()
             for s in ring if s.name == "client_op:read:rmw"]
    stages = [e["stage"] for e in roots[-1].tags["stage_ledger"]]
    i = stages.index
    assert i("fan_out") < i("ack_gather") < i("device_call") \
        < i("reply")


# ---- always-on aggregate ----------------------------------------------------
def test_untraced_write_accounts_stages(clean_tracing):
    """Tracing OFF (the default), the aggregate still attributes every
    op's stages — the ledger is always-on like perf counters."""
    c = _boot()
    cl = c.client()
    before = g_oplat.snapshot()
    ops_before = g_oplat.dump()["ops"]
    assert cl.write_full("lat", "dark", b"d" * 20000) == 0
    bd = g_oplat.breakdown_since(before, wall_s=1.0, n_ops=1)
    assert set(WRITE_STAGES_SYNC) <= set(bd["stages"])
    for st in bd["stages"].values():
        assert st["count"] >= 1
    assert g_oplat.dump()["ops"] == ops_before + 1


def test_latency_dump_shape_and_reset(clean_tracing):
    c = _boot()
    cl = c.client()
    assert cl.write_full("lat", "o", b"x" * 20000) == 0
    d = c.admin_socket.execute("latency dump")
    assert d["stage_catalog"] == list(STAGES)
    assert d["ops"] >= 1 and d["stage_samples"] >= len(WRITE_STAGES_SYNC)
    osd_daemons = {k: v for k, v in d["daemons"].items()
                   if k.startswith("osd.")}
    assert osd_daemons, "no OSD recorded stage latencies"
    for dm in osd_daemons.values():
        shares = [st["share"] for st in dm["stages"].values()]
        assert sum(shares) == pytest.approx(1.0, abs=0.01)
        for st in dm["stages"].values():
            assert st["p50_usec"] <= st["p99_usec"]
            assert st["total_usec"] >= 0
    # daemon filter
    one = next(iter(osd_daemons))
    filtered = c.admin_socket.execute("latency dump", {"daemon": one})
    assert set(filtered["daemons"]) == {one}
    # reset zeroes the oplat families and counters, nothing else
    out = c.admin_socket.execute("latency reset")
    assert out == {"reset": True}
    d2 = c.admin_socket.execute("latency dump")
    assert d2["daemons"] == {} and d2["ops"] == 0
    # non-oplat histograms survived the reset
    hd = c.admin_socket.execute("perf histogram dump")
    assert any(v.get("op_w_latency_in_bytes_histogram", {}).get("count")
               for v in hd.values())


def test_slow_op_carries_stage_breakdown(clean_tracing):
    """Satellite: dump_historic_slow_ops entries show which stage ate
    the budget WITHOUT tracing enabled and without re-running."""
    g_conf.set_val("op_complaint_time", -1.0)     # every op is "slow"
    c = _boot()
    cl = c.client()
    assert cl.write_full("lat", "slow", b"s" * 20000) == 0
    slow = c.admin_socket.execute("dump_historic_slow_ops")
    ledgers = [op["stage_ledger"] for d in slow.values()
               for op in d["ops"]
               if op["description"].startswith("osd_op(writefull")
               and "stage_ledger" in op]
    assert ledgers, "slow op carried no stage_ledger"
    led = ledgers[0]
    stages = [s["stage"] for s in led["stages"]]
    assert stages == WRITE_STAGES_SYNC
    assert sum(s["usec"] for s in led["stages"]) == \
        pytest.approx(led["total_usec"], rel=0.01)


def test_breakdown_since_percentiles_and_coverage():
    """Unit: the bench's delta breakdown — sums, shares, percentiles
    from bucket deltas, and the coverage receipt."""
    base = g_oplat.snapshot()
    for _ in range(100):
        g_oplat.record("unit.bd", "device_call", 150.0)
    g_oplat.record("unit.bd", "d2h", 850.0)
    bd = g_oplat.breakdown_since(base, wall_s=(100 * 150.0 + 850.0)
                                 / 1e6, n_ops=100)
    assert bd["coverage"] == pytest.approx(1.0, abs=0.01)
    dc = bd["stages"]["device_call"]
    assert dc["count"] == 100
    assert dc["usec_per_op"] == pytest.approx(150.0, rel=0.01)
    # log2 usec axis: 150 usec lands in the (100, 200] bucket
    assert dc["p50_usec"] == 200.0
    assert dc["p99_usec"] == 200.0
    shares = [s["share"] for s in bd["stages"].values()]
    assert sum(shares) == pytest.approx(1.0, abs=0.01)


def test_wall_reconciliation_end_to_end(clean_tracing):
    """Acceptance: a serial region's stage sum reconciles with its
    measured wall — one client, synchronous writes, coverage near 1
    (everything the client waited on is some op's attributed stage,
    modulo client-side bookkeeping between ops)."""
    c = _boot()
    cl = c.client()
    cl.write_full("lat", "warm", b"w" * 20000)    # compile outside
    before = g_oplat.snapshot()
    t0 = time.perf_counter()
    for i in range(4):
        assert cl.write_full("lat", f"w{i}", b"x" * 20000) == 0
    wall = time.perf_counter() - t0
    bd = g_oplat.breakdown_since(before, wall, n_ops=4)
    assert 0.5 <= bd["coverage"] <= 1.1, bd
