"""Archived-encoding non-regression (the ceph-object-corpus +
test/encoding/readable.sh role): blobs under tests/corpus/encodings/
were written by an earlier state of the framework; the CURRENT code
must still decode every one, and re-encode it byte-identical.

An intentional encoding change regenerates the corpus
(scripts/gen_encoding_corpus.py) so the blob diff is reviewed with
the code change; an accidental one fails here first.
"""
import glob
import os

import pytest

from ceph_tpu.tools.dencoder import _registry

DIR = os.path.join(os.path.dirname(__file__), "corpus", "encodings")
BLOBS = sorted(glob.glob(os.path.join(DIR, "*.bin")))
REG = _registry()


def _type_for(path):
    stem = os.path.basename(path).rsplit(".", 2)[0]
    # ':' is not filename-safe; the generator maps it to '_'
    for name in REG:
        if name.replace(":", "_") == stem:
            return name
    return None


def test_corpus_present():
    assert len(BLOBS) >= 60, "encoding corpus missing or truncated"


@pytest.mark.parametrize("path", BLOBS,
                         ids=[os.path.basename(p) for p in BLOBS])
def test_archived_blob_still_decodes(path):
    name = _type_for(path)
    assert name is not None, f"no registered type for {path}"
    h = REG[name]
    blob = open(path, "rb").read()
    obj = h.decode(blob)                 # the decode guarantee
    assert h.encode(obj) == blob         # and stable re-encode


def test_qos_throttle_hint_omitted_when_default():
    """Wire-format guard for the QoS throttle field (docs/QOS.md): an
    UNTHROTTLED MOSDOpReply (retry_after=0.0, the dataclass default)
    must encode byte-identical to the pre-QoS format — the archived
    corpus above stays pinned precisely because the field is dropped
    from the wire when default.  A throttled reply round-trips the
    hint; the archived blobs decode with the default filled in."""
    from ceph_tpu.msg import messages as M
    from ceph_tpu.msg import wire

    plain = wire.encode_message(M.MOSDOpReply(tid=9, result=0, epoch=4))
    assert b"retry_after" not in plain, \
        "default retry_after leaked onto the wire"
    explicit_default = wire.encode_message(
        M.MOSDOpReply(tid=9, result=0, epoch=4, retry_after=0.0))
    assert explicit_default == plain
    throttled = wire.encode_message(
        M.MOSDOpReply(tid=9, result=-11, epoch=4, retry_after=0.25))
    assert wire.decode_message(throttled).retry_after == 0.25
    # the archived MOSDOpReply blobs predate the field: decode fills
    # the default, and (per the parametrized test above) re-encode is
    # byte-identical
    for path in BLOBS:
        if os.path.basename(path).startswith("MOSDOpReply."):
            obj = REG[_type_for(path)].decode(open(path, "rb").read())
            assert getattr(obj, "retry_after", 0.0) == 0.0
