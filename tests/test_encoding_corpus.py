"""Archived-encoding non-regression (the ceph-object-corpus +
test/encoding/readable.sh role): blobs under tests/corpus/encodings/
were written by an earlier state of the framework; the CURRENT code
must still decode every one, and re-encode it byte-identical.

An intentional encoding change regenerates the corpus
(scripts/gen_encoding_corpus.py) so the blob diff is reviewed with
the code change; an accidental one fails here first.
"""
import glob
import os

import pytest

from ceph_tpu.tools.dencoder import _registry

DIR = os.path.join(os.path.dirname(__file__), "corpus", "encodings")
BLOBS = sorted(glob.glob(os.path.join(DIR, "*.bin")))
REG = _registry()


def _type_for(path):
    stem = os.path.basename(path).rsplit(".", 2)[0]
    # ':' is not filename-safe; the generator maps it to '_'
    for name in REG:
        if name.replace(":", "_") == stem:
            return name
    return None


def test_corpus_present():
    assert len(BLOBS) >= 60, "encoding corpus missing or truncated"


@pytest.mark.parametrize("path", BLOBS,
                         ids=[os.path.basename(p) for p in BLOBS])
def test_archived_blob_still_decodes(path):
    name = _type_for(path)
    assert name is not None, f"no registered type for {path}"
    h = REG[name]
    blob = open(path, "rb").read()
    obj = h.decode(blob)                 # the decode guarantee
    assert h.encode(obj) == blob         # and stable re-encode
